"""VM façade: stage machine, registration, async + stop.

Mirrors the reference's VM workflow coverage (test/api/APIVMCoreTest.cpp +
test/thread/ThreadTest.cpp:167-330 for async-cancel semantics).
"""

import time

import pytest

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import ErrCode, TrapError, WasmError
from wasmedge_tpu.models import build_fib
from wasmedge_tpu.utils.builder import ModuleBuilder
from wasmedge_tpu.vm import VM, VMStage


def test_staged_pipeline():
    vm = VM()
    assert vm.stage == VMStage.Inited
    vm.load_wasm(build_fib())
    assert vm.stage == VMStage.Loaded
    vm.validate()
    assert vm.stage == VMStage.Validated
    vm.instantiate()
    assert vm.stage == VMStage.Instantiated
    assert vm.execute("fib", [10]) == [55]


def test_wrong_workflow_rejected():
    vm = VM()
    with pytest.raises(WasmError) as e:
        vm.validate()
    assert e.value.code == ErrCode.WrongVMWorkflow
    vm.load_wasm(build_fib())
    with pytest.raises(WasmError) as e:
        vm.instantiate()  # skipped validate
    assert e.value.code == ErrCode.WrongVMWorkflow


def test_run_wasm_file_one_shot():
    assert VM().run_wasm_file(build_fib(), "fib", [12]) == [144]


def test_register_module_and_cross_call():
    b = ModuleBuilder()
    b.add_function(["i32", "i32"], ["i32"], [], [
        ("local.get", 0), ("local.get", 1), "i32.add",
    ], export="add")
    vm = VM()
    vm.register_module("math", b.build())

    main = ModuleBuilder()
    main.import_func("math", "add", ["i32", "i32"], ["i32"])
    main.add_function(["i32"], ["i32"], [], [
        ("local.get", 0), ("i32.const", 100), ("call", 0),
    ], export="plus100")
    out = vm.run_wasm_file(main.build(), "plus100", [5])
    assert out == [105]
    # registered module stays callable by name
    assert vm.execute("add", [2, 3], module_name="math") == [5]


def test_function_list():
    vm = VM().load_wasm(build_fib()).validate().instantiate()
    fl = vm.get_function_list()
    assert len(fl) == 1
    name, ft = fl[0]
    assert name == "fib"
    assert len(ft.params) == 1 and len(ft.results) == 1


def test_async_execute():
    vm = VM().load_wasm(build_fib()).validate().instantiate()
    h = vm.async_execute("fib", [15])
    assert h.get() == [610]
    assert h.done()


def test_async_cancel_interrupts_infinite_loop():
    b = ModuleBuilder()
    b.add_function([], [], [], [
        ("loop", None), ("br", 0), "end",
    ], export="spin")
    vm = VM().load_wasm(b.build()).validate().instantiate()
    h = vm.async_execute("spin")
    assert not h.wait_for(0.05)
    h.cancel()
    with pytest.raises(TrapError) as e:
        h.get()
    assert e.value.code == ErrCode.Terminated


def test_stale_stop_does_not_poison_next_run():
    vm = VM()
    vm.run_wasm_file(build_fib(), "fib", [10])
    vm.stop()  # lands after completion; must be a no-op for future runs
    assert vm.execute("fib", [10]) == [55]


def test_cancel_is_per_handle():
    b = ModuleBuilder()
    b.add_function([], [], [], [("loop", None), ("br", 0), "end"], export="spin")
    vm = VM().load_wasm(b.build()).validate().instantiate()
    h1 = vm.async_execute("spin")
    h2 = vm.async_execute("spin")
    h1.cancel()
    assert h1.wait_for(1.0)
    assert not h2.done()
    h2.cancel()
    assert h2.wait_for(1.0)


def test_execute_batch_via_vm():
    import numpy as np

    vm = VM().load_wasm(build_fib()).validate().instantiate()
    res = vm.execute_batch("fib", [np.full(8, 10, np.int64)], lanes=8)
    assert res.completed.all()
    assert (np.asarray(res.results[0]) == 55).all()


def test_cleanup_keeps_registered():
    b = ModuleBuilder()
    b.add_function([], ["i32"], [], [("i32.const", 7)], export="seven")
    vm = VM()
    vm.register_module("k", b.build())
    vm.run_wasm_file(build_fib(), "fib", [5])
    vm.cleanup()
    assert vm.stage == VMStage.Inited
    assert vm.execute("seven", [], module_name="k") == [7]
