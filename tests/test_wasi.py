"""WASI preview1 host functions, driven directly with a synthetic memory.

Mirrors the reference's unit strategy (test/host/wasi/wasi.cpp:1-1603:
hostfuncs called with a hand-built MemoryInstance) plus loopback socket
integration (test/host/socket/wasi_socket.cpp) and an end-to-end wasm
module printing through fd_write.
"""

import os
import struct
import socket
import threading

import pytest

from wasmedge_tpu.common.configure import Configure, HostRegistration
from wasmedge_tpu.host.wasi import WasiExit, WasiModule
from wasmedge_tpu.host.wasi.wasi_abi import (
    Errno,
    Oflags,
    Rights,
    Whence,
)
from wasmedge_tpu.loader.ast import Limit, MemoryType
from wasmedge_tpu.runtime.instance import MemoryInstance
from wasmedge_tpu.utils.builder import ModuleBuilder
from wasmedge_tpu.vm import VM


def make_mem(pages=1):
    return MemoryInstance(MemoryType(Limit(pages, pages)))


def call(wasi, name, mem, *args):
    hf = wasi.funcs[name]
    raw = [a & 0xFFFFFFFFFFFFFFFF for a in args]
    out = hf.run(mem, raw)
    return out[0] if out else None


# ---------------------------------------------------------------------------
# args / environ / clock / random
# ---------------------------------------------------------------------------
def test_args_roundtrip():
    wasi = WasiModule()
    wasi.init_wasi(prog_name="prog", args=["a", "bc"])
    mem = make_mem()
    assert call(wasi, "args_sizes_get", mem, 0, 8) == Errno.SUCCESS
    assert mem.load(0, 4, False) == 3
    assert mem.load(8, 4, False) == len(b"prog\0a\0bc\0")
    assert call(wasi, "args_get", mem, 16, 64) == Errno.SUCCESS
    buf = mem.load_bytes(64, 10)
    assert buf == b"prog\0a\0bc\0"
    # argv pointers
    p0 = mem.load(16, 4, False)
    p1 = mem.load(20, 4, False)
    assert (p0, p1) == (64, 69)


def test_environ_roundtrip():
    wasi = WasiModule()
    wasi.init_wasi(envs=["A=1", "LONG=xyz"])
    mem = make_mem()
    assert call(wasi, "environ_sizes_get", mem, 0, 4) == Errno.SUCCESS
    assert mem.load(0, 4, False) == 2
    assert call(wasi, "environ_get", mem, 8, 32) == Errno.SUCCESS
    assert mem.load_bytes(32, 4) == b"A=1\0"


def test_clock_and_random():
    wasi = WasiModule()
    mem = make_mem()
    assert call(wasi, "clock_time_get", mem, 0, 0, 0) == Errno.SUCCESS
    t1 = mem.load(0, 8, False)
    assert t1 > 1_600_000_000 * 10**9  # after 2020, realtime
    assert call(wasi, "clock_res_get", mem, 1, 8) == Errno.SUCCESS
    assert call(wasi, "clock_time_get", mem, 99, 0, 0) == Errno.INVAL
    assert call(wasi, "random_get", mem, 100, 16) == Errno.SUCCESS
    assert mem.load_bytes(100, 16) != bytes(16)


# ---------------------------------------------------------------------------
# fd + path family over a preopened tmpdir
# ---------------------------------------------------------------------------
@pytest.fixture
def wasi_tmp(tmp_path):
    wasi = WasiModule()
    wasi.init_wasi(dirs=[f"/:{tmp_path}"])
    return wasi, tmp_path


def _store_str(mem, off, s):
    raw = s.encode()
    mem.store_bytes(off, raw)
    return off, len(raw)


def _iovec(mem, iov_off, buf_off, data=None, length=None):
    if data is not None:
        mem.store_bytes(buf_off, data)
        length = len(data)
    mem.store(iov_off, 4, buf_off)
    mem.store(iov_off + 4, 4, length)


def test_prestat(wasi_tmp):
    wasi, _ = wasi_tmp
    mem = make_mem()
    assert call(wasi, "fd_prestat_get", mem, 3, 0) == Errno.SUCCESS
    tag = mem.load(0, 1, False)
    nlen = mem.load(4, 4, False)
    assert tag == 0 and nlen == 1
    assert call(wasi, "fd_prestat_dir_name", mem, 3, 16, nlen) == Errno.SUCCESS
    assert mem.load_bytes(16, 1) == b"/"
    assert call(wasi, "fd_prestat_get", mem, 0, 0) == Errno.BADF


def _open(wasi, mem, dirfd, path, oflags=0, rights=None, fdflags=0):
    p, plen = _store_str(mem, 1024, path)
    if rights is None:
        rights = Rights.FILE_BASE | Rights.DIR_BASE
    err = call(wasi, "path_open", mem, dirfd, 1, p, plen, oflags,
               rights, rights, fdflags, 2048)
    return err, mem.load(2048, 4, False)


def test_file_write_read_seek(wasi_tmp):
    wasi, tmp = wasi_tmp
    mem = make_mem()
    err, fd = _open(wasi, mem, 3, "hello.txt", Oflags.CREAT)
    assert err == Errno.SUCCESS
    _iovec(mem, 64, 128, b"hello wasi")
    assert call(wasi, "fd_write", mem, fd, 64, 1, 0) == Errno.SUCCESS
    assert mem.load(0, 4, False) == 10
    assert (tmp / "hello.txt").read_bytes() == b"hello wasi"
    # seek to 6, read 4
    assert call(wasi, "fd_seek", mem, fd, 6, Whence.SET, 8) == Errno.SUCCESS
    assert mem.load(8, 8, False) == 6
    _iovec(mem, 64, 256, length=4)
    assert call(wasi, "fd_read", mem, fd, 64, 1, 0) == Errno.SUCCESS
    assert mem.load(0, 4, False) == 4
    assert mem.load_bytes(256, 4) == b"wasi"
    # tell
    assert call(wasi, "fd_tell", mem, fd, 16) == Errno.SUCCESS
    assert mem.load(16, 8, False) == 10
    # pread at 0
    _iovec(mem, 64, 300, length=5)
    assert call(wasi, "fd_pread", mem, fd, 64, 1, 0, 0) == Errno.SUCCESS
    assert mem.load_bytes(300, 5) == b"hello"
    # filestat
    assert call(wasi, "fd_filestat_get", mem, fd, 512) == Errno.SUCCESS
    assert mem.load(512 + 32, 8, False) == 10  # size
    assert call(wasi, "fd_close", mem, fd) == Errno.SUCCESS
    assert call(wasi, "fd_close", mem, fd) == Errno.BADF


def test_rights_enforced(wasi_tmp):
    wasi, tmp = wasi_tmp
    (tmp / "ro.txt").write_bytes(b"x")
    mem = make_mem()
    err, fd = _open(wasi, mem, 3, "ro.txt", 0, rights=Rights.FD_READ)
    assert err == Errno.SUCCESS
    _iovec(mem, 64, 128, b"nope")
    assert call(wasi, "fd_write", mem, fd, 64, 1, 0) == Errno.NOTCAPABLE
    # requesting rights beyond the dir's inheriting set is refused
    err, _ = _open(wasi, mem, 3, "ro.txt", 0, rights=1 << 40)
    assert err == Errno.NOTCAPABLE


def test_sandbox_escape_blocked(wasi_tmp):
    wasi, tmp = wasi_tmp
    mem = make_mem()
    err, _ = _open(wasi, mem, 3, "../outside", Oflags.CREAT)
    assert err == Errno.NOTCAPABLE
    # symlink pointing outside is refused
    os.symlink("/etc", tmp / "evil")
    err, _ = _open(wasi, mem, 3, "evil/passwd")
    assert err == Errno.NOTCAPABLE


def test_dirs_and_rename(wasi_tmp):
    wasi, tmp = wasi_tmp
    mem = make_mem()
    p, plen = _store_str(mem, 1024, "sub")
    assert call(wasi, "path_create_directory", mem, 3, p, plen) == Errno.SUCCESS
    assert (tmp / "sub").is_dir()
    (tmp / "f1").write_bytes(b"data")
    o, olen = _store_str(mem, 1100, "f1")
    n, nlen = _store_str(mem, 1200, "sub/f2")
    assert call(wasi, "path_rename", mem, 3, o, olen, 3, n, nlen) == Errno.SUCCESS
    assert (tmp / "sub" / "f2").read_bytes() == b"data"
    # path_filestat_get
    assert call(wasi, "path_filestat_get", mem, 3, 1, n, nlen, 512) == Errno.SUCCESS
    assert mem.load(512 + 16, 1, False) == 4  # REGULAR_FILE
    # unlink + rmdir
    assert call(wasi, "path_unlink_file", mem, 3, n, nlen) == Errno.SUCCESS
    assert call(wasi, "path_remove_directory", mem, 3, p, plen) == Errno.SUCCESS
    assert not (tmp / "sub").exists()


def test_readdir(wasi_tmp):
    wasi, tmp = wasi_tmp
    (tmp / "aa").write_bytes(b"")
    (tmp / "bb").write_bytes(b"")
    mem = make_mem()
    err, fd = _open(wasi, mem, 3, ".", Oflags.DIRECTORY)
    assert err == Errno.SUCCESS
    assert call(wasi, "fd_readdir", mem, fd, 0, 512, 0, 600) == Errno.SUCCESS
    used = mem.load(600, 4, False)
    blob = mem.load_bytes(0, used)
    names = []
    off = 0
    while off < used:
        namlen = struct.unpack_from("<I", blob, off + 16)[0]
        names.append(blob[off + 24:off + 24 + namlen].decode())
        off += 24 + namlen
    assert names == [".", "..", "aa", "bb"]


def test_symlink_readlink(wasi_tmp):
    wasi, tmp = wasi_tmp
    mem = make_mem()
    o, olen = _store_str(mem, 1024, "target")
    n, nlen = _store_str(mem, 1100, "link")
    assert call(wasi, "path_symlink", mem, o, olen, 3, n, nlen) == Errno.SUCCESS
    assert call(wasi, "path_readlink", mem, 3, n, nlen, 0, 64, 600) == Errno.SUCCESS
    used = mem.load(600, 4, False)
    assert mem.load_bytes(0, used) == b"target"


def test_trailing_dotdot_within_sandbox_allowed(wasi_tmp):
    wasi, tmp = wasi_tmp
    (tmp / "sub").mkdir()
    mem = make_mem()
    p, plen = _store_str(mem, 1024, "sub/..")
    assert call(wasi, "path_filestat_get", mem, 3, 1, p, plen, 512) == Errno.SUCCESS
    assert mem.load(512 + 16, 1, False) == 3  # DIRECTORY (the preopen root)


def test_bad_guest_pointer_is_efault(wasi_tmp):
    wasi, _ = wasi_tmp
    mem = make_mem()
    # iovec pointing past the 64KiB page
    _iovec(mem, 64, 128, length=8)
    mem.store(64, 4, 0xFFFF0)  # buf beyond memory
    assert call(wasi, "fd_read", mem, 0, 64, 1, 0) == Errno.FAULT
    assert call(wasi, "random_get", mem, 0, 0xFFFFFFFF) == Errno.FAULT


def test_process_env_not_inherited(monkeypatch):
    from wasmedge_tpu.host.process import WasmEdgeProcessModule

    monkeypatch.setenv("LEAKY_SECRET", "s3cret")
    proc = WasmEdgeProcessModule(allowed_cmds=["env"])
    mem = make_mem()

    def pc(name, *args):
        hf = proc.funcs[name]
        out = hf.run(mem, list(args))
        return out[0] if out else None

    mem.store_bytes(0, b"env")
    pc("wasmedge_process_set_prog_name", 0, 3)
    assert pc("wasmedge_process_run") == 0
    n = pc("wasmedge_process_get_stdout_len")
    pc("wasmedge_process_get_stdout", 100)
    assert b"LEAKY_SECRET" not in mem.load_bytes(100, n)


def test_invalid_utf8_path_is_ilseq(wasi_tmp):
    wasi, _ = wasi_tmp
    mem = make_mem()
    mem.store_bytes(1024, b"\xff\xfe")
    assert call(wasi, "path_create_directory", mem, 3, 1024, 2) == Errno.ILSEQ


def test_readdir_huge_cookie_no_crash(wasi_tmp):
    wasi, _ = wasi_tmp
    mem = make_mem()
    err, fd = _open(wasi, mem, 3, ".", Oflags.DIRECTORY)
    assert err == Errno.SUCCESS
    # cookie 2^64-2 arrives as a signed -2 through marshaling; must not
    # index backwards or crash — just reports an empty tail
    assert call(wasi, "fd_readdir", mem, fd, 0, 512,
                0xFFFFFFFFFFFFFFFE, 600) == Errno.SUCCESS
    assert mem.load(600, 4, False) == 0


def test_poll_bad_fd_reports_badf():
    wasi = WasiModule()
    mem = make_mem()
    # one FD_READ subscription on a closed fd, no clock
    mem.store(0, 8, 0xABCD)       # userdata
    mem.store(8, 1, 1)            # tag FD_READ
    mem.store(16, 4, 99)          # bad fd
    assert call(wasi, "poll_oneoff", mem, 0, 128, 1, 256) == Errno.SUCCESS
    assert mem.load(256, 4, False) == 1
    assert mem.load(128, 8, False) == 0xABCD
    assert mem.load(136, 2, False) == Errno.BADF


def test_aot_section_does_not_bypass_structural_validation():
    from wasmedge_tpu import aot
    from wasmedge_tpu.common.errors import ValidationError, WasmError
    from wasmedge_tpu.loader.loader import Loader
    from wasmedge_tpu.validator.validator import Validator

    # module exporting a func index that doesn't exist
    b = ModuleBuilder()
    b.add_function([], ["i32"], [], [("i32.const", 1)])
    b.exports.append(b._name("ghost") + b"\x00" + bytes([9]))
    bad = b.build()
    # craft a "valid-looking" aot section over the bad module bytes
    good_img = aot.serialize_image(
        Validator().validate(Loader().parse_module(
            ModuleBuilder().build() if False else _hello_or_simple())).lowered)
    import hashlib as _h
    import struct as _s

    body = _s.pack("<I", aot.AOT_VERSION) + _h.sha256(bad).digest() + good_img
    name = aot.SECTION_NAME.encode()
    content = bytes([len(name)]) + name + body
    art = bad + b"\x00" + _uleb_len(len(content)) + content
    mod = Loader().parse_module(art)
    with pytest.raises((ValidationError, WasmError)):
        Validator().validate(mod)


def _hello_or_simple():
    b = ModuleBuilder()
    b.add_function([], ["i32"], [], [("i32.const", 1)], export="one")
    return b.build()


def _uleb_len(v):
    from wasmedge_tpu.utils.builder import uleb

    return uleb(v)


def test_proc_exit():
    wasi = WasiModule()
    mem = make_mem()
    with pytest.raises(WasiExit) as e:
        call(wasi, "proc_exit", mem, 42)
    assert e.value.code == 42
    assert wasi.exit_code == 42


# ---------------------------------------------------------------------------
# sockets: loopback TCP echo through the wasi socket extension
# ---------------------------------------------------------------------------
def test_socket_loopback_echo():
    wasi = WasiModule()
    mem = make_mem()

    # server socket via wasi: open/bind/listen
    assert call(wasi, "sock_open", mem, 0, 1, 0) == Errno.SUCCESS  # INET4 STREAM
    sfd = mem.load(0, 4, False)
    # address buffer: {buf=32, len=4}, 0.0.0.0
    mem.store(16, 4, 32)
    mem.store(20, 4, 4)
    mem.store_bytes(32, socket.inet_aton("127.0.0.1"))
    assert call(wasi, "sock_bind", mem, sfd, 16, 0) == Errno.SUCCESS
    assert call(wasi, "sock_listen", mem, sfd, 4) == Errno.SUCCESS
    # discover bound port
    assert call(wasi, "sock_getlocaladdr", mem, sfd, 16, 48, 52) == Errno.SUCCESS
    port = mem.load(52, 4, False)
    assert port > 0

    # plain-python client connects and echoes
    def client():
        c = socket.create_connection(("127.0.0.1", port), timeout=5)
        data = c.recv(16)
        c.sendall(data.upper())
        c.close()

    t = threading.Thread(target=client)
    t.start()

    assert call(wasi, "sock_accept", mem, sfd, 60) == Errno.SUCCESS
    cfd = mem.load(60, 4, False)
    # send "ping" via iovec at 64 -> buf 128
    _iovec(mem, 64, 128, b"ping")
    assert call(wasi, "sock_send", mem, cfd, 64, 1, 0, 72) == Errno.SUCCESS
    assert mem.load(72, 4, False) == 4
    _iovec(mem, 64, 256, length=4)
    assert call(wasi, "sock_recv", mem, cfd, 64, 1, 0, 72, 76) == Errno.SUCCESS
    assert mem.load_bytes(256, 4) == b"PING"
    assert call(wasi, "sock_shutdown", mem, cfd, 3) == Errno.SUCCESS
    assert call(wasi, "fd_close", mem, cfd) == Errno.SUCCESS
    assert call(wasi, "fd_close", mem, sfd) == Errno.SUCCESS
    t.join()


# ---------------------------------------------------------------------------
# end-to-end: wasm module printing through fd_write via the VM
# ---------------------------------------------------------------------------
def _hello_wasm():
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "fd_write",
                  ["i32", "i32", "i32", "i32"], ["i32"])
    b.add_memory(1, 1, export="memory")
    b.add_active_data(0, [("i32.const", 8)], b"hello, wasi\n")
    # iovec at 0: buf=8 len=12
    b.add_function([], ["i32"], [], [
        ("i32.const", 0), ("i32.const", 8), "i32.store",
        ("i32.const", 4), ("i32.const", 12), "i32.store",
        ("i32.const", 1),   # fd: stdout
        ("i32.const", 0),   # iovs
        ("i32.const", 1),   # iovs_len
        ("i32.const", 24),  # nwritten ptr
        ("call", 0),
    ], export="_start")
    return b.build()


def test_hello_via_vm(tmp_path):
    conf = Configure()
    conf.host_registrations.add(HostRegistration.Wasi)
    vm = VM(conf)
    # Redirect guest stdout (fd 1) into a pipe so the test can capture it.
    r, w = os.pipe()
    vm.wasi_module.env.fds[1].os_fd = w
    out = vm.run_wasm_file(_hello_wasm(), "_start")
    os.close(w)
    assert out == [Errno.SUCCESS]
    assert os.read(r, 64) == b"hello, wasi\n"
    os.close(r)
    nwritten = vm.active_module.memories[0].load(24, 4, False)
    assert nwritten == 12


def test_wasi_exit_code_via_vm():
    conf = Configure()
    conf.host_registrations.add(HostRegistration.Wasi)
    b = ModuleBuilder()
    b.import_func("wasi_snapshot_preview1", "proc_exit", ["i32"], [])
    b.add_function([], [], [], [("i32.const", 7), ("call", 0)], export="_start")
    vm = VM(conf)
    with pytest.raises(WasiExit):
        vm.run_wasm_file(b.build(), "_start")
    assert vm.wasi_module.exit_code == 7


# ---------------------------------------------------------------------------
# wasmedge_process module
# ---------------------------------------------------------------------------
def test_process_module_allowlist():
    from wasmedge_tpu.host.process import WasmEdgeProcessModule

    proc = WasmEdgeProcessModule(allowed_cmds=["echo"])
    mem = make_mem()

    def pc(name, *args):
        hf = proc.funcs[name]
        out = hf.run(mem, list(args))
        return out[0] if out else None

    mem.store_bytes(0, b"echo")
    pc("wasmedge_process_set_prog_name", 0, 4)
    mem.store_bytes(8, b"hi")
    pc("wasmedge_process_add_arg", 8, 2)
    pc("wasmedge_process_set_timeout", 5000)
    assert pc("wasmedge_process_run") == 0
    assert pc("wasmedge_process_get_exit_code") == 0
    n = pc("wasmedge_process_get_stdout_len")
    assert n == 3
    pc("wasmedge_process_get_stdout", 100)
    assert mem.load_bytes(100, n) == b"hi\n"

    # denied command
    mem.store_bytes(0, b"rm")
    pc("wasmedge_process_set_prog_name", 0, 2)
    assert pc("wasmedge_process_run") == 0xFFFFFFFF
    assert pc("wasmedge_process_get_stderr_len") > 0


# ---------------------------------------------------------------------------
# guest-controlled iovec lengths must be bounds-checked before recv
# ---------------------------------------------------------------------------
def test_sock_recv_huge_iovec_faults():
    import socket as _socket

    from wasmedge_tpu.host.wasi.environ import FdEntry
    from wasmedge_tpu.host.wasi.wasi_abi import Rights as R

    wasi = WasiModule()
    mem = make_mem()
    a, b = _socket.socketpair()
    try:
        rights = R.SOCK_RECV | R.FD_READ
        fd = wasi.env.insert_entry(FdEntry("socket", sock=a,
                                           rights_base=rights,
                                           rights_inheriting=rights))
        b.send(b"data")
        # iovec at 64: buf=128, len=0xFFFFF000 (~4 GiB) — far past memory
        mem.store(64, 4, 128)
        mem.store(68, 4, 0xFFFFF000)
        assert call(wasi, "sock_recv", mem, fd, 64, 1, 0, 72, 76) == Errno.FAULT
        assert call(wasi, "sock_recv_from", mem, fd, 64, 1, 200, 0, 72, 76) \
            == Errno.FAULT
    finally:
        a.close()
        b.close()


def test_poll_oneoff_bad_clock_is_per_subscription():
    wasi = WasiModule()
    mem = make_mem()
    from wasmedge_tpu.host.wasi import wasi_abi as abi

    # subscription 0: invalid clock id 99
    base = 0
    mem.store(base, 8, 0xAB)               # userdata
    mem.store(base + 8, 1, abi.Eventtype.CLOCK)
    mem.store(base + 16, 4, 99)            # bad clock id
    mem.store(base + 24, 8, 1000)          # timeout
    mem.store(base + 40, 2, 0)
    out = 256
    assert call(wasi, "poll_oneoff", mem, 0, out, 1, 512) == Errno.SUCCESS
    assert mem.load(512, 4, False) == 1    # one event delivered
    assert mem.load(out, 8, False) == 0xAB  # userdata echoed
    assert mem.load(out + 8, 2, False) == Errno.INVAL  # per-event errno
    assert mem.load(out + 10, 1, False) == abi.Eventtype.CLOCK


# ---------------------------------------------------------------------------
# depth: readdir cookie walks, poll fd-readiness + clock ordering, socket
# option/shutdown/dgram paths (reference: test/host/wasi/wasi.cpp breadth)
# ---------------------------------------------------------------------------
def test_readdir_cookie_walk_small_buffer(wasi_tmp):
    """Enumerate a directory entry-by-entry with a buffer that fits only
    one dirent per call, resuming from d_next each time."""
    import os as _os

    wasi, root = wasi_tmp
    for name in ("aaa", "bb", "c"):
        with open(_os.path.join(root, name), "w") as f:
            f.write("x")
    mem = make_mem()
    err, fd = _open(wasi, mem, 3, ".", Oflags.DIRECTORY)
    assert err == Errno.SUCCESS
    seen = set()
    cookie = 0
    for _ in range(16):
        # buffer barely fits one max-size entry
        assert call(wasi, "fd_readdir", mem, fd, 0, 64, cookie,
                    600) == Errno.SUCCESS
        used = mem.load(600, 4, False)
        if used == 0:
            break
        d_next = mem.load(0, 8, False)
        namelen = mem.load(16, 4, False)
        if 24 + namelen <= used:
            nm = bytes(mem.load_bytes(24, namelen)).decode()
            seen.add(nm)
        if d_next == cookie:
            break
        cookie = d_next
        if len(seen) >= 5:
            break
    assert {"aaa", "bb", "c"} <= seen


def test_poll_oneoff_fd_ready_and_clock_ordering():
    """A readable fd resolves the poll before a long clock subscription."""
    import os as _os
    import time as _t

    r, w = _os.pipe()
    _os.write(w, b"!")
    wasi = WasiModule()
    wasi.init_wasi()
    from wasmedge_tpu.host.wasi.environ import FdEntry

    guest_fd = 40
    wasi.env.fds[guest_fd] = FdEntry("stdio", os_fd=r,
                                     rights_base=Rights.FD_READ
                                     | Rights.POLL_FD_READWRITE)
    mem = make_mem()
    # sub 0: clock 10s; sub 1: fd_read on the ready pipe
    base = 0
    mem.store(base + 8, 1, 0)           # tag CLOCK
    mem.store(base + 16, 4, 1)          # monotonic
    mem.store(base + 24, 8, 10_000_000_000)
    from wasmedge_tpu.host.wasi import wasi_abi as abi

    sub1 = base + abi.SUBSCRIPTION_SIZE
    mem.store(sub1, 8, 0xBEEF)          # userdata
    mem.store(sub1 + 8, 1, int(abi.Eventtype.FD_READ))
    mem.store(sub1 + 16, 4, guest_fd)
    t0 = _t.monotonic()
    assert call(wasi, "poll_oneoff", mem, 0, 256, 2, 300) == Errno.SUCCESS
    assert _t.monotonic() - t0 < 5.0    # did not sleep out the clock
    nevents = mem.load(300, 4, False)
    assert nevents >= 1
    ud = mem.load(256, 8, False)
    assert ud == 0xBEEF                 # the fd event, not the clock
    _os.close(r)
    _os.close(w)


def test_poll_oneoff_pure_clock_sleeps():
    import time as _t

    wasi = WasiModule()
    wasi.init_wasi()
    mem = make_mem()
    mem.store(0, 8, 0x11)
    mem.store(8, 1, 0)                  # CLOCK
    mem.store(16, 4, 1)                 # monotonic
    mem.store(24, 8, 60_000_000)        # 60ms relative
    t0 = _t.monotonic()
    assert call(wasi, "poll_oneoff", mem, 0, 128, 1, 200) == Errno.SUCCESS
    assert _t.monotonic() - t0 >= 0.05
    assert mem.load(200, 4, False) == 1
    assert mem.load(128, 8, False) == 0x11


def test_socket_options_shutdown_and_errors():
    wasi = WasiModule()
    wasi.init_wasi()
    mem = make_mem()
    assert call(wasi, "sock_open", mem, 0, 1, 0) == Errno.SUCCESS
    sfd = mem.load(0, 4, False)
    # SO_REUSEADDR roundtrip (level SOL_SOCKET=0, name REUSEADDR=1)
    mem.store(8, 4, 1)
    assert call(wasi, "sock_setsockopt", mem, sfd, 0, 1, 8, 4) \
        == Errno.SUCCESS
    assert call(wasi, "sock_getsockopt", mem, sfd, 0, 1, 16, 20) \
        == Errno.SUCCESS
    # unknown option name -> NOPROTOOPT, not a crash
    assert call(wasi, "sock_setsockopt", mem, sfd, 0, 99, 8, 4) \
        == Errno.NOPROTOOPT
    # bind via {buf, len} address indirection + listen + shutdown
    mem.store(24, 4, 48)
    mem.store(28, 4, 4)
    mem.store_bytes(48, socket.inet_aton("127.0.0.1"))
    assert call(wasi, "sock_bind", mem, sfd, 24, 0) == Errno.SUCCESS
    assert call(wasi, "sock_listen", mem, sfd, 1) == Errno.SUCCESS
    # operations on a non-socket fd report NOTSOCK/BADF
    assert call(wasi, "sock_listen", mem, 0, 1) in (
        Errno.NOTSOCK, Errno.BADF)
    assert call(wasi, "sock_shutdown", mem, sfd, 3) == Errno.SUCCESS
    assert call(wasi, "fd_close", mem, sfd) == Errno.SUCCESS
    # shutdown after close: BADF
    assert call(wasi, "sock_shutdown", mem, sfd, 3) == Errno.BADF


def test_socket_dgram_sendto_recvfrom():
    wasi = WasiModule()
    wasi.init_wasi()
    mem = make_mem()
    assert call(wasi, "sock_open", mem, 0, 0, 0) == Errno.SUCCESS  # DGRAM
    a = mem.load(0, 4, False)
    assert call(wasi, "sock_open", mem, 0, 0, 4) == Errno.SUCCESS
    b = mem.load(4, 4, False)
    # bind b to 127.0.0.1:ephemeral via {buf,len} indirection
    mem.store(24, 4, 48)
    mem.store(28, 4, 4)
    mem.store_bytes(48, socket.inet_aton("127.0.0.1"))
    assert call(wasi, "sock_bind", mem, b, 24, 0) == Errno.SUCCESS
    assert call(wasi, "sock_getlocaladdr", mem, b, 24, 60, 64) \
        == Errno.SUCCESS
    port = mem.load(64, 4, False)
    assert port != 0
    # a -> b datagram via sock_send_to
    msg = b"dgram!"
    mem.store_bytes(100, msg)
    mem.store(80, 4, 100)
    mem.store(84, 4, len(msg))
    assert call(wasi, "sock_send_to", mem, a, 80, 1, 24, port, 0, 88) \
        == Errno.SUCCESS
    assert mem.load(88, 4, False) == len(msg)
    mem.store(120, 4, 140)
    mem.store(124, 4, 32)
    # recv_from: (fd, iovs, iovs_len, addr_ptr, flags, nread, roflags)
    mem.store(160, 4, 192)
    mem.store(164, 4, 16)
    assert call(wasi, "sock_recv_from", mem, b, 120, 1, 160, 0, 128,
                132) == Errno.SUCCESS
    got = bytes(mem.load_bytes(140, mem.load(128, 4, False)))
    assert got == msg
    call(wasi, "fd_close", mem, a)
    call(wasi, "fd_close", mem, b)
