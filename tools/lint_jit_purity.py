#!/usr/bin/env python3
"""Static lint: no host-side nondeterminism inside jitted chunk bodies.

The jitted regions (the SIMT/uniform step builders and chunk loops, the
recycler's column-install) trace ONCE and replay: a `time.time()`,
`np.random.*`, or `print()` inside them either burns into the trace as
a compile-time constant (silent nondeterminism between compiles — the
bit-identical-output contracts would break run-to-run) or fires on
every retrace instead of every step (misleading side effects).  Those
calls belong on the host side of the launch boundary, where
t0_time_planes / the seeded PRNG planes / the flight recorder already
provide the sanctioned equivalents.

AST-based: every function/lambda nested inside a known jit-region
builder is scanned for calls whose dotted name matches the forbidden
list.  Wired into the tier-1 suite (tests/test_analysis.py) so a hit
fails CI, and runnable standalone:

    python tools/lint_jit_purity.py [repo_root]
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

# file (repo-relative) -> top-level defs whose entire bodies are jit
# regions (the builders return traced callables; everything nested in
# them runs under trace)
TARGETS = {
    "wasmedge_tpu/batch/engine.py": ("_make_step", "_build",
                                     "_build_narrow_chunk"),
    "wasmedge_tpu/batch/uniform.py": ("make_uniform_step",
                                      "_build_uniform"),
    "wasmedge_tpu/serve/recycle.py": ("_install_fn",),
    # superinstruction fused-step builders: the specialized pattern
    # handlers trace inside make_fused_apply and — for the r19
    # absint-licensed memory runs — make_memfuse_apply (batch/fuse.py);
    # the missing-target guard below means a rename cannot silently
    # shrink this coverage
    "wasmedge_tpu/batch/fuse.py": ("make_fused_apply",
                                   "make_memfuse_apply"),
    # whole-function tier-up (r20): the compiled-body builder the step
    # merges in — lane-masked CFG bodies under bounded lax.while_loop
    "wasmedge_tpu/batch/tierup.py": ("make_tierup_apply",),
    # single-program mesh drive: the sharded jit wrapper around the
    # engine's chunk body (the body itself is covered by engine.py's
    # targets; this keeps the mesh-side wrapper honest too)
    "wasmedge_tpu/parallel/shard_drive.py": ("_build_shard_chunk",),
    # lane compaction (batch/compact.py): the jitted gather-permutation
    # builder; the narrowed chunk variant traces inside the engine's
    # _build_narrow_chunk, covered alongside the main builders
    "wasmedge_tpu/batch/compact.py": ("make_permute",),
}

# Dotted-call prefixes that are host-side nondeterminism (or host
# I/O).  A trailing "." means "anything in this namespace"; otherwise
# suffix variants also match (time.time catches time.time_ns).
FORBIDDEN_PREFIXES = (
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time",
    "np.random.", "numpy.random.", "jax.random.",  # use the PRNG planes
    "random.",
    "os.urandom", "secrets.",
)
FORBIDDEN_NAMES = {"print", "input", "open"}


def _forbidden(name: str) -> bool:
    if name in FORBIDDEN_NAMES:
        return True
    for p in FORBIDDEN_PREFIXES:
        if p.endswith("."):
            if name.startswith(p) or name == p[:-1]:
                return True
        elif name == p or name.startswith(p):
            return True
    return False


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _scan_region(fn: ast.AST, path: str) -> List[Tuple[str, int, str]]:
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name and _forbidden(name):
            out.append((path, node.lineno, name))
    return out


def run_lint(root: str = ".") -> List[Tuple[str, int, str]]:
    """All violations as (file, line, call) triples; empty = clean."""
    violations: List[Tuple[str, int, str]] = []
    for rel, region_names in sorted(TARGETS.items()):
        path = os.path.join(root, rel)
        with open(path, "r") as f:
            tree = ast.parse(f.read(), filename=path)
        found = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in region_names:
                found.add(node.name)
                violations.extend(_scan_region(node, rel))
        missing = set(region_names) - found
        if missing:
            # a renamed/removed jit builder must update this table, not
            # silently shrink the lint's coverage
            violations.append((rel, 0,
                               f"lint target(s) not found: "
                               f"{sorted(missing)}"))
    return violations


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(os.path.dirname(__file__),
                                             "..")
    violations = run_lint(root)
    for path, line, what in violations:
        sys.stderr.write(f"{path}:{line}: forbidden in jit region: "
                         f"{what}\n")
    if violations:
        sys.stderr.write(f"lint_jit_purity: {len(violations)} "
                         f"violation(s)\n")
        return 1
    print("lint_jit_purity: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
