"""Real-hardware parity evidence: batch engines vs the scalar oracle ON TPU.

The pytest suites prove parity on the IEEE CPU backend (tests/conftest.py
pins it); this script runs a representative slice on the actual chip —
Pallas kernel compiled by Mosaic, XLA SIMT compiled for TPU — and records
the result (TPU_PARITY_r02.json).  Covers the areas where hardware could
plausibly diverge: f32 arithmetic (FTZ kept out of the integer-domain
paths), softfloat f64, i64 carry chains, memory byte addressing, traps,
divergence handoff, and host outcalls."""

import json
import sys

import numpy as np

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import TrapError
from wasmedge_tpu.common.types import ValType, typed_to_bits
from wasmedge_tpu.models import (
    build_coremark_kernel, build_fac, build_fib, build_memory_workload)
from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction
from wasmedge_tpu.utils.wat import parse_wat
from tests.helpers import instantiate


def compare(data, func, per_lane_args, lanes=256, imports=None,
            max_steps=3_000_000):
    from wasmedge_tpu.batch.uniform import UniformBatchEngine

    conf = Configure()
    conf.batch.steps_per_launch = 1_000_000
    ex, store, inst = instantiate(data, conf, imports=imports)
    eng = UniformBatchEngine(inst, store=store, conf=conf, lanes=lanes)
    args = [np.asarray(a, np.int64) for a in per_lane_args]
    res = eng.run(func, args, max_steps=max_steps)
    mismatches = 0
    for lane in range(lanes):
        s_ex, s_store, s_inst = instantiate(data, Configure(),
                                            imports=imports)
        largs = [int(a[lane]) & ((1 << 64) - 1) for a in args]
        try:
            expect = s_ex.invoke_raw(s_store, s_inst.find_func(func), largs)
            ok = res.trap[lane] == -1 and all(
                (int(res.results[i][lane]) & ((1 << 64) - 1)) == v
                for i, v in enumerate(expect))
        except TrapError as te:
            ok = res.trap[lane] == int(te.code)
        mismatches += 0 if ok else 1
    return mismatches


def main():
    import jax

    platform = jax.devices()[0].platform
    checks = {}
    L = 256
    rng = np.random.default_rng(0)

    checks["fib_i32"] = compare(build_fib(), "fib",
                                [np.full(L, 20, np.int64)])
    checks["fac_i64"] = compare(build_fac(), "fac",
                                [np.full(L, 20, np.int64)])
    checks["memory_bytes"] = compare(build_memory_workload(), "mem_checksum",
                                     [np.full(L, 200, np.int64)])
    checks["coremark_mix"] = compare(build_coremark_kernel(), "coremark",
                                     [np.full(L, 64, np.int64)])
    f64_wat = """(module (func (export "f") (param f64 f64) (result f64)
      (f64.div (f64.add (f64.sqrt (local.get 0))
                        (f64.mul (local.get 1) (f64.const 0.1)))
               (f64.sub (local.get 0) (f64.const 1.5)))))"""
    bits = np.array([typed_to_bits(ValType.F64, float(x))
                     for x in rng.uniform(2, 100, L)],
                    np.uint64).view(np.int64)
    bits2 = np.array([typed_to_bits(ValType.F64, float(x))
                      for x in rng.uniform(-50, 50, L)],
                     np.uint64).view(np.int64)
    checks["f64_softfloat"] = compare(parse_wat(f64_wat), "f", [bits, bits2])
    f32_wat = """(module (func (export "f") (param f32 f32) (result f32)
      (f32.mul (f32.add (local.get 0) (local.get 1))
               (f32.sub (local.get 0) (local.get 1)))))"""
    b32 = np.array([typed_to_bits(ValType.F32, float(x))
                    for x in rng.uniform(-1e3, 1e3, L)], np.int64)
    c32 = np.array([typed_to_bits(ValType.F32, float(x))
                    for x in rng.uniform(-1e3, 1e3, L)], np.int64)
    checks["f32_arith"] = compare(parse_wat(f32_wat), "f", [b32, c32])
    div_wat = """(module (func (export "f") (param i32 i32) (result i32)
      (i32.div_s (local.get 0) (local.get 1))))"""
    divisors = rng.integers(-5, 5, L).astype(np.int64)  # incl. zeros
    checks["div_traps"] = compare(parse_wat(div_wat), "f",
                                  [np.full(L, 840, np.int64), divisors])
    checks["divergent_fib"] = compare(build_fib(), "fib",
                                      [(np.arange(L) % 15).astype(np.int64)])
    imp = ImportObject("env")
    imp.add_func("x2", PyHostFunction(lambda mem, x: x * 2,
                                      ["i32"], ["i32"]))
    from wasmedge_tpu.utils.builder import ModuleBuilder
    hb = ModuleBuilder()
    hb.import_func("env", "x2", ["i32"], ["i32"])
    hb.add_function(["i32"], ["i32"], [],
                    [("local.get", 0), ("call", 0)], export="f")
    checks["hostcall"] = compare(hb.build(), "f",
                                 [np.arange(L, dtype=np.int64)],
                                 imports=[imp])

    total_bad = sum(checks.values())
    out = {"platform": platform, "lanes_per_check": L,
           "mismatched_lanes": checks, "ok": total_bad == 0}
    print(json.dumps(out))
    sys.exit(0 if total_bad == 0 else 1)


if __name__ == "__main__":
    main()
