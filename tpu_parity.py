"""Real-hardware parity evidence: batch engines vs the scalar oracle ON TPU.

The pytest suites prove parity on the IEEE CPU backend (tests/conftest.py
pins it); this script runs a representative slice on the actual chip —
Pallas kernel compiled by Mosaic, XLA SIMT compiled for TPU — and records
the result (TPU_PARITY_r02.json).  Covers the areas where hardware could
plausibly diverge: f32 arithmetic (FTZ kept out of the integer-domain
paths), softfloat f64, i64 carry chains, memory byte addressing, traps,
divergence handoff, and host outcalls."""

import json
import sys

import numpy as np

from wasmedge_tpu.common.configure import Configure
from wasmedge_tpu.common.errors import TrapError
from wasmedge_tpu.common.types import ValType, typed_to_bits
from wasmedge_tpu.models import (
    build_coremark_kernel, build_fac, build_fib, build_memory_workload)
from wasmedge_tpu.runtime.hostfunc import ImportObject, PyHostFunction
from wasmedge_tpu.utils.wat import parse_wat
from tests.helpers import instantiate


def compare(data, func, per_lane_args, lanes=4096, imports=None,
            max_steps=3_000_000):
    """Batch engines at 4096 lanes (Lblk=4096 -> the 8-sublane remapped
    Pallas layout on TPU, r05) vs the scalar oracle.  The oracle is
    memoized by the lane's argument tuple: families use a bounded set
    of distinct args, so 4096 lanes cost ~#distinct scalar runs."""
    from wasmedge_tpu.batch.uniform import UniformBatchEngine

    conf = Configure()
    conf.batch.steps_per_launch = 1_000_000
    ex, store, inst = instantiate(data, conf, imports=imports)
    eng = UniformBatchEngine(inst, store=store, conf=conf, lanes=lanes)
    args = [np.asarray(a, np.int64) for a in per_lane_args]
    res = eng.run(func, args, max_steps=max_steps)
    mismatches = 0
    oracle = {}
    for lane in range(lanes):
        largs = tuple(int(a[lane]) & ((1 << 64) - 1) for a in args)
        if largs not in oracle:
            s_ex, s_store, s_inst = instantiate(data, Configure(),
                                                imports=imports)
            try:
                oracle[largs] = ("ok", s_ex.invoke_raw(
                    s_store, s_inst.find_func(func), list(largs)))
            except TrapError as te:
                oracle[largs] = ("trap", int(te.code))
        kind, expect = oracle[largs]
        if kind == "ok":
            ok = res.trap[lane] == -1 and all(
                (int(res.results[i][lane]) & ((1 << 64) - 1)) == v
                for i, v in enumerate(expect))
        else:
            ok = res.trap[lane] == expect
        mismatches += 0 if ok else 1
    return mismatches


def main():
    import jax

    platform = jax.devices()[0].platform
    checks = {}
    L = 4096
    B = 256  # distinct-value base tiled over the lanes
    rng = np.random.default_rng(0)

    def tileL(base):
        base = np.asarray(base, np.int64)
        return np.tile(base, L // len(base))

    checks["fib_i32"] = compare(build_fib(), "fib",
                                [np.full(L, 20, np.int64)])
    checks["fac_i64"] = compare(build_fac(), "fac",
                                [np.full(L, 20, np.int64)])
    checks["memory_bytes"] = compare(build_memory_workload(), "mem_checksum",
                                     [np.full(L, 200, np.int64)])
    checks["coremark_mix"] = compare(build_coremark_kernel(), "coremark",
                                     [np.full(L, 64, np.int64)])
    f64_wat = """(module (func (export "f") (param f64 f64) (result f64)
      (f64.div (f64.add (f64.sqrt (local.get 0))
                        (f64.mul (local.get 1) (f64.const 0.1)))
               (f64.sub (local.get 0) (f64.const 1.5)))))"""
    bits = tileL(np.array([typed_to_bits(ValType.F64, float(x))
                           for x in rng.uniform(2, 100, B)],
                          np.uint64).view(np.int64))
    bits2 = tileL(np.array([typed_to_bits(ValType.F64, float(x))
                            for x in rng.uniform(-50, 50, B)],
                           np.uint64).view(np.int64))
    checks["f64_softfloat"] = compare(parse_wat(f64_wat), "f", [bits, bits2])
    f32_wat = """(module (func (export "f") (param f32 f32) (result f32)
      (f32.mul (f32.add (local.get 0) (local.get 1))
               (f32.sub (local.get 0) (local.get 1)))))"""
    b32 = tileL([typed_to_bits(ValType.F32, float(x))
                 for x in rng.uniform(-1e3, 1e3, B)])
    c32 = tileL([typed_to_bits(ValType.F32, float(x))
                 for x in rng.uniform(-1e3, 1e3, B)])
    checks["f32_arith"] = compare(parse_wat(f32_wat), "f", [b32, c32])
    div_wat = """(module (func (export "f") (param i32 i32) (result i32)
      (i32.div_s (local.get 0) (local.get 1))))"""
    divisors = tileL(rng.integers(-5, 5, B))  # incl. zeros
    checks["div_traps"] = compare(parse_wat(div_wat), "f",
                                  [np.full(L, 840, np.int64), divisors])
    checks["divergent_fib"] = compare(build_fib(), "fib",
                                      [(np.arange(L) % 15).astype(np.int64)])
    imp = ImportObject("env")
    imp.add_func("x2", PyHostFunction(lambda mem, x: x * 2,
                                      ["i32"], ["i32"]))
    from wasmedge_tpu.utils.builder import ModuleBuilder
    hb = ModuleBuilder()
    hb.import_func("env", "x2", ["i32"], ["i32"])
    hb.add_function(["i32"], ["i32"], [],
                    [("local.get", 0), ("call", 0)], export="f")
    checks["hostcall"] = compare(hb.build(), "f",
                                 [(np.arange(L) % B).astype(np.int64)],
                                 imports=[imp])

    # -- round-4 surfaces -------------------------------------------------
    # HBM window-cache memory mode: stride walk crossing window
    # boundaries with unaligned i64 stores (auto-selected at 256 lanes)
    edge_wat = """(module (memory 1 2)
      (func (export "f") (param i32) (result i64)
        (local $i i32) (local $acc i64)
        (block (loop
          (br_if 1 (i32.ge_u (local.get $i) (local.get 0)))
          (i64.store offset=6 (i32.mul (local.get $i) (i32.const 520))
            (i64.xor (i64.extend_i32_u (local.get $i))
                     (i64.const 81985529216486895)))
          (local.set $acc (i64.xor (local.get $acc)
            (i64.load offset=6 (i32.mul (local.get $i)
                                        (i32.const 520)))))
          (local.set $i (i32.add (local.get $i) (i32.const 1)))
          (br 0)))
        (local.get $acc)))"""
    checks["hbm_window_walk"] = compare(parse_wat(edge_wat), "f",
                                        [np.full(L, 100, np.int64)])
    # optimistic rollback on partially-OOB loads (canary -> careful)
    oob_wat = """(module (memory 1 1)
      (func (export "f") (param i32) (result i32)
        (i32.load (local.get 0))))"""
    addrs = np.where(np.arange(L) % 7 == 3, 70000,
                     ((np.arange(L) % B) * 8) % 60000).astype(np.int64)
    checks["optimistic_partial_oob"] = compare(parse_wat(oob_wat), "f",
                                               [addrs])
    # SIMD on the batch path (integer + float families, SIMT fallback)
    simd_wat = """(module
      (func (export "f") (param i64 i64) (result i64) (local v128)
        (local.set 2
          (i32x4.add
            (i16x8.mul (i64x2.splat (local.get 0))
                       (i64x2.splat (local.get 1)))
            (i8x16.sub (i64x2.splat (local.get 1))
                       (i64x2.splat (local.get 0)))))
        (i64.xor (i64x2.extract_lane 0 (local.get 2))
                 (i64x2.extract_lane 1 (local.get 2)))))"""
    xs = tileL(rng.integers(-2**62, 2**62, B))
    ys = tileL(rng.integers(-2**62, 2**62, B))
    checks["simd_int"] = compare(parse_wat(simd_wat), "f", [xs, ys],
                                 max_steps=1_000_000)
    simd_f_wat = """(module
      (func (export "f") (param i64 i64) (result i64) (local v128)
        (local.set 2
          (f64x2.mul (f64x2.add (i64x2.splat (local.get 0))
                                (i64x2.splat (local.get 1)))
                     (v128.const f64x2 1.5 1.5)))
        (i64x2.extract_lane 0 (local.get 2))))"""
    fb = tileL(np.array([typed_to_bits(ValType.F64, float(x))
                         for x in rng.uniform(-100, 100, B)],
                        np.uint64).view(np.int64))
    fb2 = tileL(np.array([typed_to_bits(ValType.F64, float(x))
                          for x in rng.uniform(0.5, 8, B)],
                         np.uint64).view(np.int64))
    checks["simd_f64"] = compare(parse_wat(simd_f_wat), "f", [fb, fb2],
                                 max_steps=1_000_000)
    # bulk memory inside the kernel (fill + copy + checksum)
    bulk_wat = """(module (memory 1 1)
      (func (export "f") (param i32) (result i32)
        (memory.fill (i32.const 256) (local.get 0) (i32.const 512))
        (memory.copy (i32.const 1024) (i32.const 256) (i32.const 512))
        (i32.add (i32.load (i32.const 1500))
                 (i32.load (i32.const 300)))))"""
    checks["bulk_fill_copy"] = compare(
        parse_wat(bulk_wat), "f",
        [(np.arange(L) % 251).astype(np.int64)])

    total_bad = sum(checks.values())
    out = {"platform": platform, "lanes_per_check": L,
           "mismatched_lanes": checks, "ok": total_bad == 0}
    print(json.dumps(out))
    with open("TPU_PARITY_r05.json", "w") as f:
        json.dump(out, f)
    sys.exit(0 if total_bad == 0 else 1)


if __name__ == "__main__":
    main()
