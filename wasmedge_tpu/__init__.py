"""tpu-wasm: a TPU-native WebAssembly runtime with WasmEdge's capabilities.

Pipeline (mirrors the reference's Load -> Validate -> Instantiate -> Execute
staging, /root/reference/include/vm/vm.h:241):

  loader    : bytes -> AST (flat, branch-annotated instructions)
  validator : type-check + lowering to a dense SoA bytecode image
  executor  : scalar reference engine (oracle) over the lowered image
  batch     : SIMT lockstep JAX/Pallas engine, thousands of lanes per chip
  host      : WASI + process host modules (device lanes trap out to CPU)
  vm        : VM facade + Configure-driven engine selection
"""

__version__ = "0.1.0"

# Import-tax discipline: this module (and everything it pulls in) must
# stay free of jax/jaxlib/numpy so `import wasmedge_tpu` and the
# scalar/native CLI paths never pay the JAX import tax (~1s of the
# AOT_r05 python_spawn_floor).  Heavy entry points are exposed lazily
# below; tests/test_spawn_time.py asserts the invariant in a fresh
# interpreter.
from wasmedge_tpu.common.configure import Configure, EngineKind
from wasmedge_tpu.common.errors import ErrCode, TrapError, WasmError

_LAZY = {
    "VM": ("wasmedge_tpu.vm", "VM"),
    "make_engine": ("wasmedge_tpu.batch", "make_engine"),
    "WasiModule": ("wasmedge_tpu.host.wasi", "WasiModule"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target[0]), target[1])


__all__ = [
    "Configure",
    "EngineKind",
    "ErrCode",
    "TrapError",
    "WasmError",
    "VM",
    "make_engine",
    "WasiModule",
]
