"""tpu-wasm: a TPU-native WebAssembly runtime with WasmEdge's capabilities.

Pipeline (mirrors the reference's Load -> Validate -> Instantiate -> Execute
staging, /root/reference/include/vm/vm.h:241):

  loader    : bytes -> AST (flat, branch-annotated instructions)
  validator : type-check + lowering to a dense SoA bytecode image
  executor  : scalar reference engine (oracle) over the lowered image
  batch     : SIMT lockstep JAX/Pallas engine, thousands of lanes per chip
  host      : WASI + process host modules (device lanes trap out to CPU)
  vm        : VM facade + Configure-driven engine selection
"""

__version__ = "0.1.0"

from wasmedge_tpu.common.configure import Configure, EngineKind
from wasmedge_tpu.common.errors import ErrCode, TrapError, WasmError

__all__ = [
    "Configure",
    "EngineKind",
    "ErrCode",
    "TrapError",
    "WasmError",
]
