from wasmedge_tpu.cli import main
import sys

sys.exit(main())
