"""Static bytecode analysis over the validated/lowered image.

Three consumers share one analysis (built once per module lowering):

  - `wasmedge-tpu analyze mod.wasm` — JSON report + annotated disasm
  - `DeviceImage.analysis` — attached at image-build time, block
    metadata for the superinstruction/fusion tier (ROADMAP #3) and the
    divergence scheduler (ROADMAP #5)
  - gateway admission — `POST /v1/modules` evaluates the report
    against per-tenant AnalysisPolicy limits (analysis/policy.py)
"""

from wasmedge_tpu.analysis.absint import (  # noqa: F401
    FuncAbsint,
    LoopFact,
    MemFact,
    analyze_module_absint,
    loop_nest_cost,
)
from wasmedge_tpu.analysis.analyzer import (  # noqa: F401
    SCHEMA,
    FuncAnalysis,
    HostcallSite,
    ModuleAnalysis,
    analyze_module,
    analyze_validated,
)
from wasmedge_tpu.analysis.cfg import (  # noqa: F401
    BasicBlock,
    FuncCFG,
    build_func_cfg,
)
from wasmedge_tpu.analysis.policy import (  # noqa: F401
    AnalysisPolicy,
    AnalysisRejection,
)
from wasmedge_tpu.analysis.report import validate_report  # noqa: F401
