"""Abstract-interpretation value-range analysis over the lowered CFG.

A sound intraprocedural abstract interpreter in the superinstruction
lineage of "A fast in-place interpreter for WebAssembly": it turns
static facts into admission precision (finite cost bounds for counted
loops), tighter hv footprint budgets (proven max page touch), and a
new fused-dispatch class (statically-licensed load/store runs,
batch/fuse.py).

Domain
------
Each abstract value is an (interval, congruence) pair over the i32
signed range:

    (lo, hi, mod, rem)   value in [lo, hi], value === rem  (mod mod)

`mod` is a power of two <= 2**16.  Congruence survives i32 wraparound
(powers of two divide 2**32), so alignment facts stay precise even
when the interval widens to TOP.  Interval arithmetic that could wrap
collapses the interval to the full range instead of guessing.

State flows per basic block over the LOCALS vector (+ module globals
that are provably never written — their initial value is a constant).
The operand stack is tracked only *within* a block (suffix-only: a
block entry's inherited stack is unknown).  Addresses and loop tests
in lowered WAT are computed in-block from locals, so this loses almost
nothing while making the transfer independent of cross-block arity
bookkeeping.

Loop heads (the r12 CFG's `is_loop_head` marking) widen after
`WIDEN_DELAY` joins; after the ascending fixpoint two descending
(narrowing) Jacobi passes re-run every transfer without widening —
monotone F applied to a post-fixpoint stays above the least fixpoint,
so the result is still sound while conditional-branch refinement
(`i < N` on the continue edge) pulls widened bounds back down to the
loop invariant.  Structured wasm control flow is reducible, so every
CFG cycle passes a marked loop head and the ascending phase
terminates; MAX_ITERS is a belt-and-suspenders bail-out that degrades
to "no facts", never to a wrong fact.

Products (consumed by analysis/analyzer.py)
-------------------------------------------
  - trip bounds for counted loops: a unique-head SCC whose back-edge
    blocks each increment one induction local by the same constant
    step, tested against a constant / loop-invariant ranged limit.
    Composed through `loop_nest_cost` the previously-"unbounded"
    function gets a finite sound cost bound (exact on the canonical
    latch-tested single-block counted loop).
  - per-site memory-effect facts: static effective-address range +
    alignment class for every load/store; `licensed` means proven
    in-bounds against the module's MINIMUM memory (initial pages —
    memory only grows) and aligned enough to never straddle a device
    word, i.e. the access can never trap.  batch/fuse.py compiles
    licensed straight-line runs into fused gather/scatter cells.
  - proven max page touch (`mem_pages_touch_bound`) feeding the hv
    resident-budget math (hv/policy.py effective_lane_bytes).

Soundness contract: every fact holds for EVERY concrete execution of
the function from its entry (params unknown).  Anything the analysis
cannot prove degrades to TOP / no-license / unbounded — never a guess.
Pure Python over numpy planes: importable without jax (the analyze
CLI and the image-build analysis thunk both run device-free).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

import numpy as np

I32_MIN = -(1 << 31)
I32_MAX = (1 << 31) - 1
_MOD_CAP = 1 << 16          # congruence modulus ceiling (page math)
WIDEN_DELAY = 2             # plain joins at a loop head before widening
NARROW_PASSES = 2           # descending Jacobi passes after the fixpoint
MAX_ITERS = 10_000          # worklist safety valve: a hit bails out to
#                             "no facts" (sound), never to a wrong fact

TOP = (I32_MIN, I32_MAX, 1, 0)


def _pow2_gcd(*vals) -> int:
    """Largest power of two dividing gcd(vals) (all-zero -> cap)."""
    g = 0
    for v in vals:
        g = math.gcd(g, int(abs(v)))
    if g == 0:
        return _MOD_CAP
    return min(g & (-g), _MOD_CAP)


def const_val(c: int):
    c = int(c)
    return (c, c, _MOD_CAP, c % _MOD_CAP)


def _clamp(lo, hi, mod, rem):
    """Interval overflow -> full range; congruence survives wraparound
    (every mod is a power of two dividing 2**32)."""
    mod = max(int(mod), 1)
    rem = int(rem) % mod
    if lo < I32_MIN or hi > I32_MAX or lo > hi:
        return (I32_MIN, I32_MAX, mod, rem)
    return (int(lo), int(hi), mod, rem)


def join(a, b):
    m = _pow2_gcd(a[2], b[2], a[3] - b[3])
    return (min(a[0], b[0]), max(a[1], b[1]), m, a[3] % m)


def widen(old, new):
    lo = old[0] if new[0] >= old[0] else I32_MIN
    hi = old[1] if new[1] <= old[1] else I32_MAX
    m = _pow2_gcd(old[2], new[2], old[3] - new[3])
    return (lo, hi, m, old[3] % m)


def v_add(a, b):
    m = _pow2_gcd(a[2], b[2])
    return _clamp(a[0] + b[0], a[1] + b[1], m, a[3] + b[3])


def v_sub(a, b):
    m = _pow2_gcd(a[2], b[2])
    return _clamp(a[0] - b[1], a[1] - b[0], m, a[3] - b[3])


def v_mul(a, b):
    # exact when either side is a known constant; otherwise keep only
    # the congruence product (mixed-sign interval products are fiddly
    # and nothing downstream needs them)
    if a[0] == a[1]:
        a, b = b, a
    if b[0] == b[1]:
        c = b[0]
        if c == 0:
            return const_val(0)
        lo, hi = sorted((a[0] * c, a[1] * c))
        return _clamp(lo, hi, _pow2_gcd(a[2] * c), a[3] * c)
    m = _pow2_gcd(a[2] * b[3], b[2] * a[3], a[2] * b[2])
    return (I32_MIN, I32_MAX, m, (a[3] * b[3]) % max(m, 1))


def v_shl(a, k_val):
    if k_val[0] != k_val[1]:
        return TOP
    return v_mul(a, const_val(1 << (k_val[0] & 31)))


def v_and(a, b):
    # x & y <= min(x, y) when the bound side is non-negative
    if a[0] == a[1]:
        a, b = b, a
    if b[0] == b[1] and b[0] >= 0:
        return (0, b[0], 1, 0)
    if a[0] >= 0 and b[0] >= 0:
        return (0, min(a[1], b[1]), 1, 0)
    if a[0] >= 0:
        return (0, a[1], 1, 0)
    if b[0] >= 0:
        return (0, b[1], 1, 0)
    return TOP


BOOL = (0, 1, 1, 0)


# ---------------------------------------------------------------------------
# symbolic terms (trip-bound + branch-refinement bookkeeping)
#
#   ('k', c)          constant c
#   ('cur', i, d)     current value of local i == block-entry value + d
#   ('cmp', op, lsym, lval, rsym, rval)
#                     i32 comparison; the operand syms AND their
#                     abstract values at compare time
# ---------------------------------------------------------------------------

_CMP_NEG = {"eq": "ne", "ne": "eq",
            "lt_s": "ge_s", "ge_s": "lt_s", "gt_s": "le_s",
            "le_s": "gt_s", "lt_u": "ge_u", "ge_u": "lt_u",
            "gt_u": "le_u", "le_u": "gt_u"}
_CMP_SWAP = {"eq": "eq", "ne": "ne",
             "lt_s": "gt_s", "gt_s": "lt_s", "le_s": "ge_s",
             "ge_s": "le_s", "lt_u": "gt_u", "gt_u": "lt_u",
             "le_u": "ge_u", "ge_u": "le_u"}


@dataclasses.dataclass
class MemFact:
    """Static effect of one memory-access site (absolute image pc)."""

    pc: int
    kind: str            # "load" / "store" / "vload" / "vstore" / "bulk"
    nbytes: int
    lo: Optional[int]    # effective-address range (None = unproven)
    hi: Optional[int]
    align: int           # largest power of two dividing every address
    in_bounds: bool      # proven < initial pages for every execution
    aligned: bool        # proven never to straddle a device word
    licensed: bool       # in_bounds & aligned -> fusable (v128 too)

    def asdict(self) -> dict:
        return {"pc": self.pc, "kind": self.kind, "nbytes": self.nbytes,
                "lo": self.lo, "hi": self.hi, "align": self.align,
                "in_bounds": self.in_bounds, "aligned": self.aligned,
                "licensed": self.licensed}


@dataclasses.dataclass
class LoopFact:
    """One CFG loop: the r12 head block + the absint trip verdict."""

    head_pc: int                # start pc of the loop-head block
    trip_bound: Optional[int]   # max head executions; None = unproven

    def asdict(self) -> dict:
        return {"head": self.head_pc, "trip_bound": self.trip_bound}


@dataclasses.dataclass
class FuncAbsint:
    """Per-function absint products."""

    ok: bool = False
    loops: List[LoopFact] = dataclasses.field(default_factory=list)
    mem_facts: List[MemFact] = dataclasses.field(default_factory=list)
    trips: Dict[int, int] = dataclasses.field(default_factory=dict)
    # block_idx -> trip bound (loop_nest_cost's input; head blocks only)


# ---------------------------------------------------------------------------
# classified cells + per-class arity
# ---------------------------------------------------------------------------

class _Cells:
    """The classified device cells absint interprets.  Built once per
    module from the lowered image via batch/image.build_device_image
    (numpy only, no jax) so the transfer function reads the SAME
    two-level dispatch encoding the engine executes."""

    def __init__(self, image, globals_init=None):
        from wasmedge_tpu.batch.image import (
            ALU2_I32_BASE, CLS_GLOBAL_SET, _I32_BIN, build_device_image)

        dev = build_device_image(image)
        self.cls = dev.cls
        self.sub = dev.sub
        self.a = dev.a
        self.b = dev.b
        self.c = dev.c
        self.imm_lo = dev.imm_lo
        self.f_nparams = dev.f_nparams
        self.f_nresults = dev.f_nresults
        self.i32_sub_name = {ALU2_I32_BASE + i: n
                             for i, n in enumerate(_I32_BIN)}
        # globals never written anywhere in the module keep their
        # initial value ("non-escaping": nothing can mutate them)
        self.written_globals = set(
            int(x) for x in dev.a[dev.cls == CLS_GLOBAL_SET])
        self.globals_init = list(globals_init) if globals_init else None


def _arity_table():
    """(pops, pushes) per opcode class for cells the transfer does not
    model precisely — their results are TOP, stack depth stays exact."""
    from wasmedge_tpu.batch import image as im

    return {
        im.CLS_NOP: (0, 0), im.CLS_CONST: (0, 1),
        im.CLS_LOCAL_GET: (0, 1), im.CLS_LOCAL_SET: (1, 0),
        im.CLS_LOCAL_TEE: (1, 1), im.CLS_GLOBAL_GET: (0, 1),
        im.CLS_GLOBAL_SET: (1, 0), im.CLS_ALU1: (1, 1),
        im.CLS_ALU2: (2, 1), im.CLS_SELECT: (3, 1),
        im.CLS_DROP: (1, 0), im.CLS_LOAD: (1, 1),
        im.CLS_STORE: (2, 0), im.CLS_MEMSIZE: (0, 1),
        im.CLS_MEMGROW: (1, 1), im.CLS_MEMFILL: (3, 0),
        im.CLS_MEMCOPY: (3, 0), im.CLS_VCONST: (0, 1),
        im.CLS_V2: (2, 1), im.CLS_V1: (1, 1), im.CLS_VTEST: (1, 1),
        im.CLS_VSHIFT: (2, 1), im.CLS_VSPLAT: (1, 1),
        im.CLS_VEXTRACT: (1, 1), im.CLS_VREPLACE: (2, 1),
        im.CLS_VSHUFFLE: (2, 1), im.CLS_VBITSEL: (3, 1),
        im.CLS_VLOAD: (1, 1), im.CLS_VSTORE: (2, 0),
        im.CLS_TABLE_GET: (1, 1), im.CLS_TABLE_SET: (2, 0),
        im.CLS_TABLE_SIZE: (0, 1), im.CLS_TABLE_GROW: (2, 1),
        im.CLS_TABLE_FILL: (3, 0), im.CLS_TABLE_COPY: (3, 0),
        im.CLS_TABLE_INIT: (3, 0), im.CLS_ELEM_DROP: (0, 0),
        im.CLS_MEMINIT: (3, 0), im.CLS_DATA_DROP: (0, 0),
        im.CLS_REFFUNC: (0, 1), im.CLS_TRAP: (0, 0),
    }


class _BlockScan:
    """Result of symbolically executing one block's straight-line run."""

    __slots__ = ("locals_out", "writes", "n_writes", "cond_sym",
                 "facts", "bulk_ends")

    def __init__(self):
        self.locals_out = None   # locals after the block body
        self.writes = {}         # local idx -> sym of LAST write
        #                          (('cur', i, d) / ('k', c) / None)
        self.n_writes = {}       # local idx -> write count
        self.cond_sym = None     # ('cmp', ...) at a brz/brnz terminator
        self.facts = []          # MemFact list (final pass only)
        self.bulk_ends = []      # per bulk op: proven end byte or None


def _transfer_block(cells: _Cells, arity, block, locals_in,
                    globals_const, min_mem_bytes, collect_facts,
                    mem_decl_max_pages):
    """Symbolically run one block's straight-line body from the entry
    locals.  Returns a _BlockScan."""
    from wasmedge_tpu.batch import image as im

    env = list(locals_in)
    locsym: Dict[int, tuple] = {}
    stack: List[tuple] = []      # (absval, sym-or-None), suffix only
    scan = _BlockScan()

    def cur_sym(i):
        # a local WRITTEN in this block keeps its recorded sym — which
        # is None after an opaque (non-affine) write, severing the
        # entry-value relation for every later read: a comparison
        # computed before the clobber must never refine the interval
        # of the post-clobber value
        if i in locsym:
            return locsym[i]
        return ("cur", i, 0)

    def push(v, s=None):
        stack.append((v, s))

    def pop():
        return stack.pop() if stack else (TOP, None)

    def write_local(a, v, s):
        if not (0 <= a < len(env)):
            return
        env[a] = v
        ws = None
        if s is not None and (s[0] == "k"
                              or (s[0] == "cur" and s[1] == a)):
            ws = s
        # an opaque write stores None EXPLICITLY (never popped): a
        # later read must see "severed", not fall back to the
        # pristine entry-value sym — that fabricated baseline would
        # let a pre-clobber comparison refine a post-clobber value
        # (a false license, the one unsound shape)
        locsym[a] = ws
        scan.writes[a] = ws
        scan.n_writes[a] = scan.n_writes.get(a, 0) + 1

    end = block.end if block.kind == "fallthrough" else block.end - 1
    for pc in range(block.start, end + 1):
        k = int(cells.cls[pc])
        sub = int(cells.sub[pc])
        a = int(cells.a[pc])
        if k == im.CLS_NOP:
            continue
        if k == im.CLS_CONST:
            c = int(cells.imm_lo[pc])
            push(const_val(c), ("k", c))
        elif k == im.CLS_LOCAL_GET:
            if 0 <= a < len(env):
                push(env[a], cur_sym(a))
            else:
                push(TOP)
        elif k in (im.CLS_LOCAL_SET, im.CLS_LOCAL_TEE):
            v, s = pop()
            if k == im.CLS_LOCAL_TEE:
                push(v, s)
            write_local(a, v, s)
        elif k == im.CLS_GLOBAL_GET:
            push(globals_const.get(a, TOP))
        elif k == im.CLS_GLOBAL_SET:
            pop()
        elif k == im.CLS_ALU2:
            name = cells.i32_sub_name.get(sub)
            y, ys = pop()
            x, xs = pop()
            if name in _CMP_NEG:            # i32 comparison family
                sym = None
                if xs is not None or ys is not None:
                    sym = ("cmp", name, xs, x, ys, y)
                push(BOOL, sym)
            elif name == "add":
                s = None
                if xs and ys and xs[0] == "cur" and ys[0] == "k":
                    s = ("cur", xs[1], xs[2] + ys[1])
                elif xs and ys and xs[0] == "k" and ys[0] == "cur":
                    s = ("cur", ys[1], ys[2] + xs[1])
                elif xs and ys and xs[0] == "k" and ys[0] == "k":
                    s = ("k", xs[1] + ys[1])
                push(v_add(x, y), s)
            elif name == "sub":
                s = None
                if xs and ys and xs[0] == "cur" and ys[0] == "k":
                    s = ("cur", xs[1], xs[2] - ys[1])
                elif xs and ys and xs[0] == "k" and ys[0] == "k":
                    s = ("k", xs[1] - ys[1])
                push(v_sub(x, y), s)
            elif name == "mul":
                push(v_mul(x, y))
            elif name == "and":
                push(v_and(x, y))
            elif name in ("or", "xor"):
                # non-negative operands stay under the next power of two
                if x[0] >= 0 and y[0] >= 0:
                    bound = (1 << max(x[1], y[1], 1).bit_length()) - 1
                    push(_clamp(0, bound, 1, 0))
                else:
                    push(TOP)
            elif name == "shl":
                push(v_shl(x, y))
            elif name in ("shr_u", "shr_s"):
                if y[0] == y[1] and x[0] >= 0:
                    sh = y[0] & 31
                    push(_clamp(x[0] >> sh, x[1] >> sh, 1, 0))
                else:
                    push(TOP)
            else:
                push(TOP)
        elif k == im.CLS_ALU1:
            pop()
            # i32.eqz / i64.eqz produce booleans; the rest is TOP
            push(BOOL if sub in (3, 9) else TOP)
        elif k == im.CLS_SELECT:
            pop()
            v2, _ = pop()
            v1, _ = pop()
            push(join(v1, v2))
        elif k == im.CLS_DROP:
            pop()
        elif k in (im.CLS_LOAD, im.CLS_VLOAD):
            addr, _ = pop()
            if collect_facts:
                scalar = k == im.CLS_LOAD
                scan.facts.append(_mem_fact(
                    pc, "load" if scalar else "vload",
                    int(cells.b[pc]) if scalar else 16,
                    addr, a, min_mem_bytes))
            push(TOP)
        elif k in (im.CLS_STORE, im.CLS_VSTORE):
            pop()                           # value
            addr, _ = pop()
            if collect_facts:
                scalar = k == im.CLS_STORE
                scan.facts.append(_mem_fact(
                    pc, "store" if scalar else "vstore",
                    int(cells.b[pc]) if scalar else 16,
                    addr, a, min_mem_bytes))
        elif k in (im.CLS_MEMFILL, im.CLS_MEMCOPY, im.CLS_MEMINIT):
            n, _ = pop()
            src, _ = pop()
            dst, _ = pop()
            if collect_facts:
                bases = (dst, src) if k == im.CLS_MEMCOPY else (dst,)
                for base in bases:
                    if base[0] >= 0 and n[0] >= 0 \
                            and base[1] <= I32_MAX - n[1]:
                        scan.bulk_ends.append(base[1] + n[1])
                    else:
                        scan.bulk_ends.append(None)
        elif k == im.CLS_MEMSIZE:
            lo = max(min_mem_bytes // 65536, 0)
            hi = mem_decl_max_pages if mem_decl_max_pages > 0 else 65536
            push(_clamp(lo, max(hi, lo), 1, 0))
        elif k == im.CLS_MEMGROW:
            pop()
            push(_clamp(-1, 65536, 1, 0))
        elif k in (im.CLS_CALL, im.CLS_RETCALL):
            npar = int(cells.f_nparams[a]) \
                if 0 <= a < len(cells.f_nparams) else 0
            nres = int(cells.f_nresults[a]) \
                if 0 <= a < len(cells.f_nresults) else 0
            for _ in range(npar):
                pop()
            for _ in range(nres):
                push(TOP)
        elif k in (im.CLS_CALL_INDIRECT, im.CLS_RETCALL_INDIRECT,
                   im.CLS_HOSTCALL):
            stack.clear()                   # unknown arity: whole
            #                                 in-block suffix is gone
        else:
            p, q = arity.get(k, (0, 0))
            for _ in range(p):
                pop()
            for _ in range(q):
                push(TOP)

    if block.kind in ("brz", "brnz"):
        cv, cs = pop()
        if cs is not None and cs[0] == "cur":
            # raw-value test: continue-while-nonzero == `ne 0`
            cs = ("cmp", "ne", cs, cv, ("k", 0), const_val(0))
        if cs is not None and cs[0] != "cmp":
            cs = None
        scan.cond_sym = cs
    scan.locals_out = env
    return scan


def _mem_fact(pc, kind, nbytes, addr, off,
              min_mem_bytes) -> MemFact:
    """MemFact for one access: ea = addr + static offset `off`."""
    off = int(np.uint32(np.int32(off)))     # offsets are u32 imm
    ea = v_add(addr, const_val(off)) if off <= I32_MAX else TOP
    m, r = ea[2], ea[3] % max(ea[2], 1)
    align = _pow2_gcd(m, r)                 # divides every address
    # word-straddle threshold: a v128 access (nbytes=16) at 4-aligned
    # addresses covers exactly four whole device words, so word
    # alignment is the requirement for EVERY width above one byte
    req = min(nbytes, 4)
    aligned = align % req == 0 if req > 1 else True
    known = ea[0] > I32_MIN or ea[1] < I32_MAX
    in_b = (known and ea[0] >= 0 and min_mem_bytes > 0
            and ea[1] <= min_mem_bytes - nbytes)
    return MemFact(
        pc=pc, kind=kind, nbytes=nbytes,
        lo=int(ea[0]) if known else None,
        hi=int(ea[1]) if known else None,
        align=int(align),
        in_bounds=bool(in_b), aligned=bool(aligned),
        licensed=bool(in_b and aligned))


def _refine(locals_vec, scan, truth) -> list:
    """Constrain the out-locals along one edge of a brz/brnz whose
    condition is a tracked comparison (`truth` = condition value on
    this edge)."""
    cs = scan.cond_sym
    if cs is None:
        return locals_vec
    _, name, lsym, lval, rsym, rval = cs
    if not truth:
        name = _CMP_NEG[name]
    out = list(locals_vec)

    def constrain(sym, other_val, cmp_name):
        if sym is None or sym[0] != "cur" or other_val is None:
            return
        i, d = sym[1], sym[2]
        if not (0 <= i < len(out)):
            return
        w = scan.writes.get(i)
        if i in scan.writes and (w is None or w[0] != "cur"):
            return                       # opaque write: cannot relate
        d_cur = w[2] if w is not None else 0
        shift = d_cur - d   # current value = compared value + shift
        lo, hi, m, r = out[i]
        if cmp_name in ("lt_u", "le_u"):
            # unsigned `x < N` with N in the non-negative signed range
            # bounds BOTH sides: the bit pattern is < N, so the signed
            # value sits in [0, N-1] — this is what recovers the lower
            # bound after a widened increment had to collapse to TOP
            if other_val[0] < 0:
                return
            lo = max(lo, 0 + shift)
            hi = min(hi, other_val[1] + shift
                     - (1 if cmp_name == "lt_u" else 0))
        elif cmp_name in ("gt_u", "ge_u"):
            # sound only where the signed and unsigned orders agree
            if lo < 0 or other_val[0] < 0:
                return
            lo = max(lo, other_val[0] + shift
                     + (1 if cmp_name == "gt_u" else 0))
        elif cmp_name == "lt_s":
            hi = min(hi, other_val[1] - 1 + shift)
        elif cmp_name == "le_s":
            hi = min(hi, other_val[1] + shift)
        elif cmp_name == "gt_s":
            lo = max(lo, other_val[0] + 1 + shift)
        elif cmp_name == "ge_s":
            lo = max(lo, other_val[0] + shift)
        elif cmp_name == "eq":
            lo = max(lo, other_val[0] + shift)
            hi = min(hi, other_val[1] + shift)
        else:
            return
        if lo > hi:         # contradictory edge: dead in the concrete;
            return          # keeping the old state stays sound
        out[i] = (lo, hi, m, r)

    constrain(lsym, rval, name)
    constrain(rsym, lval, _CMP_SWAP[name])
    return out


# ---------------------------------------------------------------------------
# the per-function driver
# ---------------------------------------------------------------------------

def analyze_func(cells: _Cells, cfg, fn_meta, mem_pages_init: int,
                 mem_pages_max: int, has_memory: bool) -> FuncAbsint:
    """Run the abstract interpreter over one defined function's CFG."""
    out = FuncAbsint()
    blocks = cfg.blocks
    if not blocks:
        out.ok = True
        return out
    arity = _arity_table()
    nloc = int(fn_meta.nlocals)
    npar = int(fn_meta.nparams)
    entry = [TOP] * npar + [const_val(0)] * max(nloc - npar, 0)
    globals_const: Dict[int, tuple] = {}
    if cells.globals_init:
        for gi, gv in enumerate(cells.globals_init):
            if gi not in cells.written_globals and gv is not None:
                globals_const[gi] = const_val(
                    int(np.int32(np.uint32(int(gv) & 0xFFFFFFFF))))
    min_mem = int(mem_pages_init) * 65536 if has_memory else 0

    idx_of = {b.start: i for i, b in enumerate(blocks)}
    succs = [[idx_of[s] for s in b.succ if s in idx_of] for b in blocks]
    preds: List[List[int]] = [[] for _ in blocks]
    for i, ss in enumerate(succs):
        for s in ss:
            preds[s].append(i)

    def run_block(i, locals_in, collect=False):
        return _transfer_block(cells, arity, blocks[i], locals_in,
                               globals_const, min_mem, collect,
                               mem_pages_max)

    def edge_states(i, scan):
        """(succ block idx, refined out-locals) per out edge."""
        b = blocks[i]
        outs = []
        if b.kind in ("brz", "brnz"):
            # succ[0] is the branch target, succ[1] the fallthrough;
            # brnz branches on nonzero (cmp true), brz on zero
            for ei, s in enumerate(b.succ):
                si = idx_of.get(s)
                if si is None:
                    continue
                truth = (ei == 0) == (b.kind == "brnz")
                outs.append((si, _refine(scan.locals_out, scan, truth)))
        else:
            for s in b.succ:
                si = idx_of.get(s)
                if si is not None:
                    outs.append((si, list(scan.locals_out)))
        return outs

    # -- ascending fixpoint with widening at loop heads ------------------
    in_state: Dict[int, list] = {0: entry}
    join_count = [0] * len(blocks)
    work = [0]
    iters = 0
    while work:
        iters += 1
        if iters > MAX_ITERS:
            return out                   # sound bail-out: no facts
        i = work.pop()
        st = in_state.get(i)
        if st is None:
            continue
        scan = run_block(i, st)
        for si, sout in edge_states(i, scan):
            old = in_state.get(si)
            if old is None:
                in_state[si] = sout
                work.append(si)
                continue
            new = [join(o, n) for o, n in zip(old, sout)]
            if new == old:
                continue
            if blocks[si].is_loop_head:
                join_count[si] += 1
                if join_count[si] > WIDEN_DELAY:
                    new = [widen(o, n) for o, n in zip(old, new)]
            in_state[si] = new
            work.append(si)

    # -- descending (narrowing) passes: monotone F applied to a post-
    # fixpoint stays above the least fixpoint, so the branch
    # refinement can pull widened loop-head bounds back down ------------
    for _ in range(NARROW_PASSES):
        new_in: Dict[int, list] = {0: list(entry)}
        for i in range(len(blocks)):
            st = in_state.get(i)
            if st is None:
                continue
            scan = run_block(i, st)
            for si, sout in edge_states(i, scan):
                cur = new_in.get(si)
                new_in[si] = sout if cur is None else \
                    [join(o, n) for o, n in zip(cur, sout)]
        in_state = new_in

    # -- final pass: collect facts + per-block scans for trip bounds ----
    scans: Dict[int, _BlockScan] = {}
    for i in range(len(blocks)):
        st = in_state.get(i)
        if st is None:
            continue
        scans[i] = run_block(i, st, collect=True)
        out.mem_facts.extend(scans[i].facts)
        for e in scans[i].bulk_ends:
            out.mem_facts.append(MemFact(
                pc=blocks[i].start, kind="bulk", nbytes=0,
                lo=0, hi=e, align=1,
                in_bounds=e is not None and e <= min_mem,
                aligned=True, licensed=False))

    # -- trip bounds per loop nest (recursive SCC decomposition, the
    # exact decomposition loop_nest_cost replays: an inner loop is a
    # cyclic SCC of the outer loop's body once the back edges into the
    # outer head are removed) --------------------------------------------
    def collect_loops(nodes, edges, depth):
        for comp in _sccs_sub(sorted(nodes), edges):
            cset = set(comp)
            if not (len(comp) > 1 or comp[0] in edges.get(comp[0], ())):
                continue
            heads = [n for n in comp
                     if n == 0 or any(p not in cset for p in preds[n])]
            trip = None
            head_blk = min(comp)
            if len(heads) == 1:
                head_blk = heads[0]
                trip = _trip_bound(blocks, cset, head_blk, scans,
                                   entry, idx_of, preds,
                                   edge_states)
                if trip is not None:
                    out.trips[head_blk] = trip
            out.loops.append(LoopFact(head_pc=blocks[head_blk].start,
                                      trip_bound=trip))
            if len(heads) == 1 and depth < 64:
                inner = {n: [s for s in edges.get(n, ())
                             if s in cset and s != heads[0]]
                         for n in cset}
                collect_loops(cset, inner, depth + 1)

    collect_loops(set(range(len(blocks))),
                  {i: list(ss) for i, ss in enumerate(succs)}, 0)
    out.loops.sort(key=lambda lf: lf.head_pc)
    out.ok = True
    return out


def _sccs(n, succs) -> List[List[int]]:
    """Iterative Tarjan over [0, n) (reverse-topological order)."""
    index = [0] * n
    low = [0] * n
    on = [False] * n
    seen = [False] * n
    stack: List[int] = []
    counter = [1]
    comps: List[List[int]] = []
    for root in range(n):
        if seen[root]:
            continue
        work = [(root, 0)]
        while work:
            v, ei = work[-1]
            if ei == 0:
                seen[v] = True
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on[v] = True
            advanced = False
            while ei < len(succs[v]):
                w = succs[v][ei]
                ei += 1
                if not seen[w]:
                    work[-1] = (v, ei)
                    work.append((w, 0))
                    advanced = True
                    break
                if on[w]:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on[w] = False
                    scc.append(w)
                    if w == v:
                        break
                comps.append(scc)
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
    return comps


def _trip_bound(blocks, sset, h, scans, entry_state, idx_of, preds,
                edge_states) -> Optional[int]:
    """Counted-loop trip bound for the SCC `sset` with unique head `h`,
    or None.  Requirements (each individually sound to refuse):

      - a single conditional test block t with one successor inside
        the SCC and one outside, where t is the head or the ONLY
        back-edge source (so every iteration passes the test);
      - condition `cmp(op, local i + d, limit)` with limit a constant
        or a loop-invariant local's ranged value;
      - every SCC write to local i sits in a back-edge source block,
        exactly once per such block, all with the same constant step.

    The returned bound counts executions of the test block — an upper
    bound on every SCC block's executions (each full traversal of the
    loop passes the test exactly once), which is what loop_nest_cost
    multiplies by the per-iteration path cost.
    """
    head_pc = blocks[h].start
    back_srcs = [n for n in sset
                 if any(s == head_pc for s in blocks[n].succ)]
    if not back_srcs:
        return None
    tests = []
    for n in sset:
        b = blocks[n]
        if b.kind not in ("brz", "brnz") or len(b.succ) != 2:
            continue
        in_s = [s for s in b.succ if idx_of.get(s) in sset]
        out_s = [s for s in b.succ if idx_of.get(s) not in sset]
        if len(in_s) == 1 and len(out_s) == 1:
            tests.append((n, in_s[0]))
    tests = [(n, cont) for n, cont in tests
             if n == h or (len(back_srcs) == 1 and back_srcs[0] == n)]
    if len(tests) != 1:
        return None
    t, cont = tests[0]
    scan = scans.get(t)
    if scan is None or scan.cond_sym is None:
        return None
    _, name, lsym, lval, rsym, rval = scan.cond_sym
    # normalize: induction local on the left
    if (lsym is None or lsym[0] != "cur") \
            and rsym is not None and rsym[0] == "cur":
        lsym, lval, rsym, rval = rsym, rval, lsym, lval
        name = _CMP_SWAP[name]
    if lsym is None or lsym[0] != "cur":
        return None
    i, d = lsym[1], lsym[2]
    # continue-edge orientation: the brnz branch edge is cond-true
    taken_is_continue = (blocks[t].kind == "brnz") == \
        (cont == blocks[t].succ[0])
    op = name if taken_is_continue else _CMP_NEG[name]
    # limit: a constant, or a loop-invariant local read unmodified
    if rsym is not None and rsym[0] == "k":
        limit = const_val(rsym[1])
    elif rsym is not None and rsym[0] == "cur" and rsym[2] == 0 \
            and all(scans[n].n_writes.get(rsym[1], 0) == 0
                    for n in sset if n in scans):
        limit = rval
    else:
        return None
    # induction step: uniform across all back-edge source blocks
    step = None
    for n in sset:
        sc = scans.get(n)
        if sc is None:
            return None
        nw = sc.n_writes.get(i, 0)
        if nw == 0:
            continue
        w = sc.writes.get(i)
        if n not in back_srcs or nw != 1 or w is None \
                or w[0] != "cur" or w[1] != i:
            return None
        if w[2] == 0 or (step is not None and w[2] != step):
            return None
        step = w[2]
    if step is None:
        return None
    # the compared value's offset d is relative to the TEST block's
    # entry; when the test block also hosts the write, d already
    # includes the in-iteration step (the canonical latch shape)
    # entry value of local i at the head from OUTSIDE the loop only
    ext = None
    for p in preds[h]:
        if p in sset:
            continue
        pscan = scans.get(p)
        if pscan is None:
            continue
        for si, sout in edge_states(p, pscan):
            if si == h and i < len(sout):
                ext = sout[i] if ext is None else join(ext, sout[i])
    if ext is None:
        if h == 0 and i < len(entry_state):
            # the head IS the entry block: the only external "edge" is
            # the function entry itself (params TOP, locals zero) —
            # NOT the joined in-state, which already includes the
            # loop's own back-edge contributions
            ext = entry_state[i]
        else:
            return None
    i0_lo, i0_hi = ext[0], ext[1]
    n_lo, n_hi = limit[0], limit[1]
    if i0_lo <= I32_MIN or i0_hi >= I32_MAX \
            or n_lo <= I32_MIN or n_hi >= I32_MAX:
        return None
    if op.endswith("_u") and (i0_lo < 0 or n_lo < 0):
        return None                  # unsigned order != signed order

    def ceil_div(a, b):
        return -((-a) // b)

    # T = executions of the test block; the k-th test sees the value
    # i0 + (k-1)*step + d and continues while `value <op> limit`
    if step > 0:
        if op in ("lt_s", "lt_u"):
            t_max = ceil_div(n_hi - i0_lo - d, step) + 1
        elif op in ("le_s", "le_u"):
            t_max = (n_hi - i0_lo - d) // step + 2
        elif op == "ne":
            # an equality exit needs the advance per test to be EXACTLY
            # the step: the test block must be the sole back-edge
            # source (monotone compares tolerate extra increments per
            # traversal, `ne` would step over the exit value)
            if step != 1 or i0_hi + d > n_lo \
                    or back_srcs != [t]:
                return None
            t_max = n_hi - i0_lo - d + 1
        else:
            return None
    else:
        if op in ("gt_s", "gt_u"):
            t_max = ceil_div(i0_hi + d - n_lo, -step) + 1
        elif op in ("ge_s", "ge_u"):
            t_max = (i0_hi + d - n_lo) // (-step) + 2
        elif op == "ne":
            if step != -1 or i0_lo + d < n_hi \
                    or back_srcs != [t]:
                return None
            t_max = i0_hi + d - n_lo + 1
        else:
            return None
    # the whole progression must stay in i32 (no wraparound mid-loop)
    span = abs(step) * (max(int(t_max), 1) + 1)
    if i0_hi + span > I32_MAX or i0_lo - span < I32_MIN:
        return None
    return max(int(t_max), 1)


# ---------------------------------------------------------------------------
# loop-nest cost composition
# ---------------------------------------------------------------------------

def loop_nest_cost(cfg, block_cost, trips: Dict[int, int]) \
        -> Optional[int]:
    """Max-cost path from entry over the CFG where each counted loop
    (a cyclic SCC with a trip bound at its unique head) contributes
    trip * (max per-iteration path cost), recursively for nested
    loops (the inner graph drops the back edges into the head).  None
    whenever any needed trip bound or block cost is unknown — the
    honest "unbounded" verdict."""
    blocks = cfg.blocks
    if not blocks:
        return 0
    idx_of = {b.start: i for i, b in enumerate(blocks)}
    all_succs = [[idx_of[s] for s in b.succ if s in idx_of]
                 for b in blocks]

    def cost_of(nodes, edges, entry) -> Optional[int]:
        node_list = sorted(nodes)
        comps = _sccs_sub(node_list, edges)
        comp_of: Dict[int, int] = {}
        for ci, comp in enumerate(comps):
            for n in comp:
                comp_of[n] = ci
        comp_cost: List[Optional[int]] = []
        for comp in comps:
            cset = set(comp)
            cyclic = len(comp) > 1 or comp[0] in edges.get(comp[0], ())
            if not cyclic:
                comp_cost.append(block_cost(blocks[comp[0]]))
                continue
            heads = [n for n in comp if n == entry or any(
                n in edges.get(p, ()) for p in nodes if p not in cset)]
            if len(heads) != 1:
                comp_cost.append(None)
                continue
            head = heads[0]
            trip = trips.get(head)
            if trip is None:
                comp_cost.append(None)
                continue
            inner = {n: [s for s in edges.get(n, ())
                         if s in cset and s != head] for n in cset}
            per_iter = cost_of(cset, inner, head)
            comp_cost.append(None if per_iter is None
                             else int(trip) * per_iter)
        comp_succs: List[set] = [set() for _ in comps]
        for n in nodes:
            for s in edges.get(n, ()):
                if s in comp_of and comp_of[s] != comp_of[n]:
                    comp_succs[comp_of[n]].add(comp_of[s])
        # comps arrive reverse-topological (successors first), so one
        # forward pass memoizes every path without recursion
        memo: List[Optional[int]] = [None] * len(comps)
        done: List[bool] = [False] * len(comps)
        for ci in range(len(comps)):
            own = comp_cost[ci]
            best: Optional[int] = 0
            if own is None:
                best = None
            else:
                for s in comp_succs[ci]:
                    if not done[s] or memo[s] is None:
                        best = None
                        break
                    best = max(best, memo[s])
                if best is not None:
                    best = own + best
            memo[ci] = best
            done[ci] = True
        ei = comp_of.get(entry)
        return memo[ei] if ei is not None else 0

    return cost_of(set(range(len(blocks))),
                   {i: list(ss) for i, ss in enumerate(all_succs)}, 0)


def _sccs_sub(nodes: List[int], edges: Dict[int, list]) \
        -> List[List[int]]:
    pos = {n: i for i, n in enumerate(nodes)}
    succs = [[pos[s] for s in edges.get(n, ()) if s in pos]
             for n in nodes]
    return [[nodes[i] for i in comp]
            for comp in _sccs(len(nodes), succs)]


# ---------------------------------------------------------------------------
# module driver
# ---------------------------------------------------------------------------

def analyze_module_absint(image, cfgs: Dict[int, object],
                          mem_pages_init: int, mem_pages_max: int,
                          has_memory: bool,
                          globals_init=None) -> Dict[int, FuncAbsint]:
    """Run absint over every defined function.  `cfgs` is the r12
    {func_idx: FuncCFG} map.  Any per-function failure degrades to an
    empty FuncAbsint (no facts, honest unbounded), never an exception
    — the analyzer must stay total."""
    out: Dict[int, FuncAbsint] = {}
    try:
        cells = _Cells(image, globals_init=globals_init)
    except Exception:
        return {i: FuncAbsint() for i in cfgs}
    for i, cfg in cfgs.items():
        try:
            out[i] = analyze_func(cells, cfg, image.funcs[i],
                                  mem_pages_init, mem_pages_max,
                                  has_memory)
        except Exception:
            out[i] = FuncAbsint()
    return out
