"""ModuleAnalysis: static facts the runtime layers consume.

Per defined function, over the validated/lowered image (no execution):

  - basic-block CFG (analysis/cfg.py) with loop/back-edge marking
  - straight-line opcode n-gram census ranked as superinstruction
    candidates (block metadata for the ROADMAP #3 fusion tier)
  - a SOUND per-invocation cost upper bound: every retired instruction
    costs its cost-table weight (flat 1 by default, i.e. the bound is
    in retired-instruction units); loops, recursion, and dynamic calls
    (call_indirect — the table could route back) make the verdict
    "unbounded" (cost_bound None) rather than a guess
  - hostcall-site inventory split tier-0-serviceable (in-kernel WASI,
    batch/image.py T0_WASI_KINDS with the same fd-safety/memory gates)
    vs drain-required (device<->host round trip)
  - a divergence-risk score per block (branch fan-out, data-dependent
    brtables, dynamic calls, loop residency) for ROADMAP #5 scheduling
  - static memory/stack footprint bounds (declared pages + grow sites,
    value-stack and frame-depth bounds along the static call graph) for
    ROADMAP #4 resident-lane budgeting

Soundness contract (pinned by tests/test_analysis.py and
`bench.py --analyze-smoke`): for any terminating run of an exported
function, cost_bound is None (unbounded verdict) or >= the engine's
retired-instruction count for that invocation.  Overcounting is fine;
undercounting is a bug.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from wasmedge_tpu.analysis.cfg import BasicBlock, FuncCFG, build_func_cfg, \
    longest_path_cost
from wasmedge_tpu.common.opcodes import NAME_TO_ID, Op
from wasmedge_tpu.validator.image import LoweredModule, lop_name

SCHEMA = "wasmedge-tpu/analysis/v1"

_OP_CALL = NAME_TO_ID["call"]
_OP_RETCALL = NAME_TO_ID["return_call"]
_OP_MEMGROW = NAME_TO_ID["memory.grow"]

# An imported function executes as a 2-instruction synthetic stub on
# the batch engines (HOSTCALL + RETURN, batch/image.py): bound its cost
# by the stub length.  The host-side service time is not instruction
# retirement and is budgeted elsewhere (drain histograms, obs/).
IMPORT_STUB_COST = 2

# n-gram window sizes for the superinstruction census, and how many
# ranked candidates the report keeps.
NGRAM_SIZES = (2, 3, 4)
MAX_CANDIDATES = 16
LOOP_WEIGHT = 8  # census weight of an occurrence inside a CFG cycle


@dataclasses.dataclass
class HostcallSite:
    pc: int
    func_idx: int                   # imported function called
    import_name: str                # "module.name"
    tier0: bool                     # serviceable in-kernel (tier 0)
    kind: str                       # WASI call name, or "" for non-WASI

    def asdict(self) -> dict:
        return {"pc": self.pc, "func": self.func_idx,
                "import": self.import_name, "tier0": self.tier0,
                "kind": self.kind}


@dataclasses.dataclass
class FuncAnalysis:
    idx: int
    name: str                       # export name when exported
    entry_pc: int
    end_pc: int
    cfg: FuncCFG
    block_costs: List[int]          # per-block cost EXCLUDING callees
    has_loop: bool = False
    recursive: bool = False
    dynamic_calls: bool = False
    cost_bound: Optional[int] = None
    value_stack_bound: Optional[int] = None
    call_depth_bound: Optional[int] = None
    divergence: int = 0             # max block divergence score
    block_divergence: List[int] = dataclasses.field(default_factory=list)
    block_ngrams: List[List[int]] = dataclasses.field(default_factory=list)
    hostcall_sites: List[HostcallSite] = dataclasses.field(
        default_factory=list)
    # absint (analysis/absint.py) products: one entry per CFG loop
    # ({"head": pc, "trip_bound": int|None}) and one per memory-access
    # site ({"pc", "kind", "nbytes", "lo", "hi", "align", "in_bounds",
    # "aligned", "licensed"})
    loops: List[dict] = dataclasses.field(default_factory=list)
    mem_facts: List[dict] = dataclasses.field(default_factory=list)

    @property
    def bounded(self) -> bool:
        return self.cost_bound is not None

    def asdict(self) -> dict:
        blocks = []
        for i, b in enumerate(self.cfg.blocks):
            blocks.append({
                "start": b.start, "end": b.end,
                "succ": list(b.succ), "kind": b.kind,
                "cost": self.block_costs[i],
                "in_loop": b.in_loop, "loop_head": b.is_loop_head,
                "brtable_entries": b.brtable_entries,
                "divergence": self.block_divergence[i],
                "ngrams": list(self.block_ngrams[i]),
            })
        return {
            "idx": self.idx, "name": self.name,
            "entry_pc": self.entry_pc, "end_pc": self.end_pc,
            "has_loop": self.has_loop, "recursive": self.recursive,
            "dynamic_calls": self.dynamic_calls,
            "bounded": self.bounded,
            "cost_bound": self.cost_bound,
            "value_stack_bound": self.value_stack_bound,
            "call_depth_bound": self.call_depth_bound,
            "divergence": self.divergence,
            "hostcall_sites": [s.asdict() for s in self.hostcall_sites],
            "loops": [dict(l) for l in self.loops],
            "mem_facts": [dict(m) for m in self.mem_facts],
            "blocks": blocks,
        }


@dataclasses.dataclass
class ModuleAnalysis:
    """The full static report; attached to DeviceImage at build time
    and serialized by the analyze CLI / gateway admission policy."""

    funcs: List[FuncAnalysis]
    imports: List[dict]             # imported funcs: name/tier0/kind
    superinstructions: List[dict]
    code_len: int = 0
    n_funcs: int = 0
    exports: Dict[str, int] = dataclasses.field(default_factory=dict)
    bounded: bool = False
    cost_bound: Optional[int] = None
    value_stack_bound: Optional[int] = None
    call_depth_bound: Optional[int] = None
    divergence: int = 0
    mem_pages_init: int = 0
    mem_pages_max: int = 0          # declared max; 0 = none declared
    mem_grow_sites: int = 0
    mem_pages_bound: Optional[int] = None
    tier0_sites: int = 0
    drain_sites: int = 0
    dynamic_call_sites: int = 0
    # absint aggregate: proven max page TOUCH (every access site's
    # effective-address range is finite and hostcalls cannot write
    # guest memory), vs the declared bound above; plus the licensed
    # (trap-free-provable) vs unproven scalar load/store site split —
    # batch/fuse.py consumes licensed_pcs as its fusion license
    mem_pages_touch_bound: Optional[int] = None
    licensed_sites: int = 0
    unlicensed_sites: int = 0
    licensed_pcs: frozenset = frozenset()

    def func_by_idx(self, idx: int) -> Optional[FuncAnalysis]:
        for f in self.funcs:
            if f.idx == idx:
                return f
        return None

    def summary(self) -> dict:
        """The compact view the gateway returns in registration bodies
        and the admission policy evaluates."""
        return {
            "bounded": self.bounded,
            "cost_bound": self.cost_bound,
            "value_stack_bound": self.value_stack_bound,
            "call_depth_bound": self.call_depth_bound,
            "divergence": self.divergence,
            "mem_pages_bound": self.mem_pages_bound,
            "mem_pages_touch_bound": self.mem_pages_touch_bound,
            "mem_grow_sites": self.mem_grow_sites,
            "tier0_hostcall_sites": self.tier0_sites,
            "drain_hostcall_sites": self.drain_sites,
            "dynamic_call_sites": self.dynamic_call_sites,
            "superinstruction_candidates": len(self.superinstructions),
            "licensed_mem_sites": self.licensed_sites,
            "unlicensed_mem_sites": self.unlicensed_sites,
            "trip_bounded_loops": sum(
                1 for f in self.funcs for l in f.loops
                if l.get("trip_bound") is not None),
        }

    def to_dict(self) -> dict:
        return {
            "schema": SCHEMA,
            "code_len": self.code_len,
            "n_funcs": self.n_funcs,
            "exports": dict(self.exports),
            "summary": self.summary(),
            "memory": {
                "pages_init": self.mem_pages_init,
                "pages_max_declared": self.mem_pages_max,
                "grow_sites": self.mem_grow_sites,
                "pages_bound": self.mem_pages_bound,
                "pages_touch_bound": self.mem_pages_touch_bound,
            },
            "hostcalls": {
                "imports": list(self.imports),
                "tier0_sites": self.tier0_sites,
                "drain_sites": self.drain_sites,
                "dynamic_call_sites": self.dynamic_call_sites,
            },
            "superinstructions": list(self.superinstructions),
            "funcs": [f.asdict() for f in self.funcs],
        }

    # -- annotated disassembly --------------------------------------------
    def annotated_disasm(self, image: LoweredModule,
                         fusion: Optional[dict] = None) -> str:
        """LoweredModule.disasm interleaved with block/analysis
        annotations — the human half of the analyze CLI's report.
        `fusion` (a batch/fuse.py plan_fusion report) annotates which
        candidate runs were REALIZED as fused dispatch cells:
        `fused=<head>+<len>` marks on the owning block lines
        (`memfused=` for the r19 licensed load/store runs).  Loop
        heads carry their absint trip verdict (`trip<=N` /
        `trip=unbounded`), memory-access sites their proven
        range/alignment class."""
        runs_by_pc = {}
        for r in (fusion or {}).get("runs", ()):
            runs_by_pc[int(r[0])] = (int(r[1]), int(r[2]))
        mem_runs_by_pc = {}
        for r in (fusion or {}).get("mem_runs", ()):
            mem_runs_by_pc[int(r[0])] = (int(r[1]), int(r[2]))
        out: List[str] = []
        for f in self.funcs:
            flags = []
            if f.recursive:
                flags.append("recursive")
            if f.has_loop:
                flags.append("loop")
            if f.dynamic_calls:
                flags.append("dynamic-calls")
            bound = "unbounded" if f.cost_bound is None \
                else f"<= {f.cost_bound}"
            out.append(f";; func {f.idx} {f.name!r} "
                       f"[{f.entry_pc}..{f.end_pc}] cost {bound}"
                       + (f" ({', '.join(flags)})" if flags else ""))
            trips_by_head = {l["head"]: l["trip_bound"] for l in f.loops}
            for i, b in enumerate(f.cfg.blocks):
                marks = []
                if b.is_loop_head:
                    marks.append("loop-head")
                    t = trips_by_head.get(b.start)
                    marks.append("trip=unbounded" if t is None
                                 else f"trip<={t}")
                if b.in_loop:
                    marks.append("in-loop")
                if self.block_ngram_names(f, i):
                    marks.append(
                        "ngrams=" + ",".join(
                            "|".join(ops)
                            for ops in self.block_ngram_names(f, i)))
                fused_here = [f"{pc}+{n}" for pc, (n, _k)
                              in sorted(runs_by_pc.items())
                              if b.start <= pc <= b.end]
                if fused_here:
                    marks.append("fused=" + ",".join(fused_here))
                memfused_here = [f"{pc}+{n}" for pc, (n, _k)
                                 in sorted(mem_runs_by_pc.items())
                                 if b.start <= pc <= b.end]
                if memfused_here:
                    marks.append("memfused=" + ",".join(memfused_here))
                out.append(f";;   block [{b.start}..{b.end}] "
                           f"kind={b.kind} cost={f.block_costs[i]} "
                           f"div={f.block_divergence[i]} "
                           f"succ={list(b.succ)}"
                           + ((" " + " ".join(marks)) if marks else ""))
                for m in f.mem_facts:
                    if not (b.start <= m["pc"] <= b.end) \
                            or m["kind"] == "bulk":
                        continue
                    rng = "[?]" if m["hi"] is None \
                        else f"[{m['lo']}..{m['hi']}]"
                    verdict = "licensed" if m["licensed"] else \
                        ("in-bounds" if m["in_bounds"] else "unproven")
                    out.append(f";;     mem@{m['pc']} {m['kind']}"
                               f"{m['nbytes']} {rng} "
                               f"align={m['align']} {verdict}")
                out.append(image.disasm(b.start, b.end + 1))
        return "\n".join(out)

    def block_ngram_names(self, f: FuncAnalysis, block_i: int) \
            -> List[Tuple[str, ...]]:
        out = []
        for ci in f.block_ngrams[block_i]:
            if 0 <= ci < len(self.superinstructions):
                out.append(tuple(self.superinstructions[ci]["ops"]))
        return out


# ---------------------------------------------------------------------------
# tier-0 classification (mirrors batch/image.py build_device_image)
# ---------------------------------------------------------------------------

def _classify_imports(image: LoweredModule, has_memory: bool) \
        -> Dict[int, Tuple[bool, str, str]]:
    """func_idx -> (tier0, wasi_kind, 'module.name') for imports.
    Delegates the gating rules to batch/image.classify_t0_imports +
    T0_NEEDS_MEMORY — the SAME source the image build and
    t0_effective_kinds consume, so admission verdicts cannot drift
    from what the engine services in-kernel."""
    from wasmedge_tpu.batch.image import (
        T0_FD_WRITE, T0_NEEDS_MEMORY, T0_NONE, _WASI_MODULE,
        classify_t0_imports)

    kinds, fdwrite_safe = classify_t0_imports(image.funcs)
    out = {}
    for idx, fn in enumerate(image.funcs):
        if not fn.is_import:
            continue
        qual = f"{fn.import_module}.{fn.import_name}"
        kind = fn.import_name if fn.import_module == _WASI_MODULE else ""
        t0n = kinds.get(idx, T0_NONE)
        t0 = t0n != T0_NONE
        if t0n in T0_NEEDS_MEMORY and not has_memory:
            t0 = False
        if t0n == T0_FD_WRITE and not fdwrite_safe:
            t0 = False
        out[idx] = (t0, kind, qual)
    return out


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------

def analyze_validated(mod, cost_table=None) -> "ModuleAnalysis":
    """Analyze a VALIDATED AST module (loader/ast.py Module carrying
    `mod.lowered`): the shared front door for the CLI, bench smoke,
    and tests — one place derives exports + declared-memory facts, so
    the surfaces cannot drift from each other (the image-build path in
    batch/image.py stays the only instance-level variant)."""
    exports = {e.name: e.index for e in mod.exports if e.kind == 0}
    mems = mod.all_memory_types()
    # non-escaping-global seeding for absint: only module-local const
    # inits are extractable without instantiation (imported globals
    # make every index unknowable pre-link -> None, which degrades the
    # global domain to TOP, never to a wrong constant)
    globals_init = None
    if not mod.imported_globals():
        globals_init = []
        for g in mod.globals:
            if len(g.init) == 1 and g.init[0].op in (
                    Op.i32_const, Op.i64_const, Op.f32_const,
                    Op.f64_const):
                globals_init.append(int(g.init[0].imm))
            else:
                globals_init.append(None)
    return analyze_module(
        mod.lowered, exports=exports,
        mem_pages_init=mems[0].limit.min if mems else 0,
        mem_pages_max=(mems[0].limit.max or 0) if mems else 0,
        has_memory=bool(mems), cost_table=cost_table,
        globals_init=globals_init)


def analyze_module(image: LoweredModule,
                   exports: Optional[Dict[str, int]] = None,
                   mem_pages_init: int = 0,
                   mem_pages_max: int = 0,
                   has_memory: Optional[bool] = None,
                   cost_table=None,
                   globals_init=None) -> ModuleAnalysis:
    """Analyze a validated lowered image.  `exports` maps export name
    -> function index (used for naming and the module-level aggregate);
    `cost_table` maps opcode id -> gas weight (flat 1 = bounds in
    retired-instruction units); `globals_init` optionally carries the
    module globals' initial values (absint constant-folds the ones no
    global.set site can reach)."""
    exports = exports or {}
    if has_memory is None:
        has_memory = mem_pages_init > 0 or mem_pages_max > 0
    export_of = {}
    for name, idx in exports.items():
        export_of.setdefault(idx, name)

    def w(op: int) -> int:
        if cost_table is None:
            return 1
        try:
            return int(cost_table[op])
        except (IndexError, KeyError):
            return 1

    imports_info = _classify_imports(image, has_memory)

    # -- per-function CFGs + static call graph ------------------------------
    defined = [i for i, fn in enumerate(image.funcs) if not fn.is_import]
    cfgs: Dict[int, FuncCFG] = {i: build_func_cfg(image, i)
                                for i in defined}
    callees: Dict[int, set] = {i: set() for i in defined}
    dynamic: Dict[int, bool] = {i: False for i in defined}
    for i in defined:
        for b in cfgs[i].blocks:
            callees[i].update(b.calls)
            dynamic[i] = dynamic[i] or b.dynamic_call

    # recursion: any call-graph cycle reachable through static edges
    recursive = _callgraph_cycles(defined, callees)

    # -- abstract interpretation (analysis/absint.py): loop trip
    # bounds, memory-effect facts, fusion licenses.  Total by
    # construction (a per-function failure degrades to no facts).
    from wasmedge_tpu.analysis.absint import (
        analyze_module_absint, loop_nest_cost)

    absints = analyze_module_absint(
        image, cfgs, mem_pages_init=mem_pages_init,
        mem_pages_max=mem_pages_max, has_memory=bool(has_memory),
        globals_init=globals_init)

    # -- bottom-up bounds over the call-graph condensation ------------------
    cost_bound: Dict[int, Optional[int]] = {}
    stack_bound: Dict[int, Optional[int]] = {}
    depth_bound: Dict[int, Optional[int]] = {}
    for idx, fn in enumerate(image.funcs):
        if fn.is_import:
            cost_bound[idx] = IMPORT_STUB_COST
            stack_bound[idx] = fn.nparams + max(fn.nresults, 1)
            depth_bound[idx] = 1

    order = _postorder(defined, callees)
    block_costs: Dict[int, List[int]] = {}
    for i in order:
        fn = image.funcs[i]
        cfg = cfgs[i]
        own_costs = []
        for b in cfg.blocks:
            own_costs.append(sum(w(image.op[pc]) for pc in b.pcs()))
        block_costs[i] = own_costs
        if recursive[i] or dynamic[i]:
            cost_bound[i] = None
            stack_bound[i] = None
            depth_bound[i] = None
            continue

        bi_of = {b.start: bi for bi, b in enumerate(cfg.blocks)}

        def bcost(b: BasicBlock, _costs=own_costs, _bi=bi_of):
            total = _costs[_bi[b.start]]
            for k in b.calls:
                sub = cost_bound.get(k)
                if sub is None:
                    return None
                total += sub
            return total

        if cfg.has_loop:
            # counted loops: the absint trip bounds compose through
            # the loop-nest walk (trip x per-iteration longest path,
            # recursively); any unbounded loop poisons to None — the
            # seed's honest verdict, now only for loops that ARE
            # statically unbounded
            trips = absints[i].trips if i in absints else {}
            cost_bound[i] = loop_nest_cost(cfg, bcost, trips) \
                if trips else None
        else:
            cost_bound[i] = longest_path_cost(cfg, bcost)
        frame = fn.nlocals + fn.max_height
        sb: Optional[int] = frame
        db: Optional[int] = 1
        for k in callees[i]:
            ks, kd = stack_bound.get(k), depth_bound.get(k)
            if ks is None or kd is None:
                sb = db = None
                break
            sb = max(sb, frame + ks)
            db = max(db, 1 + kd)
        stack_bound[i] = sb
        depth_bound[i] = db

    # -- n-gram census ------------------------------------------------------
    census: Dict[Tuple[str, ...], List[int]] = {}  # ops -> [count, weight]
    runs: Dict[int, List[List[str]]] = {}  # func -> per-block op names
    for i in defined:
        per_block = []
        for b in cfgs[i].blocks:
            # the straight-line run excludes the control terminator
            # (a fused superinstruction cannot span a dispatch exit)
            end = b.end if b.kind == "fallthrough" else b.end - 1
            names = [lop_name(image.op[pc])
                     for pc in range(b.start, end + 1)]
            per_block.append(names)
            wgt = LOOP_WEIGHT if b.in_loop else 1
            for n in NGRAM_SIZES:
                for off in range(len(names) - n + 1):
                    key = tuple(names[off:off + n])
                    ent = census.setdefault(key, [0, 0])
                    ent[0] += 1
                    ent[1] += wgt
        runs[i] = per_block
    ranked = sorted(census.items(),
                    key=lambda kv: (kv[1][1] * (len(kv[0]) - 1),
                                    kv[1][0], kv[0]),
                    reverse=True)
    # weight > 1 keeps single occurrences inside loops (they execute
    # per iteration — prime fusion targets) while dropping one-shot
    # straight-line sequences
    ranked = [(ops, cnt, wgt) for ops, (cnt, wgt) in ranked
              if wgt > 1][:MAX_CANDIDATES]
    superinstructions = [{
        "ops": list(ops), "n": len(ops), "count": cnt, "weight": wgt,
        "saved_dispatches": (len(ops) - 1) * cnt,
    } for ops, cnt, wgt in ranked]
    cand_idx = {tuple(c["ops"]): ci
                for ci, c in enumerate(superinstructions)}

    # -- assemble per-function reports --------------------------------------
    mem_grow_sites = sum(1 for pc in range(image.code_len)
                         if image.op[pc] == _OP_MEMGROW)
    funcs: List[FuncAnalysis] = []
    total_t0 = total_drain = total_dyn = 0
    for i in defined:
        fn = image.funcs[i]
        cfg = cfgs[i]
        div = []
        ngrams: List[List[int]] = []
        sites: List[HostcallSite] = []
        for bi, b in enumerate(cfg.blocks):
            fanout = max(len(b.succ) - 1, 0)
            score = fanout + b.brtable_entries \
                + (4 if b.dynamic_call else 0)
            if b.in_loop:
                score *= 2
            div.append(score)
            names = runs[i][bi]
            present = []
            for n in NGRAM_SIZES:
                for off in range(len(names) - n + 1):
                    ci = cand_idx.get(tuple(names[off:off + n]))
                    if ci is not None and ci not in present:
                        present.append(ci)
            ngrams.append(sorted(present))
            for pc in b.pcs():
                if image.op[pc] in (_OP_CALL, _OP_RETCALL):
                    k = image.a[pc]
                    info = imports_info.get(k)
                    if info is not None:
                        t0, kind, qual = info
                        sites.append(HostcallSite(
                            pc=pc, func_idx=k, import_name=qual,
                            tier0=t0, kind=kind))
            if b.dynamic_call:
                total_dyn += 1
        total_t0 += sum(1 for s in sites if s.tier0)
        total_drain += sum(1 for s in sites if not s.tier0)
        ai = absints.get(i)
        funcs.append(FuncAnalysis(
            idx=i, name=export_of.get(i, f"func{i}"),
            entry_pc=fn.entry_pc, end_pc=fn.end_pc, cfg=cfg,
            block_costs=block_costs[i],
            has_loop=cfg.has_loop, recursive=recursive[i],
            dynamic_calls=dynamic[i],
            cost_bound=cost_bound[i],
            value_stack_bound=stack_bound[i],
            call_depth_bound=depth_bound[i],
            divergence=max(div) if div else 0,
            block_divergence=div, block_ngrams=ngrams,
            hostcall_sites=sites,
            loops=[l.asdict() for l in ai.loops] if ai else [],
            mem_facts=[m.asdict() for m in ai.mem_facts] if ai else []))

    # -- module aggregate ---------------------------------------------------
    roots = [f for f in funcs
             if not exports or f.idx in set(exports.values())]
    roots = roots or funcs
    agg_cost: Optional[int] = 0
    agg_stack: Optional[int] = 0
    agg_depth: Optional[int] = 0
    for f in roots:
        if agg_cost is not None:
            agg_cost = None if f.cost_bound is None \
                else max(agg_cost, f.cost_bound)
        if agg_stack is not None:
            agg_stack = None if f.value_stack_bound is None \
                else max(agg_stack, f.value_stack_bound)
        if agg_depth is not None:
            agg_depth = None if f.call_depth_bound is None \
                else max(agg_depth, f.call_depth_bound)
    if mem_grow_sites == 0:
        pages_bound: Optional[int] = mem_pages_init
    elif mem_pages_max > 0:
        pages_bound = mem_pages_max
    else:
        pages_bound = None  # growable with no declared ceiling

    # -- proven max page touch + fusion licenses (absint aggregate) ---------
    all_facts = [m for f in funcs for m in f.mem_facts]
    licensed_pcs = frozenset(m["pc"] for m in all_facts
                             if m.get("licensed"))
    mem_sites = [m for m in all_facts
                 if m["kind"] in ("load", "store", "vload", "vstore")]
    licensed_sites = sum(1 for m in mem_sites if m["licensed"])
    # touch bound: every access site's end is proven finite AND no
    # hostcall can write guest memory at a guest-chosen pointer AND
    # every function's absint ran (dead-code sites carry no facts and
    # never execute, so their absence is fine)
    touch: Optional[int] = None
    if has_memory and total_t0 + total_drain == 0 \
            and all(absints.get(i) is not None and absints[i].ok
                    for i in defined):
        ends = [(m["hi"] or 0) + m["nbytes"] if m["hi"] is not None
                else None for m in all_facts]
        if all(e is not None for e in ends):
            touch = max(
                max((-(-e // 65536) for e in ends), default=0), 1)

    return ModuleAnalysis(
        funcs=funcs,
        imports=[{"func": idx, "import": qual, "tier0": t0,
                  "kind": kind}
                 for idx, (t0, kind, qual) in sorted(imports_info.items())],
        superinstructions=superinstructions,
        code_len=image.code_len, n_funcs=len(image.funcs),
        exports=dict(exports),
        bounded=agg_cost is not None,
        cost_bound=agg_cost,
        value_stack_bound=agg_stack,
        call_depth_bound=agg_depth,
        divergence=max((f.divergence for f in funcs), default=0),
        mem_pages_init=mem_pages_init, mem_pages_max=mem_pages_max,
        mem_grow_sites=mem_grow_sites, mem_pages_bound=pages_bound,
        tier0_sites=total_t0, drain_sites=total_drain,
        dynamic_call_sites=total_dyn,
        mem_pages_touch_bound=touch,
        licensed_sites=licensed_sites,
        unlicensed_sites=len(mem_sites) - licensed_sites,
        licensed_pcs=licensed_pcs,
    )


def _callgraph_cycles(defined: List[int], callees: Dict[int, set]) \
        -> Dict[int, bool]:
    """func -> participates in a static call-graph cycle (counting
    cycles through callees: f is 'recursive' if anything reachable from
    it can re-enter a function on the path)."""
    # Tarjan over the call graph (iterative — no recursion-depth
    # dependence), then propagate: a function is cycle-tainted if its
    # SCC is cyclic or any callee is tainted.
    index: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on: Dict[int, bool] = {}
    stack: List[int] = []
    counter = [1]
    in_cycle = {i: False for i in defined}
    dset = set(defined)

    def strong(v):
        work = [(v, iter(sorted(callees[v] & dset)))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on[v] = True
        while work:
            x, it = work[-1]
            advanced = False
            for y in it:
                if y not in index:
                    index[y] = low[y] = counter[0]
                    counter[0] += 1
                    stack.append(y)
                    on[y] = True
                    work.append((y, iter(sorted(callees[y] & dset))))
                    advanced = True
                    break
                if on.get(y):
                    low[x] = min(low[x], index[y])
            if advanced:
                continue
            work.pop()
            if low[x] == index[x]:
                scc = []
                while True:
                    y = stack.pop()
                    on[y] = False
                    scc.append(y)
                    if y == x:
                        break
                if len(scc) > 1 or x in callees[x]:
                    for y in scc:
                        in_cycle[y] = True
            if work:
                px = work[-1][0]
                low[px] = min(low[px], low[x])

    for v in defined:
        if v not in index:
            strong(v)
    # propagate taint up the call graph to a fixpoint
    changed = True
    while changed:
        changed = False
        for i in defined:
            if in_cycle[i]:
                continue
            if any(in_cycle.get(k, False) for k in callees[i] & dset):
                in_cycle[i] = True
                changed = True
    return in_cycle


def _postorder(defined: List[int], callees: Dict[int, set]) -> List[int]:
    """Callees-first order (cycles broken arbitrarily — cyclic
    functions are unbounded anyway, their order never matters)."""
    dset = set(defined)
    seen = set()
    order: List[int] = []
    for root in defined:
        if root in seen:
            continue
        work = [(root, 0)]
        local_path = set()
        while work:
            v, ei = work[-1]
            if ei == 0:
                if v in seen:
                    work.pop()
                    continue
                local_path.add(v)
            nxt = sorted(callees[v] & dset)
            if ei < len(nxt):
                work[-1] = (v, ei + 1)
                k = nxt[ei]
                if k not in seen and k not in local_path:
                    work.append((k, 0))
                continue
            work.pop()
            local_path.discard(v)
            seen.add(v)
            order.append(v)
    return order
