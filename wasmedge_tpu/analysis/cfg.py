"""Basic-block CFG over the lowered SoA image.

The validator already compiles structured control flow away
(validator/image.py): every branch is an absolute-PC LOP_BR/BRZ/BRNZ,
br_table is a flat (target_pc, keep, pop_to) side table, calls are
absolute function indices.  That makes CFG construction a single linear
pass — leaders are function entries, branch/brtable targets, and the
instruction after any control transfer; edges come straight off the
instruction operands (including the full brtable entry table).

Pure Python over the image's list planes — no numpy, no jax: the
analyzer must be importable from the CLI without paying the device
stack's import cost, and it runs inside build_device_image for every
engine build.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from wasmedge_tpu.common.opcodes import NAME_TO_ID
from wasmedge_tpu.validator.image import (
    LOP_BR,
    LOP_BRNZ,
    LOP_BRZ,
    LoweredModule,
)

_OP_BR_TABLE = NAME_TO_ID["br_table"]
_OP_RETURN = NAME_TO_ID["return"]
_OP_CALL = NAME_TO_ID["call"]
_OP_CALL_INDIRECT = NAME_TO_ID["call_indirect"]
_OP_RETCALL = NAME_TO_ID["return_call"]
_OP_RETCALL_INDIRECT = NAME_TO_ID["return_call_indirect"]
_OP_UNREACHABLE = NAME_TO_ID["unreachable"]

# Terminators that leave the function (no intra-function successor).
_EXIT_OPS = frozenset((_OP_RETURN, _OP_RETCALL, _OP_RETCALL_INDIRECT,
                       _OP_UNREACHABLE))
# Terminators that transfer control somewhere else in the function.
_BRANCH_OPS = frozenset((LOP_BR, LOP_BRZ, LOP_BRNZ, _OP_BR_TABLE))
# Calls end a block too: the interpreter's pc leaves the straight-line
# run (superinstruction fusion cannot span them) and control resumes at
# pc+1 only after the callee returns.
_CALL_OPS = frozenset((_OP_CALL, _OP_CALL_INDIRECT))


@dataclasses.dataclass
class BasicBlock:
    """One straight-line run [start, end] (both pcs inclusive)."""

    start: int
    end: int
    succ: Tuple[int, ...] = ()      # successor block START pcs
    kind: str = "fallthrough"       # terminator class (see _block_kind)
    brtable_entries: int = 0        # entry-table rows (incl. default)
    calls: Tuple[int, ...] = ()     # static callee func indices in block
    dynamic_call: bool = False      # block contains call_indirect
    in_loop: bool = False           # member of a CFG cycle
    is_loop_head: bool = False      # target of a back edge

    def pcs(self) -> range:
        return range(self.start, self.end + 1)


@dataclasses.dataclass
class FuncCFG:
    """Blocks of one defined function, keyed by start pc."""

    func_idx: int
    entry_pc: int
    end_pc: int
    blocks: List[BasicBlock]
    has_loop: bool = False

    def block_at(self, pc: int) -> Optional[BasicBlock]:
        for b in self.blocks:
            if b.start <= pc <= b.end:
                return b
        return None

    @property
    def by_start(self) -> Dict[int, BasicBlock]:
        return {b.start: b for b in self.blocks}


def _brtable_targets(image: LoweredModule, pc: int) -> List[int]:
    """All entry-table targets of a br_table, default included."""
    base, n = image.a[pc], image.b[pc]
    return [image.br_table[(base + e) * 3] for e in range(n + 1)]


def _block_kind(op: int) -> str:
    if op == LOP_BR:
        return "br"
    if op == LOP_BRZ:
        return "brz"
    if op == LOP_BRNZ:
        return "brnz"
    if op == _OP_BR_TABLE:
        return "br_table"
    if op == _OP_RETURN:
        return "return"
    if op in (_OP_RETCALL, _OP_RETCALL_INDIRECT):
        return "tail_call"
    if op == _OP_UNREACHABLE:
        return "unreachable"
    if op == _OP_CALL:
        return "call"
    if op == _OP_CALL_INDIRECT:
        return "call_indirect"
    return "fallthrough"


def build_func_cfg(image: LoweredModule, func_idx: int) -> FuncCFG:
    """CFG of one defined function (entry_pc >= 0)."""
    fn = image.funcs[func_idx]
    lo, hi = fn.entry_pc, fn.end_pc
    leaders = {lo}
    for pc in range(lo, hi + 1):
        op = image.op[pc]
        if op in (LOP_BR, LOP_BRZ, LOP_BRNZ):
            leaders.add(image.a[pc])
        elif op == _OP_BR_TABLE:
            leaders.update(_brtable_targets(image, pc))
        if (op in _BRANCH_OPS or op in _EXIT_OPS or op in _CALL_OPS) \
                and pc + 1 <= hi:
            leaders.add(pc + 1)
    leaders = sorted(t for t in leaders if lo <= t <= hi)

    blocks: List[BasicBlock] = []
    for i, start in enumerate(leaders):
        end = (leaders[i + 1] - 1) if i + 1 < len(leaders) else hi
        last = image.op[end]
        kind = _block_kind(last)
        succ: List[int] = []
        brtable_entries = 0
        if last == LOP_BR:
            succ = [image.a[end]]
        elif last in (LOP_BRZ, LOP_BRNZ):
            succ = [image.a[end]]
            if end + 1 <= hi:
                succ.append(end + 1)
        elif last == _OP_BR_TABLE:
            targets = _brtable_targets(image, end)
            brtable_entries = len(targets)
            seen = set()
            for t in targets:
                if t not in seen:
                    seen.add(t)
                    succ.append(t)
        elif last in _EXIT_OPS:
            succ = []
        else:  # call / call_indirect / plain fallthrough into a leader
            if end + 1 <= hi:
                succ = [end + 1]
        calls = tuple(image.a[pc] for pc in range(start, end + 1)
                      if image.op[pc] in (_OP_CALL, _OP_RETCALL))
        dynamic = any(image.op[pc] in (_OP_CALL_INDIRECT,
                                       _OP_RETCALL_INDIRECT)
                      for pc in range(start, end + 1))
        blocks.append(BasicBlock(
            start=start, end=end, succ=tuple(succ), kind=kind,
            brtable_entries=brtable_entries, calls=calls,
            dynamic_call=dynamic))

    cfg = FuncCFG(func_idx=func_idx, entry_pc=lo, end_pc=hi,
                  blocks=blocks)
    _mark_loops(cfg)
    return cfg


def _mark_loops(cfg: FuncCFG):
    """Tag blocks on CFG cycles (iterative Tarjan SCC) and loop heads
    (back-edge targets from an iterative DFS).  `has_loop` drives the
    bounded/unbounded cost verdict; `in_loop` weights the n-gram census
    (a sequence inside a loop is hotter than straight-line prologue)."""
    idx_of = {b.start: i for i, b in enumerate(cfg.blocks)}
    n = len(cfg.blocks)
    succs = [[idx_of[s] for s in b.succ if s in idx_of]
             for b in cfg.blocks]

    # Tarjan SCC, iterative (functions can be deep).
    index = [0] * n
    low = [0] * n
    on_stack = [False] * n
    visited = [False] * n
    stack: List[int] = []
    counter = [1]

    for root in range(n):
        if visited[root]:
            continue
        work = [(root, 0)]
        while work:
            v, ei = work[-1]
            if ei == 0:
                visited[v] = True
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack[v] = True
            advanced = False
            while ei < len(succs[v]):
                w = succs[v][ei]
                ei += 1
                if not visited[w]:
                    work[-1] = (v, ei)
                    work.append((w, 0))
                    advanced = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc.append(w)
                    if w == v:
                        break
                cyclic = len(scc) > 1 or v in succs[v]
                if cyclic:
                    for w in scc:
                        cfg.blocks[w].in_loop = True
                    cfg.has_loop = True
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])

    # Loop heads: DFS back-edge targets (an edge into a block currently
    # on the DFS path).
    color = [0] * n  # 0 white, 1 on-path, 2 done
    work2: List[Tuple[int, int]] = [(0, 0)] if n else []
    while work2:
        v, ei = work2.pop()
        if ei == 0:
            color[v] = 1
        if ei < len(succs[v]):
            work2.append((v, ei + 1))
            w = succs[v][ei]
            if color[w] == 0:
                work2.append((w, 0))
            elif color[w] == 1:
                cfg.blocks[w].is_loop_head = True
        else:
            color[v] = 2


def longest_path_cost(cfg: FuncCFG, block_cost) -> Optional[int]:
    """Max-cost path from entry to any exit over an ACYCLIC block graph;
    None when the graph has a cycle (no static bound).  `block_cost`
    maps a BasicBlock to its (already call-inclusive) cost — None from
    it poisons the whole bound."""
    if cfg.has_loop:
        return None
    idx_of = {b.start: i for i, b in enumerate(cfg.blocks)}
    memo: Dict[int, Optional[int]] = {}
    order: List[int] = []
    seen = [False] * len(cfg.blocks)
    work = [(0, 0)] if cfg.blocks else []
    while work:  # iterative postorder
        v, ei = work.pop()
        if ei == 0:
            if seen[v]:
                continue
            seen[v] = True
        b = cfg.blocks[v]
        nxt = [idx_of[s] for s in b.succ if s in idx_of]
        if ei < len(nxt):
            work.append((v, ei + 1))
            if not seen[nxt[ei]]:
                work.append((nxt[ei], 0))
            continue
        order.append(v)
    for v in order:
        b = cfg.blocks[v]
        own = block_cost(b)
        if own is None:
            memo[v] = None
            continue
        best = 0
        for s in b.succ:
            if s not in idx_of:  # same out-of-range guard as the DFS
                continue
            sub = memo.get(idx_of[s])
            if sub is None:
                memo[v] = None
                break
            best = max(best, sub)
        else:
            memo[v] = own + best
    return memo.get(0, 0) if cfg.blocks else 0
