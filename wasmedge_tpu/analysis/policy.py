"""Admission policy over ModuleAnalysis — the gateway's static vetting.

A tenant file (gateway/tenants.py) may carry an `analysis` table,
either per-tenant or top-level (the default for tenants without their
own):

    {
      "analysis": {"max_static_cost": 1000000, "max_memory_pages": 16},
      "tenants": {
        "alice": {"api_key": "sk-alice",
                  "analysis": {"require_bounded": true,
                               "tier0_only_hostcalls": true}}
      }
    }

`POST /v1/modules` evaluates the already-built image's ModuleAnalysis
(one lowering, shared with the batchability probe) against the
registering tenant's policy.  Violations reject with the structured
ErrCode taxonomy (StaticPolicyViolation -> HTTP 400, violations list
in the body) — or, with `"enforce": false`, register the module and
return the violations as `analysis_warnings` (flag, don't block).

The runtime backstops stay what they were (per-request step budgets,
lane quarantine): this layer refuses work the runtime would have had
to kill, before it ever owns a lane.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from wasmedge_tpu.common.errors import ErrCode, WasmError


class AnalysisRejection(WasmError):
    """A module's static bounds exceed the registering tenant's policy.
    Carries the machine-readable violation list (rejection_info
    includes it, so HTTP bodies show limit/allowed/actual per item)."""

    def __init__(self, module: str, violations: List[dict]):
        limits = ", ".join(v["limit"] for v in violations) or "policy"
        super().__init__(
            ErrCode.StaticPolicyViolation,
            f"module {module!r} rejected by static admission policy "
            f"({limits})")
        self.violations = list(violations)


def _violation(limit: str, allowed, actual, message: str) -> dict:
    return {"limit": limit, "allowed": allowed,
            "actual": "unbounded" if actual is None else actual,
            "message": message}


@dataclasses.dataclass
class AnalysisPolicy:
    """Static-bound limits one tenant imposes on modules it registers.
    All limits optional; None/False = not enforced."""

    # Reject modules whose per-invocation retired-instruction bound is
    # unbounded (loops/recursion with no static exit) or exceeds this.
    max_static_cost: Optional[int] = None
    # Reject unbounded modules even without a numeric cost cap ("no
    # unbounded loops unless a gas budget bounds them at runtime").
    require_bounded: bool = False
    # Static memory footprint: reject when the page bound (declared max
    # when grow sites exist, initial pages otherwise) is unbounded or
    # over this — the resident-lane HBM budget (ROADMAP #4).
    max_memory_pages: Optional[int] = None
    # Proven max page TOUCH (absint, r19): reject when the abstract
    # interpreter could not bound the pages the module's accesses can
    # reach, or the proven touch exceeds this.  Stricter than
    # max_memory_pages: it demands a PROOF, not just a declaration.
    max_memory_pages_touched: Optional[int] = None
    # Value-stack / frame-depth bounds along the static call graph.
    max_value_stack: Optional[int] = None
    max_call_depth: Optional[int] = None
    # Reject modules with drain-required hostcall sites (imports the
    # kernels cannot service in-kernel — every one is a device<->host
    # round trip a hostile module can spin).
    tier0_only_hostcalls: bool = False
    # False = flag mode: violations are reported, never rejected.
    enforce: bool = True

    _KNOWN = frozenset((
        "max_static_cost", "require_bounded", "max_memory_pages",
        "max_memory_pages_touched", "max_value_stack",
        "max_call_depth", "tier0_only_hostcalls", "enforce"))

    @classmethod
    def from_dict(cls, d: dict, where: str = "analysis") \
            -> "AnalysisPolicy":
        bad = set(d) - cls._KNOWN
        if bad:
            raise ValueError(
                f"{where}: unknown analysis policy keys {sorted(bad)}")

        def _int(key):
            return int(d[key]) if d.get(key) is not None else None

        return cls(
            max_static_cost=_int("max_static_cost"),
            require_bounded=bool(d.get("require_bounded", False)),
            max_memory_pages=_int("max_memory_pages"),
            max_memory_pages_touched=_int("max_memory_pages_touched"),
            max_value_stack=_int("max_value_stack"),
            max_call_depth=_int("max_call_depth"),
            tier0_only_hostcalls=bool(d.get("tier0_only_hostcalls",
                                            False)),
            enforce=bool(d.get("enforce", True)))

    # -- evaluation --------------------------------------------------------
    def evaluate(self, analysis) -> List[dict]:
        """Violations of this policy by a ModuleAnalysis (empty = admit).
        `analysis` None (analyzer unavailable for the image) violates
        every enforced limit category at once — a policy-carrying
        tenant never admits an unvetted module."""
        out: List[dict] = []
        if analysis is None:
            if self.max_static_cost is not None or self.require_bounded \
                    or self.max_memory_pages is not None \
                    or self.max_memory_pages_touched is not None \
                    or self.max_value_stack is not None \
                    or self.max_call_depth is not None \
                    or self.tier0_only_hostcalls:
                out.append(_violation(
                    "analysis", "required", "missing",
                    "no static analysis available for this module"))
            return out
        cost = analysis.cost_bound
        if self.require_bounded and cost is None:
            out.append(_violation(
                "require_bounded", "bounded", None,
                "static cost bound is unbounded (loop/recursion/"
                "dynamic call with no static exit)"))
        if self.max_static_cost is not None and \
                (cost is None or cost > self.max_static_cost):
            out.append(_violation(
                "max_static_cost", self.max_static_cost, cost,
                "per-invocation retired-instruction bound over limit"))
        if self.max_memory_pages is not None:
            pages = analysis.mem_pages_bound
            if pages is None or pages > self.max_memory_pages:
                out.append(_violation(
                    "max_memory_pages", self.max_memory_pages, pages,
                    "static linear-memory page bound over the "
                    "resident-lane budget"))
        if self.max_memory_pages_touched is not None:
            touched = getattr(analysis, "mem_pages_touch_bound", None)
            if touched is None or touched > self.max_memory_pages_touched:
                out.append(_violation(
                    "max_memory_pages_touched",
                    self.max_memory_pages_touched, touched,
                    "abstract interpretation could not prove the "
                    "page-touch bound under the limit"))
        if self.max_value_stack is not None:
            vs = analysis.value_stack_bound
            if vs is None or vs > self.max_value_stack:
                out.append(_violation(
                    "max_value_stack", self.max_value_stack, vs,
                    "value-stack depth bound over the lane plane "
                    "budget"))
        if self.max_call_depth is not None:
            cd = analysis.call_depth_bound
            if cd is None or cd > self.max_call_depth:
                out.append(_violation(
                    "max_call_depth", self.max_call_depth, cd,
                    "frame-depth bound over the lane plane budget"))
        if self.tier0_only_hostcalls and analysis.drain_sites > 0:
            out.append(_violation(
                "tier0_only_hostcalls", 0, analysis.drain_sites,
                "module has drain-required hostcall sites (imports "
                "outside the in-kernel tier-0 set)"))
        return out
