"""Report schema for the analyze CLI / --analyze-smoke CI guard.

Hand-rolled structural checker (the container bakes no jsonschema):
`validate_report(doc)` returns a list of problem strings, empty when
the document matches the `wasmedge-tpu/analysis/v1` shape emitted by
ModuleAnalysis.to_dict().  The smoke guard and tests/test_analysis.py
run every emitted report through it, so the wire shape cannot drift
silently."""

from __future__ import annotations

from typing import List

from wasmedge_tpu.analysis.analyzer import SCHEMA


def _is_bound(v) -> bool:
    return v is None or (isinstance(v, int) and not isinstance(v, bool)
                         and v >= 0)


def _req(doc, key, typ, problems, where):
    if key not in doc:
        problems.append(f"{where}: missing key {key!r}")
        return None
    v = doc[key]
    if typ is int and isinstance(v, bool):
        problems.append(f"{where}.{key}: expected int, got bool")
        return None
    if not isinstance(v, typ):
        problems.append(f"{where}.{key}: expected {typ}, "
                        f"got {type(v).__name__}")
        return None
    return v


def validate_report(doc) -> List[str]:
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["report: not an object"]
    if doc.get("schema") != SCHEMA:
        problems.append(f"schema: expected {SCHEMA!r}, "
                        f"got {doc.get('schema')!r}")
    code_len = _req(doc, "code_len", int, problems, "report")
    _req(doc, "n_funcs", int, problems, "report")
    _req(doc, "exports", dict, problems, "report")

    summary = _req(doc, "summary", dict, problems, "report")
    if summary is not None:
        _req(summary, "bounded", bool, problems, "summary")
        for key in ("cost_bound", "value_stack_bound",
                    "call_depth_bound", "mem_pages_bound"):
            if key not in summary:
                problems.append(f"summary: missing key {key!r}")
            elif not _is_bound(summary[key]):
                problems.append(f"summary.{key}: not a bound "
                                f"(int >= 0 or null)")
        if summary.get("bounded") and summary.get("cost_bound") is None:
            problems.append("summary: bounded=true with null cost_bound")
        if not summary.get("bounded", True) \
                and summary.get("cost_bound") is not None:
            problems.append("summary: bounded=false with a cost_bound")
        # absint keys (r19) are OPTIONAL — pre-absint reports must keep
        # validating — but when present they must be well-formed
        if "mem_pages_touch_bound" in summary \
                and not _is_bound(summary["mem_pages_touch_bound"]):
            problems.append("summary.mem_pages_touch_bound: not a "
                            "bound (int >= 0 or null)")
        for key in ("licensed_mem_sites", "unlicensed_mem_sites",
                    "trip_bounded_loops"):
            if key in summary and (isinstance(summary[key], bool)
                                   or not isinstance(summary[key],
                                                     int)):
                problems.append(f"summary.{key}: expected int")

    mem = _req(doc, "memory", dict, problems, "report")
    if mem is not None:
        for key in ("pages_init", "pages_max_declared", "grow_sites"):
            _req(mem, key, int, problems, "memory")
        if "pages_bound" not in mem or not _is_bound(mem["pages_bound"]):
            problems.append("memory.pages_bound: not a bound")

    hc = _req(doc, "hostcalls", dict, problems, "report")
    if hc is not None:
        _req(hc, "imports", list, problems, "hostcalls")
        for key in ("tier0_sites", "drain_sites", "dynamic_call_sites"):
            _req(hc, key, int, problems, "hostcalls")

    supers = _req(doc, "superinstructions", list, problems, "report")
    if supers is not None:
        for i, c in enumerate(supers):
            where = f"superinstructions[{i}]"
            if not isinstance(c, dict):
                problems.append(f"{where}: not an object")
                continue
            ops = _req(c, "ops", list, problems, where)
            n = _req(c, "n", int, problems, where)
            _req(c, "count", int, problems, where)
            _req(c, "weight", int, problems, where)
            if ops is not None and n is not None and len(ops) != n:
                problems.append(f"{where}: len(ops) != n")
            if ops is not None and not all(isinstance(o, str)
                                           for o in ops):
                problems.append(f"{where}.ops: non-string opcode name")

    # optional fusion section (batch/fuse.py plan_fusion: the analyze
    # CLI attaches planned-vs-realized translation counts)
    if "fusion" in doc:
        fu = doc["fusion"]
        if not isinstance(fu, dict):
            problems.append("fusion: not an object")
        else:
            _req(fu, "enabled", bool, problems, "fusion")
            for key in ("patterns", "fused_runs", "fused_cells"):
                _req(fu, key, int, problems, "fusion")
            fcands = _req(fu, "candidates", list, problems, "fusion")
            realized_total = 0
            for i, c in enumerate(fcands or ()):
                where = f"fusion.candidates[{i}]"
                if not isinstance(c, dict):
                    problems.append(f"{where}: not an object")
                    continue
                _req(c, "ops", list, problems, where)
                _req(c, "eligible", bool, problems, where)
                planned = _req(c, "planned", int, problems, where)
                runs_n = _req(c, "realized_runs", int, problems, where)
                _req(c, "realized_cells", int, problems, where)
                if planned is not None and runs_n is not None \
                        and runs_n > planned:
                    problems.append(
                        f"{where}: realized_runs > planned")
                # planned-vs-realized delta (r18): when present it must
                # reconcile with the counts it summarizes
                if isinstance(c.get("delta_runs"), int) \
                        and planned is not None and runs_n is not None \
                        and c["delta_runs"] != planned - runs_n:
                    problems.append(
                        f"{where}: delta_runs != planned - "
                        f"realized_runs")
                if runs_n:
                    realized_total += runs_n
            if fcands is not None and isinstance(
                    fu.get("fused_runs"), int) \
                    and realized_total != fu["fused_runs"]:
                problems.append(
                    "fusion: fused_runs disagrees with candidate "
                    "realized_runs sum")
            # r19 memory-run section (optional, back-compat)
            if "memory" in fu:
                fm = fu["memory"]
                if not isinstance(fm, dict):
                    problems.append("fusion.memory: not an object")
                else:
                    for key in ("licensed_sites", "unlicensed_sites",
                                "mem_runs", "mem_cells",
                                "mem_patterns"):
                        _req(fm, key, int, problems, "fusion.memory")
                    mr = fu.get("mem_runs")
                    if isinstance(mr, list) and isinstance(
                            fm.get("mem_runs"), int) \
                            and len(mr) != fm["mem_runs"]:
                        problems.append(
                            "fusion.memory: mem_runs count disagrees "
                            "with the realized run list")

    funcs = _req(doc, "funcs", list, problems, "report")
    mem_fact_by_pc = {}
    if funcs is not None:
        for fi, f in enumerate(funcs):
            where = f"funcs[{fi}]"
            if not isinstance(f, dict):
                problems.append(f"{where}: not an object")
                continue
            # absint keys (r19): optional for back-compat; reconciled
            # when present
            loops = f.get("loops")
            if loops is not None:
                if not isinstance(loops, list):
                    problems.append(f"{where}.loops: not a list")
                    loops = []
                for li, l in enumerate(loops):
                    if not isinstance(l, dict) \
                            or not isinstance(l.get("head"), int) \
                            or not _is_bound(l.get("trip_bound")):
                        problems.append(
                            f"{where}.loops[{li}]: malformed")
                # a function with a loop can only be cost-bounded when
                # every one of its loops carries a finite trip bound
                if f.get("bounded") and f.get("has_loop") \
                        and any(l.get("trip_bound") is None
                                for l in loops
                                if isinstance(l, dict)):
                    problems.append(
                        f"{where}: bounded with an unbounded loop "
                        f"(trip bounds must license the cost bound)")
            mfs = f.get("mem_facts")
            if mfs is not None:
                if not isinstance(mfs, list):
                    problems.append(f"{where}.mem_facts: not a list")
                    mfs = []
                for mi, mf in enumerate(mfs):
                    if not isinstance(mf, dict) \
                            or not isinstance(mf.get("pc"), int) \
                            or not isinstance(mf.get("licensed"),
                                              bool):
                        problems.append(
                            f"{where}.mem_facts[{mi}]: malformed")
                        continue
                    if mf.get("licensed") and not (
                            mf.get("in_bounds") and mf.get("aligned")):
                        problems.append(
                            f"{where}.mem_facts[{mi}]: licensed "
                            f"without in_bounds+aligned proof")
                    if mf.get("kind") in ("load", "store"):
                        mem_fact_by_pc[mf["pc"]] = bool(
                            mf.get("licensed"))
            _req(f, "idx", int, problems, where)
            _req(f, "name", str, problems, where)
            entry = _req(f, "entry_pc", int, problems, where)
            end = _req(f, "end_pc", int, problems, where)
            bounded = _req(f, "bounded", bool, problems, where)
            for key in ("cost_bound", "value_stack_bound",
                        "call_depth_bound"):
                if key not in f or not _is_bound(f[key]):
                    problems.append(f"{where}.{key}: not a bound")
            if bounded is not None and "cost_bound" in f:
                if bounded != (f["cost_bound"] is not None):
                    problems.append(
                        f"{where}: bounded flag disagrees with "
                        f"cost_bound")
            blocks = _req(f, "blocks", list, problems, where)
            if blocks is None or entry is None or end is None:
                continue
            starts = set()
            for bi, b in enumerate(blocks):
                bw = f"{where}.blocks[{bi}]"
                if not isinstance(b, dict):
                    problems.append(f"{bw}: not an object")
                    continue
                s = _req(b, "start", int, problems, bw)
                e = _req(b, "end", int, problems, bw)
                succ = _req(b, "succ", list, problems, bw)
                _req(b, "cost", int, problems, bw)
                _req(b, "divergence", int, problems, bw)
                if s is not None:
                    starts.add(s)
                if s is not None and e is not None and \
                        not (entry <= s <= e <= end):
                    problems.append(f"{bw}: range outside function")
                if code_len is not None and e is not None \
                        and e >= code_len:
                    problems.append(f"{bw}: end past code_len")
            for bi, b in enumerate(blocks):
                if not isinstance(b, dict):
                    continue
                for t in b.get("succ") or []:
                    if t not in starts:
                        problems.append(
                            f"{where}.blocks[{bi}]: successor {t} is "
                            f"not a block start")
    # realized memory runs must be covered by licenses: every scalar
    # load/store inside a fused mem run carries licensed=true (the
    # "licensed runs are a superset of realized runs" reconciliation)
    if isinstance(doc.get("fusion"), dict) and mem_fact_by_pc:
        for ri, r in enumerate(doc["fusion"].get("mem_runs") or ()):
            if not (isinstance(r, list) and len(r) >= 2
                    and all(isinstance(x, int) for x in r[:2])):
                problems.append(f"fusion.mem_runs[{ri}]: malformed")
                continue
            head, n = r[0], r[1]
            for pc in range(head, head + n):
                if pc in mem_fact_by_pc and not mem_fact_by_pc[pc]:
                    problems.append(
                        f"fusion.mem_runs[{ri}]: unlicensed "
                        f"load/store at pc {pc} inside a fused "
                        f"memory run")
    return problems
