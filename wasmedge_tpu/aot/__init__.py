"""AOT analog: precompiled lowered-image artifacts ("universal twasm").

The reference AOT path (/root/reference/lib/aot/compiler.cpp) compiles
wasm to native code and appends it as a custom AOT section to the original
binary ("universal wasm", compiler.cpp:4270), with a content-addressed
cache (lib/aot/cache.cpp:36-61) and graceful fallback to the interpreter
when the section doesn't match (lib/loader/ast/module.cpp:279-326).

Our engines execute the validator's dense SoA image, so the TPU-native
"compiled artifact" is that image, serialized. compile_module() appends it
as a `tpu.aot` custom section over the original bytes; attach_precompiled()
verifies version + content hash and short-circuits validation on load,
falling back silently on any mismatch. XLA specialization of hot functions
builds on top of this image (wasmedge_tpu/aot/xla_compile.py).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
from typing import Optional

import numpy as np

from wasmedge_tpu.validator.image import FuncMeta, LoweredModule

SECTION_NAME = "tpu.aot"
AOT_VERSION = 1  # reference analog: AOT::kBinaryVersion


def _uleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            return bytes(out)


def serialize_image(img: LoweredModule) -> bytes:
    """LoweredModule -> bytes (json func metadata + npz code planes)."""
    arrays = img.arrays
    meta = {
        "version": AOT_VERSION,
        "funcs": [
            {
                "type_idx": f.type_idx, "nparams": f.nparams,
                "nresults": f.nresults, "nlocals": f.nlocals,
                "entry_pc": f.entry_pc, "end_pc": f.end_pc,
                "max_height": f.max_height,
                "local_types": [int(t) for t in f.local_types],
                "is_import": f.is_import,
                "import_module": f.import_module,
                "import_name": f.import_name,
            }
            for f in img.funcs
        ],
    }
    mjson = json.dumps(meta).encode()
    bio = io.BytesIO()
    np.savez(bio, **arrays)
    blob = bio.getvalue()
    return struct.pack("<II", len(mjson), len(blob)) + mjson + blob


def deserialize_image(data: bytes) -> LoweredModule:
    mlen, blen = struct.unpack_from("<II", data, 0)
    meta = json.loads(data[8 : 8 + mlen].decode())
    if meta["version"] != AOT_VERSION:
        raise ValueError("aot image version mismatch")
    bio = io.BytesIO(data[8 + mlen : 8 + mlen + blen])
    arrays = dict(np.load(bio))
    img = LoweredModule()
    img.op = arrays["op"].tolist()
    img.a = arrays["a"].tolist()
    img.b = arrays["b"].tolist()
    img.c = arrays["c"].tolist()
    img.imm = [int(v) for v in arrays["imm"].astype(np.uint64)]
    img.br_table = arrays["br_table"].reshape(-1).tolist()
    if "v128_lo" in arrays:
        img.v128 = [int(lo) | (int(hi) << 64)
                    for lo, hi in zip(arrays["v128_lo"].tolist(),
                                      arrays["v128_hi"].tolist())]
    for f in meta["funcs"]:
        img.funcs.append(FuncMeta(
            type_idx=f["type_idx"], nparams=f["nparams"],
            nresults=f["nresults"], nlocals=f["nlocals"],
            entry_pc=f["entry_pc"], end_pc=f["end_pc"],
            max_height=f["max_height"],
            local_types=tuple(f["local_types"]),
            is_import=f["is_import"], import_module=f["import_module"],
            import_name=f["import_name"]))
    img.finalize()
    return img


def compile_module(wasm_bytes: bytes, conf=None) -> bytes:
    """wasm -> universal twasm: original bytes + tpu.aot custom section
    (reference: outputWasmLibrary, compiler.cpp:4270)."""
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.loader.loader import Loader
    from wasmedge_tpu.validator.validator import Validator

    conf = conf or Configure()
    mod = Validator(conf).validate(Loader(conf).parse_module(wasm_bytes))
    payload = serialize_image(mod.lowered)
    digest = hashlib.sha256(wasm_bytes).digest()
    body = struct.pack("<I", AOT_VERSION) + digest + payload
    name = SECTION_NAME.encode()
    content = _uleb(len(name)) + name + body
    section = b"\x00" + _uleb(len(content)) + content
    return wasm_bytes + section


def extract_precompiled(wasm_bytes: bytes, custom_sections) -> Optional[bytes]:
    """Return the serialized image iff a tpu.aot section matches the hash
    of the bytes that precede it; None -> interpreter path (the reference's
    fallback seam, module.cpp:279-326)."""
    for name, data, start in custom_sections:
        if name != SECTION_NAME or len(data) < 36:
            continue
        (version,) = struct.unpack_from("<I", data, 0)
        if version != AOT_VERSION:
            continue
        digest = data[4:36]
        if hashlib.sha256(wasm_bytes[:start]).digest() != digest:
            continue
        return data[36:]
    return None


# -- content-addressed cache (reference: lib/aot/cache.cpp:36-61) -----------
def cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "wasmedge_tpu")


def cache_path(wasm_bytes: bytes) -> str:
    return os.path.join(cache_dir(), hashlib.sha256(wasm_bytes).hexdigest()
                        + ".twasm")


def compile_cached(wasm_bytes: bytes, conf=None) -> bytes:
    path = cache_path(wasm_bytes)
    if os.path.exists(path):
        with open(path, "rb") as f:
            return f.read()
    out = compile_module(wasm_bytes, conf)
    os.makedirs(cache_dir(), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(out)
    os.replace(tmp, path)
    return out
