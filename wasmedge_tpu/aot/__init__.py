"""AOT analog: precompiled lowered-image artifacts ("universal twasm").

The reference AOT path (/root/reference/lib/aot/compiler.cpp) compiles
wasm to native code and appends it as a custom AOT section to the original
binary ("universal wasm", compiler.cpp:4270), with a content-addressed
cache (lib/aot/cache.cpp:36-61) and graceful fallback to the interpreter
when the section doesn't match (lib/loader/ast/module.cpp:279-326).

Our engines execute the validator's dense SoA image, so the TPU-native
"compiled artifact" is that image, serialized. compile_module() appends it
as a `tpu.aot` custom section over the original bytes; attach_precompiled()
verifies version + content hash and short-circuits validation on load,
falling back silently on any mismatch; verify_image() structurally proves
an embedded image safe before the engines will execute it.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
from typing import Optional

import numpy as np

from wasmedge_tpu.validator.image import (
    LOP_BR,
    LOP_BRNZ,
    LOP_BRZ,
    FuncMeta,
    LoweredModule,
)

SECTION_NAME = "tpu.aot"
AOT_VERSION = 1  # reference analog: AOT::kBinaryVersion


def _uleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            return bytes(out)


def fused_planes_for(img: LoweredModule, mod):
    """The Pallas engine's fused encoding: the block-fused hid plane
    (fuse_blocks rewrites block-head slots to block-shape ids; operand
    planes a/b/c/ilo/ihi stay the originals — handlers read immediates
    at pc+offset) derived from the lowered image and the module's
    DECLARED types/tables (mod is required: dense type ids and the
    call_indirect table window are derived from it, and the batch
    subset forbids table mutation, so the declared minimum table size
    equals the live size).  Block SHAPES are not persisted: consumers
    regenerate them (deterministically) with the hid plane they verify
    against.  Returns None when the module is outside the batch
    subset."""
    from wasmedge_tpu.batch.image import batchability, build_device_image
    from wasmedge_tpu.batch.pallas_engine import (
        fuse_blocks,
        hid_plane,
        pallas_image_eligibility,
    )

    host_imports = {i for i, f in enumerate(img.funcs) if f.is_import}
    if batchability(img, host_imports=host_imports,
                    n_memories=len(mod.all_memory_types())) is not None:
        return None
    tables = mod.all_table_types()
    table0 = [0] * int(tables[0].limit.min) if tables else None
    dimg = build_device_image(img, mod=mod, table0=table0)
    # the shared eligibility predicate (not batchability) gates the fused
    # encoding: batchable-but-not-pallas modules (e.g. v128 today) run on
    # the SIMT engine and must serialize without fused planes rather than
    # crash (VERDICT r3 weak #1)
    if pallas_image_eligibility(dimg) is not None:
        return None
    hid, _shapes = fuse_blocks(hid_plane(dimg), dimg)
    return {"hid": hid, "a": dimg.a, "b": dimg.b, "c": dimg.c,
            "ilo": dimg.imm_lo, "ihi": dimg.imm_hi}


def serialize_image(img: LoweredModule, mod=None) -> bytes:
    """LoweredModule -> bytes (json func metadata + npz code planes +
    the fused Pallas encoding when the module is batchable and the
    declared module is available)."""
    arrays = dict(img.arrays)
    fused = fused_planes_for(img, mod) if mod is not None else None
    if fused is not None:
        for k, v in fused.items():
            arrays[f"fz_{k}"] = v
    meta = {
        "version": AOT_VERSION,
        "funcs": [
            {
                "type_idx": f.type_idx, "nparams": f.nparams,
                "nresults": f.nresults, "nlocals": f.nlocals,
                "entry_pc": f.entry_pc, "end_pc": f.end_pc,
                "max_height": f.max_height,
                "local_types": [int(t) for t in f.local_types],
                "is_import": f.is_import,
                "import_module": f.import_module,
                "import_name": f.import_name,
            }
            for f in img.funcs
        ],
    }
    mjson = json.dumps(meta).encode()
    bio = io.BytesIO()
    np.savez(bio, **arrays)
    blob = bio.getvalue()
    return struct.pack("<II", len(mjson), len(blob)) + mjson + blob


def deserialize_image(data: bytes) -> LoweredModule:
    mlen, blen = struct.unpack_from("<II", data, 0)
    meta = json.loads(data[8 : 8 + mlen].decode())
    if meta["version"] != AOT_VERSION:
        raise ValueError("aot image version mismatch")
    bio = io.BytesIO(data[8 + mlen : 8 + mlen + blen])
    arrays = dict(np.load(bio))
    img = LoweredModule()
    img.op = arrays["op"].tolist()
    img.a = arrays["a"].tolist()
    img.b = arrays["b"].tolist()
    img.c = arrays["c"].tolist()
    img.imm = [int(v) for v in arrays["imm"].astype(np.uint64)]
    img.br_table = arrays["br_table"].reshape(-1).tolist()
    if "v128_lo" in arrays:
        img.v128 = [int(lo) | (int(hi) << 64)
                    for lo, hi in zip(arrays["v128_lo"].tolist(),
                                      arrays["v128_hi"].tolist())]
    if "fz_hid" in arrays:
        # the persisted Pallas fused encoding; consumers verify it by
        # regeneration before use (verify_fused)
        img.fused = {k: arrays[f"fz_{k}"]
                     for k in ("hid", "a", "b", "c", "ilo", "ihi")}
    for f in meta["funcs"]:
        img.funcs.append(FuncMeta(
            type_idx=f["type_idx"], nparams=f["nparams"],
            nresults=f["nresults"], nlocals=f["nlocals"],
            entry_pc=f["entry_pc"], end_pc=f["end_pc"],
            max_height=f["max_height"],
            local_types=tuple(f["local_types"]),
            is_import=f["is_import"], import_module=f["import_module"],
            import_name=f["import_name"]))
    img.finalize()
    return img


def verify_fused(img: LoweredModule, mod) -> bool:
    """Verify a deserialized fused-plane section by exact regeneration
    (mod required — the same declared module serialization used).

    Regeneration is cheap (one linear pass) next to XLA compilation, so
    the security story stays trivial: a tampered or stale fused section
    can never influence execution — the engines use verified planes or
    regenerate.  The heavyweight compiled artifact (the XLA executable)
    is content-addressed in the persistent compilation cache
    (batch.ensure_jax_backend), which a verified artifact keys into."""
    fused = getattr(img, "fused", None)
    if fused is None:
        return False
    regen = fused_planes_for(img, mod)
    if regen is None:
        return False
    return all(np.array_equal(fused[k], regen[k]) for k in regen)


def compile_payload(wasm_bytes: bytes, conf=None) -> bytes:
    """wasm -> the serialized lowered-image payload.  These are the
    exact bytes a .twasm's tpu.aot section embeds AND what the
    gateway's content-addressed CompileCache stores (imagestore/
    compilecache.py) — one payload format, every cache tier."""
    from wasmedge_tpu.common.configure import Configure
    from wasmedge_tpu.loader.loader import Loader
    from wasmedge_tpu.validator.validator import Validator

    conf = conf or Configure()
    mod = Validator(conf).validate(Loader(conf).parse_module(wasm_bytes))
    return serialize_image(mod.lowered, mod=mod)


def twasm_from_payload(wasm_bytes: bytes, payload: bytes) -> bytes:
    """Append an already-built image payload as the tpu.aot section
    (reference: outputWasmLibrary, compiler.cpp:4270)."""
    digest = hashlib.sha256(wasm_bytes).digest()
    body = struct.pack("<I", AOT_VERSION) + digest + payload
    name = SECTION_NAME.encode()
    content = _uleb(len(name)) + name + body
    section = b"\x00" + _uleb(len(content)) + content
    return wasm_bytes + section


def compile_module(wasm_bytes: bytes, conf=None) -> bytes:
    """wasm -> universal twasm: original bytes + tpu.aot custom
    section."""
    return twasm_from_payload(wasm_bytes,
                              compile_payload(wasm_bytes, conf))


def extract_precompiled(wasm_bytes: bytes, custom_sections) -> Optional[bytes]:
    """Return the serialized image iff a tpu.aot section matches the hash
    of the bytes that precede it; None -> interpreter path (the reference's
    fallback seam, module.cpp:279-326)."""
    for name, data, start in custom_sections:
        if name != SECTION_NAME or len(data) < 36:
            continue
        (version,) = struct.unpack_from("<I", data, 0)
        if version != AOT_VERSION:
            continue
        digest = data[4:36]
        if hashlib.sha256(wasm_bytes[:start]).digest() != digest:
            continue
        return data[36:]
    return None


def verify_image(img: LoweredModule, mod) -> None:
    """Structural verifier for a deserialized lowered image.

    The tpu.aot section rides inside attacker-controlled bytes, so an
    embedded image must never be trusted to index stacks/globals/functions
    out of bounds (the engines do unchecked `st[fp+a]`-style access by
    design). This proves, per function, that every reachable pc has a
    consistent operand-stack height within [0, max_height], every branch
    target stays inside the function, and every index operand is in range —
    the same guarantees the FormChecker lowering pass establishes when it
    builds the image itself. Raises ValueError on any violation; the
    validator then falls back to full body validation (the reference's
    graceful AOT-mismatch fallback, lib/loader/ast/module.cpp:279-326).
    """
    from wasmedge_tpu.common.opcodes import NAME_TO_ID, OPCODES, Op

    nfuncs = len(img.funcs)
    if nfuncs != mod.total_funcs:
        raise ValueError("func count mismatch")
    code_len = img.code_len
    # cross-plane consistency: every plane deserialized independently from
    # the untrusted npz must cover the whole code image
    if not (len(img.a) == len(img.b) == len(img.c) == len(img.imm)
            == code_len) or len(img.br_table) % 3 != 0:
        raise ValueError("aot image verify: plane length mismatch")
    for fn in img.funcs:
        for v in (fn.type_idx, fn.nparams, fn.nresults, fn.nlocals,
                  fn.entry_pc, fn.end_pc, fn.max_height):
            if type(v) is not int:
                raise ValueError("aot image verify: non-int func metadata")
    brt = img.br_table
    n_brt = len(brt) // 3
    ntypes = len(mod.types)
    nglobals = len(mod.all_global_types())
    ntables = len(mod.all_table_types())
    nmems = len(mod.all_memory_types())
    nelems = len(mod.elements)
    ndatas = len(mod.datas)
    nv128 = len(img.v128)
    op_return = NAME_TO_ID["return"]

    def fail(msg):
        raise ValueError(f"aot image verify: {msg}")

    nimp = mod.num_imported_funcs
    for fi, fn in enumerate(img.funcs):
        ft = mod.func_type_of(fi)
        if fn.nparams != len(ft.params) or fn.nresults != len(ft.results):
            fail(f"func {fi} signature mismatch")
        if fi < nimp:
            if not fn.is_import:
                fail(f"func {fi} should be an import")
            continue
        if fn.is_import:
            fail(f"func {fi} should not be an import")
        if fn.nlocals < fn.nparams or fn.nlocals > (1 << 20):
            fail(f"func {fi} bad nlocals")
        if fn.max_height < 0 or fn.max_height > (1 << 20):
            fail(f"func {fi} bad max_height")
        if not (0 <= fn.entry_pc <= fn.end_pc < code_len):
            fail(f"func {fi} pc range out of bounds")

    for fi in range(nimp, nfuncs):
        fn = img.funcs[fi]
        lo, hi = fn.entry_pc, fn.end_pc
        heights = {fn.entry_pc: 0}
        work = [fn.entry_pc]

        def flow(pc, h):
            if not (lo <= pc <= hi):
                fail(f"func {fi} pc {pc} escapes function body")
            if h < 0 or h > fn.max_height:
                fail(f"func {fi} pc {pc} height {h} out of [0,{fn.max_height}]")
            prev = heights.get(pc)
            if prev is None:
                heights[pc] = h
                work.append(pc)
            elif prev != h:
                fail(f"func {fi} pc {pc} inconsistent heights {prev}/{h}")

        while work:
            pc = work.pop()
            h = heights[pc]
            op, a, b, c = img.op[pc], img.a[pc], img.b[pc], img.c[pc]

            if op == LOP_BR:
                if b < 0 or h < b or c < 0 or c > h - b:
                    fail(f"func {fi} pc {pc} bad br keep/pop")
                flow(a, c + b)
                continue
            if op == LOP_BRZ:
                if h < 1:
                    fail(f"func {fi} pc {pc} brz underflow")
                flow(a, h - 1)
                flow(pc + 1, h - 1)
                continue
            if op == LOP_BRNZ:
                if b < 0 or h < 1 + b or c < 0 or c > h - 1 - b:
                    fail(f"func {fi} pc {pc} bad br_if keep/pop")
                flow(a, c + b)
                flow(pc + 1, h - 1)
                continue
            if 0 <= op < len(OPCODES):
                name = OPCODES[op].name
                sig = OPCODES[op].sig
            else:
                fail(f"func {fi} pc {pc} unknown op {op}")
            if name == "br_table":
                if h < 1:
                    fail(f"func {fi} pc {pc} br_table underflow")
                if a < 0 or b < 0 or a + b + 1 > n_brt:
                    fail(f"func {fi} pc {pc} br_table entries out of range")
                for e in range(a, a + b + 1):
                    tgt, keep, pop_to = brt[e * 3], brt[e * 3 + 1], brt[e * 3 + 2]
                    if keep < 0 or h - 1 < keep or pop_to < 0 \
                            or pop_to > h - 1 - keep:
                        fail(f"func {fi} pc {pc} bad br_table keep/pop")
                    flow(tgt, pop_to + keep)
                continue
            if op == op_return:
                if b != fn.nresults or h < b:
                    fail(f"func {fi} pc {pc} bad return arity")
                continue
            if name in ("call", "return_call"):
                if not (0 <= a < nfuncs):
                    fail(f"func {fi} pc {pc} call target out of range")
                cm = img.funcs[a]
                if h < cm.nparams:
                    fail(f"func {fi} pc {pc} call underflow")
                if name == "return_call":
                    if cm.nresults != fn.nresults:
                        fail(f"func {fi} pc {pc} tail-call result mismatch")
                    continue
                flow(pc + 1, h - cm.nparams + cm.nresults)
                continue
            if name in ("call_indirect", "return_call_indirect"):
                if not (0 <= a < ntypes) or not (0 <= b < ntables):
                    fail(f"func {fi} pc {pc} call_indirect indices")
                ft = mod.types[a]
                if h < 1 + len(ft.params):
                    fail(f"func {fi} pc {pc} call_indirect underflow")
                if name == "return_call_indirect":
                    if len(ft.results) != fn.nresults:
                        fail(f"func {fi} pc {pc} tail-call result mismatch")
                    continue
                flow(pc + 1, h - 1 - len(ft.params) + len(ft.results))
                continue
            if name == "unreachable":
                continue

            # index-operand checks for non-control ops
            if name in ("local.get", "local.set", "local.tee"):
                if not (0 <= a < fn.nlocals):
                    fail(f"func {fi} pc {pc} local index out of range")
            elif name in ("global.get", "global.set"):
                if not (0 <= a < nglobals):
                    fail(f"func {fi} pc {pc} global index out of range")
            elif name == "ref.func":
                if not (0 <= a < nfuncs):
                    fail(f"func {fi} pc {pc} ref.func out of range")
            elif name in ("table.get", "table.set", "table.size", "table.grow",
                          "table.fill"):
                if not (0 <= a < ntables):
                    fail(f"func {fi} pc {pc} table index out of range")
            elif name == "table.copy":
                if not (0 <= a < ntables and 0 <= b < ntables):
                    fail(f"func {fi} pc {pc} table index out of range")
            elif name == "table.init":
                if not (0 <= a < nelems and 0 <= b < ntables):
                    fail(f"func {fi} pc {pc} table.init indices")
            elif name == "elem.drop":
                if not (0 <= a < nelems):
                    fail(f"func {fi} pc {pc} elem index out of range")
            elif name in ("memory.init", "data.drop"):
                if not (0 <= a < ndatas):
                    fail(f"func {fi} pc {pc} data index out of range")
            elif name in ("v128.const", "i8x16.shuffle"):
                if not (0 <= a < nv128):
                    fail(f"func {fi} pc {pc} v128 const index out of range")
            if OPCODES[op].imm in ("memarg", "memidx", "memidx2",
                                   "dataidx_memidx") and nmems < 1:
                fail(f"func {fi} pc {pc} memory op without memory")

            delta = _STACK_EFFECTS.get(name)
            if delta is None:
                if sig is None:
                    fail(f"func {fi} pc {pc} unverifiable op {name}")
                npop, npush = (len(s) for s in sig.split("->"))
            else:
                npop, npush = delta
            if h < npop:
                fail(f"func {fi} pc {pc} operand underflow ({name})")
            flow(pc + 1, h - npop + npush)


# (pops, pushes) for sig-less ops the verifier accepts.
_STACK_EFFECTS = {
    "nop": (0, 0),
    "drop": (1, 0),
    "select": (3, 1),
    "select_t": (3, 1),
    "ref.null": (0, 1),
    "ref.is_null": (1, 1),
    "ref.func": (0, 1),
    "local.get": (0, 1),
    "local.set": (1, 0),
    "local.tee": (1, 1),
    "global.get": (0, 1),
    "global.set": (1, 0),
    "table.get": (1, 1),
    "table.set": (2, 0),
    "table.grow": (2, 1),
    "table.fill": (3, 0),
    "table.copy": (3, 0),
    "table.init": (3, 0),
}


# -- content-addressed cache (reference: lib/aot/cache.cpp:36-61) -----------
def cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "wasmedge_tpu")


def cache_path(wasm_bytes: bytes) -> str:
    return os.path.join(cache_dir(), hashlib.sha256(wasm_bytes).hexdigest()
                        + ".twasm")


def compile_cached(wasm_bytes: bytes, conf=None) -> bytes:
    path = cache_path(wasm_bytes)
    if os.path.exists(path):
        with open(path, "rb") as f:
            return f.read()
    # the shared image-payload cache (imagestore/compilecache.py) lives
    # beside the .twasm artifacts: a lowering the gateway (or a prior
    # export) already paid for turns into a pure section append here,
    # and a fresh lowering here seeds the gateway's next registration
    from wasmedge_tpu.imagestore.compilecache import CompileCache

    sha = hashlib.sha256(wasm_bytes).hexdigest()
    cc = CompileCache()
    cc.enable(cache_dir())
    payload = cc.load(sha)
    if payload is None:
        payload = compile_payload(wasm_bytes, conf)
        cc.store(sha, payload)
    out = twasm_from_payload(wasm_bytes, payload)
    os.makedirs(cache_dir(), exist_ok=True)
    from wasmedge_tpu.utils.fsio import atomic_write_bytes

    atomic_write_bytes(path, out)
    return out
