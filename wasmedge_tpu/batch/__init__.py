"""tpu_batch engine: thousands of Wasm instances in SIMT lockstep on TPU.

This is the component the north star mandates (BASELINE.json): the
reference's `Executor::execute` dispatch loop (/root/reference/lib/executor/
engine/engine.cpp:68-1641) re-imagined as a vectorized lane machine. Each
TPU lane holds one instance's {pc, sp, fp, operand stack, call stack, linear
memory} as struct-of-arrays in HBM; every step fetches each lane's
instruction and executes all opcode-class handlers under lane masks
(divergence-safe SIMT), with traps recorded per lane instead of unwinding.

Values are two int32 planes (lo, hi): i32/f32 live in lo, i64 spans both —
the TPU-native layout (no 64-bit emulation tax on 32-bit ops, f32 via
bitcast). f64 and a few rare conversions are feature-gated: modules using
them fall back to the scalar/native engine via the Configure engine seam.

Known divergence on real TPU hardware: the TPU VPU flushes f32 subnormals
to zero, so float workloads touching denormals differ from IEEE in the last
ulp-range; integer workloads (the headline benches) are bit-exact. The
parity suite runs on the CPU backend where XLA is IEEE-strict; a softfloat
rare-path for denormals is planned (tracked in SURVEY.md §7 hard part (b)).
"""

from wasmedge_tpu.batch.engine import BatchEngine, BatchResult
from wasmedge_tpu.batch.image import DeviceImage, batchability
from wasmedge_tpu.batch.uniform import UniformBatchEngine


def ensure_jax_backend():
    """Initialize the JAX backend, falling back to CPU when the configured
    platform (e.g. a TPU plugin named by JAX_PLATFORMS) is unavailable in
    this process — keeps the CLI/batch path usable off-accelerator.

    Also enables the persistent XLA compilation cache (content-addressed
    on-disk, like the reference's AOT cache lib/aot/cache.cpp:36-61):
    a fresh process re-running a previously compiled kernel geometry
    loads the compiled executable from disk instead of re-running
    XLA/Mosaic.  Directory: $WASMEDGE_TPU_CACHE or
    ~/.cache/wasmedge_tpu/xla; set WASMEDGE_TPU_CACHE=off to disable."""
    import os

    import jax

    cache_dir = os.environ.get("WASMEDGE_TPU_CACHE")
    if cache_dir != "off":
        if not cache_dir:
            cache_dir = os.path.join(
                os.path.expanduser("~"), ".cache", "wasmedge_tpu", "xla")
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.1)
        except Exception:  # cache is an optimization, never a failure
            pass

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        jax.devices()


def make_engine(inst, store=None, conf=None, lanes=None, mesh=None):
    """Engine-selection seam: uniform fast path (with SIMT fallback) when
    Configure.batch.uniform is set, plain SIMT otherwise."""
    from wasmedge_tpu.common.configure import Configure

    conf = conf or Configure()
    if conf.batch.uniform:
        return UniformBatchEngine(inst, store=store, conf=conf, lanes=lanes,
                                  mesh=mesh)
    return BatchEngine(inst, store=store, conf=conf, lanes=lanes, mesh=mesh)


__all__ = ["BatchEngine", "BatchResult", "DeviceImage", "batchability",
           "UniformBatchEngine", "make_engine"]
