"""Checkpoint/resume of batch execution state.

SURVEY.md §5.4: the reference has no checkpointing (runs are short-lived),
but the batch engine's fully-SoA state makes snapshotting thousands of
in-flight instances a plain array save — the design the survey said was
worth building in.  A checkpoint is an .npz of every BatchState plane plus
a metadata record binding it to the module image (content hash) and the
execution cursor (retired steps), so a resume onto a different image or a
tampered file is refused rather than misexecuted.

Flow: `state = engine.initial_state(...)`; drive it in slices with
`engine.run_from_state(state, total, budget)`; `save(path, engine, state,
total)` at any boundary; later `state, total = load(path, engine)` and
keep driving.  Works for single-module and multi-tenant engines alike
(the state layout is the same).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Tuple

import numpy as np

from wasmedge_tpu.batch.engine import BatchEngine, BatchState
from wasmedge_tpu.utils.fsio import atomic_write_bytes

FORMAT_VERSION = 1


def image_fingerprint(img) -> str:
    """Content hash over the device image's executable planes."""
    h = hashlib.sha256()
    for name in ("cls", "sub", "a", "b", "c", "imm_lo", "imm_hi",
                 "br_table", "f_entry", "f_nparams", "f_nlocals",
                 "f_nresults", "f_frame_top", "f_type", "table0"):
        h.update(np.ascontiguousarray(getattr(img, name)).tobytes())
    if img.v128 is not None:
        # v128 constants/shuffle masks are executable content too: two
        # images identical in code planes but differing here must not
        # share a fingerprint
        h.update(np.ascontiguousarray(img.v128).tobytes())
    # r05 segment snapshots feed table.init / memory.init — executable
    # content like v128 constants (absent on pre-r05 images)
    for name in ("elem_flat", "elem_off", "elem_len",
                 "data_words", "data_off", "data_len"):
        arr = getattr(img, name, None)
        if arr is not None:
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def save(path, engine: BatchEngine, state: BatchState, total_steps: int,
         invocation=None, stdout_pos=None, extra_arrays=None):
    """Snapshot an in-flight batch to `path` (.npz).

    `invocation` (optional dict, e.g. the supervisor's function-name +
    argument fingerprint) is recorded in the metadata so a CROSS-PROCESS
    resume can refuse a snapshot taken for a different call — the image
    hash alone cannot tell f(30) from f(31).

    `stdout_pos` overrides the journaled stdout cursor with a caller-held
    snapshot.  A caller whose `state` may be older than the engine's live
    cursor (the serving layer checkpointing from another thread while a
    launch slice is in flight) must pass the positions it captured when
    `state` was current, or a restore would suppress output the saved
    state has not produced yet.

    `extra_arrays` (optional {name: ndarray}) rides alongside the state
    planes — the serving layer embeds swapped virtual-lane blobs
    (wasmedge_tpu/hv/) so a snapshot is self-contained without faulting
    cold lanes onto the device.  Names must not collide with the
    `state_` prefix; `load()` ignores them, `read_extra_arrays()` reads
    them back."""
    cfg = engine.cfg
    meta = {
        "format": FORMAT_VERSION,
        "image_sha256": image_fingerprint(engine.img),
        "lanes": engine.lanes,
        "total_steps": int(total_steps),
        # trap thresholds / plane shapes depend on the engine geometry;
        # a resume under different knobs would misexecute, so bind them
        "geometry": {
            "value_stack_depth": cfg.value_stack_depth,
            "call_stack_depth": cfg.call_stack_depth,
            "memory_pages_per_lane": cfg.memory_pages_per_lane,
            "mem_pages_max": int(engine.img.mem_pages_max),
        },
    }
    if invocation is not None:
        meta["invocation"] = invocation
    arrays = {f"state_{name}": np.asarray(getattr(state, name))
              for name in state._fields
              if getattr(state, name) is not None}
    # stdout flush cursor (batch/hostcall.py _stdout_cursor): journal the
    # logical stream positions so a restore rewinds them with the state —
    # the exactly-once half the high-water mark (engine-resident) needs.
    # Materialized (zeros) even when no flush has happened yet: a
    # snapshot taken BEFORE the first flush must still rewind pos to 0
    # on restore, or the first post-snapshot flush replays unsuppressed.
    if getattr(state, "so_buf", None) is not None:
        if stdout_pos is not None:
            arrays["stdout_pos"] = np.asarray(stdout_pos, np.int64)
        else:
            from wasmedge_tpu.batch.hostcall import _stdout_cursor

            pos, _ = _stdout_cursor(engine,
                                    int(np.asarray(state.so_off).size))
            arrays["stdout_pos"] = np.asarray(pos, np.int64)
    # lane-compaction permutation (batch/compact.py): the src mapping
    # must roll back with the state on restore, or results would come
    # back lane-shuffled after a crash mid-compacted-run.  Serving
    # engines never carry one (the server's binding journal is already
    # permuted consistently with the snapshot).
    comp = getattr(engine, "compactor", None)
    if comp is not None and not comp.identity:
        arrays["lane_src"] = np.asarray(comp.src, np.int64)
    for name, arr in (extra_arrays or {}).items():
        if name.startswith("state_") or name in arrays:
            raise ValueError(f"extra array name {name!r} collides with "
                             f"a state plane")
        arrays[name] = np.asarray(arr)
    buf = io.BytesIO()
    np.savez_compressed(buf, meta=json.dumps(meta), **arrays)
    data = buf.getvalue()
    if hasattr(path, "write"):
        path.write(data)
    else:
        # Crash-safe write: an interrupted save must never leave a
        # truncated .npz at the target path for a later resume to trip
        # over (or clobber a previous good snapshot).
        atomic_write_bytes(path, data)
        # r24 integrity sidecar: the at-rest scrubber re-verifies this
        # digest on cadence and quarantines a rotted member BEFORE a
        # recovery walk would load it.  Best-effort — the archive's own
        # validation still backstops a missing sidecar.
        try:
            atomic_write_bytes(
                os.fspath(path) + ".sha256",
                hashlib.sha256(data).hexdigest().encode())
        except OSError:
            pass


def read_meta(path) -> dict:
    """The metadata record alone (no state reconstruction) — used by
    the supervisor's cross-process lineage adoption to check the
    invocation binding before paying for a full load."""
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["meta"]))


def read_extra_arrays(path, prefix: str) -> dict:
    """Extra (non-state) arrays whose names start with `prefix` — the
    read half of save()'s `extra_arrays` (serving-layer swapped-lane
    blobs ride here)."""
    out = {}
    with np.load(path, allow_pickle=False) as z:
        for name in z.files:
            if name.startswith(prefix):
                out[name] = np.asarray(z[name])
    return out


def load(path, engine: BatchEngine) -> Tuple[BatchState, int]:
    """Restore a snapshot; refuses a checkpoint from a different module
    image or lane geometry."""
    import jax.numpy as jnp

    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        if meta.get("format") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint format {meta.get('format')}")
        if meta["image_sha256"] != image_fingerprint(engine.img):
            raise ValueError("checkpoint was taken from a different module "
                             "image")
        if meta["lanes"] != engine.lanes:
            raise ValueError(f"checkpoint has {meta['lanes']} lanes, "
                             f"engine has {engine.lanes}")
        cfg = engine.cfg
        want_geom = {
            "value_stack_depth": cfg.value_stack_depth,
            "call_stack_depth": cfg.call_stack_depth,
            "memory_pages_per_lane": cfg.memory_pages_per_lane,
            "mem_pages_max": int(engine.img.mem_pages_max),
        }
        if meta.get("geometry") != want_geom:
            raise ValueError(
                f"checkpoint geometry {meta.get('geometry')} does not "
                f"match the engine's {want_geom}")
        fields = {}
        for name in BatchState._fields:
            key = f"state_{name}"
            # optional planes (v128 extension) absent for non-SIMD images
            fields[name] = jnp.asarray(z[key]) if key in z.files else None
        if getattr(engine.img, "has_simd", False):
            # no membership guard: if these planes are ever renamed this
            # must fail loudly here, not silently skip the check
            missing = [n for n in ("stack_e2", "stack_e3")
                       if fields.get(n) is None]
            if missing:
                raise ValueError(
                    "checkpoint refused: geometry mismatch — engine image "
                    f"has v128 but checkpoint lacks planes {missing} "
                    "(pre-SIMD checkpoint resumed against a SIMD image?)")
        from wasmedge_tpu.batch.engine import r05_plane_names

        missing = [n for n in r05_plane_names(engine.img)
                   if fields.get(n) is None]
        if missing:
            raise ValueError(
                "checkpoint refused: engine image uses table/segment "
                f"families but checkpoint lacks planes {missing}")
        # r06 tier-0 hostcall planes: an engine that services tier-0
        # in-kernel traces against t0_ctr (and so_buf/so_off when
        # fd_write buffering is on) — a pre-r06 checkpoint must be
        # refused cleanly, not crash at trace time
        t0kinds = getattr(engine, "_t0kinds", None)
        if t0kinds is not None:
            from wasmedge_tpu.batch.image import T0_FD_WRITE

            want = ["t0_ctr"]
            if (t0kinds == T0_FD_WRITE).any():
                want += ["so_buf", "so_off"]
            missing = [n for n in want if fields.get(n) is None]
            if missing:
                raise ValueError(
                    "checkpoint refused: engine services tier-0 "
                    f"hostcalls but checkpoint lacks planes {missing} "
                    "(pre-r06 checkpoint?)")
        _validate_planes(fields, engine)
        # rewind the stdout flush cursor with the state: the journaled
        # logical position replaces the engine's, the written high-water
        # mark only ever grows (in-process restore keeps suppressing
        # output flushed after this snapshot; a fresh process starts its
        # high-water AT the snapshot — output the dead process flushed
        # beyond it is outside what any journal-in-checkpoint can prove)
        if "stdout_pos" in z.files:
            from wasmedge_tpu.batch.hostcall import _stdout_cursor

            journaled = np.asarray(z["stdout_pos"], np.int64)
            pos, hw = _stdout_cursor(engine, journaled.size)
            pos[:] = journaled
            np.maximum(hw, journaled, out=hw)
        # roll the lane-compaction mapping back to this snapshot's
        # (identity when the snapshot predates any compaction)
        from wasmedge_tpu.batch.compact import restore_lane_src

        restore_lane_src(engine, np.asarray(z["lane_src"], np.int64)
                         if "lane_src" in z.files else None)
    return BatchState(**fields), meta["total_steps"]


def _validate_planes(fields, engine: BatchEngine):
    """Refuse control planes a crafted npz could use to misexecute.

    The image hash/geometry checks above prove provenance of the *code*;
    this proves the restored *control state* stays inside it: device
    gathers clip silently and host-side outcall serving does raw numpy
    indexing with fp/opbase, so negative or oversized values would
    wrap-index into other frames' rows instead of trapping."""
    from wasmedge_tpu.batch.image import CLS_HOSTCALL, TRAP_HOSTCALL

    cfg = engine.cfg
    img = engine.img
    D = cfg.value_stack_depth
    CD = cfg.call_stack_depth
    pc = np.asarray(fields["pc"])
    sp = np.asarray(fields["sp"])
    fp = np.asarray(fields["fp"])
    ob = np.asarray(fields["opbase"])
    cd = np.asarray(fields["call_depth"])
    pages = np.asarray(fields["mem_pages"])
    trap = np.asarray(fields["trap"])
    # the TRAP_HOSTCALL sentinel re-enters host serving on resume: it is
    # only legitimate when the lane really sits at a hostcall stub,
    # otherwise a crafted file triggers a host call the code never made
    at_stub = img.cls[np.clip(pc, 0, img.code_len - 1)] == CLS_HOSTCALL
    checks = [
        ("pc", (pc >= 0) & (pc < img.code_len)),
        ("stack pointers", (fp >= 0) & (fp <= ob) & (ob <= sp) & (sp <= D)),
        ("call_depth", (cd >= 0) & (cd <= CD)),
        ("mem_pages", (pages >= 0) & (pages <= max(img.mem_pages_max, 0))),
        ("trap", (trap >= TRAP_HOSTCALL) & (trap < 256)
         & ((trap != TRAP_HOSTCALL) | at_stub)),
    ]
    # live call frames (rows < call_depth) feed RETURN's pc/fp/opbase pops
    # and host-side numpy indexing verbatim — same exposure as the top row
    live = np.arange(CD)[:, None] < cd[None, :]
    fr_pc = np.asarray(fields["fr_ret_pc"])
    fr_fp = np.asarray(fields["fr_fp"])
    fr_ob = np.asarray(fields["fr_opbase"])
    checks += [
        ("frame ret_pc", ~live | ((fr_pc >= 0) & (fr_pc < img.code_len))),
        ("frame fp/opbase", ~live | ((fr_fp >= 0) & (fr_fp <= fr_ob)
                                     & (fr_ob <= D))),
    ]
    for name, ok in checks:
        if not bool(np.all(ok)):
            lane = int(np.argmin(np.all(ok, axis=0) if ok.ndim == 2 else ok))
            raise ValueError(
                f"checkpoint refused: {name} plane out of range "
                f"(first bad lane {lane})")
