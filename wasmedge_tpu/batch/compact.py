"""Divergence-aware lane compaction: PC-sorted lane regrouping at
launch boundaries (ROADMAP #6a).

SIMT lanes that sit at different PCs interleave arbitrarily across the
lane axis: the dispatch step still walks every allocated column, retired
lanes keep occupying dispatch width until batch drain, and convergent
neighbourhoods (which the fused-superinstruction heads and the Pallas
block tier exploit) are destroyed by admission order.  GPUs solve the
same problem by regrouping threads at convergence points ("Control Flow
Management in Modern GPUs", PAPERS.md); this module is that regrouping
pass for the BatchState plane columns.

At a launch boundary the compactor:

  1. reads the round's pc/trap host mirrors (the trap mirror is pulled
     every round anyway; pc is one extra [lanes] int32 transfer, paid
     only when the anti-thrash quantum allows a fire);
  2. estimates divergence: adjacent-pair key breaks in the current lane
     order vs the minimum achievable (#distinct keys - 1) — the win a
     sort can buy — plus the live-lane count (the win a live-prefix
     pack can buy);
  3. decides via a deterministic cost model (skip when the estimated
     win is below the permutation's copy cost, never fire more often
     than `compact_min_interval` rounds — the same anti-thrash shape as
     hv's `min_resident_rounds`);
  4. fires ONE jitted gather-permutation over every lane-trailing
     BatchState plane (the same column-move seam the recycler, hv
     swap-in, and mesh migration use): live lanes sort to a contiguous
     prefix ordered by (divergence-score bias, pc) — high-divergence
     neighbourhoods group first, per the analyzer's r12 block scores —
     retired lanes sink to the tail;
  5. (fixed-cohort runs only) NARROWS the dispatch width to the
     smallest power of two covering the live prefix: subsequent chunk
     launches run a width-variant step over the prefix slice and write
     it back, so dead lanes stop costing dispatch work entirely.  This
     is where the raw-speed win lands on every backend; the pure
     permutation additionally restores convergent neighbourhoods for
     the fused heads and the kernel tier.

The permutation is tracked as `src` (physical position -> original lane
index, a bijection by construction): harvest paths gather results back
into original lane order through `restore_order()`, checkpoints journal
it as a `lane_src` array so crash/resume keeps the mapping, and the
serving layer (serve/server.py) instead remaps its lane->request
binding and hv virtual-lane tables through the permutation — harvest,
recycling, swap, checkpoints, and the exactly-once stdout cursors all
follow their lane.

Scoping (same caveat as recycling and hv): results are bit-identical
with compaction on/off for lane-placement-independent guests — tier-0
`random_get` keys its stream on the physical lane index, so a
random-drawing guest's output depends on placement, as at any other
lane position.  The shared stdout fd is drained in PHYSICAL lane
order, so the CROSS-lane interleaving of a multi-writer cohort's
stream follows the permutation too (each lane's own bytes stay
in-order and exactly-once; a recycled serving mix already interleaves
by placement).  `Configure.batch.compact` off (the default) compiles
and executes the exact seed path: nothing is pulled, permuted, or
rebuilt.  On a shard-drive mesh the permutation is block-diagonal per
device shard (no cross-device moves) and narrowing is disabled (the
global width is pinned by the sharding).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from wasmedge_tpu.batch.image import TRAP_HOSTCALL


class CompactDecision(NamedTuple):
    """Deterministic boundary decision (pure function of the mirrors
    and the knobs — pinned by tests/test_compact.py)."""

    fire: bool
    reason: str            # "fire" | "idle" | "interval" | "cost"
    nlive: int
    breaks: int            # adjacent key mismatches in current order
    ideal_breaks: int      # minimum achievable after a sort
    unique_pcs: int        # distinct live pcs
    largest_group: float   # largest convergent group / live lanes
    narrow_width: int      # dispatch width after this boundary


def next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def live_mask(trap: np.ndarray) -> np.ndarray:
    """Lanes that can still execute: running, or parked at a hostcall
    stub (TRAP_HOSTCALL lanes re-arm and must stay in the live
    prefix).  Finished/trapped lanes never resume in a cohort run."""
    trap = np.asarray(trap)
    return (trap == 0) | (trap == TRAP_HOSTCALL)


def divergence_key(img) -> Optional[np.ndarray]:
    """Per-pc divergence score from the analyzer's r12 per-block
    scores (block pc ranges -> block_divergence), used to bias the
    sort so high-divergence neighbourhoods group first.  None when no
    analysis is attached (concatenated multi-tenant images, analyzer
    failure) — the sort degrades to a pure (pc) key.  Never raises:
    compaction is a performance pass, not a correctness gate."""
    try:
        analysis = getattr(img, "analysis", None)
        if analysis is None:
            return None
        out = np.zeros(int(img.code_len), np.int32)
        for f in analysis.funcs:
            for bi, b in enumerate(f.cfg.blocks):
                lo = max(int(b.start), 0)
                hi = min(int(b.end), out.size - 1)
                if hi >= lo:
                    out[lo:hi + 1] = int(f.block_divergence[bi])
        return out
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return None


def function_key(img) -> Optional[np.ndarray]:
    """Per-pc ENGINE-GLOBAL function ordinal from the image's entry-pc
    plane, the r20 coarse grouping key: on a multi-tenant concatenated
    image f_entry is already rebased per tenant, so sorting by it first
    regroups serving mixes per function (and per tenant) before the
    finer (divergence, pc) keys order lanes inside one body.  Needs no
    analysis — unlike divergence_key it works on concatenated images.
    Never raises: compaction is a performance pass, not a correctness
    gate."""
    try:
        entries = np.asarray(getattr(img, "f_entry"), np.int64)
        entries = np.sort(entries[entries >= 0])
        if entries.size == 0:
            return None
        pcs = np.arange(int(img.code_len), dtype=np.int64)
        return np.searchsorted(entries, pcs, side="right").astype(
            np.int64) - 1
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        return None


def estimate_breaks(pc: np.ndarray, live: np.ndarray,
                    shard_slices: Optional[List[slice]] = None):
    """(breaks, ideal_breaks, unique_pcs, largest_group_fraction) of
    the current lane order: `breaks` counts adjacent lane pairs whose
    (live, pc) keys differ (dead lanes are one shared key), `ideal`
    is the minimum after a perfect sort.  With `shard_slices` both are
    computed per shard block and summed — a shard-blocked permutation
    can neither fix cross-shard breaks nor merge per-shard groups, so
    a globally-computed ideal would leave win > 0 forever on an
    already-shard-sorted mesh and the policy would fire no-op
    permutations every quantum.  unique/largest stay global (they are
    convergence METRICS, not the cost model)."""
    key = np.where(live, np.asarray(pc, np.int64), np.int64(-1))
    nlive = int(live.sum())
    breaks = ideal = 0
    for sl in (shard_slices or [slice(0, key.size)]):
        ks, ls = key[sl], live[sl]
        breaks += int(np.count_nonzero(ks[1:] != ks[:-1]))
        ns = int(ls.sum())
        if ns:
            ideal += int(np.unique(ks[ls]).size) - 1 \
                + (1 if ns < ls.size else 0)
    if nlive == 0:
        return breaks, 0, 0, 1.0
    _, counts = np.unique(key[live], return_counts=True)
    return breaks, ideal, int(counts.size), float(counts.max()) / nlive


def build_permutation(pc: np.ndarray, trap: np.ndarray,
                      dscore: Optional[np.ndarray] = None,
                      shard_slices: Optional[List[slice]] = None,
                      fnkey: Optional[np.ndarray] = None
                      ) -> np.ndarray:
    """The boundary permutation as `perm` (destination -> source lane):
    new_plane[..., d] = old_plane[..., perm[d]].  Within each shard
    slice (the whole array when None — no cross-device moves on a
    mesh), live lanes sort to the front keyed by (function ordinal,
    descending divergence score, pc, original position) and dead lanes
    keep their relative order at the tail.  A bijection by
    construction; stable, so an already-grouped population is a
    no-op.  `fnkey` (function_key) is the r20 coarse group: lanes
    executing the same function become contiguous before the finer
    keys order them within it."""
    pc = np.asarray(pc, np.int64)
    live = live_mask(trap)
    n = pc.size
    if dscore is not None and dscore.size:
        score = np.asarray(dscore, np.int64)[np.clip(pc, 0,
                                                     dscore.size - 1)]
    else:
        score = np.zeros(n, np.int64)
    if fnkey is not None and fnkey.size:
        fk = np.asarray(fnkey, np.int64)[np.clip(pc, 0,
                                                 fnkey.size - 1)]
    else:
        fk = np.zeros(n, np.int64)
    dead = (~live).astype(np.int64)
    pckey = np.where(live, pc, np.int64(0))
    skey = np.where(live, -score, np.int64(0))
    fkey = np.where(live, fk, np.int64(0))
    pos = np.arange(n, dtype=np.int64)
    perm = np.empty(n, np.int64)
    for sl in (shard_slices or [slice(0, n)]):
        # np.lexsort: LAST key is primary ->
        # (dead, fn, -score, pc, pos)
        order = np.lexsort((pos[sl], pckey[sl], skey[sl], fkey[sl],
                            dead[sl]))
        perm[sl] = order + sl.start
    return perm


def compact_decision(pc: np.ndarray, trap: np.ndarray, width: int,
                     steps_per_launch: int, rounds_since_fire: int,
                     knobs, can_narrow: bool,
                     shard_slices: Optional[List[slice]] = None
                     ) -> CompactDecision:
    """The deterministic when-to-fire policy (cost model + trigger +
    anti-thrash quantum).  `width` is the current dispatch width; the
    copy cost of one permutation is modelled as `compact_cost_factor`
    lane-steps per lane, the win as one saved break per dispatched
    step (sorting) plus the narrowed slice (packing).  `shard_slices`
    bounds the win to what a shard-blocked permutation can achieve."""
    interval = max(int(getattr(knobs, "compact_min_interval", 2)), 1)
    trigger = float(getattr(knobs, "compact_trigger", 0.05))
    cost_factor = float(getattr(knobs, "compact_cost_factor", 4.0))
    floor = max(int(getattr(knobs, "compact_width_floor", 64)), 1)
    lanes = int(np.asarray(trap).size)
    live = live_mask(trap)
    breaks, ideal, unique, largest = estimate_breaks(pc, live,
                                                     shard_slices)
    nlive = int(live.sum())
    narrow_w = int(width)
    if can_narrow and nlive > 0:
        target = min(max(next_pow2(nlive), floor), int(width))
        if target < width:
            narrow_w = target
    if nlive == 0:
        return CompactDecision(False, "idle", 0, breaks, ideal, unique,
                               largest, int(width))
    if rounds_since_fire < interval:
        return CompactDecision(False, "interval", nlive, breaks, ideal,
                               unique, largest, int(width))
    win = max(breaks - ideal, 0)
    sort_pays = (win >= 1 and win >= trigger * nlive
                 and win * max(int(steps_per_launch), 1)
                 >= cost_factor * lanes)
    if not sort_pays and narrow_w >= width:
        return CompactDecision(False, "cost", nlive, breaks, ideal,
                               unique, largest, int(width))
    return CompactDecision(True, "fire", nlive, breaks, ideal, unique,
                           largest, narrow_w)


def _lane_plane_names(state, lanes: int):
    from wasmedge_tpu.hv.swapstore import lane_plane_names

    return lane_plane_names(state, lanes)


def make_permute(lane_names):
    """Build the jitted gather-permutation over the lane-trailing
    planes (ONE pass, donation discipline shared with the recycler's
    install and hv's column restore).  `lane_names` is the frozen set
    of plane names carrying a lane axis — laneless planes (op_hist,
    fu_ctr) and None planes pass through untouched.

    jit-purity lint target (tools/lint_jit_purity.py): everything
    nested here runs under trace.
    """
    import jax
    import jax.numpy as jnp

    names = tuple(lane_names)

    def permute(state, perm):
        updates = {}
        for name in names:
            plane = getattr(state, name)
            updates[name] = jnp.take(plane, perm, axis=-1)
        return state._replace(**updates)

    donate = (0,)
    if jax.default_backend() == "cpu" and \
            getattr(jax.config, "jax_compilation_cache_dir", None):
        donate = ()
    return jax.jit(permute, donate_argnums=donate)


class LaneCompactor:
    """Per-run (engine cohort) or per-server lane compaction state:
    the composed permutation (`src`), the current dispatch width, the
    jitted permute pass, and the width-variant chunk cache.

    The cohort drivers (BatchEngine.run, ShardDrive.run, the uniform
    engine's divergence handoff, the batch supervisor's SIMT tier) arm
    one on the engine (`engine.compactor`) and `run_from_state` calls
    `boundary()` between rounds; the serving layer instead holds its
    own instance (narrowing off) and remaps its binding tables through
    each fired permutation (serve/server.py _compact_round)."""

    def __init__(self, engine, narrow: Optional[bool] = None):
        self.cfg = engine.cfg
        self.lanes = int(engine.lanes)
        self.mesh = getattr(engine, "mesh", None)
        allow = bool(getattr(self.cfg, "compact_narrow", True))
        if narrow is None:
            narrow = allow and self.mesh is None
        self.narrow = bool(narrow) and allow and self.mesh is None
        self.src = np.arange(self.lanes, dtype=np.int64)
        self.width = self.lanes
        self.rounds = 0
        self.last_fire = -(1 << 30)
        self._dscore = None
        self._dscore_ready = False
        self._fnkey = None
        self._fnkey_ready = False
        self._permute = None
        self._chunks = {}
        self._shards = self._shard_slices()
        self.stats = {"fires": 0, "noop_fires": 0, "rounds": 0,
                      "skipped_interval": 0, "skipped_cost": 0,
                      "moved_lanes": 0, "dispatch_slots": 0,
                      "min_width": self.lanes}

    def _shard_slices(self) -> Optional[List[slice]]:
        if self.mesh is None:
            return None
        from wasmedge_tpu.parallel.shard_drive import shard_slices

        n = int(np.prod(np.asarray(self.mesh.devices).shape))
        return shard_slices(self.lanes, n)

    def dscore(self, img) -> Optional[np.ndarray]:
        if not self._dscore_ready:
            self._dscore = divergence_key(img)
            self._dscore_ready = True
        return self._dscore

    def fnkey(self, img) -> Optional[np.ndarray]:
        if not self._fnkey_ready:
            self._fnkey = function_key(img)
            self._fnkey_ready = True
        return self._fnkey

    # -- permutation bookkeeping -------------------------------------------
    @property
    def identity(self) -> bool:
        return bool((self.src == np.arange(self.lanes)).all())

    def restore_order(self) -> Optional[np.ndarray]:
        """For each ORIGINAL lane index, the physical position holding
        it (argsort of src) — harvest paths gather result mirrors
        through it.  None when no permutation ever fired."""
        if self.identity:
            return None
        return np.argsort(self.src, kind="stable")

    def tick(self) -> bool:
        """One boundary round: False while the anti-thrash quantum
        holds (nothing is pulled or computed on skipped rounds)."""
        self.rounds += 1
        self.stats["rounds"] += 1
        interval = max(int(getattr(self.cfg, "compact_min_interval",
                                   2)), 1)
        if self.rounds - self.last_fire < interval:
            self.stats["skipped_interval"] += 1
            return False
        return True

    def decide(self, pc, trap) -> CompactDecision:
        d = compact_decision(
            pc, trap, self.width, int(self.cfg.steps_per_launch),
            self.rounds - self.last_fire, self.cfg, self.narrow,
            self._shards)
        if not d.fire and d.reason == "cost":
            self.stats["skipped_cost"] += 1
        return d

    def plan_boundary(self, engine, state):
        """tick -> decide -> build, shared by the cohort boundary()
        and the server's _compact_round so the two drivers can never
        drift: returns (decision, perm) when a non-identity
        permutation should be applied, else None.  An identity-perm
        fire still resets the quantum and applies narrowing (via
        fired()) but is NOT counted as a compaction — no lanes
        moved."""
        if not self.tick():
            return None
        trap = np.asarray(state.trap)
        pc = np.asarray(state.pc)
        d = self.decide(pc, trap)
        if not d.fire:
            return None
        perm = build_permutation(pc, trap, self.dscore(engine.img),
                                 self._shards,
                                 fnkey=self.fnkey(engine.img))
        if (perm == np.arange(perm.size)).all():
            self.fired(d, moved=False)
            return None
        return d, perm

    def fired(self, d: CompactDecision, moved: bool = True):
        """Apply a fire's side effects: narrowing + the anti-thrash
        quantum always; the fire COUNT only when lanes actually moved
        (`moved=False` = identity permutation, e.g. a narrowing-only
        boundary on already-sorted lanes) so stats['fires'] and
        wasmedge_compactions_total agree on what a compaction is."""
        if d.narrow_width < self.width:
            self.width = d.narrow_width
            self.stats["min_width"] = min(self.stats["min_width"],
                                          self.width)
        self.last_fire = self.rounds
        self.stats["fires" if moved else "noop_fires"] += 1

    def permute_state(self, engine, state, perm: np.ndarray):
        """Apply one boundary permutation: the jitted gather over the
        lane planes, the host-side exactly-once stdout cursor, and the
        composed src mapping.  Returns the permuted state."""
        import jax.numpy as jnp

        if self._permute is None:
            self._permute = make_permute(
                _lane_plane_names(state, self.lanes))
        state = self._permute(state, jnp.asarray(perm))
        if self.mesh is not None:
            # the gather's output drops the named lane sharding (the
            # permutation is an arbitrary gather to GSPMD); the shard
            # chunk pins its in_shardings, so put the planes back on
            # the mesh before the next launch
            from wasmedge_tpu.parallel.mesh import shard_batch_state

            state = shard_batch_state(state, self.mesh)
        self.src = self.src[perm]
        cur = getattr(engine, "_stdout_cursor", None)
        if cur is not None and cur[0].size == self.lanes:
            cur[0][:] = cur[0][perm]
            cur[1][:] = cur[1][perm]
        self.stats["moved_lanes"] += int((perm
                                          != np.arange(perm.size)).sum())
        return state

    # -- the engine-path boundary hook -------------------------------------
    def boundary(self, engine, state):
        """Called by run_from_state between rounds (fixed-cohort
        drivers).  Decides, permutes, and narrows; emits the `compact`
        instant + latency observation on the engine's recorder.  The
        quantum gate (inside plan_boundary's tick) runs BEFORE any
        device read: an off-cadence round costs nothing beyond a
        counter check."""
        obs = engine.obs
        t0 = obs.now()
        plan = self.plan_boundary(engine, state)
        if plan is None:
            return state
        d, perm = plan
        state = self.permute_state(engine, state, perm)
        self.fired(d)
        obs.observe_compaction(obs.now() - t0)
        obs.instant("compact", cat="compact", track="compact",
                    live=d.nlive, width=self.width,
                    breaks_before=d.breaks, breaks_ideal=d.ideal_breaks,
                    unique_pcs=d.unique_pcs)
        return state

    def note_launch(self, steps: int):
        """Dispatch-slot accounting: one slot per (step, lane) of the
        current dispatch width — the denominator of the
        retired-per-dispatch figure the bench guards."""
        self.stats["dispatch_slots"] += int(steps) * self.width

    def chunk_fn(self, engine):
        """The chunk loop for the current dispatch width: the engine's
        own full-width jit when nothing narrowed, else a width-variant
        cached ON THE ENGINE (a compactor is per-run; the compiled
        variants must survive across runs or every run re-pays the
        trace)."""
        if self.width >= self.lanes:
            return engine._run_chunk
        cache = getattr(engine, "_narrow_chunks", None)
        if cache is None:
            cache = engine._narrow_chunks = {}
        fn = cache.get(self.width)
        if fn is None:
            fn = engine._build_narrow_chunk(self.width)
            cache[self.width] = fn
        return fn


def restore_mirrors(comp, stack_lo, stack_hi, trap, retired):
    """Gather a cohort harvest's result mirrors back to original lane
    order through the compactor's composed permutation (the ONE remap
    seam shared by BatchEngine.run, the uniform handoff harvest, and
    the multi-tenant harvest; the shard drive composes it with its
    pad-strip slice instead).  Identity / no compactor -> unchanged."""
    order = None if comp is None else comp.restore_order()
    if order is None:
        return stack_lo, stack_hi, trap, retired
    return (stack_lo[:, order], stack_hi[:, order],
            trap[order], retired[order])


def arm(engine) -> Optional[LaneCompactor]:
    """Fresh per-run compactor for a cohort driver (None when the knob
    is off).  The serving layer never arms the ENGINE's compactor — it
    owns its own instance and remaps its tables itself."""
    if getattr(engine.cfg, "compact", False) \
            and not getattr(engine, "_compact_external", False):
        engine.compactor = LaneCompactor(engine)
    else:
        engine.compactor = None
    return engine.compactor


def restore_lane_src(engine, src: Optional[np.ndarray]):
    """Checkpoint-restore half of the src tracking: `src` is the
    journaled lane_src array (None when the snapshot predates any
    compaction).  Rolls the engine's compactor back to the snapshot's
    mapping — a restore to an OLDER boundary must also roll back the
    permutation — and refuses a permuted snapshot when compaction is
    unavailable (results would silently come back lane-shuffled)."""
    lanes = int(engine.lanes)
    identity = src is None or bool(
        (np.asarray(src) == np.arange(lanes)).all())
    managed = getattr(engine, "_compact_external", False)
    comp = getattr(engine, "compactor", None)
    if identity:
        if comp is not None:
            comp.src = np.arange(lanes, dtype=np.int64)
            comp.width = lanes
        return
    if managed or not getattr(engine.cfg, "compact", False):
        raise ValueError(
            "checkpoint refused: snapshot carries a lane compaction "
            "permutation (lane_src) but this engine cannot restore it "
            + ("(compaction is externally managed here — supervised "
               "rungs and serving engines run uncompacted)" if managed
               else "(Configure.batch.compact is off)"))
    if comp is None:
        comp = engine.compactor = LaneCompactor(engine)
    comp.src = np.asarray(src, np.int64).copy()
    comp.width = lanes   # restart full-width; narrowing re-fires
