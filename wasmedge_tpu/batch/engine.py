"""BatchEngine: the SIMT lockstep interpreter (pure JAX/XLA version).

One `step()` advances every lane by one instruction: fetch each lane's
(class, sub, operands) from the device image tables, run every class
handler vectorized under lane masks, and merge the candidate state updates
with `where`-selects. Divergent control flow needs no special casing — a
lane's pc simply differs; traps park a lane (trap != 0) without unwinding,
the host harvests results when all lanes halt.

This is the moral replacement of the reference's dispatch loop
(/root/reference/lib/executor/engine/engine.cpp:68-1641): the `switch`
becomes masked class handlers, `StackManager` becomes [depth, lanes] int32
planes, MemoryInstance becomes a [words, lanes] plane with software bounds
checks, Statistics/StopToken become per-lane retired/fuel counters
(SURVEY.md §2.10, §5.1-5.3).

State layout is depth-major ([depth, lanes]) so converged lanes hit
dynamic-slice-friendly rows and the lane dim vectorizes on the VPU; the
pallas kernel (batch/pallas_engine.py) consumes the same layout.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional

import numpy as np

from wasmedge_tpu.common.configure import BatchConfigure
from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.batch.image import (
    ALU1_SUB,
    ALU2_F32_BASE,
    ALU2_I32_BASE,
    ALU2_I64_BASE,
    CLS_ALU1,
    CLS_ALU2,
    CLS_BR,
    CLS_BR_TABLE,
    CLS_BRNZ,
    CLS_BRZ,
    CLS_CALL,
    CLS_CALL_INDIRECT,
    CLS_CONST,
    CLS_DROP,
    CLS_GLOBAL_GET,
    ALU2_F64_BASE,
    CLS_GLOBAL_SET,
    CLS_HOSTCALL,
    CLS_LOAD,
    CLS_LOCAL_GET,
    CLS_LOCAL_SET,
    CLS_LOCAL_TEE,
    CLS_MEMCOPY,
    CLS_MEMFILL,
    CLS_MEMGROW,
    CLS_V1,
    CLS_V2,
    CLS_VBITSEL,
    CLS_VCONST,
    CLS_VEXTRACT,
    CLS_VLOAD,
    CLS_VREPLACE,
    CLS_VSHIFT,
    CLS_VSHUFFLE,
    CLS_VSPLAT,
    CLS_VSTORE,
    CLS_VTEST,
    CLS_MEMSIZE,
    CLS_RETURN,
    CLS_SELECT,
    CLS_STORE,
    CLS_TRAP,
    CLS_TABLE_GET,
    CLS_TABLE_SET,
    CLS_TABLE_SIZE,
    CLS_TABLE_GROW,
    CLS_TABLE_FILL,
    CLS_TABLE_COPY,
    CLS_TABLE_INIT,
    CLS_ELEM_DROP,
    CLS_MEMINIT,
    CLS_DATA_DROP,
    CLS_RETCALL,
    CLS_RETCALL_INDIRECT,
    CLS_REFFUNC,
    NUM_CLASSES,
    TRAP_DONE,
    _F64_BIN,
    TRAP_HOSTCALL,
    DeviceImage,
    _F32_BIN,
    _I32_BIN,
)

_PAGE_WORDS = 65536 // 4


class BatchState(NamedTuple):
    pc: object
    sp: object
    fp: object
    opbase: object
    call_depth: object
    trap: object
    retired: object
    fuel: object
    mem_pages: object
    stack_lo: object
    stack_hi: object
    fr_ret_pc: object
    fr_fp: object
    fr_opbase: object
    glob_lo: object
    glob_hi: object
    mem: object
    # v128 extension planes (bits 64..127 of each cell) — present only
    # for modules whose image uses SIMD (img.has_simd); None otherwise
    stack_e2: object = None
    stack_e3: object = None
    # r05 optional planes (same None-when-unused discipline):
    tab: object = None     # [table_cap, lanes] per-lane mutable table
    tsize: object = None   # [lanes] per-lane table size (table.grow)
    edrop: object = None   # [n_elem_segs, lanes] dropped flags
    ddrop: object = None   # [n_data_segs, lanes] dropped flags
    # r06 tier-0 hostcall planes (three-tier pipeline, batch/hostcall.py).
    # The read-only per-launch time base is NOT a state field: it rides
    # the jitted chunk as a separate non-donated argument (an identity-
    # passthrough donated leaf miscompiles under the persistent
    # compilation cache on the CPU backend).
    t0_time: object = None  # reserved (always None; see note above)
    t0_ctr: object = None   # [4, lanes] int32: clock seq / rng seq /
    #                         fd_write count / yield+exit count
    so_buf: object = None   # [SW, lanes] int32 stdout record buffer
    so_off: object = None   # [lanes] int32 next free word in so_buf
    # r08 observability plane (Configure.obs.opcode_histogram): per-pc
    # retired count, scatter-incremented once per step across lanes and
    # folded into per-opcode counts (img.op_id -> Statistics cost_table
    # domain) on sync.  None unless the knob is on (no per-step cost).
    # Under superinstruction fusion every CONSTITUENT op of a fused run
    # increments its own pc (histogram == retired, batch/fuse.py).
    op_hist: object = None
    # r17 fusion counters [3] int32: fused dispatches / instructions
    # retired through fused cells / total retired.  Laneless like
    # op_hist; allocated only when obs is enabled AND the image
    # compiled fused cells (obs_state_planes), folded into the flight
    # recorder on sync.
    fu_ctr: object = None
    # r20 tier-up counters [3] int32: compiled function-call dispatches
    # / instructions retired through compiled bodies / total retired
    # (liveness row: never an identity passthrough in the donated carry
    # when a promoted-plane state resumes on a tierup-off build).
    tu_ctr: object = None


@dataclasses.dataclass
class BatchResult:
    results: List[np.ndarray]  # one [lanes] int64 raw-cell array per result
    # trap[k]: TRAP_DONE (-1) = finished, >0 = ErrCode trap, 0 = lane was
    # STILL RUNNING when max_steps ran out — its results slot is garbage;
    # check `completed` before consuming results.
    trap: np.ndarray
    retired: np.ndarray  # [lanes] instructions retired
    steps: int  # lockstep iterations executed

    @property
    def completed(self) -> np.ndarray:
        """Mask of lanes that finished normally (results valid)."""
        return self.trap == TRAP_DONE


def r05_plane_names(img: DeviceImage) -> tuple:
    """Names of the r05 planes this image requires (no allocation —
    checkpoint's missing-plane guard needs only the keys)."""
    out = []
    if getattr(img, "has_table_mut", False):
        out += ["tab", "tsize"]
    if bool(np.isin(img.cls, (CLS_TABLE_INIT, CLS_ELEM_DROP)).any()):
        out.append("edrop")
    if bool(np.isin(img.cls, (CLS_MEMINIT, CLS_DATA_DROP)).any()):
        out.append("ddrop")
    return tuple(out)


def r05_state_planes(img: DeviceImage, lanes: int) -> dict:
    """Initial tab/tsize/edrop/ddrop planes for the r05 table/segment
    families — shared by every BatchState constructor (engine, uniform
    handoff, multitenant, scheduler).  Returns {} (BatchState None
    defaults) when the image uses none of them."""
    import jax.numpy as jnp

    out = {}
    if getattr(img, "has_table_mut", False):
        T = max(int(img.table_cap or img.table0.shape[0]), 1)
        tb = np.zeros((T, lanes), np.int32)
        n0 = min(img.table0.shape[0], T)
        tb[:n0] = img.table0[:n0, None]
        out["tab"] = jnp.asarray(tb)
        out["tsize"] = jnp.full((lanes,), img.table_size_init, jnp.int32)
    cls = img.cls
    if bool(np.isin(cls, (CLS_TABLE_INIT, CLS_ELEM_DROP)).any()):
        out["edrop"] = jnp.zeros((img.elem_len.shape[0], lanes), jnp.int32)
    if bool(np.isin(cls, (CLS_MEMINIT, CLS_DATA_DROP)).any()):
        out["ddrop"] = jnp.zeros((img.data_len.shape[0], lanes), jnp.int32)
    return out


def obs_state_planes(conf, img: DeviceImage, mesh=None) -> dict:
    """Initial device-side observability planes: the per-pc opcode
    histogram (Configure.obs.opcode_histogram) and the fusion
    dispatch/retired counters (allocated whenever obs is enabled and
    the image compiled fused cells).  {} when obs is off — the
    BatchState defaults (None) then keep the step function free of the
    per-step scatters entirely.  Mesh runs skip both (no lane axis to
    shard)."""
    obs_conf = getattr(conf, "obs", None)
    if mesh is not None or obs_conf is None or not obs_conf.enabled:
        return {}
    import jax.numpy as jnp

    out = {}
    if obs_conf.opcode_histogram:
        out["op_hist"] = jnp.zeros((img.cls.shape[0],), jnp.int32)
    from wasmedge_tpu.batch.fuse import fusion_active

    if fusion_active(img, conf.batch):
        out["fu_ctr"] = jnp.zeros((3,), jnp.int32)
    from wasmedge_tpu.batch.tierup import tierup_active

    if tierup_active(img, conf.batch):
        out["tu_ctr"] = jnp.zeros((3,), jnp.int32)
    return out


# ---------------------------------------------------------------------------
# tier-0 hostcalls: pure WASI calls serviced inside the kernel
# ---------------------------------------------------------------------------
T0_CTR_ROWS = 4  # clock seq / rng seq / fd_write count / yield+exit count


def new_hostcall_stats() -> dict:
    """Per-run hostcall pipeline counters (reset by BatchEngine.run):
    tier0_* are in-kernel retirements (zero device<->host round trips),
    tier1_calls is lanes drained through the outcall channel, and
    serve_rounds counts park->drain->re-arm cycles (each one is at
    least one device<->host round trip)."""
    return {"tier0_clock": 0, "tier0_random": 0, "tier0_fd_write": 0,
            "tier0_sys": 0, "tier0_calls": 0,
            "tier1_calls": 0, "tier1_vectorized": 0, "serve_rounds": 0,
            "stdout_flushes": 0, "stdout_bytes": 0}


def t0_effective_kinds(img: DeviceImage, cfg) -> Optional[np.ndarray]:
    """Per-pc tier-0 kinds this image+config will service in-kernel, or
    None when tier 0 is entirely off (no recognized stubs, knob off, or
    a concatenated multi-tenant image that carries no t0kind plane)."""
    from wasmedge_tpu.batch.image import T0_FD_WRITE, T0_NEEDS_MEMORY

    kinds = getattr(img, "t0kind", None)
    if kinds is None or not getattr(cfg, "tier0_hostcalls", True):
        return None
    kinds = np.asarray(kinds, np.int32).copy()
    if not getattr(img, "t0_fdwrite_safe", False):
        kinds[kinds == T0_FD_WRITE] = 0
    if not img.has_memory:
        # these kinds all write through guest memory
        kinds[np.isin(kinds, T0_NEEDS_MEMORY)] = 0
    if not (kinds != 0).any():
        return None
    return kinds


# Shared tier-0 kernel logic lives in batch/tier0.py (one source for the
# SIMT and uniform engines' bit-identical streams); re-exported here for
# compatibility with existing importers.
from wasmedge_tpu.batch.tier0 import (  # noqa: F401
    t0_clock_value,
    t0_masked_store,
    t0_prng32,
    t0_random_fill,
    t0_rng_seq_hash,
    t0_shifted_src_word,
    t0_statics,
    t0_word_mix,
)


def check_batch_entry(inst, func_name: str) -> int:
    """Resolve an exported batch entry on `inst` with the ONE entry
    guard every batch front door shares (BatchEngine.run/export_func_idx
    and the multi-module engine's qualified-name lookup): the export
    must be a function and its signature must not carry v128 —
    install()/harvest move only the 64-bit lo/hi cell halves, so a
    v128 entry would silently compute garbage instead of failing
    loudly.  Returns the instance-local function index."""
    ex = inst.exports.get(func_name)
    if ex is None or ex[0] != 0:
        raise KeyError(f"no exported function {func_name}")
    from wasmedge_tpu.common.types import ValType

    ft = inst.funcs[ex[1]].functype
    if ValType.V128 in tuple(ft.params) + tuple(ft.results):
        raise ValueError(
            "batch entry functions cannot take or return v128 "
            f"({func_name})")
    return ex[1]


def pack_lane_args(args_lanes, lanes: int, depth: int):
    """Entry arguments -> the (stack_lo, stack_hi) int32 planes: one
    int64 cell per (arg, lane), scalars broadcast, shapes validated.
    Shared by every lane-uniform state constructor (BatchEngine and the
    multi-module engine, batch/multitenant.py)."""
    stack_lo = np.zeros((depth, lanes), np.int32)
    stack_hi = np.zeros((depth, lanes), np.int32)
    for i, arg in enumerate(args_lanes):
        arr = np.asarray(arg, dtype=np.int64)
        if arr.ndim == 0:
            arr = np.full(lanes, arr, np.int64)
        if arr.shape != (lanes,):
            raise ValueError(
                f"arg {i}: expected shape ({lanes},) (one value per "
                f"lane) or a scalar, got {arr.shape}")
        stack_lo[i] = (arr & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
        stack_hi[i] = ((arr >> 32) & 0xFFFFFFFF).astype(np.uint32) \
            .view(np.int32)
    return stack_lo, stack_hi


def t0_time_planes() -> np.ndarray:
    """Per-relaunch time base: (realtime, monotonic) ns as int32 (lo, hi).

    In-kernel clock_time_get returns base + per-lane call seq, so values
    are strictly increasing per lane even within one launch window."""
    import time

    out = np.zeros((2, 2), np.int32)
    for r, ns in enumerate((time.time_ns(), time.monotonic_ns())):
        out[r, 0] = np.int32(np.uint32(ns & 0xFFFFFFFF))
        out[r, 1] = np.int32(np.uint32((ns >> 32) & 0xFFFFFFFF))
    return out


def t0_state_planes(img: DeviceImage, cfg, lanes: int,
                    kinds: Optional[np.ndarray]) -> dict:
    """Initial tier-0 planes for a BatchState; {} when tier 0 is off.
    `kinds` is the owning engine's gated kind plane (engine._t0kinds).
    Shared by every BatchState constructor (engine, uniform/pallas
    handoffs, scheduler residue)."""
    import jax.numpy as jnp

    from wasmedge_tpu.batch.image import T0_FD_WRITE

    if kinds is None:
        return {}
    # NOTE t0_time is deliberately NOT part of the state: it is a
    # read-only per-launch input threaded as a separate (non-donated)
    # argument into the jitted chunk — an identity-passthrough donated
    # leaf miscompiles under the persistent compilation cache on jax's
    # CPU backend (deserialized executables lose the input/output alias)
    out = {
        "t0_ctr": jnp.zeros((T0_CTR_ROWS, lanes), jnp.int32),
    }
    if (kinds == T0_FD_WRITE).any():
        sw = max(int(getattr(cfg, "stdout_buffer_words", 2048)), 16)
        out["so_buf"] = jnp.zeros((sw, lanes), jnp.int32)
        out["so_off"] = jnp.zeros((lanes,), jnp.int32)
    return out


def _make_step(img: DeviceImage, cfg: BatchConfigure, lanes: int,
               t0kinds: Optional[np.ndarray] = None):
    """Build the jittable single-step function closed over image constants."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from wasmedge_tpu.batch import laneops as lo_ops

    I32 = jnp.int32
    D = cfg.value_stack_depth
    CD = cfg.call_stack_depth
    lane_iota = jnp.arange(lanes, dtype=I32)

    cls_t = jnp.asarray(img.cls)
    sub_t = jnp.asarray(img.sub)
    a_t = jnp.asarray(img.a)
    b_t = jnp.asarray(img.b)
    c_t = jnp.asarray(img.c)
    ilo_t = jnp.asarray(img.imm_lo)
    ihi_t = jnp.asarray(img.imm_hi)
    brt_t = jnp.asarray(img.br_table)  # [n, 3]
    f_entry = jnp.asarray(img.f_entry)
    f_nparams = jnp.asarray(img.f_nparams)
    f_nlocals = jnp.asarray(img.f_nlocals)
    f_frame_top = jnp.asarray(img.f_frame_top)
    f_type = jnp.asarray(img.f_type)
    table0 = jnp.asarray(img.table0)
    fuel_enabled = cfg.fuel_per_launch is not None
    # per-opcode gas weights: gather the Statistics cost table through
    # the image's original-opcode plane (flat 1/instr when no table —
    # the reference's CostTab default, statistics.h:85-98)
    weighted_gas = (
        fuel_enabled and cfg.cost_table is not None
        and getattr(img, "op_id", None) is not None
        and any(c != 1 for c in cfg.cost_table))
    if weighted_gas:
        _ct = np.clip(np.asarray(cfg.cost_table, np.int64),
                      0, 1 << 30).astype(np.int32)
        _cost_np = _ct[np.clip(img.op_id, 0, len(_ct) - 1)]
        cost_t = jnp.asarray(_cost_np)
    else:
        _cost_np = None
    HAS_SIMD = bool(getattr(img, "has_simd", False))
    if HAS_SIMD:
        from wasmedge_tpu.batch import simdops as sops

        v128_t = jnp.asarray(img.v128)  # [n, 4]
        used_of = lambda kls: {int(sv) for sv, cv in zip(img.sub, img.cls)
                               if cv == kls}
        used_v2 = used_of(CLS_V2)
        used_v1 = used_of(CLS_V1)
        used_vtest = used_of(CLS_VTEST)
        used_vshift = used_of(CLS_VSHIFT)
        used_vsplat = used_of(CLS_VSPLAT)
        used_vextract = used_of(CLS_VEXTRACT)
        used_vreplace = used_of(CLS_VREPLACE)
        uses_vshuffle = bool((img.cls == CLS_VSHUFFLE).any())
        uses_vmem = bool(((img.cls == CLS_VLOAD)
                          | (img.cls == CLS_VSTORE)).any())

    # ALU sub ids
    S_I32 = {n: ALU2_I32_BASE + i for i, n in enumerate(_I32_BIN)}
    S_I64 = {n: ALU2_I64_BASE + i for i, n in enumerate(_I32_BIN)}
    S_F32 = {n: ALU2_F32_BASE + i for i, n in enumerate(_F32_BIN)}
    A1 = ALU1_SUB

    def gat(plane, idx):
        """plane [D?, lanes] gathered at per-lane row idx -> [lanes]."""
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        return jnp.take_along_axis(plane, idx[None, :], axis=0)[0]

    def scat(plane, idx, vals, mask):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        cur = jnp.take_along_axis(plane, idx[None, :], axis=0)[0]
        new = jnp.where(mask, vals, cur)
        return plane.at[idx, lane_iota].set(new)

    def sel_chain(sub, pairs, default):
        out = default
        for sid, val in pairs:
            out = jnp.where(sub == sid, val, out)
        return out

    b2i = lo_ops.b2i
    u_lt = lo_ops.u_lt
    # r05 families: static presence flags gate what gets traced
    HAS_T_ANY = bool(np.isin(img.cls, (
        CLS_TABLE_GET, CLS_TABLE_SET, CLS_TABLE_SIZE, CLS_TABLE_GROW,
        CLS_TABLE_FILL, CLS_TABLE_COPY, CLS_TABLE_INIT)).any())
    HAS_T_MUT = bool(img.has_table_mut)
    HAS_ESEG = bool(np.isin(img.cls, (CLS_TABLE_INIT, CLS_ELEM_DROP)).any())
    HAS_DSEG = bool(np.isin(img.cls, (CLS_MEMINIT, CLS_DATA_DROP)).any())
    HAS_TAIL = bool(np.isin(img.cls, (CLS_RETCALL,
                                      CLS_RETCALL_INDIRECT)).any())
    T_CAP = max(int(img.table_cap or img.table0.shape[0]), 1)
    MAX_NPAR = int(img.f_nparams.max()) if HAS_TAIL else 0
    if HAS_ESEG:
        elem_flat_t = jnp.asarray(img.elem_flat)
        elem_off_t = jnp.asarray(img.elem_off)
        elem_len_t = jnp.asarray(img.elem_len)
    if HAS_DSEG:
        data_words_t = jnp.asarray(img.data_words)
        data_off_t = jnp.asarray(img.data_off)
        data_len_t = jnp.asarray(img.data_len)
    used_alu2 = {int(sv) for sv, cv in zip(img.sub, img.cls)
                 if cv == CLS_ALU2}
    used_alu1 = {int(sv) for sv, cv in zip(img.sub, img.cls)
                 if cv == CLS_ALU1}
    _A2F = lo_ops.alu2_fns()
    _A1F = lo_ops.alu1_fns()
    _T1F = lo_ops.alu1_trap_fns()
    _HEAVY_ALU2 = {ALU2_F64_BASE + _F64_BIN.index("div")}
    from wasmedge_tpu.batch.image import ALU1_SUB as _A1S
    _HEAVY_ALU1 = {_A1S["f64.sqrt"]}

    # ---- tier-0 hostcall statics (three-tier pipeline) ----
    from wasmedge_tpu.batch.image import (
        T0_CLOCK_TIME_GET, T0_FD_WRITE, T0_PROC_EXIT, T0_RANDOM_GET,
        T0_SCHED_YIELD)

    t0k = t0kinds
    HAS_T0 = t0k is not None
    if HAS_T0:
        t0k_t = jnp.asarray(np.asarray(t0k, np.int32))
        USE_T0_CLOCK = bool((t0k == T0_CLOCK_TIME_GET).any())
        USE_T0_RANDOM = bool((t0k == T0_RANDOM_GET).any())
        USE_T0_YIELD = bool((t0k == T0_SCHED_YIELD).any())
        USE_T0_EXIT = bool((t0k == T0_PROC_EXIT).any())
        USE_T0_FDW = bool((t0k == T0_FD_WRITE).any())
        _t0s = t0_statics(cfg)
        RMAX_W = _t0s["RMAX_W"]
        WMAX_W = _t0s["WMAX_W"]
        RNG_SEED = jnp.asarray(_t0s["RNG_SEED"])
        _E_INVAL = _t0s["E_INVAL"]
        _E_FAULT = _t0s["E_FAULT"]

        def t0_rmw(plane, idx, m, v, ok):
            """Masked word RMW through this engine's gather/scatter —
            the primitive the shared tier-0 bodies are built on."""
            cur = gat(plane, idx)
            return scat(plane, idx, (cur & ~m) | (v & m), ok & (m != 0))

    # ---- superinstruction fusion statics (batch/fuse.py) ----
    # FUSE_ON is trace-time static: knob off (or nothing realized)
    # compiles the exact seed per-op step.  Memory-run patterns (r19,
    # absint-licensed load/store runs) compile through their own
    # handler; a pattern table with only one kind builds only that
    # handler.
    from wasmedge_tpu.batch.fuse import (
        fusion_active, make_fused_apply, make_memfuse_apply,
        pattern_has_mem)

    FUSE_ON = fusion_active(img, cfg)
    HAS_PURE_PAT = HAS_MEM_PAT = False
    if FUSE_ON:
        flen_t = jnp.asarray(img.fuse_len)
        MAX_F = int(np.asarray(img.fuse_len).max())
        _pats = img.fuse_patterns or ()
        _pat_mem = np.array([pattern_has_mem(p) for p in _pats], bool)
        HAS_PURE_PAT = bool((~_pat_mem).any())
        HAS_MEM_PAT = bool(_pat_mem.any())
        if HAS_PURE_PAT:
            fused_apply = make_fused_apply(img, lanes, HAS_SIMD)
        if HAS_MEM_PAT:
            from wasmedge_tpu.batch.fuse import memfuse_store_slots

            memfuse_apply = make_memfuse_apply(img, lanes, HAS_SIMD)
            N_MEM_SLOTS = memfuse_store_slots(img)
            _fpat_np = np.asarray(img.fuse_pat)
            _memhead = np.zeros(_fpat_np.shape[0], bool)
            _valid = _fpat_np >= 0
            _memhead[_valid] = _pat_mem[_fpat_np[_valid]]
            _memhead &= np.asarray(img.fuse_len) >= 2
            memhead_t = jnp.asarray(_memhead)
            # heads of patterns that STORE (the fused-store channel's
            # any-lane gate; load-only runs never touch the plane)
            _pat_st = np.array(
                [any(cl in (CLS_STORE, CLS_VSTORE) for cl, _ in p)
                 for p in _pats],
                bool)
            _sthead = np.zeros(_fpat_np.shape[0], bool)
            _sthead[_valid] = _pat_st[_fpat_np[_valid]]
            _sthead &= _memhead
            sthead_t = jnp.asarray(_sthead)

    # ---- whole-function tier-up statics (batch/tierup.py) ----
    # TIER_ON is trace-time static like FUSE_ON: knob off (or nothing
    # promoted) compiles the exact seed/fused step by construction.
    from wasmedge_tpu.batch.tierup import make_tierup_apply, tierup_active

    TIER_ON = tierup_active(img, cfg)
    if TIER_ON:
        tier_fn_t = jnp.asarray(img.tier_fn)
        if fuel_enabled:
            tier_fuel_t = jnp.asarray(img.tier_fuel_bound)
        tierup_apply = make_tierup_apply(img, lanes, HAS_SIMD, _cost_np)

    def step(st: BatchState, t0_time=None) -> BatchState:
        """One lockstep instruction (or one fused dispatch cell — a
        whole straight-line run of stack/ALU effects for lanes parked
        at a fused run head).  `t0_time` is the [2, 2] int32
        per-launch time base (read-only; threaded as a separate argument
        so the donated state never carries an identity-passthrough
        leaf — see t0_state_planes)."""
        alive = st.trap == 0
        pc = jnp.clip(st.pc, 0, img.code_len - 1)
        if TIER_ON:
            # lanes parked at a promoted function's ENTRY pc run the
            # compiled CFG body this step (one dispatch per call); they
            # leave both the per-op and fused paths.  The fuel pre-gate
            # mirrors the fused one: a lane without fuel for the
            # worst-case whole call steps per-op instead, so gas
            # exhaustion lands at the correct op bit-identically.
            is_comp = tier_fn_t[pc] >= 0
            if fuel_enabled:
                is_comp = is_comp & (st.fuel > tier_fuel_t[pc])
            is_comp = alive & is_comp
        else:
            is_comp = jnp.bool_(False) & alive
        if FUSE_ON:
            f_n = flen_t[pc]
            is_fused = alive & (f_n >= 2)
            if TIER_ON:
                is_fused = is_fused & ~is_comp
            if fuel_enabled:
                # a lane without the fuel to retire the WHOLE run steps
                # through the original per-op cells instead, so gas
                # exhaustion lands at the correct op with the correct
                # pre-op sp/pc — bit-exact with the unfused build
                if weighted_gas:
                    fuse_cost = jnp.zeros_like(f_n)
                    for j in range(MAX_F):
                        pcj = jnp.clip(pc + j, 0, img.code_len - 1)
                        fuse_cost = fuse_cost + jnp.where(
                            j < f_n, cost_t[pcj], 0)
                else:
                    fuse_cost = f_n
                is_fused = is_fused & (st.fuel - fuse_cost > 0)
            # the per-op path must not also fire for fused lanes: the
            # head pc still carries its ORIGINAL first-op cell
            active = alive & ~is_fused
            if HAS_MEM_PAT:
                is_fused_mem = is_fused & memhead_t[pc]
                is_fused_pure = is_fused & ~memhead_t[pc]
            else:
                is_fused_mem = jnp.bool_(False) & alive
                is_fused_pure = is_fused
        else:
            is_fused = jnp.bool_(False) & alive
            is_fused_mem = is_fused_pure = is_fused
            active = alive
        if TIER_ON:
            active = active & ~is_comp
        cls = cls_t[pc]
        sub = sub_t[pc]
        a = a_t[pc]
        b = b_t[pc]
        c = c_t[pc]
        ilo = ilo_t[pc]
        ihi = ihi_t[pc]
        sp, fp, opbase = st.sp, st.fp, st.opbase

        # ---- operand prefetch (top 3 cells + addressed local/global) ----
        v0_lo = gat(st.stack_lo, sp - 1)
        v0_hi = gat(st.stack_hi, sp - 1)
        v1_lo = gat(st.stack_lo, sp - 2)
        v1_hi = gat(st.stack_hi, sp - 2)
        v2_lo = gat(st.stack_lo, sp - 3)
        v2_hi = gat(st.stack_hi, sp - 3)
        loc_lo = gat(st.stack_lo, fp + a)
        loc_hi = gat(st.stack_hi, fp + a)
        zl = jnp.zeros_like(v0_lo)
        if HAS_SIMD:
            v0_e2 = gat(st.stack_e2, sp - 1)
            v0_e3 = gat(st.stack_e3, sp - 1)
            v1_e2 = gat(st.stack_e2, sp - 2)
            v1_e3 = gat(st.stack_e3, sp - 2)
            v2_e2 = gat(st.stack_e2, sp - 3)
            v2_e3 = gat(st.stack_e3, sp - 3)
            loc_e2 = gat(st.stack_e2, fp + a)
            loc_e3 = gat(st.stack_e3, fp + a)
        else:
            v0_e2 = v0_e3 = v1_e2 = v1_e3 = v2_e2 = v2_e3 = zl
            loc_e2 = loc_e3 = zl
        ng = st.glob_lo.shape[0]
        gidx = jnp.clip(a, 0, ng - 1)
        g_lo = jnp.take_along_axis(st.glob_lo, gidx[None, :], axis=0)[0]
        g_hi = jnp.take_along_axis(st.glob_hi, gidx[None, :], axis=0)[0]

        is_cls = [cls == k for k in range(NUM_CLASSES)]
        trap = st.trap

        # =================== ALU2 ===================
        x_lo, x_hi = v1_lo, v1_hi  # first operand
        y_lo, y_hi = v0_lo, v0_hi  # second operand
        sh32 = y_lo & 31
        div_guard = jnp.where(y_lo == 0, jnp.int32(1), y_lo)
        q32 = lax.div(x_lo, div_guard)
        r32 = lax.rem(x_lo, div_guard)
        # unsigned 32-bit div via f64-free route: use i64-pair division only
        # for i64; for u32 use bit trick through uint32 dtype
        xu = x_lo.astype(jnp.uint32)
        yu = jnp.where(y_lo == 0, jnp.uint32(1), y_lo.astype(jnp.uint32))
        qu32 = lax.div(xu, yu).astype(I32)
        ru32 = lax.rem(xu, yu).astype(I32)

        i32_pairs = [
            (S_I32["add"], x_lo + y_lo),
            (S_I32["sub"], x_lo - y_lo),
            (S_I32["mul"], x_lo * y_lo),
            (S_I32["div_s"], q32),
            (S_I32["div_u"], qu32),
            (S_I32["rem_s"], r32),
            (S_I32["rem_u"], ru32),
            (S_I32["and"], x_lo & y_lo),
            (S_I32["or"], x_lo | y_lo),
            (S_I32["xor"], x_lo ^ y_lo),
            (S_I32["shl"], lax.shift_left(x_lo, sh32)),
            (S_I32["shr_s"], lax.shift_right_arithmetic(x_lo, sh32)),
            (S_I32["shr_u"], lax.shift_right_logical(x_lo, sh32)),
            (S_I32["rotl"], lo_ops.rotl32(x_lo, y_lo)),
            (S_I32["rotr"], lo_ops.rotl32(x_lo, (32 - (y_lo & 31)) & 31)),
            (S_I32["eq"], b2i(x_lo == y_lo)),
            (S_I32["ne"], b2i(x_lo != y_lo)),
            (S_I32["lt_s"], b2i(x_lo < y_lo)),
            (S_I32["lt_u"], b2i(u_lt(x_lo, y_lo))),
            (S_I32["gt_s"], b2i(x_lo > y_lo)),
            (S_I32["gt_u"], b2i(u_lt(y_lo, x_lo))),
            (S_I32["le_s"], b2i(x_lo <= y_lo)),
            (S_I32["le_u"], b2i(lo_ops.u_le(x_lo, y_lo))),
            (S_I32["ge_s"], b2i(x_lo >= y_lo)),
            (S_I32["ge_u"], b2i(lo_ops.u_le(y_lo, x_lo))),
        ]

        add64 = lo_ops.add64(x_lo, x_hi, y_lo, y_hi)
        sub64 = lo_ops.sub64(x_lo, x_hi, y_lo, y_hi)
        mul64 = lo_ops.mul64(x_lo, x_hi, y_lo, y_hi)
        sh64 = y_lo & 63
        shl64 = lo_ops.shl64(x_lo, x_hi, sh64)
        shrs64 = lo_ops.shr64_s(x_lo, x_hi, sh64)
        shru64 = lo_ops.shr64_u(x_lo, x_hi, sh64)
        rotl64 = lo_ops.rotl64(x_lo, x_hi, sh64)
        rotr64 = lo_ops.rotr64(x_lo, x_hi, sh64)
        eq64 = lo_ops.eq64(x_lo, x_hi, y_lo, y_hi)
        lts64 = lo_ops.lt64_s(x_lo, x_hi, y_lo, y_hi)
        ltu64 = lo_ops.lt64_u(x_lo, x_hi, y_lo, y_hi)
        gts64 = lo_ops.lt64_s(y_lo, y_hi, x_lo, x_hi)
        gtu64 = lo_ops.lt64_u(y_lo, y_hi, x_lo, x_hi)

        i64_pairs = [
            (S_I64["add"], add64),
            (S_I64["sub"], sub64),
            (S_I64["mul"], mul64),
            (S_I64["and"], (x_lo & y_lo, x_hi & y_hi)),
            (S_I64["or"], (x_lo | y_lo, x_hi | y_hi)),
            (S_I64["xor"], (x_lo ^ y_lo, x_hi ^ y_hi)),
            (S_I64["shl"], shl64),
            (S_I64["shr_s"], shrs64),
            (S_I64["shr_u"], shru64),
            (S_I64["rotl"], rotl64),
            (S_I64["rotr"], rotr64),
        ]
        i64_cmp_pairs = [
            (S_I64["eq"], b2i(eq64)),
            (S_I64["ne"], b2i(~eq64)),
            (S_I64["lt_s"], b2i(lts64)),
            (S_I64["lt_u"], b2i(ltu64)),
            (S_I64["gt_s"], b2i(gts64)),
            (S_I64["gt_u"], b2i(gtu64)),
            (S_I64["le_s"], b2i(~gts64)),
            (S_I64["le_u"], b2i(~gtu64)),
            (S_I64["ge_s"], b2i(~lts64)),
            (S_I64["ge_u"], b2i(~ltu64)),
        ]

        # rare i64 div/rem under an any-lane conditional (64-iteration loop)
        is_alu2 = is_cls[CLS_ALU2]
        rare_divs = is_alu2 & (
            (sub == S_I64["div_s"]) | (sub == S_I64["div_u"])
            | (sub == S_I64["rem_s"]) | (sub == S_I64["rem_u"]))

        def rare_compute(_):
            glo = jnp.where((y_lo | y_hi) == 0, jnp.int32(1), y_lo)
            ghi = jnp.where((y_lo | y_hi) == 0, jnp.int32(0), y_hi)
            qlo, qhi, rlo, rhi = lo_ops.divmod64_u(x_lo, x_hi, glo, ghi)
            sqlo, sqhi, srlo, srhi = lo_ops.div64_s(x_lo, x_hi, glo, ghi)
            dlo = sel_chain(sub, [
                (S_I64["div_s"], sqlo), (S_I64["div_u"], qlo),
                (S_I64["rem_s"], srlo), (S_I64["rem_u"], rlo)], x_lo)
            dhi = sel_chain(sub, [
                (S_I64["div_s"], sqhi), (S_I64["div_u"], qhi),
                (S_I64["rem_s"], srhi), (S_I64["rem_u"], rhi)], x_hi)
            return dlo, dhi

        rare_lo, rare_hi = lax.cond(
            jnp.any(rare_divs & active), rare_compute,
            lambda _: (x_lo, x_hi), operand=None)

        # f32
        fx = lo_ops.to_f32(x_lo)
        fy = lo_ops.to_f32(y_lo)
        fadd = lo_ops.canon32(lo_ops.from_f32(fx + fy))
        fsub = lo_ops.canon32(lo_ops.from_f32(fx - fy))
        fmul = lo_ops.canon32(lo_ops.from_f32(fx * fy))
        fdiv = lo_ops.canon32(lo_ops.from_f32(fx / fy))
        f32_pairs = [
            (S_F32["add"], fadd), (S_F32["sub"], fsub),
            (S_F32["mul"], fmul), (S_F32["div"], fdiv),
            (S_F32["min"], lo_ops.f32_min(x_lo, y_lo)),
            (S_F32["max"], lo_ops.f32_max(x_lo, y_lo)),
            (S_F32["copysign"],
             (x_lo & jnp.int32(0x7FFFFFFF)) | (y_lo & lo_ops._SIGN)),
        ]
        # comparisons in the integer domain: exact under hardware FTZ
        feq = lo_ops.f32_cmp_eq(x_lo, y_lo)
        flt = lo_ops.f32_cmp_lt(x_lo, y_lo)
        fgt = lo_ops.f32_cmp_lt(y_lo, x_lo)
        fnan = lo_ops.is_nan32(x_lo) | lo_ops.is_nan32(y_lo)
        f32_pairs += [
            (S_F32["eq"], b2i(feq)), (S_F32["ne"], b2i(~feq)),
            (S_F32["lt"], b2i(flt)), (S_F32["gt"], b2i(fgt)),
            (S_F32["le"], b2i((flt | feq) & ~fnan)),
            (S_F32["ge"], b2i((fgt | feq) & ~fnan)),
        ]

        alu2_lo = sel_chain(sub, i32_pairs + i64_cmp_pairs + f32_pairs
                            + [(s, v[0]) for s, v in i64_pairs], jnp.int32(0))
        alu2_hi = sel_chain(sub, [(s, v[1]) for s, v in i64_pairs], jnp.int32(0))
        alu2_lo = jnp.where(rare_divs, rare_lo, alu2_lo)
        alu2_hi = jnp.where(rare_divs, rare_hi, alu2_hi)

        # binary64 (softfloat) subs from the shared table, pruned to what
        # this module's image actually uses so f64-free modules pay
        # nothing; the iterative f64.div runs under an any-lane cond like
        # the i64 divisions above
        for sid in sorted(used_alu2 & set(_A2F)):
            if sid < ALU2_F64_BASE:
                continue
            fn = _A2F[sid]
            if sid in _HEAVY_ALU2:
                m = is_alu2 & (sub == sid)
                rl, rh = lax.cond(
                    jnp.any(m & active),
                    lambda fn=fn: fn(x_lo, x_hi, y_lo, y_hi),
                    lambda: (x_lo, x_hi))
            else:
                rl, rh = fn(x_lo, x_hi, y_lo, y_hi)
            alu2_lo = jnp.where(sub == sid, rl, alu2_lo)
            alu2_hi = jnp.where(sub == sid, rh, alu2_hi)

        # ALU2 traps: i32/i64 division
        div_i32 = is_alu2 & ((sub == S_I32["div_s"]) | (sub == S_I32["div_u"])
                             | (sub == S_I32["rem_s"]) | (sub == S_I32["rem_u"]))
        div_by_zero = (div_i32 & (y_lo == 0)) | (rare_divs & ((y_lo | y_hi) == 0))
        int_min32 = x_lo == jnp.int32(-0x80000000)
        ovf32 = is_alu2 & (sub == S_I32["div_s"]) & int_min32 & (y_lo == -1)
        int_min64 = (x_lo == 0) & (x_hi == jnp.int32(-0x80000000))
        ovf64 = rare_divs & (sub == S_I64["div_s"]) & int_min64 & \
            (y_lo == -1) & (y_hi == -1)
        alu2_trap = jnp.where(div_by_zero, int(ErrCode.DivideByZero), 0)
        alu2_trap = jnp.where(ovf32 | ovf64, int(ErrCode.IntegerOverflow),
                              alu2_trap)

        # =================== ALU1 ===================
        w_lo, w_hi = v0_lo, v0_hi
        fw = lo_ops.to_f32(w_lo)
        ext8 = lax.shift_right_arithmetic(lax.shift_left(w_lo, 24), 24)
        ext16 = lax.shift_right_arithmetic(lax.shift_left(w_lo, 16), 16)
        sign_w = lax.shift_right_arithmetic(w_lo, 31)
        # f32 -> i32 trunc with trap/sat handling
        tr = jnp.where(fw < 0, lax.ceil(fw), lax.floor(fw))
        # bit-domain NaN test: exact under hardware FTZ, same as uniform.py
        nan_w = lo_ops.is_nan32(w_lo)
        in_s = (tr >= jnp.float32(-2147483648.0)) & (tr <= jnp.float32(2147483520.0))
        # 2147483520 = largest f32 below 2^31
        trunc_s_val = jnp.where(in_s & ~nan_w, tr, jnp.float32(0)).astype(I32)
        in_u = (tr >= 0) & (tr <= jnp.float32(4294967040.0))
        tr_u_shift = jnp.where(in_u & ~nan_w, tr, jnp.float32(0))
        trunc_u_val = jnp.where(
            tr_u_shift >= jnp.float32(2147483648.0),
            (tr_u_shift - jnp.float32(4294967296.0)).astype(I32),
            tr_u_shift.astype(I32))
        sat_s = jnp.where(nan_w, 0, jnp.where(
            tr < jnp.float32(-2147483648.0), jnp.int32(-0x80000000), jnp.where(
                tr > jnp.float32(2147483520.0), jnp.int32(0x7FFFFFFF),
                trunc_s_val)))
        sat_u = jnp.where(nan_w | (tr < 0), 0, jnp.where(
            tr > jnp.float32(4294967040.0), jnp.int32(-1), trunc_u_val))
        # i32 -> f32 converts
        cvt_s = lo_ops.from_f32(w_lo.astype(jnp.float32))
        cvt_u = lo_ops.from_f32(w_lo.astype(jnp.uint32).astype(jnp.float32))

        alu1_pairs_lo = [
            (A1["i32.clz"], lax.clz(w_lo)),
            (A1["i32.ctz"], lo_ops.ctz32(w_lo)),
            (A1["i32.popcnt"], lax.population_count(w_lo)),
            (A1["i32.eqz"], b2i(w_lo == 0)),
            (A1["i32.extend8_s"], ext8),
            (A1["i32.extend16_s"], ext16),
            (A1["i64.clz"], lo_ops.clz64(w_lo, w_hi)),
            (A1["i64.ctz"], lo_ops.ctz64(w_lo, w_hi)),
            (A1["i64.popcnt"], lo_ops.popcnt64(w_lo, w_hi)),
            (A1["i64.eqz"], b2i((w_lo | w_hi) == 0)),
            (A1["i64.extend8_s"], ext8),
            (A1["i64.extend16_s"], ext16),
            (A1["i64.extend32_s"], w_lo),
            (A1["f32.abs"], w_lo & jnp.int32(0x7FFFFFFF)),
            (A1["f32.neg"], w_lo ^ lo_ops._SIGN),
            (A1["f32.ceil"], lo_ops.canon32(lo_ops.from_f32(lax.ceil(fw)))),
            (A1["f32.floor"], lo_ops.canon32(lo_ops.from_f32(lax.floor(fw)))),
            (A1["f32.trunc"], lo_ops.f32_trunc(w_lo)),
            (A1["f32.nearest"], lo_ops.f32_nearest(w_lo)),
            (A1["f32.sqrt"], lo_ops.canon32(lo_ops.from_f32(lax.sqrt(fw)))),
            (A1["i32.wrap_i64"], w_lo),
            (A1["i64.extend_i32_s"], w_lo),
            (A1["i64.extend_i32_u"], w_lo),
            (A1["i32.trunc_f32_s"], trunc_s_val),
            (A1["i32.trunc_f32_u"], trunc_u_val),
            (A1["i32.trunc_sat_f32_s"], sat_s),
            (A1["i32.trunc_sat_f32_u"], sat_u),
            (A1["f32.convert_i32_s"], cvt_s),
            (A1["f32.convert_i32_u"], cvt_u),
            (A1["i32.reinterpret_f32"], w_lo),
            (A1["f32.reinterpret_i32"], w_lo),
            (A1["ref.is_null"], b2i((w_lo | w_hi) == 0)),
        ]
        alu1_pairs_hi = [
            (A1["i64.clz"], jnp.int32(0)),
            (A1["i64.ctz"], jnp.int32(0)),
            (A1["i64.popcnt"], jnp.int32(0)),
            (A1["i64.extend8_s"], lax.shift_right_arithmetic(ext8, 31)),
            (A1["i64.extend16_s"], lax.shift_right_arithmetic(ext16, 31)),
            (A1["i64.extend32_s"], sign_w),
            (A1["i64.extend_i32_s"], sign_w),
            (A1["i64.extend_i32_u"], jnp.int32(0)),
        ]
        alu1_lo = sel_chain(sub, alu1_pairs_lo, w_lo)
        alu1_hi = sel_chain(sub, alu1_pairs_hi, jnp.int32(0))
        is_alu1 = is_cls[CLS_ALU1]
        # subs beyond the hand-rolled chain (the f64/softfloat family and
        # the i64<->float conversions) come from the shared table, pruned
        # to the module's image
        _handled = {sid for sid, _ in alu1_pairs_lo}
        for sid in sorted(used_alu1 & set(_A1F)):
            if sid in _handled:
                continue
            fn = _A1F[sid]
            if sid in _HEAVY_ALU1:
                m = is_alu1 & (sub == sid)
                rl, rh = lax.cond(
                    jnp.any(m & active),
                    lambda fn=fn: fn(w_lo, w_hi),
                    lambda: (w_lo, w_hi))
            else:
                rl, rh = fn(w_lo, w_hi)
            alu1_lo = jnp.where(sub == sid, rl, alu1_lo)
            alu1_hi = jnp.where(sub == sid, rh, alu1_hi)
        # traps for every trapping truncation, from the shared table
        alu1_trap = jnp.int32(0) * w_lo
        for sid in sorted(used_alu1 & set(_T1F)):
            bad, codes = _T1F[sid](w_lo, w_hi)
            m = is_alu1 & (sub == sid) & bad
            alu1_trap = jnp.where(m, codes, alu1_trap)

        # =================== memory ===================
        is_load = is_cls[CLS_LOAD]
        is_store = is_cls[CLS_STORE]
        addr_base = jnp.where(is_store, v1_lo, v0_lo)
        ea = addr_base + a  # u32 wrap
        ea_carry = u_lt(ea, addr_base) | u_lt(ea, a)
        nbytes = b
        mem_bytes = st.mem_pages * jnp.int32(65536)
        end = ea + nbytes
        mem_oob = ea_carry | u_lt(end, ea) | u_lt(mem_bytes, end)
        widx = lax.shift_right_logical(ea, 2)
        shB = (ea & 3) * 8
        mw0 = gat(st.mem, widx)
        mw1 = gat(st.mem, widx + 1)
        mw2 = gat(st.mem, widx + 2)
        inv_sh = (32 - shB) & 31
        hi_or = jnp.where(shB == 0, 0, -1)
        raw_lo = lax.shift_right_logical(mw0, shB) | \
            (lax.shift_left(mw1, inv_sh) & hi_or)
        raw_hi = lax.shift_right_logical(mw1, shB) | \
            (lax.shift_left(mw2, inv_sh) & hi_or)
        signed = (c & 1) != 0
        is64 = (c & 2) != 0
        b1 = nbytes == 1
        b2 = nbytes == 2
        b4 = nbytes == 4
        lraw = jnp.where(b1, raw_lo & 0xFF,
                         jnp.where(b2, raw_lo & 0xFFFF, raw_lo))
        lsext = jnp.where(
            b1, lax.shift_right_arithmetic(lax.shift_left(raw_lo, 24), 24),
            jnp.where(b2, lax.shift_right_arithmetic(lax.shift_left(raw_lo, 16), 16),
                      raw_lo))
        load_lo = jnp.where(signed, lsext, lraw)
        load_hi = jnp.where(
            is64,
            jnp.where(nbytes == 8, raw_hi,
                      jnp.where(signed, lax.shift_right_arithmetic(load_lo, 31), 0)),
            jnp.int32(0))

        # stores: build 3-word write masks and values
        full_m_lo = jnp.where(b1, 0xFF, jnp.where(b2, 0xFFFF, jnp.int32(-1)))
        full_m_hi = jnp.where(nbytes == 8, jnp.int32(-1), 0)
        sm0, sm1 = lo_ops.shl64(full_m_lo, full_m_hi, shB)
        sm2 = jnp.where(shB == 0, 0,
                        lo_ops.shr64_u(full_m_lo, full_m_hi, 64 - shB)[0])
        sv0, sv1 = lo_ops.shl64(v0_lo, v0_hi, shB)
        sv2 = jnp.where(shB == 0, 0,
                        lo_ops.shr64_u(v0_lo, v0_hi, 64 - shB)[0])
        nw0 = (mw0 & ~sm0) | (sv0 & sm0)
        nw1 = (mw1 & ~sm1) | (sv1 & sm1)
        nw2 = (mw2 & ~sm2) | (sv2 & sm2)
        store_ok = active & is_store & ~mem_oob

        def run_stores(mp):
            mp = scat(mp, widx, nw0, store_ok & (sm0 != 0))
            mp = scat(mp, widx + 1, nw1, store_ok & (sm1 != 0))
            mp = scat(mp, widx + 2, nw2, store_ok & (sm2 != 0))
            return mp

        # any-lane conditional: steps where no lane stores skip the
        # plane scatters entirely (lockstep batches spend most steps in
        # compute; an unconditional masked scatter still walks the
        # plane on the CPU backend)
        mem_plane = lax.cond(jnp.any(store_ok), run_stores,
                             lambda m: m, st.mem)

        # ------ bulk memory: fill / copy (full-plane masked ops, run
        # under an any-lane conditional since they rewrite [W, lanes]) ---
        # compiled only when the image contains bulk ops: the any-lane
        # lax.cond costs a full-plane pass-through on the CPU backend,
        # which a module without memory.fill/copy must never pay
        HAS_BULK = bool(np.isin(img.cls, (CLS_MEMFILL, CLS_MEMCOPY)).any())
        if HAS_BULK:
            is_fill = is_cls[CLS_MEMFILL]
            is_copy = is_cls[CLS_MEMCOPY]
            is_bulk = is_fill | is_copy
            # operands (top of stack): fill = dst,val,n / copy = dst,src,n
            bulk_n = v0_lo
            bulk_b = v1_lo            # fill value / copy src
            bulk_dst = v2_lo
            mem_bytes_v = st.mem_pages * jnp.int32(65536)
            bulk_end = bulk_dst + bulk_n
            src_end = bulk_b + bulk_n
            bulk_oob = is_bulk & active & (
                u_lt(bulk_end, bulk_dst) | u_lt(mem_bytes_v, bulk_end)
                | (is_copy & (u_lt(src_end, bulk_b)
                              | u_lt(mem_bytes_v, src_end))))
            bulk_go = is_bulk & active & ~bulk_oob & (bulk_n != 0)

            uses_copy = bool((img.cls == CLS_MEMCOPY).any())

            def run_bulk(mem_in):
                return lo_ops.plane_fill_copy(
                    mem_in, bulk_dst, bulk_end, bulk_b, bulk_go,
                    copy_lanes=is_copy if uses_copy else None)

            mem_plane = lax.cond(jnp.any(bulk_go), run_bulk,
                                 lambda m: m, mem_plane)
        else:
            is_bulk = jnp.bool_(False) & (cls == cls)
            bulk_oob = is_bulk

        # =================== v128 (SIMD) ===================
        # cells are 4 int32 planes; ops come from batch/simdops.py and
        # compile only for the sub ids the module image actually uses
        z4p = (zl, zl, zl, zl)
        if HAS_SIMD:
            is_vconst = is_cls[CLS_VCONST]
            is_v2 = is_cls[CLS_V2]
            is_v1 = is_cls[CLS_V1]
            is_vtest = is_cls[CLS_VTEST]
            is_vshift = is_cls[CLS_VSHIFT]
            is_vsplat = is_cls[CLS_VSPLAT]
            is_vextract = is_cls[CLS_VEXTRACT]
            is_vreplace = is_cls[CLS_VREPLACE]
            is_vshuffle = is_cls[CLS_VSHUFFLE]
            is_vbitsel = is_cls[CLS_VBITSEL]
            is_vload = is_cls[CLS_VLOAD]
            is_vstore = is_cls[CLS_VSTORE]
            x4 = (v1_lo, v1_hi, v1_e2, v1_e3)   # second-from-top cell
            y4 = (v0_lo, v0_hi, v0_e2, v0_e3)   # top cell
            w4 = (v2_lo, v2_hi, v2_e2, v2_e3)   # third-from-top cell

            def vsel(used, mk_fn, *args):
                acc = z4p
                for sid in sorted(used):
                    r = mk_fn(sid)(*args)
                    m = sub == sid
                    acc = tuple(jnp.where(m, rn, an)
                                for rn, an in zip(r, acc))
                return acc

            v2_res = vsel(used_v2, sops.v2_fn, x4, y4)
            v1_res = vsel(used_v1, sops.v1_fn, y4)
            vshift_res = vsel(used_vshift,
                              lambda s: sops.vshift_fn(s), x4, v0_lo)
            vsplat_res = vsel(used_vsplat,
                              lambda s: sops.vsplat_fn(s), v0_lo, v0_hi)
            vrepl_res = vsel(used_vreplace,
                             lambda s: (lambda xx, ll, hh, f=sops.
                                        vreplace_dyn(s): f(xx, a, ll, hh)),
                             x4, v0_lo, v0_hi)
            vtest_res = zl
            for sid in sorted(used_vtest):
                r = sops.vtest_fn(sid)(y4)
                vtest_res = jnp.where(sub == sid, r, vtest_res)
            vex_lo, vex_hi = zl, zl
            for sid in sorted(used_vextract):
                rl, rh = sops.vextract_dyn(sid)(y4, a)
                m = sub == sid
                vex_lo = jnp.where(m, rl, vex_lo)
                vex_hi = jnp.where(m, rh, vex_hi)
            vcidx = jnp.clip(a, 0, v128_t.shape[0] - 1)
            vconst_res = tuple(v128_t[vcidx, k] for k in range(4))
            if uses_vshuffle:
                m4 = tuple(v128_t[vcidx, k] for k in range(4))
                vshuf_res = sops.vshuffle_dyn()(x4, y4, m4)
            else:
                vshuf_res = z4p
            # bitselect: operands (v1, v2, mask) = (w4, x4, y4)
            vbit_res = sops.vbitselect()(w4, x4, y4)

            # ---- v128.load / v128.store (5-word shifted window) ----
            # compiled only when the image contains them: the 5 gathers +
            # 5 masked plane scatters are runtime-masked and XLA cannot
            # dead-code-eliminate them otherwise
            if uses_vmem:
                vaddr = jnp.where(is_vstore, v1_lo, v0_lo)
                vea = vaddr + a
                vcarry = u_lt(vea, vaddr) | u_lt(vea, a)
                vend = vea + 16
                v_oob = vcarry | u_lt(vend, vea) | u_lt(mem_bytes, vend)
                vwidx = lax.shift_right_logical(vea, 2)
                vsh = (vea & 3) * 8
                vinv = (32 - vsh) & 31
                v_hi_or = jnp.where(vsh == 0, 0, -1)
                vmw = [gat(st.mem, vwidx + k) for k in range(5)]
                vload_res = tuple(
                    lax.shift_right_logical(vmw[k], vsh)
                    | (lax.shift_left(vmw[k + 1], vinv) & v_hi_or)
                    for k in range(4))
                # store masks/values across the 5-word window
                vm = [lax.shift_left(jnp.int32(-1), vsh)] \
                    + [jnp.int32(-1) * jnp.ones_like(zl)] * 3 \
                    + [jnp.where(vsh == 0, 0,
                                 ~lax.shift_left(jnp.int32(-1), vsh))]
                sv = []
                prev = zl
                for k in range(4):
                    sv.append(lax.shift_left(y4[k], vsh)
                              | (lax.shift_right_logical(prev, vinv)
                                 & v_hi_or))
                    prev = y4[k]
                sv.append(lax.shift_right_logical(prev, vinv) & v_hi_or)
                vstore_ok = active & is_vstore & ~v_oob
                for k in range(5):
                    nw = (vmw[k] & ~vm[k]) | (sv[k] & vm[k])
                    mem_plane = scat(mem_plane, vwidx + k, nw,
                                     vstore_ok & (vm[k] != 0))
            else:
                vload_res = z4p
                v_oob = jnp.zeros_like(cls == cls)
        else:
            is_vconst = is_v2 = is_v1 = is_vtest = is_vshift = \
                is_vsplat = is_vextract = is_vreplace = is_vshuffle = \
                is_vbitsel = is_vload = is_vstore = jnp.bool_(False) & \
                (cls == cls)
            v2_res = v1_res = vshift_res = vsplat_res = vrepl_res = \
                vconst_res = vshuf_res = vbit_res = vload_res = z4p
            vtest_res = vex_lo = vex_hi = zl
            v_oob = jnp.zeros_like(cls == cls)

        is_grow = is_cls[CLS_MEMGROW]
        grow_delta = v0_lo
        grow_ok = ~u_lt(jnp.int32(img.mem_pages_max), st.mem_pages + grow_delta) \
            & (grow_delta >= 0) & ((st.mem_pages + grow_delta) >= st.mem_pages)
        grow_res = jnp.where(grow_ok, st.mem_pages, jnp.int32(-1))
        new_mem_pages = jnp.where(active & is_grow & grow_ok,
                                  st.mem_pages + grow_delta, st.mem_pages)

        # ========== memory.init / data.drop (r05) ==========
        ddrop_p = st.ddrop
        if HAS_DSEG:
            is_minit = is_cls[CLS_MEMINIT]
            is_ddrop = is_cls[CLS_DATA_DROP]
            didx = jnp.clip(a, 0, data_len_t.shape[0] - 1)
            ddropped = gat(st.ddrop, didx)
            dseg_len = jnp.where(ddropped != 0, 0, data_len_t[didx])
            dseg_off = data_off_t[didx]
            mi_n, mi_src, mi_dst = v0_lo, v1_lo, v2_lo
            mi_send = mi_src + mi_n
            mi_dend = mi_dst + mi_n
            mi_oob = is_minit & active & (
                u_lt(mi_send, mi_src) | u_lt(dseg_len, mi_send)
                | u_lt(mi_dend, mi_dst) | u_lt(mem_bytes, mi_dend))
            mi_go = active & is_minit & ~mi_oob & (mi_n != 0)

            def run_minit(mem_in):
                rows = jnp.arange(mem_in.shape[0], dtype=I32)[:, None]
                out = mem_in
                # src byte index for dst byte addr ba: seg_off+src+(ba-dst)
                base_sb = dseg_off + mi_src - mi_dst
                nW = data_words_t.shape[0]
                for bpos in range(4):
                    ba = rows * 4 + bpos
                    inr = (ba >= mi_dst) & (ba < mi_dend) & mi_go
                    sbi = ba + base_sb
                    w = data_words_t[jnp.clip(
                        lax.shift_right_logical(sbi, 2), 0, nW - 1)]
                    byte = lax.shift_right_logical(w, (sbi & 3) * 8) & 0xFF
                    mk = np.int32(np.uint32(0xFF << (bpos * 8)))
                    val = lax.shift_left(byte, bpos * 8)
                    out = jnp.where(inr, (out & ~mk) | (val & mk), out)
                return out

            mem_plane = lax.cond(jnp.any(mi_go), run_minit,
                                 lambda m: m, mem_plane)
            ddrop_p = scat(st.ddrop, didx, jnp.ones_like(didx),
                           active & is_ddrop)
        else:
            is_minit = jnp.bool_(False) & (cls == cls)
            mi_oob = is_minit

        # ========== table families (r05): per-lane table plane ==========
        # The reference's tableInstr.cpp handlers over a shared
        # TableInstance become masked ops over a [T_CAP, lanes] plane —
        # functional arrays make copy/init overlap-safe for free (gather
        # from the pre-op plane, then select).
        tab_p, tsize_p, edrop_p = st.tab, st.tsize, st.edrop
        table_trap = jnp.zeros_like(trap)
        if HAS_T_ANY:
            is_tget = is_cls[CLS_TABLE_GET]
            is_tset = is_cls[CLS_TABLE_SET]
            is_tgrow = is_cls[CLS_TABLE_GROW]
            is_tfill = is_cls[CLS_TABLE_FILL]
            is_tcopy = is_cls[CLS_TABLE_COPY]
            is_tinit = is_cls[CLS_TABLE_INIT]
            tbase = c
            tsize_l = st.tsize if st.tsize is not None else b
            tg_oob = is_tget & ~u_lt(v0_lo, tsize_l)
            if HAS_T_MUT:
                tget_val = gat(st.tab, tbase + v0_lo)
            else:
                tget_val = table0[jnp.clip(tbase + v0_lo, 0,
                                           table0.shape[0] - 1)]
            ts_oob = is_tset & ~u_lt(v1_lo, tsize_l)
            # grow: ... init delta -> v0 = delta, v1 = init ref.  The
            # instruction's b carries this table's CAPACITY (engine
            # rewrites it after clamping; per-tenant slot size in a
            # concatenated multi-tenant image) — growth past it returns
            # -1, the spec-legal failure mode.
            tgrow_new = tsize_l + v0_lo
            tgrow_ok = is_tgrow & (v0_lo >= 0) & (tgrow_new >= tsize_l) \
                & ~u_lt(b, tgrow_new)
            tgrow_res = jnp.where(tgrow_ok, tsize_l, jnp.int32(-1))
            # fill: ... i val n -> v0 = n, v1 = val, v2 = i
            tf_end = v2_lo + v0_lo
            tf_oob = is_tfill & (u_lt(tf_end, v2_lo)
                                 | u_lt(tsize_l, tf_end))
            # copy: ... dst src n -> v0 = n, v1 = src, v2 = dst
            tc_send = v1_lo + v0_lo
            tc_dend = v2_lo + v0_lo
            tc_oob = is_tcopy & (
                u_lt(tc_send, v1_lo) | u_lt(tsize_l, tc_send)
                | u_lt(tc_dend, v2_lo) | u_lt(tsize_l, tc_dend))
            # init: ... dst src n; a = elem segment (len 0 once dropped)
            if HAS_ESEG:
                eidx = jnp.clip(a, 0, elem_len_t.shape[0] - 1)
                edropped = gat(st.edrop, eidx) if st.edrop is not None \
                    else jnp.zeros_like(a)
                eseg_len = jnp.where(edropped != 0, 0, elem_len_t[eidx])
                eseg_off = elem_off_t[eidx]
                ti_send2 = v1_lo + v0_lo
                ti_dend2 = v2_lo + v0_lo
                tinit_oob = is_tinit & (
                    u_lt(ti_send2, v1_lo) | u_lt(eseg_len, ti_send2)
                    | u_lt(ti_dend2, v2_lo) | u_lt(tsize_l, ti_dend2))
            else:
                tinit_oob = is_tinit  # unreachable (no segments)
            t_oob = active & (tg_oob | ts_oob | tf_oob | tc_oob | tinit_oob)
            table_trap = jnp.where(
                t_oob, jnp.int32(int(ErrCode.TableOutOfBounds)), table_trap)
            if HAS_T_MUT:
                tab_p = scat(st.tab, tbase + v1_lo, v0_lo,
                             active & is_tset & ~ts_oob)
                m_grow = active & is_tgrow & tgrow_ok & (v0_lo > 0)
                m_fill = active & is_tfill & ~tf_oob & (v0_lo != 0)
                m_copy = active & is_tcopy & ~tc_oob & (v0_lo != 0)
                m_init = active & is_tinit & ~tinit_oob & (v0_lo != 0) \
                    if HAS_ESEG else jnp.bool_(False) & (cls == cls)
                ranged_go = m_grow | m_fill | m_copy | m_init

                def run_trange(tp):
                    rows = jnp.arange(T_CAP, dtype=I32)[:, None]
                    cur = tp
                    # constant fill: grow writes init (v1) into the new
                    # rows, table.fill writes val (v1) into [i, i+n)
                    lo_f = tbase + jnp.where(m_grow, tsize_l, v2_lo)
                    hi_f = tbase + jnp.where(m_grow, tgrow_new, tf_end)
                    inr = (rows >= lo_f) & (rows < hi_f) & (m_grow | m_fill)
                    cur = jnp.where(inr, v1_lo, cur)
                    if bool((img.cls == CLS_TABLE_COPY).any()):
                        srows = jnp.clip(rows - v2_lo + v1_lo, 0, T_CAP - 1)
                        svals = jnp.take_along_axis(
                            tp, jnp.broadcast_to(srows, tp.shape), axis=0)
                        inc = (rows >= tbase + v2_lo) \
                            & (rows < tbase + tc_dend) & m_copy
                        cur = jnp.where(inc, svals, cur)
                    if HAS_ESEG and bool((img.cls == CLS_TABLE_INIT).any()):
                        sidx = jnp.clip(
                            eseg_off + v1_lo + (rows - (tbase + v2_lo)),
                            0, elem_flat_t.shape[0] - 1)
                        ivals = elem_flat_t[sidx]
                        ini = (rows >= tbase + v2_lo) \
                            & (rows < tbase + ti_dend2) & m_init
                        cur = jnp.where(ini, ivals, cur)
                    return cur

                tab_p = lax.cond(jnp.any(ranged_go), run_trange,
                                 lambda t: t, tab_p)
                if st.tsize is not None:
                    tsize_p = jnp.where(active & is_tgrow & tgrow_ok,
                                        tgrow_new, st.tsize)
            if HAS_ESEG and st.edrop is not None:
                is_edrop = is_cls[CLS_ELEM_DROP]
                edrop_p = scat(st.edrop, eidx, jnp.ones_like(eidx),
                               active & is_edrop)
        else:
            is_tget = is_tgrow = jnp.bool_(False) & (cls == cls)
            tget_val = zl
            tgrow_res = zl
            tsize_l = b

        # =================== tier-0 hostcalls ===================
        # Pure WASI calls retired inside the kernel: the lane executes
        # its HOSTCALL stub like any other instruction (result pushed at
        # opbase, pc+1 to the stub's RETURN) instead of parking for the
        # device->host outcall channel.  Unhandled shapes (cputime
        # clocks, oversized buffers, full stdout buffer, foreign fds)
        # keep the parking path below.
        t0_push = jnp.bool_(False) & (cls == cls)   # retire with a result
        t0_exit = jnp.bool_(False) & (cls == cls)   # proc_exit lanes
        t0_val = zl                                  # pushed cell (errno)
        t0_ctr_p = st.t0_ctr
        so_buf_p = st.so_buf
        so_off_p = st.so_off
        if HAS_T0:
            k0 = t0k_t[pc]
            is_hc = is_cls[CLS_HOSTCALL] & active
            arg0 = gat(st.stack_lo, fp)
            arg1 = gat(st.stack_lo, fp + 1)
            arg2 = gat(st.stack_lo, fp + 2)
            arg3 = gat(st.stack_lo, fp + 3)
            ctr_clk = st.t0_ctr[0]
            ctr_rng = st.t0_ctr[1]
            ctr_fdw = st.t0_ctr[2]
            ctr_sys = st.t0_ctr[3]

            if USE_T0_CLOCK:
                m_clk = is_hc & (k0 == T0_CLOCK_TIME_GET)
                cid = arg0
                tptr = arg2
                bad_id = u_lt(jnp.int32(3), cid)       # unsigned id > 3
                hard_id = (cid == 2) | (cid == 3)      # cputime: tier 1
                tend = tptr + 8
                c_oob = u_lt(tend, tptr) | u_lt(mem_bytes, tend)
                tv_lo, tv_hi = t0_clock_value(t0_time, cid, ctr_clk)
                ok_c = m_clk & ~bad_id & ~hard_id
                wr_c = ok_c & ~c_oob
                mem_plane = lax.cond(
                    jnp.any(wr_c),
                    lambda mp: t0_masked_store(t0_rmw, mp, tptr, tv_lo,
                                               tv_hi, 8, wr_c),
                    lambda mp: mp, mem_plane)
                done_c = m_clk & ~hard_id
                res_c = jnp.where(bad_id, jnp.int32(_E_INVAL),
                                  jnp.where(c_oob, jnp.int32(_E_FAULT), 0))
                t0_push = t0_push | done_c
                t0_val = jnp.where(done_c, res_c, t0_val)
                t0_ctr_p = t0_ctr_p.at[0].set(
                    jnp.where(wr_c, ctr_clk + 1, ctr_clk))

            if USE_T0_RANDOM:
                m_rnd = is_hc & (k0 == T0_RANDOM_GET)
                rbuf, rlen = arg0, arg1
                fits_r = ~u_lt(jnp.int32(RMAX_W * 4), rlen)
                rend = rbuf + rlen
                r_oob = u_lt(rend, rbuf) | u_lt(mem_bytes, rend)
                ok_r = m_rnd & fits_r
                wr_r = ok_r & ~r_oob & (rlen != 0)
                seq_h = t0_rng_seq_hash(RNG_SEED, lane_iota, ctr_rng)

                mem_plane = lax.cond(
                    jnp.any(wr_r),
                    lambda mp: t0_random_fill(t0_rmw, mp, rbuf, rend,
                                              wr_r, seq_h, RMAX_W, zl),
                    lambda mp: mp, mem_plane)
                res_r = jnp.where(r_oob, jnp.int32(_E_FAULT), 0)
                t0_push = t0_push | ok_r
                t0_val = jnp.where(ok_r, res_r, t0_val)
                t0_ctr_p = t0_ctr_p.at[1].set(
                    jnp.where(wr_r, ctr_rng + 1, ctr_rng))

            if USE_T0_FDW:
                m_fdw = is_hc & (k0 == T0_FD_WRITE)
                wfd, wiovs, wcnt, wnp = arg0, arg1, arg2, arg3
                SW = so_buf_p.shape[0]
                iov_end = wiovs + 8
                iov_ok = ~(u_lt(iov_end, wiovs) | u_lt(mem_bytes, iov_end))
                iw = lax.shift_right_logical(wiovs, 2)
                wbuf = gat(mem_plane, iw)
                wlen = gat(mem_plane, iw + 1)
                fits_w = ~u_lt(jnp.int32(WMAX_W * 4), wlen)
                nwords = lax.shift_right_logical(wlen + 3, 2)
                space = ~u_lt(jnp.int32(SW), st.so_off + 1 + nwords)
                npend = wnp + 4
                np_ok = ~(u_lt(npend, wnp) | u_lt(mem_bytes, npend))
                handled_w = m_fdw & ((wfd == 1) | (wfd == 2)) \
                    & (wcnt == 1) & ((wiovs & 3) == 0) & iov_ok \
                    & fits_w & space & np_ok
                dend = wbuf + wlen
                d_oob = u_lt(dend, wbuf) | u_lt(mem_bytes, dend)
                wr_w = handled_w & ~d_oob
                shB_w = (wbuf & 3) * 8
                inv_w = (32 - shB_w) & 31
                hi_or_w = jnp.where(shB_w == 0, 0, -1)
                wsrc0 = lax.shift_right_logical(wbuf, 2)
                mem_snapshot = mem_plane

                def run_fdw(sob):
                    # record: header (fd << 28 | len), then len bytes
                    # padded to whole words — always word-aligned in the
                    # buffer, so only the guest-side source is shifted
                    hdr = wlen | lax.shift_left(wfd, 28)
                    sob = scat(sob, st.so_off, hdr, wr_w)
                    for j in range(WMAX_W):
                        v = t0_shifted_src_word(gat, mem_snapshot, wsrc0,
                                                j, shB_w, inv_w, hi_or_w)
                        sob = scat(sob, st.so_off + 1 + j, v,
                                   wr_w & (jnp.int32(j * 4) < wlen))
                    return sob

                so_buf_p = lax.cond(jnp.any(wr_w), run_fdw,
                                    lambda s: s, so_buf_p)
                mem_plane = lax.cond(
                    jnp.any(wr_w),
                    lambda mp: t0_masked_store(t0_rmw, mp, wnp, wlen,
                                               jnp.zeros_like(wlen), 4,
                                               wr_w),
                    lambda mp: mp, mem_plane)
                so_off_p = jnp.where(wr_w, st.so_off + 1 + nwords,
                                     so_off_p)
                res_w = jnp.where(d_oob, jnp.int32(_E_FAULT), 0)
                done_w = handled_w
                t0_push = t0_push | done_w
                t0_val = jnp.where(done_w, res_w, t0_val)
                t0_ctr_p = t0_ctr_p.at[2].set(
                    jnp.where(wr_w, ctr_fdw + 1, ctr_fdw))

            if USE_T0_YIELD:
                m_yld = is_hc & (k0 == T0_SCHED_YIELD)
                t0_push = t0_push | m_yld
                t0_val = jnp.where(m_yld, 0, t0_val)
                t0_ctr_p = t0_ctr_p.at[3].set(
                    jnp.where(m_yld, ctr_sys + 1, ctr_sys))
                ctr_sys = t0_ctr_p[3]

            if USE_T0_EXIT:
                m_ext = is_hc & (k0 == T0_PROC_EXIT)
                t0_exit = t0_exit | m_ext
                # exit code lands in the result slot for the harvester
                t0_val = jnp.where(m_ext, arg0, t0_val)
                t0_ctr_p = t0_ctr_p.at[3].set(
                    jnp.where(m_ext, ctr_sys + 1, ctr_sys))

        # =================== branches ===================
        is_br = is_cls[CLS_BR]
        is_brz = is_cls[CLS_BRZ]
        is_brnz = is_cls[CLS_BRNZ]
        is_brt = is_cls[CLS_BR_TABLE]
        cond_zero = v0_lo == 0
        brnz_taken = is_brnz & ~cond_zero
        bt_i = jnp.where(u_lt(b, v0_lo), b, v0_lo)  # unsigned clamp to default
        bt_entry = jnp.clip(a + bt_i, 0, brt_t.shape[0] - 1)
        bt_tgt = brt_t[bt_entry, 0]
        bt_keep = brt_t[bt_entry, 1]
        bt_pop = brt_t[bt_entry, 2]

        # =================== call / return ===================
        is_call = is_cls[CLS_CALL]
        is_calli = is_cls[CLS_CALL_INDIRECT]
        if HAS_TAIL:
            # return_call(_indirect): frame REPLACEMENT — the reference's
            # StackManager tail-call path (include/runtime/stackmgr.h:80-98)
            is_rcall = is_cls[CLS_RETCALL]
            is_rcalli = is_cls[CLS_RETCALL_INDIRECT]
        else:
            is_rcall = is_rcalli = jnp.bool_(False) & (cls == cls)
        is_tail = is_rcall | is_rcalli
        is_icall = is_calli | is_rcalli
        is_callany = is_call | is_calli | is_tail
        # per-instruction table window: b = size, c = base (multi-tenant
        # concatenated tables); per-lane tsize plane wins when present
        # (table.grow can have changed it)
        calli_size = st.tsize if (HAS_T_MUT and st.tsize is not None) else b
        ti = c + jnp.clip(v0_lo, 0, jnp.maximum(calli_size - 1, 0))
        ti = jnp.clip(ti, 0, T_CAP - 1 if HAS_T_MUT else table0.shape[0] - 1)
        t_h = gat(st.tab, ti) if HAS_T_MUT else table0[ti]
        # unsigned idx < size (never size-1 arithmetic: b == 0 — an empty
        # table — must always be UndefinedElement, not an underflow)
        ti_oob = is_icall & ~u_lt(v0_lo, calli_size)
        ti_null = is_icall & ~ti_oob & (t_h == 0)
        callee = jnp.where(is_icall, jnp.clip(t_h - 1, 0, f_entry.shape[0] - 1),
                           jnp.clip(a, 0, f_entry.shape[0] - 1))
        sig_bad = is_icall & ~ti_oob & ~ti_null & (f_type[callee] != a)
        c_entry = f_entry[callee]
        c_nparams = f_nparams[callee]
        c_nlocals = f_nlocals[callee]
        c_frame_top = f_frame_top[callee]
        sp_eff = jnp.where(is_icall, sp - 1, sp)
        # tail calls reuse the caller's frame slot: fp stays, args slide
        fp_new = jnp.where(is_tail, fp, sp_eff - c_nparams)
        opbase_new = fp_new + c_nlocals
        # CD-1, not CD: the scalar engine's entry sentinel frame counts
        # toward max_call_depth, so nesting capacity is depth-1 calls
        depth_ovf = (is_call | is_calli) & (st.call_depth >= CD - 1)
        stack_ovf = is_callany & (fp_new + c_frame_top > D)
        call_trap = jnp.where(ti_oob, int(ErrCode.UndefinedElement), 0)
        call_trap = jnp.where(ti_null, int(ErrCode.UninitializedElement), call_trap)
        call_trap = jnp.where(sig_bad, int(ErrCode.IndirectCallTypeMismatch), call_trap)
        call_trap = jnp.where(depth_ovf, int(ErrCode.CallStackExhausted), call_trap)
        call_trap = jnp.where(stack_ovf, int(ErrCode.StackOverflow), call_trap)
        call_ok = active & is_callany & (call_trap == 0)
        tail_ok = call_ok & is_tail

        # frame push (tail calls don't push — they replace)
        fr_ret_pc = scat(st.fr_ret_pc, st.call_depth, pc + 1,
                         call_ok & ~is_tail)
        fr_fp = scat(st.fr_fp, st.call_depth, fp, call_ok & ~is_tail)
        fr_opbase = scat(st.fr_opbase, st.call_depth, opbase,
                         call_ok & ~is_tail)

        # return
        is_ret = is_cls[CLS_RETURN]
        ret_done = is_ret & (st.call_depth == 0)
        rd = jnp.clip(st.call_depth - 1, 0, CD - 1)
        r_pc = gat(st.fr_ret_pc, rd)
        r_fp = gat(st.fr_fp, rd)
        r_opbase = gat(st.fr_opbase, rd)
        nres = b  # CLS_RETURN carries result count in b

        # =================== merge: stack top write ===================
        is_const = is_cls[CLS_CONST]
        is_lget = is_cls[CLS_LOCAL_GET]
        is_gget = is_cls[CLS_GLOBAL_GET]
        is_msize = is_cls[CLS_MEMSIZE]
        is_sel = is_cls[CLS_SELECT]
        sel_lo = jnp.where(cond_zero, v1_lo, v2_lo)
        sel_hi = jnp.where(cond_zero, v1_hi, v2_hi)

        sel_e2 = jnp.where(cond_zero, v1_e2, v2_e2)
        sel_e3 = jnp.where(cond_zero, v1_e3, v2_e3)
        wpos = sp  # default for push-class
        wlo = ilo
        whi = ihi
        we2 = zl
        we3 = zl
        does_write = is_const
        write_entries = [
            (is_lget, sp, loc_lo, loc_hi, loc_e2, loc_e3),
            (is_gget, sp, g_lo, g_hi),
            (is_msize, sp, st.mem_pages, jnp.zeros_like(st.mem_pages)),
            (is_alu1, sp - 1, alu1_lo, alu1_hi),
            (is_grow, sp - 1, grow_res, jnp.zeros_like(grow_res)),
            (is_load & ~mem_oob, sp - 1, load_lo, load_hi),
            (is_alu2, sp - 2, alu2_lo, alu2_hi),
            (is_sel, sp - 3, sel_lo, sel_hi, sel_e2, sel_e3),
            (is_br & (b == 1), opbase + c, v0_lo, v0_hi, v0_e2, v0_e3),
            (brnz_taken & (b == 1), opbase + c, v1_lo, v1_hi,
             v1_e2, v1_e3),
            (is_brt & (bt_keep == 1), opbase + bt_pop, v1_lo, v1_hi,
             v1_e2, v1_e3),
            (is_ret & (nres == 1), fp, v0_lo, v0_hi, v0_e2, v0_e3),
            (is_vconst, sp, *vconst_res),
            (is_v2, sp - 2, *v2_res),
            (is_vshift, sp - 2, *vshift_res),
            (is_vshuffle, sp - 2, *vshuf_res),
            (is_vreplace, sp - 2, *vrepl_res),
            (is_v1, sp - 1, *v1_res),
            (is_vsplat, sp - 1, *vsplat_res),
            (is_vextract, sp - 1, vex_lo, vex_hi),
            (is_vtest, sp - 1, vtest_res, zl),
            (is_vbitsel, sp - 3, *vbit_res),
            (is_vload & ~v_oob, sp - 1, *vload_res),
            (is_tget & (table_trap == 0), sp - 1, tget_val,
             jnp.zeros_like(tget_val)),
            (is_cls[CLS_REFFUNC], sp, a + 1, jnp.zeros_like(a)),
            (is_cls[CLS_TABLE_SIZE], sp, tsize_l, jnp.zeros_like(tsize_l)),
            (is_tgrow & (table_trap == 0), sp - 2, tgrow_res,
             jnp.zeros_like(tgrow_res)),
        ]
        if HAS_T0:
            # tier-0 retirements push their errno (or proc_exit code) at
            # the frame's operand base, exactly where the host outcall
            # serve would have written the result
            write_entries.append((t0_push | t0_exit, opbase, t0_val, zl))
        for entry in write_entries:
            m, pos, lo_v, hi_v = entry[0], entry[1], entry[2], entry[3]
            e2_v = entry[4] if len(entry) > 4 else zl
            e3_v = entry[5] if len(entry) > 5 else zl
            wpos = jnp.where(m, pos, wpos)
            wlo = jnp.where(m, lo_v, wlo)
            whi = jnp.where(m, hi_v, whi)
            if HAS_SIMD:
                we2 = jnp.where(m, e2_v, we2)
                we3 = jnp.where(m, e3_v, we3)
            does_write = does_write | m

        wmask = active & does_write & (trap == 0)
        stack_lo = scat(st.stack_lo, wpos, wlo, wmask)
        stack_hi = scat(st.stack_hi, wpos, whi, wmask)
        if HAS_SIMD:
            stack_e2 = scat(st.stack_e2, wpos, we2, wmask)
            stack_e3 = scat(st.stack_e3, wpos, we3, wmask)

        # locals write (set/tee)
        is_lset = is_cls[CLS_LOCAL_SET]
        is_ltee = is_cls[CLS_LOCAL_TEE]
        lmask = active & (is_lset | is_ltee)
        stack_lo = scat(stack_lo, fp + a, v0_lo, lmask)
        stack_hi = scat(stack_hi, fp + a, v0_hi, lmask)
        if HAS_SIMD:
            stack_e2 = scat(stack_e2, fp + a, v0_e2, lmask)
            stack_e3 = scat(stack_e3, fp + a, v0_e3, lmask)

        # tail-call arg slide: [sp_eff - nparams, sp_eff) -> [fp, fp+nparams)
        # (ascending copy is overlap-safe: src row >= dst row always,
        # because src base sp_eff - nparams >= opbase >= fp)
        if HAS_TAIL:
            for k in range(MAX_NPAR):
                amask = tail_ok & (k < c_nparams)
                srcp = sp_eff - c_nparams + k
                stack_lo = scat(stack_lo, fp + k, gat(stack_lo, srcp), amask)
                stack_hi = scat(stack_hi, fp + k, gat(stack_hi, srcp), amask)
                if HAS_SIMD:
                    stack_e2 = scat(stack_e2, fp + k, gat(stack_e2, srcp),
                                    amask)
                    stack_e3 = scat(stack_e3, fp + k, gat(stack_e3, srcp),
                                    amask)

        # zero callee locals beyond params (static unrolled window)
        for k in range(img.max_local_zeros):
            zpos = fp_new + c_nparams + k
            zmask = call_ok & (k < (c_nlocals - c_nparams))
            stack_lo = scat(stack_lo, zpos, jnp.zeros_like(v0_lo), zmask)
            stack_hi = scat(stack_hi, zpos, jnp.zeros_like(v0_hi), zmask)
            if HAS_SIMD:
                stack_e2 = scat(stack_e2, zpos, zl, zmask)
                stack_e3 = scat(stack_e3, zpos, zl, zmask)
        if not HAS_SIMD:
            stack_e2 = st.stack_e2
            stack_e3 = st.stack_e3

        # globals write
        is_gset = is_cls[CLS_GLOBAL_SET]
        gmask = active & is_gset
        gcur_lo = jnp.take_along_axis(st.glob_lo, gidx[None, :], axis=0)[0]
        gcur_hi = jnp.take_along_axis(st.glob_hi, gidx[None, :], axis=0)[0]
        glob_lo = st.glob_lo.at[gidx, lane_iota].set(
            jnp.where(gmask, v0_lo, gcur_lo))
        glob_hi = st.glob_hi.at[gidx, lane_iota].set(
            jnp.where(gmask, v0_hi, gcur_hi))

        # =================== fused dispatch cells ===================
        # one dispatch retires a whole straight-line run's stack
        # effects (batch/fuse.py); fused-lane masks are disjoint from
        # every per-op write mask above (active excludes them), so
        # applying the fused scatters after the per-op ones is exact.
        # Any-lane conditional: steps where no lane sits at a fused
        # head skip the pattern handlers entirely (same rationale as
        # the store scatters above on the CPU backend).
        if FUSE_ON:
            fused_sp = sp
            _stk = tuple([stack_lo, stack_hi] + (
                [stack_e2, stack_e3] if HAS_SIMD else []))

            if HAS_PURE_PAT:
                def _run_fused(ops):
                    stk, gl, gh = ops
                    stk2, (gl2, gh2), fsp = fused_apply(
                        list(stk), (gl, gh), pc, sp, fp,
                        is_fused_pure)
                    return tuple(stk2), gl2, gh2, fsp

                def _skip_fused(ops):
                    stk, gl, gh = ops
                    return stk, gl, gh, sp

                _stk, glob_lo, glob_hi, fused_sp = lax.cond(
                    jnp.any(is_fused_pure), _run_fused, _skip_fused,
                    (_stk, glob_lo, glob_hi))
            if HAS_MEM_PAT:
                # licensed memory runs (r19): same disjoint-mask merge
                # for the stack/global planes; the memory plane itself
                # NEVER rides the conditional's tuple carry (a big
                # buffer there costs a full-plane copy every step on
                # the CPU backend) — the handler reads it and returns
                # per-lane (widx, value, mask) store triples, applied
                # below under the per-op path's run_stores shape
                _zstores = tuple((zl, zl, is_fused_mem & False)
                                 for _ in range(N_MEM_SLOTS))

                def _run_memfused(ops):
                    stk, gl, gh = ops
                    stk2, (gl2, gh2), st_out, fsp = memfuse_apply(
                        list(stk), (gl, gh), mem_plane, pc, sp, fp,
                        is_fused_mem)
                    return tuple(stk2), gl2, gh2, st_out, fsp

                def _skip_memfused(ops):
                    stk, gl, gh = ops
                    return stk, gl, gh, _zstores, sp

                _stk, glob_lo, glob_hi, _mstores, _fsp_mem = \
                    lax.cond(jnp.any(is_fused_mem), _run_memfused,
                             _skip_memfused,
                             (_stk, glob_lo, glob_hi))
                fused_sp = jnp.where(is_fused_mem, _fsp_mem, fused_sp)
                fused_st = is_fused_mem & sthead_t[pc]

                def _apply_mstores(mp):
                    for wi, v, mk in _mstores:
                        mp = scat(mp, wi, v, mk)
                    return mp

                mem_plane = lax.cond(jnp.any(fused_st),
                                     _apply_mstores, lambda mp: mp,
                                     mem_plane)
            stack_lo, stack_hi = _stk[0], _stk[1]
            if HAS_SIMD:
                stack_e2, stack_e3 = _stk[2], _stk[3]

        # =================== compiled-function bodies ===================
        # one dispatch retires a whole promoted CALL (batch/tierup.py);
        # compiled-lane masks are disjoint from every per-op and fused
        # mask above (active/is_fused exclude them), so applying the
        # body's scatters after theirs is exact.  Any-lane conditional:
        # steps where no lane sits at a promoted entry skip the bodies
        # entirely.  The memory plane is READ-ONLY inside (v1 promotes
        # load-only functions) and the opcode histogram rides the
        # conditional so in-body retirement attributes per-pc
        # (histogram == retired, as with fused runs).
        if TIER_ON:
            _cstk = tuple([stack_lo, stack_hi] + (
                [stack_e2, stack_e3] if HAS_SIMD else []))
            _c_hist0 = st.op_hist

            def _run_comp(ops):
                stk, oh = ops
                stk2, oh2, csp, cret, cbail, cbpc, crd, cfd = \
                    tierup_apply(list(stk), mem_plane, oh, pc, sp, fp,
                                 opbase, is_comp)
                return tuple(stk2), oh2, csp, cret, cbail, cbpc, crd, cfd

            def _skip_comp(ops):
                stk, oh = ops
                fl = jnp.bool_(False) & alive
                return stk, oh, sp, fl, fl, pc, zl, zl

            (_cstk, _c_hist, comp_sp, comp_ret, comp_bail, comp_bail_pc,
             comp_rd, comp_fd) = lax.cond(
                jnp.any(is_comp), _run_comp, _skip_comp,
                (_cstk, _c_hist0))
            stack_lo, stack_hi = _cstk[0], _cstk[1]
            if HAS_SIMD:
                stack_e2, stack_e3 = _cstk[2], _cstk[3]

        # =================== merge: sp / pc / frames ===================
        new_sp = sp
        for m, v in (
            (t0_push, opbase + 1),
            (is_const | is_lget | is_gget | is_msize | is_vconst
             | is_cls[CLS_TABLE_SIZE] | is_cls[CLS_REFFUNC], sp + 1),
            (is_cls[CLS_DROP] | is_lset | is_gset | is_alu2 | is_brz
             | (is_brnz & cond_zero) | is_v2 | is_vshift | is_vshuffle
             | is_vreplace | is_tgrow, sp - 1),
            (is_cls[CLS_STORE] | is_sel | is_vstore | is_vbitsel
             | is_cls[CLS_TABLE_SET], sp - 2),
            (is_bulk | is_cls[CLS_TABLE_FILL] | is_cls[CLS_TABLE_COPY]
             | is_cls[CLS_TABLE_INIT] | is_minit, sp - 3),
            (is_br, opbase + c + b),
            (brnz_taken, opbase + c + b),
            (is_brt, opbase + bt_pop + bt_keep),
            (is_ret, fp + nres),
            (call_ok, opbase_new),
        ):
            new_sp = jnp.where(m, v, new_sp)

        new_pc = pc + 1
        new_pc = jnp.where(is_br, a, new_pc)
        new_pc = jnp.where(is_brz & cond_zero, a, new_pc)
        new_pc = jnp.where(brnz_taken, a, new_pc)
        new_pc = jnp.where(is_brt, bt_tgt, new_pc)
        new_pc = jnp.where(call_ok, c_entry, new_pc)
        new_pc = jnp.where(is_ret & ~ret_done, r_pc, new_pc)

        new_fp = jnp.where(call_ok, fp_new, fp)
        new_fp = jnp.where(is_ret & ~ret_done, r_fp, new_fp)
        new_opbase = jnp.where(call_ok, opbase_new, opbase)
        new_opbase = jnp.where(is_ret & ~ret_done, r_opbase, new_opbase)
        new_depth = st.call_depth + jnp.where(call_ok & ~is_tail, 1, 0) \
            - jnp.where(active & is_ret & ~ret_done, 1, 0)

        # =================== traps / fuel / retire ===================
        new_trap = trap
        for m, code in (
            (is_cls[CLS_TRAP], a),
            # park at the stub UNLESS tier 0 retired the call in-kernel;
            # the host outcall loop re-arms parked lanes
            (is_cls[CLS_HOSTCALL] & ~t0_push & ~t0_exit,
             jnp.int32(TRAP_HOSTCALL)),
            # in-kernel proc_exit: the lane terminates; its exit code
            # sits in the result slot (stack[opbase])
            (t0_exit, jnp.int32(int(ErrCode.Terminated))),
            (alu2_trap != 0, alu2_trap),
            (alu1_trap != 0, alu1_trap),
            ((is_load | is_store) & mem_oob,
             jnp.int32(int(ErrCode.MemoryOutOfBounds))),
            ((is_vload | is_vstore) & v_oob,
             jnp.int32(int(ErrCode.MemoryOutOfBounds))),
            (bulk_oob, jnp.int32(int(ErrCode.MemoryOutOfBounds))),
            (mi_oob, jnp.int32(int(ErrCode.MemoryOutOfBounds))),
            (table_trap != 0, table_trap),
            (is_callany & (call_trap != 0), call_trap),
            (ret_done, jnp.int32(TRAP_DONE)),
        ):
            new_trap = jnp.where(active & m, code, new_trap)

        if FUSE_ON:
            # a fused dispatch retires the whole run; each constituent
            # keeps per-op attribution (f_n ops of gas/histogram)
            ret_inc = jnp.where(
                alive, jnp.where(is_fused, f_n, jnp.int32(1)), jnp.int32(0))
        else:
            ret_inc = b2i(active)
        if TIER_ON:
            # a compiled dispatch retires the whole CALL; the body
            # reports the exact per-lane count (bail-outs included)
            ret_inc = jnp.where(is_comp, comp_rd, ret_inc)
        new_retired = st.retired + ret_inc
        if fuel_enabled:
            dec = jnp.where(active, cost_t[pc], 0) if weighted_gas \
                else b2i(active)
            if FUSE_ON:
                # fused lanes are pre-gated on fuel > run cost, so the
                # exhaustion check below (active-only) stays exact
                dec = dec + jnp.where(is_fused, fuse_cost, 0)
            if TIER_ON:
                # compiled lanes: exact per-op gas from the body, also
                # pre-gated (fuel > whole-call worst case)
                dec = dec + jnp.where(is_comp, comp_fd, 0)
            new_fuel = st.fuel - dec
            new_trap = jnp.where(active & (new_fuel <= 0) & (new_trap == 0),
                                 int(ErrCode.CostLimitExceeded), new_trap)
        else:
            new_fuel = st.fuel

        # lanes that trapped THIS step keep their pre-step control state
        halted_now = active & (new_trap != 0)
        new_pc = jnp.where(halted_now, pc, new_pc)
        keep = ~halted_now & active
        pc_out = jnp.where(keep, new_pc, st.pc)
        sp_out = jnp.where(keep, new_sp,
                           jnp.where(ret_done, fp + nres, st.sp))
        if FUSE_ON:
            # fused lanes: pc jumps past the whole run, sp takes the
            # run's net stack effect (fp/opbase/depth never change —
            # fused classes are pure stack/ALU)
            pc_out = jnp.where(is_fused, pc + f_n, pc_out)
            sp_out = jnp.where(is_fused, fused_sp, sp_out)
        fp_out = jnp.where(keep, new_fp, st.fp)
        opbase_out = jnp.where(keep, new_opbase, st.opbase)
        depth_out = jnp.where(keep, new_depth, st.call_depth)
        if TIER_ON:
            # compiled lanes come back RETURNED (the whole call retired:
            # replicate the per-op CLS_RETURN merge — the body never
            # pushed frames, so r_pc/r_fp/r_opbase gathered from the
            # pre-step frame stack are exactly the right pop) or BAILED
            # at a block head (iteration cap: resume per-op mid-function
            # with the body's partial sp/retired/fuel, bit-identically)
            comp_done = comp_ret & (st.call_depth == 0)
            comp_pop = comp_ret & (st.call_depth > 0)
            pc_out = jnp.where(comp_pop, r_pc, pc_out)
            pc_out = jnp.where(comp_bail, comp_bail_pc, pc_out)
            # comp_done lanes keep their pre-step pc (the halted shape:
            # pc_out already defaults to st.pc for non-active lanes)
            sp_out = jnp.where(is_comp, comp_sp, sp_out)
            fp_out = jnp.where(comp_pop, r_fp, fp_out)
            opbase_out = jnp.where(comp_pop, r_opbase, opbase_out)
            depth_out = jnp.where(comp_pop, st.call_depth - 1, depth_out)
            new_trap = jnp.where(comp_done, jnp.int32(TRAP_DONE),
                                 new_trap)

        # device-side obs planes: per-pc retired histogram (attributed
        # to every CONSTITUENT op of a fused run — histogram == retired
        # by construction) and the fused/unfused dispatch counters.
        # Both are trace-time static: None planes compile to nothing.
        op_hist_p = _c_hist if (TIER_ON and st.op_hist is not None) \
            else st.op_hist
        if st.op_hist is not None:
            H = st.op_hist.shape[0]
            if FUSE_ON:
                hln = jnp.where(is_fused, f_n, jnp.int32(1))
                if TIER_ON:
                    # compiled lanes attributed in-body (per block
                    # execution count -> per constituent pc)
                    hln = jnp.where(is_comp, jnp.int32(0), hln)
                for j in range(MAX_F):
                    op_hist_p = op_hist_p.at[
                        jnp.clip(pc + j, 0, H - 1)].add(
                        b2i(alive & (j < hln)))
            else:
                hm = (alive & ~is_comp) if TIER_ON else alive
                op_hist_p = op_hist_p.at[jnp.clip(pc, 0, H - 1)].add(
                    b2i(hm))
        fu_ctr_p = st.fu_ctr
        if st.fu_ctr is not None:
            if FUSE_ON:
                fu_ctr_p = st.fu_ctr + jnp.stack([
                    jnp.sum(b2i(is_fused)),
                    jnp.sum(jnp.where(is_fused, f_n, 0)),
                    jnp.sum(ret_inc)])
            else:
                # a fused-plane state resumed on an unfused build (the
                # supervisor's demotion rung) keeps the total-retired
                # row live so the plane is never an identity
                # passthrough in the donated carry
                fu_ctr_p = st.fu_ctr + jnp.stack([
                    jnp.int32(0), jnp.int32(0), jnp.sum(ret_inc)])
        tu_ctr_p = st.tu_ctr
        if st.tu_ctr is not None:
            if TIER_ON:
                tu_ctr_p = st.tu_ctr + jnp.stack([
                    jnp.sum(b2i(is_comp)),
                    jnp.sum(jnp.where(is_comp, comp_rd, 0)),
                    jnp.sum(ret_inc)])
            else:
                # same liveness discipline as fu_ctr for states resumed
                # on a tierup-off build (the simt_nocomp demotion rung)
                tu_ctr_p = st.tu_ctr + jnp.stack([
                    jnp.int32(0), jnp.int32(0), jnp.sum(ret_inc)])
        return BatchState(
            pc=pc_out,
            sp=sp_out,
            fp=fp_out,
            opbase=opbase_out,
            call_depth=depth_out,
            trap=new_trap,
            retired=new_retired,
            fuel=new_fuel,
            mem_pages=new_mem_pages,
            stack_lo=stack_lo,
            stack_hi=stack_hi,
            fr_ret_pc=fr_ret_pc,
            fr_fp=fr_fp,
            fr_opbase=fr_opbase,
            glob_lo=glob_lo,
            glob_hi=glob_hi,
            mem=mem_plane,
            stack_e2=stack_e2,
            stack_e3=stack_e3,
            tab=tab_p,
            tsize=tsize_p,
            edrop=edrop_p,
            ddrop=ddrop_p,
            # t0_time stays None in the carried state (it rides the
            # chunk as a separate non-donated argument)
            t0_ctr=t0_ctr_p,
            so_buf=so_buf_p,
            so_off=so_off_p,
            op_hist=op_hist_p,
            fu_ctr=fu_ctr_p,
            tu_ctr=tu_ctr_p,
        )

    return step


class BatchEngine:
    """Runs one module's exported function over N lanes in lockstep.

    Engine-facing analog of Executor::invoke for the tpu_batch engine
    (SURVEY.md §2.10): construct from an instantiated module, call run()
    with per-lane argument arrays.
    """

    def __init__(self, inst, store=None, conf=None, lanes: Optional[int] = None,
                 mesh=None, img=None):
        from wasmedge_tpu.common.configure import Configure
        from wasmedge_tpu.batch.image import batchability, build_device_image

        self.mesh = mesh  # lane-sharded multi-chip execution (parallel/mesh.py)
        self.conf = conf or Configure()
        cfg = self.conf.batch
        self.cfg = cfg
        self.lanes = lanes or cfg.lanes
        self.inst = inst
        self.store = store  # kept for re-deriving engines (scheduler)
        self.hostcall_stats = new_hostcall_stats()
        # flight recorder (obs/): the shared ring when conf.obs is
        # enabled, the no-op guard object otherwise
        from wasmedge_tpu.obs.recorder import recorder_of

        self.obs = recorder_of(self.conf)
        # divergence-aware lane compaction (batch/compact.py): armed
        # per run by the fixed-cohort drivers (run/ShardDrive/uniform
        # handoff/supervisor SIMT tier); the serving layer sets
        # _compact_external and owns its own compactor instead, so the
        # engine never permutes under a server's lane bindings
        self.compactor = None
        if img is not None:
            # share an already-built (and already-normalized) image — the
            # scheduler derives width-variant engines from one module
            self.img = img
            self._t0kinds = self._t0_gate(t0_effective_kinds(img, cfg))
            self._step = None
            self._run_chunk = None
            return
        host_imports = {i for i, f in enumerate(inst.funcs)
                        if getattr(f, "kind", None) == "host"}
        reason = batchability(inst.lowered, host_imports=host_imports,
                              n_memories=len(inst.memories or ()))
        if reason is not None:
            raise ValueError(f"module not batchable: {reason}")
        self.img = build_device_image(
            inst.lowered, memories=inst.memories, globals_=inst.globals,
            table0=self._table_snapshot(inst, store), mod=inst.ast,
            elem_segs=self._elem_snapshot(inst, store),
            data_segs=[bytes(d.data) for d in inst.datas])
        # Per-lane table capacity for table.grow, mirroring the memory
        # knob clamp below: declared max wins, clamped by the Configure
        # knob; grow beyond capacity returns -1 (spec-legal failure).
        tsize0 = self.img.table0.shape[0]
        if self.img.has_table_grow:
            declared = self.img.table_max if self.img.table_max > 0 \
                else cfg.table_elems_per_lane
            self.img.table_cap = max(
                tsize0, min(declared, cfg.table_elems_per_lane))
            # table.grow checks capacity from its instruction word (b):
            # per-table in a concatenated multi-tenant image
            self.img.b[self.img.cls == CLS_TABLE_GROW] = self.img.table_cap
        else:
            self.img.table_cap = tsize0
        # Static per-lane memory ceiling: the declared max clamped by the
        # Configure knob (scalar analog: MemoryInstance.grow page_limit).
        # A module with no declared max (mem_pages_max == 0) gets the knob
        # value — growth beyond memory_pages_per_lane returns -1, which is
        # the one place batch semantics are knob-dependent (static HBM
        # allocation; set the knob >= the workload's peak for parity).
        if self.img.has_memory:
            declared = self.img.mem_pages_max \
                if self.img.mem_pages_max > 0 else cfg.memory_pages_per_lane
            self.img.mem_pages_max = max(
                self.img.mem_pages_init,
                min(declared, cfg.memory_pages_per_lane))
        # type-level checks run unconditionally: a module can carry
        # v128-typed globals/signatures without any v128 OPCODE (pure
        # moves), and the 2-plane cells would silently truncate them
        from wasmedge_tpu.common.types import ValType

        for g in inst.globals:
            if g.type.val_type == ValType.V128:
                raise ValueError(
                    "module not batchable: v128-typed global")
        self._t0kinds = self._t0_gate(t0_effective_kinds(self.img, cfg))
        self._step = None
        self._run_chunk = None

    def _plan_fusion(self):
        """Run the superinstruction translation pass once per image
        (batch/fuse.py): the analyzer's top candidates become fused
        dispatch cells in new image planes.  Knob off = never planned =
        the step builder compiles the bit-identical seed path.

        Deferred to first _build() / obs-on initial_state / ladder
        gating / image concat rather than engine construction: planning
        dereferences the image's LAZY analysis binding, and a merely-
        constructed engine (batchability probes, registry stash) must
        keep the r12 guarantee that startups which never compile a step
        never pay the analyzer.  Idempotent (fusion_report sentinel)."""
        if not getattr(self.cfg, "fuse_superinstructions", True):
            return
        if getattr(self.img, "fusion_report", None) is not None:
            return  # already planned (shared image)
        from wasmedge_tpu.batch.fuse import plan_fusion

        plan_fusion(self.img, self.cfg)
        # licensed-vs-reverted memory-run counters for the Prometheus
        # export (planning statics — the device fu_ctr plane already
        # counts fused dispatches at runtime)
        mem = (self.img.fusion_report or {}).get("memory")
        if mem and self.obs.enabled:
            self.obs.set_memfuse_static(mem)

    def _plan_tierup(self):
        """Run the whole-function promotion pass once per image
        (batch/tierup.py), AFTER _plan_fusion — hot-function selection
        reads the realized fusion plan.  Same lazy/idempotent
        discipline as _plan_fusion (tierup_report sentinel); knob off =
        never planned = the step builder compiles the bit-identical
        seed/fused path."""
        if not getattr(self.cfg, "tierup", True):
            return
        if getattr(self.img, "tierup_report", None) is not None:
            return  # already planned (shared image)
        self._plan_fusion()
        from wasmedge_tpu.batch.tierup import plan_tierup

        plan_tierup(self.img, self.cfg)
        rep = self.img.tierup_report or {}
        if rep and self.obs.enabled:
            self.obs.set_tierup_static(rep)

    def _t0_gate(self, kinds):
        """Engine-level tier-0 gating: fd_write buffering additionally
        requires that the instance's WASI environ has fds 1/2 as plain
        writable sinks at engine-build time (the image-level gate
        already excludes modules that could mutate the fd table)."""
        from wasmedge_tpu.batch.image import T0_FD_WRITE

        if kinds is None or not (kinds == T0_FD_WRITE).any():
            return kinds
        from wasmedge_tpu.batch.hostcall import wasi_env_of
        from wasmedge_tpu.host.wasi.wasi_abi import Rights

        env = wasi_env_of(self)
        ok = env is not None
        for fd in (1, 2):
            e = env.fds.get(fd) if ok else None
            ok = ok and e is not None and e.kind in ("stdio", "file") \
                and bool(e.rights_base & Rights.FD_WRITE)
        if not ok:
            kinds = kinds.copy()
            kinds[kinds == T0_FD_WRITE] = 0
            if not (kinds != 0).any():
                return None
        return kinds

    @staticmethod
    def _table_snapshot(inst, store):
        """Table image: store-interned handles -> funcidx+1 (0 = null).

        Cross-module refs are unresolvable on device; batchability() already
        gates modules whose tables could contain them (no table mutation,
        active elems only reference local funcs)."""
        if not inst.tables:
            return None
        func_index = {id(f): i for i, f in enumerate(inst.funcs)}
        refs = []
        for h in inst.tables[0].refs:
            if h == 0:
                refs.append(0)
                continue
            fi = store.deref_func(h) if store is not None else None
            idx = func_index.get(id(fi)) if fi is not None else None
            if idx is None:
                raise ValueError("table entry references a non-local function; "
                                 "module not batchable")
            refs.append(idx + 1)
        return refs

    @staticmethod
    def _elem_snapshot(inst, store):
        """Element segments resolved into the device funcref domain
        (funcidx+1, 0 = null) for in-kernel table.init.  A segment
        holding a cross-module ref only blocks modules that can reach it
        (table.init); others keep batching with that segment omitted."""
        from wasmedge_tpu.common.opcodes import Op

        func_index = {id(f): i for i, f in enumerate(inst.funcs)}
        segs = []
        ops = np.asarray(inst.lowered.op[:inst.lowered.code_len])
        needs = bool((ops == int(Op.table_init)).any())
        for seg in inst.elems:
            refs = []
            bad = False
            for h in seg.refs:
                if h == 0:
                    refs.append(0)
                    continue
                fi = store.deref_func(h) if store is not None else None
                idx = func_index.get(id(fi)) if fi is not None else None
                if idx is None:
                    bad = True
                    break
                refs.append(idx + 1)
            if bad:
                if needs:
                    raise ValueError(
                        "element segment references a non-local function; "
                        "module not batchable")
                refs = []
            segs.append(refs)
        return segs

    # -- execution ---------------------------------------------------------
    def _build(self):
        from wasmedge_tpu.batch import ensure_jax_backend

        self._plan_fusion()
        self._plan_tierup()
        ensure_jax_backend()
        import jax
        import jax.numpy as jnp
        from jax import lax

        step = _make_step(self.img, self.cfg, self.lanes,
                          t0kinds=getattr(self, "_t0kinds", None))
        chunk = self.cfg.steps_per_launch

        def run_chunk(state, t0_time):
            # the obs planes (op_hist / fu_ctr) are carried and updated
            # by step() itself when allocated (obs_state_planes); a
            # None plane compiles the exact seed loop

            def cond(carry):
                i, s = carry
                return (i < chunk) & jnp.any(s.trap == 0)

            def body(carry):
                i, s = carry
                return i + 1, step(s, t0_time)

            i, state = lax.while_loop(cond, body, (jnp.int32(0), state))
            return i, state

        # jax 0.4.x CPU: an executable deserialized from the persistent
        # compilation cache can lose input/output aliasing for donated
        # carries and serve garbage outputs (observed with the r06
        # tier-0 planes in the carry).  Donation only saves allocator
        # churn on CPU; keep it for accelerator backends where it keeps
        # the big planes in place.
        donate = (0,)
        if jax.default_backend() == "cpu" and \
                getattr(jax.config, "jax_compilation_cache_dir", None):
            donate = ()
        if self.mesh is not None:
            # single-program mesh drive: ONE jitted program over the
            # named mesh, lane planes sharded on the `lanes` axis — the
            # chunk body above runs per-shard unchanged
            from wasmedge_tpu.parallel.shard_drive import \
                _build_shard_chunk

            probe = self.initial_state(0, [])
            self._run_chunk = _build_shard_chunk(run_chunk, self.mesh,
                                                 probe, donate)
        else:
            self._run_chunk = jax.jit(run_chunk, donate_argnums=donate)
        self._step = step

    def _build_narrow_chunk(self, width: int):
        """Chunk loop at a live-prefix dispatch width < lanes (lane
        compaction's narrowing rung, batch/compact.py): the step
        retraces at `width`, each launch slices the live prefix out of
        the full-width state, drives it, and writes it back in place.
        Lanes beyond the prefix are guaranteed dead (trap != 0 and
        never TRAP_HOSTCALL) by the compactor's sort, so skipping them
        cannot change any observable state; laneless obs planes
        (op_hist, fu_ctr) ride the narrow loop and replace the full
        state's copies wholesale.

        jit-purity lint target (tools/lint_jit_purity.py): everything
        nested here runs under trace.
        """
        from wasmedge_tpu.batch import ensure_jax_backend

        ensure_jax_backend()
        import jax
        import jax.numpy as jnp
        from jax import lax

        step = _make_step(self.img, self.cfg, width,
                          t0kinds=getattr(self, "_t0kinds", None))
        chunk = self.cfg.steps_per_launch
        lanes = self.lanes

        def run_chunk_narrow(state, t0_time):
            fields = {}
            lane_fields = []
            for name in state._fields:
                p = getattr(state, name)
                if p is None:
                    fields[name] = None
                elif p.ndim and p.shape[-1] == lanes:
                    lane_fields.append(name)
                    fields[name] = p[..., :width]
                else:
                    fields[name] = p
            ns = BatchState(**fields)

            def cond(carry):
                i, s = carry
                return (i < chunk) & jnp.any(s.trap == 0)

            def body(carry):
                i, s = carry
                return i + 1, step(s, t0_time)

            i, ns = lax.while_loop(cond, body, (jnp.int32(0), ns))
            updates = {}
            for name in state._fields:
                p = getattr(state, name)
                if p is None:
                    continue
                if name in lane_fields:
                    updates[name] = p.at[..., :width].set(
                        getattr(ns, name))
                else:
                    updates[name] = getattr(ns, name)
            return i, state._replace(**updates)

        donate = (0,)
        if jax.default_backend() == "cpu" and \
                getattr(jax.config, "jax_compilation_cache_dir", None):
            donate = ()
        return jax.jit(run_chunk_narrow, donate_argnums=donate)

    def initial_state(self, func_idx: int, args_lanes: List[np.ndarray]):
        import jax.numpy as jnp

        obs_conf = getattr(self.conf, "obs", None)
        if obs_conf is not None and obs_conf.enabled:
            # the fu_ctr/tu_ctr allocation decisions (obs_state_planes)
            # need the translation/promotion passes to have run; obs-off
            # states defer them to _build() with the rest of the step
            # compile
            self._plan_fusion()
            self._plan_tierup()
        cfg = self.cfg
        L = self.lanes
        img = self.img
        meta = self.inst.lowered.funcs[func_idx]
        D = cfg.value_stack_depth
        CD = cfg.call_stack_depth
        stack_lo, stack_hi = pack_lane_args(args_lanes, L, D)
        mem_words = max(img.mem_pages_max * _PAGE_WORDS, 1)
        mem = np.zeros((mem_words, L), np.int32)
        if img.mem_init.shape[0] > 1 or img.mem_pages_init:
            mem[: img.mem_init.shape[0]] = img.mem_init[:, None]
        fuel0 = cfg.fuel_per_launch if cfg.fuel_per_launch is not None else 0
        return BatchState(
            pc=jnp.full((L,), meta.entry_pc, jnp.int32),
            sp=jnp.full((L,), meta.nlocals + 0, jnp.int32),
            fp=jnp.zeros((L,), jnp.int32),
            opbase=jnp.full((L,), meta.nlocals, jnp.int32),
            call_depth=jnp.zeros((L,), jnp.int32),
            trap=jnp.zeros((L,), jnp.int32),
            retired=jnp.zeros((L,), jnp.int32),
            fuel=jnp.full((L,), fuel0, jnp.int32),
            mem_pages=jnp.full((L,), img.mem_pages_init, jnp.int32),
            stack_lo=jnp.asarray(stack_lo),
            stack_hi=jnp.asarray(stack_hi),
            fr_ret_pc=jnp.zeros((CD, L), jnp.int32),
            fr_fp=jnp.zeros((CD, L), jnp.int32),
            fr_opbase=jnp.zeros((CD, L), jnp.int32),
            glob_lo=jnp.asarray(np.repeat(img.globals_lo[:, None], L, axis=1)),
            glob_hi=jnp.asarray(np.repeat(img.globals_hi[:, None], L, axis=1)),
            mem=jnp.asarray(mem),
            stack_e2=jnp.zeros((D, L), jnp.int32) if img.has_simd else None,
            stack_e3=jnp.zeros((D, L), jnp.int32) if img.has_simd else None,
            **r05_state_planes(img, L),
            **t0_state_planes(img, cfg, L,
                              kinds=getattr(self, "_t0kinds", None)),
            **obs_state_planes(self.conf, img, mesh=self.mesh),
        )

    def run(self, func_name: str, args_lanes: List[np.ndarray],
            max_steps: int = 10_000_000) -> BatchResult:
        func_idx = self.export_func_idx(func_name)
        if self._run_chunk is None:
            self._build()
        self.hostcall_stats = new_hostcall_stats()
        # a fresh run is a fresh output stream: both cursor halves reset
        from wasmedge_tpu.batch.hostcall import stdout_cursor_reset

        stdout_cursor_reset(self)
        # divergence-aware lane compaction (batch/compact.py): fresh
        # identity mapping per cohort run; off = None = seed path
        from wasmedge_tpu.batch.compact import arm

        arm(self)
        state = self.initial_state(func_idx, args_lanes)
        if self.mesh is not None:
            from wasmedge_tpu.parallel.mesh import shard_batch_state

            state = shard_batch_state(state, self.mesh)
        state, total = self.run_from_state(state, 0, max_steps)
        nres = int(self.inst.lowered.funcs[func_idx].nresults)
        stack_lo = np.asarray(state.stack_lo)
        stack_hi = np.asarray(state.stack_hi)
        # compaction moved lanes: gather mirrors back to original order
        from wasmedge_tpu.batch.compact import restore_mirrors

        stack_lo, stack_hi, trap, retired = restore_mirrors(
            self.compactor, stack_lo, stack_hi,
            np.asarray(state.trap), np.asarray(state.retired))
        results = []
        for r in range(nres):
            lo = stack_lo[r].view(np.uint32).astype(np.uint64)
            hi = stack_hi[r].view(np.uint32).astype(np.uint64)
            results.append((lo | (hi << np.uint64(32))).view(np.int64))
        return BatchResult(
            results=results,
            trap=trap,
            retired=retired,
            steps=total,
        )

    def resolve_func(self, k: int):
        """Concatenated-image func index -> FunctionInstance (overridden by
        the multi-tenant engine, batch/multitenant.py)."""
        return self.inst.funcs[k]

    def export_func_idx(self, func_name: str) -> int:
        """Engine-global function index of an exported batch entry, with
        the shared entry guard (v128 params/results cannot ride the
        64-bit lane cells).  The serving layer's LaneRecycler resolves
        names through this seam so multi-module engines
        (batch/multitenant.py) can rebase qualified names onto the
        concatenated index space.  Raises KeyError for an unknown
        export, ValueError for a v128 signature."""
        return check_batch_entry(self.inst, func_name)

    def func_nresults(self, func_idx: int) -> int:
        """Result arity of an engine-global function index (the other
        half of the export_func_idx seam)."""
        return int(self.inst.lowered.funcs[func_idx].nresults)

    def run_from_state(self, state, total: int, max_steps: int):
        """Chunk loop from an arbitrary state (used directly and by the
        uniform/pallas engines\' divergence handoff), serving host
        outcalls between chunks (batch/hostcall.py)."""
        import jax.numpy as jnp

        from wasmedge_tpu.batch.hostcall import (
            flush_stdout_buffers, serve_batch_state)

        if self._run_chunk is None:
            self._build()
        t0_active = state.t0_ctr is not None
        if t0_active:
            ctr_in = np.asarray(state.t0_ctr, np.int64).sum(axis=1)
        dummy_time = np.zeros((2, 2), np.int32)
        # deterministic fault seam (testing/faults.py): the supervisor
        # arms this before a launch / a tier-1 serve so injected device
        # and host failures raise exactly where real ones would
        fault = getattr(self, "_fault_hook", None)
        # shadow-audit seam (wasmedge_tpu/integrity/audit.py, r24):
        # pre snapshots sampled lane columns before the launch donates
        # the state, post replays the slice and compares bit-exact —
        # a divergence raises out of this loop like a device failure
        auditor = getattr(self, "_audit_hook", None)
        # bit-flip seam (testing/faults.py BitFlip): corrupts the
        # landed state BEFORE the audit's post-slice gather, modelling
        # SDC the audit must catch rather than an error it is told of
        flip = getattr(self, "_flip_hook", None)
        # cooperative cancellation (parallel/supervisor.py): when a mesh
        # run is doomed, sibling devices stop at their next launch
        # boundary instead of driving the slice to completion
        cancel = getattr(self, "_cancel_hook", None)
        # per-device trace attribution for mesh drives (else "simt")
        track = getattr(self, "obs_track", "simt")
        # launch-boundary mirror seam (parallel/shard_drive.py): the
        # single-program mesh drive emits per-shard mesh_round spans
        # from the trap mirror this loop already gathers every round
        round_hook = getattr(self, "_round_hook", None)
        obs = self.obs
        # divergence-aware lane compaction (batch/compact.py): armed by
        # the cohort drivers only — a serving engine's compactor is
        # always None (the server remaps its own binding tables)
        comp = self.compactor
        if obs.enabled:
            prev_ret = int(np.asarray(state.retired, np.int64).sum())
        while total < max_steps:
            if cancel is not None and cancel():
                break
            if comp is not None:
                state = comp.boundary(self, state)
            # per-relaunch time base: host->device only, no round trip
            # (rides the launch as a non-donated argument)
            tt = jnp.asarray(t0_time_planes() if t0_active else dummy_time)
            audit_tok = auditor.pre(self, state, tt) \
                if auditor is not None else None
            if fault is not None:
                fault("launch", total=total)
            t_launch = obs.now()
            run_chunk = self._run_chunk if comp is None \
                else comp.chunk_fn(self)
            done_steps, state = run_chunk(state, tt)
            total += int(done_steps)
            if flip is not None:
                state = flip("corrupt_plane", state, lanes=self.lanes,
                             total=total)
            if audit_tok is not None:
                auditor.post(self, audit_tok, state, int(done_steps))
            if comp is not None:
                comp.note_launch(int(done_steps))
            trap_host = np.asarray(state.trap)
            parked = int((trap_host == TRAP_HOSTCALL).sum())
            if round_hook is not None:
                round_hook(int(done_steps), trap_host, t_launch)
            if obs.enabled:
                # per-launch span with lane occupancy + retired delta
                # (one extra device read per LAUNCH, never per step)
                live = int((trap_host == 0).sum())
                ret = int(np.asarray(state.retired, np.int64).sum())
                obs.span("launch", t_launch, cat="engine", track=track,
                         steps=int(done_steps), live_lanes=live,
                         parked_lanes=parked,
                         retired_delta=ret - prev_ret)
                prev_ret = ret
                obs.counter("live_lanes", live)
                obs.counter("hostcall_queue_depth", parked)
                # per-round convergence metrics (ROADMAP #6a): unique
                # active pcs + largest convergent group among live
                # lanes, one extra [lanes] pc read per launch
                if live:
                    pcs = np.asarray(state.pc)[trap_host == 0]
                    _, counts = np.unique(pcs, return_counts=True)
                    obs.observe_convergence(
                        int(counts.size), float(counts.max()) / live)
            if parked:
                if fault is not None:
                    fault("serve", total=total)
                t_serve = obs.now()
                state = serve_batch_state(self, state)
                obs.span("serve", t_serve, cat="engine", track=track,
                         lanes=parked)
                continue
            if not (trap_host == 0).any():
                break
            if int(done_steps) == 0:
                break
        # Never leak the internal TRAP_HOSTCALL sentinel to callers: if the
        # step budget ran out with lanes parked at a stub, serve those
        # pending calls once — the lanes come back as trap == 0 ("still
        # running when max_steps ran out"), the documented semantic.
        if (np.asarray(state.trap) == TRAP_HOSTCALL).any():
            t_serve = obs.now()
            state = serve_batch_state(self, state)
            obs.span("serve", t_serve, cat="engine", track=track)
        state = flush_stdout_buffers(self, state)
        state = self._fold_op_hist(state)
        state = self._fold_fuse_ctr(state)
        state = self._fold_tierup_ctr(state)
        if t0_active:
            ctr = np.asarray(state.t0_ctr, np.int64).sum(axis=1) - ctr_in
            st_ = self.hostcall_stats
            st_["tier0_clock"] += int(ctr[0])
            st_["tier0_random"] += int(ctr[1])
            st_["tier0_fd_write"] += int(ctr[2])
            st_["tier0_sys"] += int(ctr[3])
            st_["tier0_calls"] += int(ctr.sum())
        return state, total

    def _fold_op_hist(self, state):
        """Fold + reset the device opcode-histogram plane: per-pc counts
        map through img.op_id into the Statistics cost_table opcode
        domain and land on the flight recorder (VM.execute_batch folds
        them onward into its Statistics)."""
        if getattr(state, "op_hist", None) is None:
            return state
        import jax.numpy as jnp

        from wasmedge_tpu.validator.image import NUM_LOPS

        pc_counts = np.asarray(state.op_hist, np.int64)
        if pc_counts.any():
            out = np.zeros(NUM_LOPS, np.int64)
            np.add.at(out, np.asarray(self.img.op_id, np.int64),
                      pc_counts)
            self.obs.add_opcode_counts(out)
            state = state._replace(op_hist=jnp.zeros_like(state.op_hist))
        return state

    def _fold_fuse_ctr(self, state):
        """Fold + reset the fusion counter plane ([dispatches,
        retired-through-fused-cells, total retired]) into the flight
        recorder; the Prometheus export renders the fused/unfused
        retired split from it (obs/metrics.py)."""
        if getattr(state, "fu_ctr", None) is None:
            return state
        import jax.numpy as jnp

        ctr = np.asarray(state.fu_ctr, np.int64)
        if ctr.any():
            self.obs.add_fused_counts(int(ctr[0]), int(ctr[1]),
                                      int(ctr[2]))
            state = state._replace(fu_ctr=jnp.zeros_like(state.fu_ctr))
        return state

    def _fold_tierup_ctr(self, state):
        """Fold + reset the tier-up counter plane ([compiled-body
        dispatches, retired-through-compiled-bodies, total retired])
        into the flight recorder; the Prometheus export renders the
        compiled/interpreted retired split from it (obs/metrics.py)."""
        if getattr(state, "tu_ctr", None) is None:
            return state
        import jax.numpy as jnp

        ctr = np.asarray(state.tu_ctr, np.int64)
        if ctr.any():
            self.obs.add_tierup_counts(int(ctr[0]), int(ctr[1]),
                                       int(ctr[2]))
            state = state._replace(tu_ctr=jnp.zeros_like(state.tu_ctr))
        return state
