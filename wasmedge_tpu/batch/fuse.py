"""SIMT-tier superinstruction fusion: the ROADMAP #2 translation half.

The discovery half (r12) ranks straight-line opcode n-grams as
superinstruction candidates (`ModuleAnalysis.superinstructions`, keyed
by `saved_dispatches`, loop-weighted by the CFG's `in_loop` marking).
This module translates them: `plan_fusion` rewrites the top-K
candidates' pc runs into fused dispatch cells — new DeviceImage planes
(`fuse_len`, `fuse_pat`) naming, at each run HEAD, how many ops one
`_make_step` dispatch retires and which specialized pattern handler
does it — and `make_fused_apply` builds that handler at trace time by
symbolically executing the pattern's stack effects (intermediates live
in registers; one plane write-back per produced cell instead of one
gather/scatter round per op).

Safety rules (exactly the r12 CFG's):

  - a run never spans a branch, call, branch target, or block
    terminator — runs live strictly inside one basic block, and blocks
    split at leaders, so fusion cannot change control-flow
    observability;
  - only pure stack/ALU op classes fuse (const, local/global
    get/set/tee, drop/select, non-trapping alu1/alu2) — a fused run
    cannot trap mid-flight;
  - the original per-pc cells are never overwritten: a lane whose pc
    sits mid-run (SIMT residue handoff, hostcall re-arm, hv swap-in
    restore, checkpoint resume) executes the per-op stream until the
    next head, and a lane without the fuel to retire the whole run
    steps through the originals so gas exhaustion lands at the correct
    op with per-op attribution — bit-exactness against the scalar
    engine holds unconditionally (tests/test_fuse.py).

Each constituent op keeps its `op_id`: the opcode histogram and the
weighted-gas meter attribute per CONSTITUENT under fusion (histogram ==
retired is pinned by test).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from wasmedge_tpu.batch.image import (
    ALU1_SUB,
    ALU2_F64_BASE,
    ALU2_I32_BASE,
    ALU2_I64_BASE,
    CLS_ALU1,
    CLS_ALU2,
    CLS_CONST,
    CLS_DROP,
    CLS_GLOBAL_GET,
    CLS_GLOBAL_SET,
    CLS_LOAD,
    CLS_LOCAL_GET,
    CLS_LOCAL_SET,
    CLS_LOCAL_TEE,
    CLS_NOP,
    CLS_SELECT,
    CLS_STORE,
    CLS_VLOAD,
    CLS_VSTORE,
    _F64_BIN,
    _I32_BIN,
)

# -- eligibility ------------------------------------------------------------
# Pure stack-motion classes: no memory, no control, no traps.
_PURE_CLS = frozenset((CLS_NOP, CLS_CONST, CLS_LOCAL_GET, CLS_LOCAL_SET,
                       CLS_LOCAL_TEE, CLS_GLOBAL_GET, CLS_GLOBAL_SET,
                       CLS_DROP, CLS_SELECT))

# ALU2 subs that can trap (integer division families) or run under an
# any-lane heavy conditional in the main step (iterative f64 div) stay
# on the per-op path.
_DIV_REM = ("div_s", "div_u", "rem_s", "rem_u")
_ALU2_BLOCKED = frozenset(
    {ALU2_I32_BASE + _I32_BIN.index(n) for n in _DIV_REM}
    | {ALU2_I64_BASE + _I32_BIN.index(n) for n in _DIV_REM}
    | {ALU2_F64_BASE + _F64_BIN.index("div")})

# ALU1: the non-saturating float->int truncations trap
# (laneops.alu1_trap_fns); f64.sqrt is the any-lane heavy kernel.
_ALU1_BLOCKED = frozenset(
    ALU1_SUB[n] for n in (
        "i32.trunc_f32_s", "i32.trunc_f32_u",
        "i32.trunc_f64_s", "i32.trunc_f64_u",
        "i64.trunc_f32_s", "i64.trunc_f32_u",
        "i64.trunc_f64_s", "i64.trunc_f64_u",
        "f64.sqrt",
    ) if n in ALU1_SUB)

# Hard ceiling on merged pattern tables for concatenated multi-tenant
# images (per-module planning is already capped by cfg.fuse_max_patterns).
CONCAT_MAX_PATTERNS = 16


def cell_eligible(cls: int, sub: int) -> bool:
    """May the device cell (cls, sub) join a fused run?"""
    if cls in _PURE_CLS:
        return True
    if cls == CLS_ALU1:
        return sub not in _ALU1_BLOCKED
    if cls == CLS_ALU2:
        return sub not in _ALU2_BLOCKED
    return False


# -- memory-run cells (r19) -------------------------------------------------
# A load/store may join a fused run ONLY at a pc the abstract
# interpreter licensed (analysis/absint.py: the access is proven
# in-bounds against the module's minimum memory and aligned enough to
# never straddle a device word — it can never trap).  Pattern cells
# for memory ops encode the STATIC width/flags instead of `sub` (the
# sub plane is 0 for loads/stores; width lives in the b/c planes):
#
#   (CLS_LOAD,   nbytes | signed << 8 | is64 << 9)
#   (CLS_STORE,  nbytes)
#   (CLS_VLOAD,  16)    (v128: license requires word alignment, so the
#   (CLS_VSTORE, 16)     access is exactly four whole device words)
#
# so each pattern handler compiles a width-specialized access.
_MEM_CLS = (CLS_LOAD, CLS_STORE, CLS_VLOAD, CLS_VSTORE)


def mem_cell_key(img, pc: int):
    """Pattern-cell encoding for the load/store at `pc`."""
    cls = int(img.cls[pc])
    if cls == CLS_LOAD:
        return (CLS_LOAD, int(img.b[pc]) | (int(img.c[pc]) << 8))
    if cls in (CLS_VLOAD, CLS_VSTORE):
        return (cls, 16)
    return (CLS_STORE, int(img.b[pc]))


def pattern_has_mem(pat) -> bool:
    """Does a fused pattern contain load/store cells?  (Such patterns
    are compiled by make_memfuse_apply, never by make_fused_apply.)"""
    return any(cl in _MEM_CLS for cl, _ in pat)


def _mem_cell_ok(img, pc: int, licensed) -> bool:
    """May the cell at `pc` join a MEMORY run?  Pure-eligible cells
    always can; loads/stores only with an absint license."""
    cls = int(img.cls[pc])
    if cls in _MEM_CLS:
        return pc in licensed
    return cell_eligible(cls, int(img.sub[pc]))


def fusion_active(img, cfg) -> bool:
    """Will `_make_step(img, cfg, ...)` compile fused dispatch cells?
    Shared by the step builder, the obs counter-plane allocator, and
    the supervisor's ladder gating so they can never disagree."""
    if not getattr(cfg, "fuse_superinstructions", True):
        return False
    flen = getattr(img, "fuse_len", None)
    return flen is not None and bool((np.asarray(flen) >= 2).any())


# -- the translation pass ---------------------------------------------------

def _candidate_divergence(analysis) -> dict:
    """ops-tuple -> mean r12 block-divergence score over the blocks
    where the candidate occurs (the analyzer's block_ngrams metadata
    indexes candidates by their position in the FULL superinstructions
    list).  Candidates never seen in any block map to 0.0."""
    sums: dict = {}
    counts: dict = {}
    keys = [tuple(c["ops"]) for c in analysis.superinstructions]
    for f in analysis.funcs:
        for bi, present in enumerate(getattr(f, "block_ngrams", ())):
            score = f.block_divergence[bi] \
                if bi < len(f.block_divergence) else 0
            for ci in present:
                if 0 <= ci < len(keys):
                    k = keys[ci]
                    sums[k] = sums.get(k, 0.0) + float(score)
                    counts[k] = counts.get(k, 0) + 1
    return {k: sums[k] / counts[k] for k in sums}


def plan_fusion(img, cfg=None, analysis=None) -> dict:
    """Rewrite the top-K analyzer candidates' pc runs into fused cells.

    Mutates `img` in place (fuse_len / fuse_pat / fuse_patterns /
    fusion_report) and returns the report.  Pure numpy/python — no jax
    import, so the analyze CLI can plan without the device stack.
    `analysis` defaults to the image's lazily-bound ModuleAnalysis;
    None (concatenated images, analyzer failure) plans nothing."""
    from wasmedge_tpu.validator.image import lop_name

    if cfg is None:
        from wasmedge_tpu.common.configure import BatchConfigure

        cfg = BatchConfigure()
    top_k = max(int(getattr(cfg, "fuse_top_k", 12)), 0)
    max_pat = max(int(getattr(cfg, "fuse_max_patterns", 8)), 0)
    div_bias = float(getattr(cfg, "fuse_divergence_bias", 0.0))
    mem_on = bool(getattr(cfg, "fuse_memory_runs", True))
    mem_max_run = max(int(getattr(cfg, "memfuse_max_run", 24)), 2)
    mem_max_pat = max(int(getattr(cfg, "memfuse_max_patterns", 8)), 0)
    report = {
        "enabled": bool(getattr(cfg, "fuse_superinstructions", True)),
        "top_k": top_k,
        "max_patterns": max_pat,
        "divergence_bias": div_bias,
        "patterns": 0,
        "fused_runs": 0,
        "fused_cells": 0,
        "candidates": [],
        "runs": [],
        "mem_runs": [],
        "memory": {
            "enabled": mem_on,
            "max_run": mem_max_run,
            "max_patterns": mem_max_pat,
            "licensed_sites": 0,
            "unlicensed_sites": 0,
            "mem_runs": 0,
            "mem_cells": 0,
            "mem_patterns": 0,
        },
    }
    img.fusion_report = report
    if not report["enabled"]:
        return report
    if analysis is None:
        analysis = img.analysis
    if analysis is None:
        return report
    report["memory"]["licensed_sites"] = int(
        getattr(analysis, "licensed_sites", 0))
    report["memory"]["unlicensed_sites"] = int(
        getattr(analysis, "unlicensed_sites", 0))

    # Per-candidate divergence: the mean of the r12 per-block
    # divergence scores over the blocks where the candidate occurs
    # (block_ngrams indexes into the FULL superinstructions order).
    # With fuse_divergence_bias > 0 the ranking key becomes
    # saved_dispatches / (1 + bias * divergence), down-weighting
    # candidates whose occurrences sit in high-divergence blocks —
    # lanes there rarely reach the fused head together, so the cells
    # realize little and cost trace size.  bias == 0 (the default)
    # keeps the analyzer's exact order: planning is bit-identical.
    cand_div = _candidate_divergence(analysis)
    ranked = list(getattr(analysis, "superinstructions", None) or ())
    if div_bias > 0:
        ranked = sorted(
            ranked,
            key=lambda c: (
                float(c["saved_dispatches"])
                / (1.0 + div_bias
                   * cand_div.get(tuple(c["ops"]), 0.0)),
                c["count"], tuple(c["ops"])),
            reverse=True)
    cands = ranked[:top_k]
    cand_rows = []
    for c in cands:
        dv = cand_div.get(tuple(c["ops"]), 0.0)
        row = {
            "ops": list(c["ops"]), "n": int(c["n"]),
            "planned": int(c["count"]),
            "saved_dispatches": int(c["saved_dispatches"]),
            "divergence": round(float(dv), 4),
            "eligible": False,
            "realized_runs": 0, "realized_cells": 0,
        }
        if div_bias > 0:
            row["adjusted_saved_dispatches"] = round(
                float(c["saved_dispatches"]) / (1.0 + div_bias * dv), 4)
        cand_rows.append(row)
    report["candidates"] = cand_rows

    op_id = np.asarray(img.op_id)
    names = [lop_name(int(x)) for x in op_id]
    n_code = len(names)
    flen = np.zeros(n_code, np.int32)
    fpat = np.full(n_code, -1, np.int32)
    assigned = np.zeros(n_code, bool)
    patterns: List[tuple] = []
    pat_idx = {}
    runs: List[list] = []

    # -- memory-eligible runs FIRST (r19): maximal licensed stretches
    # beat candidate n-grams to the cells so a load/store run is never
    # fragmented by a shorter pure candidate claiming its prefix.
    # Planning order cannot affect semantics (any planning is
    # bit-identical by construction), only dispatch counts.
    licensed = getattr(analysis, "licensed_pcs", None) or frozenset()
    if mem_on and licensed:
        _plan_memory_runs(img, analysis, licensed, mem_max_run,
                          mem_max_pat, n_code, flen, fpat, assigned,
                          patterns, pat_idx, report)

    if not cands and not patterns:
        return report

    for f in analysis.funcs:
        for b in f.cfg.blocks:
            # the straight-line run excludes the control terminator —
            # the same rule the r12 census applied (a fused cell cannot
            # span a dispatch exit)
            end = b.end if b.kind == "fallthrough" else b.end - 1
            end = min(end, n_code - 1)
            i = b.start
            while i <= end:
                matched = False
                for ci, c in enumerate(cands):
                    n = int(c["n"])
                    if i + n - 1 > end:
                        continue
                    if any(names[p] != nm
                           for p, nm in zip(range(i, i + n), c["ops"])):
                        continue
                    cells = tuple((int(img.cls[p]), int(img.sub[p]))
                                  for p in range(i, i + n))
                    if not all(cell_eligible(cl, sb) for cl, sb in cells):
                        continue
                    cand_rows[ci]["eligible"] = True
                    if any(assigned[p] for p in range(i, i + n)):
                        continue
                    k = pat_idx.get(cells)
                    if k is None:
                        # the pure-tier pattern cap counts pure
                        # patterns only (memory runs have their own)
                        n_pure = len(patterns) \
                            - report["memory"]["mem_patterns"]
                        if n_pure >= max_pat:
                            continue
                        k = len(patterns)
                        patterns.append(cells)
                        pat_idx[cells] = k
                    flen[i] = n
                    fpat[i] = k
                    assigned[i:i + n] = True
                    cand_rows[ci]["realized_runs"] += 1
                    cand_rows[ci]["realized_cells"] += n
                    runs.append([int(i), n, int(k)])
                    i += n
                    matched = True
                    break
                if not matched:
                    i += 1

    if patterns:
        img.fuse_len = flen
        img.fuse_pat = fpat
        img.fuse_patterns = tuple(patterns)
    report["patterns"] = len(patterns)
    report["fused_runs"] = len(runs)
    # fused_runs/fused_cells stay the CANDIDATE tier's counts (the
    # validator reconciles them against per-candidate realized_runs);
    # the memory tier reports under report["memory"] / "mem_runs"
    report["fused_cells"] = int(flen.sum()) \
        - report["memory"]["mem_cells"]
    report["runs"] = runs
    # planned-vs-realized delta per candidate (the analyze report's
    # fusion section surfaces it; the census counts STATIC occurrences
    # so delta > 0 means overlaps/ineligible cells ate into the plan)
    for row in cand_rows:
        row["delta_runs"] = int(row["planned"]) - int(
            row["realized_runs"])
    return report


def _plan_memory_runs(img, analysis, licensed, max_run, max_pat,
                      n_code, flen, fpat, assigned, patterns, pat_idx,
                      report):
    """The r19 memory-eligible run class: maximal straight-line
    stretches of (pure-eligible | licensed load/store) cells holding
    at least one memory cell become fused runs — one dispatch retires
    the stretch, each access compiled width-specialized without the
    trap checks its license proved redundant.  Unlicensed sites never
    join (they keep the per-op path and its exact trap semantics)."""
    mem = report["memory"]
    for f in analysis.funcs:
        for b in f.cfg.blocks:
            end = b.end if b.kind == "fallthrough" else b.end - 1
            end = min(end, n_code - 1)
            i = b.start
            while i <= end:
                if assigned[i] or not _mem_cell_ok(img, i, licensed):
                    i += 1
                    continue
                j = i
                while j + 1 <= end and not assigned[j + 1] \
                        and _mem_cell_ok(img, j + 1, licensed):
                    j += 1
                k0 = i
                while k0 <= j:
                    k1 = min(k0 + max_run - 1, j)
                    has_mem = any(int(img.cls[p]) in _MEM_CLS
                                  for p in range(k0, k1 + 1))
                    n = k1 - k0 + 1
                    if n < 2 or not has_mem:
                        k0 = k1 + 1
                        continue
                    cells = tuple(
                        mem_cell_key(img, p)
                        if int(img.cls[p]) in _MEM_CLS
                        else (int(img.cls[p]), int(img.sub[p]))
                        for p in range(k0, k1 + 1))
                    k = pat_idx.get(cells)
                    if k is None:
                        if mem["mem_patterns"] >= max_pat:
                            k0 = k1 + 1
                            continue
                        k = len(patterns)
                        patterns.append(cells)
                        pat_idx[cells] = k
                        mem["mem_patterns"] += 1
                    flen[k0] = n
                    fpat[k0] = k
                    assigned[k0:k1 + 1] = True
                    report["mem_runs"].append([int(k0), n, int(k)])
                    mem["mem_runs"] += 1
                    mem["mem_cells"] += n
                    k0 = k1 + 1
                i = j + 1


# -- the fused step handler (trace-time builder) ----------------------------

def make_fused_apply(img, lanes: int, has_simd: bool):
    """Build the fused dispatch handler `_make_step` merges in.

    For each realized pattern the builder symbolically executes the
    (cls, sub) sequence over the lane planes: pops beyond what the
    pattern produced gather lazily from the live stack, pushes stay in
    registers, local/global writes scatter under the pattern mask as
    they happen (so an in-pattern local.set -> local.get dependency
    reads its own write), and the surviving register values write back
    in one masked pass at the end.  Per-slot operands (local index,
    immediate) gather from the ORIGINAL image planes at pc + slot, so
    one pattern serves every run instance.

    jit-purity lint target (tools/lint_jit_purity.py): everything
    nested here runs under trace.
    """
    import jax.numpy as jnp

    from wasmedge_tpu.batch import laneops as lo_ops

    I32 = jnp.int32
    lane_iota = jnp.arange(lanes, dtype=I32)
    a_t = jnp.asarray(img.a)
    ilo_t = jnp.asarray(img.imm_lo)
    ihi_t = jnp.asarray(img.imm_hi)
    pat_t = jnp.asarray(img.fuse_pat)
    patterns = img.fuse_patterns
    A2F = lo_ops.alu2_fns()
    A1F = lo_ops.alu1_fns()
    NC = 4 if has_simd else 2

    def gat(plane, idx):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        return jnp.take_along_axis(plane, idx[None, :], axis=0)[0]

    def scat(plane, idx, vals, mask):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        cur = jnp.take_along_axis(plane, idx[None, :], axis=0)[0]
        return plane.at[idx, lane_iota].set(jnp.where(mask, vals, cur))

    def fused_apply(stacks, globs, pc, sp, fp, is_fused):
        """stacks = [lo, hi(, e2, e3)] value planes AFTER the per-op
        path's writes (fused lanes' columns are untouched there —
        masks are disjoint); globs = (glob_lo, glob_hi).  Returns
        (stacks', globs', fused_sp) with fused lanes' effects applied;
        non-fused lanes' columns pass through bit-unchanged."""
        stacks = list(stacks)
        glob_lo, glob_hi = globs
        zl = jnp.zeros_like(sp)
        fused_sp = sp
        ng = glob_lo.shape[0]

        def cell(lo, hi):
            return (lo, hi) if NC == 2 else (lo, hi, zl, zl)

        for k, pat in enumerate(patterns):
            if pattern_has_mem(pat):
                continue             # compiled by make_memfuse_apply
            m = is_fused & (pat_t[pc] == k)
            virt: list = []
            taken = [0]

            def ppop(virt=virt, taken=taken):
                if virt:
                    return virt.pop()
                taken[0] += 1
                idx = sp - taken[0]
                return tuple(gat(p, idx) for p in stacks)

            for j, (cls_j, sub_j) in enumerate(pat):
                pcj = jnp.clip(pc + j, 0, a_t.shape[0] - 1)
                if cls_j == CLS_NOP:
                    continue
                if cls_j == CLS_CONST:
                    virt.append(cell(ilo_t[pcj], ihi_t[pcj]))
                elif cls_j == CLS_LOCAL_GET:
                    idx = fp + a_t[pcj]
                    virt.append(tuple(gat(p, idx) for p in stacks))
                elif cls_j in (CLS_LOCAL_SET, CLS_LOCAL_TEE):
                    v = ppop()
                    if cls_j == CLS_LOCAL_TEE:
                        virt.append(v)
                    idx = fp + a_t[pcj]
                    for c in range(NC):
                        stacks[c] = scat(stacks[c], idx, v[c], m)
                elif cls_j == CLS_GLOBAL_GET:
                    gi = jnp.clip(a_t[pcj], 0, ng - 1)
                    gl = jnp.take_along_axis(glob_lo, gi[None, :], axis=0)[0]
                    gh = jnp.take_along_axis(glob_hi, gi[None, :], axis=0)[0]
                    virt.append(cell(gl, gh))
                elif cls_j == CLS_GLOBAL_SET:
                    v = ppop()
                    gi = jnp.clip(a_t[pcj], 0, ng - 1)
                    cl = jnp.take_along_axis(glob_lo, gi[None, :], axis=0)[0]
                    ch = jnp.take_along_axis(glob_hi, gi[None, :], axis=0)[0]
                    glob_lo = glob_lo.at[gi, lane_iota].set(
                        jnp.where(m, v[0], cl))
                    glob_hi = glob_hi.at[gi, lane_iota].set(
                        jnp.where(m, v[1], ch))
                elif cls_j == CLS_DROP:
                    ppop()
                elif cls_j == CLS_SELECT:
                    cv = ppop()   # cond (top)
                    v2 = ppop()   # val2
                    v1 = ppop()   # val1
                    cz = cv[0] == 0
                    virt.append(tuple(jnp.where(cz, b_c, a_c)
                                      for b_c, a_c in zip(v2, v1)))
                elif cls_j == CLS_ALU1:
                    v = ppop()
                    rl, rh = A1F[sub_j](v[0], v[1])
                    virt.append(cell(rl, rh))
                elif cls_j == CLS_ALU2:
                    y = ppop()
                    x = ppop()
                    rl, rh = A2F[sub_j](x[0], x[1], y[0], y[1])
                    virt.append(cell(rl, rh))
                else:  # planner bug: surface at trace time, not as
                    # silent misexecution
                    raise AssertionError(
                        f"unfusable class {cls_j} in pattern {k}")
            base = sp - taken[0]
            for i, v in enumerate(virt):
                for c in range(NC):
                    stacks[c] = scat(stacks[c], base + i, v[c], m)
            fused_sp = jnp.where(m, base + len(virt), fused_sp)
        return stacks, (glob_lo, glob_hi), fused_sp

    return fused_apply


def memfuse_store_slots(img) -> int:
    """Static count of store slots across the image's MEMORY patterns
    (one per store cell; two for 8-byte stores).  The step builder
    sizes the fused-store channel with it: make_memfuse_apply returns
    exactly this many (widx, value, mask) triples, and the skip branch
    of the engine's any-lane conditional fabricates the same shape."""
    n = 0
    for pat in (img.fuse_patterns or ()):
        if not pattern_has_mem(pat):
            continue
        for cl, key in pat:
            if cl == CLS_STORE:
                n += 2 if key == 8 else 1
            elif cl == CLS_VSTORE:
                n += 4
    return n


def make_memfuse_apply(img, lanes: int, has_simd: bool):
    """Build the fused MEMORY-run handler (r19) `_make_step` merges in.

    Same symbolic-execution scheme as make_fused_apply, extended with
    load/store cells whose width/flags are static per pattern slot
    (mem_cell_key).  Because every memory cell carries an absint
    license — the access is proven in-bounds against the module's
    minimum memory and proven never to straddle a device word — each
    access compiles width-specialized with no bounds mask and no trap
    plumbing: ONE gather per load, and per store ONE (widx, value,
    mask) triple on the fused-store channel (a word RMW for sub-word
    stores).  The handler never carries the memory PLANE itself: the
    plane rides its own any-lane conditional in the step (exactly the
    per-op path's run_stores shape — a big buffer in a conditional's
    tuple carry costs a full-plane copy every step on the CPU
    backend), and the triples it returns are [lanes] vectors.  In-run
    store -> load dependencies read through the pending triples
    (memory columns are per-lane, and a lane runs at most one fused
    pattern per step, so cross-pattern interleaving cannot exist).

    Returns (stacks', globs', stores, fused_sp) with `stores` a tuple
    of exactly memfuse_store_slots(img) triples.

    jit-purity lint target (tools/lint_jit_purity.py): everything
    nested here runs under trace.
    """
    import jax.numpy as jnp
    from jax import lax

    from wasmedge_tpu.batch import laneops as lo_ops

    I32 = jnp.int32
    lane_iota = jnp.arange(lanes, dtype=I32)
    a_t = jnp.asarray(img.a)
    ilo_t = jnp.asarray(img.imm_lo)
    ihi_t = jnp.asarray(img.imm_hi)
    pat_t = jnp.asarray(img.fuse_pat)
    patterns = img.fuse_patterns
    A2F = lo_ops.alu2_fns()
    A1F = lo_ops.alu1_fns()
    NC = 4 if has_simd else 2
    N_SLOTS = memfuse_store_slots(img)

    def gat(plane, idx):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        return jnp.take_along_axis(plane, idx[None, :], axis=0)[0]

    def scat(plane, idx, vals, mask):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        cur = jnp.take_along_axis(plane, idx[None, :], axis=0)[0]
        return plane.at[idx, lane_iota].set(jnp.where(mask, vals, cur))

    def memfuse_apply(stacks, globs, mem, pc, sp, fp, is_fused):
        """`mem` is READ-ONLY here (loads gather from it); the
        returned store triples are applied to the plane by the step's
        own conditional.  Lanes outside `is_fused`-masked patterns
        pass through bit-unchanged."""
        stacks = list(stacks)
        glob_lo, glob_hi = globs
        zl = jnp.zeros_like(sp)
        fused_sp = sp
        ng = glob_lo.shape[0]
        stores: list = []

        def cell(lo, hi):
            return (lo, hi) if NC == 2 else (lo, hi, zl, zl)

        for k, pat in enumerate(patterns):
            if not pattern_has_mem(pat):
                continue             # compiled by make_fused_apply
            m = is_fused & (pat_t[pc] == k)
            virt: list = []
            taken = [0]
            pending: list = []       # this pattern's (widx, word) so
            #                          an in-run load reads its writes

            def ppop(virt=virt, taken=taken):
                if virt:
                    return virt.pop()
                taken[0] += 1
                idx = sp - taken[0]
                return tuple(gat(p, idx) for p in stacks)

            def read_word(w_idx, pending=pending):
                w = gat(mem, w_idx)
                for pwi, pv in pending:
                    w = jnp.where(w_idx == pwi, pv, w)
                return w

            def put_word(w_idx, val, m=m, pending=pending):
                pending.append((w_idx, val))
                stores.append((w_idx, val, m))

            for j, (cls_j, key_j) in enumerate(pat):
                pcj = jnp.clip(pc + j, 0, a_t.shape[0] - 1)
                if cls_j == CLS_NOP:
                    continue
                if cls_j == CLS_LOAD:
                    nbytes = key_j & 0xFF
                    signed = (key_j >> 8) & 1
                    is64 = (key_j >> 9) & 1
                    av = ppop()
                    ea = av[0] + a_t[pcj]
                    widx = lax.shift_right_logical(ea, 2)
                    w0 = read_word(widx)
                    hi = zl
                    if nbytes == 8:
                        lo = w0
                        hi = read_word(widx + 1)
                    elif nbytes == 4:
                        lo = w0
                    else:
                        sh = (ea & 3) * 8
                        raw = lax.shift_right_logical(w0, sh)
                        bits = nbytes * 8
                        if signed:
                            lo = lax.shift_right_arithmetic(
                                lax.shift_left(raw, 32 - bits),
                                32 - bits)
                        else:
                            lo = raw & ((1 << bits) - 1)
                    if is64 and nbytes < 8:
                        hi = lax.shift_right_arithmetic(lo, 31) \
                            if signed else zl
                    virt.append(cell(lo, hi))
                elif cls_j == CLS_VLOAD:
                    # licensed v128: word-aligned by proof, exactly
                    # four whole device words (and has_simd => NC == 4)
                    assert NC == 4, "v128 cell in a 2-comp image"
                    av = ppop()
                    ea = av[0] + a_t[pcj]
                    widx = lax.shift_right_logical(ea, 2)
                    virt.append(tuple(read_word(widx + kk)
                                      for kk in range(4)))
                elif cls_j == CLS_VSTORE:
                    assert NC == 4, "v128 cell in a 2-comp image"
                    v = ppop()       # value (top)
                    av = ppop()      # address
                    ea = av[0] + a_t[pcj]
                    widx = lax.shift_right_logical(ea, 2)
                    for kk in range(4):
                        put_word(widx + kk, v[kk])
                elif cls_j == CLS_STORE:
                    nbytes = key_j
                    v = ppop()       # value (top)
                    av = ppop()      # address
                    ea = av[0] + a_t[pcj]
                    widx = lax.shift_right_logical(ea, 2)
                    if nbytes == 8:
                        put_word(widx, v[0])
                        put_word(widx + 1, v[1])
                    elif nbytes == 4:
                        put_word(widx, v[0])
                    else:
                        # sub-word store: single-word RMW (the license
                        # proves it cannot straddle)
                        sh = (ea & 3) * 8
                        base = jnp.int32(0xFF if nbytes == 1
                                         else 0xFFFF)
                        msk = lax.shift_left(base, sh)
                        cur = read_word(widx)
                        nw = (cur & ~msk) | \
                            (lax.shift_left(v[0], sh) & msk)
                        put_word(widx, nw)
                elif cls_j == CLS_CONST:
                    virt.append(cell(ilo_t[pcj], ihi_t[pcj]))
                elif cls_j == CLS_LOCAL_GET:
                    idx = fp + a_t[pcj]
                    virt.append(tuple(gat(p, idx) for p in stacks))
                elif cls_j in (CLS_LOCAL_SET, CLS_LOCAL_TEE):
                    v = ppop()
                    if cls_j == CLS_LOCAL_TEE:
                        virt.append(v)
                    idx = fp + a_t[pcj]
                    for c in range(NC):
                        stacks[c] = scat(stacks[c], idx, v[c], m)
                elif cls_j == CLS_GLOBAL_GET:
                    gi = jnp.clip(a_t[pcj], 0, ng - 1)
                    gl = jnp.take_along_axis(glob_lo, gi[None, :],
                                             axis=0)[0]
                    gh = jnp.take_along_axis(glob_hi, gi[None, :],
                                             axis=0)[0]
                    virt.append(cell(gl, gh))
                elif cls_j == CLS_GLOBAL_SET:
                    v = ppop()
                    gi = jnp.clip(a_t[pcj], 0, ng - 1)
                    cl = jnp.take_along_axis(glob_lo, gi[None, :],
                                             axis=0)[0]
                    ch = jnp.take_along_axis(glob_hi, gi[None, :],
                                             axis=0)[0]
                    glob_lo = glob_lo.at[gi, lane_iota].set(
                        jnp.where(m, v[0], cl))
                    glob_hi = glob_hi.at[gi, lane_iota].set(
                        jnp.where(m, v[1], ch))
                elif cls_j == CLS_DROP:
                    ppop()
                elif cls_j == CLS_SELECT:
                    cv = ppop()   # cond (top)
                    v2 = ppop()   # val2
                    v1 = ppop()   # val1
                    cz = cv[0] == 0
                    virt.append(tuple(jnp.where(cz, b_c, a_c)
                                      for b_c, a_c in zip(v2, v1)))
                elif cls_j == CLS_ALU1:
                    v = ppop()
                    rl, rh = A1F[key_j](v[0], v[1])
                    virt.append(cell(rl, rh))
                elif cls_j == CLS_ALU2:
                    y = ppop()
                    x = ppop()
                    rl, rh = A2F[key_j](x[0], x[1], y[0], y[1])
                    virt.append(cell(rl, rh))
                else:  # planner bug: surface at trace time, not as
                    # silent misexecution
                    raise AssertionError(
                        f"unfusable class {cls_j} in mem pattern {k}")
            base = sp - taken[0]
            for i, v in enumerate(virt):
                for c in range(NC):
                    stacks[c] = scat(stacks[c], base + i, v[c], m)
            fused_sp = jnp.where(m, base + len(virt), fused_sp)
        # pad to the static slot count (patterns share one channel;
        # the count is exact by construction — assert loudly if not)
        assert len(stores) == N_SLOTS, (len(stores), N_SLOTS)
        return stacks, (glob_lo, glob_hi), tuple(stores), fused_sp

    return memfuse_apply
