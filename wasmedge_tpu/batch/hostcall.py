"""Device→host outcall channel: batched host-function (WASI) calls.

This is the TPU-native analog of the reference's AOT intrinsics escape
(/root/reference/lib/executor/engine/proxy.cpp:45-71) designed in
SURVEY.md §5.8: a lane that calls an imported host function parks at a
synthetic HOSTCALL stub (batch/image.py appends one per import) with its
frame already pushed, the engine marks it waiting (TRAP_HOSTCALL in the
trap plane / ST_HOSTCALL block status), and the host step-loop drains the
waiting lanes through the ordinary Python host-function layer
(runtime/hostfunc.py — the same WASI functions the scalar engine calls),
writes results and memory effects back into the SoA state, and re-arms
the lanes while the rest of the batch keeps stepping.

Sandbox model: lanes of ONE engine share that engine's instance's host
modules (one WASI environ / fd table), like threads of one OS process;
per-lane data (args, results, linear memory) is fully isolated.  Tenants
are stronger: each tenant instance carries its own registered host
modules — its own WASI environ, preopens, and fd table (the per-VM
WASI::Environ model, reference environ.h:38-1156) — and the multi-tenant
scheduler serves every tenant's outcalls through its own instance, so
tenant A can never reach tenant B's preopens
(tests/test_multitenant.py::test_per_tenant_wasi_isolation).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.host.wasi.environ import WasiExit
from wasmedge_tpu.runtime.instance import MemoryInstance

MASK32 = 0xFFFFFFFF


class _LaneMemory(MemoryInstance):
    """MemoryInstance view over one lane's column of the [W, lanes] plane.

    `page_limit` must be the plane's static capacity (img.mem_pages_max):
    a host function growing memory mid-outcall then stays inside the
    [W, lanes] allocation, and the serving loop writes the new page count
    back into the state's mem_pages plane (`pages` is derived from the
    bytearray length, so growth is visible to the caller)."""

    def __init__(self, data: bytearray, max_pages: Optional[int],
                 page_limit: int):
        # bypass MemoryInstance.__init__ (no ast.MemoryType at hand)
        self.min = len(data) // 65536
        self.max = max_pages
        self.page_limit = page_limit
        self.data = data


def lane_memory_bytes(mem_plane: np.ndarray, lane: int, pages: int) -> bytearray:
    """Extract one lane's linear memory as bytes (word-major plane)."""
    col = np.ascontiguousarray(mem_plane[:, lane])
    return bytearray(col.view(np.uint8)[: pages * 65536].tobytes())


def store_lane_memory(mem_plane: np.ndarray, lane: int, data: bytearray):
    nwords = min((len(data) + 3) // 4, mem_plane.shape[0])
    raw = np.frombuffer(bytes(data) + b"\x00" * 3, dtype=np.int32,
                        count=nwords)
    mem_plane[:nwords, lane] = raw


def serve_one(fi, args_cells: List[int],
              lane_mem: Optional[_LaneMemory]) -> Tuple[List[int], int]:
    """Run one lane's host call. Returns (result_cells, trap_code)."""
    if fi.kind != "host":
        return [], int(ErrCode.ExecutionFailed)
    try:
        out = fi.host.run(lane_mem, list(args_cells))
        return out, 0
    except TrapError as te:
        return [], int(te.code)
    except WasiExit:
        # proc_exit through the per-lane path: the lane terminates
        # (vectorized groups go through vec_proc_exit instead)
        return [], int(ErrCode.Terminated)


def wasi_env_of(engine):
    """The instance's WasiEnviron, found through any registered WASI
    host function (per-tenant instances carry per-tenant environs)."""
    inst = getattr(engine, "inst", None)
    for f in getattr(inst, "funcs", None) or []:
        if getattr(f, "kind", None) == "host":
            env = getattr(getattr(f, "host", None), "_env", None)
            if env is not None and hasattr(env, "get_fd"):
                return env
    return None


def vec_impl_for(fi):
    """(vectorized_fn, environ) for a WASI host function with a tier-1
    SoA implementation, else (None, None)."""
    host = getattr(fi, "host", None)
    env = getattr(host, "_env", None)
    if env is None or not hasattr(env, "get_fd"):
        return None, None
    from wasmedge_tpu.host.wasi.vectorized import VEC_WASI

    return VEC_WASI.get(getattr(host, "name", None)), env


def hostcall_kind(fi) -> str:
    """Stable label for a host function in the drain-latency histograms
    (the WASI function name when known, else the import pair)."""
    host = getattr(fi, "host", None)
    name = getattr(host, "name", None)
    if name:
        return str(name)
    mod = getattr(fi, "import_module", "") or "host"
    imp = getattr(fi, "import_name", "") or "?"
    return f"{mod}.{imp}"


def gather_arg_cells(stack_lo, stack_hi, fp, lanes, nargs) -> np.ndarray:
    """Raw 64-bit argument cells [nargs, n] for a lane group (one fancy
    gather, no per-lane loop)."""
    n = int(lanes.size)
    if nargs == 0:
        return np.zeros((0, n), np.int64)
    rows = np.asarray(fp[lanes], np.int64)[None, :] + \
        np.arange(nargs, dtype=np.int64)[:, None]
    lo = stack_lo[rows, lanes[None, :]].view(np.uint32).astype(np.uint64)
    hi = stack_hi[rows, lanes[None, :]].view(np.uint32).astype(np.uint64)
    return (lo | (hi << np.uint64(32))).view(np.int64)


def _stdout_cursor(engine, lanes: int):
    """Per-lane stdout stream cursor backing exactly-once flushing
    across restores (ROADMAP r7 open item).

    `pos[lane]` is the lane's LOGICAL stream position: total payload
    bytes its tier-0 fd_write records have reached in this run's
    deterministic replay order.  `hw[lane]` is the high-water mark of
    bytes actually written to the host fds.  A restore rewinds `pos`
    (checkpoint.load journals it; a restore to the initial state zeroes
    it) while `hw` survives on the engine — so replayed records are
    skipped up to the high-water mark instead of re-written."""
    cur = getattr(engine, "_stdout_cursor", None)
    if cur is None or cur[0].size != lanes:
        cur = (np.zeros(lanes, np.int64), np.zeros(lanes, np.int64))
        engine._stdout_cursor = cur
    return cur


def stdout_cursor_reset(engine, keep_highwater: bool = False):
    """Reset the logical stream position (a fresh run, or a restore to
    the initial state).  `keep_highwater=True` preserves the written
    high-water mark so a from-scratch REPLAY of the same run suppresses
    output it already flushed; False starts a genuinely new stream."""
    cur = getattr(engine, "_stdout_cursor", None)
    if cur is None:
        return
    cur[0][:] = 0
    if not keep_highwater:
        cur[1][:] = 0


def _tap_tier1_stdout(eff, engine, cache, slab_lo, slab_hi, fp, pages,
                      lanes, max_pages, plane_cap):
    """Mirror a tier-1 fd_write group's fd-1 bytes into the owning
    requests' stream buffers (effects/stream.py) before the host drain
    writes the real fds.

    Concatenated multi-module images carry no t0kind plane
    (batch/multitenant.py), so a gateway guest's stdout arrives HERE
    rather than through the tier-0 record buffer.  The same per-lane
    logical cursor advances (parked sessions journal it as stdout_pos,
    checkpoints carry it), and the same high-water mark suppresses
    re-streaming a restored round's deterministic replay — one cursor,
    whichever tier carried the bytes."""
    pos, hw = _stdout_cursor(engine, int(np.asarray(fp).size))
    for lane in lanes:
        base = int(fp[lane])

        def arg(i):
            lo = int(np.uint32(slab_lo[base + i, int(lane)]))
            hi = int(np.uint32(slab_hi[base + i, int(lane)]))
            return lo | (hi << 32)

        if (arg(0) & MASK32) != 1:
            continue
        mem = _CachedLaneMemory(cache, int(lane), int(pages[lane]),
                                max_pages, plane_cap)
        try:
            iovs = arg(1) & MASK32
            n = arg(2) & MASK32
            mem.check_bounds(iovs, 8 * n)
            data = b""
            for k in range(n):
                buf = mem.load(iovs + 8 * k, 4, False)
                ln = mem.load((iovs + 8 * k + 4) & MASK32, 4, False)
                if ln:
                    data += mem.load_bytes(buf & MASK32, ln)
        except TrapError:
            continue   # malformed iovs: the host fn reports the errno
        if not data:
            continue
        p = int(pos[lane])
        skip = min(max(int(hw[lane]) - p, 0), len(data))
        rid = eff.lane_rids.get(int(lane))
        if rid is not None and skip < len(data):
            eff.stream_append(rid, p + skip, data[skip:])
        pos[lane] = p + len(data)
        hw[lane] = max(int(hw[lane]), p + len(data))


def flush_stdout_buffers(engine, state):
    """Drain the tier-0 in-device stdout record buffers to the WASI
    environ's fds (one download, one write per fd) and reset the
    per-lane offsets.  Runs at harvest and before any tier-1 serve so
    per-lane output ordering is preserved.

    Exactly-once across restores: each lane's records advance a logical
    stream cursor; bytes at positions below the engine's written
    high-water mark are a deterministic replay of output a previous
    attempt already flushed and are skipped (see _stdout_cursor).  The
    guarantee assumes deterministic payloads — a guest that embeds
    wall-clock values in its output regenerates different bytes and the
    suppression degrades to at-least-once for the replayed window."""
    if getattr(state, "so_buf", None) is None:
        return state
    so_off = np.asarray(state.so_off)
    if not (so_off > 0).any():
        return state
    import jax.numpy as jnp

    buf = np.asarray(state.so_buf)
    env = wasi_env_of(engine)
    pos, hw = _stdout_cursor(engine, so_off.size)
    # r23 stream seam: fresh stdout record bytes also feed the owning
    # request's StreamBuf (effects/stream.py) with their logical stream
    # position, so gateway /stream subscribers follow the same
    # exactly-once cursor the host fds do
    eff = getattr(engine, "_effects", None)
    per_fd = {}
    nbytes = 0
    for lane in np.nonzero(so_off > 0)[0]:
        end = int(so_off[lane])
        col = buf[:end, lane]
        p = int(pos[lane])
        h = int(hw[lane])
        off = 0
        while off < end:
            hdr = int(np.uint32(col[off]))
            fd = hdr >> 28
            ln = hdr & 0x0FFFFFFF
            nw = (ln + 3) // 4
            skip = min(max(h - p, 0), ln)
            if skip < ln:
                data = np.ascontiguousarray(
                    col[off + 1:off + 1 + nw]).tobytes()[:ln]
                per_fd.setdefault(fd, []).append(data[skip:])
                nbytes += ln - skip
                if eff is not None and fd == 1:
                    rid = eff.lane_rids.get(int(lane))
                    if rid is not None:
                        eff.stream_append(rid, p + skip, data[skip:])
            p += ln
            off += 1 + nw
        pos[lane] = p
        hw[lane] = max(h, p)
    from wasmedge_tpu.host.wasi.vectorized import _write_all

    for fd in sorted(per_fd):
        e = env.fds.get(fd) if env is not None else None
        if e is None or e.os_fd < 0:
            continue  # fd vanished (tier-0 gating makes this unreachable)
        data = b"".join(per_fd[fd])
        if data:
            _write_all(e, data)
    stats = getattr(engine, "hostcall_stats", None)
    if stats is not None:
        stats["stdout_flushes"] += 1
        stats["stdout_bytes"] += nbytes
    return state._replace(so_off=jnp.zeros_like(state.so_off))


def serve_batch_state(engine, state):
    """Serve all TRAP_HOSTCALL lanes of a SIMT BatchState; returns the
    updated state (device arrays refreshed only where touched).

    Tier-1 vectorized drain: lanes are grouped by hostcall id and each
    group with a SoA implementation (host/wasi/vectorized.py) is served
    in one vectorized call over the memory plane — no per-lane 64 KiB
    materialization.  Groups without one (custom host functions,
    sockets, oversized iovec arrays) fall back to the per-lane loop,
    itself backed by the same chunked cache (no full-plane copies).

    Transfer discipline: argument rows ride as ONE slab download,
    guest memory as 4 KiB-row all-lane chunks fetched on touch and
    written back dirty-only, results/trap/sp/pc as row/vector updates —
    never a whole [W, lanes] plane round trip per serve."""
    import jax.numpy as jnp

    from wasmedge_tpu.batch.image import TRAP_HOSTCALL
    from wasmedge_tpu.host.wasi.vectorized import NotVectorizable

    img = engine.img
    trap = np.asarray(state.trap)
    waiting = np.nonzero(trap == TRAP_HOSTCALL)[0]
    if waiting.size == 0:
        return state
    # buffered tier-0 stdout must land before any tier-1 call can
    # observe fd state (per-lane write ordering)
    state = flush_stdout_buffers(engine, state)
    stats = getattr(engine, "hostcall_stats", None)
    if stats is not None:
        stats["serve_rounds"] += 1
        stats["tier1_calls"] += int(waiting.size)
    pc = np.asarray(state.pc)
    fp = np.asarray(state.fp)
    opbase = np.asarray(state.opbase)
    sp = np.asarray(state.sp).copy()
    pages = np.asarray(state.mem_pages).copy()
    has_mem = img.has_memory
    cache = PlaneMemoryCache(state.mem) if has_mem else None
    plane_cap = (int(state.mem.shape[0]) // (65536 // 4)) if has_mem else 0
    max_pages = img.mem_pages_max if img.mem_pages_max > 0 else None
    new_trap = trap.copy()
    new_pc = pc.copy()
    use_vec = bool(getattr(engine.cfg, "vectorized_hostcalls", True))

    ks = img.a[pc[waiting]]
    nargs_by_k = {int(k): len(engine.resolve_func(int(k)).functype.params)
                  for k in np.unique(ks)}
    nargs_arr = np.array([nargs_by_k[int(k)] for k in ks], np.int64)
    max_row = int((fp[waiting] + nargs_arr).max(initial=0))
    slab_lo = np.asarray(state.stack_lo[:max_row]) if max_row else \
        np.zeros((0, trap.size), np.int32)
    slab_hi = np.asarray(state.stack_hi[:max_row]) if max_row else \
        np.zeros((0, trap.size), np.int32)

    obs = getattr(engine, "obs", None)
    # per-kind drain-latency seam: vectorized implementations time
    # themselves (host/wasi/vectorized.py), the per-lane fallback is
    # timed below; restored after the group loop even when a host
    # function raises mid-drain
    from wasmedge_tpu.host.wasi.vectorized import set_drain_recorder

    prev_rec = set_drain_recorder(obs)
    stack_sets = []  # (rows [nres, n], lanes [n], lo [nres, n], hi)
    # r23 effect lowering: blocking hostcalls (await_event, pure-clock
    # poll_oneoff) either complete from pending wake state or mark
    # their lane TRAP_PARKED for the boundary park — either way they
    # leave the normal host drain below
    eff = getattr(engine, "_effects", None)
    if eff is not None:
        consumed = eff.intercept(engine, waiting, ks, slab_lo, slab_hi,
                                 fp, pc, opbase, sp, cache, new_trap,
                                 new_pc, stack_sets)
        if consumed:
            keep = np.array([int(lane) not in consumed
                             for lane in waiting], bool)
            waiting = waiting[keep]
            ks = ks[keep]
    try:
        for k in np.unique(ks):
            lanes = waiting[ks == k]
            fi = engine.resolve_func(int(k))
            nargs = nargs_by_k[int(k)]
            if eff is not None and has_mem and nargs >= 3 \
                    and getattr(getattr(fi, "host", None), "name",
                                None) == "fd_write":
                _tap_tier1_stdout(eff, engine, cache, slab_lo, slab_hi,
                                  fp, pages, lanes, max_pages,
                                  plane_cap)
            cells = codes = None
            if use_vec and has_mem and getattr(fi, "kind", None) == "host":
                vecfn, env = vec_impl_for(fi)
                if vecfn is not None:
                    args = gather_arg_cells(slab_lo, slab_hi, fp, lanes,
                                            nargs)
                    view = make_cached_view(cache, lanes, pages[lanes])
                    try:
                        cells, codes = vecfn(env, view, args)
                    except NotVectorizable:
                        cells = codes = None
            if cells is not None:
                if stats is not None:
                    stats["tier1_vectorized"] += int(lanes.size)
                ok = codes == 0
                okl = lanes[ok]
                nres = cells.shape[0]
                if okl.size and nres:
                    cu = cells[:, ok].astype(np.uint64)
                    obk = np.asarray(opbase[okl], np.int64)
                    rows = obk[None, :] + np.arange(nres,
                                                    dtype=np.int64)[:, None]
                    lo_v = (cu & np.uint64(MASK32)).astype(
                        np.uint32).view(np.int32)
                    hi_v = (cu >> np.uint64(32)).astype(
                        np.uint32).view(np.int32)
                    stack_sets.append((rows, okl, lo_v, hi_v))
                sp[okl] = opbase[okl] + nres
                new_trap[lanes] = np.where(ok, 0, codes)
                new_pc[okl] = pc[okl] + 1  # resume at the stub's RETURN
                continue
            # ---- per-lane fallback (chunk-cached lane memory views) ----
            # restart the drain timer: the histogram's vectorized=False
            # observation must measure the fallback loop alone, not a
            # failed NotVectorizable attempt above it
            t_drain = obs.now() if obs is not None else 0.0
            g_rows, g_lanes, g_lo, g_hi = [], [], [], []
            for lane in lanes:
                base = int(fp[lane])
                args1 = []
                for i in range(nargs):
                    lo = int(np.uint32(slab_lo[base + i, lane]))
                    hi = int(np.uint32(slab_hi[base + i, lane]))
                    args1.append(lo | (hi << 32))
                lane_mem = None
                if has_mem:
                    lane_mem = _CachedLaneMemory(
                        cache, int(lane), int(pages[lane]), max_pages,
                        plane_cap)
                out, code = serve_one(fi, args1, lane_mem)
                if code:
                    new_trap[lane] = code
                    continue
                ob = int(opbase[lane])
                for i, cell in enumerate(out):
                    g_rows.append(ob + i)
                    g_lanes.append(int(lane))
                    g_lo.append(np.int32(np.uint32(cell & MASK32)))
                    g_hi.append(np.int32(np.uint32((cell >> 32) & MASK32)))
                sp[lane] = ob + len(out)
                if has_mem:
                    pages[lane] = lane_mem.pages  # host fn may have grown
                new_trap[lane] = 0
                new_pc[lane] = pc[lane] + 1  # resume at the stub's RETURN
            if obs is not None and obs.enabled:
                obs.hostcall(hostcall_kind(fi), obs.now() - t_drain,
                             lanes=int(lanes.size), vectorized=False)
            if g_rows:
                stack_sets.append((np.asarray(g_rows, np.int64)[None, :],
                                   np.asarray(g_lanes, np.int64),
                                   np.asarray(g_lo, np.int32)[None, :],
                                   np.asarray(g_hi, np.int32)[None, :]))

    finally:
        set_drain_recorder(prev_rec)
    new_stack_lo = state.stack_lo
    new_stack_hi = state.stack_hi
    for rows, lanes_w, lo_v, hi_v in stack_sets:
        rj = jnp.asarray(rows)
        lj = jnp.asarray(np.broadcast_to(lanes_w[None, :], rows.shape))
        new_stack_lo = new_stack_lo.at[rj, lj].set(jnp.asarray(lo_v))
        new_stack_hi = new_stack_hi.at[rj, lj].set(jnp.asarray(hi_v))
    kw = dict(
        pc=jnp.asarray(new_pc), sp=jnp.asarray(sp),
        trap=jnp.asarray(new_trap),
        stack_lo=new_stack_lo, stack_hi=new_stack_hi,
    )
    if has_mem:
        kw["mem"] = cache.flush()  # dirty chunks only
        kw["mem_pages"] = jnp.asarray(pages)
    return state._replace(**kw)


# ---------------------------------------------------------------------------
# round-cached serving: vectorized memory views over the device plane
# ---------------------------------------------------------------------------
class PlaneMemoryCache:
    """Row-chunked host cache over a device [W, lanes] memory plane for
    one serve round.

    The host link (a tunneled TPU pays ~100ms per transfer) must never
    carry per-lane traffic: chunks of guest memory are downloaded for
    ALL lanes at once (one transfer per touched 4 KiB window, however
    many lanes read it), per-lane views slice columns out of the cached
    slabs, and dirty chunks are written back in one device update per
    chunk at flush.  A serve round that only READS guest memory (the
    common WASI shape: fd_write, path_open, clock, random) uploads
    nothing at all."""

    CHUNK_ROWS = 1024  # 4 KiB of guest memory per chunk

    def __init__(self, mem_dev):
        self.dev = mem_dev
        self.W = int(mem_dev.shape[0])
        self.L = int(mem_dev.shape[1])
        self._chunks = {}
        self._dirty = set()
        self._writes = {}  # lane -> [(off, n)] for pad-lane replay

    def _chunk(self, ci: int) -> np.ndarray:
        c = self._chunks.get(ci)
        if c is None:
            lo = ci * self.CHUNK_ROWS
            hi = min(lo + self.CHUNK_ROWS, self.W)
            c = np.array(self.dev[lo:hi, :])  # one all-lane download
            self._chunks[ci] = c
        return c

    def read_bytes(self, lane: int, off: int, n: int) -> bytes:
        if n == 0:
            return b""
        w0 = off // 4
        w1 = (off + n - 1) // 4
        words = np.empty(w1 - w0 + 1, np.int32)
        w = w0
        while w <= w1:
            ci = w // self.CHUNK_ROWS
            base = ci * self.CHUNK_ROWS
            chunk = self._chunk(ci)
            upto = min(w1 + 1, base + chunk.shape[0])
            words[w - w0:upto - w0] = chunk[w - base:upto - base, lane]
            w = upto
        raw = words.tobytes()
        start = off - w0 * 4
        return raw[start:start + n]

    def writes_of(self, lane: int):
        """(off, n) write extents recorded for a lane this round."""
        return list(self._writes.get(lane, ()))

    def write_bytes(self, lane: int, off: int, data: bytes):
        n = len(data)
        if n == 0:
            return
        self._writes.setdefault(lane, []).append((off, n))
        w0 = off // 4
        w1 = (off + n - 1) // 4
        cur = bytearray(self.read_bytes(lane, w0 * 4,
                                        (w1 - w0 + 1) * 4))
        start = off - w0 * 4
        cur[start:start + n] = data
        words = np.frombuffer(bytes(cur), dtype=np.int32)
        w = w0
        while w <= w1:
            ci = w // self.CHUNK_ROWS
            base = ci * self.CHUNK_ROWS
            chunk = self._chunk(ci)
            upto = min(w1 + 1, base + chunk.shape[0])
            chunk[w - base:upto - base, lane] = words[w - w0:upto - w0]
            self._dirty.add(ci)
            w = upto

    def flush(self):
        """Apply dirty chunks device-side; returns the updated array."""
        dev = self.dev
        for ci in sorted(self._dirty):
            lo = ci * self.CHUNK_ROWS
            chunk = self._chunks[ci]
            dev = dev.at[lo:lo + chunk.shape[0], :].set(chunk)
        self._dirty.clear()
        return dev


class _CachedLaneMemory(MemoryInstance):
    """MemoryInstance view over one lane's column of a PlaneMemoryCache.

    Byte accesses hit the cache's all-lane slabs; `page_limit` is the
    plane's row capacity, so in-place growth stays inside the
    allocation (rows beyond the current page count are zero)."""

    def __init__(self, cache: PlaneMemoryCache, lane: int, pages: int,
                 max_pages: Optional[int], page_limit: int):
        self._cache = cache
        self._lane = lane
        self._pages = pages
        self.min = pages
        self.max = max_pages
        self.page_limit = page_limit

    @property
    def pages(self) -> int:
        return self._pages

    def _nbytes(self) -> int:
        return self._pages * 65536

    def check_bounds(self, off: int, length: int):
        if off < 0 or off + length > self._nbytes():
            raise TrapError(ErrCode.MemoryOutOfBounds)

    def grow(self, delta: int) -> int:
        old = self._pages
        new = old + delta
        # KNOWN ENGINE DIVERGENCE: growth past the plane's row capacity
        # (page_limit, watermark-sized = mem_pages_init) fails with -1
        # here, while the same grow issued from *guest* code gets
        # ST_REGROW and re-executes on a bigger-plane engine, and the
        # SIMT/scalar engines succeed up to the declared max.  Spec-legal
        # (memory.grow may fail at any size) and covered by
        # tests/test_hostcall.py; routing host-driven growth through the
        # ST_REGROW handoff would require parking the whole block
        # mid-serve.  Revisit if a real WASI workload hits it.
        limit = self.page_limit
        if self.max is not None:
            limit = min(limit, self.max)
        if delta < 0 or new > limit or new > 65536:
            return -1
        self._pages = new
        return old

    def load(self, off: int, nbytes: int, signed: bool) -> int:
        self.check_bounds(off, nbytes)
        return int.from_bytes(
            self._cache.read_bytes(self._lane, off, nbytes), "little",
            signed=signed)

    def store(self, off: int, nbytes: int, value: int):
        self.check_bounds(off, nbytes)
        self._cache.write_bytes(
            self._lane, off,
            (value & ((1 << (8 * nbytes)) - 1)).to_bytes(nbytes, "little"))

    def load_bytes(self, off: int, n: int) -> bytes:
        self.check_bounds(off, n)
        return self._cache.read_bytes(self._lane, off, n)

    def store_bytes(self, off: int, data: bytes):
        self.check_bounds(off, len(data))
        self._cache.write_bytes(self._lane, off, bytes(data))

    def as_numpy(self) -> np.ndarray:
        return np.frombuffer(
            self._cache.read_bytes(self._lane, 0, self._nbytes()),
            dtype=np.uint8)


def make_cached_view(cache: PlaneMemoryCache, lanes, pages):
    """MemView over a PlaneMemoryCache for the Pallas block serve: word
    gathers assemble from the cache's 4 KiB all-lane chunks
    (download-on-touch); byte stores go through cache.write_bytes so
    dirty-chunk flushing and pad-lane write replay keep working."""
    from wasmedge_tpu.host.wasi.vectorized import MemView

    class _CachedPlaneView(MemView):
        def __init__(self):
            super().__init__(lanes, pages)
            self.cache = cache

        def _words(self, widx):
            widx = np.clip(np.asarray(widx, np.int64), 0, cache.W - 1)
            out = np.empty(widx.shape, np.int32)
            cr = PlaneMemoryCache.CHUNK_ROWS
            cis = widx // cr
            cols = np.broadcast_to(self.lanes[None, :], widx.shape) \
                if widx.ndim == 2 else self.lanes
            for ci in np.unique(cis):
                chunk = cache._chunk(int(ci))
                m = cis == ci
                out[m] = chunk[widx[m] - int(ci) * cr, cols[m]]
            return out

        def _store_bytes_one(self, i, off, data):
            cache.write_bytes(int(self.lanes[i]), off, bytes(data))

    return _CachedPlaneView()
