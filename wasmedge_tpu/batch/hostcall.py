"""Device→host outcall channel: batched host-function (WASI) calls.

This is the TPU-native analog of the reference's AOT intrinsics escape
(/root/reference/lib/executor/engine/proxy.cpp:45-71) designed in
SURVEY.md §5.8: a lane that calls an imported host function parks at a
synthetic HOSTCALL stub (batch/image.py appends one per import) with its
frame already pushed, the engine marks it waiting (TRAP_HOSTCALL in the
trap plane / ST_HOSTCALL block status), and the host step-loop drains the
waiting lanes through the ordinary Python host-function layer
(runtime/hostfunc.py — the same WASI functions the scalar engine calls),
writes results and memory effects back into the SoA state, and re-arms
the lanes while the rest of the batch keeps stepping.

Sandbox model: lanes of ONE engine share that engine's instance's host
modules (one WASI environ / fd table), like threads of one OS process;
per-lane data (args, results, linear memory) is fully isolated.  Tenants
are stronger: each tenant instance carries its own registered host
modules — its own WASI environ, preopens, and fd table (the per-VM
WASI::Environ model, reference environ.h:38-1156) — and the multi-tenant
scheduler serves every tenant's outcalls through its own instance, so
tenant A can never reach tenant B's preopens
(tests/test_multitenant.py::test_per_tenant_wasi_isolation).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.runtime.instance import MemoryInstance

MASK32 = 0xFFFFFFFF


class _LaneMemory(MemoryInstance):
    """MemoryInstance view over one lane's column of the [W, lanes] plane.

    `page_limit` must be the plane's static capacity (img.mem_pages_max):
    a host function growing memory mid-outcall then stays inside the
    [W, lanes] allocation, and the serving loop writes the new page count
    back into the state's mem_pages plane (`pages` is derived from the
    bytearray length, so growth is visible to the caller)."""

    def __init__(self, data: bytearray, max_pages: Optional[int],
                 page_limit: int):
        # bypass MemoryInstance.__init__ (no ast.MemoryType at hand)
        self.min = len(data) // 65536
        self.max = max_pages
        self.page_limit = page_limit
        self.data = data


def lane_memory_bytes(mem_plane: np.ndarray, lane: int, pages: int) -> bytearray:
    """Extract one lane's linear memory as bytes (word-major plane)."""
    col = np.ascontiguousarray(mem_plane[:, lane])
    return bytearray(col.view(np.uint8)[: pages * 65536].tobytes())


def store_lane_memory(mem_plane: np.ndarray, lane: int, data: bytearray):
    nwords = min((len(data) + 3) // 4, mem_plane.shape[0])
    raw = np.frombuffer(bytes(data) + b"\x00" * 3, dtype=np.int32,
                        count=nwords)
    mem_plane[:nwords, lane] = raw


def serve_one(fi, args_cells: List[int],
              lane_mem: Optional[_LaneMemory]) -> Tuple[List[int], int]:
    """Run one lane's host call. Returns (result_cells, trap_code)."""
    if fi.kind != "host":
        return [], int(ErrCode.ExecutionFailed)
    try:
        out = fi.host.run(lane_mem, list(args_cells))
        return out, 0
    except TrapError as te:
        return [], int(te.code)


def serve_batch_state(engine, state):
    """Serve all TRAP_HOSTCALL lanes of a SIMT BatchState; returns the
    updated state (device arrays refreshed only where touched)."""
    import jax.numpy as jnp

    from wasmedge_tpu.batch.image import TRAP_HOSTCALL

    img = engine.img
    trap = np.asarray(state.trap)
    waiting = np.nonzero(trap == TRAP_HOSTCALL)[0]
    if waiting.size == 0:
        return state
    pc = np.asarray(state.pc)
    fp = np.asarray(state.fp)
    opbase = np.asarray(state.opbase)
    sp = np.asarray(state.sp).copy()
    pages = np.asarray(state.mem_pages).copy()
    stack_lo = np.asarray(state.stack_lo).copy()
    stack_hi = np.asarray(state.stack_hi).copy()
    has_mem = img.has_memory
    mem_plane = np.asarray(state.mem).copy() if has_mem else None
    new_trap = trap.copy()
    new_pc = pc.copy()
    max_pages = img.mem_pages_max if img.mem_pages_max > 0 else None

    for lane in waiting:
        k = int(img.a[pc[lane]])
        fi = engine.resolve_func(k)
        nargs = len(fi.functype.params)
        base = int(fp[lane])
        args = []
        for i in range(nargs):
            lo = int(np.uint32(stack_lo[base + i, lane]))
            hi = int(np.uint32(stack_hi[base + i, lane]))
            args.append(lo | (hi << 32))
        lane_mem = None
        if has_mem:
            lane_mem = _LaneMemory(
                lane_memory_bytes(mem_plane, lane, int(pages[lane])),
                max_pages, img.mem_pages_max)
        out, code = serve_one(fi, args, lane_mem)
        if code:
            new_trap[lane] = code
            continue
        ob = int(opbase[lane])
        for i, cell in enumerate(out):
            stack_lo[ob + i, lane] = np.int32(np.uint32(cell & MASK32))
            stack_hi[ob + i, lane] = np.int32(np.uint32((cell >> 32) & MASK32))
        sp[lane] = ob + len(out)
        if has_mem:
            store_lane_memory(mem_plane, lane, lane_mem.data)
            pages[lane] = lane_mem.pages  # host fn may have grown memory
        new_trap[lane] = 0
        new_pc[lane] = pc[lane] + 1  # resume at the stub's RETURN

    kw = dict(
        pc=jnp.asarray(new_pc), sp=jnp.asarray(sp),
        trap=jnp.asarray(new_trap),
        stack_lo=jnp.asarray(stack_lo), stack_hi=jnp.asarray(stack_hi),
    )
    if has_mem:
        kw["mem"] = jnp.asarray(mem_plane)
        kw["mem_pages"] = jnp.asarray(pages)
    return state._replace(**kw)
