"""Device→host outcall channel: batched host-function (WASI) calls.

This is the TPU-native analog of the reference's AOT intrinsics escape
(/root/reference/lib/executor/engine/proxy.cpp:45-71) designed in
SURVEY.md §5.8: a lane that calls an imported host function parks at a
synthetic HOSTCALL stub (batch/image.py appends one per import) with its
frame already pushed, the engine marks it waiting (TRAP_HOSTCALL in the
trap plane / ST_HOSTCALL block status), and the host step-loop drains the
waiting lanes through the ordinary Python host-function layer
(runtime/hostfunc.py — the same WASI functions the scalar engine calls),
writes results and memory effects back into the SoA state, and re-arms
the lanes while the rest of the batch keeps stepping.

Sandbox model: lanes of ONE engine share that engine's instance's host
modules (one WASI environ / fd table), like threads of one OS process;
per-lane data (args, results, linear memory) is fully isolated.  Tenants
are stronger: each tenant instance carries its own registered host
modules — its own WASI environ, preopens, and fd table (the per-VM
WASI::Environ model, reference environ.h:38-1156) — and the multi-tenant
scheduler serves every tenant's outcalls through its own instance, so
tenant A can never reach tenant B's preopens
(tests/test_multitenant.py::test_per_tenant_wasi_isolation).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from wasmedge_tpu.common.errors import ErrCode, TrapError
from wasmedge_tpu.runtime.instance import MemoryInstance

MASK32 = 0xFFFFFFFF


class _LaneMemory(MemoryInstance):
    """MemoryInstance view over one lane's column of the [W, lanes] plane.

    `page_limit` must be the plane's static capacity (img.mem_pages_max):
    a host function growing memory mid-outcall then stays inside the
    [W, lanes] allocation, and the serving loop writes the new page count
    back into the state's mem_pages plane (`pages` is derived from the
    bytearray length, so growth is visible to the caller)."""

    def __init__(self, data: bytearray, max_pages: Optional[int],
                 page_limit: int):
        # bypass MemoryInstance.__init__ (no ast.MemoryType at hand)
        self.min = len(data) // 65536
        self.max = max_pages
        self.page_limit = page_limit
        self.data = data


def lane_memory_bytes(mem_plane: np.ndarray, lane: int, pages: int) -> bytearray:
    """Extract one lane's linear memory as bytes (word-major plane)."""
    col = np.ascontiguousarray(mem_plane[:, lane])
    return bytearray(col.view(np.uint8)[: pages * 65536].tobytes())


def store_lane_memory(mem_plane: np.ndarray, lane: int, data: bytearray):
    nwords = min((len(data) + 3) // 4, mem_plane.shape[0])
    raw = np.frombuffer(bytes(data) + b"\x00" * 3, dtype=np.int32,
                        count=nwords)
    mem_plane[:nwords, lane] = raw


def serve_one(fi, args_cells: List[int],
              lane_mem: Optional[_LaneMemory]) -> Tuple[List[int], int]:
    """Run one lane's host call. Returns (result_cells, trap_code)."""
    if fi.kind != "host":
        return [], int(ErrCode.ExecutionFailed)
    try:
        out = fi.host.run(lane_mem, list(args_cells))
        return out, 0
    except TrapError as te:
        return [], int(te.code)


def serve_batch_state(engine, state):
    """Serve all TRAP_HOSTCALL lanes of a SIMT BatchState; returns the
    updated state (device arrays refreshed only where touched)."""
    import jax.numpy as jnp

    from wasmedge_tpu.batch.image import TRAP_HOSTCALL

    img = engine.img
    trap = np.asarray(state.trap)
    waiting = np.nonzero(trap == TRAP_HOSTCALL)[0]
    if waiting.size == 0:
        return state
    pc = np.asarray(state.pc)
    fp = np.asarray(state.fp)
    opbase = np.asarray(state.opbase)
    sp = np.asarray(state.sp).copy()
    pages = np.asarray(state.mem_pages).copy()
    stack_lo = np.asarray(state.stack_lo).copy()
    stack_hi = np.asarray(state.stack_hi).copy()
    has_mem = img.has_memory
    mem_plane = np.asarray(state.mem).copy() if has_mem else None
    new_trap = trap.copy()
    new_pc = pc.copy()
    max_pages = img.mem_pages_max if img.mem_pages_max > 0 else None

    for lane in waiting:
        k = int(img.a[pc[lane]])
        fi = engine.resolve_func(k)
        nargs = len(fi.functype.params)
        base = int(fp[lane])
        args = []
        for i in range(nargs):
            lo = int(np.uint32(stack_lo[base + i, lane]))
            hi = int(np.uint32(stack_hi[base + i, lane]))
            args.append(lo | (hi << 32))
        lane_mem = None
        if has_mem:
            lane_mem = _LaneMemory(
                lane_memory_bytes(mem_plane, lane, int(pages[lane])),
                max_pages, img.mem_pages_max)
        out, code = serve_one(fi, args, lane_mem)
        if code:
            new_trap[lane] = code
            continue
        ob = int(opbase[lane])
        for i, cell in enumerate(out):
            stack_lo[ob + i, lane] = np.int32(np.uint32(cell & MASK32))
            stack_hi[ob + i, lane] = np.int32(np.uint32((cell >> 32) & MASK32))
        sp[lane] = ob + len(out)
        if has_mem:
            store_lane_memory(mem_plane, lane, lane_mem.data)
            pages[lane] = lane_mem.pages  # host fn may have grown memory
        new_trap[lane] = 0
        new_pc[lane] = pc[lane] + 1  # resume at the stub's RETURN

    kw = dict(
        pc=jnp.asarray(new_pc), sp=jnp.asarray(sp),
        trap=jnp.asarray(new_trap),
        stack_lo=jnp.asarray(stack_lo), stack_hi=jnp.asarray(stack_hi),
    )
    if has_mem:
        kw["mem"] = jnp.asarray(mem_plane)
        kw["mem_pages"] = jnp.asarray(pages)
    return state._replace(**kw)


# ---------------------------------------------------------------------------
# round-cached serving: vectorized memory views over the device plane
# ---------------------------------------------------------------------------
class PlaneMemoryCache:
    """Row-chunked host cache over a device [W, lanes] memory plane for
    one serve round.

    The host link (a tunneled TPU pays ~100ms per transfer) must never
    carry per-lane traffic: chunks of guest memory are downloaded for
    ALL lanes at once (one transfer per touched 4 KiB window, however
    many lanes read it), per-lane views slice columns out of the cached
    slabs, and dirty chunks are written back in one device update per
    chunk at flush.  A serve round that only READS guest memory (the
    common WASI shape: fd_write, path_open, clock, random) uploads
    nothing at all."""

    CHUNK_ROWS = 1024  # 4 KiB of guest memory per chunk

    def __init__(self, mem_dev):
        self.dev = mem_dev
        self.W = int(mem_dev.shape[0])
        self.L = int(mem_dev.shape[1])
        self._chunks = {}
        self._dirty = set()
        self._writes = {}  # lane -> [(off, n)] for pad-lane replay

    def _chunk(self, ci: int) -> np.ndarray:
        c = self._chunks.get(ci)
        if c is None:
            lo = ci * self.CHUNK_ROWS
            hi = min(lo + self.CHUNK_ROWS, self.W)
            c = np.array(self.dev[lo:hi, :])  # one all-lane download
            self._chunks[ci] = c
        return c

    def read_bytes(self, lane: int, off: int, n: int) -> bytes:
        if n == 0:
            return b""
        w0 = off // 4
        w1 = (off + n - 1) // 4
        words = np.empty(w1 - w0 + 1, np.int32)
        w = w0
        while w <= w1:
            ci = w // self.CHUNK_ROWS
            base = ci * self.CHUNK_ROWS
            chunk = self._chunk(ci)
            upto = min(w1 + 1, base + chunk.shape[0])
            words[w - w0:upto - w0] = chunk[w - base:upto - base, lane]
            w = upto
        raw = words.tobytes()
        start = off - w0 * 4
        return raw[start:start + n]

    def writes_of(self, lane: int):
        """(off, n) write extents recorded for a lane this round."""
        return list(self._writes.get(lane, ()))

    def write_bytes(self, lane: int, off: int, data: bytes):
        n = len(data)
        if n == 0:
            return
        self._writes.setdefault(lane, []).append((off, n))
        w0 = off // 4
        w1 = (off + n - 1) // 4
        cur = bytearray(self.read_bytes(lane, w0 * 4,
                                        (w1 - w0 + 1) * 4))
        start = off - w0 * 4
        cur[start:start + n] = data
        words = np.frombuffer(bytes(cur), dtype=np.int32)
        w = w0
        while w <= w1:
            ci = w // self.CHUNK_ROWS
            base = ci * self.CHUNK_ROWS
            chunk = self._chunk(ci)
            upto = min(w1 + 1, base + chunk.shape[0])
            chunk[w - base:upto - base, lane] = words[w - w0:upto - w0]
            self._dirty.add(ci)
            w = upto

    def flush(self):
        """Apply dirty chunks device-side; returns the updated array."""
        dev = self.dev
        for ci in sorted(self._dirty):
            lo = ci * self.CHUNK_ROWS
            chunk = self._chunks[ci]
            dev = dev.at[lo:lo + chunk.shape[0], :].set(chunk)
        self._dirty.clear()
        return dev


class _CachedLaneMemory(MemoryInstance):
    """MemoryInstance view over one lane's column of a PlaneMemoryCache.

    Byte accesses hit the cache's all-lane slabs; `page_limit` is the
    plane's row capacity, so in-place growth stays inside the
    allocation (rows beyond the current page count are zero)."""

    def __init__(self, cache: PlaneMemoryCache, lane: int, pages: int,
                 max_pages: Optional[int], page_limit: int):
        self._cache = cache
        self._lane = lane
        self._pages = pages
        self.min = pages
        self.max = max_pages
        self.page_limit = page_limit

    @property
    def pages(self) -> int:
        return self._pages

    def _nbytes(self) -> int:
        return self._pages * 65536

    def check_bounds(self, off: int, length: int):
        if off < 0 or off + length > self._nbytes():
            raise TrapError(ErrCode.MemoryOutOfBounds)

    def grow(self, delta: int) -> int:
        old = self._pages
        new = old + delta
        # KNOWN ENGINE DIVERGENCE: growth past the plane's row capacity
        # (page_limit, watermark-sized = mem_pages_init) fails with -1
        # here, while the same grow issued from *guest* code gets
        # ST_REGROW and re-executes on a bigger-plane engine, and the
        # SIMT/scalar engines succeed up to the declared max.  Spec-legal
        # (memory.grow may fail at any size) and covered by
        # tests/test_hostcall.py; routing host-driven growth through the
        # ST_REGROW handoff would require parking the whole block
        # mid-serve.  Revisit if a real WASI workload hits it.
        limit = self.page_limit
        if self.max is not None:
            limit = min(limit, self.max)
        if delta < 0 or new > limit or new > 65536:
            return -1
        self._pages = new
        return old

    def load(self, off: int, nbytes: int, signed: bool) -> int:
        self.check_bounds(off, nbytes)
        return int.from_bytes(
            self._cache.read_bytes(self._lane, off, nbytes), "little",
            signed=signed)

    def store(self, off: int, nbytes: int, value: int):
        self.check_bounds(off, nbytes)
        self._cache.write_bytes(
            self._lane, off,
            (value & ((1 << (8 * nbytes)) - 1)).to_bytes(nbytes, "little"))

    def load_bytes(self, off: int, n: int) -> bytes:
        self.check_bounds(off, n)
        return self._cache.read_bytes(self._lane, off, n)

    def store_bytes(self, off: int, data: bytes):
        self.check_bounds(off, len(data))
        self._cache.write_bytes(self._lane, off, bytes(data))

    def as_numpy(self) -> np.ndarray:
        return np.frombuffer(
            self._cache.read_bytes(self._lane, 0, self._nbytes()),
            dtype=np.uint8)
