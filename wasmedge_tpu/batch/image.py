"""DeviceImage: lowered module + instance snapshot -> device-resident tables.

The batch engine does not interpret the 180-op lowered ISA directly; at
image-build time every instruction is re-encoded as (class, sub, a, b, c,
imm_lo, imm_hi) where `class` selects one of ~20 vectorized SIMT handlers
and `sub` selects within a handler's fused select tree (e.g. ALU2 sub 0 =
i32.add). This is the two-level dispatch SURVEY.md §7 predicts the 439-op
switch must become to fit a TPU kernel.

`batchability()` is the feature gate: modules using ops outside the batch
subset (f64 arithmetic, i64<->f32 conversions, table mutation, bulk memory,
multi-value arities > 1, host calls) report a reason and fall back to the
scalar/native engine through the Configure seam — the same graceful
degradation the reference uses when an AOT section mismatches
(/root/reference/lib/loader/ast/module.cpp:279-326).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.common.opcodes import NAME_TO_ID, Op, name_of
from wasmedge_tpu.common.types import PAGE_SIZE
from wasmedge_tpu.validator.image import LOP_BR, LOP_BRNZ, LOP_BRZ, LoweredModule

# -- opcode classes ---------------------------------------------------------
CLS_NOP = 0
CLS_CONST = 1
CLS_LOCAL_GET = 2
CLS_LOCAL_SET = 3
CLS_LOCAL_TEE = 4
CLS_GLOBAL_GET = 5
CLS_GLOBAL_SET = 6
CLS_ALU1 = 7
CLS_ALU2 = 8
CLS_SELECT = 9
CLS_DROP = 10
CLS_BR = 11
CLS_BRZ = 12
CLS_BRNZ = 13
CLS_BR_TABLE = 14
CLS_RETURN = 15
CLS_CALL = 16
CLS_CALL_INDIRECT = 17
CLS_LOAD = 18
CLS_STORE = 19
CLS_MEMSIZE = 20
CLS_MEMGROW = 21
CLS_TRAP = 22
CLS_HOSTCALL = 23  # synthetic stub: park lane for the host outcall channel
CLS_MEMFILL = 24
CLS_MEMCOPY = 25
# v128 (4x int32 planes per cell; op tables in batch/simdops.py)
CLS_VCONST = 26    # a = v128 table idx -> push
CLS_V2 = 27        # sub = V2_SUB id: pop2 push1
CLS_V1 = 28        # sub = V1_SUB id: pop1 push1
CLS_VTEST = 29     # sub = VTEST_SUB id: pop v128 push i32
CLS_VSHIFT = 30    # sub = VSHIFT_SUB id: pop (v128, i32) push v128
CLS_VSPLAT = 31    # sub = VSPLAT_SUB id: pop scalar push v128
CLS_VEXTRACT = 32  # sub = VEXTRACT_SUB id, a = lane: pop v128 push scalar
CLS_VREPLACE = 33  # sub = VREPLACE_SUB id, a = lane: pop2 push v128
CLS_VSHUFFLE = 34  # a = v128 table idx (16-byte mask): pop2 push1
CLS_VBITSEL = 35   # pop3 push1
CLS_VLOAD = 36     # a = offset: pop addr push v128
CLS_VSTORE = 37    # a = offset: pop (addr, v128)
# table / bulk-segment / tail-call families (r05).  The reference runs
# all of these inside its one dispatch loop
# (/root/reference/lib/executor/engine/engine.cpp:181-205 +
# lib/executor/engine/tableInstr.cpp); here they are SIMT handlers over
# a per-lane table plane and per-lane segment-dropped flags.  Device
# funcref domain: funcidx+1, 0 = null (same as table0).  c carries the
# lane's table base inside a concatenated multi-tenant plane, b the
# static table size (per-lane tsize plane overrides when table.grow is
# present).
CLS_TABLE_GET = 38   # pop idx, push ref
CLS_TABLE_SET = 39   # pop (idx, ref)
CLS_TABLE_SIZE = 40  # push size
CLS_TABLE_GROW = 41  # pop (init, delta), push old size | -1
CLS_TABLE_FILL = 42  # pop (i, ref, n)
CLS_TABLE_COPY = 43  # pop (dst, src, n)
CLS_TABLE_INIT = 44  # a = elem seg idx; pop (dst, src, n)
CLS_ELEM_DROP = 45   # a = elem seg idx
CLS_MEMINIT = 46     # a = data seg idx; pop (dst, src, n)
CLS_DATA_DROP = 47   # a = data seg idx
CLS_RETCALL = 48     # a = callee (tail call: frame replacement)
CLS_RETCALL_INDIRECT = 49  # a = dense type id, b = size, c = base
CLS_REFFUNC = 50     # a = funcidx: push device handle a+1 (rebasable)
NUM_CLASSES = 51

# -- ALU2 sub-ops (binary: pop2 push1) --------------------------------------
_I32_BIN = ["add", "sub", "mul", "div_s", "div_u", "rem_s", "rem_u", "and",
            "or", "xor", "shl", "shr_s", "shr_u", "rotl", "rotr",
            "eq", "ne", "lt_s", "lt_u", "gt_s", "gt_u", "le_s", "le_u",
            "ge_s", "ge_u"]
_F32_BIN = ["add", "sub", "mul", "div", "min", "max", "copysign",
            "eq", "ne", "lt", "gt", "le", "ge"]
_F64_BIN = list(_F32_BIN)  # same op set, softfloat binary64 kernels

ALU2_I32_BASE = 0
ALU2_I64_BASE = len(_I32_BIN)           # 25
ALU2_F32_BASE = 2 * len(_I32_BIN)       # 50
ALU2_F64_BASE = ALU2_F32_BASE + len(_F32_BIN)  # 63
NUM_ALU2 = ALU2_F64_BASE + len(_F64_BIN)  # 76

# i64 div/rem are "rare" subs: executed under an any-lane cond (64-iter loop)
RARE_ALU2_SUBS = tuple(ALU2_I64_BASE + _I32_BIN.index(n)
                       for n in ("div_s", "div_u", "rem_s", "rem_u"))

# -- ALU1 sub-ops (unary: pop1 push1) ---------------------------------------
_ALU1 = [
    "i32.clz", "i32.ctz", "i32.popcnt", "i32.eqz",
    "i32.extend8_s", "i32.extend16_s",
    "i64.clz", "i64.ctz", "i64.popcnt", "i64.eqz",
    "i64.extend8_s", "i64.extend16_s", "i64.extend32_s",
    "f32.abs", "f32.neg", "f32.ceil", "f32.floor", "f32.trunc",
    "f32.nearest", "f32.sqrt",
    "i32.wrap_i64", "i64.extend_i32_s", "i64.extend_i32_u",
    "i32.trunc_f32_s", "i32.trunc_f32_u",
    "i32.trunc_sat_f32_s", "i32.trunc_sat_f32_u",
    "f32.convert_i32_s", "f32.convert_i32_u",
    "i32.reinterpret_f32", "f32.reinterpret_i32",
    "ref.is_null",
    # binary64 (softfloat lo/hi-plane kernels, batch/softfloat.py)
    "f64.abs", "f64.neg", "f64.ceil", "f64.floor", "f64.trunc",
    "f64.nearest", "f64.sqrt",
    "f32.demote_f64", "f64.promote_f32",
    "i64.reinterpret_f64", "f64.reinterpret_i64",
    "f64.convert_i32_s", "f64.convert_i32_u",
    "f64.convert_i64_s", "f64.convert_i64_u",
    "f32.convert_i64_s", "f32.convert_i64_u",
    "i32.trunc_f64_s", "i32.trunc_f64_u",
    "i64.trunc_f32_s", "i64.trunc_f32_u",
    "i64.trunc_f64_s", "i64.trunc_f64_u",
    "i32.trunc_sat_f64_s", "i32.trunc_sat_f64_u",
    "i64.trunc_sat_f32_s", "i64.trunc_sat_f32_u",
    "i64.trunc_sat_f64_s", "i64.trunc_sat_f64_u",
]
ALU1_SUB = {n: i for i, n in enumerate(_ALU1)}
NUM_ALU1 = len(_ALU1)

# -- loads/stores -----------------------------------------------------------
_LOADS = {
    "i32.load": (4, 0, 0), "i64.load": (8, 0, 1), "f32.load": (4, 0, 0),
    "f64.load": (8, 0, 1),
    "i32.load8_s": (1, 1, 0), "i32.load8_u": (1, 0, 0),
    "i32.load16_s": (2, 1, 0), "i32.load16_u": (2, 0, 0),
    "i64.load8_s": (1, 1, 1), "i64.load8_u": (1, 0, 1),
    "i64.load16_s": (2, 1, 1), "i64.load16_u": (2, 0, 1),
    "i64.load32_s": (4, 1, 1), "i64.load32_u": (4, 0, 1),
}
_STORES = {
    "i32.store": 4, "i64.store": 8, "f32.store": 4, "f64.store": 8,
    "i32.store8": 1, "i32.store16": 2,
    "i64.store8": 1, "i64.store16": 2, "i64.store32": 4,
}

# Ops outside the batch subset. Modules containing them in *reachable
# batched code* fall back to the scalar engine.  The integer v128
# families are batchable (batch/simdops.py SUPPORTED_V128); the float
# families and the widening/narrowing extensions still gate out.
_UNSUPPORTED_PREFIXES = ("v128.", "i8x16.", "i16x8.", "i32x4.",
                         "i64x2.", "f32x4.", "f64x2.")

# Table ops address only table 0 on the batch engines (the reference's
# multi-table support exists, but multi-table modules fall back).
_TABLE0_OPS = {"table.get", "table.set", "table.size", "table.grow",
               "table.fill"}

TRAP_DONE = -1  # lane finished normally (trap plane sentinel)
TRAP_HOSTCALL = -2  # lane waiting on a host outcall
TRAP_PARKED = -3  # lane suspended on a blocking effect (effects/) —
#                   excluded from the runnable mask like any nonzero
#                   trap; the serving boundary swaps it out and frees
#                   the physical lane

# ---------------------------------------------------------------------------
# Tier-0 hostcalls: "pure" WASI imports the batch kernels can retire
# in-kernel (no device->host round trip).  The stub's t0kind plane entry
# names the call; the engine decides per-config whether to trace the
# in-kernel handler (batch/engine.py) or leave the stub parking as usual.
# ---------------------------------------------------------------------------
T0_NONE = 0
T0_CLOCK_TIME_GET = 1   # time from the per-relaunch time base + seq plane
T0_RANDOM_GET = 2       # counter-PRNG plane (deterministic under cfg seed)
T0_SCHED_YIELD = 3      # no-op, errno SUCCESS
T0_PROC_EXIT = 4        # lane terminates (ErrCode.Terminated, code on stack)
T0_FD_WRITE = 5         # fd 1/2 append into the in-device stdout record buf

T0_WASI_KINDS = {
    "clock_time_get": T0_CLOCK_TIME_GET,
    "random_get": T0_RANDOM_GET,
    "sched_yield": T0_SCHED_YIELD,
    "proc_exit": T0_PROC_EXIT,
    "fd_write": T0_FD_WRITE,
}

_WASI_MODULE = "wasi_snapshot_preview1"

# fd_write may only be serviced from the in-device stdout buffer when no
# other import can observe or mutate fd-table state mid-run (a guest that
# can close/renumber/seek fds would make the kernel's "fd 1/2 is a plain
# sink" assumption stale).  Anything in these families other than
# fd_write itself disables the fd_write tier-0 path for the module.
_T0_FD_UNSAFE_PREFIXES = ("fd_", "path_", "sock_", "poll_")

# Tier-0 kinds that write through guest linear memory — serviceable
# in-kernel only when the module has one (engine.t0_effective_kinds and
# the static analyzer share this set).
T0_NEEDS_MEMORY = (T0_CLOCK_TIME_GET, T0_RANDOM_GET, T0_FD_WRITE)


def classify_t0_imports(funcs) -> Tuple[dict, bool]:
    """Per-import tier-0 kind + module-level fd_write safety over a
    FuncMeta list: {func_idx: T0_*} and whether fd_write may buffer
    in-device.  The ONE source for the import-gating rules — consumed
    by build_device_image (t0kind plane, t0_fdwrite_safe) and the
    static analyzer (analysis/analyzer.py), so admission verdicts can
    never drift from what the engine services in-kernel."""
    kinds = {}
    fdwrite_safe = True
    for idx, fn in enumerate(funcs):
        if not fn.is_import:
            continue
        if fn.import_module == _WASI_MODULE:
            kinds[idx] = T0_WASI_KINDS.get(fn.import_name, T0_NONE)
            if fn.import_name != "fd_write" and fn.import_name.startswith(
                    _T0_FD_UNSAFE_PREFIXES):
                fdwrite_safe = False
        else:
            # non-WASI host imports can do anything — a custom import
            # observing output ordering would make in-device stdout
            # buffering visible; keep fd_write conservative.  The
            # "wasmedge" effect-handler module (effects/hostfuncs.py)
            # is OURS and fd-inert: await_event only touches its own
            # guest buffer, so it must not demote a module's stdout to
            # tier-1 — streaming and exactly-once stdout both ride the
            # tier-0 flush cursor
            kinds[idx] = T0_NONE
            if fn.import_module != "wasmedge":
                fdwrite_safe = False
    return kinds, fdwrite_safe




def _i32(v: int) -> np.int32:
    """Wrap an unsigned value into int32 two's complement."""
    v &= 0xFFFFFFFF
    return np.int32(v - (1 << 32) if v >= (1 << 31) else v)


def batchability(image: LoweredModule,
                 host_imports: Optional[set] = None,
                 n_memories: int = 1) -> Optional[str]:
    """None if the module image can run on the batch engine, else reason.

    host_imports: func indices backed by host functions the engine can
    serve through the outcall channel (batch/hostcall.py); imports outside
    it (e.g. cross-module wasm imports) stay unbatchable.
    n_memories: linear memories on the instance — the lane state carries
    exactly one mem plane, so multi-memory modules (MultiMemories
    proposal) fall back rather than silently addressing memory 0."""
    if n_memories > 1:
        return "multiple memories"
    for idx, fn in enumerate(image.funcs):
        if fn.is_import:
            if host_imports is None or idx not in host_imports:
                return (f"unservable imported function "
                        f"{fn.import_module}.{fn.import_name}")
        if fn.nresults > 1:
            return "multi-value results"
    for pc in range(image.code_len):
        op = image.op[pc]
        if op in (LOP_BR, LOP_BRZ, LOP_BRNZ):
            if image.b[pc] > 1:
                return "multi-value branch arity"
            continue
        name = name_of(op)
        if name == "br_table":
            base, n = image.a[pc], image.b[pc]
            for e in range(n + 1):
                if image.br_table[(base + e) * 3 + 1] > 1:
                    return "multi-value branch arity"
            continue
        if name == "return" and image.b[pc] > 1:
            return "multi-value results"
        if any(name.startswith(p) for p in _UNSUPPORTED_PREFIXES):
            from wasmedge_tpu.batch.simdops import SUPPORTED_V128

            if name not in SUPPORTED_V128:
                return f"unsupported op {name}"
        if name in _TABLE0_OPS and image.a[pc] != 0:
            return f"{name} on table != 0"
        if name == "table.copy" and (image.a[pc] != 0 or image.b[pc] != 0):
            return "table.copy on table != 0"
        if name == "table.init" and image.b[pc] != 0:
            return "table.init on table != 0"
        if name in ("call_indirect", "return_call_indirect") \
                and image.b[pc] != 0:
            return f"{name} on table != 0"
    return None


@dataclasses.dataclass
class DeviceImage:
    """Numpy-side image; the engine moves these to device once per module."""

    # per-pc planes [code_len]
    cls: np.ndarray
    sub: np.ndarray
    a: np.ndarray
    b: np.ndarray
    c: np.ndarray
    imm_lo: np.ndarray
    imm_hi: np.ndarray
    br_table: np.ndarray  # [n_entries, 3]
    # per-function planes [n_funcs]
    f_entry: np.ndarray
    f_nparams: np.ndarray
    f_nlocals: np.ndarray
    f_nresults: np.ndarray
    f_frame_top: np.ndarray  # nlocals + max_height: stack room a frame needs
    f_type: np.ndarray  # dense functype id for call_indirect checks
    # instance snapshot
    table0: np.ndarray  # [table_size] funcidx+1, 0=null
    globals_lo: np.ndarray
    globals_hi: np.ndarray
    mem_init: np.ndarray  # [mem_words] int32 initial memory content
    mem_pages_init: int
    mem_pages_max: int
    has_memory: bool
    max_local_zeros: int  # max (nlocals - nparams) over funcs
    code_len: int
    # v128 constant/shuffle-mask table as 4 int32 planes [n, 4]
    v128: np.ndarray = None
    has_simd: bool = False
    # passive/active segment snapshots for table.init / memory.init
    # (funcref domain funcidx+1; data packed little-endian into words)
    elem_flat: np.ndarray = None   # [sum lens] int32
    elem_off: np.ndarray = None    # [nseg] int32
    elem_len: np.ndarray = None    # [nseg] int32
    data_words: np.ndarray = None  # [ceil(bytes/4)] int32
    data_off: np.ndarray = None    # [ndseg] byte offsets
    data_len: np.ndarray = None    # [ndseg] byte lengths
    # original opcode id per pc (Statistics cost-table domain; stubs
    # and padding carry nop) — the per-opcode gas weights gather through
    # this plane (reference CostTab: include/common/statistics.h:85-98)
    op_id: np.ndarray = None
    table_max: int = 0             # declared table0 max (0 = none)
    table_cap: int = 0             # per-lane table plane rows (engine clamps)
    table_size_init: int = 0       # true initial size (table0 is pad>=1)
    has_table_mut: bool = False    # any set/grow/fill/copy/init
    has_table_grow: bool = False
    # tier-0 hostcall kind per pc (T0_* above; nonzero only at HOSTCALL
    # stubs of recognized pure WASI imports).  None = no tier-0 service
    # (e.g. multi-tenant concatenated images keep every call on the
    # per-tenant outcall channel).
    t0kind: np.ndarray = None
    # fd_write tier-0 is additionally gated on the module's import set —
    # see _T0_FD_UNSAFE_PREFIXES
    t0_fdwrite_safe: bool = False
    # --- superinstruction fusion planes (batch/fuse.py plan_fusion) ---
    # fuse_len[pc]: at the HEAD pc of a fused straight-line run, the
    # number of constituent ops (>= 2); 0 everywhere else.  The
    # original per-pc cells are NEVER overwritten — a lane whose pc
    # sits mid-run (residue handoff, hostcall re-arm, swap-in restore)
    # executes the original per-op stream until the next head, and a
    # lane without the fuel to retire the whole run steps through the
    # originals so gas exhaustion lands at the correct op.
    fuse_len: np.ndarray = None
    # fuse_pat[pc]: fused-cell pattern id at run heads, -1 elsewhere.
    fuse_pat: np.ndarray = None
    # Ordered pattern table: tuple of ((cls, sub), ...) per pattern id.
    fuse_patterns: tuple = None
    # Planner report: planned-vs-realized per analyzer candidate plus
    # the realized run list (head pc, len, pattern) — the analyze CLI
    # and the --fuse-smoke guard read it.  None = planning never ran.
    fusion_report: dict = None
    # Static-analysis thunk (wasmedge_tpu/analysis/), bound at build
    # time and evaluated on FIRST ACCESS of `.analysis` — run/serve
    # startups that never read the report never pay for it.  Advisory
    # metadata only: nothing in the execution path reads it
    # (analysis-off runs are bit-identical by construction); the
    # gateway admission policy and the superinstruction tier
    # (ROADMAP #3) are the consumers.
    analysis_builder: object = None

    @property
    def analysis(self):
        """ModuleAnalysis of the lowered module, built lazily and
        cached; None when no builder was bound (e.g. concatenated
        multi-tenant images) or the analyzer failed — admission
        policies treat None as a violation, never as a pass."""
        cached = self.__dict__.get("_analysis", _ANALYSIS_UNSET)
        if cached is _ANALYSIS_UNSET:
            cached = None
            if self.analysis_builder is not None:
                try:
                    cached = self.analysis_builder()
                except Exception:
                    cached = None
            self.__dict__["_analysis"] = cached
        return cached


_ANALYSIS_UNSET = object()


def build_device_image(image: LoweredModule, memories=None, globals_=None,
                       table0=None, mod=None, elem_segs=None,
                       data_segs=None) -> DeviceImage:
    # Imported (host) functions get a 2-instruction synthetic stub after
    # the module code: HOSTCALL (parks the lane; the host writes results
    # at the frame's operand base and re-arms at the next pc) followed by
    # RETURN.  f_entry points imports at their stub, so CALL needs no
    # special casing — the reference's 3-way enterFunction dispatch
    # (helper.cpp:35-97) becomes one extra opcode class.
    imports = [i for i, fn in enumerate(image.funcs) if fn.is_import]
    n = image.code_len + 2 * len(imports)
    cls = np.zeros(n, np.int32)
    sub = np.zeros(n, np.int32)
    a = np.zeros(n, np.int32)
    b = np.zeros(n, np.int32)
    c = np.zeros(n, np.int32)
    imm_lo = np.zeros(n, np.int32)
    imm_hi = np.zeros(n, np.int32)

    # Dense structural functype ids, shared by function table and
    # call_indirect immediates (typecheck is id equality on device).
    type_ids = {}

    def _dense_type(type_idx: int) -> int:
        key = type_idx
        if mod is not None:
            ft = mod.types[type_idx]
            key = (ft.params, ft.results)
        return type_ids.setdefault(key, len(type_ids))

    if table0 is None:
        table0 = np.zeros(1, np.int32)
    else:
        table0 = np.asarray(table0, np.int32)
    # call_indirect's bounds check uses the instruction's `b` (true size);
    # the array itself is padded so a declared-but-empty table still
    # yields a gatherable plane (the padding slot is null and unreachable)
    table_size = len(table0)
    if table_size == 0:
        table0 = np.zeros(1, np.int32)

    from wasmedge_tpu.batch.simdops import (
        V1_SUB, V2_SUB, VEXTRACT_SUB, VREPLACE_SUB, VSHIFT_SUB,
        VSPLAT_SUB, VTEST_SUB)

    v2_ops = {NAME_TO_ID[n]: s for n, s in V2_SUB.items()}
    v1_ops = {NAME_TO_ID[n]: s for n, s in V1_SUB.items()}
    vtest_ops = {NAME_TO_ID[n]: s for n, s in VTEST_SUB.items()}
    vshift_ops = {NAME_TO_ID[n]: s for n, s in VSHIFT_SUB.items()}
    vsplat_ops = {NAME_TO_ID[n]: s for n, s in VSPLAT_SUB.items()}
    vextract_ops = {NAME_TO_ID[n]: s for n, s in VEXTRACT_SUB.items()}
    vreplace_ops = {NAME_TO_ID[n]: s for n, s in VREPLACE_SUB.items()}
    op_vconst = NAME_TO_ID["v128.const"]
    op_vshuffle = NAME_TO_ID["i8x16.shuffle"]
    op_vbitsel = NAME_TO_ID["v128.bitselect"]
    op_vload = NAME_TO_ID["v128.load"]
    op_vstore = NAME_TO_ID["v128.store"]

    i32_bin = {NAME_TO_ID[f"i32.{s}"]: ALU2_I32_BASE + i
               for i, s in enumerate(_I32_BIN)}
    i64_bin = {NAME_TO_ID[f"i64.{s}"]: ALU2_I64_BASE + i
               for i, s in enumerate(_I32_BIN)}
    f32_bin = {NAME_TO_ID[f"f32.{s}"]: ALU2_F32_BASE + i
               for i, s in enumerate(_F32_BIN)}
    f64_bin = {NAME_TO_ID[f"f64.{s}"]: ALU2_F64_BASE + i
               for i, s in enumerate(_F64_BIN)}
    alu1 = {NAME_TO_ID[nm]: s for nm, s in ALU1_SUB.items()}
    loads = {NAME_TO_ID[nm]: v for nm, v in _LOADS.items()}
    stores = {NAME_TO_ID[nm]: v for nm, v in _STORES.items()}
    consts = {Op.i32_const, Op.i64_const, Op.f32_const, Op.f64_const}
    op_return = NAME_TO_ID["return"]

    op_id = np.full(n, int(Op.nop), np.int32)
    op_id[:image.code_len] = np.asarray(
        image.op[:image.code_len], np.int32)

    stub_pc = {}
    t0kind = np.zeros(n, np.int32)
    t0_kind_of, t0_fdwrite_safe = classify_t0_imports(image.funcs)
    for si, k in enumerate(imports):
        at = image.code_len + 2 * si
        stub_pc[k] = at
        cls[at] = CLS_HOSTCALL
        a[at] = k
        cls[at + 1] = CLS_RETURN
        b[at + 1] = image.funcs[k].nresults
        t0kind[at] = t0_kind_of.get(k, T0_NONE)

    for pc in range(image.code_len):
        op = image.op[pc]
        ia, ib, ic, imm = image.a[pc], image.b[pc], image.c[pc], image.imm[pc]
        if op == LOP_BR:
            cls[pc], a[pc], b[pc], c[pc] = CLS_BR, ia, ib, ic
        elif op == LOP_BRZ:
            cls[pc], a[pc] = CLS_BRZ, ia
        elif op == LOP_BRNZ:
            cls[pc], a[pc], b[pc], c[pc] = CLS_BRNZ, ia, ib, ic
        elif op == Op.br_table:
            cls[pc], a[pc], b[pc] = CLS_BR_TABLE, ia, ib
        elif op == op_return:
            cls[pc], b[pc] = CLS_RETURN, ib
        elif op == Op.call:
            cls[pc], a[pc] = CLS_CALL, ia
        elif op == Op.call_indirect:
            # a = dense type id, b = table size, c = table base offset —
            # base/size in the instruction keep multi-tenant concatenated
            # tables addressable per lane (batch/multitenant.py)
            cls[pc], a[pc] = CLS_CALL_INDIRECT, _dense_type(ia)
            b[pc] = table_size
            c[pc] = 0
        elif op in consts:
            cls[pc] = CLS_CONST
            imm_lo[pc] = _i32(imm)
            imm_hi[pc] = _i32(imm >> 32)
        elif op == Op.ref_null:
            cls[pc] = CLS_CONST
        elif op == Op.local_get:
            cls[pc], a[pc] = CLS_LOCAL_GET, ia
        elif op == Op.local_set:
            cls[pc], a[pc] = CLS_LOCAL_SET, ia
        elif op == Op.local_tee:
            cls[pc], a[pc] = CLS_LOCAL_TEE, ia
        elif op == Op.global_get:
            cls[pc], a[pc] = CLS_GLOBAL_GET, ia
        elif op == Op.global_set:
            cls[pc], a[pc] = CLS_GLOBAL_SET, ia
        elif op in i32_bin:
            cls[pc], sub[pc] = CLS_ALU2, i32_bin[op]
        elif op in i64_bin:
            cls[pc], sub[pc] = CLS_ALU2, i64_bin[op]
        elif op in f32_bin:
            cls[pc], sub[pc] = CLS_ALU2, f32_bin[op]
        elif op in f64_bin:
            cls[pc], sub[pc] = CLS_ALU2, f64_bin[op]
        elif op in alu1:
            cls[pc], sub[pc] = CLS_ALU1, alu1[op]
        elif op in loads:
            nbytes, signed, is64 = loads[op]
            cls[pc] = CLS_LOAD
            a[pc] = _i32(imm)  # static offset
            b[pc] = nbytes
            c[pc] = signed | (is64 << 1)
        elif op in stores:
            cls[pc] = CLS_STORE
            a[pc] = _i32(imm)
            b[pc] = stores[op]
        elif op == op_vconst:
            cls[pc], a[pc] = CLS_VCONST, ia
        elif op == op_vshuffle:
            cls[pc], a[pc] = CLS_VSHUFFLE, ia
        elif op == op_vbitsel:
            cls[pc] = CLS_VBITSEL
        elif op == op_vload:
            cls[pc], a[pc] = CLS_VLOAD, _i32(imm)
        elif op == op_vstore:
            cls[pc], a[pc] = CLS_VSTORE, _i32(imm)
        elif op in v2_ops:
            cls[pc], sub[pc] = CLS_V2, v2_ops[op]
        elif op in v1_ops:
            cls[pc], sub[pc] = CLS_V1, v1_ops[op]
        elif op in vtest_ops:
            cls[pc], sub[pc] = CLS_VTEST, vtest_ops[op]
        elif op in vshift_ops:
            cls[pc], sub[pc] = CLS_VSHIFT, vshift_ops[op]
        elif op in vsplat_ops:
            cls[pc], sub[pc] = CLS_VSPLAT, vsplat_ops[op]
        elif op in vextract_ops:
            cls[pc], sub[pc], a[pc] = CLS_VEXTRACT, vextract_ops[op], ia
        elif op in vreplace_ops:
            cls[pc], sub[pc], a[pc] = CLS_VREPLACE, vreplace_ops[op], ia
        elif op == Op.memory_fill:
            cls[pc] = CLS_MEMFILL
        elif op == Op.memory_copy:
            cls[pc] = CLS_MEMCOPY
        elif op == Op.table_get:
            cls[pc], b[pc] = CLS_TABLE_GET, table_size
        elif op == Op.table_set:
            cls[pc], b[pc] = CLS_TABLE_SET, table_size
        elif op == Op.table_size:
            cls[pc], b[pc] = CLS_TABLE_SIZE, table_size
        elif op == Op.table_grow:
            cls[pc], b[pc] = CLS_TABLE_GROW, table_size
        elif op == Op.table_fill:
            cls[pc], b[pc] = CLS_TABLE_FILL, table_size
        elif op == Op.table_copy:
            cls[pc], b[pc] = CLS_TABLE_COPY, table_size
        elif op == Op.table_init:
            cls[pc], a[pc], b[pc] = CLS_TABLE_INIT, ia, table_size
        elif op == Op.elem_drop:
            cls[pc], a[pc] = CLS_ELEM_DROP, ia
        elif op == Op.memory_init:
            cls[pc], a[pc] = CLS_MEMINIT, ia
        elif op == Op.data_drop:
            cls[pc], a[pc] = CLS_DATA_DROP, ia
        elif op == Op.ref_func:
            # device funcref domain: funcidx+1 (matches table0 cells).
            # Own class (not CLS_CONST) so multi-tenant concatenation can
            # rebase the function index (multitenant.py concat_images)
            cls[pc], a[pc] = CLS_REFFUNC, ia
        elif op == Op.return_call:
            cls[pc], a[pc] = CLS_RETCALL, ia
        elif op == Op.return_call_indirect:
            cls[pc], a[pc] = CLS_RETCALL_INDIRECT, _dense_type(ia)
            b[pc] = table_size
            c[pc] = 0
        elif op == Op.memory_size:
            cls[pc] = CLS_MEMSIZE
        elif op == Op.memory_grow:
            cls[pc] = CLS_MEMGROW
        elif op == Op.select:
            cls[pc] = CLS_SELECT
        elif op == Op.drop:
            cls[pc] = CLS_DROP
        elif op == Op.nop:
            cls[pc] = CLS_NOP
        elif op == Op.unreachable:
            cls[pc], a[pc] = CLS_TRAP, int(ErrCode.Unreachable)
        else:
            # batchability() should have rejected; encode a trap as backstop
            cls[pc], a[pc] = CLS_TRAP, int(ErrCode.ExecutionFailed)

    nf = len(image.funcs)
    f_entry = np.zeros(nf, np.int32)
    f_nparams = np.zeros(nf, np.int32)
    f_nlocals = np.zeros(nf, np.int32)
    f_nresults = np.zeros(nf, np.int32)
    f_frame_top = np.zeros(nf, np.int32)
    f_type = np.zeros(nf, np.int32)
    max_zeros = 0
    for i, fn in enumerate(image.funcs):
        if fn.is_import:
            f_entry[i] = stub_pc[i]
            f_nparams[i] = fn.nparams
            f_nlocals[i] = fn.nparams
            f_nresults[i] = fn.nresults
            f_frame_top[i] = fn.nparams + max(fn.nresults, 1)
            f_type[i] = _dense_type(fn.type_idx)
            continue
        f_entry[i] = fn.entry_pc
        f_nparams[i] = fn.nparams
        f_nlocals[i] = fn.nlocals
        f_nresults[i] = fn.nresults
        f_frame_top[i] = fn.nlocals + fn.max_height
        f_type[i] = _dense_type(fn.type_idx)
        max_zeros = max(max_zeros, fn.nlocals - fn.nparams)

    # instance snapshots (table0: [size] of funcidx+1, 0 = null)
    ng = len(globals_) if globals_ else 0
    g_lo = np.zeros(max(ng, 1), np.int32)
    g_hi = np.zeros(max(ng, 1), np.int32)
    for i in range(ng):
        v = globals_[i].value
        g_lo[i] = _i32(v)
        g_hi[i] = _i32(v >> 32)

    if memories:
        m = memories[0]
        raw = np.frombuffer(bytes(m.data), dtype=np.uint8)
        pad = (-len(raw)) % 4
        if pad:
            raw = np.concatenate([raw, np.zeros(pad, np.uint8)])
        mem_init = raw.view(np.int32).astype(np.int32)
        pages_init = m.pages
        pages_max = m.max if m.max is not None else 0  # 0 = no declared max
    else:
        mem_init = np.zeros(1, np.int32)
        pages_init = 0
        pages_max = 0

    v_lo = image.arrays["v128_lo"]
    v_hi = image.arrays["v128_hi"]
    v128 = np.zeros((max(len(v_lo), 1), 4), np.int32)
    for i in range(len(v_lo)):
        v128[i, 0] = _i32(int(v_lo[i]))
        v128[i, 1] = _i32(int(v_lo[i]) >> 32)
        v128[i, 2] = _i32(int(v_hi[i]))
        v128[i, 3] = _i32(int(v_hi[i]) >> 32)
    has_simd = bool(((cls >= CLS_VCONST) & (cls <= CLS_VSTORE)).any())

    # segment snapshots (table.init / memory.init sources; per-lane
    # dropped flags live in engine state, not here)
    esegs = elem_segs or []
    elem_off = np.zeros(max(len(esegs), 1), np.int32)
    elem_len = np.zeros(max(len(esegs), 1), np.int32)
    eflat: list = []
    for i, seg in enumerate(esegs):
        elem_off[i] = len(eflat)
        elem_len[i] = len(seg)
        eflat.extend(int(x) for x in seg)
    elem_flat = np.asarray(eflat or [0], np.int32)
    dsegs = data_segs or []
    data_off = np.zeros(max(len(dsegs), 1), np.int32)
    data_len = np.zeros(max(len(dsegs), 1), np.int32)
    dbytes = bytearray()
    for i, seg in enumerate(dsegs):
        data_off[i] = len(dbytes)
        data_len[i] = len(seg)
        dbytes.extend(seg)
    while len(dbytes) % 4:
        dbytes.append(0)
    data_words = (np.frombuffer(bytes(dbytes), np.uint8).view(np.int32)
                  .astype(np.int32) if dbytes else np.zeros(1, np.int32))

    table_max = 0
    if mod is not None and getattr(mod, "tables", None):
        lim = mod.tables[0].limit
        table_max = lim.max if lim.max is not None else 0
    _TMUT = (CLS_TABLE_SET, CLS_TABLE_GROW, CLS_TABLE_FILL,
             CLS_TABLE_COPY, CLS_TABLE_INIT)
    has_table_mut = bool(np.isin(cls, _TMUT).any())
    has_table_grow = bool((cls == CLS_TABLE_GROW).any())

    # Static analysis rides the image (same lowering the batchability
    # probe used — the gateway never analyzes from scratch), bound as
    # a thunk the `.analysis` property evaluates on first access: a
    # run/serve that never reads the report never pays for it.  The
    # declared (pre-knob-clamp) page values are captured HERE — the
    # engine mutates img.mem_pages_max afterwards and footprint policy
    # must judge what the module declares, not one host's clamp.
    exports = None
    if mod is not None:
        exports = {e.name: e.index for e in mod.exports if e.kind == 0}

    def _analysis_builder(_image=image, _exports=exports,
                          _init=pages_init, _max=pages_max,
                          _has_mem=bool(memories),
                          _globals=[int(g.value) for g in (globals_ or ())]
                          or None):
        from wasmedge_tpu.analysis import analyze_module

        return analyze_module(_image, exports=_exports,
                              mem_pages_init=_init, mem_pages_max=_max,
                              has_memory=_has_mem,
                              globals_init=_globals)

    return DeviceImage(
        cls=cls, sub=sub, a=a, b=b, c=c, imm_lo=imm_lo, imm_hi=imm_hi,
        br_table=image.arrays["br_table"],
        f_entry=f_entry, f_nparams=f_nparams, f_nlocals=f_nlocals,
        f_nresults=f_nresults, f_frame_top=f_frame_top, f_type=f_type,
        table0=table0, globals_lo=g_lo, globals_hi=g_hi,
        mem_init=mem_init, mem_pages_init=pages_init, mem_pages_max=pages_max,
        has_memory=bool(memories),
        max_local_zeros=max_zeros, code_len=n,
        v128=v128, has_simd=has_simd,
        elem_flat=elem_flat, elem_off=elem_off, elem_len=elem_len,
        data_words=data_words, data_off=data_off, data_len=data_len,
        op_id=op_id,
        table_max=table_max, table_cap=len(table0),
        table_size_init=table_size,
        has_table_mut=has_table_mut, has_table_grow=has_table_grow,
        t0kind=t0kind, t0_fdwrite_safe=t0_fdwrite_safe,
        analysis_builder=_analysis_builder,
    )


def image_fingerprint(img: DeviceImage) -> str:
    """Content fingerprint of a DeviceImage's static planes (sha256 over
    the code/function/snapshot arrays plus the fusion/tier attributes).

    The imagestore segment cache (wasmedge_tpu/imagestore/segments.py)
    keys memoized concat segments on this: two engines lowered from
    identical bytes under identical knobs fingerprint identically, and
    a re-planned image (fusion/tierup planes bound later) fingerprints
    differently — a stale segment can never alias a changed image.
    Cached on the instance: the planes are frozen after normalization,
    so one hash per image covers every later generation build."""
    import hashlib

    cached = getattr(img, "_fingerprint", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for name in ("cls", "sub", "a", "b", "c", "imm_lo", "imm_hi",
                 "op_id", "br_table", "f_entry", "f_nparams",
                 "f_nlocals", "f_nresults", "f_frame_top", "f_type",
                 "table0", "globals_lo", "globals_hi", "mem_init",
                 "v128", "elem_flat", "elem_off", "elem_len",
                 "data_words", "data_off", "data_len", "fuse_len",
                 "fuse_pat", "tier_fn", "tier_fuel_bound"):
        arr = getattr(img, name, None)
        h.update(name.encode())
        if arr is None:
            h.update(b"\x00")
            continue
        arr = np.ascontiguousarray(arr)
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(arr.tobytes())
    for scalar in (img.mem_pages_init, img.mem_pages_max,
                   int(img.has_memory), img.max_local_zeros,
                   img.code_len, int(img.has_simd), img.table_cap,
                   img.table_size_init,
                   int(getattr(img, "has_table_mut", False)),
                   int(getattr(img, "has_table_grow", False)),
                   len(getattr(img, "fuse_patterns", None) or ()),
                   len(getattr(img, "tier_fns", None) or ())):
        h.update(str(int(scalar)).encode() + b",")
    for key in getattr(img, "fuse_patterns", None) or ():
        h.update(repr(key).encode())
    fp = h.hexdigest()
    try:
        img._fingerprint = fp
    except Exception:
        pass  # frozen dataclass variants: recompute per call
    return fp
