"""Vectorized lane-level value operations for the batch engine.

Value encoding: each 64-bit wasm cell is two int32 planes (lo, hi).
i32/f32 use lo only (hi kept zero for i32 results to keep cells canonical);
i64/f64-bits span both. All functions here are elementwise over [lanes]
arrays and shape-polymorphic — the pallas kernel reuses them unchanged.

Semantics match executor/numeric.py bit-for-bit (the parity tests in
tests/test_batch_parity.py enforce this lane-by-lane).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

I32 = jnp.int32
# Host-side (numpy) scalars, not device arrays: pallas kernels trace these
# functions and cannot capture concrete jax Arrays as closure constants.
_SIGN = np.int32(-0x80000000)  # 0x80000000 as int32
_TRAP_INVALID_CONV = 0x86   # ErrCode.InvalidConvToInt
_TRAP_INT_OVERFLOW = 0x85   # ErrCode.IntegerOverflow


def u_lt(a, b):
    """Unsigned < on int32 planes via sign-bias trick."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def u_le(a, b):
    return (a ^ _SIGN) <= (b ^ _SIGN)


def b2i(x):
    return x.astype(I32)


def to_f32(lo):
    return lax.bitcast_convert_type(lo, jnp.float32)


def from_f32(f):
    return lax.bitcast_convert_type(f, jnp.int32)


F32_CANON_NAN = np.int32(0x7FC00000)


def canon32(bits):
    """Canonicalize NaN bit patterns (policy shared with the oracle)."""
    exp_all = (bits & jnp.int32(0x7F800000)) == jnp.int32(0x7F800000)
    frac = (bits & jnp.int32(0x007FFFFF)) != 0
    return jnp.where(exp_all & frac, F32_CANON_NAN, bits)


# ---------------------------------------------------------------------------
# i32 scalar-plane ops
# ---------------------------------------------------------------------------

def shamt32(b):
    return b & 31


def rotl32(a, n):
    n = n & 31
    return lax.shift_left(a, n) | lax.shift_right_logical(a, (32 - n) & 31) & \
        jnp.where(n == 0, 0, -1)


def clz32(v):
    return lax.clz(v)


def ctz32(v):
    # popcount((v & -v) - 1); v==0 -> popcount(-1) = 32
    return lax.population_count((v & -v) - 1)


# ---------------------------------------------------------------------------
# i64 pair-plane ops
# ---------------------------------------------------------------------------

def add64(alo, ahi, blo, bhi):
    lo = alo + blo
    carry = b2i(u_lt(lo, alo))
    return lo, ahi + bhi + carry


def sub64(alo, ahi, blo, bhi):
    lo = alo - blo
    borrow = b2i(u_lt(alo, blo))
    return lo, ahi - bhi - borrow


def _umul32_wide(a, b):
    """32x32 -> 64 unsigned multiply on int32 planes via 16-bit halves."""
    a0 = a & 0xFFFF
    a1 = lax.shift_right_logical(a, 16)
    b0 = b & 0xFFFF
    b1 = lax.shift_right_logical(b, 16)
    ll = a0 * b0                      # <= 2^32-2^17+1, wraps fine in i32? no: fits 32 bits unsigned
    lh = a0 * b1
    hl = a1 * b0
    hh = a1 * b1
    # low = ll + ((lh + hl) << 16); compute with carries
    mid = lh + hl                     # may wrap past 2^32: detect
    mid_carry = b2i(u_lt(mid, lh))    # wrapped -> add 2^32 at bit 48 => hh += 2^16
    lo = ll + lax.shift_left(mid, 16)
    lo_carry = b2i(u_lt(lo, ll))
    hi = hh + lax.shift_right_logical(mid, 16) + lax.shift_left(mid_carry, 16) + lo_carry
    return lo, hi


def mul64(alo, ahi, blo, bhi):
    lo, hi = _umul32_wide(alo, blo)
    hi = hi + alo * bhi + ahi * blo
    return lo, hi


def neg64(lo, hi):
    nlo = -lo
    nhi = ~hi + b2i(lo == 0)
    return nlo, nhi


def shl64(lo, hi, n):
    n = n & 63
    big = n >= 32
    ns = n & 31
    # n < 32 case
    lo_s = lax.shift_left(lo, ns)
    hi_s = lax.shift_left(hi, ns) | jnp.where(
        ns == 0, 0, lax.shift_right_logical(lo, (32 - ns) & 31))
    # n >= 32 case
    hi_b = lax.shift_left(lo, ns)
    return jnp.where(big, 0, lo_s), jnp.where(big, hi_b, hi_s)


def shr64_u(lo, hi, n):
    n = n & 63
    big = n >= 32
    ns = n & 31
    lo_s = lax.shift_right_logical(lo, ns) | jnp.where(
        ns == 0, 0, lax.shift_left(hi, (32 - ns) & 31))
    hi_s = lax.shift_right_logical(hi, ns)
    lo_b = lax.shift_right_logical(hi, ns)
    return jnp.where(big, lo_b, lo_s), jnp.where(big, 0, hi_s)


def shr64_s(lo, hi, n):
    n = n & 63
    big = n >= 32
    ns = n & 31
    lo_s = lax.shift_right_logical(lo, ns) | jnp.where(
        ns == 0, 0, lax.shift_left(hi, (32 - ns) & 31))
    hi_s = lax.shift_right_arithmetic(hi, ns)
    lo_b = lax.shift_right_arithmetic(hi, ns)
    sign = lax.shift_right_arithmetic(hi, 31)
    return jnp.where(big, lo_b, lo_s), jnp.where(big, sign, hi_s)


def rotl64(lo, hi, n):
    n = n & 63
    l1, h1 = shl64(lo, hi, n)
    l2, h2 = shr64_u(lo, hi, (64 - n) & 63)
    nz = n != 0
    return l1 | jnp.where(nz, l2, 0), h1 | jnp.where(nz, h2, 0)


def rotr64(lo, hi, n):
    return rotl64(lo, hi, (64 - (n & 63)) & 63)


def clz64(lo, hi):
    return jnp.where(hi == 0, 32 + lax.clz(lo), lax.clz(hi))


def ctz64(lo, hi):
    return jnp.where(lo == 0, 32 + ctz32(hi), ctz32(lo))


def popcnt64(lo, hi):
    return lax.population_count(lo) + lax.population_count(hi)


def eq64(alo, ahi, blo, bhi):
    return (alo == blo) & (ahi == bhi)


def lt64_s(alo, ahi, blo, bhi):
    return (ahi < bhi) | ((ahi == bhi) & u_lt(alo, blo))


def lt64_u(alo, ahi, blo, bhi):
    return u_lt(ahi, bhi) | ((ahi == bhi) & u_lt(alo, blo))


# -- unsigned 64-bit divide: restoring long division, 64 fixed iterations --
def divmod64_u(nlo, nhi, dlo, dhi):
    """Returns (qlo, qhi, rlo, rhi); divisor 0 must be guarded by caller."""

    def body(i, st):
        qlo, qhi, rlo, rhi = st
        bit_idx = 63 - i
        # r = (r << 1) | bit(n, bit_idx)
        nbit = jnp.where(
            bit_idx >= 32,
            lax.shift_right_logical(nhi, bit_idx - 32) & 1,
            lax.shift_right_logical(nlo, bit_idx & 31) & 1,
        )
        rlo2, rhi2 = shl64(rlo, rhi, jnp.int32(1))
        rlo2 = rlo2 | nbit
        ge = ~lt64_u(rlo2, rhi2, dlo, dhi)  # r >= d
        slo, shi = sub64(rlo2, rhi2, dlo, dhi)
        rlo3 = jnp.where(ge, slo, rlo2)
        rhi3 = jnp.where(ge, shi, rhi2)
        qbit = b2i(ge)
        qlo2 = jnp.where(bit_idx < 32, qlo | lax.shift_left(qbit, bit_idx & 31), qlo)
        qhi2 = jnp.where(bit_idx >= 32, qhi | lax.shift_left(qbit, (bit_idx - 32) & 31), qhi)
        return qlo2, qhi2, rlo3, rhi3

    z = jnp.zeros_like(nlo)
    return lax.fori_loop(0, 64, body, (z, z, z, z))


def div64_s(nlo, nhi, dlo, dhi):
    nneg = nhi < 0
    dneg = dhi < 0
    anlo, anhi = neg64(nlo, nhi)
    ulo = jnp.where(nneg, anlo, nlo)
    uhi = jnp.where(nneg, anhi, nhi)
    adlo, adhi = neg64(dlo, dhi)
    vlo = jnp.where(dneg, adlo, dlo)
    vhi = jnp.where(dneg, adhi, dhi)
    qlo, qhi, rlo, rhi = divmod64_u(ulo, uhi, vlo, vhi)
    qneg = nneg != dneg
    nqlo, nqhi = neg64(qlo, qhi)
    nrlo, nrhi = neg64(rlo, rhi)
    return (
        jnp.where(qneg, nqlo, qlo), jnp.where(qneg, nqhi, qhi),
        jnp.where(nneg, nrlo, rlo), jnp.where(nneg, nrhi, rhi),
    )


# ---------------------------------------------------------------------------
# f32 ops with wasm semantics
# ---------------------------------------------------------------------------

def is_nan32(bits):
    """NaN test on raw bits — immune to hardware denormal flushing."""
    return ((bits & jnp.int32(0x7F800000)) == jnp.int32(0x7F800000)) & \
        ((bits & jnp.int32(0x007FFFFF)) != 0)


def f32_key(bits):
    """Order-preserving int32 key for f32 bits (excluding NaN): float a < b
    iff key(a) < key(b) as signed ints. -0 maps with +0; denormals compare
    exactly even on FTZ hardware (TPU flushes subnormals, so comparisons go
    through the integer domain — SURVEY.md §7 hard part (b))."""
    z = jnp.where(bits == _SIGN, 0, bits)  # -0 -> +0
    return z ^ (lax.shift_right_arithmetic(z, 31) & jnp.int32(0x7FFFFFFF))


def f32_cmp_eq(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    za = jnp.where(a_bits == _SIGN, 0, a_bits)
    zb = jnp.where(b_bits == _SIGN, 0, b_bits)
    return (za == zb) & ~nan


def f32_cmp_lt(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    return (f32_key(a_bits) < f32_key(b_bits)) & ~nan


def f32_min(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    both_zero = ((a_bits | b_bits) & jnp.int32(0x7FFFFFFF)) == 0
    zero_pick = a_bits | b_bits  # -0 if either is -0
    r = jnp.where(f32_key(a_bits) < f32_key(b_bits), a_bits, b_bits)
    r = jnp.where(both_zero, zero_pick, r)
    return jnp.where(nan, F32_CANON_NAN, r)


def f32_max(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    both_zero = ((a_bits | b_bits) & jnp.int32(0x7FFFFFFF)) == 0
    zero_pick = a_bits & b_bits  # +0 unless both are -0
    r = jnp.where(f32_key(a_bits) > f32_key(b_bits), a_bits, b_bits)
    r = jnp.where(both_zero, zero_pick, r)
    return jnp.where(nan, F32_CANON_NAN, r)


def f32_nearest(a_bits):
    f = to_f32(a_bits)
    r = lax.round(f, lax.RoundingMethod.TO_NEAREST_EVEN)
    bits = from_f32(r)
    # |f| < 0.5 rounds to a zero that must keep f's sign
    bits = jnp.where(r == 0.0, bits | (a_bits & _SIGN), bits)
    return canon32(bits)


def f32_trunc(a_bits):
    f = to_f32(a_bits)
    r = jnp.where(f < 0, lax.ceil(f), lax.floor(f))
    bits = from_f32(r)
    # trunc of -0.x must be -0
    return canon32(jnp.where(r == 0.0, bits | (a_bits & _SIGN), bits))


# ---------------------------------------------------------------------------
# ALU op tables: sub-id -> elementwise fn over (lo, hi) planes.
# Single source of truth for the batch engines (the XLA uniform engine
# and the Pallas kernel both build their dispatch from these; the SIMT
# engine and executor/numeric.py are pinned to them by the parity suites).
# Indexed by the ALU2/ALU1 sub ids from batch/image.py.
# ---------------------------------------------------------------------------
from wasmedge_tpu.batch.image import (  # noqa: E402
    ALU1_SUB, ALU2_F32_BASE, ALU2_F64_BASE, ALU2_I32_BASE, ALU2_I64_BASE,
    _F32_BIN, _F64_BIN, _I32_BIN)


def alu2_fns():
    """sub -> (xl, xh, yl, yh) -> (rl, rh); indexed by ALU2 sub id.

    Semantics mirror batch/uniform.py:_alu_result, which mirrors the
    reference's binary_numeric.ipp kernels."""
    I32 = jnp.int32

    def z_of(x):
        return jnp.zeros_like(x)

    fns = {}

    def i32op(name, fn):
        fns[ALU2_I32_BASE + _I32_BIN.index(name)] = fn

    def i64op(name, fn):
        fns[ALU2_I64_BASE + _I32_BIN.index(name)] = fn

    def f32op(name, fn):
        fns[ALU2_F32_BASE + _F32_BIN.index(name)] = fn

    i32op("add", lambda xl, xh, yl, yh: (xl + yl, z_of(xl)))
    i32op("sub", lambda xl, xh, yl, yh: (xl - yl, z_of(xl)))
    i32op("mul", lambda xl, xh, yl, yh: (xl * yl, z_of(xl)))
    i32op("div_s", lambda xl, xh, yl, yh: (
        lax.div(xl, jnp.where(yl == 0, I32(1), yl)), z_of(xl)))
    i32op("div_u", lambda xl, xh, yl, yh: (
        lax.div(xl.astype(jnp.uint32),
                jnp.where(yl == 0, I32(1), yl).astype(jnp.uint32)).astype(I32),
        z_of(xl)))
    i32op("rem_s", lambda xl, xh, yl, yh: (
        lax.rem(xl, jnp.where(yl == 0, I32(1), yl)), z_of(xl)))
    i32op("rem_u", lambda xl, xh, yl, yh: (
        lax.rem(xl.astype(jnp.uint32),
                jnp.where(yl == 0, I32(1), yl).astype(jnp.uint32)).astype(I32),
        z_of(xl)))
    i32op("and", lambda xl, xh, yl, yh: (xl & yl, z_of(xl)))
    i32op("or", lambda xl, xh, yl, yh: (xl | yl, z_of(xl)))
    i32op("xor", lambda xl, xh, yl, yh: (xl ^ yl, z_of(xl)))
    i32op("shl", lambda xl, xh, yl, yh: (lax.shift_left(xl, yl & 31), z_of(xl)))
    i32op("shr_s", lambda xl, xh, yl, yh: (
        lax.shift_right_arithmetic(xl, yl & 31), z_of(xl)))
    i32op("shr_u", lambda xl, xh, yl, yh: (
        lax.shift_right_logical(xl, yl & 31), z_of(xl)))
    i32op("rotl", lambda xl, xh, yl, yh: (rotl32(xl, yl), z_of(xl)))
    i32op("rotr", lambda xl, xh, yl, yh: (
        rotl32(xl, (32 - (yl & 31)) & 31), z_of(xl)))
    i32op("eq", lambda xl, xh, yl, yh: (b2i(xl == yl), z_of(xl)))
    i32op("ne", lambda xl, xh, yl, yh: (b2i(xl != yl), z_of(xl)))
    i32op("lt_s", lambda xl, xh, yl, yh: (b2i(xl < yl), z_of(xl)))
    i32op("lt_u", lambda xl, xh, yl, yh: (b2i(u_lt(xl, yl)), z_of(xl)))
    i32op("gt_s", lambda xl, xh, yl, yh: (b2i(xl > yl), z_of(xl)))
    i32op("gt_u", lambda xl, xh, yl, yh: (b2i(u_lt(yl, xl)), z_of(xl)))
    i32op("le_s", lambda xl, xh, yl, yh: (b2i(xl <= yl), z_of(xl)))
    i32op("le_u", lambda xl, xh, yl, yh: (b2i(u_le(xl, yl)), z_of(xl)))
    i32op("ge_s", lambda xl, xh, yl, yh: (b2i(xl >= yl), z_of(xl)))
    i32op("ge_u", lambda xl, xh, yl, yh: (b2i(u_le(yl, xl)), z_of(xl)))

    i64op("add", lambda xl, xh, yl, yh: add64(xl, xh, yl, yh))
    i64op("sub", lambda xl, xh, yl, yh: sub64(xl, xh, yl, yh))
    i64op("mul", lambda xl, xh, yl, yh: mul64(xl, xh, yl, yh))

    def div64(kind):
        def fn(xl, xh, yl, yh):
            glo = jnp.where((yl | yh) == 0, I32(1), yl)
            ghi = jnp.where((yl | yh) == 0, I32(0), yh)
            if kind.endswith("_u"):
                qlo, qhi, rlo, rhi = divmod64_u(xl, xh, glo, ghi)
            else:
                qlo, qhi, rlo, rhi = div64_s(xl, xh, glo, ghi)
            return (qlo, qhi) if kind.startswith("div") else (rlo, rhi)
        return fn

    for kind in ("div_s", "div_u", "rem_s", "rem_u"):
        i64op(kind, div64(kind))
    i64op("and", lambda xl, xh, yl, yh: (xl & yl, xh & yh))
    i64op("or", lambda xl, xh, yl, yh: (xl | yl, xh | yh))
    i64op("xor", lambda xl, xh, yl, yh: (xl ^ yl, xh ^ yh))
    i64op("shl", lambda xl, xh, yl, yh: shl64(xl, xh, yl & 63))
    i64op("shr_s", lambda xl, xh, yl, yh: shr64_s(xl, xh, yl & 63))
    i64op("shr_u", lambda xl, xh, yl, yh: shr64_u(xl, xh, yl & 63))
    i64op("rotl", lambda xl, xh, yl, yh: rotl64(xl, xh, yl & 63))
    i64op("rotr", lambda xl, xh, yl, yh: rotr64(xl, xh, yl & 63))
    i64op("eq", lambda xl, xh, yl, yh: (b2i(eq64(xl, xh, yl, yh)), z_of(xl)))
    i64op("ne", lambda xl, xh, yl, yh: (b2i(~eq64(xl, xh, yl, yh)), z_of(xl)))
    i64op("lt_s", lambda xl, xh, yl, yh: (b2i(lt64_s(xl, xh, yl, yh)), z_of(xl)))
    i64op("lt_u", lambda xl, xh, yl, yh: (b2i(lt64_u(xl, xh, yl, yh)), z_of(xl)))
    i64op("gt_s", lambda xl, xh, yl, yh: (b2i(lt64_s(yl, yh, xl, xh)), z_of(xl)))
    i64op("gt_u", lambda xl, xh, yl, yh: (b2i(lt64_u(yl, yh, xl, xh)), z_of(xl)))
    i64op("le_s", lambda xl, xh, yl, yh: (b2i(~lt64_s(yl, yh, xl, xh)), z_of(xl)))
    i64op("le_u", lambda xl, xh, yl, yh: (b2i(~lt64_u(yl, yh, xl, xh)), z_of(xl)))
    i64op("ge_s", lambda xl, xh, yl, yh: (b2i(~lt64_s(xl, xh, yl, yh)), z_of(xl)))
    i64op("ge_u", lambda xl, xh, yl, yh: (b2i(~lt64_u(xl, xh, yl, yh)), z_of(xl)))

    def fbin(op):
        def fn(xl, xh, yl, yh):
            fx, fy = to_f32(xl), to_f32(yl)
            return (canon32(from_f32(op(fx, fy))), z_of(xl))
        return fn

    f32op("add", fbin(lambda a, b: a + b))
    f32op("sub", fbin(lambda a, b: a - b))
    f32op("mul", fbin(lambda a, b: a * b))
    f32op("div", fbin(lambda a, b: a / b))
    f32op("min", lambda xl, xh, yl, yh: (f32_min(xl, yl), z_of(xl)))
    f32op("max", lambda xl, xh, yl, yh: (f32_max(xl, yl), z_of(xl)))
    f32op("copysign", lambda xl, xh, yl, yh: (
        (xl & jnp.int32(0x7FFFFFFF)) | (yl & _SIGN), z_of(xl)))

    def fcmp(which):
        def fn(xl, xh, yl, yh):
            feq = f32_cmp_eq(xl, yl)
            flt = f32_cmp_lt(xl, yl)
            fgt = f32_cmp_lt(yl, xl)
            fnan = is_nan32(xl) | is_nan32(yl)
            v = {"eq": feq, "ne": ~feq, "lt": flt, "gt": fgt,
                 "le": (flt | feq) & ~fnan, "ge": (fgt | feq) & ~fnan}[which]
            return (b2i(v), z_of(xl))
        return fn

    for which in ("eq", "ne", "lt", "gt", "le", "ge"):
        f32op(which, fcmp(which))

    # binary64: softfloat kernels on the (lo, hi) planes
    from wasmedge_tpu.batch import softfloat as sf

    def f64op(name, fn):
        fns[ALU2_F64_BASE + _F64_BIN.index(name)] = fn

    f64op("add", lambda xl, xh, yl, yh: sf.f64_add(xl, xh, yl, yh))
    f64op("sub", lambda xl, xh, yl, yh: sf.f64_sub(xl, xh, yl, yh))
    f64op("mul", lambda xl, xh, yl, yh: sf.f64_mul(xl, xh, yl, yh))
    f64op("div", lambda xl, xh, yl, yh: sf.f64_div(xl, xh, yl, yh))
    f64op("min", lambda xl, xh, yl, yh: sf.f64_min(xl, xh, yl, yh))
    f64op("max", lambda xl, xh, yl, yh: sf.f64_max(xl, xh, yl, yh))
    f64op("copysign", lambda xl, xh, yl, yh: (
        xl, (xh & jnp.int32(0x7FFFFFFF)) | (yh & _SIGN)))

    def f64cmp(which):
        def fn(xl, xh, yl, yh):
            eqv = sf.f64_eq(xl, xh, yl, yh)
            ltv = sf.f64_lt(xl, xh, yl, yh)
            gtv = sf.f64_lt(yl, yh, xl, xh)
            v = {"eq": eqv, "ne": ~eqv, "lt": ltv, "gt": gtv,
                 "le": ltv | eqv, "ge": gtv | eqv}[which]
            return (b2i(v), jnp.zeros_like(xl))
        return fn

    for which in ("eq", "ne", "lt", "gt", "le", "ge"):
        f64op(which, f64cmp(which))
    return fns



def alu1_fns():
    """sub -> (wl, wh) -> (rl, rh); indexed by ALU1 sub id."""
    I32 = jnp.int32
    A1 = ALU1_SUB

    def z_of(x):
        return jnp.zeros_like(x)

    def sext8(wl):
        return lax.shift_right_arithmetic(lax.shift_left(wl, 24), 24)

    def sext16(wl):
        return lax.shift_right_arithmetic(lax.shift_left(wl, 16), 16)

    def trunc_core(wl):
        fw = to_f32(wl)
        return jnp.where(fw < 0, lax.ceil(fw), lax.floor(fw))

    def trunc_s(wl):
        tr = trunc_core(wl)
        nan = is_nan32(wl)
        in_s = (tr >= jnp.float32(-2147483648.0)) & \
            (tr <= jnp.float32(2147483520.0))
        return jnp.where(in_s & ~nan, tr, jnp.float32(0)).astype(I32)

    def trunc_u(wl):
        tr = trunc_core(wl)
        nan = is_nan32(wl)
        in_u = (tr >= 0) & (tr <= jnp.float32(4294967040.0))
        t = jnp.where(in_u & ~nan, tr, jnp.float32(0))
        return jnp.where(t >= jnp.float32(2147483648.0),
                         (t - jnp.float32(4294967296.0)).astype(I32),
                         t.astype(I32))

    def sat_s(wl):
        tr = trunc_core(wl)
        nan = is_nan32(wl)
        return jnp.where(
            nan, 0,
            jnp.where(tr < jnp.float32(-2147483648.0), jnp.int32(-0x80000000),
                      jnp.where(tr > jnp.float32(2147483520.0),
                                jnp.int32(0x7FFFFFFF), trunc_s(wl))))

    def sat_u(wl):
        tr = trunc_core(wl)
        nan = is_nan32(wl)
        return jnp.where(nan | (tr < 0), 0,
                         jnp.where(tr > jnp.float32(4294967040.0),
                                   jnp.int32(-1), trunc_u(wl)))

    fns = {
        A1["i32.clz"]: lambda wl, wh: (lax.clz(wl), z_of(wl)),
        A1["i32.ctz"]: lambda wl, wh: (ctz32(wl), z_of(wl)),
        A1["i32.popcnt"]: lambda wl, wh: (lax.population_count(wl), z_of(wl)),
        A1["i32.eqz"]: lambda wl, wh: (b2i(wl == 0), z_of(wl)),
        A1["i32.extend8_s"]: lambda wl, wh: (sext8(wl), z_of(wl)),
        A1["i32.extend16_s"]: lambda wl, wh: (sext16(wl), z_of(wl)),
        A1["i64.clz"]: lambda wl, wh: (clz64(wl, wh), z_of(wl)),
        A1["i64.ctz"]: lambda wl, wh: (ctz64(wl, wh), z_of(wl)),
        A1["i64.popcnt"]: lambda wl, wh: (popcnt64(wl, wh), z_of(wl)),
        A1["i64.eqz"]: lambda wl, wh: (b2i((wl | wh) == 0), z_of(wl)),
        A1["i64.extend8_s"]: lambda wl, wh: (
            sext8(wl), lax.shift_right_arithmetic(sext8(wl), 31)),
        A1["i64.extend16_s"]: lambda wl, wh: (
            sext16(wl), lax.shift_right_arithmetic(sext16(wl), 31)),
        A1["i64.extend32_s"]: lambda wl, wh: (
            wl, lax.shift_right_arithmetic(wl, 31)),
        A1["f32.abs"]: lambda wl, wh: (wl & jnp.int32(0x7FFFFFFF), z_of(wl)),
        A1["f32.neg"]: lambda wl, wh: (wl ^ _SIGN, z_of(wl)),
        A1["f32.ceil"]: lambda wl, wh: (
            canon32(from_f32(lax.ceil(to_f32(wl)))),
            z_of(wl)),
        A1["f32.floor"]: lambda wl, wh: (
            canon32(from_f32(lax.floor(to_f32(wl)))),
            z_of(wl)),
        A1["f32.trunc"]: lambda wl, wh: (f32_trunc(wl), z_of(wl)),
        A1["f32.nearest"]: lambda wl, wh: (f32_nearest(wl), z_of(wl)),
        A1["f32.sqrt"]: lambda wl, wh: (
            canon32(from_f32(lax.sqrt(to_f32(wl)))),
            z_of(wl)),
        A1["i32.wrap_i64"]: lambda wl, wh: (wl, z_of(wl)),
        A1["i64.extend_i32_s"]: lambda wl, wh: (
            wl, lax.shift_right_arithmetic(wl, 31)),
        A1["i64.extend_i32_u"]: lambda wl, wh: (wl, z_of(wl)),
        A1["i32.trunc_f32_s"]: lambda wl, wh: (trunc_s(wl), z_of(wl)),
        A1["i32.trunc_f32_u"]: lambda wl, wh: (trunc_u(wl), z_of(wl)),
        A1["i32.trunc_sat_f32_s"]: lambda wl, wh: (sat_s(wl), z_of(wl)),
        A1["i32.trunc_sat_f32_u"]: lambda wl, wh: (sat_u(wl), z_of(wl)),
        A1["f32.convert_i32_s"]: lambda wl, wh: (
            from_f32(wl.astype(jnp.float32)), z_of(wl)),
        A1["f32.convert_i32_u"]: lambda wl, wh: (
            from_f32(wl.astype(jnp.uint32).astype(jnp.float32)),
            z_of(wl)),
        A1["i32.reinterpret_f32"]: lambda wl, wh: (wl, z_of(wl)),
        A1["f32.reinterpret_i32"]: lambda wl, wh: (wl, z_of(wl)),
        A1["ref.is_null"]: lambda wl, wh: (b2i((wl | wh) == 0), z_of(wl)),
    }

    from wasmedge_tpu.batch import softfloat as sf

    fns.update({
        A1["f64.abs"]: lambda wl, wh: (wl, wh & jnp.int32(0x7FFFFFFF)),
        A1["f64.neg"]: lambda wl, wh: (wl, wh ^ _SIGN),
        A1["f64.ceil"]: sf.f64_ceil,
        A1["f64.floor"]: sf.f64_floor,
        A1["f64.trunc"]: sf.f64_trunc,
        A1["f64.nearest"]: sf.f64_nearest,
        A1["f64.sqrt"]: sf.f64_sqrt,
        A1["f32.demote_f64"]: lambda wl, wh: (sf.f64_to_f32(wl, wh),
                                              jnp.zeros_like(wl)),
        A1["f64.promote_f32"]: lambda wl, wh: sf.f32_to_f64(wl),
        A1["i64.reinterpret_f64"]: lambda wl, wh: (wl, wh),
        A1["f64.reinterpret_i64"]: lambda wl, wh: (wl, wh),
        A1["f64.convert_i32_s"]: lambda wl, wh: sf.f64_from_i32(wl, True),
        A1["f64.convert_i32_u"]: lambda wl, wh: sf.f64_from_i32(wl, False),
        A1["f64.convert_i64_s"]: lambda wl, wh: sf.f64_from_i64(wl, wh, True),
        A1["f64.convert_i64_u"]: lambda wl, wh: sf.f64_from_i64(wl, wh,
                                                                False),
        A1["f32.convert_i64_s"]: lambda wl, wh: (
            sf.f32_from_i64(wl, wh, True), jnp.zeros_like(wl)),
        A1["f32.convert_i64_u"]: lambda wl, wh: (
            sf.f32_from_i64(wl, wh, False), jnp.zeros_like(wl)),
    })

    # float->int truncations, all via the exact f64 integer path (an f32
    # operand promotes exactly first).  Non-sat variants return the
    # in-range value (traps handled by alu1_trap_fns); sat variants clamp.
    def trunc64(src32, to32, signed, sat):
        def fn(wl, wh):
            if src32:
                vlo, vhi = sf.f32_to_f64(wl)
            else:
                vlo, vhi = wl, wh
            olo, ohi, ok_s, ok_u, nan = sf.f64_to_i64_trunc(vlo, vhi)
            neg = vhi < 0
            if to32:
                sgn = lax.shift_right_arithmetic(olo, 31)
                fits_s = ok_s & (ohi == sgn)
                fits_u = ok_u & (ohi == 0)
                if not sat:
                    # i32 result cells keep a zero hi plane
                    return olo, jnp.zeros_like(olo)
                if signed:
                    r = jnp.where(nan, 0,
                                  jnp.where(fits_s, olo,
                                            jnp.where(neg,
                                                      jnp.int32(-0x80000000),
                                                      jnp.int32(0x7FFFFFFF))))
                else:
                    r = jnp.where(nan, 0,
                                  jnp.where(fits_u, olo,
                                            jnp.where(neg, jnp.int32(0),
                                                      jnp.int32(-1))))
                return r, jnp.zeros_like(olo)
            if not sat:
                return olo, ohi
            if signed:
                rlo = jnp.where(nan, 0,
                                jnp.where(ok_s, olo,
                                          jnp.where(neg, jnp.int32(0),
                                                    jnp.int32(-1))))
                rhi = jnp.where(nan, 0,
                                jnp.where(ok_s, ohi,
                                          jnp.where(neg, _SIGN,
                                                    jnp.int32(0x7FFFFFFF))))
            else:
                rlo = jnp.where(nan, 0,
                                jnp.where(ok_u, olo,
                                          jnp.where(neg, jnp.int32(0),
                                                    jnp.int32(-1))))
                rhi = jnp.where(nan, 0,
                                jnp.where(ok_u, ohi,
                                          jnp.where(neg, jnp.int32(0),
                                                    jnp.int32(-1))))
            return rlo, rhi
        return fn

    for src32 in (True, False):
        fsrc = "f32" if src32 else "f64"
        for to32 in (True, False):
            ity = "i32" if to32 else "i64"
            for sgn in (True, False):
                su = "s" if sgn else "u"
                fns[A1[f"{ity}.trunc_{fsrc}_{su}"]] =                     trunc64(src32, to32, sgn, False)
                fns[A1[f"{ity}.trunc_sat_{fsrc}_{su}"]] =                     trunc64(src32, to32, sgn, True)
    return fns


# byte-position write masks as signed int32 (0xFF << 24 wraps negative)
BYTE_MASKS = (0xFF, 0xFF00, 0xFF0000, -0x1000000)


def plane_fill_copy(mem, dst, end, src_or_val, go, copy_lanes=None):
    """Masked bulk fill/copy over a word-major [W, lanes] memory plane.

    dst/end/src_or_val/go are per-lane vectors (byte addresses; go gates
    the write).  copy_lanes: None = every lane fills; a boolean vector =
    lanes where the op is memory.copy (src_or_val is then the source
    address).  Source reads come from the unmodified input plane, giving
    memmove semantics for overlapping ranges.  Shared by the SIMT and
    XLA-uniform engines (the Pallas kernel has a chunked in-kernel
    variant)."""
    W = mem.shape[0]
    widx = jnp.arange(W, dtype=I32)[:, None]
    byte0 = widx * 4
    mask = jnp.zeros_like(mem)
    for bpos in range(4):
        ba = byte0 + bpos
        inr = (~u_lt(ba, dst[None, :])) & u_lt(ba, end[None, :])
        mask = mask | jnp.where(inr, jnp.int32(BYTE_MASKS[bpos]), 0)
    fill_word = ((src_or_val & 0xFF) * jnp.int32(0x01010101))[None, :]
    if copy_lanes is None:
        new_word = jnp.broadcast_to(fill_word, mem.shape)
    else:
        delta = src_or_val - dst

        def src_path(m):
            src_addr0 = byte0 + delta[None, :]
            # arithmetic shift: backward-overlap deltas make early word
            # addresses negative and must round toward -inf
            swi = lax.shift_right_arithmetic(src_addr0, 2)
            shB = (src_addr0 & 3) * 8
            s0 = jnp.take_along_axis(m, jnp.clip(swi, 0, W - 1), axis=0)
            s1 = jnp.take_along_axis(m, jnp.clip(swi + 1, 0, W - 1),
                                     axis=0)
            inv = (32 - shB) & 31
            hi_or = jnp.where(shB == 0, 0, -1)
            return (lax.shift_right_logical(s0, shB)
                    | (lax.shift_left(s1, inv) & hi_or))

        # skip the two full-plane gathers when no lane copies this step
        src_word = lax.cond(jnp.any(copy_lanes & go), src_path,
                            lambda m: m, mem)
        new_word = jnp.where(copy_lanes[None, :], src_word, fill_word)
    write = (mask != 0) & go[None, :]
    return jnp.where(write, (mem & ~mask) | (new_word & mask), mem)


def alu1_trap_fns():
    """Trap checks for the trapping ALU1 subs (non-sat float->int):
    sub -> fn(wl, wh) -> (bad_mask, code_vec).  Shared by all batch
    engines so trap semantics cannot diverge."""
    from wasmedge_tpu.batch import softfloat as sf

    A1 = ALU1_SUB
    fns = {}

    def mk(src32, to32, signed):
        def fn(wl, wh):
            if src32:
                vlo, vhi = sf.f32_to_f64(wl)
            else:
                vlo, vhi = wl, wh
            olo, ohi, ok_s, ok_u, nan = sf.f64_to_i64_trunc(vlo, vhi)
            neg = vhi < 0
            if to32:
                sgn = lax.shift_right_arithmetic(olo, 31)
                ok = (ok_s & (ohi == sgn)) if signed else                     (ok_u & (ohi == 0))
            else:
                ok = ok_s if signed else ok_u
            bad = nan | ~ok
            code = jnp.where(nan, jnp.int32(_TRAP_INVALID_CONV),
                             jnp.int32(_TRAP_INT_OVERFLOW))
            return bad, code
        return fn

    for src32 in (True, False):
        fsrc = "f32" if src32 else "f64"
        for to32 in (True, False):
            ity = "i32" if to32 else "i64"
            for sgn in (True, False):
                su = "s" if sgn else "u"
                fns[A1[f"{ity}.trunc_{fsrc}_{su}"]] = mk(src32, to32, sgn)
    return fns

