"""Vectorized lane-level value operations for the batch engine.

Value encoding: each 64-bit wasm cell is two int32 planes (lo, hi).
i32/f32 use lo only (hi kept zero for i32 results to keep cells canonical);
i64/f64-bits span both. All functions here are elementwise over [lanes]
arrays and shape-polymorphic — the pallas kernel reuses them unchanged.

Semantics match executor/numeric.py bit-for-bit (the parity tests in
tests/test_batch_parity.py enforce this lane-by-lane).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

I32 = jnp.int32
# Host-side (numpy) scalars, not device arrays: pallas kernels trace these
# functions and cannot capture concrete jax Arrays as closure constants.
_SIGN = np.int32(-0x80000000)  # 0x80000000 as int32


def u_lt(a, b):
    """Unsigned < on int32 planes via sign-bias trick."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def u_le(a, b):
    return (a ^ _SIGN) <= (b ^ _SIGN)


def b2i(x):
    return x.astype(I32)


def to_f32(lo):
    return lax.bitcast_convert_type(lo, jnp.float32)


def from_f32(f):
    return lax.bitcast_convert_type(f, jnp.int32)


F32_CANON_NAN = np.int32(0x7FC00000)


def canon32(bits):
    """Canonicalize NaN bit patterns (policy shared with the oracle)."""
    exp_all = (bits & jnp.int32(0x7F800000)) == jnp.int32(0x7F800000)
    frac = (bits & jnp.int32(0x007FFFFF)) != 0
    return jnp.where(exp_all & frac, F32_CANON_NAN, bits)


# ---------------------------------------------------------------------------
# i32 scalar-plane ops
# ---------------------------------------------------------------------------

def shamt32(b):
    return b & 31


def rotl32(a, n):
    n = n & 31
    return lax.shift_left(a, n) | lax.shift_right_logical(a, (32 - n) & 31) & \
        jnp.where(n == 0, 0, -1)


def clz32(v):
    return lax.clz(v)


def ctz32(v):
    # popcount((v & -v) - 1); v==0 -> popcount(-1) = 32
    return lax.population_count((v & -v) - 1)


# ---------------------------------------------------------------------------
# i64 pair-plane ops
# ---------------------------------------------------------------------------

def add64(alo, ahi, blo, bhi):
    lo = alo + blo
    carry = b2i(u_lt(lo, alo))
    return lo, ahi + bhi + carry


def sub64(alo, ahi, blo, bhi):
    lo = alo - blo
    borrow = b2i(u_lt(alo, blo))
    return lo, ahi - bhi - borrow


def _umul32_wide(a, b):
    """32x32 -> 64 unsigned multiply on int32 planes via 16-bit halves."""
    a0 = a & 0xFFFF
    a1 = lax.shift_right_logical(a, 16)
    b0 = b & 0xFFFF
    b1 = lax.shift_right_logical(b, 16)
    ll = a0 * b0                      # <= 2^32-2^17+1, wraps fine in i32? no: fits 32 bits unsigned
    lh = a0 * b1
    hl = a1 * b0
    hh = a1 * b1
    # low = ll + ((lh + hl) << 16); compute with carries
    mid = lh + hl                     # may wrap past 2^32: detect
    mid_carry = b2i(u_lt(mid, lh))    # wrapped -> add 2^32 at bit 48 => hh += 2^16
    lo = ll + lax.shift_left(mid, 16)
    lo_carry = b2i(u_lt(lo, ll))
    hi = hh + lax.shift_right_logical(mid, 16) + lax.shift_left(mid_carry, 16) + lo_carry
    return lo, hi


def mul64(alo, ahi, blo, bhi):
    lo, hi = _umul32_wide(alo, blo)
    hi = hi + alo * bhi + ahi * blo
    return lo, hi


def neg64(lo, hi):
    nlo = -lo
    nhi = ~hi + b2i(lo == 0)
    return nlo, nhi


def shl64(lo, hi, n):
    n = n & 63
    big = n >= 32
    ns = n & 31
    # n < 32 case
    lo_s = lax.shift_left(lo, ns)
    hi_s = lax.shift_left(hi, ns) | jnp.where(
        ns == 0, 0, lax.shift_right_logical(lo, (32 - ns) & 31))
    # n >= 32 case
    hi_b = lax.shift_left(lo, ns)
    return jnp.where(big, 0, lo_s), jnp.where(big, hi_b, hi_s)


def shr64_u(lo, hi, n):
    n = n & 63
    big = n >= 32
    ns = n & 31
    lo_s = lax.shift_right_logical(lo, ns) | jnp.where(
        ns == 0, 0, lax.shift_left(hi, (32 - ns) & 31))
    hi_s = lax.shift_right_logical(hi, ns)
    lo_b = lax.shift_right_logical(hi, ns)
    return jnp.where(big, lo_b, lo_s), jnp.where(big, 0, hi_s)


def shr64_s(lo, hi, n):
    n = n & 63
    big = n >= 32
    ns = n & 31
    lo_s = lax.shift_right_logical(lo, ns) | jnp.where(
        ns == 0, 0, lax.shift_left(hi, (32 - ns) & 31))
    hi_s = lax.shift_right_arithmetic(hi, ns)
    lo_b = lax.shift_right_arithmetic(hi, ns)
    sign = lax.shift_right_arithmetic(hi, 31)
    return jnp.where(big, lo_b, lo_s), jnp.where(big, sign, hi_s)


def rotl64(lo, hi, n):
    n = n & 63
    l1, h1 = shl64(lo, hi, n)
    l2, h2 = shr64_u(lo, hi, (64 - n) & 63)
    nz = n != 0
    return l1 | jnp.where(nz, l2, 0), h1 | jnp.where(nz, h2, 0)


def rotr64(lo, hi, n):
    return rotl64(lo, hi, (64 - (n & 63)) & 63)


def clz64(lo, hi):
    return jnp.where(hi == 0, 32 + lax.clz(lo), lax.clz(hi))


def ctz64(lo, hi):
    return jnp.where(lo == 0, 32 + ctz32(hi), ctz32(lo))


def popcnt64(lo, hi):
    return lax.population_count(lo) + lax.population_count(hi)


def eq64(alo, ahi, blo, bhi):
    return (alo == blo) & (ahi == bhi)


def lt64_s(alo, ahi, blo, bhi):
    return (ahi < bhi) | ((ahi == bhi) & u_lt(alo, blo))


def lt64_u(alo, ahi, blo, bhi):
    return u_lt(ahi, bhi) | ((ahi == bhi) & u_lt(alo, blo))


# -- unsigned 64-bit divide: restoring long division, 64 fixed iterations --
def divmod64_u(nlo, nhi, dlo, dhi):
    """Returns (qlo, qhi, rlo, rhi); divisor 0 must be guarded by caller."""

    def body(i, st):
        qlo, qhi, rlo, rhi = st
        bit_idx = 63 - i
        # r = (r << 1) | bit(n, bit_idx)
        nbit = jnp.where(
            bit_idx >= 32,
            lax.shift_right_logical(nhi, bit_idx - 32) & 1,
            lax.shift_right_logical(nlo, bit_idx & 31) & 1,
        )
        rlo2, rhi2 = shl64(rlo, rhi, jnp.int32(1))
        rlo2 = rlo2 | nbit
        ge = ~lt64_u(rlo2, rhi2, dlo, dhi)  # r >= d
        slo, shi = sub64(rlo2, rhi2, dlo, dhi)
        rlo3 = jnp.where(ge, slo, rlo2)
        rhi3 = jnp.where(ge, shi, rhi2)
        qbit = b2i(ge)
        qlo2 = jnp.where(bit_idx < 32, qlo | lax.shift_left(qbit, bit_idx & 31), qlo)
        qhi2 = jnp.where(bit_idx >= 32, qhi | lax.shift_left(qbit, (bit_idx - 32) & 31), qhi)
        return qlo2, qhi2, rlo3, rhi3

    z = jnp.zeros_like(nlo)
    return lax.fori_loop(0, 64, body, (z, z, z, z))


def div64_s(nlo, nhi, dlo, dhi):
    nneg = nhi < 0
    dneg = dhi < 0
    anlo, anhi = neg64(nlo, nhi)
    ulo = jnp.where(nneg, anlo, nlo)
    uhi = jnp.where(nneg, anhi, nhi)
    adlo, adhi = neg64(dlo, dhi)
    vlo = jnp.where(dneg, adlo, dlo)
    vhi = jnp.where(dneg, adhi, dhi)
    qlo, qhi, rlo, rhi = divmod64_u(ulo, uhi, vlo, vhi)
    qneg = nneg != dneg
    nqlo, nqhi = neg64(qlo, qhi)
    nrlo, nrhi = neg64(rlo, rhi)
    return (
        jnp.where(qneg, nqlo, qlo), jnp.where(qneg, nqhi, qhi),
        jnp.where(nneg, nrlo, rlo), jnp.where(nneg, nrhi, rhi),
    )


# ---------------------------------------------------------------------------
# f32 ops with wasm semantics
# ---------------------------------------------------------------------------

def is_nan32(bits):
    """NaN test on raw bits — immune to hardware denormal flushing."""
    return ((bits & jnp.int32(0x7F800000)) == jnp.int32(0x7F800000)) & \
        ((bits & jnp.int32(0x007FFFFF)) != 0)


def f32_key(bits):
    """Order-preserving int32 key for f32 bits (excluding NaN): float a < b
    iff key(a) < key(b) as signed ints. -0 maps with +0; denormals compare
    exactly even on FTZ hardware (TPU flushes subnormals, so comparisons go
    through the integer domain — SURVEY.md §7 hard part (b))."""
    z = jnp.where(bits == _SIGN, 0, bits)  # -0 -> +0
    return z ^ (lax.shift_right_arithmetic(z, 31) & jnp.int32(0x7FFFFFFF))


def f32_cmp_eq(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    za = jnp.where(a_bits == _SIGN, 0, a_bits)
    zb = jnp.where(b_bits == _SIGN, 0, b_bits)
    return (za == zb) & ~nan


def f32_cmp_lt(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    return (f32_key(a_bits) < f32_key(b_bits)) & ~nan


def f32_min(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    both_zero = ((a_bits | b_bits) & jnp.int32(0x7FFFFFFF)) == 0
    zero_pick = a_bits | b_bits  # -0 if either is -0
    r = jnp.where(f32_key(a_bits) < f32_key(b_bits), a_bits, b_bits)
    r = jnp.where(both_zero, zero_pick, r)
    return jnp.where(nan, F32_CANON_NAN, r)


def f32_max(a_bits, b_bits):
    nan = is_nan32(a_bits) | is_nan32(b_bits)
    both_zero = ((a_bits | b_bits) & jnp.int32(0x7FFFFFFF)) == 0
    zero_pick = a_bits & b_bits  # +0 unless both are -0
    r = jnp.where(f32_key(a_bits) > f32_key(b_bits), a_bits, b_bits)
    r = jnp.where(both_zero, zero_pick, r)
    return jnp.where(nan, F32_CANON_NAN, r)


def f32_nearest(a_bits):
    f = to_f32(a_bits)
    r = lax.round(f, lax.RoundingMethod.TO_NEAREST_EVEN)
    bits = from_f32(r)
    # |f| < 0.5 rounds to a zero that must keep f's sign
    bits = jnp.where(r == 0.0, bits | (a_bits & _SIGN), bits)
    return canon32(bits)


def f32_trunc(a_bits):
    f = to_f32(a_bits)
    r = jnp.where(f < 0, lax.ceil(f), lax.floor(f))
    bits = from_f32(r)
    # trunc of -0.x must be -0
    return canon32(jnp.where(r == 0.0, bits | (a_bits & _SIGN), bits))
