"""Shared checkpoint-lineage machinery.

BatchSupervisor (batch/supervisor.py), BatchServer (serve/server.py) and
the MeshSupervisor (parallel/supervisor.py) all keep a bounded, ordered
list of snapshot members and apply the same moves to it: adopt an
existing directory at startup, walk newest-first on restore while
recording and dropping corrupt members, replace-or-append an entry at an
unchanged position, and prune members beyond a keep depth.  Before r10
the walk and the adoption were near-twin copies in the supervisor and
the server (ROADMAP r9 open item); this module is the single
implementation, with the member *payload* — the server's lane->request
binding snapshot, the mesh supervisor's shard manifest — riding along
opaquely.

The lineage itself is storage-agnostic: members are (path, steps,
payload) and loading/validation stays with the caller (invocation
binding, fault-injection seams, engine geometry checks differ per
consumer), passed in as the `load` callback of `walk_newest`.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable, List, Optional, Tuple


@dataclasses.dataclass
class Member:
    """One lineage member: a snapshot path, its execution cursor, and an
    opaque consumer payload (None for the supervisor's plain members)."""

    path: str
    steps: int
    payload: object = None


class Lineage:
    """Bounded newest-last list of checkpoint members."""

    def __init__(self):
        self.members: List[Member] = []

    def __len__(self) -> int:
        return len(self.members)

    def __bool__(self) -> bool:
        return bool(self.members)

    def newest(self) -> Optional[Member]:
        return self.members[-1] if self.members else None

    def next_seq(self) -> int:
        """The cursor one past the newest member's — snapshot consumers
        whose members are plain sequence-numbered files (the gateway's
        durable manifest/journal, gateway/durable.py) allocate their
        next filename from it."""
        m = self.newest()
        return (m.steps + 1) if m is not None else 0

    def reset(self):
        """Drop every member (a fresh run must never inherit a previous
        run()'s lineage; only an explicit resume adopts disk state)."""
        self.members = []

    # -- directory adoption ------------------------------------------------
    @staticmethod
    def scan(dirpath: Optional[str], pattern: str) -> List[Tuple[str, int]]:
        """Member candidates on disk: entries of `dirpath` whose name
        fullmatches `pattern` (one int group = the steps cursor), sorted
        oldest-first by that cursor.  Missing directory -> []."""
        if not dirpath or not os.path.isdir(dirpath):
            return []
        out = []
        for fn in sorted(os.listdir(dirpath)):
            m = re.fullmatch(pattern, fn)
            if m:
                out.append((os.path.join(dirpath, fn), int(m.group(1))))
        out.sort(key=lambda t: t[1])
        return out

    def install(self, scanned: List[Tuple[str, int]]):
        """Replace the lineage with scanned (path, steps) candidates."""
        self.members = [Member(p, s) for p, s in scanned]

    # -- growth / pruning --------------------------------------------------
    def add(self, path: str, steps: int, payload=None):
        """Append a member — or replace the newest one in place when it
        has the same path (an on-demand re-snapshot at an unchanged
        cursor must not stack duplicate entries the prune pass would
        unlink while survivors still reference the file)."""
        m = Member(path, int(steps), payload)
        if self.members and self.members[-1].path == path:
            self.members[-1] = m
        else:
            self.members.append(m)

    def prune(self, keep: int, unlink: Callable[[str], None] = os.unlink):
        """Drop (and best-effort delete) members beyond the newest
        `keep`; a failed delete never fails the run."""
        while len(self.members) > max(int(keep), 1):
            old = self.members.pop(0)
            try:
                unlink(old.path)
            except OSError:
                pass

    # -- the newest-good-member walk ---------------------------------------
    def walk_newest(self, load: Callable[[Member], object],
                    on_bad: Callable[[BaseException, Member], None]):
        """Try `load(member)` newest-first.  A member whose load raises
        is reported through `on_bad(exc, member)` and dropped from the
        lineage (corrupt/truncated/mismatched snapshots never get a
        second chance); the first member that loads stays the newest and
        its load result is returned.  Returns None when no member
        survives — the caller falls back to its initial state."""
        while self.members:
            m = self.members[-1]
            try:
                return load(m)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                on_bad(e, m)
                self.members.pop()
        return None
