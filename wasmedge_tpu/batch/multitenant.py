"""Multi-tenant heterogeneous batching: many modules, one lane batch.

BASELINE config 5 (the serverless mix) and SURVEY.md §7 step 8: different
tenants' modules run concurrently in one SIMT batch.  The design is pure
image concatenation — every tenant's DeviceImage is appended into one
super-image with its code/function/global/type/table/br-table index
spaces rebased, and each lane's control state is initialized at its own
tenant's entry pc.  The general SIMT engine is already per-lane-pc (its
dispatch gathers per-lane instruction words), so heterogeneous execution
needs no kernel changes; `call_indirect` reads its table window
(size/base) from the instruction, so each tenant's indirect calls stay
inside its own table.

Sandbox model matches batch/hostcall.py: per-lane data (stack, memory,
globals) is fully isolated per tenant; host modules are shared.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from wasmedge_tpu.batch.engine import BatchEngine, BatchResult, BatchState
from wasmedge_tpu.batch.image import (
    CLS_BR,
    CLS_BR_TABLE,
    CLS_BRNZ,
    CLS_BRZ,
    CLS_CALL,
    CLS_CALL_INDIRECT,
    CLS_DATA_DROP,
    CLS_ELEM_DROP,
    CLS_GLOBAL_GET,
    CLS_GLOBAL_SET,
    CLS_HOSTCALL,
    CLS_MEMINIT,
    CLS_REFFUNC,
    CLS_RETCALL,
    CLS_RETCALL_INDIRECT,
    CLS_TABLE_COPY,
    CLS_TABLE_FILL,
    CLS_TABLE_GET,
    CLS_TABLE_GROW,
    CLS_TABLE_INIT,
    CLS_TABLE_SET,
    CLS_TABLE_SIZE,
    DeviceImage,
)

_PAGE_WORDS = 65536 // 4

# merged fused-pattern table cap for concatenated images (fuse.py is
# numpy-only, so this import never pulls in the device stack)
from wasmedge_tpu.batch.fuse import CONCAT_MAX_PATTERNS \
    as _CONCAT_MAX_PATTERNS  # noqa: E402


@dataclasses.dataclass
class Tenant:
    """One module's share of the batch."""

    engine: BatchEngine      # per-module BatchEngine (provides the image)
    func_name: str
    args_lanes: List[np.ndarray]   # one array per param, [lanes] each
    lanes: int

    @property
    def inst(self):
        return self.engine.inst

    @property
    def img(self) -> DeviceImage:
        return self.engine.img


@dataclasses.dataclass
class Segment:
    """One tenant's fully-rebased contribution to a concatenated image.

    A segment is a pure function of (tenant image, index-space offsets,
    merged-fuse-pattern prefix): every array in it is already rebased
    into the super-image's coordinate space, so assembly is plain
    concatenation.  The imagestore SegmentCache keys on exactly those
    inputs — appending module N+1 leaves modules 1..N's offsets and
    pattern prefix untouched, so their segments replay from cache and
    only the new module is rebased."""

    base: dict                 # indirection row: per-index-space offsets
    planes: dict               # cls/sub/a/b/c/imm_lo/imm_hi/op_id
    brt: np.ndarray
    tbl: np.ndarray
    ef: np.ndarray
    eoff: np.ndarray
    elen: np.ndarray
    dwords: np.ndarray
    doff: np.ndarray
    dlen: np.ndarray
    flen: np.ndarray
    fpat: np.ndarray
    has_fuse: bool
    new_patterns: list         # fuse patterns novel vs. the entry prefix
    tfn: np.ndarray
    tfb: np.ndarray
    tier_fns: list             # rebased whole-function promotion entries
    has_tier: bool
    f_parts: dict              # f_entry/f_nparams/... (rebased)
    g_lo: np.ndarray
    g_hi: np.ndarray
    v128: np.ndarray
    advance: dict              # per-index-space deltas for the next seg


def build_segment(t: Tenant, off: dict, pat_state: tuple) -> Segment:
    """Rebase one tenant's DeviceImage at the given index-space offsets.

    `off` carries the running offsets (pc/func/glob/type/brt/table/v128/
    eseg/eflat/dseg/dbyte/tier_slot); `pat_state` is the tuple of fused
    patterns merged before this tenant.  Pure — reads only the tenant
    image and its arguments, which is what makes segments cacheable."""
    from wasmedge_tpu.batch.image import CLS_VCONST, CLS_VSHUFFLE

    img = t.img
    pc_b = off["pc"]
    fn_b = off["func"]
    gl_b = off["glob"]
    ty_b = off["type"]
    brt_b = off["brt"]
    tbl_b = off["table"]
    v128_b = off["v128"]
    eseg_b = off["eseg"]
    eflat_b = off["eflat"]
    dseg_b = off["dseg"]
    dbyte_b = off["dbyte"]
    tier_slot_b = off["tier_slot"]
    base = dict(pc=pc_b, func=fn_b, glob=gl_b, type=ty_b, brt=brt_b,
                table=tbl_b, eseg=eseg_b, dseg=dseg_b)
    a = img.a.copy()
    b = img.b.copy()
    c = img.c.copy()
    cls = img.cls
    is_branch = (cls == CLS_BR) | (cls == CLS_BRZ) | (cls == CLS_BRNZ)
    a[is_branch] += pc_b
    a[cls == CLS_CALL] += fn_b
    a[cls == CLS_RETCALL] += fn_b
    a[cls == CLS_HOSTCALL] += fn_b
    a[(cls == CLS_GLOBAL_GET) | (cls == CLS_GLOBAL_SET)] += gl_b
    is_ci = (cls == CLS_CALL_INDIRECT) | (cls == CLS_RETCALL_INDIRECT)
    a[is_ci] += ty_b
    c[is_ci] += tbl_b
    a[cls == CLS_BR_TABLE] += brt_b
    a[(cls == CLS_VCONST) | (cls == CLS_VSHUFFLE)] += v128_b
    # table ops address the tenant's slot [tbl_b, tbl_b + slot) in
    # the concatenated plane; ref.func pushes rebase with the
    # function index space
    is_tb = np.isin(cls, (CLS_TABLE_GET, CLS_TABLE_SET, CLS_TABLE_SIZE,
                          CLS_TABLE_GROW, CLS_TABLE_FILL,
                          CLS_TABLE_COPY, CLS_TABLE_INIT))
    c[is_tb] += tbl_b
    a[(cls == CLS_TABLE_INIT) | (cls == CLS_ELEM_DROP)] += eseg_b
    a[(cls == CLS_MEMINIT) | (cls == CLS_DATA_DROP)] += dseg_b
    a[cls == CLS_REFFUNC] += fn_b
    planes = dict(
        cls=cls, sub=img.sub, a=a, b=b, c=c,
        imm_lo=img.imm_lo, imm_hi=img.imm_hi,
        op_id=(img.op_id if img.op_id is not None
               else np.zeros(img.code_len, np.int32)))
    brt = img.br_table.copy()
    brt[:, 0] += pc_b
    # each tenant's table slot is its table_cap rows (grow room);
    # per-instruction capacity (b of CLS_TABLE_GROW) is already the
    # slot size, so growth can never cross into a neighbour's slot
    slot = max(int(img.table_cap or img.table0.shape[0]),
               img.table0.shape[0])
    tbl = np.zeros(slot, img.table0.dtype)
    tbl[:img.table0.shape[0]] = img.table0
    tbl[tbl != 0] += fn_b
    # segment snapshots: flat entries rebase with the function index
    # space (funcref domain), offsets with the flat concatenation
    ef = img.elem_flat.copy() if img.elem_flat is not None \
        else np.zeros(1, np.int32)
    ef[ef != 0] += fn_b
    eoff = (img.elem_off if img.elem_off is not None
            else np.zeros(1, np.int32)) + eflat_b
    elen = (img.elem_len if img.elem_len is not None
            else np.zeros(1, np.int32))
    dwords = (img.data_words if img.data_words is not None
              else np.zeros(1, np.int32))
    doff = (img.data_off if img.data_off is not None
            else np.zeros(1, np.int32)) + dbyte_b
    dlen = (img.data_len if img.data_len is not None
            else np.zeros(1, np.int32))
    # superinstruction fusion planes (batch/fuse.py): per-tenant runs
    # concatenate with NO pc rebasing needed beyond the plane offset
    # (runs are block-local); pattern ids remap into one deduped table
    t_flen = getattr(img, "fuse_len", None)
    new_patterns: list = []
    if t_flen is None:
        has_fuse = False
        flen = np.zeros(img.code_len, np.int32)
        fpat = np.full(img.code_len, -1, np.int32)
    else:
        has_fuse = True
        pat_map = {key: i for i, key in enumerate(pat_state)}
        remap = {}
        for ki, key in enumerate(img.fuse_patterns or ()):
            k2 = pat_map.get(key)
            if k2 is None:
                k2 = len(pat_map)
                pat_map[key] = k2
                new_patterns.append(key)
            remap[ki] = k2
        flen = np.asarray(t_flen, np.int32).copy()
        fpat = np.full(img.code_len, -1, np.int32)
        for p in np.nonzero(flen >= 2)[0]:
            k2 = remap.get(int(img.fuse_pat[p]), -1)
            if 0 <= k2 < _CONCAT_MAX_PATTERNS:
                fpat[p] = k2
            else:
                flen[p] = 0  # beyond the merged cap: stay per-op
    # whole-function promotion planes (batch/tierup.py): entry pcs,
    # block lists and branch targets all rebase by the plane offset,
    # slots by the running promoted count — the compiled bodies read
    # the CONCATENATED planes at the rebased static pcs, which match
    # the tenant planes verbatim (cls/sub/b/c/imms copy; `a` rebases
    # identically for branches on both sides)
    t_tfn = getattr(img, "tier_fn", None)
    tier_fns: list = []
    if t_tfn is None:
        has_tier = False
        tfn = np.full(img.code_len, -1, np.int32)
        tfb = np.zeros(img.code_len, np.int32)
        ntier = 0
    else:
        has_tier = True
        tfn = np.asarray(t_tfn, np.int32).copy()
        tfn[tfn >= 0] += tier_slot_b
        tfb = np.asarray(img.tier_fuel_bound, np.int32)
        for p in img.tier_fns:
            tier_fns.append(dict(
                p,
                slot=p["slot"] + tier_slot_b,
                entry_pc=p["entry_pc"] + pc_b,
                end_pc=p["end_pc"] + pc_b,
                blocks=[dict(bk, start=bk["start"] + pc_b,
                             end=bk["end"] + pc_b,
                             succ=tuple(s + pc_b
                                        for s in bk["succ"]))
                        for bk in p["blocks"]],
            ))
        ntier = len(img.tier_fns)
    f_parts = dict(
        f_entry=img.f_entry + pc_b,
        f_nparams=img.f_nparams,
        f_nlocals=img.f_nlocals,
        f_nresults=img.f_nresults,
        f_frame_top=img.f_frame_top,
        f_type=img.f_type + ty_b,
    )
    v128 = img.v128 if img.v128 is not None else np.zeros((1, 4), np.int32)
    advance = dict(
        pc=img.code_len,
        func=len(img.f_entry),
        glob=img.globals_lo.shape[0],
        type=int(img.f_type.max(initial=0)) + 1,
        brt=img.br_table.shape[0],
        table=slot,
        v128=v128.shape[0],
        eseg=elen.shape[0],
        eflat=ef.shape[0],
        dseg=dlen.shape[0],
        dbyte=4 * dwords.shape[0],
        tier_slot=ntier,
    )
    return Segment(base=base, planes=planes, brt=brt, tbl=tbl, ef=ef,
                   eoff=eoff, elen=elen, dwords=dwords, doff=doff,
                   dlen=dlen, flen=flen, fpat=fpat, has_fuse=has_fuse,
                   new_patterns=new_patterns, tfn=tfn, tfb=tfb,
                   tier_fns=tier_fns, has_tier=has_tier,
                   f_parts=f_parts, g_lo=img.globals_lo,
                   g_hi=img.globals_hi, v128=v128, advance=advance)


def concat_images(tenants: Sequence[Tenant], cache=None
                  ) -> Tuple[DeviceImage, list]:
    """Concatenate tenant DeviceImages into one super-image.

    Returns (image, bases) where bases[i] = dict of per-tenant index-space
    offsets (pc/func/glob/type/brt/table/eseg/dseg) — the indirection
    table.  `cache` (an imagestore SegmentCache, or None) memoizes the
    rebased per-tenant segments: with a cache, appending one module to an
    N-module generation rebuilds exactly one segment; without one this is
    the same per-tenant loop as ever, one build_segment call each, so the
    cache-off path is bit-identical by construction."""
    off = dict(pc=0, func=0, glob=0, type=0, brt=0, table=0, v128=0,
               eseg=0, eflat=0, dseg=0, dbyte=0, tier_slot=0)
    merged_patterns: list = []
    segments: List[Segment] = []
    for t in tenants:
        # planning is deferred to first build — run each tenant's
        # translation pass now so the concatenated planes see it
        # (idempotent; knob off plans nothing)
        plan = getattr(t.engine, "_plan_fusion", None)
        if plan is not None:
            plan()
        plan_t = getattr(t.engine, "_plan_tierup", None)
        if plan_t is not None:
            plan_t()
        pat_state = tuple(merged_patterns)
        seg = cache.lookup(t.img, off, pat_state) if cache is not None \
            else None
        if seg is None:
            seg = build_segment(t, off, pat_state)
            if cache is not None:
                cache.store(t.img, off, pat_state, seg)
        segments.append(seg)
        merged_patterns.extend(seg.new_patterns)
        for k, v in seg.advance.items():
            off[k] += v
    bases = [seg.base for seg in segments]
    any_fuse = any(seg.has_fuse for seg in segments)
    any_tier = any(seg.has_tier for seg in segments)
    # promotion descriptors are copied out of the (possibly cached,
    # cross-generation) segments so no two images ever share dicts
    merged_tier_fns = [dict(p, blocks=[dict(bk) for bk in p["blocks"]])
                       for seg in segments for p in seg.tier_fns]

    image = DeviceImage(
        cls=np.concatenate([s.planes["cls"] for s in segments]),
        sub=np.concatenate([s.planes["sub"] for s in segments]),
        a=np.concatenate([s.planes["a"] for s in segments]),
        b=np.concatenate([s.planes["b"] for s in segments]),
        c=np.concatenate([s.planes["c"] for s in segments]),
        imm_lo=np.concatenate([s.planes["imm_lo"] for s in segments]),
        imm_hi=np.concatenate([s.planes["imm_hi"] for s in segments]),
        op_id=np.concatenate([s.planes["op_id"] for s in segments]),
        br_table=np.concatenate([s.brt for s in segments], axis=0),
        f_entry=np.concatenate([s.f_parts["f_entry"] for s in segments]),
        f_nparams=np.concatenate([s.f_parts["f_nparams"]
                                  for s in segments]),
        f_nlocals=np.concatenate([s.f_parts["f_nlocals"]
                                  for s in segments]),
        f_nresults=np.concatenate([s.f_parts["f_nresults"]
                                   for s in segments]),
        f_frame_top=np.concatenate([s.f_parts["f_frame_top"]
                                    for s in segments]),
        f_type=np.concatenate([s.f_parts["f_type"] for s in segments]),
        table0=np.concatenate([s.tbl for s in segments]),
        globals_lo=np.concatenate([s.g_lo for s in segments]),
        globals_hi=np.concatenate([s.g_hi for s in segments]),
        mem_init=np.zeros(1, np.int32),       # per-lane init in the engine
        # watermark sizing reads mem_pages_init; cover every tenant's
        # initial pages (per-lane counts come from initial_state)
        mem_pages_init=max((t.img.mem_pages_init for t in tenants
                            if t.img.has_memory), default=0),
        mem_pages_max=max((t.img.mem_pages_max for t in tenants
                           if t.img.has_memory), default=0),
        has_memory=any(t.img.has_memory for t in tenants),
        max_local_zeros=max(t.img.max_local_zeros for t in tenants),
        code_len=off["pc"],
        v128=np.concatenate([s.v128 for s in segments], axis=0),
        has_simd=any(t.img.has_simd for t in tenants),
        elem_flat=np.concatenate([s.ef for s in segments]),
        elem_off=np.concatenate([s.eoff for s in segments]),
        elem_len=np.concatenate([s.elen for s in segments]),
        data_words=np.concatenate([s.dwords for s in segments]),
        data_off=np.concatenate([s.doff for s in segments]),
        data_len=np.concatenate([s.dlen for s in segments]),
        table_cap=off["table"],
        has_table_mut=any(getattr(t.img, "has_table_mut", False)
                          for t in tenants),
        has_table_grow=any(getattr(t.img, "has_table_grow", False)
                           for t in tenants),
        fuse_len=(np.concatenate([s.flen for s in segments])
                  if any_fuse else None),
        fuse_pat=(np.concatenate([s.fpat for s in segments])
                  if any_fuse else None),
        fuse_patterns=tuple(merged_patterns[:_CONCAT_MAX_PATTERNS])
        if any_fuse else None,
        fusion_report={
            "enabled": any_fuse,
            "patterns": min(len(merged_patterns), _CONCAT_MAX_PATTERNS),
            # recomputed from the MERGED planes (a run whose pattern
            # fell beyond the merged cap reverted to per-op cells and
            # must not be counted)
            "fused_runs": int(sum((s.flen >= 2).sum() for s in segments)),
            "fused_cells": int(sum(s.flen.sum() for s in segments)),
            "candidates": [], "runs": [],
        },
    )
    # whole-function promotion planes ride as plain attributes, like
    # plan_tierup binds them (batch/tierup.py); the report doubles as
    # the planned-sentinel so the merged engine's _plan_tierup never
    # re-plans (the concat image has no ModuleAnalysis to plan from)
    image.tier_fn = (np.concatenate([s.tfn for s in segments])
                     if any_tier else None)
    image.tier_fuel_bound = (np.concatenate([s.tfb for s in segments])
                             if any_tier else None)
    image.tier_fns = tuple(merged_tier_fns)
    image.tierup_report = {
        "enabled": any_tier,
        "promoted": [{k: p[k] for k in ("slot", "idx", "name",
                                        "entry_pc", "cost_bound",
                                        "fuel_bound", "device_loops")}
                     for p in merged_tier_fns],
        "candidates": [],
    }
    return image, bases


class MultiTenantBatchEngine(BatchEngine):
    """SIMT batch over the concatenation of several tenants' modules.

    Built from per-module BatchEngines (so each tenant's image reflects
    its own instance snapshot); lanes are assigned contiguously per
    tenant in order."""

    def __init__(self, tenants: Sequence[Tenant], conf=None, mesh=None):
        from wasmedge_tpu.common.configure import Configure

        if not tenants:
            raise ValueError("no tenants")
        self.tenants = list(tenants)
        # lane-sharded mesh execution (parallel/shard_drive.py): the
        # concatenated image replicates, lane planes shard — the same
        # single-program chunk the single-module engine jits
        self.mesh = mesh
        self.conf = conf or Configure()
        self.cfg = self.conf.batch
        self.lanes = sum(t.lanes for t in self.tenants)
        self.inst = self.tenants[0].inst  # nresults fallback; see run()
        self.img, self.bases = concat_images(
            self.tenants, cache=getattr(self, "_segment_cache", None))
        self._func_owner = []
        for ti, t in enumerate(self.tenants):
            self._func_owner.extend([ti] * len(t.img.f_entry))
        # concatenated images carry no t0kind plane: every tenant's
        # hostcalls stay on the per-tenant outcall channel (tier 1),
        # which is what keeps per-tenant WASI environs authoritative
        from wasmedge_tpu.batch.engine import new_hostcall_stats

        self._t0kinds = None
        self.hostcall_stats = new_hostcall_stats()
        from wasmedge_tpu.obs.recorder import recorder_of

        self.obs = recorder_of(self.conf)
        self._step = None
        self._run_chunk = None

    # hostcall serve resolves concatenated func index -> tenant-local one
    def resolve_func(self, k: int):
        ti = self._func_owner[k]
        return self.tenants[ti].inst.funcs[k - self.bases[ti]["func"]]

    def initial_state(self, func_idx=None, args_lanes=None) -> BatchState:
        import jax.numpy as jnp

        cfg = self.cfg
        L = self.lanes
        img = self.img
        D = cfg.value_stack_depth
        CD = cfg.call_stack_depth
        stack_lo = np.zeros((D, L), np.int32)
        stack_hi = np.zeros((D, L), np.int32)
        pc = np.zeros(L, np.int32)
        sp = np.zeros(L, np.int32)
        opbase = np.zeros(L, np.int32)
        pages = np.zeros(L, np.int32)
        mem_words = max(img.mem_pages_max * _PAGE_WORDS, 1)
        mem = np.zeros((mem_words, L), np.int32)
        from wasmedge_tpu.common.types import ValType

        lane0 = 0
        self._tenant_slices = []
        self._tenant_funcidx = []
        for ti, t in enumerate(self.tenants):
            sl = slice(lane0, lane0 + t.lanes)
            self._tenant_slices.append(sl)
            ex = t.inst.exports.get(t.func_name)
            if ex is None or ex[0] != 0:
                raise KeyError(f"tenant {ti}: no export {t.func_name}")
            ft = t.inst.funcs[ex[1]].functype
            if ValType.V128 in tuple(ft.params) + tuple(ft.results):
                raise ValueError(
                    f"tenant {ti}: batch entry functions cannot take or "
                    f"return v128 (lane args are 64-bit cells)")
            fidx = ex[1] + self.bases[ti]["func"]
            self._tenant_funcidx.append(fidx)
            meta = t.inst.lowered.funcs[ex[1]]
            pc[sl] = int(self.img.f_entry[fidx])
            sp[sl] = meta.nlocals
            opbase[sl] = meta.nlocals
            for i, arg in enumerate(t.args_lanes):
                arr = np.asarray(arg, np.int64)
                if arr.ndim == 0:
                    arr = np.full(t.lanes, arr, np.int64)
                stack_lo[i, sl] = (arr & 0xFFFFFFFF).astype(
                    np.uint32).view(np.int32)
                stack_hi[i, sl] = ((arr >> 32) & 0xFFFFFFFF).astype(
                    np.uint32).view(np.int32)
            if t.img.has_memory:
                pages[sl] = t.img.mem_pages_init
                n = min(t.img.mem_init.shape[0], mem_words)
                mem[:n, sl] = t.img.mem_init[:n, None]
            lane0 += t.lanes
        g_lo = np.repeat(img.globals_lo[:, None], L, axis=1)
        g_hi = np.repeat(img.globals_hi[:, None], L, axis=1)
        fuel0 = cfg.fuel_per_launch if cfg.fuel_per_launch is not None else 0
        return BatchState(
            pc=jnp.asarray(pc), sp=jnp.asarray(sp),
            fp=jnp.zeros(L, jnp.int32), opbase=jnp.asarray(opbase),
            call_depth=jnp.zeros(L, jnp.int32),
            trap=jnp.zeros(L, jnp.int32), retired=jnp.zeros(L, jnp.int32),
            fuel=jnp.full(L, fuel0, jnp.int32),
            mem_pages=jnp.asarray(pages),
            stack_lo=jnp.asarray(stack_lo), stack_hi=jnp.asarray(stack_hi),
            fr_ret_pc=jnp.zeros((CD, L), jnp.int32),
            fr_fp=jnp.zeros((CD, L), jnp.int32),
            fr_opbase=jnp.zeros((CD, L), jnp.int32),
            glob_lo=jnp.asarray(g_lo), glob_hi=jnp.asarray(g_hi),
            mem=jnp.asarray(mem),
            stack_e2=jnp.zeros((D, L), jnp.int32) if img.has_simd else None,
            stack_e3=jnp.zeros((D, L), jnp.int32) if img.has_simd else None,
            **self._r05_planes(),
        )

    def _r05_planes(self, tsize: Optional[np.ndarray] = None,
                    patches: Optional[dict] = None) -> dict:
        """Concatenated-image variant of engine.r05_state_planes: the
        tab plane holds every tenant's slot; `tsize` is the per-lane
        table-size vector — None derives the fixed-cohort default
        (each tenant's slice sees its own table size); the serving
        engine passes a lane-uniform vector instead.  `patches` is the
        snapshot-overlay row-range writes ({"tab"/"edrop"/"ddrop":
        (row0, column)}) applied lane-uniformly before upload; None
        (every non-snapshot caller) leaves the planes untouched."""
        import jax.numpy as jnp

        img = self.img
        L = self.lanes
        out = {}
        if getattr(img, "has_table_mut", False):
            T = max(int(img.table_cap or img.table0.shape[0]), 1)
            tb = np.zeros((T, L), np.int32)
            n0 = min(img.table0.shape[0], T)
            tb[:n0] = img.table0[:n0, None]
            if patches and "tab" in patches:
                row0, col = patches["tab"]
                n = min(col.shape[0], T - row0)
                if n > 0:
                    tb[row0:row0 + n] = col[:n, None]
            if tsize is None:
                tsize = np.zeros(L, np.int32)
                for ti, t in enumerate(self.tenants):
                    tsize[self._tenant_slices[ti]] = t.img.table_size_init
            out["tab"] = jnp.asarray(tb)
            out["tsize"] = jnp.asarray(np.asarray(tsize, np.int32))
        if bool(np.isin(img.cls, (CLS_TABLE_INIT, CLS_ELEM_DROP)).any()):
            ed = np.zeros((img.elem_len.shape[0], L), np.int32)
            if patches and "edrop" in patches:
                row0, col = patches["edrop"]
                n = min(col.shape[0], ed.shape[0] - row0)
                if n > 0:
                    ed[row0:row0 + n] = col[:n, None]
            out["edrop"] = jnp.asarray(ed)
        if bool(np.isin(img.cls, (CLS_MEMINIT, CLS_DATA_DROP)).any()):
            dd = np.zeros((img.data_len.shape[0], L), np.int32)
            if patches and "ddrop" in patches:
                row0, col = patches["ddrop"]
                n = min(col.shape[0], dd.shape[0] - row0)
                if n > 0:
                    dd[row0:row0 + n] = col[:n, None]
            out["ddrop"] = jnp.asarray(dd)
        return out

    def _try_pallas(self):
        """Pallas fast path when every tenant\'s lane count aligns to the
        kernel\'s lane blocks (tenant blocks are block-uniform control,
        which is exactly the kernel\'s convergence model)."""
        from wasmedge_tpu.batch.pallas_engine import (
            PallasUniformEngine, pallas_enabled)

        if not pallas_enabled(self.cfg):
            return None
        eng = PallasUniformEngine(self.tenants[0].inst, conf=self.conf,
                                  simt=self,
                                  interpret=self.cfg.interpret or None)
        eng._blk_cap = min(t.lanes for t in self.tenants)
        eng.ineligible_reason = eng._eligibility()
        if not eng.eligible:
            return None
        Lblk = eng._lane_block()
        if Lblk is None or any(t.lanes % Lblk for t in self.tenants):
            return None
        return eng

    def _try_schedulers(self, max_steps):
        """Per-tenant Pallas engines driven by interleaved block
        schedulers.  Tenants are share-nothing, so each gets its OWN
        kernel geometry (a memory-heavy tenant no longer drags
        memory-free tenants' lane blocks down to its VMEM footprint) and
        its own entry grouping.  Launches are asynchronous: while one
        tenant's host side processes results, the others' kernels run —
        the (module, PC)-bucket scheduling SURVEY §7 step 8 prescribes.
        Returns {tenant_index: BlockScheduler} for the eligible tenants,
        or None when the Pallas path is off."""
        from wasmedge_tpu.batch.pallas_engine import (
            PallasUniformEngine, pallas_enabled)
        from wasmedge_tpu.batch.scheduler import BlockScheduler

        if not pallas_enabled(self.cfg):
            return None
        scheds = {}
        for ti, t in enumerate(self.tenants):
            if t.engine.conf is self.conf:
                # reuse the tenant's existing BatchEngine (its image is
                # already built and normalized) as the SIMT side
                eng = PallasUniformEngine(
                    t.inst, simt=t.engine,
                    interpret=self.cfg.interpret or None)
            else:
                # mismatched confs: THIS engine's knobs must govern the
                # run (fuel, steps_per_launch, memory ceilings), so build
                # a fresh SIMT side under self.conf
                eng = PallasUniformEngine(
                    t.inst, store=t.engine.store, conf=self.conf,
                    lanes=t.lanes, interpret=self.cfg.interpret or None)
            if not eng.eligible:
                continue
            scheds[ti] = BlockScheduler(eng, t.func_name,
                                        list(t.args_lanes), max_steps)
        return scheds or None

    def run_tenants(self, max_steps: int = 10_000_000) -> List[BatchResult]:
        """Run the whole mixed batch; returns one BatchResult per tenant."""
        scheds = self._try_schedulers(max_steps)
        if scheds is not None:
            self.used_pallas = True
            active = dict(scheds)
            while active:
                for s in active.values():
                    s.launch()
                done = [ti for ti, s in active.items() if not s.process()]
                for ti in done:
                    del active[ti]
            for s in scheds.values():
                s._run_simt_residue()
            out = []
            for ti, t in enumerate(self.tenants):
                if ti in scheds:
                    out.append(scheds[ti].result())
                else:
                    # ineligible tenant: its own SIMT engine, alone
                    res = t.engine.run(t.func_name, list(t.args_lanes),
                                       max_steps)
                    out.append(res)
            return out
        from wasmedge_tpu.batch.compact import arm

        arm(self)   # fresh per-run lane-compaction mapping (off = None)
        state = self.initial_state()
        total = 0
        pallas = self._try_pallas()
        self.used_pallas = pallas is not None
        if pallas is not None:
            state, steps_per_block, fell_back = pallas.run_blocks(
                state, max_steps)
            total = int(steps_per_block.max())
            if fell_back or (np.asarray(state.trap) == 0).any():
                state, total = self.run_from_state(state, total, max_steps)
        else:
            state, total = self.run_from_state(state, 0, max_steps)
        return self.results_from_state(state, total)

    def results_from_state(self, state: BatchState, total: int
                           ) -> List[BatchResult]:
        """Harvest one BatchResult per tenant from a final SIMT state —
        shared by run_tenants and the supervised entry
        (batch/supervisor.py drives run_from_state slices itself for
        checkpoint cadence, then harvests here)."""
        stack_lo = np.asarray(state.stack_lo)
        stack_hi = np.asarray(state.stack_hi)
        # lane compaction permutes across tenant slice boundaries: the
        # src mapping restores original (per-tenant-contiguous) order
        from wasmedge_tpu.batch.compact import restore_mirrors

        stack_lo, stack_hi, trap, retired = restore_mirrors(
            getattr(self, "compactor", None), stack_lo, stack_hi,
            np.asarray(state.trap), np.asarray(state.retired))
        out = []
        for ti, t in enumerate(self.tenants):
            sl = self._tenant_slices[ti]
            ex = t.inst.exports[t.func_name]
            nres = int(t.inst.lowered.funcs[ex[1]].nresults)
            results = []
            for r in range(nres):
                lo = stack_lo[r, sl].view(np.uint32).astype(np.uint64)
                hi = stack_hi[r, sl].view(np.uint32).astype(np.uint64)
                results.append((lo | (hi << np.uint64(32))).view(np.int64))
            out.append(BatchResult(results=results, trap=trap[sl],
                                   retired=retired[sl], steps=total))
        return out


class MultiModuleBatchEngine(MultiTenantBatchEngine):
    """Serving-oriented concatenation: many modules, ANY lane, ANY entry.

    `MultiTenantBatchEngine` packs a fixed cohort — each tenant owns a
    contiguous lane slice initialized once at its own entry.  The
    serving gateway needs the transpose: one long-lived lane pool where
    a freed lane can be re-initialized onto ANY registered module's
    exported function (the LaneRecycler `initial_state` template seam).
    This engine keeps the pure-concatenation image (every module's
    index spaces rebased into one super-image, so per-module execution
    is bit-identical to a solo run) but makes `initial_state` lane-
    UNIFORM per engine-global function index: entry pc/locals from the
    owning module, that module's memory/table snapshot in every lane,
    the full concatenated global plane (a fresh request resets its
    lane's whole global column to init — fresh-instance semantics).

    Entry names are qualified `module:func` (`export_func_idx`); an
    unqualified name falls back to the first registered module, so a
    one-module engine behaves like a plain BatchEngine under the
    serving layer.  Hostcalls stay on the per-module tier-1 channel
    (concatenated images carry no t0kind plane), which is what keeps
    per-module WASI environs authoritative.

    `modules` is an ordered [(name, inst, store)]; `lanes` is the
    serving pool width (unrelated to any per-module cohort).
    `engines` optionally supplies the per-module BatchEngines (one per
    entry of `modules`, order-matched) so repeated generation builds
    reuse the already-built-and-normalized DeviceImages instead of
    re-lowering every registered module on each swap (the gateway's
    registry caches one engine per module at registration time)."""

    def __init__(self, modules: Sequence[Tuple[str, object, object]],
                 conf=None, lanes: Optional[int] = None, engines=None,
                 mesh=None, segment_cache=None, init_overlays=None,
                 snapshot_counts=None):
        if not modules:
            raise ValueError("no modules")
        names = [name for name, _, _ in modules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate module names in {names}")
        tenants = []
        for k, (name, inst, store) in enumerate(modules):
            # per-module BatchEngine: builds + normalizes the module's
            # own DeviceImage (raises ValueError when not batchable);
            # lanes=1 — only the image is used, never its state
            eng = engines[k] if engines is not None \
                else BatchEngine(inst, store=store, conf=conf, lanes=1)
            tenants.append(Tenant(engine=eng, func_name="",
                                  args_lanes=[], lanes=0))
        # segment memoization must be visible to the base __init__'s
        # concat_images call; overlays only matter to initial_state
        self._segment_cache = segment_cache
        super().__init__(tenants, conf=conf, mesh=mesh)
        self._init_overlays = dict(init_overlays) if init_overlays else {}
        self.snapshot_counts = (snapshot_counts
                                if snapshot_counts is not None else {})
        self.lanes = int(lanes) if lanes else self.cfg.lanes
        if mesh is not None:
            # even lane split across the mesh: round the serving pool
            # up — the extra lanes are plain capacity (idle lanes park
            # TRAP_DONE and cost only their plane storage)
            from wasmedge_tpu.parallel.shard_drive import padded_lanes

            self.lanes = padded_lanes(self.lanes, int(mesh.devices.size))
        self.module_names = list(names)
        self._mod_index = {name: ti for ti, name in enumerate(names)}

    # -- the export_func_idx / func_nresults seam (serve/recycle.py) ------
    def export_func_idx(self, func_name: str) -> int:
        from wasmedge_tpu.batch.engine import check_batch_entry

        mod, sep, fn = func_name.partition(":")
        if not sep:
            mod, fn = self.module_names[0], func_name
        ti = self._mod_index.get(mod)
        if ti is None:
            raise KeyError(f"no registered module {mod!r}")
        try:
            local = check_batch_entry(self.tenants[ti].inst, fn)
        except KeyError:
            raise KeyError(
                f"no exported function {fn!r} in module {mod!r}") \
                from None
        return local + self.bases[ti]["func"]

    def func_nresults(self, func_idx: int) -> int:
        return int(self.img.f_nresults[func_idx])

    def func_owner(self, func_idx: int) -> str:
        """Owning module name of an engine-global function index."""
        return self.module_names[self._func_owner[func_idx]]

    def note_snapshot_install(self, func_idx: int, n: int) -> None:
        """Recycler hook: count lanes admitted onto a snapshot overlay.

        serve/recycle.py calls this on every install; only entries whose
        owning module carries a pre-initialized overlay count (modules
        without one admit through plain template init)."""
        if not self._init_overlays:
            return
        if self.module_names[self._func_owner[func_idx]] \
                in self._init_overlays:
            self.snapshot_counts["installs"] = \
                self.snapshot_counts.get("installs", 0) + int(n)

    def exported_funcs(self, module: str) -> List[str]:
        return self.tenants[self._mod_index[module]].inst.func_names()

    # -- lane-uniform entry state (the recycler's template source) --------
    def initial_state(self, func_idx: int = 0, args_lanes=None
                      ) -> BatchState:
        import jax.numpy as jnp

        from wasmedge_tpu.batch.engine import pack_lane_args

        args_lanes = args_lanes or []
        cfg = self.cfg
        L = self.lanes
        img = self.img
        ti = self._func_owner[func_idx]
        t = self.tenants[ti]
        meta = t.inst.lowered.funcs[func_idx - self.bases[ti]["func"]]
        D = cfg.value_stack_depth
        CD = cfg.call_stack_depth
        stack_lo, stack_hi = pack_lane_args(args_lanes, L, D)
        # plane geometry is function-INDEPENDENT (the pool's lanes are
        # recycled across modules): memory sized to the concatenated
        # image's max, initialized with the owning module's snapshot
        mem_words = max(img.mem_pages_max * _PAGE_WORDS, 1)
        mem = np.zeros((mem_words, L), np.int32)
        pages = 0
        if t.img.has_memory:
            pages = t.img.mem_pages_init
            n = min(t.img.mem_init.shape[0], mem_words)
            mem[:n] = t.img.mem_init[:n, None]
        g_lo = np.repeat(img.globals_lo[:, None], L, axis=1)
        g_hi = np.repeat(img.globals_hi[:, None], L, axis=1)
        tsize_val = t.img.table_size_init
        patches = None
        ov = (self._init_overlays.get(self.module_names[ti])
              if getattr(self, "_init_overlays", None) else None)
        if ov is not None:
            # pre-initialized snapshot overlay (imagestore/snapshot.py):
            # the captured post-init columns replace the owning module's
            # template init in every lane — memory/pages from row 0 of
            # the shared per-lane planes, globals/table/drop flags into
            # the module's segment rows via the indirection bases
            om = ov.get("mem")
            if om is not None:
                n = min(om.shape[0], mem_words)
                mem[:n] = om[:n, None]
            if ov.get("mem_pages") is not None:
                pages = int(ov["mem_pages"])
            og = ov.get("glob_lo")
            if og is not None:
                gb = self.bases[ti]["glob"]
                g_lo[gb:gb + og.shape[0]] = og[:, None]
                oh = ov["glob_hi"]
                g_hi[gb:gb + oh.shape[0]] = oh[:, None]
            patches = {}
            ot = ov.get("tab")
            if ot is not None:
                # runtime table entries are funcidx+1 (0 = null); rebase
                # exactly the way concat rebases table0 snapshots
                col = np.asarray(ot, np.int32).copy()
                col[col != 0] += self.bases[ti]["func"]
                patches["tab"] = (self.bases[ti]["table"], col)
            if ov.get("tsize") is not None:
                tsize_val = int(ov["tsize"])
            if ov.get("edrop") is not None:
                patches["edrop"] = (self.bases[ti]["eseg"],
                                    np.asarray(ov["edrop"], np.int32))
            if ov.get("ddrop") is not None:
                patches["ddrop"] = (self.bases[ti]["dseg"],
                                    np.asarray(ov["ddrop"], np.int32))
        fuel0 = cfg.fuel_per_launch if cfg.fuel_per_launch is not None \
            else 0
        return BatchState(
            pc=jnp.full((L,), int(img.f_entry[func_idx]), jnp.int32),
            sp=jnp.full((L,), meta.nlocals, jnp.int32),
            fp=jnp.zeros(L, jnp.int32),
            opbase=jnp.full((L,), meta.nlocals, jnp.int32),
            call_depth=jnp.zeros(L, jnp.int32),
            trap=jnp.zeros(L, jnp.int32),
            retired=jnp.zeros(L, jnp.int32),
            fuel=jnp.full(L, fuel0, jnp.int32),
            mem_pages=jnp.full((L,), pages, jnp.int32),
            stack_lo=jnp.asarray(stack_lo),
            stack_hi=jnp.asarray(stack_hi),
            fr_ret_pc=jnp.zeros((CD, L), jnp.int32),
            fr_fp=jnp.zeros((CD, L), jnp.int32),
            fr_opbase=jnp.zeros((CD, L), jnp.int32),
            glob_lo=jnp.asarray(g_lo),
            glob_hi=jnp.asarray(g_hi),
            mem=jnp.asarray(mem),
            stack_e2=jnp.zeros((D, L), jnp.int32) if img.has_simd
            else None,
            stack_e3=jnp.zeros((D, L), jnp.int32) if img.has_simd
            else None,
            # lane-uniform tsize: every lane sees the owning module's
            # table size (the tab plane still holds every module's
            # slot — table ops address slots through the rebased
            # instruction words)
            **self._r05_planes(
                np.full(L, tsize_val, np.int32), patches=patches),
        )


def run_mixed(specs, conf=None, max_steps: int = 10_000_000):
    """Convenience: specs = [(inst, store, func_name, args_lanes, lanes)].

    Builds per-module BatchEngines, concatenates, runs, returns one
    BatchResult per tenant."""
    from wasmedge_tpu.common.configure import Configure

    conf = conf or Configure()
    tenants = []
    for inst, store, func_name, args_lanes, lanes in specs:
        eng = BatchEngine(inst, store=store, conf=conf, lanes=lanes)
        tenants.append(Tenant(engine=eng, func_name=func_name,
                              args_lanes=list(args_lanes), lanes=lanes))
    mt = MultiTenantBatchEngine(tenants, conf=conf)
    return mt.run_tenants(max_steps=max_steps)
