"""Pallas warp-interpreter: the on-device Wasm dispatch loop.

This is the engine SURVEY.md §7 step 4 calls the north star: the moral
equivalent of the reference's `while (PC != PCEnd) switch (opcode)` hot loop
(/root/reference/lib/executor/engine/engine.cpp:68-1641), rebuilt as a TPU
kernel.  The whole fetch→decode→execute loop runs *inside one Pallas kernel
launch*: code tables live in SMEM (scalar memory), lane state (value stacks,
globals, linear memory, trap plane) lives in VMEM refs that handlers mutate
in place, and control state (pc/sp/fp/...) is a scalar `lax.while_loop`
carry.  One launch retires up to `steps_per_launch` instructions for every
lane with zero host round-trips, which is what removes the ~400µs/step
dispatch overhead the pure-XLA engines pay (every XLA step re-threads
multi-MB state through a conditional).

Execution model (same as batch/uniform.py): lanes are *converged* within a
lane block — pc/sp/fp/call_depth are block-uniform scalars; per-lane data
diverges freely.  The lane axis is tiled into grid blocks so that large
per-lane linear memories still fit VMEM (e.g. 64 KiB/lane × 128 lanes);
different blocks may take different control paths (each grid program runs
its own dispatch loop).  A data-dependent branch (or per-lane trap or
memory fault) that disagrees *within* a block stops that block with
status=DIVERGED and the host hands the whole batch to the general SIMT
engine (batch/engine.py).  Handlers that bail on divergence do so *before*
any ref mutation, so the handed-over state re-executes the divergent
instruction exactly like uniform.py's functional rewind.

Memory: per-lane linear memory is a word-major [W, lanes] VMEM ref.  Loads
and stores take a *uniform-address fast path* (row dynamic-slice — converged
code almost always computes identical addresses in every lane) and, when the
memory is small enough, fall back to a masked compare-reduce gather/scatter
over the whole [W, block] array for divergent addresses.

Dispatch is a balanced binary tree of `lax.cond` over *densely renumbered*
handler ids (Mosaic lowers `lax.switch` to a linear if-chain, ~15ns per
position walked; the tree is ~log2(N) branches, uniform across ids): only
the handlers a module actually uses are compiled into its kernel, so small
modules get small, fast-compiling kernels.  Kernels are cached by
(used-handler set, state geometry); modules sharing both share a compile.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.batch.image import (
    ALU1_SUB,
    ALU2_F32_BASE,
    ALU2_I32_BASE,
    ALU2_I64_BASE,
    NUM_ALU1,
    NUM_ALU2,
    CLS_ALU1,
    CLS_ALU2,
    CLS_BR,
    CLS_BR_TABLE,
    CLS_BRNZ,
    CLS_BRZ,
    CLS_CALL,
    CLS_CALL_INDIRECT,
    CLS_CONST,
    CLS_DROP,
    CLS_GLOBAL_GET,
    CLS_GLOBAL_SET,
    CLS_HOSTCALL,
    CLS_LOAD,
    CLS_LOCAL_GET,
    CLS_LOCAL_SET,
    CLS_LOCAL_TEE,
    CLS_MEMCOPY,
    CLS_MEMFILL,
    CLS_MEMGROW,
    CLS_MEMSIZE,
    CLS_NOP,
    CLS_RETURN,
    CLS_SELECT,
    CLS_STORE,
    CLS_TRAP,
    CLS_V1,
    CLS_V2,
    CLS_VBITSEL,
    CLS_VCONST,
    CLS_VEXTRACT,
    CLS_VLOAD,
    CLS_VREPLACE,
    CLS_VSHIFT,
    CLS_VSHUFFLE,
    CLS_VSPLAT,
    CLS_VSTORE,
    CLS_VTEST,
    DeviceImage,
    TRAP_DONE,
    _F32_BIN,
    _I32_BIN,
)

# ---------------------------------------------------------------------------
# Flat handler-id space (before per-module dense renumbering)
# ---------------------------------------------------------------------------
H_NOP = 0
H_CONST = 1
H_LOCAL_GET = 2
H_LOCAL_SET = 3
H_LOCAL_TEE = 4
H_GLOBAL_GET = 5
H_GLOBAL_SET = 6
H_DROP = 7
H_SELECT = 8
H_BR = 9
H_BRZ = 10
H_BRNZ = 11
H_BR_TABLE = 12
H_RETURN = 13
H_CALL = 14
H_CALL_INDIRECT = 15
H_MEMSIZE = 16
H_MEMGROW = 17
H_TRAP = 18
H_LOAD = 19
H_STORE = 20
H_HOSTCALL = 21
H_MEMFILL = 22
H_MEMCOPY = 23
H_ALU2_BASE = 24                      # + ALU2 sub id
H_ALU1_BASE = H_ALU2_BASE + NUM_ALU2  # + ALU1 sub id
# superinstructions (pallas-only peephole fusion, see fuse_image):
#   GCA: local.get a; const imm; alu2 sub   -> one dispatch, pc += 3
#   GBR: local.get sub; br a,b,c            -> one dispatch
#   GCB: local.get a; const imm; alu2 sub; brz b -> one dispatch
#   A2R: alu2 sub; return(1 result)             -> one dispatch
H_FUSE_GCA_BASE = H_ALU1_BASE + NUM_ALU1      # + ALU2 sub id
H_FUSE_GCB_BASE = H_FUSE_GCA_BASE + NUM_ALU2  # + ALU2 sub id
#   GCC: local.get a; const imm; alu2 sub; call b -> one dispatch
H_FUSE_A2R_BASE = H_FUSE_GCB_BASE + NUM_ALU2  # + ALU2 sub id
H_FUSE_GCC_BASE = H_FUSE_A2R_BASE + NUM_ALU2  # + ALU2 sub id
# loop-body families (the hot patterns of counted loops; fields at fuse
# time:  a/b/c keep the branch or dst operands, ilo/ihi carry local idxs
# or the immediate):
#   GCS:   local.get a; const ilo/ihi; alu2; local.set b   -> pc += 4
#   GGA:   local.get a; local.get c; alu2                  -> pc += 3
#   GGS:   local.get a; local.get c; alu2; local.set b     -> pc += 4
#   GGBZ:  local.get ilo; local.get ihi; alu2; brz a       -> pc += 4
#   GGBNZ: local.get ilo; local.get ihi; alu2; brnz a,b,c  -> pc += 4
H_FUSE_GCS_BASE = H_FUSE_GCC_BASE + NUM_ALU2
H_FUSE_GGA_BASE = H_FUSE_GCS_BASE + NUM_ALU2
H_FUSE_GGS_BASE = H_FUSE_GGA_BASE + NUM_ALU2
H_FUSE_GGBZ_BASE = H_FUSE_GGS_BASE + NUM_ALU2
H_FUSE_GGBNZ_BASE = H_FUSE_GGBZ_BASE + NUM_ALU2
H_FUSE_GBR = H_FUSE_GGBNZ_BASE + NUM_ALU2
# width-specialized memory ops (appended so earlier ids stay stable):
# plain 32/64-bit loads/stores skip the sub-word sign/width machinery —
# the hot shapes in compiled code
H_LOAD_W = H_FUSE_GBR + 1    # i32.load  (nbytes=4, no extension)
H_LOAD_D = H_FUSE_GBR + 2    # i64.load  (nbytes=8)
H_STORE_W = H_FUSE_GBR + 3   # i32.store / f32.store
H_STORE_D = H_FUSE_GBR + 4   # i64.store / f64.store
# v128: cells are 4 int32 planes (lo, hi, e2, e3); op semantics come
# from batch/simdops.py — the same fns the SIMT engine dispatches
# (engine.py "v128 (SIMD)" section), here as per-sub handlers.  Dense
# renumbering means a module compiles only the subs it uses.
H_VCONST = H_STORE_D + 1
H_VSHUFFLE = H_VCONST + 1
H_VBITSEL = H_VSHUFFLE + 1
H_VLOAD = H_VBITSEL + 1
H_VSTORE = H_VLOAD + 1
from wasmedge_tpu.batch.simdops import (   # noqa: E402
    V1_NAMES,
    V2_NAMES,
    VEXTRACT_NAMES,
    VREPLACE_NAMES,
    VSHIFT_NAMES,
    VSPLAT_NAMES,
    VTEST_NAMES,
)
H_V2_BASE = H_VSTORE + 1
H_V1_BASE = H_V2_BASE + len(V2_NAMES)
H_VTEST_BASE = H_V1_BASE + len(V1_NAMES)
H_VSHIFT_BASE = H_VTEST_BASE + len(VTEST_NAMES)
H_VSPLAT_BASE = H_VSHIFT_BASE + len(VSHIFT_NAMES)
H_VEXTRACT_BASE = H_VSPLAT_BASE + len(VSPLAT_NAMES)
H_VREPLACE_BASE = H_VEXTRACT_BASE + len(VEXTRACT_NAMES)
NUM_HANDLERS = H_VREPLACE_BASE + len(VREPLACE_NAMES)

_CLS_TO_HID = {
    CLS_NOP: H_NOP, CLS_CONST: H_CONST, CLS_LOCAL_GET: H_LOCAL_GET,
    CLS_LOCAL_SET: H_LOCAL_SET, CLS_LOCAL_TEE: H_LOCAL_TEE,
    CLS_GLOBAL_GET: H_GLOBAL_GET, CLS_GLOBAL_SET: H_GLOBAL_SET,
    CLS_DROP: H_DROP, CLS_SELECT: H_SELECT, CLS_BR: H_BR, CLS_BRZ: H_BRZ,
    CLS_BRNZ: H_BRNZ, CLS_BR_TABLE: H_BR_TABLE, CLS_RETURN: H_RETURN,
    CLS_CALL: H_CALL, CLS_CALL_INDIRECT: H_CALL_INDIRECT,
    CLS_MEMSIZE: H_MEMSIZE, CLS_MEMGROW: H_MEMGROW, CLS_TRAP: H_TRAP,
    CLS_LOAD: H_LOAD, CLS_STORE: H_STORE, CLS_HOSTCALL: H_HOSTCALL,
    CLS_MEMFILL: H_MEMFILL, CLS_MEMCOPY: H_MEMCOPY,
    CLS_VCONST: H_VCONST, CLS_VSHUFFLE: H_VSHUFFLE,
    CLS_VBITSEL: H_VBITSEL, CLS_VLOAD: H_VLOAD, CLS_VSTORE: H_VSTORE,
}

# sub-indexed v128 classes -> handler base id
_VCLS_TO_BASE = {
    CLS_V2: H_V2_BASE, CLS_V1: H_V1_BASE, CLS_VTEST: H_VTEST_BASE,
    CLS_VSHIFT: H_VSHIFT_BASE, CLS_VSPLAT: H_VSPLAT_BASE,
    CLS_VEXTRACT: H_VEXTRACT_BASE, CLS_VREPLACE: H_VREPLACE_BASE,
}

# status values (shared with batch/uniform.py)
ST_RUNNING = 0
ST_DONE = 1
ST_DIVERGED = 2
ST_HOSTCALL = 3  # block parked at a host outcall stub
# memory.grow needs more rows than the watermark-sized plane holds: the
# grow is legal (<= declared max) but the kernel geometry is too small.
# The block stops un-advanced; the host re-executes on an engine with a
# bigger plane (SIMT today; a re-geometried kernel when the scheduler
# learns to migrate).  Watermark sizing is SURVEY §5.7's design: the
# plane covers *current* pages, not the declared max, so a module that
# declares max=16 pages but touches one page keeps a VMEM-sized state.
ST_REGROW = 4
# optimistic-convergence rollback: the block was rewound to its last
# validated snapshot; the driver re-runs it on the careful kernel
ST_RECHECK = 5
ST_TRAPPED_BASE = 16

_PAGE_WORDS = 65536 // 4
_FUEL_OFF = 0x7FFFFFFF  # fuel column value when gas metering is disabled

# ctrl row layout (SMEM, int32[nblk, 16])
_C_PC, _C_SP, _C_FP, _C_OB, _C_CD, _C_STATUS, _C_PAGES, _C_CHUNK = range(8)
_C_STEPS = 8
_C_FUEL = 9
# per-block optimistic snapshot interval (adaptive: the host halves it
# when a block rolls back — bounding the run-up a divergent block
# discards — and doubles it back toward SNAP_STEPS on clean launches).
# 0 means "use the kernel's build-time snap_steps".
_C_SNAP = 10
_SNAP_MIN = 256


def merge_block_status_into_trap(trap_v: np.ndarray, ctrl: np.ndarray,
                                 Lblk: int) -> np.ndarray:
    """Fold per-block exit status into the per-lane trap plane:
    DONE blocks -> TRAP_DONE sentinel, trapped blocks -> their code on
    lanes that have no more specific per-lane code yet."""
    for b in range(ctrl.shape[0]):
        status = int(ctrl[b, _C_STATUS])
        sl = slice(b * Lblk, (b + 1) * Lblk)
        if status == ST_DONE:
            trap_v[sl] = TRAP_DONE
        elif status >= ST_TRAPPED_BASE:
            seg = trap_v[sl]
            seg[seg == 0] = status - ST_TRAPPED_BASE
            trap_v[sl] = seg
    return trap_v


def decode_result_rows(stack_lo: np.ndarray, stack_hi: np.ndarray,
                       nres: int):
    """Reassemble 64-bit result cells from the lo/hi int32 planes."""
    results = []
    for r in range(nres):
        lo = stack_lo[r].view(np.uint32).astype(np.uint64)
        hi = stack_hi[r].view(np.uint32).astype(np.uint64)
        results.append((lo | (hi << np.uint64(32))).view(np.int64))
    return results


def fuse_image(hid, a, b, c, ilo, ihi, img):
    """Peephole superinstruction fusion over the flat-hid planes.

    The dominant dispatch patterns in call-heavy code are
    `local.get; const; alu2` (operand setup + op) and `local.get; br`
    (loop/return value shuffles).  Fusing them cuts dispatches and stack
    row traffic (one read + one write instead of three of each).  Only
    positions never targeted by a branch/call may be absorbed, and only
    non-trapping alu2 subs fuse (div/rem keep their own trap handler).
    Returns rewritten copies; the originals (and every other engine's
    image) are untouched — this is a pallas-private encoding."""
    n = img.code_len
    targets = set(int(x) for x in img.f_entry)
    for pc in range(n):
        cl = int(img.cls[pc])
        if cl in (CLS_BR, CLS_BRZ, CLS_BRNZ):
            targets.add(int(img.a[pc]))
    for e in range(img.br_table.shape[0]):
        targets.add(int(img.br_table[e, 0]))
    hid = hid.copy()
    a = a.copy()
    b = b.copy()
    c = c.copy()
    ilo = ilo.copy()
    ihi = ihi.copy()
    pc = 0
    while pc < n - 1:
        h0 = int(hid[pc])
        absorb2 = pc + 1 not in targets
        absorb3 = absorb2 and pc + 2 not in targets and pc + 2 < n
        h1 = int(hid[pc + 1]) if absorb2 else -1
        h2 = int(hid[pc + 2]) if absorb3 else -1
        if h0 == H_LOCAL_GET and absorb3 and h1 == H_CONST and \
                H_ALU2_BASE <= h2 < H_ALU2_BASE + NUM_ALU2:
            sub = h2 - H_ALU2_BASE
            if sub not in _DIV32_SUBS and sub not in _DIV64_SUBS:
                ok4 = pc + 3 not in targets and pc + 3 < n
                h3 = int(hid[pc + 3]) if ok4 else -1
                if h3 == H_BRZ:
                    # quad: the compare feeds a brz; no stack writes at all
                    hid[pc] = H_FUSE_GCB_BASE + sub
                    ilo[pc] = ilo[pc + 1]
                    ihi[pc] = ihi[pc + 1]
                    b[pc] = a[pc + 3]        # brz target
                    pc += 4
                    continue
                if h3 == H_CALL:
                    # quad: computed value is the callee's argument
                    hid[pc] = H_FUSE_GCC_BASE + sub
                    ilo[pc] = ilo[pc + 1]
                    ihi[pc] = ihi[pc + 1]
                    b[pc] = a[pc + 3]        # callee index
                    pc += 4
                    continue
                if h3 == H_LOCAL_SET:
                    # quad: local.set dst of the computed value
                    hid[pc] = H_FUSE_GCS_BASE + sub
                    ilo[pc] = ilo[pc + 1]
                    ihi[pc] = ihi[pc + 1]
                    b[pc] = a[pc + 3]        # dst local
                    pc += 4
                    continue
                hid[pc] = H_FUSE_GCA_BASE + sub
                # a keeps the local idx; imm moves up from the const
                ilo[pc] = ilo[pc + 1]
                ihi[pc] = ihi[pc + 1]
                pc += 3
                continue
        if h0 == H_LOCAL_GET and absorb3 and h1 == H_LOCAL_GET and \
                H_ALU2_BASE <= h2 < H_ALU2_BASE + NUM_ALU2:
            sub = h2 - H_ALU2_BASE
            if sub not in _DIV32_SUBS and sub not in _DIV64_SUBS:
                ok4 = pc + 3 not in targets and pc + 3 < n
                h3 = int(hid[pc + 3]) if ok4 else -1
                src1, src2 = int(a[pc]), int(a[pc + 1])
                if h3 == H_BRZ:
                    hid[pc] = H_FUSE_GGBZ_BASE + sub
                    a[pc] = a[pc + 3]        # brz target
                    ilo[pc] = src1
                    ihi[pc] = src2
                    pc += 4
                    continue
                if h3 == H_BRNZ:
                    hid[pc] = H_FUSE_GGBNZ_BASE + sub
                    a[pc] = a[pc + 3]        # brnz target
                    b[pc] = b[pc + 3]        # nkeep
                    c[pc] = c[pc + 3]        # pop_to
                    ilo[pc] = src1
                    ihi[pc] = src2
                    pc += 4
                    continue
                if h3 == H_LOCAL_SET:
                    hid[pc] = H_FUSE_GGS_BASE + sub
                    b[pc] = a[pc + 3]        # dst local
                    c[pc] = src2
                    pc += 4
                    continue
                hid[pc] = H_FUSE_GGA_BASE + sub
                c[pc] = src2
                pc += 3
                continue
        if h0 == H_LOCAL_GET and absorb2 and h1 == H_BR:
            hid[pc] = H_FUSE_GBR
            b_, c_, a_ = int(b[pc + 1]), int(c[pc + 1]), int(a[pc + 1])
            # ilo carries the local idx; a/b/c carry the branch
            c[pc] = c_
            b[pc] = b_
            ilo[pc] = a[pc]
            a[pc] = a_
            pc += 2
            continue
        if H_ALU2_BASE <= h0 < H_ALU2_BASE + NUM_ALU2 and absorb2 and \
                h1 == H_RETURN and int(b[pc + 1]) == 1:
            sub = h0 - H_ALU2_BASE
            if sub not in _DIV32_SUBS and sub not in _DIV64_SUBS:
                hid[pc] = H_FUSE_A2R_BASE + sub
                pc += 2
                continue
        pc += 1
    return hid, a, b, c, ilo, ihi


# ---------------------------------------------------------------------------
# Basic-block fusion
# ---------------------------------------------------------------------------
# The generalized successor of the peephole superinstructions above:
# every maximal straight-line run of *pure* stack ops (const, local/
# global traffic, drop/select, non-trapping alu) fuses into ONE handler
# that keeps intermediate values in vector registers — dispatch cost
# (measured ~150ns/dispatch: the lax.cond tree walk plus the VMEM
# dependency chain between consecutive stack ops) is paid once per
# block instead of once per instruction.  Any non-pure op (branch,
# call, return, load/store, div/rem, memory.*, hostcall) is absorbed as
# the block's TERMINAL: the handler flushes its virtual stack to the
# VMEM rows the op expects and delegates to the op's ORIGINAL handler,
# so branch/trap/park/divergence semantics are reused verbatim.
#
# Only the head slot's hid is rewritten; absorbed slots keep their
# original hids and operand fields, so any pc remains independently
# dispatchable — mid-block branch targets, SIMT-handoff resumptions and
# hostcall re-arms execute the original per-op stream until the next
# block head (every jump target starts a fresh block, so hot loop
# bodies always re-enter fused).  A terminal that stops un-advanced
# (divergence, regrow) leaves pc at the terminal's own slot where the
# scheduler sees the ORIGINAL opcode and resolves it with the existing
# split machinery.  This mirrors what the reference's threaded
# interpreter gets from its compiler for free: straight-line runs with
# values in registers (/root/reference/lib/executor/engine/
# engine.cpp:68-1641).
H_BLOCK_BASE = NUM_HANDLERS
MAX_BLOCK_SHAPES = 96   # distinct block shapes compiled per kernel
MAX_BLOCK_LEN = 24      # ops per block (incl. the terminal)


def _trapping_alu1_subs():
    from wasmedge_tpu.batch import laneops as lo_ops

    return set(lo_ops.alu1_trap_fns().keys())


def fuse_blocks(hid, img):
    """Rewrite block-head hids to H_BLOCK_BASE + shape id.

    Returns (hid', shapes) where shapes is a tuple of block shapes;
    each shape is a tuple of op descriptors:

      ("nop",) ("const",) ("drop",) ("select",) ("memsize",)
      ("lget", k) ("lset", k) ("ltee", k)   k = local ORDINAL (first-
      ("gget", k) ("gset", k)                occurrence rank, so blocks
      ("alu2", sub) ("alu1", sub)            using different locals in
      ("loadi", nbytes, flags)               the same pattern share)
      ("storei", nbytes)
      ("guardz",) ("guardnz",)
      ("term", flat_hid)

    loadi/storei are loads/stores fused INLINE (uniform-address fast
    path; divergence/OOB bails un-advanced at the op's own slot).
    guardz/guardnz are FORWARD branches absorbed mid-block: the block
    speculates fallthrough and the taken path exits at the branch with
    everything before it committed — loop back-edges (backward
    targets) stay terminals so the common taken path pays nothing.
    guardnz requires nkeep == 0 (no value move on the taken exit).

    Immediates/indices are NOT in the shape (handlers read them from
    the SMEM planes at pc+offset), except local/global ordinals, whose
    equality structure decides value forwarding, and alu subs, which
    pick the compute fn.  Deterministic: tpu.aot artifacts verify the
    persisted hid plane by regeneration (aot/__init__.py)."""
    n = img.code_len
    targets = set(int(x) for x in img.f_entry)
    for pc in range(n):
        cl = int(img.cls[pc])
        if cl in (CLS_BR, CLS_BRZ, CLS_BRNZ):
            targets.add(int(img.a[pc]))
    for e in range(img.br_table.shape[0]):
        targets.add(int(img.br_table[e, 0]))
    # call-return / hostcall-re-arm / trap-partial-resume addresses need
    # no seeding: a non-pure op always ends its block, so the next block
    # starts at its pc+1 anyway, and absorbed slots keep their original
    # hids, so any resume pc stays independently dispatchable.

    trap1 = _trapping_alu1_subs()

    def pure_desc(pc, lmap, gmap):
        """Descriptor if the op at pc is pure (fusible mid-block)."""
        cl = int(img.cls[pc])
        if cl == CLS_NOP:
            return ("nop",)
        if cl == CLS_CONST:
            return ("const",)
        if cl == CLS_DROP:
            return ("drop",)
        if cl == CLS_SELECT:
            return ("select",)
        if cl == CLS_MEMSIZE:
            return ("memsize",)
        if cl in (CLS_LOCAL_GET, CLS_LOCAL_SET, CLS_LOCAL_TEE):
            k = lmap.setdefault(int(img.a[pc]), len(lmap))
            return ({CLS_LOCAL_GET: "lget", CLS_LOCAL_SET: "lset",
                     CLS_LOCAL_TEE: "ltee"}[cl], k)
        if cl in (CLS_GLOBAL_GET, CLS_GLOBAL_SET):
            k = gmap.setdefault(int(img.a[pc]), len(gmap))
            return ("gget" if cl == CLS_GLOBAL_GET else "gset", k)
        if cl == CLS_ALU2:
            sub = int(img.sub[pc])
            if sub in _DIV32_SUBS or sub in _DIV64_SUBS:
                return None
            return ("alu2", sub)
        if cl == CLS_ALU1:
            sub = int(img.sub[pc])
            if sub in trap1:
                return None
            return ("alu1", sub)
        if cl == CLS_V2:
            return ("v2", int(img.sub[pc]))
        if cl == CLS_V1:
            return ("v1", int(img.sub[pc]))
        if cl == CLS_VTEST:
            return ("vtest", int(img.sub[pc]))
        if cl == CLS_VSHIFT:
            return ("vshift", int(img.sub[pc]))
        if cl == CLS_VSPLAT:
            return ("vsplat", int(img.sub[pc]))
        if cl == CLS_VEXTRACT:
            return ("vextract", int(img.sub[pc]))
        if cl == CLS_VREPLACE:
            return ("vreplace", int(img.sub[pc]))
        if cl == CLS_VCONST:
            return ("vconst",)
        if cl == CLS_VSHUFFLE:
            return ("vshuffle",)
        if cl == CLS_VBITSEL:
            return ("vbitsel",)
        if cl == CLS_LOAD:
            return ("loadi", int(img.b[pc]), int(img.c[pc]))
        if cl == CLS_STORE:
            return ("storei", int(img.b[pc]))
        if cl == CLS_BRZ and int(img.a[pc]) > pc:
            return ("guardz",)
        if cl == CLS_BRNZ and int(img.a[pc]) > pc and int(img.b[pc]) == 0:
            return ("guardnz",)
        return None

    hid = hid.copy()
    shapes = []
    shape_ids = {}
    pc = 0
    while pc < n:
        # scan a candidate block starting at pc
        lmap, gmap = {}, {}
        ops = []
        j = pc
        while (j < n and len(ops) < MAX_BLOCK_LEN - 1
               and (j == pc or j not in targets)):
            d = pure_desc(j, lmap, gmap)
            if d is None:
                break
            ops.append(d)
            j += 1
        # absorb the stopping op as terminal unless the run stopped at
        # a pure op (a jump-target boundary: that op starts its own
        # block).  A non-pure terminal may itself be a jump target —
        # direct jumps to it dispatch its untouched original hid.
        term = None
        if ops and j < n and pure_desc(j, {}, {}) is None:
            term = ("term", int(hid[j]))
            j += 1
        total = len(ops) + (1 if term else 0)
        shape = tuple(ops) + ((term,) if term else ())
        if total >= 2 and (shape in shape_ids
                           or len(shapes) < MAX_BLOCK_SHAPES):
            sid = shape_ids.get(shape)
            if sid is None:
                sid = len(shapes)
                shape_ids[shape] = sid
                shapes.append(shape)
            hid[pc] = H_BLOCK_BASE + sid
            pc = j
        else:
            pc += 1
    return hid, tuple(shapes)


# SMEM budget for the 7 code planes — the ONE code-size limit shared by
# the engine (PallasUniformEngine.MAX_CODE_LEN) and the tpu.aot
# serializer via pallas_image_eligibility's default.
MAX_CODE_LEN = 16384


def pallas_image_eligibility(img: DeviceImage,
                             max_code_len: int = MAX_CODE_LEN
                             ) -> Optional[str]:
    """Static (lane-count-independent) Pallas eligibility of a device
    image — the ONE source of truth shared by the engine, the scheduler
    and the tpu.aot serializer, so those layers can never disagree about
    what the kernel can execute.  Returns a reason string when the image
    must stay on the SIMT engine, None when the Pallas kernel can run it.
    Mirrors the reference's never-crash AOT fallback seam
    (/root/reference/lib/loader/ast/module.cpp:279-326)."""
    if img.code_len > max_code_len:
        return f"code too large for SMEM ({img.code_len} instrs)"
    unhandled = (set(np.unique(img.cls).tolist())
                 - set(_CLS_TO_HID) - set(_VCLS_TO_BASE)
                 - {CLS_ALU2, CLS_ALU1})
    if unhandled:
        return f"classes without Pallas handlers: {sorted(unhandled)}"
    return None


def hid_plane(img: DeviceImage) -> np.ndarray:
    """Per-pc flat handler id from the (class, sub) encoding."""
    hid = np.zeros(img.code_len, np.int32)
    for pc in range(img.code_len):
        c = int(img.cls[pc])
        if c == CLS_ALU2:
            hid[pc] = H_ALU2_BASE + int(img.sub[pc])
        elif c == CLS_ALU1:
            hid[pc] = H_ALU1_BASE + int(img.sub[pc])
        elif c in _VCLS_TO_BASE:
            hid[pc] = _VCLS_TO_BASE[c] + int(img.sub[pc])
        elif c == CLS_LOAD and int(img.b[pc]) == 4 \
                and int(img.c[pc]) in (0, 2):
            # i32.load / f32.load / i64.load32_u: lo = raw word, hi = 0
            hid[pc] = H_LOAD_W
        elif c == CLS_LOAD and int(img.b[pc]) == 8:
            hid[pc] = H_LOAD_D
        elif c == CLS_STORE and int(img.b[pc]) == 4:
            hid[pc] = H_STORE_W
        elif c == CLS_STORE and int(img.b[pc]) == 8:
            hid[pc] = H_STORE_D
        else:
            hid[pc] = _CLS_TO_HID[c]
    return hid




# ALU2 subs that can trap (div/rem)
_DIV32_SUBS = {ALU2_I32_BASE + _I32_BIN.index(n) for n in
               ("div_s", "div_u", "rem_s", "rem_u")}
_DIV64_SUBS = {ALU2_I64_BASE + _I32_BIN.index(n) for n in
               ("div_s", "div_u", "rem_s", "rem_u")}
_DIVS_SUBS = {ALU2_I32_BASE + _I32_BIN.index("div_s"),
              ALU2_I64_BASE + _I32_BIN.index("div_s")}
# trapping ALU1 subs come from the shared table (laneops.alu1_trap_fns)


@functools.lru_cache(maxsize=64)
def _build_kernel(used_hids: tuple, D: int, CD: int, W: int, L: int,
                  Lblk: int, NG: int, code_len: int, nf: int, tsize: int,
                  max_local_zeros: int, mem_pages_cap: int,
                  mem_pages_hard: int, gatherable: bool, interpret: bool,
                  mem_hbm: bool = False, CW: int = 0,
                  block_shapes: tuple = (),
                  simd: bool = False, NV: int = 1,
                  optimistic: bool = False, snap_steps: int = 8192,
                  shadow_full: bool = None, hid_weights: tuple = ()):
    """Compile the chunk-runner for one kernel geometry.

    Returns a jitted callable over
      (hid, a, b, c, ilo, ihi, fent, fnpar, fnloc, ftop, ftyp, brt, tbl,
       ctrl, frames, stack_lo, stack_hi, glob_lo, glob_hi, mem, trap)
    yielding (ctrl, frames, stack_lo, stack_hi, glob_lo, glob_hi, mem,
    trap); the VMEM planes are aliased in-place.

    mem_hbm=True is the large-block memory mode: the [W, L] linear-memory
    plane stays HBM-resident instead of being DMA'd wholesale into VMEM
    scratch, and loads/stores go through a 2-way LRU *window cache* of CW
    rows per way in VMEM.  Uniform-address accesses that hit a resident
    window are direct row ops (the common case — converged code computes
    identical addresses); misses write back the dirty victim way and DMA
    a fresh CW-row window; per-lane address divergence that still fits
    one window is served by compare-reduce inside the window.  This
    removes the W-words-per-lane term from the VMEM budget, so a 1-page
    module runs thousands of lanes per block instead of 128 — the
    reference's guard-page slab redesign
    (/root/reference/include/runtime/instance/memory.h:34-332) rebuilt a
    second time around the HBM/VMEM split instead of virtual-memory
    protection.  memory.fill streams aligned GR-row chunks through
    scratch; memory.copy runs through the windows (single-window when
    the whole src+dst span fits, way-per-region when src and dst are
    ≥CW+8 rows apart, SIMT handoff for large overlapping moves).

    optimistic=True is the *optimistic-convergence* mode, the engine's
    core TPU perf move: every cross-lane agreement reduction (branch
    conds, load/store address uniformity, trap uniformity — each a
    vector→scalar sync costing ~Lblk-linear time in Mosaic, measured
    ~1.7µs at Lblk=4096) is replaced by a lane-0 decision plus a pure
    vector *canary* accumulation (canary |= lane ^ lane0).  The canary
    is validated by ONE reduction per commit point: every `snap_steps`
    dispatches, before any dirty-window writeback, and at kernel exit.
    A clean validation writes a snapshot (stacks/globals/trap → shadow
    HBM planes, frames/carry → SMEM); a dirty one rolls back to the
    previous snapshot and exits with ST_RECHECK, and the driver re-runs
    the block on the non-optimistic ("careful", optimistic=False)
    kernel for one short chunk to reach the divergent instruction with
    exact per-step semantics — the scheduler then splits as usual.
    memory.fill/copy and in-window divergent addressing always exit to
    the careful kernel.  Convergence validation thus costs O(1)
    reductions per ~snap_steps instructions instead of O(1) per
    instruction, which is what lets one TensorCore retire thousands of
    converged lanes per dispatch at row-op cost."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from wasmedge_tpu.batch import laneops as lo_ops

    I32 = jnp.int32
    u_lt = lo_ops.u_lt
    alu2 = lo_ops.alu2_fns()
    alu1 = lo_ops.alu1_fns()
    alu1_traps = lo_ops.alu1_trap_fns()
    nblk = L // Lblk
    NGp = max(NG, 1)
    # Divergent-address memory ops scan memory in row chunks so the scan
    # temporaries stay bounded (~512 KiB) instead of materializing a full
    # [W, Lblk] iota next to the state.
    GR = W
    while GR > 8 and GR * Lblk * 4 > 512 * 1024:
        GR //= 2
    while GR > 8 and W % GR != 0:
        GR //= 2
    if mem_hbm:
        # fill/copy chunks stage through the CW-row window scratch
        while GR > 8 and GR > CW:
            GR //= 2
    GATHER_CHUNKS = W // GR if W % GR == 0 else 0

    # ---- 8-sublane lane remap ------------------------------------------
    # A (1, Lblk) int32 row occupies Lblk/128 vregs at 1/8 sublane
    # utilization — the measured ~590ns/instr dispatch floor of r04
    # (MEMORY_r04.json ceiling analysis).  When the lane block splits
    # into 8 stripes of whole lane tiles (Lpb % 128 == 0), kernel state
    # is laid out [rows, 8, Lpb] instead of [rows, Lblk]: every row op
    # then runs on an (8, Lpb) array = Lblk/1024 fully-packed vregs, an
    # 8x denser vector layout for identical state.  Host-side HBM
    # planes keep their [rows, L] layout; the jit wrapper bitcast-
    # reshapes them to [rows, L/Lpb, Lpb] so that block b's lanes are
    # exactly stripes [8b, 8b+8) and each plane DMA stays one copy.
    # Lane l maps to (stripe l//Lpb, column l%Lpb): lane 0 stays at
    # (0, 0), so scal()/lane-0 optimistic decisions are unchanged.
    # Interpret mode (CPU tests) takes the remap whenever Lblk divides
    # by 8 so the suite exercises the 3-d path at small lane counts.
    if interpret:
        SUB = 8 if Lblk % 8 == 0 else 1
    else:
        SUB = 8 if Lblk % 1024 == 0 else 1
    Lpb = Lblk // SUB
    three_d = SUB > 1
    ROW = (SUB, Lpb)

    # inputs/outputs: frames + 12 base planes (+4 v128 planes: stack
    # e2/e3 and their rollback shadows, appended LAST so every existing
    # index — scheduler plane map, hostcall serving, checkpointing —
    # stays stable whether or not the module uses v128)
    N_IN = 13 + (4 if simd else 0)

    def kernel(*kargs_):
        (hid_r, a_r, b_r, c_r, ilo_r, ihi_r,
         fent_r, fnpar_r, fnloc_r, ftop_r, ftyp_r, brt_r, tbl_r,
         v128t_r, ctrl_r) = kargs_[:15]
        ins_ = kargs_[15:15 + N_IN]
        (frames_in, s_lo_in, s_hi_in, g_lo_in, g_hi_in, mem_in,
         trap_in, sh_slo_in, sh_shi_in, sh_glo_in, sh_ghi_in,
         sh_trap_in, sh_mem_in) = ins_[:13]
        outs_ = kargs_[15 + N_IN: 15 + N_IN + 1 + N_IN]
        (ctrl_out, frames_out, s_lo_out, s_hi_out, g_lo_out, g_hi_out,
         mem_out, trap_out, sh_slo, sh_shi, sh_glo, sh_ghi, sh_trap,
         sh_mem) = outs_[:14]
        if simd:
            se2_in, se3_in, sh_se2_in, sh_se3_in = ins_[13:17]
            se2_out, se3_out, sh_se2, sh_se3 = outs_[14:18]
        scr = kargs_[15 + N_IN + 1 + N_IN:]
        # sh_* are the rollback-snapshot shadow planes (HBM, aliased
        # in/out, only touched in optimistic mode; degenerate [1, L]
        # sh_mem when the memory plane is HBM-resident — the plane
        # itself then already holds last-commit state).
        it_ = iter(scr)
        slo, shi = next(it_), next(it_)
        se2s = next(it_) if simd else None
        se3s = next(it_) if simd else None
        glo, ghi = next(it_), next(it_)
        if mem_hbm:
            mwin0, mwin1 = next(it_), next(it_)
            memr = None
        else:
            memr = next(it_)
            mwin0 = mwin1 = None
        trapr, sems = next(it_), next(it_)
        if optimistic:
            canr, flag, snapf, snapc = (next(it_), next(it_),
                                        next(it_), next(it_))
        blk = pl.program_id(0)
        lo = blk * Lblk
        # lane-block slices of the (wrapper-reshaped) HBM planes: in
        # three_d mode a plane is [rows, L/Lpb, Lpb] and the block's
        # lanes are stripes [8b, 8b+8) (whole (8,128) tiles, so the
        # slice start is statically 8-aligned for Mosaic).
        if three_d:
            lo3 = pl.multiple_of(blk * SUB, SUB)

            def lslice(ref):
                return ref.at[:, pl.ds(lo3, SUB)]

            def lsliceR(ref, r0, n):
                return ref.at[pl.ds(r0, n), pl.ds(lo3, SUB)]
        else:
            def lslice(ref):
                return ref.at[:, pl.ds(lo, Lblk)]

            def lsliceR(ref, r0, n):
                return ref.at[pl.ds(r0, n), pl.ds(lo, Lblk)]

        # State planes live in HBM (pl.ANY); the working copy is VMEM
        # scratch, DMA'd in per lane block and DMA'd back at the end.
        # Keeping VMEM usage at 1x state size (no separate input/output
        # windows, no automatic double buffering) is what lets a
        # memory-free module run all lanes in a single block.  In
        # mem_hbm mode the memory plane is NOT staged: handlers DMA
        # CW-row windows of mem_out (aliased with mem_in) on demand.
        def dma(i, src, dst):
            return pltpu.make_async_copy(src, dst, sems.at[i])

        ins = [dma(0, lslice(s_lo_in), slo),
               dma(1, lslice(s_hi_in), shi),
               dma(2, lslice(g_lo_in), glo),
               dma(3, lslice(g_hi_in), ghi),
               dma(5, lslice(trap_in), trapr)]
        if not mem_hbm:
            ins.append(dma(4, lslice(mem_in), memr))
        if simd:
            # sems 6/7 are reused for the e2/e3 planes here and in the
            # snapshot paths: window DMAs (the other users of 6/7) are
            # never in flight across those batches
            ins += [dma(6, lslice(se2_in), se2s),
                    dma(7, lslice(se3_in), se3s)]
        for c in ins:
            c.start()
        for c in ins:
            c.wait()

        # frames: whole-array SMEM refs [nblk, 3, CD]; each grid program
        # copies and mutates only its own block's rows.
        def cp_frame(i, _):
            frames_out[blk, 0, i] = frames_in[blk, 0, i]
            frames_out[blk, 1, i] = frames_in[blk, 1, i]
            frames_out[blk, 2, i] = frames_in[blk, 2, i]
            return 0

        lax.fori_loop(0, CD, cp_frame, 0)

        chunk = ctrl_r[blk, _C_CHUNK]
        # per-block fuel (gas analog, block-uniform like all control state);
        # _FUEL_OFF disables.  The loop stops at the fuel boundary and the
        # post-loop check below converts exhaustion into CostLimitExceeded —
        # same per-instruction decrement semantics as the SIMT engine's
        # per-lane fuel plane.  Fused dispatches may overshoot the
        # boundary by their block length (< MAX_BLOCK_LEN instructions);
        # the kill itself is always delivered — only the exact stopping
        # instruction is block-granular, like the reference's
        # per-codeblock cost check (lib/executor/engine/engine.cpp).
        fuel_in = ctrl_r[blk, _C_FUEL]
        chunk_eff = jnp.minimum(chunk, fuel_in)
        snap_in = ctrl_r[blk, _C_SNAP]
        snap_dyn = jnp.where(snap_in > 0, snap_in, I32(snap_steps))

        def full(v):
            return jnp.full(ROW, v, I32)

        # row access: a logical row is always a 2-d (SUB, Lpb) array —
        # (1, Lblk) in legacy mode, (8, Lpb) fully tiled in three_d mode
        if three_d:
            def srow(ref, i):
                return ref[pl.ds(i, 1)][0]

            def wrow(ref, i, v):
                ref[pl.ds(i, 1)] = v[None]

            def srows(ref, r0, n):
                return ref[pl.ds(r0, n)]            # (n, SUB, Lpb)

            def wrows(ref, r0, n, v):
                ref[pl.ds(r0, n)] = v

            def riota(n):
                # row-index iota over an (n, SUB, Lpb) row stack
                return jax.lax.broadcasted_iota(I32, (n,) + ROW, 0)

            def rsum(x):
                # reduce an (n, SUB, Lpb) stack to one row
                return jnp.sum(x, axis=0)
        else:
            def srow(ref, i):
                return ref[pl.ds(i, 1), :]

            def wrow(ref, i, v):
                ref[pl.ds(i, 1), :] = v

            def srows(ref, r0, n):
                return ref[pl.ds(r0, n), :]

            def wrows(ref, r0, n, v):
                ref[pl.ds(r0, n), :] = v

            def riota(n):
                return jax.lax.broadcasted_iota(I32, (n, Lblk), 0)

            def rsum(x):
                return jnp.sum(x, axis=0, keepdims=True)

        def scal(vec):
            return vec[0, 0]

        def trap_where(cond_row, code_row):
            """Per-lane trap-code write: codes where cond, else keep."""
            wrow(trapr, 0, jnp.where(cond_row, code_row, srow(trapr, 0)))

        # 4-plane cell accessors (v128 cells span lo/hi/e2/e3; scalar
        # cells leave e2/e3 don't-care — copies move whatever is there)
        def srow4(i):
            if simd:
                return (srow(slo, i), srow(shi, i),
                        srow(se2s, i), srow(se3s, i))
            return (srow(slo, i), srow(shi, i))

        def wrow4(i, v):
            wrow(slo, i, v[0])
            wrow(shi, i, v[1])
            if simd:
                wrow(se2s, i, v[2])
                wrow(se3s, i, v[3])

        def allsame(vec, s):
            return jnp.all(vec == s)

        def shifted_store_triples(m_lo, m_hi, vl, vh, shB):
            """(mask, value) pairs for the up-to-3 words a (possibly
            unaligned) store touches, shifted into word lanes.  The ONE
            copy of this construction — scalar or vector masks/shifts
            both broadcast through."""
            sm0, sm1 = lo_ops.shl64(m_lo, m_hi, shB)
            sm2 = jnp.where(shB == 0, 0,
                            lo_ops.shr64_u(m_lo, m_hi, 64 - shB)[0])
            sv0, sv1 = lo_ops.shl64(vl, vh, shB)
            sv2 = jnp.where(shB == 0, 0,
                            lo_ops.shr64_u(vl, vh, 64 - shB)[0])
            return ((sm0, sv0), (sm1, sv1), (sm2, sv2))

        # carry: (steps, pc, sp, fp, ob, cd, pages, status) — mem_hbm
        # mode appends the window-cache fields (wb0, wd0, wb1, wd1, mru):
        # per-way window base row / dirty flag + the MRU way for LRU
        # victim choice.  optimistic mode appends ls (step count at the
        # last snapshot).  Block-uniform scalars like the rest of ctrl.
        _CARRY = ("steps", "pc", "sp", "fp", "ob", "cd", "pages", "status")
        if mem_hbm:
            _CARRY = _CARRY + ("wb0", "wd0", "wb1", "wd1", "mru")
        if optimistic:
            _CARRY = _CARRY + ("ls",)
        IDX = {n: i for i, n in enumerate(_CARRY)}
        NCARRY = len(_CARRY)

        def keep(c, **kw):
            d = dict(zip(_CARRY, c))
            d.update(kw)
            return tuple(d[k] for k in _CARRY)

        # ---- optimistic-convergence machinery -------------------------
        # (see _build_kernel docstring) canr is the divergence canary;
        # snapc/snapf/shadow planes hold the rollback point.
        if optimistic:
            SENT_W = I32(-(1 << 30))

            def agree_i32(vec):
                """lane-0 value decision; exact-mismatch canary."""
                s = scal(vec)
                wrow(canr, 0, srow(canr, 0) | (vec ^ s))
                return s

            def opt_addr_prolog(ea, off, nbytes, pages):
                """Lane-0 effective-address decision plus a fully
                SCALAR bounds check (address agreement is the
                optimistic assumption, so OOB agreement follows; lane
                mismatches go to the canary and roll back).  The ONE
                copy of this math, shared by the width-specialized
                unfused handlers and the fused inline loads/stores.
                Returns (ea0, oob0, word index u, bit shift shB)."""
                ea0 = agree_i32(ea)
                addr0 = ea0 - off
                mem_bytes = pages * I32(65536)
                end0 = ea0 + nbytes
                oob0 = u_lt(ea0, addr0) | u_lt(ea0, off) | \
                    u_lt(end0, ea0) | u_lt(mem_bytes, end0)
                u = jnp.clip(lax.shift_right_logical(ea0, 2), 0, W - 1)
                shB = (ea0 & 3) * 8
                return ea0, oob0, u, shB

            def agree_nz(vec):
                """lane-0 zeroness decision (branch conditions agree when
                their zeroness agrees, not their values)."""
                s = scal(vec)
                wrow(canr, 0, srow(canr, 0) | jnp.where(
                    (vec != 0) != (s != 0), I32(1), I32(0)))
                return s

            def do_snapshot(c):
                """Record the rollback point = the CURRENT (validated)
                state: planes -> shadow HBM, live frames + carry ->
                SMEM, canary reset."""
                cps = [dma(0, slo, lslice(sh_slo)),
                       dma(1, shi, lslice(sh_shi)),
                       dma(2, glo, lslice(sh_glo)),
                       dma(3, ghi, lslice(sh_ghi)),
                       dma(5, trapr, lslice(sh_trap))]
                if not mem_hbm and W > 1:
                    cps.append(dma(4, memr, lslice(sh_mem)))
                if simd:
                    cps += [dma(6, se2s, lslice(sh_se2)),
                            dma(7, se3s, lslice(sh_se3))]
                for cp_ in cps:
                    cp_.start()
                for cp_ in cps:
                    cp_.wait()
                cd_now = c[IDX["cd"]]

                def cpf(i, _):
                    for j in range(3):
                        snapf[j, i] = frames_out[blk, j, i]
                    return 0

                lax.fori_loop(0, jnp.clip(cd_now, 0, CD), cpf, 0)
                for k in range(NCARRY):
                    snapc[k] = c[k]
                wrow(canr, 0, full(0))

            def do_restore():
                """Rewind to the last snapshot (inverse of do_snapshot)."""
                cps = [dma(0, lslice(sh_slo), slo),
                       dma(1, lslice(sh_shi), shi),
                       dma(2, lslice(sh_glo), glo),
                       dma(3, lslice(sh_ghi), ghi),
                       dma(5, lslice(sh_trap), trapr)]
                if not mem_hbm and W > 1:
                    cps.append(dma(4, lslice(sh_mem), memr))
                if simd:
                    cps += [dma(6, lslice(sh_se2), se2s),
                            dma(7, lslice(sh_se3), se3s)]
                for cp_ in cps:
                    cp_.start()
                for cp_ in cps:
                    cp_.wait()
                cd_snap = snapc[IDX["cd"]]

                def cpf(i, _):
                    for j in range(3):
                        frames_out[blk, j, i] = snapf[j, i]
                    return 0

                lax.fori_loop(0, jnp.clip(cd_snap, 0, CD), cpf, 0)
                wrow(canr, 0, full(0))

            def rolled_carry():
                """Post-restore carry: snapshot scalars, ST_RECHECK, and
                (hbm) invalidated windows — their VMEM contents are
                stale relative to the restored plane."""
                vals = {n: snapc[i] for i, n in enumerate(_CARRY)}
                vals["status"] = I32(ST_RECHECK)
                if mem_hbm:
                    vals["wb0"] = SENT_W
                    vals["wd0"] = I32(0)
                    vals["wb1"] = SENT_W
                    vals["wd1"] = I32(0)
                return tuple(vals[n] for n in _CARRY)

            def _opt_bulk_exit(c):
                """Ops the optimistic kernel defers to the careful one
                (memory.fill/copy: per-lane ranged, reduction-heavy).
                Validate; roll back if a stale decision is pending; exit
                at this exact instruction with ST_RECHECK."""
                flag[0] = jnp.any(srow(canr, 0) != 0).astype(jnp.int32)
                dirty = flag[0] != 0

                @pl.when(dirty)
                def _():
                    do_restore()

                return lax.cond(
                    dirty, rolled_carry,
                    lambda: keep(c, status=I32(ST_RECHECK)))

        # ------------------- handlers ---------------------------------
        def h_nop(c):
            return keep(c, pc=c[1] + 1)

        def h_const(c):
            pc, sp = c[1], c[2]
            wrow(slo, sp, full(ilo_r[pc]))
            wrow(shi, sp, full(ihi_r[pc]))
            return keep(c, pc=pc + 1, sp=sp + 1)

        def h_local_get(c):
            pc, sp, fp = c[1], c[2], c[3]
            src = fp + a_r[pc]
            wrow4(sp, srow4(src))
            return keep(c, pc=pc + 1, sp=sp + 1)

        def h_local_set(c):
            pc, sp, fp = c[1], c[2], c[3]
            dst = fp + a_r[pc]
            wrow4(dst, srow4(sp - 1))
            return keep(c, pc=pc + 1, sp=sp - 1)

        def h_local_tee(c):
            pc, sp, fp = c[1], c[2], c[3]
            dst = fp + a_r[pc]
            wrow4(dst, srow4(sp - 1))
            return keep(c, pc=pc + 1)

        def h_global_get(c):
            pc, sp = c[1], c[2]
            g = a_r[pc]
            wrow(slo, sp, srow(glo, g))
            wrow(shi, sp, srow(ghi, g))
            return keep(c, pc=pc + 1, sp=sp + 1)

        def h_global_set(c):
            pc, sp = c[1], c[2]
            g = a_r[pc]
            wrow(glo, g, srow(slo, sp - 1))
            wrow(ghi, g, srow(shi, sp - 1))
            return keep(c, pc=pc + 1, sp=sp - 1)

        def h_drop(c):
            return keep(c, pc=c[1] + 1, sp=c[2] - 1)

        def h_select(c):
            pc, sp = c[1], c[2]
            cond = srow(slo, sp - 1)
            v1 = srow4(sp - 2)
            v2 = srow4(sp - 3)
            wrow4(sp - 3, tuple(jnp.where(cond == 0, a, b)
                                for a, b in zip(v1, v2)))
            return keep(c, pc=pc + 1, sp=sp - 2)

        def br_with(c, top1=None):
            pc, sp, ob = c[1], c[2], c[4]
            tgt, nkeep, pop_to = a_r[pc], b_r[pc], c_r[pc]
            tgt_sp = ob + pop_to
            kept = top1 if top1 is not None else srow4(sp - 1)

            @pl.when(nkeep == 1)
            def _():
                wrow4(tgt_sp, kept)

            return keep(c, pc=tgt, sp=tgt_sp + nkeep)

        def h_br(c):
            return br_with(c)

        # The *_with cores take optional vreg views of the top one/two
        # stack cells (top1 = value at sp-1, top2 = at sp-2, each a
        # (lo, hi) pair).  Fused blocks pass values still held in
        # vector registers, skipping the VMEM round trip between the
        # producing op and the branch (~100ns of store-load dependency
        # per block); the unfused h_* wrappers pass None and read rows.
        # `spill` marks vreg-passed inputs that are NOT yet in their
        # rows: careful-mode divergence bails write them back so the
        # scheduler's split machinery sees the exact pre-op stack.
        def _spill_tops(sp, top1, top2, spill):
            if not spill:
                return
            if top1 is not None:
                wrow4(sp - 1, top1)
            if top2 is not None:
                wrow4(sp - 2, top2)

        def brz_with(c, top1=None, spill=False):
            pc, sp = c[1], c[2]
            cond = top1[0] if top1 is not None else srow(slo, sp - 1)
            if optimistic:
                t0 = agree_nz(cond)
                new_pc = jnp.where(t0 == 0, a_r[pc], pc + 1)
                return keep(c, pc=new_pc, sp=sp - 1)
            t0 = scal(cond)
            agree = allsame(cond, t0)
            new_pc = jnp.where(t0 == 0, a_r[pc], pc + 1)

            def diverge():
                _spill_tops(sp, top1, None, spill)
                return keep(c, status=I32(ST_DIVERGED))

            return lax.cond(
                agree,
                lambda: keep(c, pc=new_pc, sp=sp - 1),
                diverge)

        def h_brz(c):
            return brz_with(c)

        def brnz_with(c, top1=None, top2=None, spill=False):
            pc, sp, ob = c[1], c[2], c[4]
            cond = top1[0] if top1 is not None else srow(slo, sp - 1)
            kept = top2 if top2 is not None else srow4(sp - 2)
            tgt, nkeep, pop_to = a_r[pc], b_r[pc], c_r[pc]
            tgt_sp = ob + pop_to
            if optimistic:
                t0 = agree_nz(cond)
                taken = t0 != 0

                @pl.when(taken & (nkeep == 1))
                def _():
                    wrow4(tgt_sp, kept)

                return lax.cond(
                    taken,
                    lambda: keep(c, pc=tgt, sp=tgt_sp + nkeep),
                    lambda: keep(c, pc=pc + 1, sp=sp - 1))
            t0 = scal(cond)
            agree = allsame(cond, t0)
            taken = t0 != 0

            @pl.when(agree & taken & (nkeep == 1))
            def _():
                wrow4(tgt_sp, kept)

            def diverge():
                _spill_tops(sp, top1, top2, spill)
                return keep(c, status=I32(ST_DIVERGED))

            return lax.cond(
                agree,
                lambda: lax.cond(
                    taken,
                    lambda: keep(c, pc=tgt, sp=tgt_sp + nkeep),
                    lambda: keep(c, pc=pc + 1, sp=sp - 1)),
                diverge)

        def h_brnz(c):
            return brnz_with(c)

        def br_table_with(c, top1=None, top2=None, spill=False):
            pc, sp, ob = c[1], c[2], c[4]
            idx = top1[0] if top1 is not None else srow(slo, sp - 1)
            kept = top2 if top2 is not None else srow4(sp - 2)
            i0 = agree_i32(idx) if optimistic else scal(idx)
            agree = True if optimistic else allsame(idx, i0)
            base, n = a_r[pc], b_r[pc]
            ii = jnp.where(u_lt(n, i0), n, i0)
            e = (base + ii) * 3
            tgt, nkeep, pop_to = brt_r[e], brt_r[e + 1], brt_r[e + 2]
            tgt_sp = ob + pop_to

            @pl.when(agree & (nkeep == 1))
            def _():
                wrow4(tgt_sp, kept)

            def diverge():
                _spill_tops(sp, top1, top2, spill)
                return keep(c, status=I32(ST_DIVERGED))

            return lax.cond(
                agree,
                lambda: keep(c, pc=tgt, sp=tgt_sp + nkeep),
                diverge)

        def h_br_table(c):
            return br_table_with(c)

        def return_with(c, top1=None):
            pc, sp, fp, cd = c[1], c[2], c[3], c[5]
            nres = b_r[pc]
            res = top1 if top1 is not None else srow4(sp - 1)

            @pl.when(nres == 1)
            def _():
                wrow4(fp, res)

            new_sp = fp + nres
            rd = jnp.clip(cd - 1, 0, CD - 1)
            return lax.cond(
                cd == 0,
                lambda: keep(c, sp=new_sp, status=I32(ST_DONE)),
                lambda: keep(c, pc=frames_out[blk, 0, rd], sp=new_sp,
                             fp=frames_out[blk, 1, rd],
                             ob=frames_out[blk, 2, rd], cd=cd - 1))

        def h_return(c):
            return return_with(c)

        def _do_call(c, callee, sp_eff):
            pc, fp, ob, cd = c[1], c[3], c[4], c[5]
            nargs = fnpar_r[callee]
            nloc = fnloc_r[callee]
            ftop = ftop_r[callee]
            fp_new = sp_eff - nargs
            ob_new = fp_new + nloc
            ovf = (cd >= CD - 1) | (fp_new + ftop > D)

            def trap_fn():
                code = jnp.where(cd >= CD - 1,
                                 I32(int(ErrCode.CallStackExhausted)),
                                 I32(int(ErrCode.StackOverflow)))
                wrow(trapr, 0, full(code))
                return keep(c, status=I32(ST_TRAPPED_BASE) + code)

            def go_fn():
                slot = jnp.clip(cd, 0, CD - 1)
                frames_out[blk, 0, slot] = pc + 1
                frames_out[blk, 1, slot] = fp
                frames_out[blk, 2, slot] = ob
                zrow = full(0)
                z4 = (zrow, zrow, zrow, zrow) if simd else (zrow, zrow)
                for k in range(max_local_zeros):
                    @pl.when(k < (nloc - nargs))
                    def _(k=k):
                        wrow4(fp_new + nargs + k, z4)
                return keep(c, pc=fent_r[callee], sp=ob_new, fp=fp_new,
                            ob=ob_new, cd=cd + 1)

            return lax.cond(ovf, trap_fn, go_fn)

        def h_call(c):
            return _do_call(c, a_r[c[1]], c[2])

        def calli_with(c, top1=None, spill=False):
            pc, sp = c[1], c[2]
            idx = top1[0] if top1 is not None else srow(slo, sp - 1)
            i0 = agree_i32(idx) if optimistic else scal(idx)
            agree = True if optimistic else allsame(idx, i0)
            tb_size, tb_base = b_r[pc], c_r[pc]
            oob = ~u_lt(i0, tb_size)  # unsigned; tb_size == 0 always oob
            h = tbl_r[jnp.clip(tb_base + jnp.clip(i0, 0,
                                                  jnp.maximum(tb_size - 1, 0)),
                               0, tsize - 1)]
            null = h == 0
            callee = jnp.clip(h - 1, 0, nf - 1)
            sig_bad = ftyp_r[callee] != a_r[pc]

            def bad():
                code = jnp.where(
                    oob, I32(int(ErrCode.UndefinedElement)),
                    jnp.where(null, I32(int(ErrCode.UninitializedElement)),
                              I32(int(ErrCode.IndirectCallTypeMismatch))))
                wrow(trapr, 0, full(code))
                return keep(c, status=I32(ST_TRAPPED_BASE) + code)

            def diverge():
                _spill_tops(sp, top1, None, spill)
                return keep(c, status=I32(ST_DIVERGED))

            return lax.cond(
                agree,
                lambda: lax.cond(
                    oob | null | sig_bad, bad,
                    lambda: _do_call(keep(c, sp=sp - 1), callee, sp - 1)),
                diverge)

        def h_call_indirect(c):
            return calli_with(c)

        def h_memsize(c):
            pc, sp, pages = c[1], c[2], c[6]
            wrow(slo, sp, full(pages))
            wrow(shi, sp, full(0))
            return keep(c, pc=pc + 1, sp=sp + 1)

        def h_memgrow(c):
            pc, sp, pages = c[1], c[2], c[6]
            delta = srow(slo, sp - 1)
            d0 = agree_i32(delta) if optimistic else scal(delta)
            agree = True if optimistic else allsame(delta, d0)
            legal = (d0 >= 0) & ((pages + d0) <= mem_pages_hard) & \
                ((pages + d0) >= pages)
            # legal but beyond the watermark plane: stop un-advanced so the
            # host re-executes on a bigger-plane engine (ST_REGROW)
            fits = legal & ((pages + d0) <= mem_pages_cap)
            res = jnp.where(legal, pages, I32(-1))
            settled = fits | ~legal

            @pl.when(agree & settled)
            def _():
                wrow(slo, sp - 1, full(res))
                wrow(shi, sp - 1, full(0))

            return lax.cond(
                agree,
                lambda: lax.cond(
                    settled,
                    lambda: keep(c, pc=pc + 1,
                                 pages=jnp.where(fits, pages + d0, pages)),
                    lambda: keep(c, status=I32(ST_REGROW))),
                lambda: keep(c, status=I32(ST_DIVERGED)))

        def h_trap(c):
            code = a_r[c[1]]
            wrow(trapr, 0, full(code))
            return keep(c, status=I32(ST_TRAPPED_BASE) + code)

        def h_memfill(c):
            if optimistic:
                return _opt_bulk_exit(c)
            pc, sp, pages = c[1], c[2], c[6]
            n = srow(slo, sp - 1)
            val = srow(slo, sp - 2)
            dst = srow(slo, sp - 3)
            mem_bytes = pages * I32(65536)
            end = dst + n
            oob = u_lt(end, dst) | u_lt(full(mem_bytes), end)
            go = (~oob) & (n != 0)
            fill_word = (val & 0xFF) * I32(0x01010101)
            # scan only the touched row window (a small fill must not pay
            # a whole-plane pass)
            dst_ok = jnp.where(go, dst, I32(0x7FFFFFFF))
            end_ok = jnp.where(go, end, I32(0))
            c_lo = jnp.clip(
                lax.div(lax.shift_right_logical(jnp.min(dst_ok), 2),
                        I32(GR)), 0, GATHER_CHUNKS)
            c_hi = jnp.clip(
                lax.div(lax.shift_right_logical(jnp.max(end_ok) + 3, 2)
                        + I32(GR - 1), I32(GR)), 0, GATHER_CHUNKS)

            def chunk(i, _):
                base = i * GR
                rows = srows(memr, base, GR)
                wi = base + riota(GR)
                byte0 = wi * 4
                mask = jnp.zeros_like(rows)
                for bpos in range(4):
                    ba = byte0 + bpos
                    inr = (~u_lt(ba, dst)) & u_lt(ba, end)
                    mask = mask | jnp.where(
                        inr, jnp.int32(lo_ops.BYTE_MASKS[bpos]), 0)
                write = (mask != 0) & go
                wrows(memr, base, GR, jnp.where(
                    write, (rows & ~mask) | (fill_word & mask), rows))
                return 0

            lax.fori_loop(c_lo, c_hi, chunk, 0)
            any_oob = jnp.any(oob)

            @pl.when(any_oob)
            def _():
                trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

            return lax.cond(
                any_oob,
                lambda: keep(c, pc=pc + 1, sp=sp - 3,
                             status=I32(ST_DIVERGED)),
                lambda: keep(c, pc=pc + 1, sp=sp - 3))

        def h_memcopy(c):
            if optimistic:
                return _opt_bulk_exit(c)
            # In-kernel memmove when every lane agrees on (src - dst): the
            # byte shift between source and destination is then a scalar,
            # so each destination row is two shifted source rows under the
            # same per-lane byte masks h_memfill uses.  Row order follows
            # the copy direction (backward when dst > src) for overlap
            # correctness — the same memmove discipline as the reference's
            # std::memmove in runDataCopy.  Per-lane divergent deltas (one
            # lane copying up, another down) hand off un-advanced.
            pc, sp, pages = c[1], c[2], c[6]
            n = srow(slo, sp - 1)
            src = srow(slo, sp - 2)
            dst = srow(slo, sp - 3)
            mem_bytes = pages * I32(65536)
            send = src + n
            dend = dst + n
            oob = u_lt(send, src) | u_lt(full(mem_bytes), send) | \
                u_lt(dend, dst) | u_lt(full(mem_bytes), dend)
            delta = src - dst
            live = (~oob) & (n != 0)
            # lanes with nothing to copy don't constrain the shift
            d_eff = jnp.where(live, delta, I32(0x7FFFFFFF))
            d0 = jnp.min(d_eff)
            agree = jnp.all(jnp.where(live, delta, d0) == d0)
            any_live = jnp.any(live)
            d0 = jnp.where(any_live, d0, I32(0))

            def go():
                sm = d0 & 3
                qv = lax.shift_right_arithmetic(d0 - sm, 2)
                shB = sm * 8
                inv = (32 - shB) & 31
                hi_or = jnp.where(shB == 0, 0, -1)
                dst_ok = jnp.where(live, dst, I32(0x7FFFFFFF))
                dend_ok = jnp.where(live, dend, I32(0))
                row_lo = lax.shift_right_logical(jnp.min(dst_ok), 2)
                row_hi = lax.shift_right_logical(jnp.max(dend_ok) + 3, 2)
                row_lo = jnp.minimum(row_lo, I32(W))
                row_hi = jnp.minimum(row_hi, I32(W))
                nrows = jnp.maximum(row_hi - row_lo, 0)
                fwd = d0 >= 0

                def body(i, _):
                    r = jnp.where(fwd, row_lo + i, row_hi - 1 - i)
                    m0 = srow(memr, jnp.clip(r + qv, 0, W - 1))
                    m1 = srow(memr, jnp.clip(r + qv + 1, 0, W - 1))
                    val = lax.shift_right_logical(m0, shB) | \
                        (lax.shift_left(m1, inv) & hi_or)
                    mask = full(0)
                    for bpos in range(4):
                        ba = full(r * 4 + bpos)
                        inr = (~u_lt(ba, dst)) & u_lt(ba, dend)
                        mask = mask | jnp.where(
                            inr & live, jnp.int32(lo_ops.BYTE_MASKS[bpos]),
                            0)
                    old = srow(memr, jnp.clip(r, 0, W - 1))
                    wrow(memr, jnp.clip(r, 0, W - 1),
                         jnp.where(mask != 0, (old & ~mask) | (val & mask),
                                   old))
                    return 0

                lax.fori_loop(0, nrows, body, 0)
                any_oob = jnp.any(oob)

                @pl.when(any_oob)
                def _():
                    trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

                return lax.cond(
                    any_oob,
                    lambda: keep(c, pc=pc + 1, sp=sp - 3,
                                 status=I32(ST_DIVERGED)),
                    lambda: keep(c, pc=pc + 1, sp=sp - 3))

            return lax.cond(agree, go,
                            lambda: keep(c, status=I32(ST_DIVERGED)))

        def h_hostcall(c):
            # park the block; the host serves every lane then re-arms at
            # pc+1 (the stub RETURN) with sp = opbase + nresults
            return keep(c, status=I32(ST_HOSTCALL))

        # ---- memory access ------------------------------------------
        # NOTE predication discipline: `lax.cond` whose branches return
        # vectors or mutate refs is DISCHARGED by pallas into
        # execute-both-and-select — a "rare" divergent-gather branch
        # would then run its whole-memory scan on every access.  All
        # vector/ref work below therefore sits under `pl.when` (real
        # Mosaic predicated blocks); only the scalar carry goes through
        # lax.cond.

        def _gather_word(widx, row_lo, row_hi):
            """Per-lane word gather from [W, Lblk] by chunked
            compare-reduce over the touched row window only."""
            c_lo = jnp.clip(lax.div(row_lo, I32(GR)), 0, GATHER_CHUNKS)
            c_hi = jnp.clip(lax.div(row_hi + I32(GR - 1), I32(GR)),
                            0, GATHER_CHUNKS)

            def chunk(i, acc):
                base = i * GR
                rows = srows(memr, base, GR)
                wi = base + riota(GR)
                return acc + rsum(jnp.where(wi == widx, rows, 0))

            return lax.fori_loop(c_lo, c_hi, chunk, full(0))

        def _load_finish(c, mw0, mw1, mw2, shB, oob, any_oob):
            pc, sp = c[1], c[2]
            nbytes, flags = b_r[pc], c_r[pc]
            inv = (32 - shB) & 31
            hi_or = jnp.where(shB == 0, 0, -1)
            raw_lo = lax.shift_right_logical(mw0, shB) | \
                (lax.shift_left(mw1, inv) & hi_or)
            raw_hi = lax.shift_right_logical(mw1, shB) | \
                (lax.shift_left(mw2, inv) & hi_or)
            signed = (flags & 1) != 0
            is64 = (flags & 2) != 0
            b1 = nbytes == 1
            b2_ = nbytes == 2
            lraw = jnp.where(b1, raw_lo & 0xFF,
                             jnp.where(b2_, raw_lo & 0xFFFF, raw_lo))
            lsext = jnp.where(
                b1,
                lax.shift_right_arithmetic(lax.shift_left(raw_lo, 24), 24),
                jnp.where(
                    b2_,
                    lax.shift_right_arithmetic(lax.shift_left(raw_lo, 16),
                                               16),
                    raw_lo))
            ll = jnp.where(signed, lsext, lraw)
            lh = jnp.where(
                is64,
                jnp.where(nbytes == 8, raw_hi,
                          jnp.where(signed,
                                    lax.shift_right_arithmetic(ll, 31),
                                    full(0))),
                full(0))
            wrow(slo, sp - 1, ll)
            wrow(shi, sp - 1, lh)

            @pl.when(any_oob)
            def _():
                trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

        def h_load(c):
            pc, sp, pages = c[1], c[2], c[6]
            off, nbytes = a_r[pc], b_r[pc]
            addr = srow(slo, sp - 1)
            ea = addr + off
            carry_ = u_lt(ea, addr) | u_lt(ea, full(off))
            mem_bytes = pages * I32(65536)
            end = ea + nbytes
            oob = carry_ | u_lt(end, ea) | u_lt(full(mem_bytes), end)
            if optimistic:
                # lane-0 address decision; the canary covers widx/shB/oob
                # agreement at once (all derive from ea and scalars)
                ea0 = agree_i32(ea)
                oob0 = jnp.where(oob, I32(1), I32(0))[0, 0] != 0
                u = jnp.clip(lax.shift_right_logical(ea0, 2), 0, W - 1)
                shB0 = (ea0 & 3) * 8
                _load_finish(c, srow(memr, u),
                             srow(memr, jnp.minimum(u + 1, W - 1)),
                             srow(memr, jnp.minimum(u + 2, W - 1)),
                             shB0, oob, oob0)
                return lax.cond(
                    oob0,
                    lambda: keep(c, pc=pc + 1, status=I32(ST_DIVERGED)),
                    lambda: keep(c, pc=pc + 1))
            widx = jnp.clip(lax.shift_right_logical(ea, 2), 0, W - 1)
            shB = (ea & 3) * 8
            u0 = scal(widx)
            uni = allsame(widx, u0) & allsame(shB, scal(shB))
            commit = jnp.bool_(True) if gatherable else uni
            any_oob = jnp.any(oob)

            @pl.when(uni)
            def _():
                u = jnp.clip(u0, 0, W - 1)
                _load_finish(c, srow(memr, u),
                             srow(memr, jnp.clip(u + 1, 0, W - 1)),
                             srow(memr, jnp.clip(u + 2, 0, W - 1)),
                             shB, oob, any_oob)

            if gatherable:
                @pl.when(~uni)
                def _():
                    r_lo = jnp.min(widx)
                    r_hi = jnp.max(widx) + 3
                    w1 = jnp.clip(widx + 1, 0, W - 1)
                    w2 = jnp.clip(widx + 2, 0, W - 1)
                    _load_finish(c, _gather_word(widx, r_lo, r_hi),
                                 _gather_word(w1, r_lo, r_hi),
                                 _gather_word(w2, r_lo, r_hi),
                                 shB, oob, any_oob)

            return lax.cond(
                commit,
                lambda: lax.cond(
                    any_oob,
                    lambda: keep(c, pc=pc + 1, status=I32(ST_DIVERGED)),
                    lambda: keep(c, pc=pc + 1)),
                lambda: keep(c, status=I32(ST_DIVERGED)))

        def h_store(c):
            pc, sp, pages = c[1], c[2], c[6]
            off, nbytes = a_r[pc], b_r[pc]
            vl, vh = srow(slo, sp - 1), srow(shi, sp - 1)
            addr = srow(slo, sp - 2)
            ea = addr + off
            carry_ = u_lt(ea, addr) | u_lt(ea, full(off))
            mem_bytes = pages * I32(65536)
            end = ea + nbytes
            oob = carry_ | u_lt(end, ea) | u_lt(full(mem_bytes), end)
            ok = ~oob
            if optimistic:
                ea0 = agree_i32(ea)
                oob0 = jnp.where(oob, I32(1), I32(0))[0, 0] != 0
                u = jnp.clip(lax.shift_right_logical(ea0, 2), 0, W - 1)
                shB0 = (ea0 & 3) * 8
                b1 = nbytes == 1
                b2_ = nbytes == 2
                # scalar byte masks (address is block-uniform by
                # assumption); value planes stay per-lane vectors
                m_lo = jnp.where(b1, I32(0xFF),
                                 jnp.where(b2_, I32(0xFFFF), I32(-1)))
                m_hi = jnp.where(nbytes == 8, I32(-1), I32(0))
                for k, (m, v) in enumerate(
                        shifted_store_triples(m_lo, m_hi, vl, vh, shB0)):
                    w = jnp.minimum(u + k, W - 1)

                    @pl.when(m != 0)
                    def _(m=m, v=v, w=w):
                        cur = srow(memr, w)
                        wrow(memr, w,
                             jnp.where(ok, (cur & ~m) | (v & m), cur))

                @pl.when(oob0)
                def _():
                    trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

                return lax.cond(
                    oob0,
                    lambda: keep(c, pc=pc + 1, sp=sp - 2,
                                 status=I32(ST_DIVERGED)),
                    lambda: keep(c, pc=pc + 1, sp=sp - 2))
            widx = jnp.clip(lax.shift_right_logical(ea, 2), 0, W - 1)
            shB = (ea & 3) * 8
            b1 = nbytes == 1
            b2_ = nbytes == 2
            full_lo = jnp.where(b1, 0xFF, jnp.where(b2_, 0xFFFF, I32(-1)))
            full_hi = jnp.where(nbytes == 8, I32(-1), 0)
            full_lo = jnp.broadcast_to(full_lo, ROW)
            full_hi = jnp.broadcast_to(full_hi, ROW)
            ((sm0, sv0), (sm1, sv1), (sm2, sv2)) = shifted_store_triples(
                full_lo, full_hi, vl, vh, shB)
            u0 = scal(widx)
            uni = allsame(widx, u0) & allsame(shB, scal(shB))
            commit = jnp.bool_(True) if gatherable else uni
            any_oob = jnp.any(oob)

            @pl.when(uni)
            def _():
                for k, (m, v) in enumerate(((sm0, sv0), (sm1, sv1),
                                            (sm2, sv2))):
                    w = jnp.clip(u0 + k, 0, W - 1)

                    @pl.when(jnp.any(m != 0))
                    def _(m=m, v=v, w=w):
                        cur = srow(memr, w)
                        wrow(memr, w,
                             jnp.where(ok & (m != 0), (cur & ~m) | (v & m),
                                       cur))

            if gatherable:
                @pl.when(~uni)
                def _():
                    c_lo = jnp.clip(lax.div(jnp.min(widx), I32(GR)),
                                    0, GATHER_CHUNKS)
                    c_hi = jnp.clip(
                        lax.div(jnp.max(widx) + I32(2 + GR), I32(GR)),
                        0, GATHER_CHUNKS)
                    for k, (m, v) in enumerate(((sm0, sv0), (sm1, sv1),
                                                (sm2, sv2))):
                        wk = jnp.clip(widx + k, 0, W - 1)

                        def chunk(i, _, m=m, v=v, wk=wk):
                            base = i * GR
                            rows = srows(memr, base, GR)
                            wi = base + riota(GR)
                            hit = (wi == wk) & (ok & (m != 0))
                            wrows(memr, base, GR, jnp.where(
                                hit, (rows & ~m) | (v & m), rows))
                            return 0

                        lax.fori_loop(c_lo, c_hi, chunk, 0)

            @pl.when(commit & any_oob)
            def _():
                trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

            return lax.cond(
                commit,
                lambda: lax.cond(
                    any_oob,
                    lambda: keep(c, pc=pc + 1, sp=sp - 2,
                                 status=I32(ST_DIVERGED)),
                    lambda: keep(c, pc=pc + 1, sp=sp - 2)),
                lambda: keep(c, status=I32(ST_DIVERGED)))

        # ---- mem_hbm mode: window-cached memory handlers --------------
        # The memory plane stays HBM-resident; h_load/h_store/h_memfill/
        # h_memcopy are shadowed below with window-cache versions.  The
        # invariant maintained by _win_select is that at most ONE way
        # holds any given plane row (a fetch overlapping the other way
        # writes that way back and invalidates it first), so hit
        # priority and flush order can never replay stale rows.
        if mem_hbm:
            SENT = I32(-(1 << 30))  # "window invalid" base sentinel

            def a8(v):
                # every HBM row offset here is 8-aligned by construction
                # (window bases are align8'd; W, CW, GR are multiples of
                # 8) but Mosaic needs the divisibility stated to slice
                # the (8,128)-tiled HBM memref at a dynamic row
                return pl.multiple_of(v, 8)

            def _wb_way0(wb):
                cp = dma(6, mwin0, lsliceR(mem_out, a8(jnp.clip(wb, 0, W - CW)), CW))
                cp.start()
                cp.wait()

            def _wb_way1(wb):
                cp = dma(7, mwin1, lsliceR(mem_out, a8(jnp.clip(wb, 0, W - CW)), CW))
                cp.start()
                cp.wait()

            def _win_select(wfs, rlo, rhi, en):
                """Make rows [rlo, rhi] resident in one way; returns
                (way, wfs').  All DMAs are predicated on `en`; callers
                must have checked (rhi - align8(rlo)) < CW."""
                wb0, wd0, wb1, wd1, mru = wfs
                hit0 = (rlo >= wb0) & (rhi < wb0 + CW)
                hit1 = (rlo >= wb1) & (rhi < wb1 + CW)
                nb = jnp.clip(rlo - lax.rem(rlo, 8), 0, W - CW)
                miss = en & ~(hit0 | hit1)
                vic1 = mru == 0
                repl0 = miss & ~vic1
                repl1 = miss & vic1
                # the single-resident-copy invariant: evict the OTHER way
                # when the incoming window overlaps it
                ov0 = repl1 & (wb0 < nb + CW) & (nb < wb0 + CW)
                ov1 = repl0 & (wb1 < nb + CW) & (nb < wb1 + CW)

                @pl.when(ov0 & (wd0 != 0))
                def _():
                    _wb_way0(wb0)

                @pl.when(ov1 & (wd1 != 0))
                def _():
                    _wb_way1(wb1)

                @pl.when(repl0 & (wd0 != 0))
                def _():
                    _wb_way0(wb0)

                @pl.when(repl0)
                def _():
                    cp = dma(6, lsliceR(mem_out, a8(nb), CW), mwin0)
                    cp.start()
                    cp.wait()

                @pl.when(repl1 & (wd1 != 0))
                def _():
                    _wb_way1(wb1)

                @pl.when(repl1)
                def _():
                    cp = dma(7, lsliceR(mem_out, a8(nb), CW), mwin1)
                    cp.start()
                    cp.wait()

                wb0n = jnp.where(repl0, nb, jnp.where(ov0, SENT, wb0))
                wd0n = jnp.where(repl0 | ov0, I32(0), wd0)
                wb1n = jnp.where(repl1, nb, jnp.where(ov1, SENT, wb1))
                wd1n = jnp.where(repl1 | ov1, I32(0), wd1)
                way = jnp.where(hit0, I32(0),
                                jnp.where(hit1, I32(1),
                                          jnp.where(vic1, I32(1), I32(0))))
                mrun = jnp.where(en, way, mru)
                return way, (wb0n, wd0n, wb1n, wd1n, mrun)

            def _win_flush(wfs):
                """Write back both dirty ways and invalidate (used before
                chunk-streaming ops that bypass the cache)."""
                wb0, wd0, wb1, wd1, _ = wfs

                @pl.when(wd0 != 0)
                def _():
                    _wb_way0(wb0)

                @pl.when(wd1 != 0)
                def _():
                    _wb_way1(wb1)

                return (SENT, I32(0), SENT, I32(0), I32(0))

            def win_read_row(way, wfs, r):
                i0 = jnp.clip(r - wfs[0], 0, CW - 1)
                i1 = jnp.clip(r - wfs[2], 0, CW - 1)
                return jnp.where(way == 0, srow(mwin0, i0), srow(mwin1, i1))

            def win_write_row(way, wfs, r, v):
                @pl.when(way == 0)
                def _():
                    wrow(mwin0, jnp.clip(r - wfs[0], 0, CW - 1), v)

                @pl.when(way == 1)
                def _():
                    wrow(mwin1, jnp.clip(r - wfs[2], 0, CW - 1), v)

            def _win_gather(way, wfs, wk):
                """Per-lane word gather from the selected resident way."""
                base = jnp.where(way == 0, wfs[0], wfs[2])
                rel = wk - base
                wi = riota(CW)
                rows = jnp.where(way == 0, srows(mwin0, 0, CW),
                                 srows(mwin1, 0, CW))
                return rsum(jnp.where(wi == rel, rows, 0))

            def _wfs_of(c):
                return (c[8], c[9], c[10], c[11], c[12])

            def _keep_win(c, wfs, **kw):
                return keep(c, wb0=wfs[0], wd0=wfs[1], wb1=wfs[2],
                            wd1=wfs[3], mru=wfs[4], **kw)

            def _opt_window(c, u, rhi):
                """Optimistic scalar window select: resolve [u, rhi] to
                a resident way with all decisions scalar.  A dirty
                eviction is a commit point — validate the canary first,
                roll back on a pending stale decision, snapshot
                otherwise.  Returns (dirty, way, wfs') where wfs' has
                the new window fields with mru=way; callers must gate
                every ref mutation on ~dirty and return rolled_carry()
                when dirty.

                INVARIANT SYNC: the hit predicates, victim choice,
                overlap eviction (single-resident-copy rule) and
                wb/wd/mru update formulas here MUST match _win_select
                above — the careful kernel runs that one against the
                same window state this one leaves behind."""
                wb0, wd0, wb1, wd1, mru = _wfs_of(c)
                hit0 = (u >= wb0) & (rhi < wb0 + CW)
                hit1 = (u >= wb1) & (rhi < wb1 + CW)
                miss = ~(hit0 | hit1)
                vic1 = mru == 0
                nb = jnp.clip(u - lax.rem(u, 8), 0, W - CW)
                ov0 = miss & vic1 & (wb0 < nb + CW) & (nb < wb0 + CW)
                ov1 = miss & ~vic1 & (wb1 < nb + CW) & (nb < wb1 + CW)
                repl0 = miss & ~vic1
                repl1 = miss & vic1
                needs_wb = (repl0 & (wd0 != 0)) | (repl1 & (wd1 != 0)) | \
                    (ov0 & (wd0 != 0)) | (ov1 & (wd1 != 0))

                @pl.when(needs_wb)
                def _():
                    flag[0] = jnp.any(srow(canr, 0) != 0).astype(jnp.int32)

                dirty = needs_wb & (flag[0] != 0)
                okp = ~dirty

                @pl.when(dirty)
                def _():
                    do_restore()

                # publish BOTH dirty ways before the snapshot so the HBM
                # plane IS the snapshot's memory state — otherwise a
                # later rollback would discard the non-victim way's
                # validated stores (same discipline as the periodic
                # commit in body())
                @pl.when(needs_wb & okp & (wd0 != 0))
                def _():
                    _wb_way0(wb0)

                @pl.when(needs_wb & okp & (wd1 != 0))
                def _():
                    _wb_way1(wb1)

                @pl.when(needs_wb & okp)
                def _():
                    do_snapshot(c)

                @pl.when(okp & repl0)
                def _():
                    cp = dma(6, lsliceR(mem_out, a8(nb), CW), mwin0)
                    cp.start()
                    cp.wait()

                @pl.when(okp & repl1)
                def _():
                    cp = dma(7, lsliceR(mem_out, a8(nb), CW), mwin1)
                    cp.start()
                    cp.wait()

                flushed = needs_wb & okp
                wb0n = jnp.where(repl0, nb, jnp.where(ov0, SENT, wb0))
                wd0n = jnp.where(flushed | repl0 | ov0, I32(0), wd0)
                wb1n = jnp.where(repl1, nb, jnp.where(ov1, SENT, wb1))
                wd1n = jnp.where(flushed | repl1 | ov1, I32(0), wd1)
                way = jnp.where(hit0, I32(0),
                                jnp.where(hit1, I32(1),
                                          jnp.where(vic1, I32(1), I32(0))))
                return dirty, flushed, way, \
                    (wb0n, wd0n, wb1n, wd1n, way)

            def _opt_ls_prolog(c, addr_row, nb_extra):
                """Shared optimistic load/store address computation."""
                pc, pages = c[1], c[6]
                off, nbytes = a_r[pc], b_r[pc]
                ea = addr_row + off
                carry_ = u_lt(ea, addr_row) | u_lt(ea, full(off))
                mem_bytes = pages * I32(65536)
                end = ea + nbytes
                oob = carry_ | u_lt(end, ea) | u_lt(full(mem_bytes), end)
                ea0 = agree_i32(ea)
                oob0 = jnp.where(oob, I32(1), I32(0))[0, 0] != 0
                u = jnp.clip(lax.shift_right_logical(ea0, 2), 0, W - 1)
                shB0 = (ea0 & 3) * 8
                rhi = jnp.minimum(u + nb_extra, W - 1)
                return oob, oob0, u, shB0, rhi, nbytes

            def _opt_ls_scalar(c, addr_row, nbytes, want_rows):
                """Reduction-free load/store prolog (opt_addr_prolog
                plus the window row bound the hbm handlers need)."""
                pc, pages = c[1], c[6]
                off = a_r[pc]
                ea = addr_row + off
                _ea0, oob0, u, shB0 = opt_addr_prolog(
                    ea, off, nbytes, pages)
                rhi = jnp.minimum(u + want_rows, W - 1)
                return ea, oob0, u, shB0, rhi

            def _opt_trap_oob(c, ea, nbytes, oob0):
                """Per-lane OOB trap plane write, only materialized on
                the (rare) lane-0-oob path."""
                @pl.when(oob0)
                def _():
                    pages = c[6]
                    addr = ea - a_r[c[1]]
                    carry_ = u_lt(ea, addr) | u_lt(ea, full(a_r[c[1]]))
                    end = ea + nbytes
                    oob = carry_ | u_lt(end, ea) | \
                        u_lt(full(pages * I32(65536)), end)
                    trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

            def _mk_load_wd(is64):
                nbytes = 8 if is64 else 4
                want = 2 if is64 else 1

                def h(c):
                    pc, sp = c[1], c[2]
                    ea, oob0, u, shB0, rhi = _opt_ls_scalar(
                        c, srow(slo, sp - 1), nbytes, want)
                    dirty, snapped, way, wfs2 = _opt_window(c, u, rhi)
                    inv = (32 - shB0) & 31
                    hi_or = jnp.where(shB0 == 0, 0, -1)

                    @pl.when(~dirty)
                    def _():
                        m0 = win_read_row(way, wfs2, u)
                        m1 = win_read_row(way, wfs2,
                                          jnp.minimum(u + 1, W - 1))
                        ll = lax.shift_right_logical(m0, shB0) | \
                            (lax.shift_left(m1, inv) & hi_or)
                        wrow(slo, sp - 1, ll)
                        if is64:
                            m2 = win_read_row(way, wfs2,
                                              jnp.minimum(u + 2, W - 1))
                            lh = lax.shift_right_logical(m1, shB0) | \
                                (lax.shift_left(m2, inv) & hi_or)
                            wrow(shi, sp - 1, lh)
                        else:
                            wrow(shi, sp - 1, full(0))
                        _opt_trap_oob(c, ea, nbytes, oob0)

                    c2 = _keep_win(
                        c, wfs2,
                        ls=jnp.where(snapped, c[0], c[IDX["ls"]]))
                    return lax.cond(
                        dirty, rolled_carry,
                        lambda: lax.cond(
                            oob0,
                            lambda: keep(c2, pc=pc + 1,
                                         status=I32(ST_DIVERGED)),
                            lambda: keep(c2, pc=pc + 1)))
                return h

            def _mk_store_wd(is64):
                nbytes = 8 if is64 else 4
                want = 2 if is64 else 1

                def h(c):
                    pc, sp = c[1], c[2]
                    vl, vh = srow(slo, sp - 1), srow(shi, sp - 1)
                    ea, oob0, u, shB0, rhi = _opt_ls_scalar(
                        c, srow(slo, sp - 2), nbytes, want)
                    dirty, snapped, way, wfs2 = _opt_window(c, u, rhi)
                    m_lo = I32(-1)
                    m_hi = I32(-1) if is64 else I32(0)
                    triples = shifted_store_triples(m_lo, m_hi, vl, vh,
                                                    shB0)

                    @pl.when(~dirty & ~oob0)
                    def _():
                        # common path: no lane traps assumed — write
                        # unmasked (a lane disagreeing on the address is
                        # already canary-marked and will roll back)
                        for k, (m, v) in enumerate(triples):
                            w = jnp.minimum(u + k, W - 1)

                            @pl.when(m != 0)
                            def _(m=m, v=v, w=w):
                                cur = win_read_row(way, wfs2, w)
                                win_write_row(way, wfs2, w,
                                              (cur & ~m) | (v & m))

                    _opt_trap_oob(c, ea, nbytes, oob0 & ~dirty)
                    nwd0 = jnp.where(way == 0, I32(1), wfs2[1])
                    nwd1 = jnp.where(way == 1, I32(1), wfs2[3])
                    c2 = keep(c, wb0=wfs2[0], wd0=nwd0, wb1=wfs2[2],
                              wd1=nwd1, mru=wfs2[4],
                              ls=jnp.where(snapped, c[0], c[IDX["ls"]]))
                    return lax.cond(
                        dirty, rolled_carry,
                        lambda: lax.cond(
                            oob0,
                            lambda: keep(c2, pc=pc + 1, sp=sp - 2,
                                         status=I32(ST_DIVERGED)),
                            lambda: keep(c2, pc=pc + 1, sp=sp - 2)))
                return h

            h_load_w = _mk_load_wd(False)
            h_load_d = _mk_load_wd(True)
            h_store_w = _mk_store_wd(False)
            h_store_d = _mk_store_wd(True)

            def h_load(c):
                if optimistic:
                    pc, sp = c[1], c[2]
                    oob, oob0, u, shB0, rhi, _nb = _opt_ls_prolog(
                        c, srow(slo, sp - 1), 2)
                    dirty, snapped, way, wfs2 = _opt_window(c, u, rhi)

                    @pl.when(~dirty)
                    def _():
                        _load_finish(
                            c, win_read_row(way, wfs2, u),
                            win_read_row(way, wfs2,
                                         jnp.minimum(u + 1, W - 1)),
                            win_read_row(way, wfs2,
                                         jnp.minimum(u + 2, W - 1)),
                            shB0, oob, oob0)

                    c2 = _keep_win(
                        c, wfs2,
                        ls=jnp.where(snapped, c[0], c[IDX["ls"]]))
                    return lax.cond(
                        dirty, rolled_carry,
                        lambda: lax.cond(
                            oob0,
                            lambda: keep(c2, pc=pc + 1,
                                         status=I32(ST_DIVERGED)),
                            lambda: keep(c2, pc=pc + 1)))
                pc, sp, pages = c[1], c[2], c[6]
                off, nbytes = a_r[pc], b_r[pc]
                addr = srow(slo, sp - 1)
                ea = addr + off
                carry_ = u_lt(ea, addr) | u_lt(ea, full(off))
                mem_bytes = pages * I32(65536)
                end = ea + nbytes
                oob = carry_ | u_lt(end, ea) | u_lt(full(mem_bytes), end)
                widx = jnp.clip(lax.shift_right_logical(ea, 2), 0, W - 1)
                shB = (ea & 3) * 8
                rlo = jnp.min(widx)
                rhi = jnp.minimum(jnp.max(widx) + 2, W - 1)
                fits = (rhi - (rlo - lax.rem(rlo, 8))) < CW
                any_oob = jnp.any(oob)
                way, wfs = _win_select(_wfs_of(c), rlo, rhi, fits)
                u0 = scal(widx)
                uni = allsame(widx, u0) & allsame(shB, scal(shB))

                @pl.when(fits & uni)
                def _():
                    _load_finish(
                        c, win_read_row(way, wfs, u0),
                        win_read_row(way, wfs, jnp.minimum(u0 + 1, W - 1)),
                        win_read_row(way, wfs, jnp.minimum(u0 + 2, W - 1)),
                        shB, oob, any_oob)

                @pl.when(fits & ~uni)
                def _():
                    w1 = jnp.clip(widx + 1, 0, W - 1)
                    w2 = jnp.clip(widx + 2, 0, W - 1)
                    _load_finish(c, _win_gather(way, wfs, widx),
                                 _win_gather(way, wfs, w1),
                                 _win_gather(way, wfs, w2),
                                 shB, oob, any_oob)

                c = _keep_win(c, wfs)
                return lax.cond(
                    fits,
                    lambda: lax.cond(
                        any_oob,
                        lambda: keep(c, pc=pc + 1, status=I32(ST_DIVERGED)),
                        lambda: keep(c, pc=pc + 1)),
                    lambda: keep(c, status=I32(ST_DIVERGED)))

            def h_store(c):
                if optimistic:
                    pc, sp = c[1], c[2]
                    vl, vh = srow(slo, sp - 1), srow(shi, sp - 1)
                    oob, oob0, u, shB0, rhi, nbytes = _opt_ls_prolog(
                        c, srow(slo, sp - 2), 2)
                    ok = ~oob
                    dirty, snapped, way, wfs2 = _opt_window(c, u, rhi)
                    b1 = nbytes == 1
                    b2_ = nbytes == 2
                    m_lo = jnp.where(b1, I32(0xFF),
                                     jnp.where(b2_, I32(0xFFFF), I32(-1)))
                    m_hi = jnp.where(nbytes == 8, I32(-1), I32(0))
                    for k, (m, v) in enumerate(
                            shifted_store_triples(m_lo, m_hi, vl, vh,
                                                  shB0)):
                        w = jnp.minimum(u + k, W - 1)

                        @pl.when(~dirty & (m != 0))
                        def _(m=m, v=v, w=w):
                            cur = win_read_row(way, wfs2, w)
                            win_write_row(
                                way, wfs2, w,
                                jnp.where(ok, (cur & ~m) | (v & m), cur))

                    @pl.when(~dirty & oob0)
                    def _():
                        trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

                    nwd0 = jnp.where(way == 0, I32(1), wfs2[1])
                    nwd1 = jnp.where(way == 1, I32(1), wfs2[3])
                    c2 = keep(c, wb0=wfs2[0], wd0=nwd0, wb1=wfs2[2],
                              wd1=nwd1, mru=wfs2[4],
                              ls=jnp.where(snapped, c[0], c[IDX["ls"]]))
                    return lax.cond(
                        dirty, rolled_carry,
                        lambda: lax.cond(
                            oob0,
                            lambda: keep(c2, pc=pc + 1, sp=sp - 2,
                                         status=I32(ST_DIVERGED)),
                            lambda: keep(c2, pc=pc + 1, sp=sp - 2)))
                pc, sp, pages = c[1], c[2], c[6]
                off, nbytes = a_r[pc], b_r[pc]
                vl, vh = srow(slo, sp - 1), srow(shi, sp - 1)
                addr = srow(slo, sp - 2)
                ea = addr + off
                carry_ = u_lt(ea, addr) | u_lt(ea, full(off))
                mem_bytes = pages * I32(65536)
                end = ea + nbytes
                oob = carry_ | u_lt(end, ea) | u_lt(full(mem_bytes), end)
                ok = ~oob
                widx = jnp.clip(lax.shift_right_logical(ea, 2), 0, W - 1)
                shB = (ea & 3) * 8
                b1 = nbytes == 1
                b2_ = nbytes == 2
                full_lo = jnp.where(b1, 0xFF,
                                    jnp.where(b2_, 0xFFFF, I32(-1)))
                full_hi = jnp.where(nbytes == 8, I32(-1), 0)
                full_lo = jnp.broadcast_to(full_lo, ROW)
                full_hi = jnp.broadcast_to(full_hi, ROW)
                ((sm0, sv0), (sm1, sv1), (sm2, sv2)) = \
                    shifted_store_triples(full_lo, full_hi, vl, vh, shB)
                rlo = jnp.min(widx)
                rhi = jnp.minimum(jnp.max(widx) + 2, W - 1)
                fits = (rhi - (rlo - lax.rem(rlo, 8))) < CW
                any_oob = jnp.any(oob)
                way, wfs = _win_select(_wfs_of(c), rlo, rhi, fits)
                u0 = scal(widx)
                uni = allsame(widx, u0) & allsame(shB, scal(shB))

                @pl.when(fits & uni)
                def _():
                    for k, (m, v) in enumerate(((sm0, sv0), (sm1, sv1),
                                                (sm2, sv2))):
                        w = jnp.minimum(u0 + k, W - 1)

                        @pl.when(jnp.any(m != 0))
                        def _(m=m, v=v, w=w):
                            cur = win_read_row(way, wfs, w)
                            win_write_row(
                                way, wfs, w,
                                jnp.where(ok & (m != 0),
                                          (cur & ~m) | (v & m), cur))

                @pl.when(fits & ~uni)
                def _():
                    base = jnp.where(way == 0, wfs[0], wfs[2])
                    wi = riota(CW) + base
                    for k, (m, v) in enumerate(((sm0, sv0), (sm1, sv1),
                                                (sm2, sv2))):
                        wk = jnp.clip(widx + k, 0, W - 1)
                        hit = (wi == wk) & (ok & (m != 0))

                        @pl.when(way == 0)
                        def _(hit=hit, m=m, v=v):
                            cur = srows(mwin0, 0, CW)
                            wrows(mwin0, 0, CW, jnp.where(
                                hit, (cur & ~m) | (v & m), cur))

                        @pl.when(way == 1)
                        def _(hit=hit, m=m, v=v):
                            cur = srows(mwin1, 0, CW)
                            wrows(mwin1, 0, CW, jnp.where(
                                hit, (cur & ~m) | (v & m), cur))

                nwd0 = jnp.where(fits & (way == 0), I32(1), wfs[1])
                nwd1 = jnp.where(fits & (way == 1), I32(1), wfs[3])
                c = keep(c, wb0=wfs[0], wd0=nwd0, wb1=wfs[2], wd1=nwd1,
                         mru=wfs[4])

                @pl.when(fits & any_oob)
                def _():
                    trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

                return lax.cond(
                    fits,
                    lambda: lax.cond(
                        any_oob,
                        lambda: keep(c, pc=pc + 1, sp=sp - 2,
                                     status=I32(ST_DIVERGED)),
                        lambda: keep(c, pc=pc + 1, sp=sp - 2)),
                    lambda: keep(c, status=I32(ST_DIVERGED)))

            def h_memfill(c):
                if optimistic:
                    return _opt_bulk_exit(c)
                pc, sp, pages = c[1], c[2], c[6]
                n = srow(slo, sp - 1)
                val = srow(slo, sp - 2)
                dst = srow(slo, sp - 3)
                mem_bytes = pages * I32(65536)
                end = dst + n
                oob = u_lt(end, dst) | u_lt(full(mem_bytes), end)
                go = (~oob) & (n != 0)
                fill_word = (val & 0xFF) * I32(0x01010101)
                dst_ok = jnp.where(go, dst, I32(0x7FFFFFFF))
                end_ok = jnp.where(go, end, I32(0))
                c_lo = jnp.clip(
                    lax.div(lax.shift_right_logical(jnp.min(dst_ok), 2),
                            I32(GR)), 0, GATHER_CHUNKS)
                c_hi = jnp.clip(
                    lax.div(lax.shift_right_logical(jnp.max(end_ok) + 3, 2)
                            + I32(GR - 1), I32(GR)), 0, GATHER_CHUNKS)
                # stream aligned GR-row chunks through scratch; the window
                # cache is flushed+invalidated first so it cannot hold
                # stale copies of the filled rows
                wfs = _win_flush(_wfs_of(c))

                def chunk(i, _):
                    base = a8(i * GR)
                    cin = dma(6, lsliceR(mem_out, base, GR),
                              mwin0.at[pl.ds(0, GR)])
                    cin.start()
                    cin.wait()
                    rows = srows(mwin0, 0, GR)
                    wi = base + riota(GR)
                    byte0 = wi * 4
                    mask = jnp.zeros_like(rows)
                    for bpos in range(4):
                        ba = byte0 + bpos
                        inr = (~u_lt(ba, dst)) & u_lt(ba, end)
                        mask = mask | jnp.where(
                            inr, jnp.int32(lo_ops.BYTE_MASKS[bpos]), 0)
                    write = (mask != 0) & go
                    wrows(mwin0, 0, GR, jnp.where(
                        write, (rows & ~mask) | (fill_word & mask), rows))
                    cout = dma(6, mwin0.at[pl.ds(0, GR)],
                               lsliceR(mem_out, base, GR))
                    cout.start()
                    cout.wait()
                    return 0

                lax.fori_loop(c_lo, c_hi, chunk, 0)
                any_oob = jnp.any(oob)

                @pl.when(any_oob)
                def _():
                    trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

                c = _keep_win(c, wfs)
                return lax.cond(
                    any_oob,
                    lambda: keep(c, pc=pc + 1, sp=sp - 3,
                                 status=I32(ST_DIVERGED)),
                    lambda: keep(c, pc=pc + 1, sp=sp - 3))

            def h_memcopy(c):
                if optimistic:
                    return _opt_bulk_exit(c)
                pc, sp, pages = c[1], c[2], c[6]
                n = srow(slo, sp - 1)
                src = srow(slo, sp - 2)
                dst = srow(slo, sp - 3)
                mem_bytes = pages * I32(65536)
                send = src + n
                dend = dst + n
                oob = u_lt(send, src) | u_lt(full(mem_bytes), send) | \
                    u_lt(dend, dst) | u_lt(full(mem_bytes), dend)
                delta = src - dst
                live = (~oob) & (n != 0)
                d_eff = jnp.where(live, delta, I32(0x7FFFFFFF))
                d0 = jnp.min(d_eff)
                agree = jnp.all(jnp.where(live, delta, d0) == d0)
                any_live = jnp.any(live)
                d0 = jnp.where(any_live, d0, I32(0))
                sm = d0 & 3
                qv = lax.shift_right_arithmetic(d0 - sm, 2)
                shB = sm * 8
                inv = (32 - shB) & 31
                hi_or = jnp.where(shB == 0, 0, -1)
                dst_ok = jnp.where(live, dst, I32(0x7FFFFFFF))
                dend_ok = jnp.where(live, dend, I32(0))
                row_lo = lax.shift_right_logical(jnp.min(dst_ok), 2)
                row_hi = lax.shift_right_logical(jnp.max(dend_ok) + 3, 2)
                row_lo = jnp.minimum(row_lo, I32(W))
                row_hi = jnp.minimum(row_hi, I32(W))
                nrows = jnp.maximum(row_hi - row_lo, 0)
                fwd = d0 >= 0
                # whole src+dst span in one window / disjoint regions a
                # way apart; large *overlapping* moves hand off to SIMT
                lo_all = jnp.clip(jnp.minimum(row_lo, row_lo + qv),
                                  0, W - 1)
                hi_all = jnp.clip(jnp.maximum(row_hi, row_hi + qv + 1) - 1,
                                  0, W - 1)
                one_win = (hi_all - (lo_all - lax.rem(lo_all, 8))) < CW
                disjoint = jnp.abs(qv) >= I32(CW + 8)
                feasible = agree & (one_win | disjoint | (nrows == 0))

                def row_mask(r):
                    mask = full(0)
                    for bpos in range(4):
                        ba = full(r * 4 + bpos)
                        inr = (~u_lt(ba, dst)) & u_lt(ba, dend)
                        mask = mask | jnp.where(
                            inr & live,
                            jnp.int32(lo_ops.BYTE_MASKS[bpos]), 0)
                    return mask

                def shift_val(m0, m1):
                    return lax.shift_right_logical(m0, shB) | \
                        (lax.shift_left(m1, inv) & hi_or)

                useA = agree & one_win & (nrows > 0)
                wayA, wfsA = _win_select(_wfs_of(c), lo_all, hi_all, useA)

                def bodyA(i, _):
                    r = jnp.where(fwd, row_lo + i, row_hi - 1 - i)
                    rc = jnp.clip(r, 0, W - 1)
                    m0 = win_read_row(wayA, wfsA,
                                      jnp.clip(r + qv, 0, W - 1))
                    m1 = win_read_row(wayA, wfsA,
                                      jnp.clip(r + qv + 1, 0, W - 1))
                    val = shift_val(m0, m1)
                    mask = row_mask(r)
                    old = win_read_row(wayA, wfsA, rc)
                    win_write_row(
                        wayA, wfsA, rc,
                        jnp.where(mask != 0, (old & ~mask) | (val & mask),
                                  old))
                    return 0

                lax.fori_loop(0, jnp.where(useA, nrows, 0), bodyA, 0)
                wfsA = (wfsA[0],
                        jnp.where(useA & (wayA == 0), I32(1), wfsA[1]),
                        wfsA[2],
                        jnp.where(useA & (wayA == 1), I32(1), wfsA[3]),
                        wfsA[4])

                useB = agree & ~one_win & disjoint & (nrows > 0)

                def bodyB(i, wfs):
                    r = jnp.where(fwd, row_lo + i, row_hi - 1 - i)
                    rs0 = jnp.clip(r + qv, 0, W - 1)
                    rs1 = jnp.clip(r + qv + 1, 0, W - 1)
                    ws, wfs = _win_select(wfs, jnp.minimum(rs0, rs1),
                                          jnp.maximum(rs0, rs1),
                                          jnp.bool_(True))
                    m0 = win_read_row(ws, wfs, rs0)
                    m1 = win_read_row(ws, wfs, rs1)
                    val = shift_val(m0, m1)
                    rc = jnp.clip(r, 0, W - 1)
                    wd_, wfs = _win_select(wfs, rc, rc, jnp.bool_(True))
                    mask = row_mask(r)
                    old = win_read_row(wd_, wfs, rc)
                    win_write_row(
                        wd_, wfs, rc,
                        jnp.where(mask != 0, (old & ~mask) | (val & mask),
                                  old))
                    return (wfs[0],
                            jnp.where(wd_ == 0, I32(1), wfs[1]),
                            wfs[2],
                            jnp.where(wd_ == 1, I32(1), wfs[3]),
                            wfs[4])

                wfsB = lax.fori_loop(0, jnp.where(useB, nrows, 0), bodyB,
                                     wfsA)
                any_oob = jnp.any(oob)

                @pl.when(feasible & any_oob)
                def _():
                    trap_where(oob, I32(int(ErrCode.MemoryOutOfBounds)))

                c = _keep_win(c, wfsB)
                return lax.cond(
                    feasible,
                    lambda: lax.cond(
                        any_oob,
                        lambda: keep(c, pc=pc + 1, sp=sp - 3,
                                     status=I32(ST_DIVERGED)),
                        lambda: keep(c, pc=pc + 1, sp=sp - 3)),
                    lambda: keep(c, status=I32(ST_DIVERGED)))

        def mk_fuse_gca(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                src = fp + a_r[pc]
                xl, xh = srow(slo, src), srow(shi, src)
                yl, yh = full(ilo_r[pc]), full(ihi_r[pc])
                rl, rh = fn(xl, xh, yl, yh)
                wrow(slo, sp, rl)
                wrow(shi, sp, rh)
                # retires 3 wasm instructions (the dispatch loop adds 1)
                return keep(c, steps=c[0] + 2, pc=pc + 3, sp=sp + 1)
            return h

        def mk_fuse_gcb(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                src = fp + a_r[pc]
                xl, xh = srow(slo, src), srow(shi, src)
                yl, yh = full(ilo_r[pc]), full(ihi_r[pc])
                cond, _rh = fn(xl, xh, yl, yh)
                if optimistic:
                    t0 = agree_nz(cond)
                    new_pc = jnp.where(t0 == 0, b_r[pc], pc + 4)
                    return keep(c, steps=c[0] + 3, pc=new_pc)
                t0 = scal(cond)
                agree = allsame(cond, t0)
                new_pc = jnp.where(t0 == 0, b_r[pc], pc + 4)
                return lax.cond(
                    agree,
                    lambda: keep(c, steps=c[0] + 3, pc=new_pc),
                    lambda: keep(c, status=I32(ST_DIVERGED)))
            return h

        def mk_fuse_a2r(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp, cd = c[1], c[2], c[3], c[5]
                xl, xh = srow(slo, sp - 2), srow(shi, sp - 2)
                yl, yh = srow(slo, sp - 1), srow(shi, sp - 1)
                rl, rh = fn(xl, xh, yl, yh)
                wrow(slo, fp, rl)
                wrow(shi, fp, rh)
                new_sp = fp + 1
                rd = jnp.clip(cd - 1, 0, CD - 1)
                return lax.cond(
                    cd == 0,
                    lambda: keep(c, steps=c[0] + 1, sp=new_sp,
                                 status=I32(ST_DONE)),
                    lambda: keep(c, steps=c[0] + 1,
                                 pc=frames_out[blk, 0, rd], sp=new_sp,
                                 fp=frames_out[blk, 1, rd],
                                 ob=frames_out[blk, 2, rd], cd=cd - 1))
            return h

        def mk_fuse_gcs(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                src = fp + a_r[pc]
                xl, xh = srow(slo, src), srow(shi, src)
                yl, yh = full(ilo_r[pc]), full(ihi_r[pc])
                rl, rh = fn(xl, xh, yl, yh)
                dst = fp + b_r[pc]
                wrow(slo, dst, rl)
                wrow(shi, dst, rh)
                return keep(c, steps=c[0] + 3, pc=pc + 4)
            return h

        def mk_fuse_gga(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                s1, s2 = fp + a_r[pc], fp + c_r[pc]
                rl, rh = fn(srow(slo, s1), srow(shi, s1),
                            srow(slo, s2), srow(shi, s2))
                wrow(slo, sp, rl)
                wrow(shi, sp, rh)
                return keep(c, steps=c[0] + 2, pc=pc + 3, sp=sp + 1)
            return h

        def mk_fuse_ggs(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                s1, s2 = fp + a_r[pc], fp + c_r[pc]
                rl, rh = fn(srow(slo, s1), srow(shi, s1),
                            srow(slo, s2), srow(shi, s2))
                dst = fp + b_r[pc]
                wrow(slo, dst, rl)
                wrow(shi, dst, rh)
                return keep(c, steps=c[0] + 3, pc=pc + 4)
            return h

        def mk_fuse_ggbz(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                s1, s2 = fp + ilo_r[pc], fp + ihi_r[pc]
                cond, _rh = fn(srow(slo, s1), srow(shi, s1),
                               srow(slo, s2), srow(shi, s2))
                if optimistic:
                    t0 = agree_nz(cond)
                    new_pc = jnp.where(t0 == 0, a_r[pc], pc + 4)
                    return keep(c, steps=c[0] + 3, pc=new_pc)
                t0 = scal(cond)
                agree = allsame(cond, t0)
                new_pc = jnp.where(t0 == 0, a_r[pc], pc + 4)
                return lax.cond(
                    agree,
                    lambda: keep(c, steps=c[0] + 3, pc=new_pc),
                    lambda: keep(c, status=I32(ST_DIVERGED)))
            return h

        def mk_fuse_ggbnz(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp, ob = c[1], c[2], c[3], c[4]
                s1, s2 = fp + ilo_r[pc], fp + ihi_r[pc]
                cond, _rh = fn(srow(slo, s1), srow(shi, s1),
                               srow(slo, s2), srow(shi, s2))
                t0 = agree_nz(cond) if optimistic else scal(cond)
                agree = True if optimistic else allsame(cond, t0)
                tgt, nkeep, pop_to = a_r[pc], b_r[pc], c_r[pc]
                tgt_sp = ob + pop_to
                taken = t0 != 0

                @pl.when(agree & taken & (nkeep == 1))
                def _():
                    # the would-be kept value sits at the pre-fusion top
                    wrow(slo, tgt_sp, srow(slo, sp - 1))
                    wrow(shi, tgt_sp, srow(shi, sp - 1))

                return lax.cond(
                    agree,
                    lambda: lax.cond(
                        taken,
                        lambda: keep(c, steps=c[0] + 3, pc=tgt,
                                     sp=tgt_sp + nkeep),
                        lambda: keep(c, steps=c[0] + 3, pc=pc + 4)),
                    lambda: keep(c, status=I32(ST_DIVERGED)))
            return h

        def mk_fuse_gcc(sub):
            fn = alu2[sub]

            def h(c):
                pc, sp, fp = c[1], c[2], c[3]
                src = fp + a_r[pc]
                xl, xh = srow(slo, src), srow(shi, src)
                yl, yh = full(ilo_r[pc]), full(ihi_r[pc])
                rl, rh = fn(xl, xh, yl, yh)
                wrow(slo, sp, rl)
                wrow(shi, sp, rh)
                # the fused call returns to pc+4
                c2 = keep(c, steps=c[0] + 3, pc=pc + 3, sp=sp + 1)
                return _do_call(c2, b_r[pc], sp + 1)
            return h

        def h_fuse_gbr(c):
            pc, sp, fp, ob = c[1], c[2], c[3], c[4]
            tgt, nkeep, pop_to = a_r[pc], b_r[pc], c_r[pc]
            tgt_sp = ob + pop_to

            @pl.when(nkeep == 1)
            def _():
                src = fp + ilo_r[pc]
                wrow(slo, tgt_sp, srow(slo, src))
                wrow(shi, tgt_sp, srow(shi, src))

            return keep(c, steps=c[0] + 1, pc=tgt, sp=tgt_sp + nkeep)

        def mk_alu2(sub):
            fn = alu2[sub]
            can_trap = sub in _DIV32_SUBS or sub in _DIV64_SUBS

            def h(c):
                pc, sp = c[1], c[2]
                xl, xh = srow(slo, sp - 2), srow(shi, sp - 2)
                yl, yh = srow(slo, sp - 1), srow(shi, sp - 1)
                rl, rh = fn(xl, xh, yl, yh)
                wrow(slo, sp - 2, rl)
                wrow(shi, sp - 2, rh)
                if not can_trap:
                    return keep(c, pc=pc + 1, sp=sp - 1)
                if sub in _DIV32_SUBS:
                    dz = yl == 0
                    ovf = (xl == jnp.int32(-0x80000000)) & (yl == -1) \
                        if sub in _DIVS_SUBS else jnp.zeros_like(dz)
                else:
                    dz = (yl | yh) == 0
                    ovf = ((xl == 0) & (xh == jnp.int32(-0x80000000)) &
                           (yl == -1) & (yh == -1)) \
                        if sub in _DIVS_SUBS else jnp.zeros_like(dz)
                bad = dz | ovf
                kind = jnp.where(dz, I32(1), jnp.where(ovf, I32(2), I32(0)))
                if optimistic:
                    k0 = agree_i32(kind)
                    code0 = jnp.where(k0 == 1,
                                      I32(int(ErrCode.DivideByZero)),
                                      I32(int(ErrCode.IntegerOverflow)))

                    @pl.when(k0 != 0)
                    def _():
                        codes = jnp.where(dz,
                                          I32(int(ErrCode.DivideByZero)),
                                          I32(int(ErrCode.IntegerOverflow)))
                        trap_where(bad, codes)

                    return lax.cond(
                        k0 != 0,
                        lambda: keep(c, status=I32(ST_TRAPPED_BASE) + code0),
                        lambda: keep(c, pc=pc + 1, sp=sp - 1))
                any_bad = jnp.any(bad)
                k0 = scal(kind)
                code0 = jnp.where(k0 == 1, I32(int(ErrCode.DivideByZero)),
                                  I32(int(ErrCode.IntegerOverflow)))

                @pl.when(any_bad)
                def _():
                    codes = jnp.where(dz, I32(int(ErrCode.DivideByZero)),
                                      I32(int(ErrCode.IntegerOverflow)))
                    trap_where(bad, codes)

                return lax.cond(
                    any_bad,
                    lambda: lax.cond(
                        jnp.all(bad) & allsame(kind, k0),
                        lambda: keep(c, status=I32(ST_TRAPPED_BASE) + code0),
                        lambda: keep(c, pc=pc + 1, sp=sp - 1,
                                     status=I32(ST_DIVERGED))),
                    lambda: keep(c, pc=pc + 1, sp=sp - 1))
            return h

        def mk_alu1(sub):
            fn = alu1[sub]
            trap_fn = alu1_traps.get(sub)

            def h(c):
                pc, sp = c[1], c[2]
                wl, wh = srow(slo, sp - 1), srow(shi, sp - 1)
                rl, rh = fn(wl, wh)
                wrow(slo, sp - 1, rl)
                wrow(shi, sp - 1, rh)
                if trap_fn is None:
                    return keep(c, pc=pc + 1)
                bad, codes = trap_fn(wl, wh)
                if optimistic:
                    # one canary covers both badness and code agreement
                    badk = jnp.where(bad, codes, 0)
                    k0 = agree_i32(badk)

                    @pl.when(k0 != 0)
                    def _():
                        trap_where(bad, codes)

                    return lax.cond(
                        k0 != 0,
                        lambda: keep(c, status=I32(ST_TRAPPED_BASE) + k0),
                        lambda: keep(c, pc=pc + 1))
                any_bad = jnp.any(bad)
                code0 = scal(codes)

                @pl.when(any_bad)
                def _():
                    trap_where(bad, codes)

                return lax.cond(
                    any_bad,
                    lambda: lax.cond(
                        jnp.all(bad) & allsame(codes, code0),
                        lambda: keep(c, status=I32(ST_TRAPPED_BASE) + code0),
                        lambda: keep(c, pc=pc + 1,
                                     status=I32(ST_DIVERGED))),
                    lambda: keep(c, pc=pc + 1))
            return h

        def mk_block(shape):
            """Fused basic block: pure ops run with intermediates in
            vregs (virtual stack resolved at trace time); local/global/
            memory writes commit immediately in op order.  Forward
            branches absorbed as GUARDS speculate fallthrough — the
            taken path exits through a lax.cond branch that flushes the
            guard-point virtual stack, so nothing after the guard
            commits.  Inline loads/stores take the uniform-address fast
            path; address divergence (careful kernel) or a lane-0 OOB
            bails un-advanced at the op's own slot with everything
            before it committed, which is exactly the state the
            scheduler's split machinery expects for the op's ORIGINAL
            opcode.  The terminal (if any) runs via the *_with cores,
            consuming the virtual-stack top directly from vregs."""
            body_ops = shape[:-1] if shape[-1][0] == "term" else shape
            term = shape[-1] if shape[-1][0] == "term" else None
            nops = len(body_ops)

            def h(c):
                pc, sp0, fp = c[1], c[2], c[3]

                class VS:
                    """Trace-time virtual stack (immutable snapshots:
                    guard/bail closures capture the state at their
                    point)."""
                    __slots__ = ("items", "nbelow")

                    def __init__(self, items=(), nbelow=0):
                        self.items = tuple(items)
                        self.nbelow = nbelow

                    def push(self, v):
                        return VS(self.items + (v,), self.nbelow)

                    def pop(self):
                        if self.items:
                            return self.items[-1], VS(self.items[:-1],
                                                      self.nbelow)
                        k = self.nbelow
                        idx = sp0 - 1 - k
                        return srow4(idx), VS((), k + 1)

                    def drop1(self):
                        if self.items:
                            return VS(self.items[:-1], self.nbelow)
                        return VS((), self.nbelow + 1)

                    def peek(self):
                        if self.items:
                            return self.items[-1]
                        idx = sp0 - 1 - self.nbelow
                        return srow4(idx)

                    def sp(self):
                        return sp0 + (len(self.items) - self.nbelow)

                    def flush(self, skip_top=0):
                        base = sp0 - self.nbelow
                        n = len(self.items) - skip_top
                        for i in range(n):
                            wrow4(base + i, self.items[i])

                def cell2(lo_v, hi_v):
                    """A scalar-result cell: e2/e3 cleared when the
                    module carries v128 planes (scalar consumers never
                    read them; clearing beats stale garbage)."""
                    if simd:
                        z = full(0)
                        return (lo_v, hi_v, z, z)
                    return (lo_v, hi_v)

                def bail(cb, j, vs):
                    """Un-advanced stop at op j: everything before j is
                    committed; flush the virtual stack so VMEM holds
                    the exact pre-op state, leave pc at the op's slot
                    (original hid) for the scheduler/SIMT."""
                    vs.flush()
                    return keep(cb, steps=cb[0] + j, pc=pc + j,
                                sp=vs.sp(), status=I32(ST_DIVERGED))

                def emit(j, cb, vs, pend_l, pend_g):
                    if j == nops:
                        return finish(cb, vs)
                    pcj = pc + j
                    op = body_ops[j]
                    kind = op[0]
                    if kind == "nop":
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "const":
                        vs = vs.push(cell2(full(ilo_r[pcj]),
                                           full(ihi_r[pcj])))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "lget":
                        v = pend_l.get(op[1])
                        if v is None:
                            v = srow4(fp + a_r[pcj])
                        return emit(j + 1, cb, vs.push(v), pend_l, pend_g)
                    if kind in ("lset", "ltee"):
                        if kind == "lset":
                            v, vs = vs.pop()
                        else:
                            v = vs.peek()
                        wrow4(fp + a_r[pcj], v)
                        return emit(j + 1, cb, vs,
                                    {**pend_l, op[1]: v}, pend_g)
                    if kind == "gget":
                        v = pend_g.get(op[1])
                        if v is None:
                            g = a_r[pcj]
                            v = cell2(srow(glo, g), srow(ghi, g))
                        return emit(j + 1, cb, vs.push(v), pend_l, pend_g)
                    if kind == "gset":
                        v, vs = vs.pop()
                        g = a_r[pcj]
                        wrow(glo, g, v[0])
                        wrow(ghi, g, v[1])
                        return emit(j + 1, cb, vs, pend_l,
                                    {**pend_g, op[1]: v})
                    if kind == "drop":
                        return emit(j + 1, cb, vs.drop1(), pend_l, pend_g)
                    if kind == "select":
                        cnd, vs = vs.pop()
                        x2, vs = vs.pop()
                        x1, vs = vs.pop()
                        z = cnd[0] == 0
                        vs = vs.push(tuple(jnp.where(z, a, b)
                                           for a, b in zip(x2, x1)))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "memsize":
                        vs = vs.push(cell2(full(cb[6]), full(0)))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "alu2":
                        y, vs = vs.pop()
                        x, vs = vs.pop()
                        vs = vs.push(cell2(*alu2[op[1]](x[0], x[1],
                                                        y[0], y[1])))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "alu1":
                        x, vs = vs.pop()
                        vs = vs.push(cell2(*alu1[op[1]](x[0], x[1])))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "v2":
                        y, vs = vs.pop()
                        x, vs = vs.pop()
                        vs = vs.push(sops.v2_fn(op[1])(x, y))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "v1":
                        x, vs = vs.pop()
                        vs = vs.push(sops.v1_fn(op[1])(x))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vtest":
                        x, vs = vs.pop()
                        vs = vs.push(cell2(sops.vtest_fn(op[1])(x),
                                           full(0)))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vshift":
                        cnt, vs = vs.pop()
                        x, vs = vs.pop()
                        vs = vs.push(sops.vshift_fn(op[1])(x, cnt[0]))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vsplat":
                        v, vs = vs.pop()
                        vs = vs.push(sops.vsplat_fn(op[1])(v[0], v[1]))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vextract":
                        x, vs = vs.pop()
                        rl, rh = sops.vextract_dyn(op[1])(x, a_r[pcj])
                        vs = vs.push(cell2(rl, rh))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vreplace":
                        v, vs = vs.pop()
                        x, vs = vs.pop()
                        vs = vs.push(sops.vreplace_dyn(op[1])(
                            x, a_r[pcj], v[0], v[1]))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vconst":
                        vs = vs.push(_vconst4(a_r[pcj]))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vshuffle":
                        y, vs = vs.pop()
                        x, vs = vs.pop()
                        vs = vs.push(sops.vshuffle_dyn()(
                            x, y, _vconst4(a_r[pcj])))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind == "vbitsel":
                        y, vs = vs.pop()
                        x, vs = vs.pop()
                        w_, vs = vs.pop()
                        vs = vs.push(sops.vbitselect()(w_, x, y))
                        return emit(j + 1, cb, vs, pend_l, pend_g)
                    if kind in ("guardz", "guardnz"):
                        return emit_guard(j, cb, vs, pend_l, pend_g)
                    if kind == "loadi":
                        return emit_load(j, cb, vs, pend_l, pend_g)
                    if kind == "storei":
                        return emit_store(j, cb, vs, pend_l, pend_g)
                    raise AssertionError(f"unknown block op {kind}")

                def emit_guard(j, cb, vs, pend_l, pend_g):
                    pcj = pc + j
                    nz = body_ops[j][0] == "guardnz"
                    vs_pre = vs           # incl. cond (careful bail)
                    cond, vs = vs.pop()

                    def exit_taken():
                        vs.flush()
                        # brz taken: sp = post-pop; brnz (nkeep==0)
                        # taken: unwind to ob + pop_to
                        tsp = (cb[4] + c_r[pcj]) if nz else vs.sp()
                        return keep(cb, steps=cb[0] + j, pc=a_r[pcj],
                                    sp=tsp)

                    if optimistic:
                        t0 = agree_nz(cond[0])
                        taken = (t0 != 0) if nz else (t0 == 0)
                        return lax.cond(
                            taken, exit_taken,
                            lambda: emit(j + 1, cb, vs, pend_l, pend_g))
                    t0 = scal(cond[0])
                    agree = allsame(cond[0], t0)
                    taken = (t0 != 0) if nz else (t0 == 0)
                    return lax.cond(
                        agree & ~taken,
                        lambda: emit(j + 1, cb, vs, pend_l, pend_g),
                        lambda: lax.cond(
                            agree, exit_taken,
                            lambda: bail(cb, j, vs_pre)))

                def _load_val(m0, m1, m2, shB, nbytes, flags):
                    """Static-width load value extraction (the runtime
                    where-chains of _load_finish specialized away)."""
                    inv = (32 - shB) & 31
                    hi_or = jnp.where(shB == 0, 0, -1)
                    raw_lo = lax.shift_right_logical(m0, shB) | \
                        (lax.shift_left(m1, inv) & hi_or)
                    signed = (flags & 1) != 0
                    is64 = (flags & 2) != 0
                    if nbytes == 8:
                        raw_hi = lax.shift_right_logical(m1, shB) | \
                            (lax.shift_left(m2, inv) & hi_or)
                        return raw_lo, raw_hi
                    if nbytes == 4:
                        ll = raw_lo
                    elif nbytes == 2:
                        ll = lax.shift_right_arithmetic(
                            lax.shift_left(raw_lo, 16), 16) if signed \
                            else raw_lo & 0xFFFF
                    else:
                        ll = lax.shift_right_arithmetic(
                            lax.shift_left(raw_lo, 24), 24) if signed \
                            else raw_lo & 0xFF
                    if is64:
                        lh = lax.shift_right_arithmetic(ll, 31) if signed \
                            else jnp.zeros_like(ll)
                    else:
                        lh = jnp.zeros_like(ll)
                    return ll, lh

                def emit_load(j, cb, vs, pend_l, pend_g):
                    pcj = pc + j
                    nbytes, flags = body_ops[j][1], body_ops[j][2]
                    want = 2 if nbytes == 8 else 1
                    vs_pre = vs
                    addr, vs = vs.pop()
                    off = a_r[pcj]
                    ea = addr[0] + off
                    if optimistic:
                        _ea0, oob0, u, shB = opt_addr_prolog(
                            ea, off, nbytes, cb[6])
                        if mem_hbm:
                            rhi = jnp.minimum(u + want, W - 1)
                            # _opt_window may SNAPSHOT (dirty-way
                            # eviction): the snapshot must pair the
                            # planes with a carry positioned at THIS
                            # op — flush the pre-op virtual stack and
                            # hand it a mid-block-consistent carry, so
                            # a later rollback re-enters at pcj (an
                            # absorbed slot with the original hid) and
                            # never re-runs the committed prefix.
                            vs_pre.flush()
                            cb_snap = keep(cb, steps=cb[0] + j,
                                           pc=pc + j, sp=vs_pre.sp())
                            dirty, snapped, way, wfs2 = _opt_window(
                                cb_snap, u, rhi)
                            cb2 = _keep_win(
                                cb, wfs2,
                                ls=jnp.where(snapped, cb[0] + j,
                                             cb[IDX["ls"]]))
                            m0 = win_read_row(way, wfs2, u)
                            m1 = win_read_row(way, wfs2,
                                              jnp.minimum(u + 1, W - 1))
                            m2 = win_read_row(way, wfs2,
                                              jnp.minimum(u + 2, W - 1)) \
                                if nbytes == 8 else None
                            vs2 = vs.push(cell2(*_load_val(
                                m0, m1, m2, shB, nbytes, flags)))
                            return lax.cond(
                                dirty, rolled_carry,
                                lambda: lax.cond(
                                    oob0,
                                    lambda: bail(cb2, j, vs_pre),
                                    lambda: emit(j + 1, cb2, vs2,
                                                 pend_l, pend_g)))
                        m0 = srow(memr, u)
                        m1 = srow(memr, jnp.minimum(u + 1, W - 1))
                        m2 = srow(memr, jnp.minimum(u + 2, W - 1)) \
                            if nbytes == 8 else None
                        vs2 = vs.push(cell2(*_load_val(m0, m1, m2, shB,
                                                       nbytes, flags)))
                        return lax.cond(
                            oob0,
                            lambda: bail(cb, j, vs_pre),
                            lambda: emit(j + 1, cb, vs2, pend_l, pend_g))
                    # careful kernel: flush and delegate to the original
                    # handler (keeps its divergent-address gather paths
                    # and trap-partial semantics); execution continues
                    # UNFUSED at pcj+1 until the next block head —
                    # careful runs only on recheck rounds, so parity
                    # beats speed here.
                    return _delegate_mem(j, cb, vs_pre,
                                         _load_flat_hid(nbytes, flags))

                def _load_flat_hid(nbytes, flags):
                    if nbytes == 4 and flags in (0, 2):
                        return H_LOAD_W
                    if nbytes == 8:
                        return H_LOAD_D
                    return H_LOAD

                def _delegate_mem(j, cb, vs_pre, flat_hid):
                    vs_pre.flush()
                    c2 = keep(cb, steps=cb[0] + j, pc=pc + j,
                              sp=vs_pre.sp())
                    return handler_for(flat_hid)(c2)

                def emit_store(j, cb, vs, pend_l, pend_g):
                    pcj = pc + j
                    nbytes = body_ops[j][1]
                    want = 2 if nbytes == 8 else 1
                    vs_pre = vs
                    val, vs = vs.pop()
                    addr, vs = vs.pop()
                    off = a_r[pcj]
                    ea = addr[0] + off
                    m_lo = I32(-1) if nbytes >= 4 else \
                        I32(0xFF if nbytes == 1 else 0xFFFF)
                    m_hi = I32(-1) if nbytes == 8 else I32(0)

                    def masks_vals(shB):
                        return shifted_store_triples(m_lo, m_hi,
                                                     val[0], val[1], shB)

                    if optimistic:
                        _ea0, oob0, u, shB = opt_addr_prolog(
                            ea, off, nbytes, cb[6])
                        if mem_hbm:
                            rhi = jnp.minimum(u + want, W - 1)
                            # snapshot-consistency: see emit_load
                            vs_pre.flush()
                            cb_snap = keep(cb, steps=cb[0] + j,
                                           pc=pc + j, sp=vs_pre.sp())
                            dirty, snapped, way, wfs2 = _opt_window(
                                cb_snap, u, rhi)
                            okw = ~dirty & ~oob0
                            for k, (m, v) in enumerate(masks_vals(shB)):
                                w = jnp.minimum(u + k, W - 1)

                                @pl.when(okw & (m != 0))
                                def _(m=m, v=v, w=w):
                                    cur = win_read_row(way, wfs2, w)
                                    win_write_row(way, wfs2, w,
                                                  (cur & ~m) | (v & m))

                            nwd0 = jnp.where(way == 0, I32(1), wfs2[1])
                            nwd1 = jnp.where(way == 1, I32(1), wfs2[3])
                            cb2 = keep(cb, wb0=wfs2[0], wd0=nwd0,
                                       wb1=wfs2[2], wd1=nwd1, mru=wfs2[4],
                                       ls=jnp.where(snapped, cb[0] + j,
                                                    cb[IDX["ls"]]))
                            return lax.cond(
                                dirty, rolled_carry,
                                lambda: lax.cond(
                                    oob0,
                                    lambda: bail(cb2, j, vs_pre),
                                    lambda: emit(j + 1, cb2, vs,
                                                 pend_l, pend_g)))
                        for k, (m, v) in enumerate(masks_vals(shB)):
                            w = jnp.minimum(u + k, W - 1)

                            @pl.when(~oob0 & (m != 0))
                            def _(m=m, v=v, w=w):
                                cur = srow(memr, w)
                                wrow(memr, w, (cur & ~m) | (v & m))

                        return lax.cond(
                            oob0,
                            lambda: bail(cb, j, vs_pre),
                            lambda: emit(j + 1, cb, vs, pend_l, pend_g))
                    # careful kernel: flush + delegate (see emit_load)
                    return _delegate_mem(
                        j, cb, vs_pre,
                        H_STORE_W if nbytes == 4 else
                        H_STORE_D if nbytes == 8 else H_STORE)

                def finish(cb, vs):
                    sp_t = vs.sp()
                    if term is None:
                        vs.flush()
                        return keep(cb, steps=cb[0] + nops - 1,
                                    pc=pc + nops, sp=sp_t)
                    t_hid = term[1]
                    # Only the cell the terminal POPS (or that dies
                    # with the unwind: return/br kept values) may skip
                    # its flush; a brnz fallthrough keeps sp-2 live, so
                    # deeper cells always flush even when also passed
                    # as vregs.
                    nvreg = 0
                    if t_hid in (H_BRZ, H_BRNZ, H_BR_TABLE, H_RETURN,
                                 H_BR, H_CALL_INDIRECT):
                        nvreg = min(1, len(vs.items))
                    vs.flush(skip_top=nvreg)
                    top1 = vs.items[-1] if len(vs.items) >= 1 else None
                    top2 = vs.items[-2] if len(vs.items) >= 2 else None
                    c2 = keep(cb, steps=cb[0] + nops, pc=pc + nops,
                              sp=sp_t)
                    if t_hid == H_BRZ:
                        return brz_with(c2, top1, spill=top1 is not None)
                    if t_hid == H_BRNZ:
                        return brnz_with(c2, top1, top2,
                                         spill=top1 is not None)
                    if t_hid == H_BR_TABLE:
                        return br_table_with(c2, top1, top2,
                                             spill=top1 is not None)
                    if t_hid == H_RETURN:
                        return return_with(c2, top1)
                    if t_hid == H_BR:
                        return br_with(c2, top1)
                    if t_hid == H_CALL_INDIRECT:
                        return calli_with(c2, top1,
                                          spill=top1 is not None)
                    return handler_for(t_hid)(c2)

                return emit(0, c, VS(), {}, {})
            return h

        # ------------------- v128 handlers ----------------------------
        # Same 4-plane cell model and simdops semantics as the SIMT
        # engine (engine.py "v128 (SIMD)" section), executed in the one
        # hot loop like the reference's interpreter runs the whole 0xFD
        # page in its dispatch loop (lib/executor/engine/engine.cpp
        # ~700-1610).  Only traced when the module's image uses them.
        if simd:
            from wasmedge_tpu.batch import simdops as sops

            def _vconst4(idx):
                i = jnp.clip(idx, 0, NV - 1)
                return tuple(full(v128t_r[i, k]) for k in range(4))

            def h_vconst(c):
                pc, sp = c[1], c[2]
                wrow4(sp, _vconst4(a_r[pc]))
                return keep(c, pc=pc + 1, sp=sp + 1)

            def mk_v2(sub):
                fn = sops.v2_fn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    wrow4(sp - 2, fn(srow4(sp - 2), srow4(sp - 1)))
                    return keep(c, pc=pc + 1, sp=sp - 1)
                return h

            def mk_v1(sub):
                fn = sops.v1_fn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    wrow4(sp - 1, fn(srow4(sp - 1)))
                    return keep(c, pc=pc + 1)
                return h

            def mk_vtest(sub):
                fn = sops.vtest_fn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    r = fn(srow4(sp - 1))
                    wrow(slo, sp - 1, r)
                    wrow(shi, sp - 1, full(0))
                    return keep(c, pc=pc + 1)
                return h

            def mk_vshift(sub):
                fn = sops.vshift_fn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    cnt = srow(slo, sp - 1)
                    wrow4(sp - 2, fn(srow4(sp - 2), cnt))
                    return keep(c, pc=pc + 1, sp=sp - 1)
                return h

            def mk_vsplat(sub):
                fn = sops.vsplat_fn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    wrow4(sp - 1, fn(srow(slo, sp - 1),
                                     srow(shi, sp - 1)))
                    return keep(c, pc=pc + 1)
                return h

            def mk_vextract(sub):
                fn = sops.vextract_dyn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    rl, rh = fn(srow4(sp - 1), a_r[pc])
                    wrow(slo, sp - 1, rl)
                    wrow(shi, sp - 1, rh)
                    return keep(c, pc=pc + 1)
                return h

            def mk_vreplace(sub):
                fn = sops.vreplace_dyn(sub)

                def h(c):
                    pc, sp = c[1], c[2]
                    r = fn(srow4(sp - 2), a_r[pc],
                           srow(slo, sp - 1), srow(shi, sp - 1))
                    wrow4(sp - 2, r)
                    return keep(c, pc=pc + 1, sp=sp - 1)
                return h

            def h_vshuffle(c):
                pc, sp = c[1], c[2]
                r = sops.vshuffle_dyn()(srow4(sp - 2), srow4(sp - 1),
                                        _vconst4(a_r[pc]))
                wrow4(sp - 2, r)
                return keep(c, pc=pc + 1, sp=sp - 1)

            def h_vbitsel(c):
                pc, sp = c[1], c[2]
                r = sops.vbitselect()(srow4(sp - 3), srow4(sp - 2),
                                      srow4(sp - 1))
                wrow4(sp - 3, r)
                return keep(c, pc=pc + 1, sp=sp - 2)

            def _vmem_rows(cb, u, n_rows, wfs_sel):
                """Read n_rows consecutive memory words starting at
                scalar row u (resident rows or window rows)."""
                if mem_hbm:
                    way, wfs2 = wfs_sel
                    return [win_read_row(way, wfs2,
                                         jnp.minimum(u + k, W - 1))
                            for k in range(n_rows)]
                return [srow(memr, jnp.minimum(u + k, W - 1))
                        for k in range(n_rows)]

            def _v128_from_words(m, shB):
                """Compose 4 planes from 5 words shifted right by shB
                bits (the 16-byte unaligned window)."""
                inv = (32 - shB) & 31
                hi_or = jnp.where(shB == 0, 0, -1)
                return tuple(
                    lax.shift_right_logical(m[k], shB) |
                    (lax.shift_left(m[k + 1], inv) & hi_or)
                    for k in range(4))

            def h_vload(c):
                pc, sp = c[1], c[2]
                addr = srow(slo, sp - 1)
                off = a_r[pc]
                ea = addr + off
                if optimistic:
                    _ea0, oob0, u, shB = opt_addr_prolog(
                        ea, off, 16, c[6])
                    if mem_hbm:
                        rhi = jnp.minimum(u + 4, W - 1)
                        dirty, snapped, way, wfs2 = _opt_window(
                            c, u, rhi)
                        m = _vmem_rows(c, u, 5, (way, wfs2))

                        @pl.when(~dirty & ~oob0)
                        def _():
                            wrow4(sp - 1, _v128_from_words(m, shB))

                        c2 = _keep_win(
                            c, wfs2,
                            ls=jnp.where(snapped, c[0], c[IDX["ls"]]))
                        return lax.cond(
                            dirty, rolled_carry,
                            lambda: lax.cond(
                                oob0,
                                lambda: keep(c2,
                                             status=I32(ST_DIVERGED)),
                                lambda: keep(c2, pc=pc + 1)))
                    m = _vmem_rows(c, u, 5, None)

                    @pl.when(~oob0)
                    def _():
                        wrow4(sp - 1, _v128_from_words(m, shB))

                    return lax.cond(
                        oob0,
                        lambda: keep(c, status=I32(ST_DIVERGED)),
                        lambda: keep(c, pc=pc + 1))
                # careful: uniform-address fast path, else hand the
                # block to SIMT (full per-lane v128 over there)
                carry_ = u_lt(ea, addr) | u_lt(ea, full(off))
                end = ea + 16
                mem_bytes = c[6] * I32(65536)
                oob = carry_ | u_lt(end, ea) | u_lt(mem_bytes, end)
                widx = jnp.clip(lax.shift_right_logical(ea, 2),
                                0, W - 1)
                shBv = (ea & 3) * 8
                u0 = scal(widx)
                ok = allsame(widx, u0) & allsame(shBv, scal(shBv)) & \
                    ~jnp.any(oob)
                shB = scal(shBv)
                if mem_hbm:
                    rhi = jnp.minimum(u0 + 4, W - 1)
                    way, wfs = _win_select(_wfs_of(c), u0, rhi, ok)
                    c2 = _keep_win(c, wfs)
                    m = _vmem_rows(c2, u0, 5, (way, wfs))
                else:
                    c2 = c
                    m = _vmem_rows(c2, u0, 5, None)

                @pl.when(ok)
                def _():
                    wrow4(sp - 1, _v128_from_words(m, shB))

                return lax.cond(
                    ok,
                    lambda: keep(c2, pc=pc + 1),
                    lambda: keep(c2, status=I32(ST_DIVERGED)))

            def h_vstore(c):
                pc, sp = c[1], c[2]
                v4 = srow4(sp - 1)
                addr = srow(slo, sp - 2)
                off = a_r[pc]
                ea = addr + off

                def word_val_mask(k, shB):
                    """Word k (0..4) of the 128-bit value shifted left
                    by shB bits, and its byte mask."""
                    inv = (32 - shB) & 31
                    hi_or = jnp.where(shB == 0, 0, -1)
                    lo_p = lax.shift_left(v4[k], shB) if k < 4 else 0
                    hi_p = (lax.shift_right_logical(v4[k - 1], inv)
                            & hi_or) if k > 0 else 0
                    m_lo = lax.shift_left(I32(-1), shB) if k < 4 else 0
                    m_hi = (lax.shift_right_logical(I32(-1), inv)
                            & hi_or) if k > 0 else 0
                    return lo_p | hi_p, m_lo | m_hi

                def commit(u, shB, okp, win):
                    for k in range(5):
                        v, mmask = word_val_mask(k, shB)
                        w = jnp.minimum(u + k, W - 1)

                        @pl.when(okp & (mmask != 0))
                        def _(v=v, mmask=mmask, w=w):
                            if mem_hbm:
                                way, wfs2 = win
                                cur = win_read_row(way, wfs2, w)
                                win_write_row(
                                    way, wfs2, w,
                                    (cur & ~mmask) | (v & mmask))
                            else:
                                cur = srow(memr, w)
                                wrow(memr, w,
                                     (cur & ~mmask) | (v & mmask))

                if optimistic:
                    _ea0, oob0, u, shB = opt_addr_prolog(
                        ea, off, 16, c[6])
                    if mem_hbm:
                        rhi = jnp.minimum(u + 4, W - 1)
                        dirty, snapped, way, wfs2 = _opt_window(
                            c, u, rhi)
                        commit(u, shB, ~dirty & ~oob0, (way, wfs2))
                        nwd0 = jnp.where(way == 0, I32(1), wfs2[1])
                        nwd1 = jnp.where(way == 1, I32(1), wfs2[3])
                        c2 = keep(c, wb0=wfs2[0], wd0=nwd0,
                                  wb1=wfs2[2], wd1=nwd1, mru=wfs2[4],
                                  ls=jnp.where(snapped, c[0],
                                               c[IDX["ls"]]))
                        return lax.cond(
                            dirty, rolled_carry,
                            lambda: lax.cond(
                                oob0,
                                lambda: keep(c2,
                                             status=I32(ST_DIVERGED)),
                                lambda: keep(c2, pc=pc + 1,
                                             sp=sp - 2)))
                    commit(u, shB, ~oob0, None)
                    return lax.cond(
                        oob0,
                        lambda: keep(c, status=I32(ST_DIVERGED)),
                        lambda: keep(c, pc=pc + 1, sp=sp - 2))
                carry_ = u_lt(ea, addr) | u_lt(ea, full(off))
                end = ea + 16
                mem_bytes = c[6] * I32(65536)
                oob = carry_ | u_lt(end, ea) | u_lt(mem_bytes, end)
                widx = jnp.clip(lax.shift_right_logical(ea, 2),
                                0, W - 1)
                shBv = (ea & 3) * 8
                u0 = scal(widx)
                ok = allsame(widx, u0) & allsame(shBv, scal(shBv)) & \
                    ~jnp.any(oob)
                shB = scal(shBv)
                if mem_hbm:
                    rhi = jnp.minimum(u0 + 4, W - 1)
                    way, wfs = _win_select(_wfs_of(c), u0, rhi, ok)
                    commit(u0, shB, ok, (way, wfs))
                    nwd0 = jnp.where(ok & (way == 0), I32(1), wfs[1])
                    nwd1 = jnp.where(ok & (way == 1), I32(1), wfs[3])
                    c2 = keep(c, wb0=wfs[0], wd0=nwd0, wb1=wfs[2],
                              wd1=nwd1, mru=wfs[4])
                else:
                    commit(u0, shB, ok, None)
                    c2 = c
                return lax.cond(
                    ok,
                    lambda: keep(c2, pc=pc + 1, sp=sp - 2),
                    lambda: keep(c2, status=I32(ST_DIVERGED)))

        base_handlers = {
            H_NOP: h_nop, H_CONST: h_const, H_LOCAL_GET: h_local_get,
            H_LOCAL_SET: h_local_set, H_LOCAL_TEE: h_local_tee,
            H_GLOBAL_GET: h_global_get, H_GLOBAL_SET: h_global_set,
            H_DROP: h_drop, H_SELECT: h_select, H_BR: h_br, H_BRZ: h_brz,
            H_BRNZ: h_brnz, H_BR_TABLE: h_br_table, H_RETURN: h_return,
            H_CALL: h_call, H_CALL_INDIRECT: h_call_indirect,
            H_MEMSIZE: h_memsize, H_MEMGROW: h_memgrow, H_TRAP: h_trap,
            H_LOAD: h_load, H_STORE: h_store, H_HOSTCALL: h_hostcall,
            H_MEMFILL: h_memfill, H_MEMCOPY: h_memcopy,
        }

        def handler_for(hid):
            if hid >= H_BLOCK_BASE:
                return mk_block(block_shapes[hid - H_BLOCK_BASE])
            if simd and hid >= H_VCONST:
                if hid >= H_VREPLACE_BASE:
                    return mk_vreplace(hid - H_VREPLACE_BASE)
                if hid >= H_VEXTRACT_BASE:
                    return mk_vextract(hid - H_VEXTRACT_BASE)
                if hid >= H_VSPLAT_BASE:
                    return mk_vsplat(hid - H_VSPLAT_BASE)
                if hid >= H_VSHIFT_BASE:
                    return mk_vshift(hid - H_VSHIFT_BASE)
                if hid >= H_VTEST_BASE:
                    return mk_vtest(hid - H_VTEST_BASE)
                if hid >= H_V1_BASE:
                    return mk_v1(hid - H_V1_BASE)
                if hid >= H_V2_BASE:
                    return mk_v2(hid - H_V2_BASE)
                return {H_VCONST: h_vconst, H_VSHUFFLE: h_vshuffle,
                        H_VBITSEL: h_vbitsel, H_VLOAD: h_vload,
                        H_VSTORE: h_vstore}[hid]
            if hid in (H_LOAD_W, H_LOAD_D, H_STORE_W, H_STORE_D):
                # width-specialized paths exist for the hbm+optimistic
                # kernel; everywhere else they alias the generic ops
                if mem_hbm and optimistic:
                    return {H_LOAD_W: h_load_w, H_LOAD_D: h_load_d,
                            H_STORE_W: h_store_w,
                            H_STORE_D: h_store_d}[hid]
                return h_load if hid in (H_LOAD_W, H_LOAD_D) else h_store
            if hid == H_FUSE_GBR:
                return h_fuse_gbr
            if hid >= H_FUSE_GGBNZ_BASE:
                return mk_fuse_ggbnz(hid - H_FUSE_GGBNZ_BASE)
            if hid >= H_FUSE_GGBZ_BASE:
                return mk_fuse_ggbz(hid - H_FUSE_GGBZ_BASE)
            if hid >= H_FUSE_GGS_BASE:
                return mk_fuse_ggs(hid - H_FUSE_GGS_BASE)
            if hid >= H_FUSE_GGA_BASE:
                return mk_fuse_gga(hid - H_FUSE_GGA_BASE)
            if hid >= H_FUSE_GCS_BASE:
                return mk_fuse_gcs(hid - H_FUSE_GCS_BASE)
            if hid >= H_FUSE_GCC_BASE:
                return mk_fuse_gcc(hid - H_FUSE_GCC_BASE)
            if hid >= H_FUSE_A2R_BASE:
                return mk_fuse_a2r(hid - H_FUSE_A2R_BASE)
            if hid >= H_FUSE_GCB_BASE:
                return mk_fuse_gcb(hid - H_FUSE_GCB_BASE)
            if hid >= H_FUSE_GCA_BASE:
                return mk_fuse_gca(hid - H_FUSE_GCA_BASE)
            if hid >= H_ALU1_BASE:
                return mk_alu1(hid - H_ALU1_BASE)
            if hid >= H_ALU2_BASE:
                return mk_alu2(hid - H_ALU2_BASE)
            return base_handlers[hid]

        handlers = [handler_for(h) for h in used_hids]

        def dispatch(hid, c):
            """Weight-balanced binary tree of lax.cond over the dense
            handler ids.  Mosaic lowers lax.switch to a LINEAR if-chain
            (~15ns per position walked), so the tree keeps dispatch at
            ~log branches; splitting on cumulative STATIC OPCODE
            FREQUENCY instead of id count puts the handlers that
            actually run at shallow depth (expected depth approaches
            the hid distribution's entropy — a concatenated
            multi-tenant image with dozens of live handlers gains the
            most).  Bit-exact vs lax.switch; plain midpoint split when
            no weights are known."""
            w = list(hid_weights) if hid_weights else [1] * len(handlers)

            def tree(lo, hi):
                if hi - lo == 1:
                    return handlers[lo](c)
                total = sum(w[lo:hi])
                best_mid, best_bal, acc = lo + 1, None, 0
                for m in range(lo + 1, hi):
                    acc += w[m - 1]
                    bal = abs(2 * acc - total)
                    if best_bal is None or bal < best_bal:
                        best_bal, best_mid = bal, m
                mid = best_mid
                return lax.cond(hid < mid,
                                lambda: tree(lo, mid),
                                lambda: tree(mid, hi))
            return tree(0, len(handlers))

        def cond(c):
            return (c[0] < chunk_eff) & (c[7] == ST_RUNNING)

        def body(c):
            pc = jnp.clip(c[1], 0, code_len - 1)
            nc = dispatch(hid_r[pc], c)
            # un-advanced stops rewind the step count (the next engine
            # re-executes the instruction): divergence, regrow, and
            # optimistic rollbacks (whose steps were already rewound)
            counted = jnp.where((nc[7] == I32(ST_DIVERGED)) |
                                (nc[7] == I32(ST_REGROW)) |
                                (nc[7] == I32(ST_RECHECK)), I32(0), I32(1))
            nc = (nc[0] + counted,) + nc[1:]
            if not optimistic:
                return nc
            # periodic commit: one canary validation + snapshot per
            # snap_steps dispatches (the whole point — per-step
            # cross-lane reductions become per-interval).  The FIRST
            # interval after launch is short: genuinely divergent blocks
            # (mixed entries the scheduler could not group) diverge
            # within a few hundred steps, and a short first window
            # bounds the optimistic run-up their rollback discards.
            interval = jnp.where(nc[IDX["ls"]] == 0,
                                 jnp.minimum(I32(min(512, snap_steps)),
                                             snap_dyn),
                                 snap_dyn)
            due = ((nc[0] - nc[IDX["ls"]]) >= interval) & \
                (nc[7] == I32(ST_RUNNING))

            @pl.when(due)
            def _():
                flag[0] = jnp.any(srow(canr, 0) != 0).astype(jnp.int32)

            dirty = due & (flag[0] != 0)
            clean = due & ~dirty

            @pl.when(dirty)
            def _():
                do_restore()

            if mem_hbm:
                # publish dirty windows before the snapshot so the HBM
                # plane IS the snapshot's memory state
                @pl.when(clean & (nc[IDX["wd0"]] != 0))
                def _():
                    _wb_way0(nc[IDX["wb0"]])

                @pl.when(clean & (nc[IDX["wd1"]] != 0))
                def _():
                    _wb_way1(nc[IDX["wb1"]])

            @pl.when(clean)
            def _():
                do_snapshot(nc)

            out = []
            for i, name in enumerate(_CARRY):
                v = nc[i]
                if name == "ls":
                    v = jnp.where(clean, nc[0], v)
                elif mem_hbm and name in ("wd0", "wd1"):
                    v = jnp.where(clean, I32(0), v)
                out.append(v)
            rolled = rolled_carry()
            return tuple(jnp.where(dirty, r, v)
                         for r, v in zip(rolled, out))

        init = (I32(0), ctrl_r[blk, _C_PC], ctrl_r[blk, _C_SP],
                ctrl_r[blk, _C_FP], ctrl_r[blk, _C_OB], ctrl_r[blk, _C_CD],
                ctrl_r[blk, _C_PAGES], ctrl_r[blk, _C_STATUS])
        if mem_hbm:
            # window cache starts invalid each launch (host serving and
            # SIMT handoffs mutate the HBM plane between launches)
            init = init + (I32(-(1 << 30)), I32(0),
                           I32(-(1 << 30)), I32(0), I32(0))
        if optimistic:
            init = init + (I32(0),)  # ls: last-snapshot step count
            # entry state was validated at the previous exit: it IS the
            # first rollback point
            wrow(canr, 0, full(0))
            do_snapshot(init)
        fin = lax.while_loop(cond, body, init)
        if optimistic:
            # exit validation: every path out of the loop (chunk/fuel
            # exhaustion, DONE, trap, park, diverge) must not publish
            # state built on an unvalidated lane-0 decision
            flag[0] = jnp.any(srow(canr, 0) != 0).astype(jnp.int32)
            pdirty = flag[0] != 0

            @pl.when(pdirty)
            def _():
                do_restore()

            rolledf = rolled_carry()
            fin = tuple(jnp.where(pdirty, r, v)
                        for r, v in zip(rolledf, fin))
        steps, pc, sp, fp, ob, cd, pages, status = fin[:8]
        if mem_hbm:
            # commit dirty windows so the HBM plane is coherent for the
            # host/SIMT on every exit path (done, parked, diverged)
            wb0f, wd0f, wb1f, wd1f = fin[8], fin[9], fin[10], fin[11]

            @pl.when(wd0f != 0)
            def _():
                _wb_way0(wb0f)

            @pl.when(wd1f != 0)
            def _():
                _wb_way1(wb1f)
        exhausted = (status == I32(ST_RUNNING)) & (steps >= fuel_in)
        status = jnp.where(
            exhausted,
            I32(ST_TRAPPED_BASE) + I32(int(ErrCode.CostLimitExceeded)),
            status)

        @pl.when(exhausted)
        def _():
            tr_ = srow(trapr, 0)
            wrow(trapr, 0, jnp.where(tr_ == 0,
                                     I32(int(ErrCode.CostLimitExceeded)),
                                     tr_))

        # the disabled-fuel sentinel must not drift down across launches
        # (a >2^31-step run would spuriously exhaust it)
        ctrl_out[blk, _C_FUEL] = jnp.where(fuel_in == I32(_FUEL_OFF),
                                           fuel_in, fuel_in - steps)
        ctrl_out[blk, _C_PC] = pc
        ctrl_out[blk, _C_SP] = sp
        ctrl_out[blk, _C_FP] = fp
        ctrl_out[blk, _C_OB] = ob
        ctrl_out[blk, _C_CD] = cd
        ctrl_out[blk, _C_STATUS] = status
        ctrl_out[blk, _C_PAGES] = pages
        ctrl_out[blk, _C_CHUNK] = chunk
        ctrl_out[blk, _C_STEPS] = steps
        ctrl_out[blk, _C_SNAP] = snap_in

        outs = [dma(0, slo, lslice(s_lo_out)),
                dma(1, shi, lslice(s_hi_out)),
                dma(2, glo, lslice(g_lo_out)),
                dma(3, ghi, lslice(g_hi_out)),
                dma(5, trapr, lslice(trap_out))]
        if not mem_hbm:
            outs.append(dma(4, memr, lslice(mem_out)))
        if simd:
            outs += [dma(6, se2s, lslice(se2_out)),
                     dma(7, se3s, lslice(se3_out))]
        for c in outs:
            c.start()
        for c in outs:
            c.wait()

    def aspec():
        return pl.BlockSpec(memory_space=pl.ANY)

    # shadow (rollback) plane geometry: full-size whenever the ENGINE
    # is optimistic (its careful recheck kernel shares the same state
    # list, so both kernels must declare the same shadow shapes); a
    # careful-only engine degenerates them to placeholders (no HBM
    # doubling).
    if shadow_full is None:
        shadow_full = optimistic
    SH_D = D if shadow_full else 1
    SH_NG = NGp if shadow_full else 1
    SH_L = L if shadow_full else 1
    WSH = (W if (not mem_hbm and W > 1) else 1) if shadow_full else 1
    n_planes = 12 + (4 if simd else 0)  # aliased plane inputs/outputs

    def vmem_rows(n):
        """VMEM scratch holding n state rows in the active row layout."""
        return pltpu.VMEM((n,) + ROW if three_d else (n, Lblk), jnp.int32)

    def p3(shape):
        """Out-shape for an HBM plane: striped 3-d iff it is a full
        lane plane (shape[-1] == L) and the remap is active."""
        if three_d and shape[-1] == L:
            return (shape[0], L // Lpb, Lpb)
        return shape
    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=15,
        grid=(nblk,),
        in_specs=(
            [pl.BlockSpec(memory_space=pltpu.SMEM)]     # frames_in
            + [aspec()] * n_planes),                    # planes (HBM)
        out_specs=(
            [pl.BlockSpec(memory_space=pltpu.SMEM),     # ctrl_out
             pl.BlockSpec(memory_space=pltpu.SMEM)]     # frames_out
            + [aspec()] * n_planes),
        scratch_shapes=(
            [vmem_rows(D),                              # slo
             vmem_rows(D)]                              # shi
            + ([vmem_rows(D),                           # se2 (v128)
                vmem_rows(D)]                           # se3 (v128)
               if simd else [])
            + [vmem_rows(NGp),                          # glo
               vmem_rows(NGp)]                          # ghi
            + ([vmem_rows(CW),                          # mwin0 (way 0)
                vmem_rows(CW)]                          # mwin1 (way 1)
               if mem_hbm else
               [vmem_rows(W)])                          # memr (resident)
            + [vmem_rows(1),                            # trapr
               pltpu.SemaphoreType.DMA((8,))]           # sems
            + ([vmem_rows(1),                           # canr (canary)
                pltpu.SMEM((2,), jnp.int32),            # flag
                pltpu.SMEM((3, CD), jnp.int32),         # snapf (frames)
                pltpu.SMEM((16,), jnp.int32)]           # snapc (carry)
               if optimistic else [])
        ),
    )
    out_shape = [
        jax.ShapeDtypeStruct((nblk, 16), jnp.int32),    # ctrl
        jax.ShapeDtypeStruct((nblk, 3, CD), jnp.int32),  # frames
        jax.ShapeDtypeStruct(p3((D, L)), jnp.int32),    # stack_lo
        jax.ShapeDtypeStruct(p3((D, L)), jnp.int32),    # stack_hi
        jax.ShapeDtypeStruct(p3((NGp, L)), jnp.int32),  # glob_lo
        jax.ShapeDtypeStruct(p3((NGp, L)), jnp.int32),  # glob_hi
        jax.ShapeDtypeStruct(p3((W, L)), jnp.int32),    # mem
        jax.ShapeDtypeStruct(p3((1, L)), jnp.int32),    # trap
        jax.ShapeDtypeStruct(p3((SH_D, SH_L)), jnp.int32),   # sh_slo
        jax.ShapeDtypeStruct(p3((SH_D, SH_L)), jnp.int32),   # sh_shi
        jax.ShapeDtypeStruct(p3((SH_NG, SH_L)), jnp.int32),  # sh_glo
        jax.ShapeDtypeStruct(p3((SH_NG, SH_L)), jnp.int32),  # sh_ghi
        jax.ShapeDtypeStruct(p3((1, SH_L)), jnp.int32),      # sh_trap
        jax.ShapeDtypeStruct(p3((WSH, SH_L)), jnp.int32),    # sh_mem
    ]
    if simd:
        out_shape += [
            jax.ShapeDtypeStruct(p3((D, L)), jnp.int32),     # stack_e2
            jax.ShapeDtypeStruct(p3((D, L)), jnp.int32),     # stack_e3
            jax.ShapeDtypeStruct(p3((SH_D, SH_L)), jnp.int32),  # sh_se2
            jax.ShapeDtypeStruct(p3((SH_D, SH_L)), jnp.int32),  # sh_se3
        ]
    # plane inputs (operands: 15 prefetch args, frames_in at 15, planes
    # from 16) alias the plane outputs (after ctrl/frames)
    aliases = {16 + k: 2 + k for k in range(n_planes)}
    # jax renamed TPUCompilerParams -> CompilerParams around 0.5; accept
    # both so the kernel builds across the supported range
    _CParams = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    fn = pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
        compiler_params=_CParams(
            dimension_semantics=("arbitrary",)),
    )
    if not three_d:
        return jax.jit(fn, donate_argnums=tuple(
            range(16, 16 + n_planes)))

    # The remap wrapper: the host/engine keep every plane [rows, L];
    # stripe-reshape to [rows, L/Lpb, Lpb] around the pallas_call (a
    # bitcast — XLA aliases it, so donation still runs in place).
    def run(*args):
        pre = args[:16]                    # 15 prefetch + frames_in
        planes = args[16:]
        p3s = [x.reshape(x.shape[0], -1, Lpb) if x.shape[-1] == L else x
               for x in planes]
        out = fn(*pre, *p3s)
        res = [out[0], out[1]]
        for x in out[2:]:
            res.append(x.reshape(x.shape[0], -1) if x.ndim == 3 else x)
        return tuple(res)

    return jax.jit(run, donate_argnums=tuple(
        range(16, 16 + n_planes)))


def pallas_enabled(cfg) -> bool:
    """One policy for whether the Pallas fast path is on: the explicit
    `use_pallas` knob wins; unset means TPU-backend auto-detect; and
    `interpret=True` opts in on CPU (tests).  Shared by the uniform and
    multi-tenant engines so they can never disagree."""
    use = cfg.use_pallas
    if use is None:
        from wasmedge_tpu.batch import ensure_jax_backend

        ensure_jax_backend()
        import jax

        use = jax.default_backend() == "tpu"
    return bool(use or cfg.interpret)


class PallasUniformEngine:
    """Block-converged engine running the dispatch loop on-device.

    Wraps the SIMT engine for divergence fallback exactly like
    UniformBatchEngine; the difference is the converged fast path runs as a
    Pallas kernel (one launch per `steps_per_launch` instructions) instead
    of per-step XLA, and convergence is only required within a lane block."""

    # geometry knobs (state must fit VMEM; ~16 MiB/core on v5e)
    MAX_CODE_LEN = MAX_CODE_LEN  # module-level constant, shared with aot
    # Per-block VMEM scratch budget (1x state size: state planes stay in
    # HBM and are DMA'd into scratch per lane block; ~2 MiB headroom is
    # left for gather-chunk temporaries and compiler spill).
    VMEM_BUDGET_BYTES = 9 * 1024 * 1024
    # Divergent-address loads/stores scan the whole [W, Lblk] memory block
    # (compare-reduce); cap that scan's size, not W alone — one wasm page
    # is already 16384 words.
    MAX_GATHER_ELEMS = 4 * 1024 * 1024
    # Window-cache rows per way in mem_hbm mode (2 ways).  128 rows =
    # 512 B of guest memory per lane per way; misses move CW×Lblk words
    # over DMA, so sequential access amortizes one miss over ~CW rows.
    HBM_WINDOW_ROWS = 128
    # Optimistic-convergence commit interval: dispatches between canary
    # validations/snapshots.  Bounds both the validation amortization
    # and the worst-case replay a rollback hands the careful kernel.
    # Snapshot cadence of the optimistic kernel.  Measured r05 (one
    # v5e chip, 4096 lanes): raising 8192 -> 131072 moved flagship
    # fib(30) 56 -> ~70-74G instr/s and the memory-heavy mix 29 -> 49G
    # (snapshot DMA was ~25% of wall), with the divergent mix flat.
    # Worst case a block that ran clean past its FIRST short window
    # (512 steps — genuinely divergent blocks diverge inside it) and
    # diverges late discards + carefully re-executes up to this many
    # steps ONCE (~0.2 s at 4096 lanes); its per-block interval then
    # halves adaptively (careful_recheck) down to _SNAP_MIN, so
    # repeated rollbacks are geometrically cheaper.
    SNAP_STEPS = 131072

    def __init__(self, inst, store=None, conf=None, lanes=None, mesh=None,
                 interpret=None, simt=None):
        from wasmedge_tpu.batch.engine import BatchEngine

        self.simt = simt if simt is not None else BatchEngine(
            inst, store=store, conf=conf, lanes=lanes, mesh=mesh)
        self.inst = inst
        self.cfg = self.simt.cfg
        self.lanes = self.simt.lanes
        self.img = self.simt.img
        self.obs = self.simt.obs  # shared flight recorder (obs/)
        self.interpret = interpret
        opt = getattr(self.cfg, "optimistic", None)
        self.optimistic = True if opt is None else bool(opt)
        self._fn = None
        self._fn_careful_cache = None
        self._tables = None
        self._blk_cap = None  # lane-block ceiling (multi-tenant alignment)
        self.fell_back_to_simt = False
        self.splits = 0  # block-scheduler split count from the last run()
        self.recheck_rounds = 0  # careful-kernel rounds (optimistic mode)
        # None = no tpu.aot fused section attached; set by _build when a
        # loaded artifact carries one (True = matched regeneration)
        self.aot_fused_verified = None
        # per-lane page counts recorded when a host outcall grows memory
        # (block ctrl keeps one uniform count; growth diverges the block)
        self._pages_override = {}
        self.ineligible_reason = self._eligibility()

    # -- geometry / eligibility -------------------------------------------
    def _interpret(self) -> bool:
        if self.interpret is not None:
            return self.interpret
        from wasmedge_tpu.batch import ensure_jax_backend

        ensure_jax_backend()
        import jax

        return jax.default_backend() == "cpu"

    def _depths(self):
        # The configured depths are honored exactly — same trap thresholds
        # as the XLA engines' _do_call; _lane_block gates whether they fit
        # VMEM (ineligible -> XLA fallback), never silently shrinks them.
        return self.cfg.value_stack_depth, self.cfg.call_stack_depth

    def _mem_words(self):
        # Watermark sizing (SURVEY §5.7): the VMEM plane covers *current*
        # pages, not the declared max — a module declaring max=16 pages
        # but touching one keeps a small state and a big lane block.
        # memory.grow beyond this capacity (but within the declared max)
        # raises ST_REGROW and the host re-executes on a bigger plane.
        img = self.img
        if not img.has_memory:
            return 1
        return max(img.mem_pages_init, 1) * _PAGE_WORDS

    def _state_bytes_per_lane(self, mem_hbm: bool) -> int:
        D, CD = self._depths()
        NGp = max(self.img.globals_lo.shape[0], 1)
        memw = 2 * self.HBM_WINDOW_ROWS if mem_hbm else self._mem_words()
        # v128 modules carry 4 stack planes (lo/hi/e2/e3) in scratch
        nstack = 4 if self.img.has_simd else 2
        return 4 * (nstack * D + 2 * NGp + memw + 1)

    def _blk_for(self, per_lane: int) -> Optional[int]:
        """Largest power-of-two lane block whose state fits the budget."""
        # Mosaic requires lane-dim slices aligned to the 128-lane tiling;
        # interpret mode (CPU tests) has no such constraint.
        align = 1 if self._interpret() else 128
        cap = self._blk_cap or self.lanes
        # start at the cap: the scheduler's lane totals need not be a
        # power of two (nblk * Lblk with arbitrary nblk), so halving from
        # self.lanes would walk past the intended block size
        blk = min(self.lanes, cap)

        def bad(k):
            return (k * per_lane > self.VMEM_BUDGET_BYTES
                    or self.lanes % k != 0 or k > cap or k % align != 0)

        while blk > align and bad(blk):
            blk //= 2
        if bad(blk):
            return None
        return blk

    def _mem_mode(self) -> bool:
        """True when the kernel should keep the memory plane HBM-resident
        behind the window cache (bigger lane blocks, DMA on window miss)
        instead of staging the whole [W, Lblk] slab into VMEM scratch
        (zero-latency access, 128-ish lane blocks).  Auto rule: pick HBM
        whenever it strictly enlarges the lane block; cfg.mem_hbm forces
        either way (tests, experiments)."""
        if not self.img.has_memory:
            return False
        if self._mem_words() < self.HBM_WINDOW_ROWS:
            return False
        blk_hbm = self._blk_for(self._state_bytes_per_lane(True))
        forced = getattr(self.cfg, "mem_hbm", None)
        if forced is not None:
            return bool(forced) and blk_hbm is not None
        if blk_hbm is None:
            return False
        blk_res = self._blk_for(self._state_bytes_per_lane(False))
        return blk_res is None or blk_hbm > blk_res

    def _lane_block(self) -> Optional[int]:
        return self._blk_for(self._state_bytes_per_lane(self._mem_mode()))

    def _eligibility(self) -> Optional[str]:
        img = self.img
        reason = pallas_image_eligibility(img, self.MAX_CODE_LEN)
        if reason is not None:
            return reason
        if self.simt.mesh is not None:
            return "mesh sharding handled by SIMT engine"
        if self.cfg.fuel_per_launch is not None and \
                self.cfg.cost_table is not None and \
                any(c != 1 for c in self.cfg.cost_table):
            return "per-opcode cost-table gas handled by SIMT engine"
        if self._lane_block() is None:
            return (f"state too large for VMEM "
                    f"({self._mem_words()} mem words/lane)")
        return None

    @property
    def eligible(self) -> bool:
        return self.ineligible_reason is None

    # -- build ------------------------------------------------------------
    def _build(self):
        from wasmedge_tpu.batch import ensure_jax_backend

        ensure_jax_backend()
        import jax
        import jax.numpy as jnp

        img = self.img
        interpret = self._interpret()
        hid = hid_plane(img)
        a_p, b_p, c_p = img.a, img.b, img.c
        ilo_p, ihi_p = img.imm_lo, img.imm_hi
        bf = getattr(self.cfg, "block_fusion", None)
        self.block_fusion = True if bf is None else bool(bf)
        if self.block_fusion:
            hid, block_shapes = fuse_blocks(hid, img)
        else:
            block_shapes = ()
            if not img.has_simd:
                # the legacy peephole superinstructions move only the
                # lo/hi planes of kept values, which would truncate
                # v128 cells — simd modules run unfused on this path
                hid, a_p, b_p, c_p, ilo_p, ihi_p = fuse_image(
                    hid, a_p, b_p, c_p, ilo_p, ihi_p, img)
        # tpu.aot artifacts carry the fused encoding.  Verification IS
        # regeneration (cheap next to XLA compilation); once verified,
        # the attached planes are the ones executed — a stale or
        # tampered section is detected here and never runs.
        attached = getattr(self.inst.lowered, "fused", None)
        if attached is not None:
            self.aot_fused_verified = all(
                getattr(attached[k], "dtype", None) == v.dtype
                and np.array_equal(attached[k], v)
                for k, v in (("hid", hid), ("a", a_p), ("b", b_p),
                             ("c", c_p), ("ilo", ilo_p), ("ihi", ihi_p)))
            if self.aot_fused_verified:
                hid, a_p, b_p, c_p, ilo_p, ihi_p = (
                    attached["hid"], attached["a"], attached["b"],
                    attached["c"], attached["ilo"], attached["ihi"])
        used = tuple(sorted(set(int(h) for h in hid)))
        dense = {h: i for i, h in enumerate(used)}
        hid_dense = np.asarray([dense[int(h)] for h in hid], np.int32)
        # static frequency of each dense handler id: the dispatch tree
        # splits on cumulative weight, so hot handlers sit shallow
        self._hid_weights = tuple(
            int(c) for c in np.bincount(hid_dense,
                                        minlength=len(used)))
        # host-side view of the fused encoding: the block scheduler's
        # divergence splitter evaluates the stopped instruction from
        # these.  _np_hid_orig is the UNfused plane: a block whose
        # first op bails leaves pc at the head (hid = block id), but
        # its operand fields are the original op's, so the splitter
        # resolves it via the original opcode.
        self._np_fused = {"hid": hid, "a": a_p, "b": b_p, "c": c_p,
                          "ilo": ilo_p, "ihi": ihi_p}
        self._np_hid_orig = hid_plane(img)
        D, CD = self._depths()
        W = self._mem_words()
        NG = img.globals_lo.shape[0]
        Lblk = self._lane_block()
        pages_cap = W // _PAGE_WORDS if img.has_memory else 0
        pages_hard = max(img.mem_pages_max, img.mem_pages_init) \
            if img.has_memory else 0
        mem_hbm = self._mem_mode()
        self._geom = (D, CD, W, Lblk)
        v128_t = np.asarray(img.v128, np.int32)
        self._kargs = (
            used, D, CD, W, self.lanes, Lblk, NG, img.code_len,
            len(img.f_entry), img.table0.shape[0],
            img.max_local_zeros, pages_cap, pages_hard,
            (not mem_hbm) and W * Lblk <= self.MAX_GATHER_ELEMS,
            interpret, mem_hbm,
            self.HBM_WINDOW_ROWS if mem_hbm else 0,
            block_shapes, bool(img.has_simd), v128_t.shape[0])
        self._tables = tuple(jnp.asarray(t) for t in (
            hid_dense, a_p, b_p, c_p, ilo_p, ihi_p,
            img.f_entry, img.f_nparams, img.f_nlocals, img.f_frame_top,
            img.f_type, img.br_table.reshape(-1), img.table0, v128_t))
        self._fn = self._with_export_cache(
            lambda: _build_kernel(*self._kargs,
                                  optimistic=self.optimistic,
                                  snap_steps=self.SNAP_STEPS,
                                  shadow_full=self.optimistic,
                                  hid_weights=self._hid_weights))
        self._fn_careful_cache = None if self.optimistic else self._fn

    def _export_cache_key(self):
        """Content key for the serialized compiled kernel: geometry +
        fused-plane hash + backend + jax version (the reference keys its
        AOT cache on the wasm bytes, lib/aot/cache.cpp:36-61; here the
        kernel is a function of the fused encoding and geometry)."""
        import hashlib

        import jax

        import inspect

        h = hashlib.sha256()
        # the kernel SOURCE is part of the key: any edit to the kernel
        # body must invalidate previously exported artifacts.  The
        # traced kernel also inlines helpers from sibling modules
        # (laneops alu/shift/mul emulation, image opcode encodings,
        # softfloat, simdops) — a semantic change there must invalidate
        # too, so hash the whole modules, not just this file.
        h.update(inspect.getsource(_build_kernel).encode())
        import wasmedge_tpu.batch.image as _image_mod
        import wasmedge_tpu.batch.laneops as _laneops_mod
        import wasmedge_tpu.batch.simdops as _simdops_mod
        import wasmedge_tpu.batch.softfloat as _softfloat_mod
        for _m in (_laneops_mod, _softfloat_mod, _simdops_mod, _image_mod):
            h.update(inspect.getsource(_m).encode())
        h.update(repr(self._kargs).encode())
        h.update(repr((self.optimistic, self.SNAP_STEPS)).encode())
        for k in ("hid", "a", "b", "c", "ilo", "ihi"):
            h.update(np.ascontiguousarray(self._np_fused[k]).tobytes())
        h.update(jax.__version__.encode())
        h.update(jax.default_backend().encode())
        return h.hexdigest()

    def _with_export_cache(self, build):
        """Warm-start path: persist the traced+lowered kernel via
        jax.export so a fresh process skips Python/Pallas tracing (the
        ~2s `engine_build` phase in AOT_r04.json); XLA's persistent
        compilation cache already covers the compile itself.  Any
        failure falls back to a plain build — the cache is an
        optimization, never a correctness dependency."""
        import os

        if self._interpret():
            return build()  # interpret mode: nothing worth persisting
        try:
            import jax
            import jax.export as jexport

            from wasmedge_tpu.aot import cache_dir

            d = os.path.join(cache_dir(), "kexport")
            path = os.path.join(d, self._export_cache_key() + ".bin")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    exp = jexport.deserialize(bytearray(f.read()))
                return exp.call
            fn = build()
            specs = self._arg_specs()
            exp = jexport.export(fn)(*specs)
            os.makedirs(d, exist_ok=True)
            from wasmedge_tpu.utils.fsio import atomic_write_bytes

            atomic_write_bytes(path, exp.serialize())
            return exp.call
        except Exception:
            return build()

    def _arg_specs(self):
        """ShapeDtypeStructs matching (tables..., ctrl, frames, state)."""
        import jax

        D, CD, W, Lblk = self._geom
        L = self.lanes
        nblk = L // Lblk
        NGp = max(self.img.globals_lo.shape[0], 1)
        mem_hbm = self._mem_mode()
        wsh = (W if (not mem_hbm and W > 1) else 1) if self.optimistic \
            else 1
        i32 = jax.ShapeDtypeStruct
        import numpy as _np

        specs = [i32(t.shape, t.dtype) for t in self._tables]
        specs += [i32((nblk, 16), _np.int32),
                  i32((nblk, 3, CD), _np.int32),
                  i32((D, L), _np.int32), i32((D, L), _np.int32),
                  i32((NGp, L), _np.int32), i32((NGp, L), _np.int32),
                  i32((W, L), _np.int32), i32((1, L), _np.int32)]
        sh_l = L if self.optimistic else 1
        sh_d = D if self.optimistic else 1
        sh_ng = NGp if self.optimistic else 1
        specs += [i32((sh_d, sh_l), _np.int32),
                  i32((sh_d, sh_l), _np.int32),
                  i32((sh_ng, sh_l), _np.int32),
                  i32((sh_ng, sh_l), _np.int32),
                  i32((1, sh_l), _np.int32), i32((wsh, sh_l), _np.int32)]
        if self.img.has_simd:
            specs += [i32((D, L), _np.int32), i32((D, L), _np.int32),
                      i32((sh_d, sh_l), _np.int32),
                      i32((sh_d, sh_l), _np.int32)]
        return specs

    def _fn_careful(self):
        """The non-optimistic kernel, compiled lazily on the first
        ST_RECHECK (most runs never diverge and never pay the compile)."""
        if self._fn_careful_cache is None:
            self._fn_careful_cache = _build_kernel(
                *self._kargs, optimistic=False,
                snap_steps=self.SNAP_STEPS, shadow_full=self.optimistic,
                hid_weights=self._hid_weights)
        return self._fn_careful_cache

    def shadow_planes(self):
        """Fresh rollback-shadow planes matching this geometry (appended
        to the kernel state list; contents only matter intra-launch)."""
        import jax.numpy as jnp

        D, CD, W, Lblk = self._geom
        z = jnp.zeros
        if not self.optimistic:
            # careful-only kernel: placeholder shadows
            return [z((1, 1), jnp.int32) for _ in range(5)] + \
                [z((1, 1), jnp.int32)]
        L = self.lanes
        NGp = max(self.img.globals_lo.shape[0], 1)
        wsh = W if (not self._mem_mode() and W > 1) else 1
        return [z((D, L), jnp.int32), z((D, L), jnp.int32),
                z((NGp, L), jnp.int32), z((NGp, L), jnp.int32),
                z((1, L), jnp.int32), z((wsh, L), jnp.int32)]

    def _shadow_simd_planes(self):
        """Rollback shadows for the v128 e2/e3 planes (appended after
        them at the end of the state list)."""
        import jax.numpy as jnp

        D = self._geom[0]
        if not self.optimistic:
            return [jnp.zeros((1, 1), jnp.int32),
                    jnp.zeros((1, 1), jnp.int32)]
        return [jnp.zeros((D, self.lanes), jnp.int32),
                jnp.zeros((D, self.lanes), jnp.int32)]

    # -- state ------------------------------------------------------------
    def _from_simt_state(self, simt_state):
        """Build pallas-geometry state from a block-uniform SIMT state
        (every control scalar identical within each lane block) — the
        multi-tenant entry path: tenants occupy whole blocks, so their
        heterogeneous entries are per-block ctrl rows."""
        import jax.numpy as jnp

        D, CD, W, Lblk = self._geom
        L = self.lanes
        nblk = L // Lblk
        pc = np.asarray(simt_state.pc)
        sp = np.asarray(simt_state.sp)
        fp = np.asarray(simt_state.fp)
        ob = np.asarray(simt_state.opbase)
        cd = np.asarray(simt_state.call_depth)
        pages = np.asarray(simt_state.mem_pages)
        if (cd != 0).any():
            # the converter drops the SIMT frame planes; entering with
            # live frames would corrupt the first return
            raise ValueError("cannot enter the pallas engine mid-call "
                            "(call_depth != 0)")
        fuel_v = np.asarray(simt_state.fuel)
        fuel_on = self.cfg.fuel_per_launch is not None
        ctrl = np.zeros((nblk, 16), np.int32)
        for b in range(nblk):
            sl = slice(b * Lblk, (b + 1) * Lblk)
            for col, vec in ((_C_PC, pc), (_C_SP, sp), (_C_FP, fp),
                             (_C_OB, ob), (_C_CD, cd), (_C_PAGES, pages)):
                seg = vec[sl]
                if not (seg == seg[0]).all():
                    raise ValueError(
                        f"block {b} not control-uniform; cannot enter the "
                        f"pallas engine")
                ctrl[b, col] = seg[0]
            if fuel_on:
                seg = fuel_v[sl]
                if not (seg == seg[0]).all():
                    raise ValueError(
                        f"block {b} fuel not uniform; cannot enter the "
                        f"pallas engine")
                ctrl[b, _C_FUEL] = seg[0]
            else:
                ctrl[b, _C_FUEL] = _FUEL_OFF
        cap_pages = W // _PAGE_WORDS
        if self.img.has_memory and (pages > cap_pages).any():
            raise ValueError(
                "state has grown beyond the watermark plane; cannot enter "
                "the pallas engine")
        ctrl[:, _C_CHUNK] = self.cfg.steps_per_launch
        stack_lo = np.asarray(simt_state.stack_lo)[:D]
        stack_hi = np.asarray(simt_state.stack_hi)[:D]
        mem = np.asarray(simt_state.mem)
        if mem.shape[0] < W:
            mem = np.concatenate(
                [mem, np.zeros((W - mem.shape[0], L), np.int32)], axis=0)
        mem = mem[:W]
        NGp = max(self.img.globals_lo.shape[0], 1)
        glo = np.asarray(simt_state.glob_lo)
        ghi = np.asarray(simt_state.glob_hi)
        if glo.shape[0] < NGp:
            pad = np.zeros((NGp - glo.shape[0], L), np.int32)
            glo = np.concatenate([glo, pad], axis=0)
            ghi = np.concatenate([ghi, pad], axis=0)
        trap = np.asarray(simt_state.trap)[None, :]
        state = [jnp.asarray(ctrl), jnp.zeros((nblk, 3, CD), jnp.int32),
                 jnp.asarray(stack_lo), jnp.asarray(stack_hi),
                 jnp.asarray(glo[:NGp]), jnp.asarray(ghi[:NGp]),
                 jnp.asarray(mem), jnp.asarray(trap)] + \
            self.shadow_planes()
        if self.img.has_simd:
            import jax.numpy as jnp2

            for plane in (simt_state.stack_e2, simt_state.stack_e3):
                p = np.asarray(plane)[:D] if plane is not None else \
                    np.zeros((D, L), np.int32)
                state.append(jnp2.asarray(p))
            state += self._shadow_simd_planes()
        return state

    def run_blocks(self, simt_state, max_steps: int = 10_000_000):
        """Run from a block-uniform SIMT state; returns (simt_state,
        steps_per_block, fell_back). Used by the multi-tenant engine."""
        if self._fn is None:
            self._build()
        state = self._from_simt_state(simt_state)
        self._pages_override = {}
        state, steps_per_block, statuses = self._drive(state, max_steps)
        fell_back = ((statuses == ST_DIVERGED) |
                     (statuses == ST_REGROW)).any()
        self.fell_back_to_simt = bool(fell_back)
        return (self._to_simt_state(state, steps_per_block),
                steps_per_block, bool(fell_back))

    def _drive(self, state, max_steps):
        """Launch loop: run chunks, serve host outcalls, stop when no
        block is runnable or max_steps is reached."""
        nblk = state[0].shape[0]
        steps_per_block = np.zeros(nblk, np.int64)
        while True:
            out = self._fn(*self._tables, state[0], state[1], *state[2:])
            state = list(out)
            ctrl_np = np.asarray(state[0])
            steps_per_block += ctrl_np[:, _C_STEPS].astype(np.int64)
            statuses = ctrl_np[:, _C_STATUS]
            if (statuses == ST_RECHECK).any():
                state, ctrl_np = self._run_recheck(state, ctrl_np)
                steps_per_block += ctrl_np[:, _C_STEPS].astype(np.int64)
                statuses = ctrl_np[:, _C_STATUS]
            else:
                # adaptive window growth: a launch with no rollback
                # doubles a shrunken snapshot interval back toward
                # SNAP_STEPS (careful_recheck is the halving side)
                snap = ctrl_np[:, _C_SNAP]
                if (snap > 0).any() and (snap < self.SNAP_STEPS).any():
                    import jax.numpy as jnp

                    ctrl_np = ctrl_np.copy()
                    ctrl_np[:, _C_SNAP] = np.where(
                        snap > 0,
                        np.minimum(snap * 2, self.SNAP_STEPS), snap)
                    state[0] = jnp.asarray(ctrl_np)
            if (statuses == ST_HOSTCALL).any() and \
                    int(steps_per_block.max()) < max_steps:
                state = self._serve_hostcalls(state, ctrl_np)
                continue
            if (statuses == ST_RUNNING).any() and \
                    int(steps_per_block.max()) < max_steps:
                continue
            return state, steps_per_block, statuses

    def careful_recheck(self, state, ctrl_np, recheck_mask):
        """ONE recheck protocol for both drive paths (engine._drive and
        BlockScheduler): re-run ST_RECHECK blocks on the careful kernel
        for one short chunk.  An optimistic rollback rewound them to
        their last validated snapshot; exact per-step checking reaches
        the divergent instruction and stops there with the precise
        status (DIVERGED/trap/...), after which normal handling
        proceeds.  Non-recheck blocks get chunk=0 (zero steps, state
        untouched).  Returns (state, ctrl_np) with saved chunk restored
        and non-recheck step counts zeroed so callers' accounting is
        exact."""
        import jax.numpy as jnp

        self.recheck_rounds += 1
        ctrl = ctrl_np.copy()
        saved_chunk = ctrl[:, _C_CHUNK].copy()
        # adaptive window: a block that just rolled back gets half its
        # snapshot interval next time (down to _SNAP_MIN), so the run-up
        # a genuinely divergent block discards shrinks geometrically;
        # clean launches grow it back (engine._drive / BlockScheduler)
        snap = np.where(ctrl[:, _C_SNAP] > 0, ctrl[:, _C_SNAP],
                        self.SNAP_STEPS)
        ctrl[:, _C_SNAP] = np.where(
            recheck_mask, np.maximum(snap // 2, _SNAP_MIN), snap)
        ctrl[:, _C_CHUNK] = np.where(recheck_mask, snap + 64, 0)
        ctrl[:, _C_STATUS] = np.where(recheck_mask, ST_RUNNING,
                                      ctrl[:, _C_STATUS])
        state[0] = jnp.asarray(ctrl)
        out = self._fn_careful()(*self._tables, state[0], state[1],
                                 *state[2:])
        state = list(out)
        ctrl = np.asarray(state[0]).copy()
        ctrl[:, _C_CHUNK] = saved_chunk
        # blocks that ran clean past the divergence window resume
        # optimistic on the next launch
        ctrl[:, _C_STEPS] = np.where(recheck_mask, ctrl[:, _C_STEPS], 0)
        state[0] = jnp.asarray(ctrl)
        return state, ctrl

    def _run_recheck(self, state, ctrl_np):
        recheck = ctrl_np[:, _C_STATUS] == ST_RECHECK
        return self.careful_recheck(state, ctrl_np, recheck)

    def _to_simt_state(self, state, steps_per_block):
        """Expand per-block scalars to the SIMT engine's per-lane layout."""
        import jax.numpy as jnp

        from wasmedge_tpu.batch.engine import BatchState

        cfg = self.cfg
        L = self.lanes
        D, CD, W, Lblk = self._geom
        ctrl = np.asarray(state[0])
        frames = np.asarray(state[1])
        nblk = ctrl.shape[0]
        D_s, CD_s = cfg.value_stack_depth, cfg.call_stack_depth

        def pad_rows(x, target):
            x = np.asarray(x)
            if x.shape[0] >= target:
                return x[:target]
            return np.concatenate(
                [x, np.zeros((target - x.shape[0], L), x.dtype)], axis=0)

        def lanes_of(col):
            return np.repeat(ctrl[:, col].astype(np.int32), Lblk)

        pages_v = lanes_of(_C_PAGES)
        for b, arr in self._pages_override.items():
            pages_v[b * Lblk:(b + 1) * Lblk] = arr

        trap_v = merge_block_status_into_trap(
            np.asarray(state[7])[0].copy(), ctrl, Lblk)
        fr = np.zeros((3, CD_s, L), np.int32)
        ncd = min(CD, CD_s)
        for b in range(nblk):
            fr[:, :ncd, b * Lblk:(b + 1) * Lblk] = \
                frames[b][:, :ncd, None]
        fuel_on = cfg.fuel_per_launch is not None
        retired = np.repeat(np.asarray(steps_per_block, np.int64), Lblk)
        fuel_v = np.maximum(lanes_of(_C_FUEL), 0) if fuel_on \
            else np.zeros(L, np.int32)
        # The SIMT engine's plane is sized by the declared/effective max,
        # not the watermark — pad rows so grow works over there.
        mem_np = np.asarray(state[6])
        simt_w = max(self.img.mem_pages_max * _PAGE_WORDS, 1) \
            if self.img.has_memory else mem_np.shape[0]
        if mem_np.shape[0] < simt_w:
            mem_np = np.concatenate(
                [mem_np, np.zeros((simt_w - mem_np.shape[0], L), np.int32)],
                axis=0)
        simd = self.img.has_simd
        from wasmedge_tpu.batch.engine import t0_state_planes

        return BatchState(
            **t0_state_planes(self.img, cfg, L,
                              getattr(self.simt, "_t0kinds", None)),
            pc=jnp.asarray(lanes_of(_C_PC)), sp=jnp.asarray(lanes_of(_C_SP)),
            fp=jnp.asarray(lanes_of(_C_FP)),
            opbase=jnp.asarray(lanes_of(_C_OB)),
            call_depth=jnp.asarray(lanes_of(_C_CD)),
            trap=jnp.asarray(trap_v),
            retired=jnp.asarray(retired.astype(np.int32)),
            fuel=jnp.asarray(fuel_v.astype(np.int32)),
            mem_pages=jnp.asarray(pages_v),
            stack_lo=jnp.asarray(pad_rows(state[2], D_s)),
            stack_hi=jnp.asarray(pad_rows(state[3], D_s)),
            fr_ret_pc=jnp.asarray(fr[0]), fr_fp=jnp.asarray(fr[1]),
            fr_opbase=jnp.asarray(fr[2]),
            glob_lo=jnp.asarray(np.asarray(state[4])),
            glob_hi=jnp.asarray(np.asarray(state[5])),
            mem=jnp.asarray(mem_np),
            stack_e2=jnp.asarray(pad_rows(state[14], D_s)) if simd
            else None,
            stack_e3=jnp.asarray(pad_rows(state[15], D_s)) if simd
            else None,
        )

    # -- run --------------------------------------------------------------
    def run(self, func_name: str, args_lanes: List,
            max_steps: int = 10_000_000):
        """Run through the block scheduler (batch/scheduler.py): entry
        grouping packs same-args lanes into the same blocks, data
        divergence splits blocks instead of abandoning the kernel, and
        only the genuinely per-lane residue finishes on SIMT."""
        ex = self.inst.exports.get(func_name)
        if ex is None or ex[0] != 0:
            raise KeyError(f"no exported function {func_name}")
        if not self.eligible:
            return self.simt.run(func_name, args_lanes, max_steps)
        from wasmedge_tpu.batch.scheduler import BlockScheduler

        sched = BlockScheduler(self, func_name, args_lanes, max_steps)
        sched.run()
        self.fell_back_to_simt = sched.fell_back_to_simt
        self.splits = sched.splits
        self.quarantined = sched.quarantined
        self.recheck_rounds = sched.eng.recheck_rounds
        self.aot_fused_verified = sched.eng.aot_fused_verified
        return sched.result()

    def _serve_hostcalls(self, state, ctrl_np, valid_blocks=None):
        """Drain parked blocks through the host outcall channel and
        re-arm them (synchronous composition of the begin/finish halves
        below — the block scheduler calls the halves directly so host
        service of parked blocks OVERLAPS the next kernel launch)."""
        import jax.numpy as jnp

        pending = self._serve_hostcalls_begin(state, ctrl_np,
                                              valid_blocks)
        state, rearms = self._serve_hostcalls_finish(state, pending)
        ctrl = ctrl_np.copy()
        for b, row in rearms.items():
            ctrl[b] = row
        state[0] = jnp.asarray(ctrl)
        return state

    def _serve_hostcalls_begin(self, state, ctrl_np, valid_blocks=None):
        """Phase 1 of the outcall serve: capture every device-side read
        the serve needs — parked blocks' metas and ctrl rows, ONE
        stack-slab download covering all argument rows, and a device-
        side gather of the parked blocks' memory columns into a fresh
        (non-donated) array.  After this returns, the caller may launch
        the next kernel round; phase 2 never touches the launched
        planes for reads.

        Transfer discipline (the host link costs ~100ms per transfer on
        a tunneled TPU): the slab is one download, guest memory goes
        through a PlaneMemoryCache over the gathered columns whose
        4 KiB row chunks are fetched for ALL lanes at once and written
        back dirty-chunks-only — per-lane data never rides the link
        alone (the "vectorized memory views" serve, SURVEY §5.8/§7(d))."""
        import jax.numpy as jnp

        img = self.img
        D, CD, W, Lblk = self._geom
        t_begin = self.obs.now()
        blocks = [int(b) for b in
                  np.nonzero(ctrl_np[:, _C_STATUS] == ST_HOSTCALL)[0]]
        metas = []
        max_row = 0
        for b in blocks:
            pc = int(ctrl_np[b, _C_PC])
            k = int(img.a[pc])
            fi = self.simt.resolve_func(k)
            nargs = len(fi.functype.params)
            metas.append((b, pc, k, fi, nargs,
                          int(ctrl_np[b, _C_FP]), int(ctrl_np[b, _C_OB]),
                          int(ctrl_np[b, _C_PAGES]),
                          ctrl_np[b].copy()))
            max_row = max(max_row, int(ctrl_np[b, _C_FP]) + nargs)
        has_mem = img.has_memory and bool(blocks)
        cols = np.concatenate(
            [np.arange(b * Lblk, (b + 1) * Lblk, dtype=np.int64)
             for b in blocks]) if blocks else np.zeros(0, np.int64)
        # device-side column gather: a fresh array the next launch's
        # donation cannot invalidate (chunk downloads happen lazily in
        # phase 2, overlapping the kernel)
        mem_cols = state[6][:, jnp.asarray(cols)] if has_mem else None
        slab_lo = np.asarray(state[2][:max_row]) if max_row else None
        slab_hi = np.asarray(state[3][:max_row]) if max_row else None
        obs = self.obs
        if obs.enabled and blocks:
            obs.span("serve_begin", t_begin, cat="scheduler",
                     track="serve", blocks=len(blocks))
            # queue depth counts REAL parked lanes: pad (clone) lanes
            # are never served, so a near-empty block must not inflate
            # the counter track by Lblk
            vb = valid_blocks or {}
            obs.counter("hostcall_queue_depth", sum(
                int(vb[b].sum()) if vb.get(b) is not None else Lblk
                for b in blocks))
        return {"blocks": blocks, "metas": metas, "cols": cols,
                "mem_cols": mem_cols, "slab_lo": slab_lo,
                "slab_hi": slab_hi, "Lblk": Lblk,
                "valid_blocks": valid_blocks or {}}

    def _serve_hostcalls_finish(self, state, pending):
        """Phase 2: run the host functions (vectorized per block where
        a tier-1 SoA WASI implementation exists, per-lane otherwise)
        and apply the results — result rows, trap columns, and dirty
        memory chunks go back as device column updates; re-armed ctrl
        rows are RETURNED for the caller to fold into its ctrl mirror
        (the kernel may be mid-flight on the other blocks).

        valid_blocks: {block: bool[Lblk]} from the scheduler — pad
        (clone) lanes are NOT served (a host function's side effects
        must fire once per real instance, never for padding); their
        result columns and memory writes are replayed from the block's
        first valid lane (their clone source), keeping them converged."""
        import jax.numpy as jnp

        from wasmedge_tpu.batch.hostcall import (
            PlaneMemoryCache,
            _CachedLaneMemory,
            make_cached_view,
            serve_one,
            vec_impl_for,
        )
        from wasmedge_tpu.host.wasi.vectorized import NotVectorizable

        img = self.img
        D, CD, W, Lblk = self._geom
        metas = pending["metas"]
        valid_blocks = pending["valid_blocks"]
        slab_lo = pending["slab_lo"]
        slab_hi = pending["slab_hi"]
        has_mem = img.has_memory and pending["mem_cols"] is not None
        cache = PlaneMemoryCache(pending["mem_cols"]) if has_mem else None
        plane_cap = (W // _PAGE_WORDS) if has_mem else 0
        if img.mem_pages_max > 0:
            max_pages = min(img.mem_pages_max, plane_cap)
        else:
            max_pages = plane_cap or None
        use_vec = bool(getattr(self.cfg, "vectorized_hostcalls", True))
        stats = getattr(self.simt, "hostcall_stats", None)
        rearms = {}
        obs = self.obs
        t_finish = obs.now()
        from wasmedge_tpu.host.wasi.vectorized import set_drain_recorder

        prev_rec = set_drain_recorder(obs)

        try:
            for bi, (b, pc, k, fi, nargs, fp, ob, pages, cc) in \
                    enumerate(metas):
                lo_col = b * Lblk      # absolute columns (slab / state)
                loc = bi * Lblk        # local columns (gathered mem cache)
                vmask = valid_blocks.get(b)
                nres = int(img.f_nresults[k])
                res_lo = np.zeros((max(nres, 1), Lblk), np.int32)
                res_hi = np.zeros((max(nres, 1), Lblk), np.int32)
                trap_codes = np.zeros(Lblk, np.int32)
                new_pages = np.full(Lblk, pages, np.int32)
                if stats is not None:
                    n_real = int(vmask.sum()) if vmask is not None else Lblk
                    stats["serve_rounds"] += 1 if bi == 0 else 0
                    stats["tier1_calls"] += n_real
                served_vec = False
                if use_vec and has_mem and getattr(fi, "kind", None) == "host":
                    vecfn, env = vec_impl_for(fi)
                    if vecfn is not None:
                        from wasmedge_tpu.batch.hostcall import \
                            gather_arg_cells

                        vsel = np.arange(Lblk, dtype=np.int64) \
                            if vmask is None else \
                            np.nonzero(vmask)[0].astype(np.int64)
                        fp_vec = np.full(slab_lo.shape[1], fp, np.int64)
                        args = gather_arg_cells(slab_lo, slab_hi, fp_vec,
                                                lo_col + vsel, nargs)
                        view = make_cached_view(cache, loc + vsel,
                                                np.full(vsel.size, pages))
                        try:
                            cells, codes = vecfn(env, view, args)
                            served_vec = True
                        except NotVectorizable:
                            served_vec = False
                        if served_vec:
                            if stats is not None:
                                stats["tier1_vectorized"] += int(vsel.size)
                            cu = cells.astype(np.uint64)
                            for r in range(cells.shape[0]):
                                res_lo[r, vsel] = (
                                    cu[r] & np.uint64(0xFFFFFFFF)).astype(
                                        np.uint32).view(np.int32)
                                res_hi[r, vsel] = (
                                    cu[r] >> np.uint64(32)).astype(
                                        np.uint32).view(np.int32)
                            trap_codes[vsel] = codes
                if not served_vec:
                    t_drain = obs.now()
                    for li in range(Lblk):
                        if vmask is not None and not vmask[li]:
                            continue  # pad lane: replayed from clone below
                        args = []
                        for i in range(nargs):
                            a_lo = int(np.uint32(slab_lo[fp + i, lo_col + li]))
                            a_hi = int(np.uint32(slab_hi[fp + i, lo_col + li]))
                            args.append(a_lo | (a_hi << 32))
                        lane_mem = None
                        if has_mem:
                            lane_mem = _CachedLaneMemory(
                                cache, loc + li, pages, max_pages, plane_cap)
                        out, code = serve_one(fi, args, lane_mem)
                        if code:
                            trap_codes[li] = code
                            continue
                        for i, cell in enumerate(out):
                            res_lo[i, li] = np.int32(
                                np.uint32(cell & 0xFFFFFFFF))
                            res_hi[i, li] = np.int32(
                                np.uint32((cell >> 32) & 0xFFFFFFFF))
                        if has_mem:
                            new_pages[li] = lane_mem.pages
                    if obs.enabled:
                        from wasmedge_tpu.batch.hostcall import hostcall_kind

                        n_real = int(vmask.sum()) if vmask is not None else Lblk
                        obs.hostcall(hostcall_kind(fi), obs.now() - t_drain,
                                     lanes=n_real, vectorized=False)
                if vmask is not None and not vmask.all():
                    src = int(np.argmax(vmask))  # first valid = clone source
                    pads = np.nonzero(~vmask)[0]
                    for li in pads:
                        res_lo[:, li] = res_lo[:, src]
                        res_hi[:, li] = res_hi[:, src]
                        trap_codes[li] = trap_codes[src]
                        new_pages[li] = new_pages[src]
                    if has_mem:
                        # replay the clone source's memory writes onto pads
                        for (off, n) in cache.writes_of(loc + src):
                            data = cache.read_bytes(loc + src, off, n)
                            for li in pads:
                                cache.write_bytes(loc + int(li), off, data)
                grew = (new_pages != pages) & (trap_codes == 0)
                if trap_codes.any() or grew.any():
                    # Per-lane outcomes: record them, re-arm at pc+1 with the
                    # served lanes' results applied (their host calls MUST
                    # NOT re-run), then leave the block DIVERGED for the
                    # scheduler to partition per lane.
                    state[7] = state[7].at[0, lo_col:lo_col + Lblk].max(
                        jnp.asarray(trap_codes))
                    if grew.any():
                        self._pages_override[b] = new_pages.copy()
                    if (trap_codes != 0).all() and \
                            len(set(trap_codes.tolist())) == 1:
                        cc[_C_STATUS] = ST_TRAPPED_BASE + int(trap_codes[0])
                        rearms[b] = cc
                        continue
                    if nres:
                        state[2] = state[2].at[ob:ob + nres,
                                               lo_col:lo_col + Lblk].set(
                            jnp.asarray(res_lo[:nres]))
                        state[3] = state[3].at[ob:ob + nres,
                                               lo_col:lo_col + Lblk].set(
                            jnp.asarray(res_hi[:nres]))
                    cc[_C_PC] = pc + 1
                    cc[_C_SP] = ob + nres
                    cc[_C_STATUS] = ST_DIVERGED
                    rearms[b] = cc
                    continue
                if nres:
                    state[2] = state[2].at[ob:ob + nres,
                                           lo_col:lo_col + Lblk].set(
                        jnp.asarray(res_lo[:nres]))
                    state[3] = state[3].at[ob:ob + nres,
                                           lo_col:lo_col + Lblk].set(
                        jnp.asarray(res_hi[:nres]))
                cc[_C_PC] = pc + 1
                cc[_C_SP] = ob + nres
                cc[_C_STATUS] = ST_RUNNING
                rearms[b] = cc
        finally:
            set_drain_recorder(prev_rec)
        if has_mem and cache._dirty:
            # dirty chunks go back to the live plane as column updates
            colsj = jnp.asarray(pending["cols"])
            cr = PlaneMemoryCache.CHUNK_ROWS
            for ci in sorted(cache._dirty):
                lo = ci * cr
                ch = cache._chunks[ci]
                state[6] = state[6].at[lo:lo + ch.shape[0], colsj].set(
                    jnp.asarray(ch))
            cache._dirty.clear()
        if obs.enabled and metas:
            obs.span("serve_finish", t_finish, cat="scheduler",
                     track="serve", blocks=len(metas))
        return state, rearms

