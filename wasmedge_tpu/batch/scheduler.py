"""Block scheduler: divergence as a scheduling problem, not a kernel one.

SURVEY.md §7 step 8 prescribes "batching by (module, PC) buckets;
retire/refill lanes from a host queue" for heterogeneous/divergent
execution.  This module is that scheduler.  The Pallas warp-interpreter
(batch/pallas_engine.py) is deliberately *uniform* — every lane in a
block shares one pc/sp/fp, which is what keeps its dispatch loop free of
per-lane gathers (the TPU has no per-lane addressing across sublanes).
Divergence is handled here, outside the kernel:

- **Entry grouping**: lanes are sorted by their argument tuples before
  packing into lane blocks, so lanes that will follow the same control
  path (Wasm instances are deterministic share-nothing state machines)
  land in the same block and never diverge at all.  Groups are padded to
  whole blocks with cloned lanes; pads compute redundantly and are
  dropped at harvest.
- **Split on divergence**: when a block stops at a data-dependent branch
  whose condition disagrees (status=DIVERGED), the splitter evaluates
  that ONE instruction per lane on the host, partitions the lanes by
  outcome, and installs each side as a new control-uniform block — the
  moral equivalent of a GPU warp scheduler's divergence stack, with
  re-packing explicit and amortized.  For fib(n) with mixed n this fires
  once per mixed block; afterwards every block is converged forever.
- **SIMT residue**: anything the splitter can't express (float-fused
  branches, per-lane divergent memory addressing, growth beyond the
  watermark plane) queues its lanes for one final pass on the
  per-lane-pc SIMT engine; everything else keeps running on the kernel.

The reference runs every instance on the same dispatch loop
(/root/reference/lib/executor/engine/engine.cpp:68-1641) one thread at a
time; here 'threads' are lane blocks and 'context switches' are block
installs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

import numpy as np

from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.batch.image import (
    ALU2_I32_BASE,
    ALU2_I64_BASE,
    TRAP_DONE,
    _I32_BIN,
)
from wasmedge_tpu.batch.pallas_engine import (
    H_BR_TABLE,
    H_BRNZ,
    H_BRZ,
    H_CALL_INDIRECT,
    H_FUSE_GCB_BASE,
    H_FUSE_GGBNZ_BASE,
    H_FUSE_GGBZ_BASE,
    H_BLOCK_BASE,
    H_MEMGROW,
    NUM_ALU2,
    ST_DIVERGED,
    ST_DONE,
    ST_HOSTCALL,
    ST_RECHECK,
    ST_REGROW,
    ST_RUNNING,
    ST_TRAPPED_BASE,
    _C_CD,
    _C_SNAP,
    _C_CHUNK,
    _C_FP,
    _C_FUEL,
    _C_OB,
    _C_PAGES,
    _C_PC,
    _C_SP,
    _C_STATUS,
    _C_STEPS,
    _FUEL_OFF,
    _PAGE_WORDS,
    PallasUniformEngine,
)

# host-side block slot states
_B_FREE = 0
_B_LIVE = 1     # installed in the device state (any kernel status)

_PLANE_IDX = {"slo": 2, "shi": 3, "glo": 4, "ghi": 5, "mem": 6, "trap": 7}
# v128 e2/e3 planes sit AFTER the 6 rollback shadows (indices 8-13) so
# every non-simd index stays stable
_PLANE_IDX_SIMD = dict(_PLANE_IDX, se2=14, se3=15)


def _u32(x):
    return np.asarray(x).astype(np.int64) & 0xFFFFFFFF


def _host_alu2(sub: int, xl, xh, yl, yh):
    """Evaluate one integer ALU2 sub on int32 lo/hi column vectors.

    Only the non-trapping integer families (what superinstruction fusion
    admits) are supported; returns None for float subs — the caller then
    routes the block to the SIMT residue.  Semantics mirror
    batch/laneops.py's device kernels."""
    names = _I32_BIN
    if ALU2_I32_BASE <= sub < ALU2_I32_BASE + len(names):
        name = names[sub - ALU2_I32_BASE]
        xu, yu = _u32(xl), _u32(yl)
        xs = xu.astype(np.uint32).view(np.int32).astype(np.int64)
        ys = yu.astype(np.uint32).view(np.int32).astype(np.int64)
        sh = yu & 31
        ops = {
            "add": lambda: xu + yu, "sub": lambda: xu - yu,
            "mul": lambda: xu * yu,
            "and": lambda: xu & yu, "or": lambda: xu | yu,
            "xor": lambda: xu ^ yu,
            "shl": lambda: xu << sh,
            "shr_s": lambda: xs >> sh,
            "shr_u": lambda: xu >> sh,
            "rotl": lambda: (xu << sh) | (xu >> ((32 - sh) & 31)),
            "rotr": lambda: (xu >> sh) | (xu << ((32 - sh) & 31)),
            "eq": lambda: xu == yu, "ne": lambda: xu != yu,
            "lt_s": lambda: xs < ys, "lt_u": lambda: xu < yu,
            "gt_s": lambda: xs > ys, "gt_u": lambda: xu > yu,
            "le_s": lambda: xs <= ys, "le_u": lambda: xu <= yu,
            "ge_s": lambda: xs >= ys, "ge_u": lambda: xu >= yu,
        }.get(name)
        if ops is None:
            return None
        lo = (ops().astype(np.int64) & 0xFFFFFFFF).astype(
            np.uint32).view(np.int32)
        return lo, np.zeros_like(lo)
    if ALU2_I64_BASE <= sub < ALU2_I64_BASE + len(names):
        name = names[sub - ALU2_I64_BASE]
        x = (_u32(xl) | (_u32(xh) << 32)).astype(np.uint64)
        y = (_u32(yl) | (_u32(yh) << 32)).astype(np.uint64)
        xs, ys = x.view(np.int64), y.view(np.int64)
        sh = (y & np.uint64(63))
        with np.errstate(over="ignore"):
            ops = {
                "add": lambda: x + y, "sub": lambda: x - y,
                "mul": lambda: x * y,
                "and": lambda: x & y, "or": lambda: x | y,
                "xor": lambda: x ^ y,
                "shl": lambda: x << sh,
                "shr_s": lambda: (xs >> sh.astype(np.int64)).view(
                    np.uint64),
                "shr_u": lambda: x >> sh,
                "rotl": lambda: (x << sh) |
                (x >> ((np.uint64(64) - sh) & np.uint64(63))),
                "rotr": lambda: (x >> sh) |
                (x << ((np.uint64(64) - sh) & np.uint64(63))),
                "eq": lambda: (x == y).astype(np.uint64),
                "ne": lambda: (x != y).astype(np.uint64),
                "lt_s": lambda: (xs < ys).astype(np.uint64),
                "lt_u": lambda: (x < y).astype(np.uint64),
                "gt_s": lambda: (xs > ys).astype(np.uint64),
                "gt_u": lambda: (x > y).astype(np.uint64),
                "le_s": lambda: (xs <= ys).astype(np.uint64),
                "le_u": lambda: (x <= y).astype(np.uint64),
                "ge_s": lambda: (xs >= ys).astype(np.uint64),
                "ge_u": lambda: (x >= y).astype(np.uint64),
            }.get(name)
            if ops is None:
                return None
            v = ops().astype(np.uint64)
        lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
        hi = (v >> np.uint64(32)).astype(np.uint32).view(np.int32)
        return lo, hi
    return None


class _Rows:
    """Lazy row-sliced view of a [rows, L] device plane: downloads one
    row's block columns at a time, cached."""

    def __init__(self, arr, lo: int, n: int):
        self._arr, self._lo, self._n = arr, lo, n
        self._c = {}

    def __getitem__(self, key):
        if isinstance(key, tuple):
            row, cols = key
            return self[row][cols]
        r = int(key)
        if r not in self._c:
            self._c[r] = np.asarray(self._arr[r, self._lo:self._lo + self._n])
        return self._c[r]


@dataclasses.dataclass
class _Pending:
    """A control-uniform lane group waiting for a free block slot."""

    ctrl: np.ndarray              # [16] int32
    frames: np.ndarray            # [3, CD] int32
    cols: Dict[str, np.ndarray]   # plane name -> [rows, n] columns
    lane_ids: np.ndarray          # [n] original lane ids (no pads)
    steps0: int = 0               # instructions already retired
    pages: np.ndarray = None      # [n] per-lane page counts when a host
    #                               outcall grew memory (else ctrl value)


class BlockScheduler:
    """Drives one module's batch through the Pallas kernel with entry
    grouping, divergence splitting, and a SIMT residue pass."""

    # don't pre-group when the median group is this small — the SIMT
    # engine is the right tool for fully-heterogeneous inputs
    MIN_GROUP_LANES = 8

    def __init__(self, outer: PallasUniformEngine, func_name: str,
                 args_lanes: List, max_steps: int):
        self.outer = outer
        self.inst = outer.inst
        self.cfg = outer.cfg
        self.func_name = func_name
        self.max_steps = max_steps
        self.lanes = outer.lanes
        ex = self.inst.exports.get(func_name)
        if ex is None or ex[0] != 0:
            raise KeyError(f"no exported function {func_name}")
        self.func_idx = ex[1]
        self.nres = int(self.inst.lowered.funcs[self.func_idx].nresults)
        self.args = []
        for a in args_lanes:
            arr = np.asarray(a, np.int64)
            if arr.ndim == 0:
                arr = np.full(self.lanes, arr, np.int64)
            if arr.shape != (self.lanes,):
                raise ValueError(
                    f"arg: expected shape ({self.lanes},) or scalar, "
                    f"got {arr.shape}")
            self.args.append(arr)
        # results in original lane order
        self.res_lo = np.zeros((max(self.nres, 1), self.lanes), np.int32)
        self.res_hi = np.zeros((max(self.nres, 1), self.lanes), np.int32)
        self.trap = np.zeros(self.lanes, np.int32)
        self.retired = np.zeros(self.lanes, np.int64)
        self.fell_back_to_simt = False
        self.splits = 0
        self.quarantined = 0
        # flight recorder shared with the outer engine (obs/): the
        # scheduler reports launches, serves, splits, frees, residue
        # handoffs and live-lane occupancy; NULL_RECORDER when off
        self.obs = outer.obs
        # per-device trace attribution (parallel/mesh.py sets obs_track
        # on each device's engine so multi-chip runs keep their devices'
        # events on separate tracks instead of one interleaved "pallas")
        self._track = getattr(outer, "obs_track", "pallas")
        self._track_simt = "simt" if self._track == "pallas" \
            else self._track
        self._t_launch = 0.0
        self._plane_idx = _PLANE_IDX_SIMD if outer.img.has_simd \
            else _PLANE_IDX
        self._plan()

    # -- entry packing -----------------------------------------------------
    def _plan(self):
        """Choose (L_sched, Lblk), build the engine and the packed state."""
        outer = self.outer
        if self.args:
            order = np.lexsort(tuple(self.args))
            keys = np.stack(self.args, axis=0)[:, order]
            starts = np.concatenate((
                [0],
                np.flatnonzero(np.any(keys[:, 1:] != keys[:, :-1],
                                      axis=0)) + 1))
            sizes = np.diff(np.concatenate((starts, [self.lanes])))
        else:
            order = np.arange(self.lanes)
            sizes = np.array([self.lanes])
        lblk_max = outer._lane_block()
        align = 1 if outer._interpret() else 128
        med = int(np.median(sizes))
        if len(sizes) == 1 or med < self.MIN_GROUP_LANES:
            # uniform batch (no grouping needed) or hopelessly shattered
            # (grouping can't help): one geometry, identity packing
            lblk = lblk_max
            self.order = np.arange(self.lanes)
            group_sizes = [self.lanes]
        else:
            # Smallest block covering the typical group: throughput is
            # Lblk x step-rate and blocks serialize on the core, so a
            # group split across two blocks runs its program twice.
            # Padding a block out to the group size is free by comparison
            # (pad lanes ride along in otherwise-idle vector lanes).
            lblk = align
            while lblk < med and lblk * 2 <= lblk_max:
                lblk *= 2
            self.order = order
            group_sizes = [int(s) for s in sizes]
            # guard: per-group padding must not inflate the packed state
            # unboundedly (hundreds of sub-align groups would each claim
            # a full block of HBM planes and a serialized block slot) —
            # past 2x the caller's lanes, identity packing + in-flight
            # splitting degrades more gracefully
            padded = sum(-(-g // lblk) * lblk for g in group_sizes)
            if padded > 2 * self.lanes:
                lblk = lblk_max
                self.order = np.arange(self.lanes)
                group_sizes = [self.lanes]
        blocks: List[np.ndarray] = []   # each [lblk] lane ids (-1 = pad)
        pos = 0
        for g in group_sizes:
            ids = self.order[pos:pos + g]
            pos += g
            for off in range(0, g, lblk):
                chunk = ids[off:off + lblk].astype(np.int64)
                if len(chunk) < lblk:
                    chunk = np.concatenate(
                        [chunk, np.full(lblk - len(chunk), -1, np.int64)])
                blocks.append(chunk)
        self.Lblk = lblk
        self.nblk = len(blocks)
        L = self.nblk * lblk
        # splits that outgrow this budget route to SIMT instead of
        # thrashing the host with block surgery
        self.split_budget = 4 * self.nblk + 16
        # internal engine at the scheduler's geometry, cached on the
        # long-lived SIMT engine per (L, Lblk) so repeated run() calls
        # reuse the image, the fused tables, and the jitted kernel
        cache = getattr(outer.simt, "_sched_cache", None)
        if cache is None:
            cache = outer.simt._sched_cache = {}
        eng = cache.get((L, lblk))
        if eng is None:
            from wasmedge_tpu.batch.engine import BatchEngine

            simt = BatchEngine(self.inst, store=outer.simt.store,
                               conf=outer.simt.conf, lanes=L,
                               img=outer.img)
            eng = PallasUniformEngine(self.inst, simt=simt,
                                      interpret=outer.interpret)
            eng._blk_cap = lblk
            eng.ineligible_reason = eng._eligibility()
            if not eng.eligible:
                raise RuntimeError(
                    f"scheduler geometry ineligible: "
                    f"{eng.ineligible_reason}")
            eng._build()
            assert eng._geom[3] == lblk, (eng._geom, lblk)
            cache[(L, lblk)] = eng
        self.eng = eng
        self.block_lanes = np.stack(blocks)  # [nblk, lblk]
        self.block_state = np.full(self.nblk, _B_LIVE, np.int32)
        self.block_steps = np.zeros(self.nblk, np.int64)
        self._pending: List[_Pending] = []
        self._simt_queue: List[_Pending] = []
        self._pending_serve = None   # tier-2 deferred hostcall serve
        self._serve_rearms = None
        self._ctrl_cache = None
        self._ctrl_dirty = False
        self._frames_cache = None
        self._frames_dirty = False
        self._build_initial_state()

    def _build_initial_state(self):
        """Construct the packed state ON DEVICE.  Host->device bandwidth
        is the scarce resource (the bench TPU sits behind a tunnel):
        only the argument rows (nargs x L) and the module's memory init
        image (<= W words) are uploaded; the big zero planes are
        jnp.zeros and the per-lane broadcast of mem_init happens
        device-side."""
        import jax.numpy as jnp

        eng = self.eng
        img = eng.img
        D, CD, W, Lblk = eng._geom
        L = eng.lanes
        meta = self.inst.lowered.funcs[self.func_idx]
        # packed column -> original lane (pads clone their block's first
        # valid lane so they run the same program)
        flat = self.block_lanes.reshape(-1).copy()
        for b in range(self.nblk):
            seg = self.block_lanes[b]
            first = seg[seg >= 0][0]
            flat[b * Lblk:(b + 1) * Lblk][seg < 0] = first
        stack_lo = jnp.zeros((D, L), jnp.int32)
        stack_hi = jnp.zeros((D, L), jnp.int32)
        if self.args:
            arg_m = np.stack([a[flat] for a in self.args])  # [nargs, L]
            lo = (arg_m & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
            hi = ((arg_m >> 32) & 0xFFFFFFFF).astype(np.uint32).view(
                np.int32)
            stack_lo = stack_lo.at[:len(self.args)].set(jnp.asarray(lo))
            stack_hi = stack_hi.at[:len(self.args)].set(jnp.asarray(hi))
        NGp = max(img.globals_lo.shape[0], 1)
        glo = jnp.zeros((NGp, L), jnp.int32)
        ghi = jnp.zeros((NGp, L), jnp.int32)
        ng = img.globals_lo.shape[0]
        if ng:
            glo = glo.at[:ng].set(
                jnp.broadcast_to(jnp.asarray(img.globals_lo)[:, None],
                                 (ng, L)))
            ghi = ghi.at[:ng].set(
                jnp.broadcast_to(jnp.asarray(img.globals_hi)[:, None],
                                 (ng, L)))
        mem = jnp.zeros((W, L), jnp.int32)
        if img.mem_init.shape[0] > 1 or img.mem_pages_init:
            n = min(img.mem_init.shape[0], W)
            mem = mem.at[:n].set(
                jnp.broadcast_to(jnp.asarray(img.mem_init[:n])[:, None],
                                 (n, L)))
        ctrl = np.zeros((self.nblk, 16), np.int32)
        ctrl[:, _C_PC] = meta.entry_pc
        ctrl[:, _C_SP] = meta.nlocals
        ctrl[:, _C_OB] = meta.nlocals
        ctrl[:, _C_PAGES] = img.mem_pages_init
        ctrl[:, _C_CHUNK] = self.cfg.steps_per_launch
        fuel = self.cfg.fuel_per_launch
        ctrl[:, _C_FUEL] = _FUEL_OFF if fuel is None else fuel
        self.state = [jnp.asarray(ctrl),
                      jnp.zeros((self.nblk, 3, CD), jnp.int32),
                      stack_lo, stack_hi, glo, ghi, mem,
                      jnp.zeros((1, L), jnp.int32)] + eng.shadow_planes()
        if img.has_simd:
            self.state += [jnp.zeros((D, L), jnp.int32),
                           jnp.zeros((D, L), jnp.int32)]
            self.state += eng._shadow_simd_planes()

    # -- drive -------------------------------------------------------------
    def run(self):
        """Run to completion; fills result/trap/retired arrays.

        Tier-2 overlap: parked hostcall blocks are captured (device
        reads) during process(), then SERVED on the CPU after the next
        launch() has dispatched — block A's WASI calls drain while
        block B keeps executing on the device.  Re-arms are column
        updates into the live state (no kernel rebuild/relaunch cost);
        the re-armed blocks run from the following launch."""
        # cooperative mesh cancellation (parallel/supervisor.py): a
        # doomed sharded run stops sibling schedulers at their next
        # launch boundary instead of running to completion
        cancel = getattr(self, "cancel_check", None)
        while True:
            if cancel is not None and cancel():
                return
            self.launch()
            if not self.process():
                break
        self._run_simt_residue()

    def _finish_pending_serve(self):
        """Phase 2 of a deferred hostcall serve: host-side WASI work
        overlapping the in-flight kernel; re-armed ctrl rows are folded
        into the mirror by process() after it syncs on the launch."""
        p = self._pending_serve
        if p is None:
            return
        self._pending_serve = None
        self.state, rearms = self.eng._serve_hostcalls_finish(
            self.state, p)
        self._serve_rearms = rearms

    def launch(self):
        """Dispatch one kernel round if any block is runnable.  The
        dispatch is asynchronous (JAX): multiple schedulers' launches
        pipeline on the device while hosts process results — the
        latency-hiding seam the multi-tenant driver uses."""
        import jax.numpy as jnp

        ctrl_np = self._ctrl()
        if self._ctrl_dirty:
            self.state[0] = jnp.asarray(ctrl_np)
            self._ctrl_dirty = False
        if self._frames_dirty:
            self.state[1] = jnp.asarray(self._frames_cache)
            self._frames_dirty = False
        live = self.block_state == _B_LIVE
        runnable = live & (ctrl_np[:, _C_STATUS] == ST_RUNNING) & \
            (self.block_steps < self.max_steps)
        self._launched = bool(runnable.any())
        if self._launched:
            self._live_at_launch = live
            self._t_launch = self.obs.now()
            self._launch_blocks = int(runnable.sum())
            out = self.eng._fn(*self.eng._tables, self.state[0],
                               self.state[1], *self.state[2:])
            self.state = list(out)
            self._ctrl_cache = None   # kernel wrote fresh ctrl/frames
            self._frames_cache = None

    def _ctrl(self) -> np.ndarray:
        """Host mirror of the ctrl plane: ONE transfer per kernel round.
        Every per-block interaction below reads/writes this mirror (tiny
        transfers each pay the host link's full round-trip latency —
        fatal over a tunneled TPU at ~100ms RTT)."""
        if self._ctrl_cache is None:
            self._ctrl_cache = np.array(self.state[0])
            self._ctrl_dirty = False
        return self._ctrl_cache

    def _frames(self) -> np.ndarray:
        """Host mirror of the frames plane (same discipline as _ctrl)."""
        if self._frames_cache is None:
            self._frames_cache = np.array(self.state[1])
            self._frames_dirty = False
        return self._frames_cache

    def process(self) -> bool:
        """Sync on the launch (if any) and handle block statuses.
        Returns False when the kernel side is finished (residue may
        remain for _run_simt_residue)."""
        # phase 2 of a serve captured by the PREVIOUS process(): the
        # host-side WASI work runs now, before we sync on the launch
        # dispatched in between — CPU drain overlapping device compute
        self._finish_pending_serve()
        ctrl_np = self._ctrl()
        served = False
        if self._serve_rearms:
            # fold the overlapped serve's re-arms into the fresh mirror
            # (the kernel passed the parked blocks' ctrl rows through)
            for b, row in self._serve_rearms.items():
                ctrl_np[b] = row
            self._serve_rearms = None
            self._ctrl_dirty = True
            served = True
        if self._launched:
            live = self._live_at_launch
            new_steps = ctrl_np[:, _C_STEPS].astype(np.int64)
            self.block_steps[live] += new_steps[live]
            obs = self.obs
            if obs.enabled:
                # per-launch span closed at THIS sync point (the ctrl
                # mirror download above is the launch's completion);
                # occupancy counts real (non-pad) lanes of live blocks
                valid = self.block_lanes >= 0
                obs.span(
                    "kernel_round", self._t_launch, cat="scheduler",
                    track=self._track, blocks=self._launch_blocks,
                    retired_delta=int(
                        (new_steps[live] * valid[live].sum(axis=1)).sum()))
                obs.counter("live_lanes", int(
                    valid[self.block_state == _B_LIVE].sum()))
            if (live & (ctrl_np[:, _C_STATUS] == ST_RECHECK)).any():
                ctrl_np = self._run_recheck(live)
            else:
                # adaptive-window growth (careful_recheck halves):
                # clean launches double a shrunken snapshot interval
                snap = ctrl_np[:, _C_SNAP]
                grow = live & (snap > 0) & (snap < self.eng.SNAP_STEPS)
                if grow.any():
                    cc = self._ctrl()
                    cc[:, _C_SNAP] = np.where(
                        grow, np.minimum(snap * 2, self.eng.SNAP_STEPS),
                        snap)
                    self._ctrl_dirty = True
                    ctrl_np = cc
            self._handle_statuses(ctrl_np)
            return True
        if self._handle_statuses(ctrl_np) or served:
            return True
        if self._pending_serve is not None:
            return True  # a captured serve still needs its finish pass
        # starved: pending children with no free slot go to SIMT
        for p in self._pending:
            self._simt_queue.append(p)
        self._pending = []
        return False

    def _run_recheck(self, live) -> np.ndarray:
        """Re-run ST_RECHECK blocks on the careful kernel (synchronous)
        via the engine's shared careful_recheck protocol, then stops
        with the precise status which _handle_statuses splits/serves."""
        import jax.numpy as jnp

        recheck = live & (self._ctrl()[:, _C_STATUS] == ST_RECHECK)
        if self._frames_dirty:
            self.state[1] = jnp.asarray(self._frames_cache)
            self._frames_dirty = False
        self.state, ctrl = self.eng.careful_recheck(
            self.state, self._ctrl(), recheck)
        self.block_steps += ctrl[:, _C_STEPS].astype(np.int64)
        self._ctrl_cache = ctrl
        self._ctrl_dirty = False
        self._frames_cache = None
        return ctrl

    def _handle_statuses(self, ctrl_np) -> bool:
        """Harvest/serve/split each live block by its status.  Returns
        True if progress was made that could unblock another pass."""
        progress = False
        hostcall_blocks = []
        # classify first so the downloads below batch into single
        # transfers covering every block that needs them
        harvests = []
        splits = []
        for b in range(self.nblk):
            if self.block_state[b] != _B_LIVE:
                continue
            status = int(ctrl_np[b, _C_STATUS])
            if status == ST_RUNNING:
                if self.block_steps[b] >= self.max_steps:
                    harvests.append((b, True))
                continue
            if status == ST_DONE or status >= ST_TRAPPED_BASE:
                harvests.append((b, False))
            elif status == ST_HOSTCALL:
                hostcall_blocks.append(b)
            elif status in (ST_DIVERGED, ST_REGROW):
                splits.append((b, status))
        if harvests or splits:
            self._trap_full = np.asarray(self.state[7][0])
            if self.nres and harvests:
                self._res_lo_full = np.asarray(self.state[2][:self.nres])
                self._res_hi_full = np.asarray(self.state[3][:self.nres])
        for b, running in harvests:
            self._harvest(b, ctrl_np, running=running)
            progress = True
        for b, status in splits:
            self._split(b, ctrl_np, status)
            progress = True
        if hostcall_blocks:
            # tier-2 overlap: capture the serve's device reads now (the
            # state arrays are valid pre-launch); the host-side WASI
            # work runs in the NEXT process() after a launch has been
            # dispatched, so block A's calls drain on the CPU while
            # block B executes on the device.  The kernel passes parked
            # (non-RUNNING) blocks through untouched with zero steps,
            # so the deferred writebacks land on unchanged columns.
            valid = {b: self.block_lanes[b] >= 0 for b in hostcall_blocks}
            self._pending_serve = self.eng._serve_hostcalls_begin(
                self.state, ctrl_np, valid_blocks=valid)
            progress = True
        # a prior serve's re-arms may have left per-lane outcomes
        # (folded into ctrl_np by process): DIVERGED/trapped re-armed
        # blocks were already classified by the split/harvest passes
        # above, since they arrive through the normal status scan.
        progress |= self._install_pending()
        return progress

    # -- harvest -----------------------------------------------------------
    def _harvest(self, b: int, ctrl_np, running: bool = False):
        Lblk = self.Lblk
        lo = b * Lblk
        ids = self.block_lanes[b]
        valid = ids >= 0
        vids = ids[valid].astype(np.int64)
        status = int(ctrl_np[b, _C_STATUS])
        trap_row = self._trap_full[lo:lo + Lblk]
        if running:
            codes = trap_row.copy()  # 0 = still running
        elif status == ST_DONE:
            codes = np.full(Lblk, TRAP_DONE, np.int32)
            if self.nres:
                self.res_lo[:self.nres, vids] = \
                    self._res_lo_full[:, lo:lo + Lblk][:, valid]
                self.res_hi[:self.nres, vids] = \
                    self._res_hi_full[:, lo:lo + Lblk][:, valid]
        else:
            code = status - ST_TRAPPED_BASE
            codes = np.where(trap_row != 0, trap_row, code).astype(np.int32)
        self.trap[vids] = codes[valid]
        self.retired[vids] = self.block_steps[b]
        self._free_block(b)

    def _free_block(self, b: int):
        """Park the slot (host mirror only; uploaded before the next
        launch)."""
        self.block_state[b] = _B_FREE
        self._ctrl()[b, _C_STATUS] = ST_DONE
        self._ctrl_dirty = True
        self.obs.instant("block_free", cat="scheduler", track=self._track,
                         block=b)

    # -- split machinery ---------------------------------------------------
    def _split(self, b: int, ctrl_np, status: int):
        """Resolve a stopped block: evaluate the divergent instruction
        per lane, partition lanes by outcome, install uniform children."""
        eng = self.eng
        ctrl = ctrl_np[b].copy()
        frames = self._frames()[b]
        pages_over = eng._pages_override.pop(b, None)
        self.splits += 1
        self.obs.instant("split", cat="scheduler", track=self._track,
                         block=b, pc=int(ctrl[_C_PC]), status=status,
                         splits=self.splits)
        if status == ST_REGROW or self.splits > self.split_budget:
            self._to_simt(b, ctrl, frames, pages_over)
            return
        pc = int(ctrl[_C_PC])
        hid = int(eng._np_fused["hid"][pc])
        if hid >= H_BLOCK_BASE:
            # stop at a fused block head (its first op bailed): the
            # operand fields are the original op's, so resolve via the
            # original opcode instead of demoting the lanes to SIMT
            hid = int(eng._np_hid_orig[pc])
        if not self._try_resolve(b, ctrl, frames, hid, pc, pages_over):
            self._to_simt(b, ctrl, frames, pages_over)

    def _try_resolve(self, b, ctrl, frames, hid, pc, pages_over) -> bool:
        """Dispatch on the stopped instruction.  Returns False when the
        case must go to the SIMT residue."""
        fused = self.eng._np_fused
        sp = int(ctrl[_C_SP])
        fp = int(ctrl[_C_FP])
        ob = int(ctrl[_C_OB])
        a = int(fused["a"][pc])
        b_op = int(fused["b"][pc])
        c_op = int(fused["c"][pc])
        Lblk = self.Lblk
        lo = b * Lblk
        # lazy per-row download: the resolver inspects only a handful of
        # stack rows; whole-plane transfers would ride the slow host link
        slo = _Rows(self.state[2], lo, Lblk)
        shi = _Rows(self.state[3], lo, Lblk)
        trap_row = self._trap_full[lo:lo + Lblk]

        # Advanced-with-per-lane-outcomes stops come FIRST, regardless of
        # what instruction ctrl now points at: trap-partial sites (div/rem
        # by zero, partial-OOB memory ops) and served hostcalls advance
        # control uniformly and record per-lane trap codes / grown pages —
        # the divergence IS those outcomes, not the next opcode.  Peel
        # trapped lanes off; the rest resume RUNNING at the current ctrl.
        # (Live blocks otherwise carry all-zero trap planes: every split
        # hands children trap-free columns.)
        if trap_row.any() or pages_over is not None:
            keys = [trap_row.astype(np.int64)]
            if pages_over is not None:
                keys.append(pages_over.astype(np.int64))
            children = []
            for key, cols in self._partition(keys):
                cc = ctrl.copy()
                code = int(key[0])
                cc[_C_STATUS] = (ST_TRAPPED_BASE + code) if code \
                    else ST_RUNNING
                if pages_over is not None:
                    cc[_C_PAGES] = int(key[1])
                children.append((cc, frames.copy(), cols, {}))
            self._install_children(b, children)
            return True

        if hid == H_BRZ:
            cond = _u32(slo[sp - 1])
            children = []
            for key, cols in self._partition([(cond == 0).astype(np.int64)]):
                cc = ctrl.copy()
                cc[_C_PC] = a if key[0] else pc + 1
                cc[_C_SP] = sp - 1
                cc[_C_STATUS] = ST_RUNNING
                children.append((cc, frames.copy(), cols, {}))
            self._install_children(b, children)
            return True

        if hid == H_BRNZ:
            cond = _u32(slo[sp - 1])
            tgt_sp = ob + c_op
            children = []
            for key, cols in self._partition([(cond != 0).astype(np.int64)]):
                cc = ctrl.copy()
                writes = {}
                if key[0]:  # taken
                    cc[_C_PC] = a
                    cc[_C_SP] = tgt_sp + b_op
                    if b_op == 1:
                        writes[("stack", tgt_sp)] = (slo[sp - 2, cols],
                                                     shi[sp - 2, cols])
                else:
                    cc[_C_PC] = pc + 1
                    cc[_C_SP] = sp - 1
                cc[_C_STATUS] = ST_RUNNING
                children.append((cc, frames.copy(), cols, writes))
            self._install_children(b, children)
            return True

        if hid == H_BR_TABLE:
            idx = _u32(slo[sp - 1])
            brt = self.eng.img.br_table
            ii = np.minimum(idx, b_op)
            children = []
            for key, cols in self._partition([ii]):
                e = a + int(key[0])
                tgt, nkeep, pop_to = (int(brt[e, 0]), int(brt[e, 1]),
                                     int(brt[e, 2]))
                tgt_sp = ob + pop_to
                cc = ctrl.copy()
                cc[_C_PC] = tgt
                cc[_C_SP] = tgt_sp + nkeep
                cc[_C_STATUS] = ST_RUNNING
                writes = {}
                if nkeep == 1:
                    writes[("stack", tgt_sp)] = (slo[sp - 2, cols],
                                                 shi[sp - 2, cols])
                children.append((cc, frames.copy(), cols, writes))
            self._install_children(b, children)
            return True

        if H_FUSE_GCB_BASE <= hid < H_FUSE_GCB_BASE + NUM_ALU2:
            sub = hid - H_FUSE_GCB_BASE
            src = fp + a
            imm_lo = np.full(Lblk, fused["ilo"][pc], np.int32)
            imm_hi = np.full(Lblk, fused["ihi"][pc], np.int32)
            res = _host_alu2(sub, slo[src], shi[src], imm_lo, imm_hi)
            if res is None:
                return False
            cond = _u32(res[0])
            children = []
            for key, cols in self._partition([(cond == 0).astype(np.int64)]):
                cc = ctrl.copy()
                cc[_C_PC] = b_op if key[0] else pc + 4
                cc[_C_STATUS] = ST_RUNNING
                children.append((cc, frames.copy(), cols, {}))
            self._install_children(b, children)
            return True

        if H_FUSE_GGBZ_BASE <= hid < H_FUSE_GGBNZ_BASE + NUM_ALU2:
            nz = hid >= H_FUSE_GGBNZ_BASE
            sub = hid - (H_FUSE_GGBNZ_BASE if nz else H_FUSE_GGBZ_BASE)
            s1 = fp + int(fused["ilo"][pc])
            s2 = fp + int(fused["ihi"][pc])
            res = _host_alu2(sub, slo[s1], shi[s1], slo[s2], shi[s2])
            if res is None:
                return False
            cond = _u32(res[0])
            taken_key = (cond != 0) if nz else (cond == 0)
            tgt_sp = ob + c_op
            children = []
            for key, cols in self._partition([taken_key.astype(np.int64)]):
                cc = ctrl.copy()
                writes = {}
                if key[0]:  # taken
                    cc[_C_PC] = a
                    if nz:
                        cc[_C_SP] = tgt_sp + b_op
                        if b_op == 1:
                            writes[("stack", tgt_sp)] = (slo[sp - 1, cols],
                                                         shi[sp - 1, cols])
                else:
                    cc[_C_PC] = pc + 4
                cc[_C_STATUS] = ST_RUNNING
                children.append((cc, frames.copy(), cols, writes))
            self._install_children(b, children)
            return True

        if hid == H_CALL_INDIRECT:
            idx = _u32(slo[sp - 1])
            tbl = self.eng.img.table0
            children = []
            for key, cols in self._partition([idx]):
                i0 = int(key[0])
                cc = ctrl.copy()
                code = 0
                if i0 >= b_op:
                    code = int(ErrCode.UndefinedElement)
                else:
                    h = int(tbl[min(c_op + i0, len(tbl) - 1)])
                    if h == 0:
                        code = int(ErrCode.UninitializedElement)
                    elif int(self.eng.img.f_type[h - 1]) != a:
                        code = int(ErrCode.IndirectCallTypeMismatch)
                if code:
                    cc[_C_STATUS] = ST_TRAPPED_BASE + code
                    children.append((cc, frames.copy(), cols, {}))
                    continue
                cc[_C_SP] = sp - 1
                trip = self._host_call(cc, frames.copy(), h - 1, sp - 1, pc)
                children.append((trip[0], trip[1], cols, trip[2]))
            self._install_children(b, children)
            return True

        if hid == H_MEMGROW:
            delta = slo[sp - 1].astype(np.int64)
            img = self.eng.img
            cap = self.eng._geom[2] // _PAGE_WORDS if img.has_memory else 0
            hard = max(img.mem_pages_max, img.mem_pages_init) \
                if img.has_memory else 0
            pages = int(ctrl[_C_PAGES])
            children = []
            for key, cols in self._partition([delta]):
                d = int(key[0])
                legal = 0 <= d and pages + d <= hard
                if legal and pages + d > cap:
                    return False  # needs the big-plane engine
                cc = ctrl.copy()
                cc[_C_PC] = pc + 1
                cc[_C_PAGES] = pages + d if legal else pages
                cc[_C_STATUS] = ST_RUNNING
                writes = {("stack", sp - 1): (
                    np.full(len(cols), pages if legal else -1, np.int32),
                    np.zeros(len(cols), np.int32))}
                children.append((cc, frames.copy(), cols, writes))
            self._install_children(b, children)
            return True

        # data-divergent loads/stores/copies (no trap codes, control not
        # advanced) need per-lane memory addressing -> SIMT
        return False

    def _host_call(self, cc, frames, callee, sp_eff, pc):
        """Apply _do_call semantics host-side for one uniform side."""
        img = self.eng.img
        D, CD = self.eng._geom[0], self.eng._geom[1]
        nargs = int(img.f_nparams[callee])
        nloc = int(img.f_nlocals[callee])
        cd = int(cc[_C_CD])
        fp_new = sp_eff - nargs
        ob_new = fp_new + nloc
        if cd >= CD - 1:
            cc[_C_STATUS] = ST_TRAPPED_BASE + int(ErrCode.CallStackExhausted)
            return cc, frames, {}
        if fp_new + int(img.f_frame_top[callee]) > D:
            cc[_C_STATUS] = ST_TRAPPED_BASE + int(ErrCode.StackOverflow)
            return cc, frames, {}
        frames[0, cd] = pc + 1
        frames[1, cd] = int(cc[_C_FP])
        frames[2, cd] = int(cc[_C_OB])
        writes = {}
        for k in range(nloc - nargs):
            writes[("stack", fp_new + nargs + k)] = (0, 0)
        cc[_C_PC] = int(img.f_entry[callee])
        cc[_C_SP] = ob_new
        cc[_C_FP] = fp_new
        cc[_C_OB] = ob_new
        cc[_C_CD] = cd + 1
        cc[_C_STATUS] = ST_RUNNING
        return cc, frames, writes

    @staticmethod
    def _partition(keys: List[np.ndarray]):
        """Partition columns by key tuples, first-seen order.  Pads carry
        their clone source's data, so they follow its side and stay
        harmless clones there."""
        out = []
        seen = {}
        for col in range(len(keys[0])):
            key = tuple(int(k[col]) for k in keys)
            if key in seen:
                out[seen[key]][1].append(col)
            else:
                seen[key] = len(out)
                out.append((key, [col]))
        return [(k, np.asarray(c, np.int64)) for k, c in out]

    def _install_children(self, b: int, children):
        """Queue child groups; immediately-trapped ones harvest in place."""
        ids = self.block_lanes[b]
        steps0 = int(self.block_steps[b])
        for (cc, fr, cols, writes) in children:
            lane_ids = ids[cols]
            sel = lane_ids >= 0
            if not sel.any():
                continue  # a pad-only side: drop it
            st = int(cc[_C_STATUS])
            if st >= ST_TRAPPED_BASE:
                vids = lane_ids[sel].astype(np.int64)
                self.trap[vids] = st - ST_TRAPPED_BASE
                self.retired[vids] = steps0
                continue
            vcols = cols[sel]
            child_cols = self._extract_cols(b, vcols, writes, sel)
            cc[_C_CHUNK] = self.cfg.steps_per_launch
            self._pending.append(_Pending(
                ctrl=cc, frames=fr, cols=child_cols,
                lane_ids=lane_ids[sel].astype(np.int64), steps0=steps0))
        self._free_block(b)

    def _extract_cols(self, b: int, cols, writes, sel=None):
        """Snapshot a child's valid columns as DEVICE arrays (gathers —
        no host transfer), applying the side's writes.

        `writes` values are either (lo, hi) scalars or (lo, hi) arrays
        indexed like the PRE-selection column list; `sel` maps them down
        to the valid columns."""
        import jax.numpy as jnp

        Lblk = self.Lblk
        lo = b * Lblk
        idx = jnp.asarray(lo + np.asarray(cols, np.int64))
        out = {}
        for name, i in self._plane_idx.items():
            out[name] = self.state[i][:, idx]
        for key, val in writes.items():
            row = key[1]
            vlo, vhi = val
            if np.ndim(vlo):
                vlo = np.asarray(vlo)[sel] if sel is not None else vlo
            if np.ndim(vhi):
                vhi = np.asarray(vhi)[sel] if sel is not None else vhi
            out["slo"] = out["slo"].at[row].set(jnp.asarray(vlo))
            out["shi"] = out["shi"].at[row].set(jnp.asarray(vhi))
        return out

    def _install_pending(self) -> bool:
        """Move queued children into free block slots.  Plane writes are
        device-side column-block sets (the snapshots are device arrays),
        so no state crosses the host link."""
        if not self._pending:
            return False
        free = [b for b in range(self.nblk)
                if self.block_state[b] == _B_FREE]
        if not free:
            return False
        import jax.numpy as jnp

        ctrl = self._ctrl()
        frames = self._frames()
        Lblk = self.Lblk
        while self._pending and free:
            p = self._pending.pop(0)
            b = free.pop(0)
            lo = b * Lblk
            n = len(p.lane_ids)
            # pad by cloning the first column
            sel = jnp.asarray(np.concatenate(
                [np.arange(n), np.zeros(max(Lblk - n, 0), np.int64)]))
            for name, i in self._plane_idx.items():
                self.state[i] = self.state[i].at[:, lo:lo + Lblk].set(
                    p.cols[name][:, sel])
            ctrl[b] = p.ctrl
            frames[b] = p.frames
            ids = np.full(Lblk, -1, np.int64)
            ids[:n] = p.lane_ids
            self.block_lanes[b] = ids
            self.block_state[b] = _B_LIVE
            self.block_steps[b] = p.steps0
            self._ctrl_dirty = True
            self._frames_dirty = True
        return True

    # -- SIMT residue ------------------------------------------------------
    def _to_simt(self, b: int, ctrl, frames, pages_over=None):
        """Queue a block's valid lanes for the final SIMT pass."""
        ids = self.block_lanes[b]
        vcols = np.nonzero(ids >= 0)[0]
        self.obs.instant("simt_residue_queue", cat="scheduler",
                         track=self._track, block=b, lanes=int(vcols.size))
        cols = self._extract_cols(b, vcols, {})
        self._simt_queue.append(_Pending(
            ctrl=ctrl.copy(), frames=frames.copy(), cols=cols,
            lane_ids=ids[vcols].astype(np.int64),
            steps0=int(self.block_steps[b]),
            pages=pages_over[vcols].astype(np.int32)
            if pages_over is not None else None))
        self._free_block(b)

    def _run_simt_residue(self):
        if not self._simt_queue:
            return
        import jax.numpy as jnp

        from wasmedge_tpu.batch.engine import BatchState

        self.fell_back_to_simt = True
        t_residue = self.obs.now()
        simt = self.eng.simt
        cfg = self.cfg
        L = simt.lanes
        D_s, CD_s = cfg.value_stack_depth, cfg.call_stack_depth
        img = self.eng.img
        simt_w = max(img.mem_pages_max * _PAGE_WORDS, 1) \
            if img.has_memory else 1
        NG = max(img.globals_lo.shape[0], 1)
        pc = np.zeros(L, np.int32)
        sp = np.zeros(L, np.int32)
        fp = np.zeros(L, np.int32)
        ob = np.zeros(L, np.int32)
        cd = np.zeros(L, np.int32)
        pages = np.zeros(L, np.int32)
        fuel = np.zeros(L, np.int32)
        trap = np.full(L, TRAP_DONE, np.int32)   # non-members: done
        retired0 = np.zeros(L, np.int64)
        s_lo = np.zeros((D_s, L), np.int32)
        s_hi = np.zeros((D_s, L), np.int32)
        g_lo = np.zeros((NG, L), np.int32)
        g_hi = np.zeros((NG, L), np.int32)
        mem = np.zeros((simt_w, L), np.int32)
        frp = np.zeros((CD_s, L), np.int32)
        frf = np.zeros((CD_s, L), np.int32)
        fro = np.zeros((CD_s, L), np.int32)
        simd = img.has_simd
        s_e2 = np.zeros((D_s, L), np.int32) if simd else None
        s_e3 = np.zeros((D_s, L), np.int32) if simd else None
        members = []
        for p in self._simt_queue:
            n = len(p.lane_ids)
            li = p.lane_ids
            members.append(li)
            pc[li] = p.ctrl[_C_PC]
            sp[li] = p.ctrl[_C_SP]
            fp[li] = p.ctrl[_C_FP]
            ob[li] = p.ctrl[_C_OB]
            cd[li] = p.ctrl[_C_CD]
            pages[li] = p.ctrl[_C_PAGES] if p.pages is None else p.pages
            if cfg.fuel_per_launch is not None:
                fuel[li] = max(int(p.ctrl[_C_FUEL]), 0)
            trap[li] = p.cols["trap"][0][:n]
            retired0[li] = p.steps0
            d = min(p.cols["slo"].shape[0], D_s)
            s_lo[:d, li] = p.cols["slo"][:d, :n]
            s_hi[:d, li] = p.cols["shi"][:d, :n]
            if simd:
                s_e2[:d, li] = p.cols["se2"][:d, :n]
                s_e3[:d, li] = p.cols["se3"][:d, :n]
            g = min(p.cols["glo"].shape[0], NG)
            g_lo[:g, li] = p.cols["glo"][:g, :n]
            g_hi[:g, li] = p.cols["ghi"][:g, :n]
            m = min(p.cols["mem"].shape[0], simt_w)
            mem[:m, li] = p.cols["mem"][:m, :n]
            ncd = min(p.frames.shape[1], CD_s)
            frp[:ncd, li] = p.frames[0, :ncd, None]
            frf[:ncd, li] = p.frames[1, :ncd, None]
            fro[:ncd, li] = p.frames[2, :ncd, None]
        from wasmedge_tpu.batch.engine import t0_state_planes

        state = BatchState(
            pc=jnp.asarray(pc), sp=jnp.asarray(sp), fp=jnp.asarray(fp),
            opbase=jnp.asarray(ob), call_depth=jnp.asarray(cd),
            trap=jnp.asarray(trap),
            retired=jnp.asarray(np.zeros(L, np.int32)),
            fuel=jnp.asarray(fuel), mem_pages=jnp.asarray(pages),
            stack_lo=jnp.asarray(s_lo), stack_hi=jnp.asarray(s_hi),
            fr_ret_pc=jnp.asarray(frp), fr_fp=jnp.asarray(frf),
            fr_opbase=jnp.asarray(fro),
            glob_lo=jnp.asarray(g_lo), glob_hi=jnp.asarray(g_hi),
            mem=jnp.asarray(mem),
            stack_e2=jnp.asarray(s_e2) if simd else None,
            stack_e3=jnp.asarray(s_e3) if simd else None,
            **t0_state_planes(img, cfg, L,
                              getattr(simt, "_t0kinds", None)))
        # account for work already done on the kernel so the caller's
        # max_steps bounds TOTAL execution, not each engine separately
        # (coarse like the pre-scheduler handoff: the max over members)
        total0 = max(int(p.steps0) for p in self._simt_queue)
        # v128 quarantine (VERDICT r5 weak #1): the XLA per-step v128
        # fallback is known to fault TPU workers on very long runs, so
        # a divergent v128 tenant's residue is step-capped; survivors
        # are re-run on the scalar engine (side-effect-free modules) or
        # trapped CostLimitExceeded instead of crashing the device
        # process under every other tenant.
        cap = getattr(cfg, "v128_residue_step_cap", None)
        simd_capped = bool(img.has_simd) and cap is not None
        max_steps_eff = min(self.max_steps, total0 + int(cap)) \
            if simd_capped else self.max_steps
        state, total = simt.run_from_state(state, total0, max_steps_eff)
        self._residue_steps = int(total)
        all_m = np.concatenate(members)
        trap_f = np.asarray(state.trap)
        ret_f = np.asarray(state.retired).astype(np.int64)
        self.trap[all_m] = trap_f[all_m]
        self.retired[all_m] = retired0[all_m] + ret_f[all_m]
        if self.nres:
            s_lo_f = np.asarray(state.stack_lo[:self.nres])
            s_hi_f = np.asarray(state.stack_hi[:self.nres])
            self.res_lo[:, all_m] = s_lo_f[:, all_m]
            self.res_hi[:, all_m] = s_hi_f[:, all_m]
        self.obs.span("simt_residue", t_residue, cat="scheduler",
                      track=self._track_simt, lanes=int(all_m.size),
                      steps=int(total))
        if simd_capped and max_steps_eff < self.max_steps:
            survivors = all_m[trap_f[all_m] == 0]
            if survivors.size:
                self._quarantine_lanes(survivors)

    def _quarantine_lanes(self, lanes: np.ndarray):
        """Lanes still running when the v128 residue cap hit: re-run
        them from their original arguments on the scalar engine when
        the module is side-effect-free (no host imports), else report
        CostLimitExceeded.  Either way the device process survives.

        The gas-metered scalar re-run itself is the shared bottom rung
        of the supervisor's degradation ladder (batch/supervisor.py
        scalar_rerun); host-side errors inside it surface as
        FailureRecords in the process-wide log instead of being
        silently swallowed."""
        self.quarantined = getattr(self, "quarantined", 0) + int(lanes.size)
        self.obs.instant("quarantine", cat="scheduler", track=self._track_simt,
                         lanes=int(lanes.size))
        inst = self.inst
        has_host = any(getattr(f, "kind", None) == "host"
                       for f in inst.funcs)
        if has_host:
            self.trap[lanes] = int(ErrCode.CostLimitExceeded)
            return
        from wasmedge_tpu.batch.supervisor import scalar_rerun
        from wasmedge_tpu.common.statistics import record_failure

        cells, trap_codes, records = scalar_rerun(
            inst, getattr(self.eng.simt, "conf", None), self.func_name,
            self.func_idx, self.args, np.asarray(lanes, np.int64),
            self.max_steps)
        for rec in records:
            record_failure(rec)
        nres = len(inst.funcs[self.func_idx].functype.results)
        for col, lane in enumerate(np.asarray(lanes, np.int64)):
            code = int(trap_codes[col])
            if code == TRAP_DONE:
                for r in range(nres):
                    cell = int(cells[r, col])
                    self.res_lo[r, lane] = np.int32(np.uint32(
                        cell & 0xFFFFFFFF))
                    self.res_hi[r, lane] = np.int32(np.uint32(
                        (cell >> 32) & 0xFFFFFFFF))
            self.trap[int(lane)] = code

    # -- result ------------------------------------------------------------
    def result(self):
        from wasmedge_tpu.batch.engine import BatchResult
        from wasmedge_tpu.batch.pallas_engine import decode_result_rows

        results = decode_result_rows(self.res_lo, self.res_hi, self.nres)
        steps = max(int(self.block_steps.max(initial=0)),
                    getattr(self, "_residue_steps", 0))
        return BatchResult(results=results, trap=self.trap,
                           retired=self.retired, steps=steps)
