"""v128 lane ops over 4x int32 planes — the batch engines' SIMD kernels.

A v128 cell is four int32 plane words per lane (e0..e3, little-endian:
e0 holds bytes 0-3).  Shapes i8x16/i16x8/i32x4 operate on each word
independently; i64x2 pairs (e0,e1)/(e2,e3) and reuses the 64-bit pair
kernels from batch/laneops.py.  Sub-byte shapes unpack each word into
per-lane byte/half vectors, apply the op on full int32 arrays (the lane
axis stays vectorized on the VPU), and repack — 16x the op count of a
native byte ALU but branch-free and bit-exact, which is what the
batched path needs (the reference's v128 section:
/root/reference/lib/executor/engine/engine.cpp ~700-1610).

Float f32x4/f64x2 families reuse the scalar batch ALU kernels
(laneops alu2/alu1: native float32 with canonical-NaN wrapping for f32,
the bit-exact softfloat binary64 kernels for f64) applied per plane /
per plane-pair, so vector float semantics are identical to the scalar
batch path by construction.  The narrowing / widening / extended
multiply / pairwise-add integer extensions operate on the packed words
directly (reference v128 section:
/root/reference/lib/executor/engine/engine.cpp ~700-1610)."""

from __future__ import annotations

from typing import Dict, List, Tuple

# ---------------------------------------------------------------------------
# op name tables (ids = index; shared by image encoding and engines)
# ---------------------------------------------------------------------------
_ICMP = ["eq", "ne", "lt_s", "lt_u", "gt_s", "gt_u", "le_s", "le_u",
         "ge_s", "ge_u"]
_ICMP_S = ["eq", "ne", "lt_s", "gt_s", "le_s", "ge_s"]  # i64x2 set

_FBIN = ["add", "sub", "mul", "div", "min", "max", "pmin", "pmax",
         "eq", "ne", "lt", "gt", "le", "ge"]
_FUN = ["abs", "neg", "sqrt", "ceil", "floor", "trunc", "nearest"]

V2_NAMES: List[str] = (
    ["v128.and", "v128.or", "v128.xor", "v128.andnot"]
    + [f"i8x16.{n}" for n in
       ["add", "sub", "add_sat_s", "add_sat_u", "sub_sat_s", "sub_sat_u",
        "min_s", "min_u", "max_s", "max_u", "avgr_u", "swizzle"] + _ICMP]
    + [f"i16x8.{n}" for n in
       ["add", "sub", "mul", "add_sat_s", "add_sat_u", "sub_sat_s",
        "sub_sat_u", "min_s", "min_u", "max_s", "max_u", "avgr_u"] + _ICMP]
    + [f"i32x4.{n}" for n in
       ["add", "sub", "mul", "min_s", "min_u", "max_s", "max_u"] + _ICMP]
    + [f"i64x2.{n}" for n in ["add", "sub", "mul"] + _ICMP_S]
    # appended families keep earlier sub ids stable:
    + [f"f32x4.{n}" for n in _FBIN]
    + [f"f64x2.{n}" for n in _FBIN]
    + ["i8x16.narrow_i16x8_s", "i8x16.narrow_i16x8_u",
       "i16x8.narrow_i32x4_s", "i16x8.narrow_i32x4_u",
       "i16x8.q15mulr_sat_s", "i32x4.dot_i16x8_s"]
    + [f"i16x8.extmul_{p}_i8x16_{s}"
       for p in ("low", "high") for s in ("s", "u")]
    + [f"i32x4.extmul_{p}_i16x8_{s}"
       for p in ("low", "high") for s in ("s", "u")]
    + [f"i64x2.extmul_{p}_i32x4_{s}"
       for p in ("low", "high") for s in ("s", "u")]
)
V1_NAMES: List[str] = (
    ["v128.not", "i8x16.abs", "i8x16.neg", "i8x16.popcnt",
     "i16x8.abs", "i16x8.neg", "i32x4.abs", "i32x4.neg",
     "i64x2.abs", "i64x2.neg"]
    + [f"f32x4.{n}" for n in _FUN]
    + [f"f64x2.{n}" for n in _FUN]
    + ["i32x4.trunc_sat_f32x4_s", "i32x4.trunc_sat_f32x4_u",
       "f32x4.convert_i32x4_s", "f32x4.convert_i32x4_u",
       "i32x4.trunc_sat_f64x2_s_zero", "i32x4.trunc_sat_f64x2_u_zero",
       "f64x2.convert_low_i32x4_s", "f64x2.convert_low_i32x4_u",
       "f32x4.demote_f64x2_zero", "f64x2.promote_low_f32x4"]
    + [f"i16x8.extend_{p}_i8x16_{s}"
       for p in ("low", "high") for s in ("s", "u")]
    + [f"i32x4.extend_{p}_i16x8_{s}"
       for p in ("low", "high") for s in ("s", "u")]
    + [f"i64x2.extend_{p}_i32x4_{s}"
       for p in ("low", "high") for s in ("s", "u")]
    + ["i16x8.extadd_pairwise_i8x16_s", "i16x8.extadd_pairwise_i8x16_u",
       "i32x4.extadd_pairwise_i16x8_s", "i32x4.extadd_pairwise_i16x8_u"]
)
VTEST_NAMES: List[str] = (
    ["v128.any_true"]
    + [f"{s}.all_true" for s in ("i8x16", "i16x8", "i32x4", "i64x2")]
    + [f"{s}.bitmask" for s in ("i8x16", "i16x8", "i32x4", "i64x2")]
)
VSHIFT_NAMES: List[str] = [
    f"{s}.{k}" for s in ("i8x16", "i16x8", "i32x4", "i64x2")
    for k in ("shl", "shr_s", "shr_u")]
VSPLAT_NAMES: List[str] = [f"{s}.splat" for s in
                           ("i8x16", "i16x8", "i32x4", "i64x2",
                            "f32x4", "f64x2")]
VEXTRACT_NAMES: List[str] = [
    "i8x16.extract_lane_s", "i8x16.extract_lane_u",
    "i16x8.extract_lane_s", "i16x8.extract_lane_u",
    "i32x4.extract_lane", "i64x2.extract_lane",
    "f32x4.extract_lane", "f64x2.extract_lane"]
VREPLACE_NAMES: List[str] = [f"{s}.replace_lane" for s in
                             ("i8x16", "i16x8", "i32x4", "i64x2",
                              "f32x4", "f64x2")]

V2_SUB = {n: i for i, n in enumerate(V2_NAMES)}
V1_SUB = {n: i for i, n in enumerate(V1_NAMES)}
VTEST_SUB = {n: i for i, n in enumerate(VTEST_NAMES)}
VSHIFT_SUB = {n: i for i, n in enumerate(VSHIFT_NAMES)}
VSPLAT_SUB = {n: i for i, n in enumerate(VSPLAT_NAMES)}
VEXTRACT_SUB = {n: i for i, n in enumerate(VEXTRACT_NAMES)}
VREPLACE_SUB = {n: i for i, n in enumerate(VREPLACE_NAMES)}

SUPPORTED_V128 = (set(V2_NAMES) | set(V1_NAMES) | set(VTEST_NAMES)
                  | set(VSHIFT_NAMES) | set(VSPLAT_NAMES)
                  | set(VEXTRACT_NAMES) | set(VREPLACE_NAMES)
                  | {"v128.const", "v128.load", "v128.store",
                     "i8x16.shuffle", "v128.bitselect"})


# ---------------------------------------------------------------------------
# jnp kernels (imported lazily so the module stays importable without jax)
# ---------------------------------------------------------------------------
def _j():
    import jax.numpy as jnp
    from jax import lax

    return jnp, lax


def _bytes(w, signed):
    """int32 word [L] -> list of 4 per-byte int32 arrays."""
    jnp, lax = _j()
    out = []
    for k in range(4):
        b = lax.shift_right_logical(w, 8 * k) & 0xFF
        if signed:
            b = lax.shift_right_arithmetic(
                lax.shift_left(b, 24), 24)
        out.append(b)
    return out


def _pack_bytes(bs):
    jnp, lax = _j()
    w = bs[0] & 0xFF
    for k in range(1, 4):
        w = w | lax.shift_left(bs[k] & 0xFF, 8 * k)
    return w


def _halves(w, signed):
    jnp, lax = _j()
    out = []
    for k in range(2):
        h = lax.shift_right_logical(w, 16 * k) & 0xFFFF
        if signed:
            h = lax.shift_right_arithmetic(lax.shift_left(h, 16), 16)
        out.append(h)
    return out


def _pack_halves(hs):
    jnp, lax = _j()
    return (hs[0] & 0xFFFF) | lax.shift_left(hs[1] & 0xFFFF, 16)


def _sat(x, lo, hi):
    jnp, _ = _j()
    return jnp.clip(x, lo, hi)


def _elemwise(shape_w, signed, fn, x, y=None):
    """Apply fn to per-element int32 arrays of one 32-bit word."""
    if shape_w == 8:
        xs = _bytes(x, signed)
        ys = _bytes(y, signed) if y is not None else [None] * 4
        return _pack_bytes([fn(a, b) for a, b in zip(xs, ys)])
    if shape_w == 16:
        xs = _halves(x, signed)
        ys = _halves(y, signed) if y is not None else [None] * 2
        return _pack_halves([fn(a, b) for a, b in zip(xs, ys)])
    return fn(x, y)


def _u32(x):
    jnp, _ = _j()
    return x.astype(jnp.uint32)


def _b2m(cond, shape_w):
    """bool -> all-ones element mask (int32 word context)."""
    jnp, _ = _j()
    ones = {8: 0xFF, 16: 0xFFFF, 32: -1}[shape_w]
    return jnp.where(cond, jnp.int32(ones), jnp.int32(0))


def _int_binop(name, shape_w):
    """Return fn(a, b) over sign-appropriate element arrays, or None."""
    jnp, lax = _j()
    lim = {8: (-128, 127, 0, 255), 16: (-32768, 32767, 0, 65535)}

    def u(v):
        # _elemwise gives signed or unsigned depending on `signed` flag;
        # unsigned ops request signed=False so values are already >= 0
        return v

    if name == "add":
        return lambda a, b: a + b
    if name == "sub":
        return lambda a, b: a - b
    if name == "mul":
        return lambda a, b: a * b
    if name in ("add_sat_s", "sub_sat_s"):
        lo, hi = lim[shape_w][0], lim[shape_w][1]
        op = (lambda a, b: a + b) if name.startswith("add") \
            else (lambda a, b: a - b)
        return lambda a, b: _sat(op(a, b), lo, hi)
    if name in ("add_sat_u", "sub_sat_u"):
        hi = lim[shape_w][3]
        op = (lambda a, b: a + b) if name.startswith("add") \
            else (lambda a, b: a - b)
        return lambda a, b: _sat(op(a, b), 0, hi)
    if name == "min_s" or name == "min_u":
        return lambda a, b: jnp.minimum(a, b)
    if name == "max_s" or name == "max_u":
        return lambda a, b: jnp.maximum(a, b)
    if name == "avgr_u":
        return lambda a, b: lax.shift_right_logical(a + b + 1, 1)
    if name in ("eq", "ne", "lt_s", "lt_u", "gt_s", "gt_u",
                "le_s", "le_u", "ge_s", "ge_u"):
        cmp = {"eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
               "lt_s": lambda a, b: a < b, "lt_u": lambda a, b: a < b,
               "gt_s": lambda a, b: a > b, "gt_u": lambda a, b: a > b,
               "le_s": lambda a, b: a <= b, "le_u": lambda a, b: a <= b,
               "ge_s": lambda a, b: a >= b, "ge_u": lambda a, b: a >= b,
               }[name]
        return lambda a, b: _b2m(cmp(a, b), shape_w)
    return None


def _signedness(name: str) -> bool:
    """Whether element extraction should sign-extend for this op."""
    if name.endswith("_u") or name == "avgr_u":
        return False
    return True


def _v2_float(px: str, op: str):
    """f32x4/f64x2 binary ops, built on the scalar batch ALU kernels
    (laneops alu2: canonical-NaN float32 for f32, softfloat binary64 for
    f64) so vector float semantics equal the scalar batch path by
    construction.  Comparisons widen the scalar 0/1 result to the
    all-ones element mask v128 comparisons produce."""
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops
    from wasmedge_tpu.batch.image import (
        ALU2_F32_BASE, ALU2_F64_BASE, _F32_BIN, _F64_BIN)

    alu2 = lo_ops.alu2_fns()
    cmps = ("eq", "ne", "lt", "gt", "le", "ge")
    if px == "f32x4":
        base, bins = ALU2_F32_BASE, _F32_BIN
        if op in ("pmin", "pmax"):
            lt = alu2[base + bins.index("lt")]

            def pm(x, y, op=op):
                out = []
                for a, b in zip(x, y):
                    z = jnp.zeros_like(a)
                    # pmin: b < a ? b : a; pmax: a < b ? b : a
                    c, _ = lt(b, z, a, z) if op == "pmin" else lt(a, z, b, z)
                    out.append(jnp.where(c != 0, b, a))
                return tuple(out)
            return pm
        fn = alu2[base + bins.index(op)]
        mask = op in cmps

        def per_word(x, y):
            out = []
            for a, b in zip(x, y):
                rl, _ = fn(a, jnp.zeros_like(a), b, jnp.zeros_like(b))
                out.append(jnp.where(rl != 0, jnp.int32(-1), jnp.int32(0))
                           if mask else rl)
            return tuple(out)
        return per_word
    base, bins = ALU2_F64_BASE, _F64_BIN
    if op in ("pmin", "pmax"):
        lt = alu2[base + bins.index("lt")]

        def pm64(x, y, op=op):
            r = []
            for k in (0, 2):
                al, ah, bl, bh = x[k], x[k + 1], y[k], y[k + 1]
                c, _ = (lt(bl, bh, al, ah) if op == "pmin"
                        else lt(al, ah, bl, bh))
                r.append(jnp.where(c != 0, bl, al))
                r.append(jnp.where(c != 0, bh, ah))
            return tuple(r)
        return pm64
    fn = alu2[base + bins.index(op)]
    mask = op in cmps

    def bin64(x, y):
        r = []
        for k in (0, 2):
            rl, rh = fn(x[k], x[k + 1], y[k], y[k + 1])
            if mask:
                m = jnp.where(rl != 0, jnp.int32(-1), jnp.int32(0))
                rl = rh = m
            r.extend((rl, rh))
        return tuple(r)
    return bin64


def _v2_intext(name: str):
    """Narrowing / q15 / dot / extended-multiply integer extensions."""
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops

    if name.startswith("i8x16.narrow_i16x8"):
        lo_, hi_ = (-128, 127) if name.endswith("_s") else (0, 255)

        def nar(x, y):
            hs = [h for w in x for h in _halves(w, True)] + \
                 [h for w in y for h in _halves(w, True)]
            bs = [_sat(h, lo_, hi_) for h in hs]
            return tuple(_pack_bytes(bs[4 * k:4 * k + 4]) for k in range(4))
        return nar
    if name.startswith("i16x8.narrow_i32x4"):
        lo_, hi_ = (-32768, 32767) if name.endswith("_s") else (0, 65535)

        def nar(x, y):
            ws = [_sat(w, lo_, hi_) for w in list(x) + list(y)]
            return tuple(_pack_halves([ws[2 * k], ws[2 * k + 1]])
                         for k in range(4))
        return nar
    if name == "i16x8.q15mulr_sat_s":
        def q15(x, y):
            out = []
            for a, b in zip(x, y):
                rs = [_sat(lax.shift_right_arithmetic(p * q + 0x4000, 15),
                           -32768, 32767)
                      for p, q in zip(_halves(a, True), _halves(b, True))]
                out.append(_pack_halves(rs))
            return tuple(out)
        return q15
    if name == "i32x4.dot_i16x8_s":
        def dot(x, y):
            out = []
            for a, b in zip(x, y):
                ha, hb = _halves(a, True), _halves(b, True)
                out.append(ha[0] * hb[0] + ha[1] * hb[1])
            return tuple(out)
        return dot
    if ".extmul_" not in name:
        return None
    px, rest = name.split(".", 1)
    parts = rest.split("_")          # extmul, low|high, <src>, s|u
    low = parts[1] == "low"
    signed = parts[-1] == "s"
    if px == "i16x8":
        def em(x, y):
            xb = [b for w in x for b in _bytes(w, signed)]
            yb = [b for w in y for b in _bytes(w, signed)]
            sel = range(0, 8) if low else range(8, 16)
            ps = [xb[i] * yb[i] for i in sel]
            return tuple(_pack_halves([ps[2 * k], ps[2 * k + 1]])
                         for k in range(4))
        return em
    if px == "i32x4":
        def em(x, y):
            xh = [h for w in x for h in _halves(w, signed)]
            yh = [h for w in y for h in _halves(w, signed)]
            sel = range(0, 4) if low else range(4, 8)
            return tuple(xh[i] * yh[i] for i in sel)
        return em

    def em64(x, y):
        idx = (0, 1) if low else (2, 3)
        r = []
        for i in idx:
            a, b = x[i], y[i]
            ah = (lax.shift_right_arithmetic(a, 31) if signed
                  else jnp.zeros_like(a))
            bh = (lax.shift_right_arithmetic(b, 31) if signed
                  else jnp.zeros_like(b))
            r.extend(lo_ops.mul64(a, ah, b, bh))
        return tuple(r)
    return em64


def v2_fn(sub: int):
    """Binary v128 op: (x4, y4) -> r4 where x4/y4 are 4-plane tuples."""
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops

    name = V2_NAMES[sub]
    px0 = name.split(".", 1)[0]
    if px0 in ("f32x4", "f64x2"):
        return _v2_float(px0, name.split(".", 1)[1])
    ext = _v2_intext(name)
    if ext is not None:
        return ext
    if name == "v128.and":
        return lambda x, y: tuple(a & b for a, b in zip(x, y))
    if name == "v128.or":
        return lambda x, y: tuple(a | b for a, b in zip(x, y))
    if name == "v128.xor":
        return lambda x, y: tuple(a ^ b for a, b in zip(x, y))
    if name == "v128.andnot":
        return lambda x, y: tuple(a & ~b for a, b in zip(x, y))
    if name == "i8x16.swizzle":
        def swizzle(x, y):
            # dest byte j = src byte s (s = selector byte j), 0 if s>=16
            xb = [b for w in x for b in _bytes(w, False)]  # 16 src bytes
            out = []
            for wi in range(4):
                sel = _bytes(y[wi], False)
                obs = []
                for s in sel:
                    v = jnp.zeros_like(s)
                    for j in range(16):
                        v = jnp.where(s == j, xb[j], v)
                    obs.append(v)
                out.append(_pack_bytes(obs))
            return tuple(out)
        return swizzle
    px, op = name.split(".", 1)
    if px == "i64x2":
        def pair(x, y, op=op):
            r = []
            for k in (0, 2):
                xl, xh, yl, yh = x[k], x[k + 1], y[k], y[k + 1]
                if op == "add":
                    lo, hi = lo_ops.add64(xl, xh, yl, yh)
                elif op == "sub":
                    lo, hi = lo_ops.sub64(xl, xh, yl, yh)
                elif op == "mul":
                    lo, hi = lo_ops.mul64(xl, xh, yl, yh)
                else:
                    if op == "eq":
                        c = lo_ops.eq64(xl, xh, yl, yh)
                    elif op == "ne":
                        c = ~lo_ops.eq64(xl, xh, yl, yh)
                    elif op == "lt_s":
                        c = lo_ops.lt64_s(xl, xh, yl, yh)
                    elif op == "gt_s":
                        c = lo_ops.lt64_s(yl, yh, xl, xh)
                    elif op == "le_s":
                        c = ~lo_ops.lt64_s(yl, yh, xl, xh)
                    else:  # ge_s
                        c = ~lo_ops.lt64_s(xl, xh, yl, yh)
                    m = jnp.where(c, jnp.int32(-1), jnp.int32(0))
                    lo, hi = m, m
                r.extend((lo, hi))
            return tuple(r)
        return pair
    shape_w = {"i8x16": 8, "i16x8": 16, "i32x4": 32}[px]
    signed = _signedness(op)
    if shape_w == 32:
        fn32 = _int_binop(op, 32)
        if op.endswith("_u"):
            def u32op(x, y, op=op):
                out = []
                for a, b in zip(x, y):
                    au, bu = _u32(a), _u32(b)
                    if op in ("min_u", "max_u"):
                        r = (jnp.minimum(au, bu) if op == "min_u"
                             else jnp.maximum(au, bu)).astype(jnp.int32)
                    else:
                        cmp = {"lt_u": au < bu, "gt_u": au > bu,
                               "le_u": au <= bu, "ge_u": au >= bu}[op]
                        r = jnp.where(cmp, jnp.int32(-1), jnp.int32(0))
                    out.append(r)
                return tuple(out)
            return u32op
        return lambda x, y: tuple(
            _elemwise(32, True, lambda a, b: fn32(a, b), a2, b2)
            for a2, b2 in zip(x, y))
    fn = _int_binop(op, shape_w)
    return lambda x, y: tuple(
        _elemwise(shape_w, signed, fn, a, b) for a, b in zip(x, y))


def _v1_special(name: str):
    """Float unaries, float<->int conversions and the widening /
    pairwise-add integer extensions (unary v128 ops)."""
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops
    from wasmedge_tpu.batch.image import ALU1_SUB

    alu1 = lo_ops.alu1_fns()

    def a1(nm):
        return alu1[ALU1_SUB[nm]]

    px, op = name.split(".", 1)
    if px == "f32x4" and op in _FUN:
        fn = a1(f"f32.{op}")
        return lambda x: tuple(fn(w, jnp.zeros_like(w))[0] for w in x)
    if px == "f64x2" and op in _FUN:
        fn = a1(f"f64.{op}")

        def un64(x):
            r = []
            for k in (0, 2):
                lo, hi = fn(x[k], x[k + 1])
                r.extend((lo, hi))
            return tuple(r)
        return un64
    per_word_cvt = {
        "i32x4.trunc_sat_f32x4_s": "i32.trunc_sat_f32_s",
        "i32x4.trunc_sat_f32x4_u": "i32.trunc_sat_f32_u",
        "f32x4.convert_i32x4_s": "f32.convert_i32_s",
        "f32x4.convert_i32x4_u": "f32.convert_i32_u",
    }
    if name in per_word_cvt:
        fn = a1(per_word_cvt[name])
        return lambda x: tuple(fn(w, jnp.zeros_like(w))[0] for w in x)
    if name.startswith("i32x4.trunc_sat_f64x2"):
        fn = a1("i32.trunc_sat_f64_s" if "_s_" in name
                else "i32.trunc_sat_f64_u")

        def ts(x):
            r0, r1 = fn(x[0], x[1])[0], fn(x[2], x[3])[0]
            z = jnp.zeros_like(r0)
            return (r0, r1, z, z)
        return ts
    if name.startswith("f64x2.convert_low_i32x4"):
        fn = a1("f64.convert_i32_s" if name.endswith("_s")
                else "f64.convert_i32_u")

        def cv(x):
            l0, h0 = fn(x[0], jnp.zeros_like(x[0]))
            l1, h1 = fn(x[1], jnp.zeros_like(x[1]))
            return (l0, h0, l1, h1)
        return cv
    if name == "f32x4.demote_f64x2_zero":
        fn = a1("f32.demote_f64")

        def dm(x):
            r0, r1 = fn(x[0], x[1])[0], fn(x[2], x[3])[0]
            z = jnp.zeros_like(r0)
            return (r0, r1, z, z)
        return dm
    if name == "f64x2.promote_low_f32x4":
        fn = a1("f64.promote_f32")

        def pr(x):
            l0, h0 = fn(x[0], jnp.zeros_like(x[0]))
            l1, h1 = fn(x[1], jnp.zeros_like(x[1]))
            return (l0, h0, l1, h1)
        return pr
    if ".extend_" in name:
        parts = op.split("_")        # extend, low|high, <src>, s|u
        low = parts[1] == "low"
        signed = parts[-1] == "s"
        if px == "i16x8":
            def ex(x):
                bs = [b for w in x for b in _bytes(w, signed)]
                sel = bs[0:8] if low else bs[8:16]
                return tuple(_pack_halves([sel[2 * k], sel[2 * k + 1]])
                             for k in range(4))
            return ex
        if px == "i32x4":
            def ex(x):
                hs = [h for w in x for h in _halves(w, signed)]
                return tuple(hs[0:4] if low else hs[4:8])
            return ex

        def ex64(x):
            idx = (0, 1) if low else (2, 3)
            r = []
            for i in idx:
                w = x[i]
                hi = (lax.shift_right_arithmetic(w, 31) if signed
                      else jnp.zeros_like(w))
                r.extend((w, hi))
            return tuple(r)
        return ex64
    if ".extadd_pairwise_" in name:
        signed = name.endswith("_s")
        if px == "i16x8":
            def ea(x):
                out = []
                for w in x:
                    bs = _bytes(w, signed)
                    out.append(_pack_halves([bs[0] + bs[1], bs[2] + bs[3]]))
                return tuple(out)
            return ea

        def ea32(x):
            out = []
            for w in x:
                hs = _halves(w, signed)
                out.append(hs[0] + hs[1])
            return tuple(out)
        return ea32
    return None


def v1_fn(sub: int):
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops

    name = V1_NAMES[sub]
    if name == "v128.not":
        return lambda x: tuple(~a for a in x)
    special = _v1_special(name)
    if special is not None:
        return special
    if name == "i8x16.popcnt":
        def pc(x):
            out = []
            for w in x:
                bs = _bytes(w, False)
                rs = []
                for b in bs:
                    v = b - (lax.shift_right_logical(b, 1) & 0x55)
                    v = (v & 0x33) + (lax.shift_right_logical(v, 2) & 0x33)
                    v = (v + lax.shift_right_logical(v, 4)) & 0x0F
                    rs.append(v)
                out.append(_pack_bytes(rs))
            return tuple(out)
        return pc
    px, op = name.split(".", 1)
    if px == "i64x2":
        def pair(x, op=op):
            r = []
            for k in (0, 2):
                xl, xh = x[k], x[k + 1]
                nl, nh = lo_ops.sub64(jnp.zeros_like(xl),
                                      jnp.zeros_like(xh), xl, xh)
                if op == "neg":
                    lo, hi = nl, nh
                else:  # abs
                    neg = xh < 0
                    lo = jnp.where(neg, nl, xl)
                    hi = jnp.where(neg, nh, xh)
                r.extend((lo, hi))
            return tuple(r)
        return pair
    shape_w = {"i8x16": 8, "i16x8": 16, "i32x4": 32}[px]

    def fn(a, _b):
        if op == "neg":
            return -a
        return jnp.abs(a)

    return lambda x: tuple(_elemwise(shape_w, True, fn, a) for a in x)


def vtest_fn(sub: int):
    """v128 -> per-lane i32 scalar."""
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops

    name = VTEST_NAMES[sub]
    if name == "v128.any_true":
        return lambda x: jnp.where(
            (x[0] | x[1] | x[2] | x[3]) != 0, 1, 0).astype(jnp.int32)
    px, op = name.split(".", 1)
    if op == "all_true":
        if px == "i64x2":
            return lambda x: jnp.where(
                ((x[0] | x[1]) != 0) & ((x[2] | x[3]) != 0),
                1, 0).astype(jnp.int32)
        shape_w = {"i8x16": 8, "i16x8": 16, "i32x4": 32}[px]

        def all_true(x, shape_w=shape_w):
            ok = None
            for w in x:
                if shape_w == 32:
                    nz = w != 0
                    ok = nz if ok is None else (ok & nz)
                    continue
                els = (_bytes(w, False) if shape_w == 8
                       else _halves(w, False))
                for e in els:
                    nz = e != 0
                    ok = nz if ok is None else (ok & nz)
            return jnp.where(ok, 1, 0).astype(jnp.int32)
        return all_true
    # bitmask: top bit of each element, packed little-lane-first
    if px == "i64x2":
        return lambda x: (
            lax.shift_right_logical(x[1], 31) & 1
            | lax.shift_left(lax.shift_right_logical(x[3], 31) & 1, 1)
        ).astype(jnp.int32)
    shape_w = {"i8x16": 8, "i16x8": 16, "i32x4": 32}[px]

    def bitmask(x, shape_w=shape_w):
        acc = jnp.zeros_like(x[0])
        lane = 0
        for w in x:
            if shape_w == 32:
                acc = acc | lax.shift_left(
                    lax.shift_right_logical(w, 31) & 1, lane)
                lane += 1
                continue
            els = (_bytes(w, False) if shape_w == 8
                   else _halves(w, False))
            top = shape_w - 1
            for e in els:
                acc = acc | lax.shift_left(
                    lax.shift_right_logical(e, top) & 1, lane)
                lane += 1
        return acc.astype(jnp.int32)
    return bitmask


def vshift_fn(sub: int):
    """(v128, i32 shift) -> v128."""
    jnp, lax = _j()
    from wasmedge_tpu.batch import laneops as lo_ops

    name = VSHIFT_NAMES[sub]
    px, op = name.split(".", 1)
    if px == "i64x2":
        def sh64(x, n, op=op):
            n = n & 63
            r = []
            for k in (0, 2):
                if op == "shl":
                    lo, hi = lo_ops.shl64(x[k], x[k + 1], n)
                elif op == "shr_s":
                    lo, hi = lo_ops.shr64_s(x[k], x[k + 1], n)
                else:
                    lo, hi = lo_ops.shr64_u(x[k], x[k + 1], n)
                r.extend((lo, hi))
            return tuple(r)
        return sh64
    shape_w = {"i8x16": 8, "i16x8": 16, "i32x4": 32}[px]
    signed = op == "shr_s"

    def sh(x, n, op=op, shape_w=shape_w, signed=signed):
        n = n & (shape_w - 1)

        def one(a, _b):
            if op == "shl":
                return lax.shift_left(a, n)
            if op == "shr_s":
                return lax.shift_right_arithmetic(a, n)
            if shape_w == 32:
                return lax.shift_right_logical(a, n)
            return lax.shift_right_logical(a & ((1 << shape_w) - 1), n)
        if shape_w == 32:
            return tuple(one(a, None) for a in x)
        return tuple(_elemwise(shape_w, signed, one, a) for a in x)
    return sh


def vsplat_fn(sub: int):
    """(lo, hi scalar planes) -> v128 4-plane."""
    jnp, lax = _j()

    name = VSPLAT_NAMES[sub]
    px = name.split(".", 1)[0]

    def splat(lo, hi, px=px):
        if px == "i8x16":
            b = lo & 0xFF
            w = b * jnp.int32(0x01010101)
            return (w, w, w, w)
        if px == "i16x8":
            h = lo & 0xFFFF
            w = h | lax.shift_left(h, 16)
            return (w, w, w, w)
        if px in ("i32x4", "f32x4"):
            return (lo, lo, lo, lo)
        return (lo, hi, lo, hi)      # i64x2 / f64x2
    return splat


def vbitselect():
    def f(v1, v2, c):
        return tuple((a & m) | (b & ~m) for a, b, m in zip(v1, v2, c))
    return f


# ---------------------------------------------------------------------------
# dynamic variants: lane indices / masks as PER-LANE arrays (the SIMT
# engine executes all lanes at once, each potentially at a different pc)
# ---------------------------------------------------------------------------
def vextract_dyn(sub: int):
    """(x4, lane_arr) -> (lo, hi) with per-lane dynamic lane index."""
    jnp, lax = _j()

    name = VEXTRACT_NAMES[sub]
    px = name.split(".", 1)[0]
    signed = name.endswith("_s")

    def ex(x, lane):
        if px == "i8x16":
            wi = lax.shift_right_logical(lane, 2)
            w = x[0]
            for k in range(1, 4):
                w = jnp.where(wi == k, x[k], w)
            b = lax.shift_right_logical(w, 8 * (lane & 3)) & 0xFF
            if signed:
                b = lax.shift_right_arithmetic(lax.shift_left(b, 24), 24)
            return b, jnp.zeros_like(b)
        if px == "i16x8":
            wi = lax.shift_right_logical(lane, 1)
            w = x[0]
            for k in range(1, 4):
                w = jnp.where(wi == k, x[k], w)
            h = lax.shift_right_logical(w, 16 * (lane & 1)) & 0xFFFF
            if signed:
                h = lax.shift_right_arithmetic(lax.shift_left(h, 16), 16)
            return h, jnp.zeros_like(h)
        if px in ("i32x4", "f32x4"):
            w = x[0]
            for k in range(1, 4):
                w = jnp.where(lane == k, x[k], w)
            return w, jnp.zeros_like(w)
        lo = jnp.where(lane == 0, x[0], x[2])
        hi = jnp.where(lane == 0, x[1], x[3])
        return lo, hi
    return ex


def vreplace_dyn(sub: int):
    """(x4, lane_arr, lo, hi) -> x4 with per-lane dynamic lane index."""
    jnp, lax = _j()

    name = VREPLACE_NAMES[sub]
    px = name.split(".", 1)[0]

    def rp(x, lane, lo, hi):
        out = []
        if px == "i8x16":
            wi = lax.shift_right_logical(lane, 2)
            bmask = lax.shift_left(jnp.int32(0xFF), 8 * (lane & 3))
            bval = lax.shift_left(lo & 0xFF, 8 * (lane & 3))
            for k in range(4):
                hit = wi == k
                out.append(jnp.where(hit, (x[k] & ~bmask) | (bval & bmask),
                                     x[k]))
            return tuple(out)
        if px == "i16x8":
            wi = lax.shift_right_logical(lane, 1)
            hmask = lax.shift_left(jnp.int32(0xFFFF), 16 * (lane & 1))
            hval = lax.shift_left(lo & 0xFFFF, 16 * (lane & 1))
            for k in range(4):
                hit = wi == k
                out.append(jnp.where(hit, (x[k] & ~hmask) | (hval & hmask),
                                     x[k]))
            return tuple(out)
        if px in ("i32x4", "f32x4"):
            for k in range(4):
                out.append(jnp.where(lane == k, lo, x[k]))
            return tuple(out)
        for k in range(2):
            out.append(jnp.where(lane == k, lo, x[2 * k]))
            out.append(jnp.where(lane == k, hi, x[2 * k + 1]))
        return (out[0], out[1], out[2], out[3])
    return rp


def vshuffle_dyn():
    """(x4, y4, m4) -> shuffled v128; m4 = per-lane mask planes (each
    selector byte in 0..31 selects from the 32 source bytes)."""
    jnp, lax = _j()

    def shuf(x, y, m):
        src = []
        for w in x:
            src.extend(_bytes(w, False))
        for w in y:
            src.extend(_bytes(w, False))
        out = []
        for wi in range(4):
            sel = _bytes(m[wi], False)
            obs = []
            for s in sel:
                v = jnp.zeros_like(s)
                for j in range(32):
                    v = jnp.where(s == j, src[j], v)
                obs.append(v)
            out.append(_pack_bytes(obs))
        return tuple(out)
    return shuf
