"""Software IEEE-754 binary64 on (lo, hi) int32 lane planes.

The TPU has no f64 units and XLA's x64 emulation is not bit-exact, so the
batch engines carry their own softfloat — the hard part SURVEY.md §7(b)
predicted for bit-exact f64 on a 32-bit-lane ISA.  Every op is elementwise
over [lanes]-shaped int32 (lo, hi) pairs built from the 64-bit integer
helpers in laneops.py, with round-to-nearest-even, subnormals, signed
zeros, and canonical-NaN outputs matching executor/numeric.py (which the
parity suite pins to the reference's binary_numeric.ipp semantics).

Representation notes: a binary64 is {sign s, biased exponent e[11],
significand m[52]}.  Arithmetic runs in an internal window holding the
53-bit significand shifted left 3 (guard/round/sticky in the low bits) —
56 bits, comfortably inside the 64-bit (lo, hi) pair ops.  `_round_pack`
is the single normalize+round+overflow/underflow path every op funnels
through.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from wasmedge_tpu.batch import laneops as lo

I32 = jnp.int32
_EXP_MASK = np.int32(0x7FF00000)       # exponent bits in hi
_MANT_HI_MASK = np.int32(0x000FFFFF)   # mantissa bits in hi
_SIGN = np.int32(-0x80000000)
CANON_HI = np.int32(0x7FF80000)        # canonical NaN (hi plane; lo = 0)


def _i(v):
    return jnp.int32(v)


# -- field extraction -------------------------------------------------------

def f64_sign(hi):
    return lax.shift_right_logical(hi, 31)


def f64_exp(hi):
    return lax.shift_right_logical(hi & _EXP_MASK, 20)


def f64_mant(vlo, vhi):
    return vlo, vhi & _MANT_HI_MASK


def is_nan(vlo, vhi):
    e = f64_exp(vhi)
    mlo, mhi = f64_mant(vlo, vhi)
    return (e == 2047) & ((mlo | mhi) != 0)


def is_inf(vlo, vhi):
    e = f64_exp(vhi)
    mlo, mhi = f64_mant(vlo, vhi)
    return (e == 2047) & ((mlo | mhi) == 0)


def is_zero(vlo, vhi):
    return ((vhi & _i(0x7FFFFFFF)) | vlo) == 0


def canon_nan(like_lo):
    z = jnp.zeros_like(like_lo)
    return z, jnp.full_like(like_lo, CANON_HI)


def _inf(s, like_lo):
    z = jnp.zeros_like(like_lo)
    hi = jnp.where(s != 0, _i(0xFFF00000 - (1 << 32)), _i(0x7FF00000))
    return z, hi


def _zero(s, like_lo):
    z = jnp.zeros_like(like_lo)
    return z, jnp.where(s != 0, _SIGN, _i(0))


def _sig53_norm(vlo, vhi):
    """Significand normalized into [2^52, 2^53) with the matching biased
    exponent (subnormals shifted up; exponent may go <= 0)."""
    mlo, mhi, e = _sig53(vlo, vhi)
    lead = lo.clz64(mlo, mhi) - _i(11)
    sh = jnp.clip(lead, 0, 63)
    nlo, nhi = lo.shl64(mlo, mhi, sh)
    return nlo, nhi, e - lead


def _sig53(vlo, vhi):
    """53-bit significand with implicit bit (subnormals: no implicit bit),
    plus the effective unbiased-ish exponent e' (subnormal -> 1)."""
    e = f64_exp(vhi)
    mlo, mhi = f64_mant(vlo, vhi)
    norm = e != 0
    mhi = jnp.where(norm, mhi | _i(0x00100000), mhi)
    e_eff = jnp.where(norm, e, _i(1))
    return mlo, mhi, e_eff


# -- the rounding funnel ----------------------------------------------------

def _round_pack(s, e, mlo, mhi, sticky):
    """Pack sign/exponent/significand-window into binary64 with RNE.

    (mlo, mhi) holds the candidate significand shifted left 3 (GRS in
    bits [2:0]); it must satisfy m < 2^57.  e is the biased exponent the
    MSB at bit 55 corresponds to; zero significand -> signed zero."""
    # normalize: put MSB at bit 55
    nz = (mlo | mhi) != 0
    lead = lo.clz64(mlo, mhi)           # 0..64
    shift = _i(8) - lead                # >0: right shift, <0: left shift
    e = e + shift
    # subnormal squeeze: if e <= 0, shift right extra (1 - e) and pin e=0
    extra = jnp.where(e <= 0, _i(1) - e, _i(0))
    shift = shift + extra
    e = jnp.where(e <= 0, _i(0), e)
    rsh = jnp.clip(shift, 0, 63)
    lsh = jnp.clip(-shift, 0, 63)
    # sticky collects bits shifted out on the right
    lost_mask_lo, lost_mask_hi = lo.shl64(jnp.full_like(mlo, -1),
                                          jnp.full_like(mlo, -1), rsh)
    lost_lo = mlo & ~lost_mask_lo
    lost_hi = mhi & ~lost_mask_hi
    sticky = sticky | ((shift > 0) & ((lost_lo | lost_hi) != 0))
    rlo, rhi = lo.shr64_u(mlo, mhi, rsh)
    llo, lhi = lo.shl64(mlo, mhi, lsh)
    mlo = jnp.where(shift >= 0, rlo, llo)
    mhi = jnp.where(shift >= 0, rhi, lhi)
    # round to nearest even: result = m >> 3, round bit = bit 2,
    # sticky = bits [1:0] | accumulated sticky
    rnd = lax.shift_right_logical(mlo, 2) & 1
    low_sticky = ((mlo & 3) != 0) | sticky
    lsb = lax.shift_right_logical(mlo, 3) & 1
    inc = (rnd == 1) & (low_sticky | (lsb == 1))
    mlo, mhi = lo.shr64_u(mlo, mhi, _i(3))
    alo, ahi = lo.add64(mlo, mhi, b2i32(inc), jnp.zeros_like(mlo))
    mlo, mhi = alo, ahi
    # rounding may carry into bit 53 -> renormalize
    carry = (mhi & _i(0x00200000)) != 0
    clo, chi = lo.shr64_u(mlo, mhi, _i(1))
    mlo = jnp.where(carry, clo, mlo)
    mhi = jnp.where(carry, chi, mhi)
    e = e + b2i32(carry)
    # subnormal that rounded up into normal range
    e = jnp.where((e == 0) & ((mhi & _i(0x00100000)) != 0), _i(1), e)
    # overflow -> inf
    inf_lo, inf_hi = _inf(s, mlo)
    over = e >= 2047
    # assemble
    out_hi = (jnp.where(s != 0, _SIGN, _i(0))
              | lax.shift_left(jnp.clip(e, 0, 2046), 20)
              | (mhi & _MANT_HI_MASK))
    out_lo = mlo
    out_lo = jnp.where(over, inf_lo, out_lo)
    out_hi = jnp.where(over, inf_hi, out_hi)
    zlo, zhi = _zero(s, mlo)
    out_lo = jnp.where(nz, out_lo, zlo)
    out_hi = jnp.where(nz, out_hi, zhi)
    return out_lo, out_hi


def b2i32(b):
    return b.astype(I32)


# -- addition / subtraction -------------------------------------------------

def f64_add(alo, ahi, blo, bhi):
    return _addsub(alo, ahi, blo, bhi, False)


def f64_sub(alo, ahi, blo, bhi):
    return _addsub(alo, ahi, blo, bhi, True)


def _addsub(alo, ahi, blo, bhi, negate_b):
    sb_in = f64_sign(bhi) ^ (1 if negate_b else 0)
    sa = f64_sign(ahi)
    ea = f64_exp(ahi)
    eb = f64_exp(bhi)
    # significands in the  <<3 window
    amlo, amhi, ea_eff = _sig53(alo, ahi)
    bmlo, bmhi, eb_eff = _sig53(blo, bhi)
    amlo, amhi = lo.shl64(amlo, amhi, _i(3))
    bmlo, bmhi = lo.shl64(bmlo, bmhi, _i(3))
    # order by (exponent, significand): big op absorbs small
    swap = (eb_eff > ea_eff) | ((eb_eff == ea_eff) &
                                lo.lt64_u(amlo, amhi, bmlo, bmhi))
    s_big = jnp.where(swap, sb_in, sa)
    s_sml = jnp.where(swap, sa, sb_in)
    e_big = jnp.where(swap, eb_eff, ea_eff)
    e_sml = jnp.where(swap, ea_eff, eb_eff)
    big_lo = jnp.where(swap, bmlo, amlo)
    big_hi = jnp.where(swap, bmhi, amhi)
    sml_lo = jnp.where(swap, amlo, bmlo)
    sml_hi = jnp.where(swap, amhi, bmhi)
    # align small significand; beyond 60 bits it is pure sticky
    d = jnp.clip(e_big - e_sml, 0, 63)
    lost_mask_lo, lost_mask_hi = lo.shl64(jnp.full_like(big_lo, -1),
                                          jnp.full_like(big_lo, -1), d)
    sticky = ((sml_lo & ~lost_mask_lo) | (sml_hi & ~lost_mask_hi)) != 0
    shl_lo, shl_hi = lo.shr64_u(sml_lo, sml_hi, d)
    same_sign = s_big == s_sml
    sum_lo, sum_hi = lo.add64(big_lo, big_hi, shl_lo, shl_hi)
    # subtraction borrows one extra when nonzero bits were shifted out
    # below the window (the true small operand was slightly larger)
    dlo, dhi = lo.sub64(big_lo, big_hi, shl_lo, shl_hi)
    slo_, shi_ = lo.sub64(dlo, dhi, b2i32(sticky), jnp.zeros_like(dlo))
    mlo = jnp.where(same_sign, sum_lo, slo_)
    mhi = jnp.where(same_sign, sum_hi, shi_)
    # when subtracting with sticky, the "sticky" now means a 1 beyond the
    # kept bits was borrowed: keep sticky set so RNE sees inexactness
    res_lo, res_hi = _round_pack(s_big, e_big, mlo, mhi, sticky)
    # exact cancel -> +0 (RNE mode), unless both were -
    cancel = ((mlo | mhi) == 0) & ~sticky & ~same_sign
    zlo, zhi = _zero(jnp.zeros_like(s_big), res_lo)
    res_lo = jnp.where(cancel, zlo, res_lo)
    res_hi = jnp.where(cancel, zhi, res_hi)
    # specials
    a_nan = is_nan(alo, ahi)
    b_nan = is_nan(blo, bhi)
    a_inf = is_inf(alo, ahi)
    b_inf = is_inf(blo, bhi)
    nlo, nhi = canon_nan(alo)
    both_inf_opp = a_inf & b_inf & (sa != sb_in)
    res_lo = jnp.where(a_inf, jnp.zeros_like(res_lo), res_lo)
    res_hi = jnp.where(a_inf, _inf(sa, res_lo)[1], res_hi)
    res_lo = jnp.where(b_inf & ~a_inf, jnp.zeros_like(res_lo), res_lo)
    res_hi = jnp.where(b_inf & ~a_inf, _inf(sb_in, res_lo)[1], res_hi)
    bad = a_nan | b_nan | both_inf_opp
    res_lo = jnp.where(bad, nlo, res_lo)
    res_hi = jnp.where(bad, nhi, res_hi)
    return res_lo, res_hi


# -- multiplication ---------------------------------------------------------

def f64_mul(alo, ahi, blo, bhi):
    sa = f64_sign(ahi)
    sb = f64_sign(bhi)
    s = sa ^ sb
    amlo, amhi, ea = _sig53_norm(alo, ahi)
    bmlo, bmhi, eb = _sig53_norm(blo, bhi)
    # 53x53 -> 106-bit product via 32-bit limbs: a = a1*2^32 + a0
    a0 = amlo
    a1 = amhi
    b0 = bmlo
    b1 = bmhi
    p00lo, p00hi = lo._umul32_wide(a0, b0)
    p01lo, p01hi = lo._umul32_wide(a0, b1)
    p10lo, p10hi = lo._umul32_wide(a1, b0)
    p11lo, p11hi = lo._umul32_wide(a1, b1)
    # accumulate limbs L0..L3 (32-bit each, with carries)
    L0 = p00lo
    c1lo, c1hi = lo.add64(p00hi, jnp.zeros_like(a0), p01lo,
                          jnp.zeros_like(a0))
    c1lo, c1hi = lo.add64(c1lo, c1hi, p10lo, jnp.zeros_like(a0))
    L1 = c1lo
    c2lo, c2hi = lo.add64(p01hi, jnp.zeros_like(a0), p10hi,
                          jnp.zeros_like(a0))
    c2lo, c2hi = lo.add64(c2lo, c2hi, p11lo, jnp.zeros_like(a0))
    c2lo, c2hi = lo.add64(c2lo, c2hi, c1hi, jnp.zeros_like(a0))
    L2 = c2lo
    L3 = p11hi + c2hi
    # product ~ 2^104..2^106.  Take the top into the <<3 window: the
    # significand window wants the value at bits [55:0].  product bit 104
    # (or 105) is the MSB; shift right by 104-55 = 49 keeping sticky.
    # full product as two 64-bit halves: PH = L3:L2, PL = L1:L0
    sticky = ((L0 | (L1 & _i(0x0003FFFF))) != 0)
    # we need bits [105:50] -> take (PH << 14) | (PL >> 50)
    ph_lo, ph_hi = L2, L3
    pl_lo, pl_hi = L0, L1
    w1lo, w1hi = lo.shl64(ph_lo, ph_hi, _i(14))
    w2lo, w2hi = lo.shr64_u(pl_lo, pl_hi, _i(50))
    mlo = w1lo | w2lo
    mhi = w1hi | w2hi
    e = ea + eb - _i(1023) + _i(1)  # window MSB at bit 55 ~ product bit 105
    res_lo, res_hi = _round_pack(s, e, mlo, mhi, sticky)
    # specials
    a_nan = is_nan(alo, ahi)
    b_nan = is_nan(blo, bhi)
    a_inf = is_inf(alo, ahi)
    b_inf = is_inf(blo, bhi)
    a_z = is_zero(alo, ahi)
    b_z = is_zero(blo, bhi)
    ilo, ihi = _inf(s, res_lo)
    zlo, zhi = _zero(s, res_lo)
    res_lo = jnp.where((a_inf | b_inf), ilo, res_lo)
    res_hi = jnp.where((a_inf | b_inf), ihi, res_hi)
    res_lo = jnp.where((a_z | b_z), zlo, res_lo)
    res_hi = jnp.where((a_z | b_z), zhi, res_hi)
    nlo, nhi = canon_nan(alo)
    bad = a_nan | b_nan | (a_inf & b_z) | (b_inf & a_z)
    res_lo = jnp.where(bad, nlo, res_lo)
    res_hi = jnp.where(bad, nhi, res_hi)
    return res_lo, res_hi


# -- division ---------------------------------------------------------------

def f64_div(alo, ahi, blo, bhi):
    sa = f64_sign(ahi)
    sb = f64_sign(bhi)
    s = sa ^ sb
    amlo, amhi, ea = _sig53_norm(alo, ahi)
    bmlo, bmhi, eb = _sig53_norm(blo, bhi)

    # restoring long division: integer bit first (ma, mb in [2^52, 2^53)
    # so the ratio is in (1/2, 2)), then 56 fraction bits keeping r < mb.
    ge0 = ~lo.lt64_u(amlo, amhi, bmlo, bmhi)
    d0lo, d0hi = lo.sub64(amlo, amhi, bmlo, bmhi)
    rlo0 = jnp.where(ge0, d0lo, amlo)
    rhi0 = jnp.where(ge0, d0hi, amhi)
    z = jnp.zeros_like(alo)
    q0 = b2i32(ge0)

    def body(i, carry):
        rlo, rhi, qlo, qhi = carry
        rlo, rhi = lo.shl64(rlo, rhi, _i(1))
        ge = ~lo.lt64_u(rlo, rhi, bmlo, bmhi)
        slo_, shi_ = lo.sub64(rlo, rhi, bmlo, bmhi)
        rlo = jnp.where(ge, slo_, rlo)
        rhi = jnp.where(ge, shi_, rhi)
        qlo, qhi = lo.shl64(qlo, qhi, _i(1))
        qlo = qlo | b2i32(ge)
        return rlo, rhi, qlo, qhi

    rlo, rhi, qlo, qhi = lax.fori_loop(
        0, 56, body, (rlo0, rhi0, q0, z))
    sticky = (rlo | rhi) != 0
    # q = floor(ma*2^56/mb) in [2^55, 2^57); v = q * 2^(ea-eb-56)
    e = ea - eb + _i(1022)
    res_lo, res_hi = _round_pack(s, e, qlo, qhi, sticky)
    # specials
    a_nan = is_nan(alo, ahi)
    b_nan = is_nan(blo, bhi)
    a_inf = is_inf(alo, ahi)
    b_inf = is_inf(blo, bhi)
    a_z = is_zero(alo, ahi)
    b_z = is_zero(blo, bhi)
    ilo, ihi = _inf(s, res_lo)
    zlo, zhi = _zero(s, res_lo)
    res_lo = jnp.where(a_inf | (b_z & ~a_z), ilo, res_lo)
    res_hi = jnp.where(a_inf | (b_z & ~a_z), ihi, res_hi)
    res_lo = jnp.where(b_inf | (a_z & ~b_z), zlo, res_lo)
    res_hi = jnp.where(b_inf | (a_z & ~b_z), zhi, res_hi)
    nlo, nhi = canon_nan(alo)
    bad = a_nan | b_nan | (a_inf & b_inf) | (a_z & b_z)
    res_lo = jnp.where(bad, nlo, res_lo)
    res_hi = jnp.where(bad, nhi, res_hi)
    return res_lo, res_hi


# -- square root ------------------------------------------------------------

def f64_sqrt(vlo, vhi):
    s = f64_sign(vhi)
    mlo, mhi, e = _sig53(vlo, vhi)
    # normalize subnormals so the significand has its MSB at bit 52
    lead = lo.clz64(mlo, mhi) - _i(11)   # extra left shifts needed
    mlo, mhi = lo.shl64(mlo, mhi, jnp.clip(lead, 0, 63))
    e = e - lead
    eu = e - _i(1023)                    # unbiased
    odd = (eu & 1) != 0
    # radicand window: m << (5 or 6) so result has 56 bits (53+3 GRS):
    # sqrt(m * 2^k) — make exponent even by an extra shift
    rad_lo, rad_hi = lo.shl64(mlo, mhi, jnp.where(odd, _i(6), _i(5)))
    e_half = jnp.where(odd, (eu - 1), eu)
    e_res = lax.shift_right_arithmetic(e_half, 1) + _i(1023)

    # bit-by-bit restoring sqrt ("remainder doubling"), unrolled in
    # Python so every shift amount is static — traced-scalar shifts
    # inside fori_loop trip Mosaic layout inference.
    z = jnp.zeros_like(vlo)
    rem_lo, rem_hi, q_lo, q_hi = z, z, z, z
    for i in range(56):
        sh = 57 - 2 * i              # bits [sh+1:sh] of rad; <0 once the
        if sh >= 0:                  # radicand is exhausted (python-static)
            b_lo, _bh = lo.shr64_u(rad_lo, rad_hi, sh)
            two_bits = b_lo & 3
        else:
            two_bits = z
        rem_lo, rem_hi = lo.shl64(rem_lo, rem_hi, _i(2))
        rem_lo = rem_lo | two_bits
        t_lo, t_hi = lo.shl64(q_lo, q_hi, _i(2))
        t_lo = t_lo | 1
        ge = ~lo.lt64_u(rem_lo, rem_hi, t_lo, t_hi)
        s_lo, s_hi = lo.sub64(rem_lo, rem_hi, t_lo, t_hi)
        rem_lo = jnp.where(ge, s_lo, rem_lo)
        rem_hi = jnp.where(ge, s_hi, rem_hi)
        q_lo, q_hi = lo.shl64(q_lo, q_hi, _i(1))
        q_lo = q_lo | b2i32(ge)
    sticky = (rem_lo | rem_hi) != 0
    res_lo, res_hi = _round_pack(jnp.zeros_like(s), e_res, q_lo, q_hi,
                                 sticky)
    # specials: sqrt(-x) = nan (x != -0), sqrt(+-0) = +-0, sqrt(inf)=inf
    v_nan = is_nan(vlo, vhi)
    v_inf = is_inf(vlo, vhi)
    v_z = is_zero(vlo, vhi)
    neg = (s != 0) & ~v_z
    nlo, nhi = canon_nan(vlo)
    res_lo = jnp.where(v_inf & (s == 0), 0, res_lo)
    res_hi = jnp.where(v_inf & (s == 0), _i(0x7FF00000), res_hi)
    res_lo = jnp.where(v_z, vlo, res_lo)
    res_hi = jnp.where(v_z, vhi, res_hi)
    bad = v_nan | neg
    res_lo = jnp.where(bad, nlo, res_lo)
    res_hi = jnp.where(bad, nhi, res_hi)
    return res_lo, res_hi


# -- comparisons ------------------------------------------------------------

def _cmp_key(vlo, vhi):
    """Total-order key for finite comparison: flip for negatives."""
    neg = vhi < 0
    klo = jnp.where(neg, ~vlo, vlo)
    khi = jnp.where(neg, ~vhi, vhi | _SIGN)
    # +0/-0 equalize handled by callers (both map near the midpoint)
    return klo, khi


def f64_eq(alo, ahi, blo, bhi):
    nan = is_nan(alo, ahi) | is_nan(blo, bhi)
    both_zero = is_zero(alo, ahi) & is_zero(blo, bhi)
    bit_eq = lo.eq64(alo, ahi, blo, bhi)
    return ~nan & (bit_eq | both_zero)


def f64_lt(alo, ahi, blo, bhi):
    nan = is_nan(alo, ahi) | is_nan(blo, bhi)
    both_zero = is_zero(alo, ahi) & is_zero(blo, bhi)
    aklo, akhi = _cmp_key(alo, ahi)
    bklo, bkhi = _cmp_key(blo, bhi)
    return ~nan & ~both_zero & lo.lt64_u(aklo, akhi, bklo, bkhi)


def f64_le(alo, ahi, blo, bhi):
    return f64_lt(alo, ahi, blo, bhi) | f64_eq(alo, ahi, blo, bhi)


def f64_min(alo, ahi, blo, bhi):
    nan = is_nan(alo, ahi) | is_nan(blo, bhi)
    nlo, nhi = canon_nan(alo)
    eq = f64_eq(alo, ahi, blo, bhi)
    # equal (incl. +-0): pick the sign-set one
    sa = ahi < 0
    lt_ab = f64_lt(alo, ahi, blo, bhi)
    pick_a = (eq & sa) | (~eq & lt_ab)
    rlo = jnp.where(pick_a, alo, blo)
    rhi = jnp.where(pick_a, ahi, bhi)
    return jnp.where(nan, nlo, rlo), jnp.where(nan, nhi, rhi)


def f64_max(alo, ahi, blo, bhi):
    nan = is_nan(alo, ahi) | is_nan(blo, bhi)
    nlo, nhi = canon_nan(alo)
    eq = f64_eq(alo, ahi, blo, bhi)
    sa = ahi < 0
    lt_ba = f64_lt(blo, bhi, alo, ahi)
    pick_a = (eq & ~sa) | (~eq & lt_ba)
    rlo = jnp.where(pick_a, alo, blo)
    rhi = jnp.where(pick_a, ahi, bhi)
    return jnp.where(nan, nlo, rlo), jnp.where(nan, nhi, rhi)


# -- rounding to integral ---------------------------------------------------

def _round_integral(vlo, vhi, mode):
    """mode: 'trunc' | 'floor' | 'ceil' | 'nearest' (ties to even)."""
    s = f64_sign(vhi)
    e = f64_exp(vhi) - 1023           # unbiased
    # |v| < 1: result is 0 or +-1 depending on mode
    frac_bits = jnp.clip(_i(52) - e, 0, 63)
    mask_lo, mask_hi = lo.shl64(jnp.full_like(vlo, -1),
                                jnp.full_like(vlo, -1), frac_bits)
    int_lo = vlo & mask_lo
    int_hi = vhi & mask_hi
    frac_nz = ((vlo & ~mask_lo) | (vhi & ~mask_hi)) != 0
    big = e >= 52                      # already integral
    # increment by one ULP-at-integer-scale
    ulp_lo, ulp_hi = lo.shl64(jnp.ones_like(vlo), jnp.zeros_like(vlo),
                              frac_bits)
    inc_lo, inc_hi = lo.add64(int_lo, int_hi, ulp_lo, ulp_hi)
    if mode == "trunc":
        rlo, rhi = int_lo, int_hi
    elif mode == "floor":
        rlo = jnp.where(frac_nz & (s != 0), inc_lo, int_lo)
        rhi = jnp.where(frac_nz & (s != 0), inc_hi, int_hi)
    elif mode == "ceil":
        rlo = jnp.where(frac_nz & (s == 0), inc_lo, int_lo)
        rhi = jnp.where(frac_nz & (s == 0), inc_hi, int_hi)
    else:  # nearest, ties to even
        half_lo, half_hi = lo.shl64(jnp.ones_like(vlo),
                                    jnp.zeros_like(vlo),
                                    jnp.clip(frac_bits - 1, 0, 63))
        frac_lo = vlo & ~mask_lo
        frac_hi = vhi & ~mask_hi
        gt_half = lo.lt64_u(half_lo, half_hi, frac_lo, frac_hi)
        eq_half = lo.eq64(frac_lo, frac_hi, half_lo, half_hi) & \
            (frac_bits > 0)
        int_odd = (lo.shr64_u(int_lo, int_hi, frac_bits)[0] & 1) == 1
        up = gt_half | (eq_half & int_odd)
        rlo = jnp.where(frac_nz & up, inc_lo, int_lo)
        rhi = jnp.where(frac_nz & up, inc_hi, int_hi)
    # |v| < 1 handling: e < 0 -> int part is +-0; frac decides
    ones_hi = _i(0x3FF00000)
    lt1 = e < 0
    nz = ~is_zero(vlo, vhi)
    if mode == "trunc":
        z_lo, z_hi = _zero(s, vlo)
        rlo = jnp.where(lt1, z_lo, rlo)
        rhi = jnp.where(lt1, z_hi, rhi)
    elif mode == "floor":
        z_lo, z_hi = _zero(s, vlo)
        rlo = jnp.where(lt1, jnp.where((s != 0) & nz, _i(0), z_lo), rlo)
        rhi = jnp.where(lt1, jnp.where((s != 0) & nz,
                                       ones_hi | _SIGN, z_hi), rhi)
    elif mode == "ceil":
        z_lo, z_hi = _zero(s, vlo)
        rlo = jnp.where(lt1, jnp.where((s == 0) & nz, _i(0), z_lo), rlo)
        rhi = jnp.where(lt1, jnp.where((s == 0) & nz, ones_hi, z_hi), rhi)
    else:
        # nearest: |v| <= 0.5 -> +-0 ; 0.5 < |v| < 1 -> +-1
        # (|v| == 0.5 ties to even = 0)
        mag_hi = vhi & _i(0x7FFFFFFF)
        gt_half_mag = (mag_hi > _i(0x3FE00000)) | \
            ((mag_hi == _i(0x3FE00000)) & (vlo != 0))
        z_lo, z_hi = _zero(s, vlo)
        rlo = jnp.where(lt1, jnp.where(gt_half_mag, _i(0), z_lo), rlo)
        rhi = jnp.where(lt1, jnp.where(
            gt_half_mag,
            jnp.where(s != 0, ones_hi | _SIGN, ones_hi), z_hi), rhi)
    # specials passthrough (nan canonicalized, inf, zero)
    passthru = big | is_inf(vlo, vhi) | is_zero(vlo, vhi)
    rlo = jnp.where(passthru, vlo, rlo)
    rhi = jnp.where(passthru, vhi, rhi)
    nlo, nhi = canon_nan(vlo)
    nan = is_nan(vlo, vhi)
    return jnp.where(nan, nlo, rlo), jnp.where(nan, nhi, rhi)


def f64_trunc(vlo, vhi):
    return _round_integral(vlo, vhi, "trunc")


def f64_floor(vlo, vhi):
    return _round_integral(vlo, vhi, "floor")


def f64_ceil(vlo, vhi):
    return _round_integral(vlo, vhi, "ceil")


def f64_nearest(vlo, vhi):
    return _round_integral(vlo, vhi, "nearest")


# -- conversions ------------------------------------------------------------

def f64_from_i64(vlo, vhi, signed=True):
    if signed:
        s = (vhi < 0)
        nlo, nhi = lo.neg64(vlo, vhi)
        mlo = jnp.where(s, nlo, vlo)
        mhi = jnp.where(s, nhi, vhi)
    else:
        s = jnp.zeros_like(vlo, dtype=bool)
        mlo, mhi = vlo, vhi
    # place value's MSB at window bit 55; magnitude < 2^64
    lead = lo.clz64(mlo, mhi)
    shift = _i(8) - lead
    rsh = jnp.clip(shift, 0, 63)
    lsh = jnp.clip(-shift, 0, 63)
    lost_mask_lo, lost_mask_hi = lo.shl64(jnp.full_like(mlo, -1),
                                          jnp.full_like(mlo, -1), rsh)
    sticky = (shift > 0) & \
        (((mlo & ~lost_mask_lo) | (mhi & ~lost_mask_hi)) != 0)
    r_lo, r_hi = lo.shr64_u(mlo, mhi, rsh)
    l_lo, l_hi = lo.shl64(mlo, mhi, lsh)
    wlo = jnp.where(shift >= 0, r_lo, l_lo)
    whi = jnp.where(shift >= 0, r_hi, l_hi)
    return _round_pack(b2i32(s), _i(1023) + (_i(63) - lead), wlo, whi,
                       sticky)


def f64_from_i32(v, signed=True):
    if signed:
        hi = lax.shift_right_arithmetic(v, 31)
    else:
        hi = jnp.zeros_like(v)
    return f64_from_i64(v, hi, signed=signed)


def f64_to_i64_trunc(vlo, vhi):
    """Truncate toward zero; returns (lo, hi, ok_signed, ok_unsigned,
    is_nan) for the engines' trap/sat handling."""
    s = f64_sign(vhi)
    e = f64_exp(vhi) - 1023
    mlo, mhi, _e_eff = _sig53(vlo, vhi)
    # magnitude = m * 2^(e-52)
    sh = e - _i(52)
    l_lo, l_hi = lo.shl64(mlo, mhi, jnp.clip(sh, 0, 63))
    r_lo, r_hi = lo.shr64_u(mlo, mhi, jnp.clip(-sh, 0, 63))
    mag_lo = jnp.where(sh >= 0, l_lo, r_lo)
    mag_hi = jnp.where(sh >= 0, l_hi, r_hi)
    mag_lo = jnp.where(e < 0, 0, mag_lo)
    mag_hi = jnp.where(e < 0, 0, mag_hi)
    nan = is_nan(vlo, vhi)
    inf = is_inf(vlo, vhi)
    # signed range: -2^63 <= trunc(v) < 2^63 (exactly -2^63 allowed)
    ok_s = ((e < 63) & ~nan & ~inf) | \
        ((s != 0) & (e == 63) & (mag_lo == 0) & (mag_hi == _SIGN) & ~nan)
    ok_u = (s == 0) & (e < 64) & ~nan & ~inf
    ok_u = ok_u | (is_zero(vlo, vhi)) | ((s != 0) & (e < 0))  # -0.x -> 0
    neg_lo, neg_hi = lo.neg64(mag_lo, mag_hi)
    out_lo = jnp.where(s != 0, neg_lo, mag_lo)
    out_hi = jnp.where(s != 0, neg_hi, mag_hi)
    return out_lo, out_hi, ok_s, ok_u, nan


def f64_to_f32(vlo, vhi):
    """Demote with RNE; canonical NaN on NaN input (numeric.py policy)."""
    s = f64_sign(vhi)
    mlo, mhi, e_eff = _sig53(vlo, vhi)
    # f32 window: 24-bit significand + GRS -> reuse _round_pack32 logic
    # value = m53 * 2^(e-1075).  For f32: out_m24 with exponent bias 127.
    # shift m53 right by 29-3 = 26 to get 24+3 bits
    lost = (mlo & _i(0x03FFFFFF)) != 0
    w_lo, w_hi = lo.shr64_u(mlo, mhi, _i(26))
    w = w_lo  # fits in 30 bits
    e32 = e_eff - _i(1023) + _i(127)
    # subnormal squeeze for f32
    extra = jnp.where(e32 <= 0, _i(1) - e32, _i(0))
    extra = jnp.clip(extra, 0, 31)
    lost = lost | ((w & (lax.shift_left(_i(1), extra) - 1)) != 0)
    w = lax.shift_right_logical(w, extra)
    e32 = jnp.where(e32 <= 0, _i(0), e32)
    rnd = lax.shift_right_logical(w, 2) & 1
    sticky2 = ((w & 3) != 0) | lost
    lsb = lax.shift_right_logical(w, 3) & 1
    inc = (rnd == 1) & (sticky2 | (lsb == 1))
    m = lax.shift_right_logical(w, 3) + b2i32(inc)
    carry = (m & _i(0x01000000)) != 0
    m = jnp.where(carry, lax.shift_right_logical(m, 1), m)
    e32 = e32 + b2i32(carry)
    e32 = jnp.where((e32 == 0) & ((m & _i(0x00800000)) != 0), _i(1), e32)
    over = e32 >= 255
    out = (jnp.where(s != 0, _i(-0x80000000), _i(0))
           | lax.shift_left(jnp.clip(e32, 0, 254), 23)
           | (m & _i(0x007FFFFF)))
    inf32 = jnp.where(s != 0, _i(0xFF800000 - (1 << 32)), _i(0x7F800000))
    out = jnp.where(over, inf32, out)
    zero32 = jnp.where(s != 0, _i(-0x80000000), _i(0))
    out = jnp.where(is_zero(vlo, vhi), zero32, out)
    out = jnp.where(is_inf(vlo, vhi), inf32, out)
    out = jnp.where(is_nan(vlo, vhi), _i(0x7FC00000), out)
    return out


def f32_to_f64(v32):
    """Promote (exact); canonical NaN on NaN input."""
    s = lax.shift_right_logical(v32, 31)
    e = lax.shift_right_logical(v32 & _i(0x7F800000), 23)
    m = v32 & _i(0x007FFFFF)
    # normals
    e64 = e - _i(127) + _i(1023)
    hi = (lax.shift_left(s, 31) | lax.shift_left(e64, 20)
          | lax.shift_right_logical(m, 3))
    lo_ = lax.shift_left(m & 7, 29)
    # zero
    hi = jnp.where((e == 0) & (m == 0), lax.shift_left(s, 31), hi)
    lo_ = jnp.where((e == 0) & (m == 0), 0, lo_)
    # subnormal f32: value = m * 2^-149 with MSB at bit p => normal
    # binary64 with exponent (p - 149) + 1023 = p + 874
    nz_sub = (e == 0) & (m != 0)
    msb = _i(31) - lax.clz(jnp.where(nz_sub, m, _i(1)))
    frac = lax.shift_left(m, jnp.clip(_i(23) - msb, 0, 31)) & _i(0x007FFFFF)
    e_sub = msb + _i(874)
    hi_sub = (lax.shift_left(s, 31) | lax.shift_left(e_sub, 20)
              | lax.shift_right_logical(frac, 3))
    lo_sub = lax.shift_left(frac & 7, 29)
    hi = jnp.where(nz_sub, hi_sub, hi)
    lo_ = jnp.where(nz_sub, lo_sub, lo_)
    # inf / nan
    is_inf32 = (e == 255) & (m == 0)
    is_nan32v = (e == 255) & (m != 0)
    hi = jnp.where(is_inf32, lax.shift_left(s, 31) | _i(0x7FF00000), hi)
    lo_ = jnp.where(is_inf32, 0, lo_)
    hi = jnp.where(is_nan32v, CANON_HI, hi)
    lo_ = jnp.where(is_nan32v, 0, lo_)
    return lo_, hi


# -- f32 <- i64 (the other missing conversion family) -----------------------

def f32_from_i64(vlo, vhi, signed=True):
    """i64 -> f32 with single RNE rounding via the f64 path + demote is
    WRONG (double rounding); round directly to 24 bits instead."""
    if signed:
        neg = vhi < 0
        nlo, nhi = lo.neg64(vlo, vhi)
        mlo = jnp.where(neg, nlo, vlo)
        mhi = jnp.where(neg, nhi, vhi)
        s = b2i32(neg)
    else:
        s = jnp.zeros_like(vlo)
        mlo, mhi = vlo, vhi
    zero = (mlo | mhi) == 0
    lead = lo.clz64(mlo, mhi)
    msb = _i(63) - lead
    # bring MSB to bit 26 (24 significand + 2... use 24+3 GRS window at 26)
    shift = msb - _i(26)
    rsh = jnp.clip(shift, 0, 63)
    lsh = jnp.clip(-shift, 0, 63)
    lost_mask_lo, lost_mask_hi = lo.shl64(jnp.full_like(mlo, -1),
                                          jnp.full_like(mlo, -1), rsh)
    sticky = (shift > 0) & \
        (((mlo & ~lost_mask_lo) | (mhi & ~lost_mask_hi)) != 0)
    r_lo, _rhi = lo.shr64_u(mlo, mhi, rsh)
    l_lo, _lhi = lo.shl64(mlo, mhi, lsh)
    w = jnp.where(shift >= 0, r_lo, l_lo)   # 27-bit window
    e32 = msb + _i(127)
    rnd = lax.shift_right_logical(w, 2) & 1
    sticky2 = ((w & 3) != 0) | sticky
    lsb = lax.shift_right_logical(w, 3) & 1
    inc = (rnd == 1) & (sticky2 | (lsb == 1))
    m = lax.shift_right_logical(w, 3) + b2i32(inc)
    carry = (m & _i(0x01000000)) != 0
    m = jnp.where(carry, lax.shift_right_logical(m, 1), m)
    e32 = e32 + b2i32(carry)
    out = (lax.shift_left(s, 31) | lax.shift_left(e32, 23)
           | (m & _i(0x007FFFFF)))
    return jnp.where(zero, lax.shift_left(s, 31), out)
