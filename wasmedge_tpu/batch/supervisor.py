"""Supervised batch execution: auto-checkpoint, retry, degradation ladder.

The north star serves long-lived batches of thousands of lanes; before
this layer a single device fault, XLA miscompile, or host-serve exception
mid-run killed the whole batch and lost every in-flight lane.  The
supervisor wraps BlockScheduler/BatchEngine runs with the recovery loop a
hypervisor owes its guests ("Towards a Linear-Algebraic Hypervisor",
PAPERS.md) — cheap here because BatchState is plain SoA arrays the
checkpoint layer (batch/checkpoint.py) already snapshots:

1. **Periodic checkpointing** — step- and/or wall-clock cadence
   (SupervisorConfigure.checkpoint_every_steps / _every_s), atomic
   temp-file+rename writes, bounded lineage with pruning.  Cadence
   applies on the SIMT tier, whose BatchState the checkpoint layer
   understands; slices land on steps_per_launch chunk boundaries, so a
   resumed run replays the exact chunk sequence an uninterrupted run
   executes — crash/resume is bit-identical (tests/test_supervisor.py).

2. **Retry with exponential backoff** — a launch (kernel dispatch/XLA)
   or hostcall-serve exception restores the last good checkpoint (older
   lineage members when the newest is corrupt; the initial state when
   none survive) and retries under a budget.

3. **Engine-degradation ladder** — Pallas/BlockScheduler -> per-step jit
   SIMT -> gas-metered scalar engine.  A tier that exhausts its retry
   budget is demoted; the bottom rung re-executes side-effect-free
   batches lane-by-lane on the scalar interpreter with a fuel limit
   (the generalization of the r6 v128-residue quarantine, whose scalar
   re-run now lives here as `scalar_rerun`).  Per-lane poison
   quarantine: a failure attributed to concrete lanes (exceptions
   carrying `.lanes`) that repeats demotes those lanes to the scalar
   rung or terminates them (ErrCode.Terminated) instead of sinking the
   batch; a lane running past `lane_step_cap` retired instructions is a
   runaway and is terminated.

4. **Structured FailureRecords** (common/statistics.py) — every
   incident (fault class, lane set, retry count, checkpoint lineage,
   tier) lands on the supplied Statistics and the process-wide log.

Side-effect caveat: tier-0 stdout is exactly-once across SIMT-tier
restores since r9 — flushes advance a per-lane stream cursor journaled
in every checkpoint, and replayed records are suppressed up to the
engine's written high-water mark (batch/hostcall.py _stdout_cursor).
Tier-1 writes, and any output a *demoted* tier already flushed (the
pallas attempt's flushes live on its own engine object and lane
packing, so its cursor cannot transfer to the SIMT replay), remain
at-least-once; pure-compute batches are exactly-once by construction.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import List, Optional, Tuple

import numpy as np

from wasmedge_tpu.common.errors import EngineFailure, ErrCode, TrapError, WasmError
from wasmedge_tpu.common.statistics import FailureRecord, record_failure
from wasmedge_tpu.batch.lineage import Lineage

MASK64 = (1 << 64) - 1


class _TierExhausted(Exception):
    """Internal: the current ladder tier burned its retry budget."""

    def __init__(self, cause):
        super().__init__(repr(cause))
        self.cause = cause


def backoff_seconds(knobs, attempt: int) -> float:
    """Exponential backoff shared by the supervisor and the serving
    layer (both knob objects carry backoff_base_s/_factor/_max_s)."""
    base = float(knobs.backoff_base_s)
    if base <= 0:
        return 0.0
    return min(float(knobs.backoff_max_s),
               base * float(knobs.backoff_factor) ** max(attempt - 1, 0))


def scalar_rerun(inst, conf, func_name: str, func_idx: int, args_lanes,
                 lanes, max_steps: int):
    """Gas-metered scalar re-execution of `lanes` from their original
    arguments — the ladder's bottom rung, shared with the block
    scheduler's v128-residue quarantine (batch/scheduler.py).

    Only sound for modules without host imports (no WASI side effects to
    double-apply); callers gate on that.  Returns (cells [max(nres,1), n]
    uint64 raw result cells, trap [n] int32 with TRAP_DONE on success,
    records) where `records` are FailureRecords for host-side errors the
    scalar engine itself hit (guest traps are per-lane trap codes, not
    incidents)."""
    import copy

    from wasmedge_tpu.batch.image import TRAP_DONE
    from wasmedge_tpu.common.types import bits_to_typed, typed_to_bits
    from wasmedge_tpu.executor import Executor
    from wasmedge_tpu.runtime.store import StoreManager

    # the scalar re-run must honor the caller's max_steps contract:
    # gas-meter it (flat 1/instr) so an infinite-loop guest traps
    # CostLimitExceeded instead of hanging the host
    conf = copy.deepcopy(conf) if conf is not None else None
    if conf is not None:
        conf.statistics.cost_measuring = True
        conf.statistics.cost_limit = max(int(max_steps), 1)
    ft = inst.funcs[func_idx].functype
    nres = len(ft.results)
    lanes = np.asarray(lanes, np.int64)
    n = int(lanes.size)
    cells = np.zeros((max(nres, 1), n), np.uint64)
    trap = np.zeros(n, np.int32)
    records: List[FailureRecord] = []
    for col, lane in enumerate(lanes):
        # lane args are raw 64-bit cells; the scalar invoke takes TYPED
        # values (float params would otherwise be re-encoded from bits)
        args = [bits_to_typed(t, int(np.uint64(a[lane])))
                for t, a in zip(ft.params, args_lanes)]
        try:
            ex = Executor(conf)
            st = StoreManager()
            fresh = ex.instantiate(st, inst.ast)
            out = ex.invoke(st, fresh.find_func(func_name), args)
        except TrapError as te:
            # a genuine guest trap (incl. CostLimitExceeded from the
            # fuel meter): per-lane outcome, same as the batch engines
            trap[col] = int(te.code) or int(ErrCode.CostLimitExceeded)
            continue
        except WasmError:
            # non-trap engine refusal (instantiation etc.): the lane did
            # not complete within its budget
            trap[col] = int(ErrCode.CostLimitExceeded)
            continue
        except Exception as e:  # host-side bug — record, don't silence
            records.append(FailureRecord(
                fault_class="scalar_rerun", error=repr(e),
                lanes=(int(lane),), tier="scalar").stamp())
            trap[col] = int(ErrCode.CostLimitExceeded)
            continue
        for r, (t, v) in enumerate(zip(ft.results, out)):
            cells[r, col] = np.uint64(typed_to_bits(t, v) & MASK64)
        trap[col] = TRAP_DONE
    return cells, trap, records


class BatchSupervisor:
    """Drives one engine's batch to completion under supervision.

    `engine` is a SIMT BatchEngine or a MultiTenantBatchEngine; `run()`
    returns the same shape their unsupervised entries do (a BatchResult,
    or one per tenant).  `faults` is an optional
    wasmedge_tpu.testing.faults.FaultInjector armed on the engine's
    deterministic seams; `stats` an optional common.statistics.Statistics
    that collects the FailureRecords (the process-wide log gets them
    either way)."""

    def __init__(self, engine, conf=None, stats=None, faults=None,
                 checkpoint_dir: Optional[str] = None,
                 resume: Optional[bool] = None):
        from wasmedge_tpu.obs.recorder import recorder_of

        self.engine = engine
        # pristine reference: run() restores it so a fused->unfused
        # demotion in one run() never silently de-fuses later runs
        self._engine0 = engine
        self.conf = conf if conf is not None else engine.conf
        self.k = self.conf.supervisor
        self.stats = stats
        self.faults = faults
        self.obs = recorder_of(self.conf)
        self.failures: List[FailureRecord] = []
        self.retries = 0
        self.checkpoint_dir = checkpoint_dir or self.k.checkpoint_dir
        self.resume = self.k.resume if resume is None else bool(resume)
        self._lineage = Lineage()   # shared machinery (batch/lineage.py)
        self._restored_from: Optional[str] = None
        self._overlay = {}  # lane -> (result cells, trap) from scalar rung

    # -- public -----------------------------------------------------------
    def run(self, func_name: Optional[str] = None, args_lanes=None,
            max_steps: int = 10_000_000):
        self.engine = eng = self._engine0
        # supervised rungs run UNcompacted (the poison-lane
        # quarantine, runaway caps, and scalar-overlay harvest all key
        # on physical lane indices across restores).  Marking the
        # engine externally-managed BEFORE lineage adoption makes
        # restore_lane_src REFUSE a lane-compacted (lane_src) snapshot
        # loudly instead of arming a compactor this tier would then
        # silently discard — which would return every lane's result at
        # the wrong index (batch/compact.py).
        eng._compact_external = True
        eng.compactor = None
        self._multi = hasattr(eng, "tenants")
        self._max_steps = int(max_steps)
        self._overlay = {}
        self._replay_tier = False
        if not self._multi:
            ex = eng.inst.exports.get(func_name)
            if ex is None or ex[0] != 0:
                raise KeyError(f"no exported function {func_name}")
            self._func_name = func_name
            self._func_idx = ex[1]
            self._args = []
            for a in (args_lanes or []):
                arr = np.asarray(a, np.int64)
                if arr.ndim == 0:
                    arr = np.full(eng.lanes, arr, np.int64)
                self._args.append(arr)
        # a fresh run never inherits a previous run()'s lineage (stale
        # checkpoints would restore the OLD run's state under new args);
        # only an explicit resume adopts what is on disk
        self._lineage.reset()
        self._adopted = None
        self._invocation = self._invocation_fingerprint()
        self._resumed = self.resume and self._adopt_lineage()
        tiers = []
        # a resumed run continues from its snapshot on the SIMT tier —
        # the kernel tier can only start from the original arguments
        # and would redo (and double-serve) the checkpointed work
        if self.k.use_kernel_tier and not self._multi \
                and not self._resumed:
            tiers.append("pallas")
        tiers.append("simt")
        # a compiled-function-tier fault demotes to the plain fused
        # SIMT build first (tierup off, fusion kept): same image, same
        # lane geometry — the tu_ctr counter plane stays live on the
        # demoted build, so checkpoints transfer untouched and only
        # the compiled step program changes (batch/tierup.py).  Knob
        # gate only, like simt_unfused below: whether functions were
        # actually promoted is decided at demotion time.
        if getattr(self.engine.cfg, "tierup", True):
            tiers.append("simt_nocomp")
        # a fused-step fault demotes to the UNFUSED SIMT build before
        # the scalar rung: same image, same state geometry (fusion adds
        # no lane planes), checkpoints transfer untouched — only the
        # compiled step program changes (batch/fuse.py).  Gated here on
        # the KNOB only: whether the image actually realized fused
        # cells is decided at demotion time, when the SIMT rung has
        # already planned — keeping the lazy-analyzer guarantee for
        # runs the kernel tier serves outright.
        if getattr(self.engine.cfg, "fuse_superinstructions", True):
            tiers.append("simt_unfused")
        if self._scalar_ok():
            tiers.append("scalar")
        last_exc = None
        obs = self.obs
        for tier in tiers:
            t_tier = obs.now()
            ran = True
            try:
                if tier == "pallas":
                    res = self._run_kernel_tier(max_steps)
                    if res is None:
                        ran = False  # ineligible: no residency to record
                        continue
                    return res
                if tier in ("simt", "simt_nocomp", "simt_unfused"):
                    if tier == "simt_nocomp":
                        from wasmedge_tpu.batch.tierup import tierup_active

                        if not tierup_active(self.engine.img,
                                             self.engine.cfg):
                            # the SIMT rung promoted nothing (or never
                            # planned): no compiled bodies to shed,
                            # fall through to the un-fuse rung
                            ran = False
                            continue
                        self._demote_nocomp()
                    if tier == "simt_unfused":
                        from wasmedge_tpu.batch.fuse import fusion_active

                        if not fusion_active(self.engine.img,
                                             self.engine.cfg):
                            # the SIMT rung compiled nothing fused (no
                            # realized runs, or already demoted):
                            # nothing to un-fuse, fall through
                            ran = False
                            continue
                        self._demote_unfused()
                    state, total = self._run_simt_tier(max_steps)
                    if self._multi:
                        return self.engine.results_from_state(state, total)
                    return self._result_single(state, total)
                return self._run_scalar_tier(max_steps)
            except _TierExhausted as e:
                last_exc = e.cause
                self._record("demote", e.cause, tier=tier)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if tier != "pallas":
                    raise
                # the kernel tier is best-effort: any failure demotes
                last_exc = e
                self._record("launch", e, tier="pallas")
                self._record("demote", e, tier="pallas")
            finally:
                # tier-residency: monotonic span whenever the tier ran
                # (success, demotion, or raise — not ineligible-skip)
                if ran:
                    obs.add_tier_seconds(tier, obs.now() - t_tier)
                    obs.span(f"tier/{tier}", t_tier, cat="supervisor",
                             track="supervisor")
        raise EngineFailure(
            f"supervised run failed on every tier: {last_exc!r}",
            self.failures)

    # -- ladder tiers -----------------------------------------------------
    def _demote_nocomp(self):
        """Swap the supervised engine for a shallow clone whose step
        builder keeps fusion but compiles no whole-function bodies
        (tierup knob off).  The clone shares image, instance, stats,
        and recorder; the compiled tier adds only the laneless tu_ctr
        counter plane, which the tierup-off step keeps live, so the
        compiled rung's checkpoints restore onto it bit-exactly (the
        image fingerprint ignores the tier_fn promotion plane).  The
        newest surviving lineage member is adopted so this rung
        continues from the compiled rung's progress."""
        import copy
        import dataclasses as _dc

        eng = copy.copy(self.engine)
        eng.cfg = _dc.replace(eng.cfg, tierup=False)
        # keep conf.batch consistent with cfg (see _demote_unfused)
        eng.conf = copy.copy(eng.conf)
        eng.conf.batch = eng.cfg
        eng._step = None
        eng._run_chunk = None
        self.engine = eng
        self._replay_tier = True
        got = self._lineage.walk_newest(self._load_member,
                                        self._bad_member)
        if got is not None:
            self._adopted = got
            self._resumed = True

    def _demote_unfused(self):
        """Swap the supervised engine for a shallow clone whose step
        builder compiles the seed per-op path (fuse knob off).  The
        clone shares image, instance, stats, and recorder; fusion adds
        no state planes, so the fused tier's checkpoints restore onto
        it bit-exactly (the image fingerprint ignores fusion planes).
        The newest surviving lineage member is adopted so the unfused
        rung continues from the fused rung's progress instead of
        replaying from scratch."""
        import copy
        import dataclasses as _dc

        eng = copy.copy(self.engine)
        # tierup is pinned off too: reaching this rung means the
        # compiled tier either already demoted (simt_nocomp) or was
        # never eligible, and the un-fused build must not resurrect it
        eng.cfg = _dc.replace(eng.cfg, fuse_superinstructions=False,
                              tierup=False)
        # keep conf.batch consistent with cfg: the obs plane allocator
        # (obs_state_planes reads conf.batch) must agree with the step
        # builder that this rung compiles nothing fused — fusion_active
        # can never disagree across the two
        eng.conf = copy.copy(eng.conf)
        eng.conf.batch = eng.cfg
        eng._step = None
        eng._run_chunk = None
        self.engine = eng
        self._replay_tier = True
        got = self._lineage.walk_newest(self._load_member,
                                        self._bad_member)
        if got is not None:
            self._adopted = got
            self._resumed = True

    def _run_kernel_tier(self, max_steps):
        from wasmedge_tpu.batch.pallas_engine import (
            PallasUniformEngine, pallas_enabled)

        eng = self.engine
        if not pallas_enabled(eng.cfg):
            return None
        peng = PallasUniformEngine(eng.inst, simt=eng,
                                   interpret=eng.cfg.interpret or None)
        if not peng.eligible:
            return None
        return peng.run(self._func_name, list(self._args), max_steps)

    def _run_simt_tier(self, max_steps):
        eng = self.engine
        k = self.k
        # uncompacted invariant (see run()): the flag is set before
        # lineage adoption; this re-assert is defensive only
        eng.compactor = None
        if self._resumed and self._adopted is not None:
            # adopted lineage (cross-process resume): continue from the
            # newest good member — already loaded by _adopt_lineage's
            # verification pass, so no second deserialization here
            state, total = self._adopted
            self._adopted = None
            self._restored_from = self._lineage.newest().path
        else:
            # a fresh (non-resumed) run starts a fresh output stream; a
            # demoted-from-fused replay keeps the written high-water
            # mark so tier-0 output stays exactly-once across the
            # fused -> unfused restart (the clone shares the engine's
            # stdout cursor)
            from wasmedge_tpu.batch.hostcall import stdout_cursor_reset

            stdout_cursor_reset(self.engine,
                                keep_highwater=getattr(
                                    self, "_replay_tier", False))
            state, total = self._initial_state(), 0
        consecutive = 0
        fail_keys = {}
        # shadow-audit lanes (wasmedge_tpu/integrity/, r24): armed once
        # per tier — a divergence raises IntegrityDivergence out of the
        # launch loop and lands in the same retry/restore path below
        # with fault class "integrity"
        integ = getattr(self.conf, "integrity", None)
        if integ is not None and integ.audit \
                and getattr(eng, "_audit_hook", None) is None:
            from wasmedge_tpu.integrity import ShadowAuditor

            eng._audit_hook = ShadowAuditor(integ, obs=self.obs,
                                            faults=self.faults)
        # anchor the checkpoint cadence at the STARTING position (the
        # restored step on resume, else 0) so a resumed run neither
        # fires an immediate off-cadence save nor leaves the replayed
        # region unprotected
        self._reset_cadence(total)
        while True:
            target = self._slice_target(total, max_steps)
            try:
                if self.faults is not None:
                    eng._fault_hook = self.faults.fire
                    if hasattr(self.faults, "flip"):
                        eng._flip_hook = self.faults.flip
                state, total = eng.run_from_state(state, total, target)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                self.retries += 1
                consecutive += 1
                point = getattr(e, "point", None) or "launch"
                lanes = tuple(getattr(e, "lanes", ()) or ())
                cls = "integrity" if point == "integrity" \
                    else ("serve" if point == "serve" else "launch")
                self._record(cls, e, lanes=lanes)
                self.obs.instant("retry", cat="supervisor",
                                 track="supervisor", retry=self.retries,
                                 consecutive=consecutive, point=point)
                key = (point, lanes)
                fail_keys[key] = fail_keys.get(key, 0) + 1
                # the failed attempt may have consumed donated buffers:
                # never reuse `state`, restore from the lineage
                state, total = self._restore()
                if lanes and fail_keys[key] >= k.poison_lane_retries:
                    state = self._quarantine_lanes(state, lanes)
                    fail_keys.pop(key, None)
                    consecutive = 0
                    continue
                if consecutive > k.max_retries:
                    raise _TierExhausted(e)
                self._backoff(consecutive)
                continue
            finally:
                eng._fault_hook = None
                eng._flip_hook = None
            consecutive = 0
            state = self._check_runaways(state)
            if not (np.asarray(state.trap) == 0).any() \
                    or total >= max_steps:
                return state, total
            self._maybe_checkpoint(state, total)

    def _scalar_ok(self) -> bool:
        return (self.k.allow_scalar_tier and not self._multi
                and not any(getattr(f, "kind", None) == "host"
                            for f in self.engine.inst.funcs))

    def _run_scalar_tier(self, max_steps):
        from wasmedge_tpu.batch.engine import BatchResult

        eng = self.engine
        lanes = np.arange(eng.lanes, dtype=np.int64)
        cells, trap, recs = scalar_rerun(
            eng.inst, self.conf, self._func_name, self._func_idx,
            self._args, lanes, max_steps)
        for r in recs:
            self._record_rec(r)
        nres = int(eng.inst.lowered.funcs[self._func_idx].nresults)
        results = [cells[r].view(np.int64).copy() for r in range(nres)]
        # retired counts live in device state the scalar rung never has;
        # zeros keep the BatchResult contract (trap is authoritative)
        return BatchResult(results=results, trap=trap,
                           retired=np.zeros(eng.lanes, np.int64), steps=0)

    # -- state / lineage --------------------------------------------------
    def _invocation_fingerprint(self) -> dict:
        """What this run is computing: the exported function plus a hash
        of the per-lane arguments (multi-tenant: every tenant's tuple).
        Recorded into each checkpoint and checked at lineage adoption —
        the image hash alone cannot tell f(30) from f(31), and a resume
        must never answer a NEW command with an OLD run's snapshot."""
        import hashlib

        h = hashlib.sha256()
        if self._multi:
            names = []
            for t in self.engine.tenants:
                names.append(t.func_name)
                for a in t.args_lanes:
                    h.update(np.ascontiguousarray(
                        np.asarray(a, np.int64)).tobytes())
            func = "|".join(names)
        else:
            func = self._func_name
            for a in self._args:
                h.update(np.ascontiguousarray(a).tobytes())
        return {"func": func, "args_sha256": h.hexdigest()}

    def _load_member(self, m):
        """Load one lineage member against THIS engine: fault seam,
        invocation binding (a snapshot of a different call — other
        export / other args — must be refused, not silently continued
        and reported as THIS run's answer; pre-invocation-stamp
        checkpoints carry no record and are accepted for back
        compatibility), then checkpoint.load (image hash + geometry
        binding is its job)."""
        from wasmedge_tpu.batch import checkpoint

        if self.faults is not None:
            self.faults.fire("checkpoint_load", path=m.path)
        inv = checkpoint.read_meta(m.path).get("invocation")
        if inv is not None and inv != self._invocation:
            raise ValueError(
                f"checkpoint invocation mismatch: snapshot is "
                f"{inv}, this run is {self._invocation}")
        t_load = self.obs.now()
        state, total = checkpoint.load(m.path, self.engine)
        self.obs.span("checkpoint_load", t_load, cat="supervisor",
                      track="supervisor", checkpoint=m.path,
                      steps=int(total))
        return state, total

    def _bad_member(self, exc, m):
        self._record("checkpoint", exc, checkpoint=m.path)

    def _adopt_lineage(self) -> bool:
        """Cross-process resume: adopt an existing checkpoint_dir
        lineage written by a previous process (shared newest-good-member
        walk, batch/lineage.py).  Verifies the newest member NOW so the
        run never starts from a snapshot that will refuse to load
        mid-recovery; older members stay lazily verified by _restore's
        fallback walk.  The loaded state is kept for _run_simt_tier (one
        deserialization, and the checkpoint_load fault seam fires once
        per member).  Returns True when a good member exists."""
        lin = self._lineage
        lin.install(Lineage.scan(self.checkpoint_dir, r"ckpt-(\d+)\.npz"))
        self._adopted = lin.walk_newest(self._load_member,
                                        self._bad_member)
        if lin:
            newest = lin.newest()
            self.obs.instant("resume_adopted", cat="supervisor",
                             track="supervisor", checkpoint=newest.path,
                             steps=newest.steps, lineage=len(lin))
        return bool(lin)

    def _initial_state(self):
        if self._multi:
            return self.engine.initial_state()
        return self.engine.initial_state(self._func_idx, self._args)

    def _restore(self):
        """Newest surviving checkpoint, else the initial state.  A member
        that fails to load (corrupt/truncated/injected) is recorded and
        dropped from the lineage — the next-older one is tried.  (Older
        adopted members were only filename-scanned at adoption;
        _load_member re-checks the invocation binding here so a retry
        can never walk back into a different call's snapshot.)"""
        def load(m):
            state, total = self._load_member(m)
            self._restored_from = m.path
            self._reset_cadence(total)
            return state, total

        got = self._lineage.walk_newest(load, self._bad_member)
        if got is not None:
            return got
        self._restored_from = None
        self._reset_cadence(0)
        # replay from scratch: rewind the logical stdout position but
        # KEEP the written high-water mark — output the failed attempt
        # already flushed is suppressed on replay, not written twice
        # (exactly-once stdout across restores, batch/hostcall.py)
        from wasmedge_tpu.batch.hostcall import stdout_cursor_reset

        stdout_cursor_reset(self.engine, keep_highwater=True)
        return self._initial_state(), 0

    def _reset_cadence(self, total: int):
        """Re-anchor the checkpoint cadence at the restored position —
        otherwise a restore to an older lineage member (or the initial
        state) leaves the step anchor ahead of `total` and the replayed
        region runs unprotected for up to several intervals."""
        self._last_ckpt_total = int(total)
        self._last_ckpt_wall = time.monotonic()

    def _cadence(self) -> bool:
        return bool(self.k.checkpoint_every_steps
                    or self.k.checkpoint_every_s)

    def _slice_target(self, total, max_steps) -> int:
        # slice the run so checkpoint decisions land on chunk-aligned
        # boundaries; without a cadence, one slice runs to the budget.
        # Both cadences are "whichever fires first": a wall-clock
        # cadence needs per-chunk boundary checks even when a (large)
        # step cadence is also configured.
        step = None
        if self.k.checkpoint_every_steps:
            step = int(self.k.checkpoint_every_steps)
        if self.k.checkpoint_every_s:
            chunk = max(int(self.engine.cfg.steps_per_launch), 1)
            step = chunk if step is None else min(step, chunk)
        if step is None:
            return max_steps
        return min(max_steps, total + step)

    def _maybe_checkpoint(self, state, total):
        if not self._cadence():
            return
        k = self.k
        due = bool(k.checkpoint_every_steps
                   and total - self._last_ckpt_total
                   >= k.checkpoint_every_steps)
        due = due or bool(k.checkpoint_every_s
                          and time.monotonic() - self._last_ckpt_wall
                          >= k.checkpoint_every_s)
        if not due:
            return
        from wasmedge_tpu.batch import checkpoint

        if self.checkpoint_dir is None:
            self.checkpoint_dir = tempfile.mkdtemp(prefix="wasmedge-ckpt-")
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        path = os.path.join(self.checkpoint_dir, f"ckpt-{total:012d}.npz")
        t_save = self.obs.now()
        try:
            if self.faults is not None:
                self.faults.fire("checkpoint_save", path=path)
            checkpoint.save(path, self.engine, state, total,
                            invocation=self._invocation)
            self.obs.span("checkpoint_save", t_save, cat="supervisor",
                          track="supervisor", checkpoint=path,
                          steps=int(total))
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # a failed snapshot must never kill a healthy run
            self._record("checkpoint", e, checkpoint=path)
            return
        self._lineage.add(path, total)
        self._last_ckpt_total = total
        self._last_ckpt_wall = time.monotonic()
        self._lineage.prune(self.k.keep_checkpoints)

    # -- quarantine -------------------------------------------------------
    def _quarantine_lanes(self, state, lanes):
        """Lanes that repeatedly fault the kernel: demote to the scalar
        rung (side-effect-free single-module batches — their results
        overlay the final harvest) or terminate (ErrCode.Terminated);
        either way the batch proceeds without them."""
        import jax.numpy as jnp

        lane_arr = np.asarray(sorted({int(x) for x in lanes}), np.int64)
        demoted = False
        if self._scalar_ok():
            cells, trap, recs = scalar_rerun(
                self.engine.inst, self.conf, self._func_name,
                self._func_idx, self._args, lane_arr, self._max_steps)
            for r in recs:
                self._record_rec(r)
            for col, lane in enumerate(lane_arr):
                self._overlay[int(lane)] = (cells[:, col].copy(),
                                            int(trap[col]))
            demoted = True
        self._record(
            "poison_lane", None, lanes=tuple(int(x) for x in lane_arr),
            tier="scalar" if demoted else "simt",
            error="demoted to scalar engine" if demoted
            else "terminated (ErrCode.Terminated)")
        trap_p = state.trap.at[jnp.asarray(lane_arr)].set(
            jnp.int32(int(ErrCode.Terminated)))
        return state._replace(trap=trap_p)

    def _check_runaways(self, state):
        cap = self.k.lane_step_cap
        if cap is None:
            return state
        trap_np = np.asarray(state.trap)
        ret_np = np.asarray(state.retired)
        over = np.nonzero((trap_np == 0) & (ret_np >= int(cap)))[0]
        if not over.size:
            return state
        import jax.numpy as jnp

        self._record("runaway", None,
                     lanes=tuple(int(x) for x in over),
                     error=f"lane_step_cap={int(cap)} exceeded; "
                           "terminated (ErrCode.Terminated)")
        trap_p = state.trap.at[jnp.asarray(over)].set(
            jnp.int32(int(ErrCode.Terminated)))
        return state._replace(trap=trap_p)

    # -- bookkeeping ------------------------------------------------------
    def _backoff(self, attempt: int):
        nap = backoff_seconds(self.k, attempt)
        if nap > 0:
            time.sleep(nap)

    def _record(self, fault_class, exc, lanes=(), tier="simt",
                checkpoint=None, error=None):
        # stamp() fills both clocks: wall time_s for logs, mono_s for
        # durations between incidents (survives wall-clock steps)
        self._record_rec(FailureRecord(
            fault_class=fault_class,
            error=error if error is not None
            else ("" if exc is None else repr(exc)),
            lanes=tuple(int(x) for x in lanes), retry=self.retries,
            checkpoint=checkpoint or self._restored_from,
            tier=tier).stamp())

    def _record_rec(self, rec: FailureRecord):
        self.failures.append(rec.stamp())
        # every incident is mirrored into the flight recorder as an
        # instant event on the supervisor track (obs/)
        self.obs.failure(rec)
        if self.stats is not None:
            self.stats.add_failure(rec)
        else:
            record_failure(rec)

    # -- harvest ----------------------------------------------------------
    def _result_single(self, state, total):
        from wasmedge_tpu.batch.engine import BatchResult

        nres = int(self.engine.inst.lowered.funcs[self._func_idx].nresults)
        stack_lo = np.asarray(state.stack_lo)
        stack_hi = np.asarray(state.stack_hi)
        results = []
        for r in range(nres):
            lo = stack_lo[r].view(np.uint32).astype(np.uint64)
            hi = stack_hi[r].view(np.uint32).astype(np.uint64)
            results.append((lo | (hi << np.uint64(32))).view(np.int64))
        trap = np.asarray(state.trap).copy()
        retired = np.asarray(state.retired).copy()
        for lane, (cells, tc) in self._overlay.items():
            trap[lane] = tc
            for r in range(nres):
                results[r][lane] = np.asarray(
                    [cells[r]], np.uint64).view(np.int64)[0]
        return BatchResult(results=results, trap=trap, retired=retired,
                           steps=total)
