"""Shared tier-0 hostcall kernel logic (three-tier pipeline, r06).

The SIMT engine (batch/engine.py) and the uniform converged engine
(batch/uniform.py) both service pure WASI calls in-kernel, and their
random_get streams / stored timestamps / stdout records must be
BIT-IDENTICAL across a divergence handoff (pinned by tests/
test_hostcall_pipeline.py::test_tier0_random_uniform_simt_bit_identical).
The two engines address memory differently — per-lane gathers/scatters
under lane masks vs dynamic-slice rows — so the shared bodies here are
parameterized by the caller's primitives:

  gather(plane, idx) -> [L]     per-lane word read at row idx
  rmw(plane, idx, m, v, ok)     masked read-modify-write:
                                plane[idx] = (cur & ~m) | (v & m)
                                where ok & (m != 0), else unchanged

Everything value-producing (the counter-PRNG, per-word whitening, clock
arithmetic, byte-granular store masks) lives here exactly once; the
engines keep only their dispatch/bail plumbing.
"""

from __future__ import annotations

import numpy as np


def t0_statics(cfg) -> dict:
    """Shared tier-0 kernel constants — ONE source for the SIMT and
    uniform engines (the random_get stream must stay bit-identical
    across a divergence handoff; errnos mirror host/wasi/wasi_abi)."""
    from wasmedge_tpu.host.wasi.wasi_abi import Errno

    seed = getattr(cfg, "rng_seed", None)
    if seed is None:
        # fresh entropy, drawn ONCE per Configure so every engine built
        # from it (SIMT + uniform fast path) shares the same stream
        seed = getattr(cfg, "_rng_seed_drawn", None)
        if seed is None:
            import os

            seed = int.from_bytes(os.urandom(4), "little")
            cfg._rng_seed_drawn = seed
    return {
        "RMAX_W": max(int(getattr(cfg, "tier0_random_max", 64)), 4) // 4,
        "WMAX_W": max(int(getattr(cfg, "tier0_write_max", 256)), 4) // 4,
        "RNG_SEED": np.array(seed & 0xFFFFFFFF, np.uint32).view(np.int32),
        "E_INVAL": int(Errno.INVAL),
        "E_FAULT": int(Errno.FAULT),
    }


def t0_prng32(x):
    """Counter-PRNG avalanche (int32 xorshift-multiply) behind tier-0
    random_get, deterministic per (cfg.rng_seed, lane, call seq, word)."""
    from jax import lax

    x = x ^ lax.shift_right_logical(x, 16)
    x = x * np.int32(0x7FEB352D)
    x = x ^ lax.shift_right_logical(x, 15)
    x = x * np.int32(np.uint32(0x846CA68B))
    x = x ^ lax.shift_right_logical(x, 16)
    return x


def t0_word_mix(j: int) -> np.ndarray:
    """Per-word whitening constant of the tier-0 random stream."""
    return np.array((j * 0x27220A95) & 0xFFFFFFFF, np.uint32).view(np.int32)


def t0_rng_seq_hash(rng_seed, lane_iota, ctr):
    """Per-(lane, call-seq) hash seeding the random_get word stream.
    Identical on both engines by construction — this IS the stream
    identity the handoff contract pins."""
    lane_h = t0_prng32(rng_seed ^ ((lane_iota + 1)
                                   * np.int32(-1640531527)))
    return lane_h ^ (ctr * np.int32(np.uint32(0x85EBCA6B)))


def t0_clock_value(t0_time, cid, ctr):
    """clock_time_get value: per-launch time base (row 0 realtime, row 1
    monotonic) plus the per-lane call sequence, as an int32 (lo, hi)
    pair — strictly increasing per lane even within one launch."""
    import jax.numpy as jnp

    from wasmedge_tpu.batch import laneops as lo_ops

    base_lo = jnp.where(cid == 1, t0_time[1, 0], t0_time[0, 0])
    base_hi = jnp.where(cid == 1, t0_time[1, 1], t0_time[0, 1])
    return lo_ops.add64(base_lo, base_hi, ctr, jnp.zeros_like(ctr))


def t0_masked_store(rmw, plane, ea, v_lo, v_hi, nbytes_c, ok):
    """Masked little-endian store of nbytes_c (4/8, static) at per-lane
    byte address ea (bounds checked by the caller) through the caller's
    read-modify-write primitive."""
    import jax.numpy as jnp
    from jax import lax

    from wasmedge_tpu.batch import laneops as lo_ops

    widx = lax.shift_right_logical(ea, 2)
    shB = (ea & 3) * 8
    f_lo = jnp.full_like(ea, jnp.int32(-1))
    f_hi = jnp.full_like(
        ea, jnp.int32(-1) if nbytes_c == 8 else jnp.int32(0))
    m0, m1 = lo_ops.shl64(f_lo, f_hi, shB)
    m2 = jnp.where(shB == 0, 0,
                   lo_ops.shr64_u(f_lo, f_hi, 64 - shB)[0])
    s0, s1 = lo_ops.shl64(v_lo, v_hi, shB)
    s2 = jnp.where(shB == 0, 0,
                   lo_ops.shr64_u(v_lo, v_hi, 64 - shB)[0])
    for k, (m, v) in enumerate(((m0, s0), (m1, s1), (m2, s2))):
        plane = rmw(plane, widx + k, m, v, ok)
    return plane


def t0_random_fill(rmw, mem, rbuf, rend, wr, seq_h, rmax_w, zero):
    """random_get word loop: write the counter-PRNG stream into guest
    bytes [rbuf, rend) with byte-granular edge masks.  `zero` is the
    caller's [L] int32 zero vector; the loop shape (rmax_w + 1 shifted
    windows) is the stream layout both engines must share."""
    import jax.numpy as jnp
    from jax import lax

    from wasmedge_tpu.batch import laneops as lo_ops

    shB = (rbuf & 3) * 8
    inv = (32 - shB) & 31
    hi_or = jnp.where(shB == 0, 0, -1)
    w0 = lax.shift_right_logical(rbuf, 2)
    prev = zero
    for j in range(rmax_w + 1):
        pw = t0_prng32(seq_h ^ jnp.asarray(t0_word_mix(j))) \
            if j < rmax_w else zero
        val = lax.shift_left(pw, shB) | \
            (lax.shift_right_logical(prev, inv) & hi_or)
        mk = zero
        for bpos in range(4):
            ba = (w0 + j) * 4 + bpos
            inr = ~lo_ops.u_lt(ba, rbuf) & lo_ops.u_lt(ba, rend)
            mk = mk | jnp.where(
                inr, jnp.int32(lo_ops.BYTE_MASKS[bpos]), 0)
        mem = rmw(mem, w0 + j, mk, val, wr)
        prev = pw
    return mem


def t0_shifted_src_word(gather, mem, w0, j, shB, inv, hi_or):
    """fd_write record payload: the j-th guest-memory source word of an
    unaligned iovec buffer, assembled from the two straddling plane
    words (the stdout record buffer itself is always word-aligned)."""
    from jax import lax

    s0 = gather(mem, w0 + j)
    s1 = gather(mem, w0 + j + 1)
    return lax.shift_right_logical(s0, shB) | \
        (lax.shift_left(s1, inv) & hi_or)
