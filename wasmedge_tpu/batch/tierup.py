"""Whole-function tier-up compilation (r20).

The r17/r19 superinstruction tiers shortened straight-line runs, but
every basic block still returns to the any-lane dispatch switch: a
counted loop of 8 ops pays one dispatch per op per iteration.  This
module promotes the hottest COMPILABLE whole functions out of the
dispatch loop entirely — the tiering argument of "A fast in-place
interpreter for WebAssembly" applied to the lockstep batch engine —
while keeping the promoted bodies lane-masked so divergent cohorts
stay correct ("Control Flow Management in Modern GPUs").

Three pieces, mirroring batch/fuse.py's planner/builder split:

  plan_tierup(img, cfg)   -- pure numpy/python planning pass: select
                             hot candidates (realized fusion weight +
                             analyzer cost bounds), apply the
                             compilability verdict, and bind the
                             promotion planes to the image
                             (tier_fn / tier_fuel_bound / tier_fns /
                             tierup_report).
  tierup_active(img, cfg) -- will `_make_step` compile promoted
                             bodies?  Shared by the step builder, the
                             obs counter-plane allocator and the
                             supervisor ladder so they never disagree.
  make_tierup_apply(...)  -- the jit-pure compiled-body builder the
                             step merges in (lint target).

The COMPILABILITY VERDICT is deliberately conservative (v1): a
promoted function must be a defined, non-recursive LEAF whose every
op is either a pure-eligible cell (batch/fuse.py eligibility: stack
motion + non-trapping ALU), an absint-LICENSED load (proven in-bounds
and aligned — it can never trap), or structured control flow
(br / br_if lowered forms / return), and whose analyzer cost bound is
finite — the r19 trip-bound license is what turns the function's
loops into bounded device loops.  Everything else keeps the
interpreted path; promotion never changes semantics, only dispatch
count.

A promoted call retires in ONE dispatch: the step routes lanes parked
at a promoted entry pc into a lane-masked CFG body (block dispatch
inside a bounded `lax.while_loop`), and the lanes come back either
RETURNED (the step's return merge pops their frame exactly like the
per-op CLS_RETURN rung) or BAILED at a block head (the iteration cap —
never reached when the bound is exact — hands them back to the per-op
path mid-function, bit-identically).  Off (or nothing promoted)
compiles the bit-identical seed step by construction.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from wasmedge_tpu.batch.fuse import cell_eligible
from wasmedge_tpu.batch.image import (
    CLS_ALU1,
    CLS_ALU2,
    CLS_BR,
    CLS_BRNZ,
    CLS_BRZ,
    CLS_CONST,
    CLS_DROP,
    CLS_LOAD,
    CLS_LOCAL_GET,
    CLS_LOCAL_SET,
    CLS_LOCAL_TEE,
    CLS_NOP,
    CLS_RETURN,
    CLS_SELECT,
)

# Block terminator kinds the v1 body compiles (analysis/cfg.py
# _block_kind).  Everything else (br_table, calls, tail calls,
# unreachable) fails the verdict.
_OK_KINDS = frozenset(("fallthrough", "br", "brz", "brnz", "return"))

# Straight-line classes the body compiles besides licensed loads and
# the terminators above.  GLOBAL_GET/SET are pure-eligible for fusion
# but excluded here to keep the conditional's tuple carry to the stack
# planes (the counted-loop shapes that win never touch globals).
_PURE_OK = frozenset((CLS_NOP, CLS_CONST, CLS_LOCAL_GET, CLS_LOCAL_SET,
                      CLS_LOCAL_TEE, CLS_DROP, CLS_SELECT))
_TERM_CLS = frozenset((CLS_BR, CLS_BRZ, CLS_BRNZ, CLS_RETURN))


def tierup_active(img, cfg) -> bool:
    """Will `_make_step(img, cfg, ...)` compile promoted bodies?"""
    if not getattr(cfg, "tierup", True):
        return False
    tf = getattr(img, "tier_fn", None)
    return tf is not None and bool((np.asarray(tf) >= 0).any())


def _op_verdict(img, pc: int, licensed) -> Optional[str]:
    """None when the op at `pc` may join a compiled body, else the
    refusal reason."""
    cls = int(img.cls[pc])
    if cls in _PURE_OK or cls in _TERM_CLS:
        return None
    if cls in (CLS_ALU1, CLS_ALU2):
        if cell_eligible(cls, int(img.sub[pc])):
            return None
        return f"trapping/heavy alu at pc {pc}"
    if cls == CLS_LOAD:
        if pc in licensed:
            return None
        return f"unlicensed load at pc {pc}"
    return f"class {cls} at pc {pc}"


def _func_verdict(img, f, licensed, max_blocks: int,
                  max_ops: int) -> Optional[str]:
    """None when FuncAnalysis `f` is promotable, else the reason."""
    cfg = getattr(f, "cfg", None)
    if cfg is None or f.entry_pc < 0:
        return "no cfg / import"
    if getattr(f, "recursive", False):
        return "recursive"
    if getattr(f, "dynamic_calls", False):
        return "dynamic calls"
    if getattr(f, "hostcall_sites", None):
        return "hostcall sites"
    if f.cost_bound is None:
        return "unbounded cost (no trip license)"
    if len(cfg.blocks) > max_blocks:
        return f"{len(cfg.blocks)} blocks > cap {max_blocks}"
    n_ops = f.end_pc - f.entry_pc + 1
    if n_ops > max_ops:
        return f"{n_ops} ops > cap {max_ops}"
    by_start = {b.start for b in cfg.blocks}
    for bi, b in enumerate(cfg.blocks):
        if b.calls or b.dynamic_call:
            return "leaf only (calls in body)"
        if b.kind not in _OK_KINDS:
            return f"terminator {b.kind}"
        if b.kind == "fallthrough" and not b.succ:
            return "falls off function end"
        if b.kind in ("brz", "brnz") and len(b.succ) != 2:
            return "conditional without fallthrough"
        for s in b.succ:
            if s not in by_start:
                return f"successor {s} outside function"
        # analyzer block cost must dominate the block's op count so
        # cost_bound also bounds RETIRED OPS (the device-loop cap and
        # the ops-times-max-weight fuel gate both lean on this; a
        # zero-weight cost table would break the domination)
        costs = getattr(f, "block_costs", None)
        if costs is not None and bi < len(costs) \
                and costs[bi] < (b.end - b.start + 1):
            return "zero-weight cost table"
    for pc in range(f.entry_pc, f.end_pc + 1):
        r = _op_verdict(img, pc, licensed)
        if r is not None:
            return r
    return None


def _fuel_bound(img, cfg, f) -> int:
    """Static upper bound on the WEIGHTED gas one full call consumes.

    cost_bound bounds retired ops (block costs dominate op counts —
    verdict-checked), so ops x the function's max per-op engine weight
    bounds the gas.  Conservative is fine: lanes failing the fuel
    pre-gate step per-op, bit-identically."""
    maxw = 1
    ct = getattr(cfg, "cost_table", None)
    op_id = getattr(img, "op_id", None)
    if ct is not None and op_id is not None:
        for pc in range(f.entry_pc, f.end_pc + 1):
            o = int(op_id[pc])
            try:
                maxw = max(maxw, int(ct[o]))
            except (IndexError, KeyError):
                maxw = max(maxw, 1)
    return int(f.cost_bound) * maxw


def _hot_score(img, f) -> int:
    """Hotness rank: realized fused-run weight within the function
    (the r17/r19 `.fusion.json` plan, read back off the fuse_len
    plane) plus the analyzer cost bound (bounded loop nests are where
    the per-op dispatches go)."""
    score = int(f.cost_bound or 0)
    flen = getattr(img, "fuse_len", None)
    if flen is not None:
        fl = np.asarray(flen)[f.entry_pc:f.end_pc + 1]
        score += int(fl[fl >= 2].sum())
    return score


def plan_tierup(img, cfg=None, analysis=None) -> dict:
    """Select + verdict the promoted set and bind it to `img`.

    Mutates the image in place (tier_fn / tier_fuel_bound / tier_fns /
    tierup_report) and returns the report.  Pure numpy/python — no jax
    import.  `analysis` defaults to the image's lazily-bound
    ModuleAnalysis; None (concatenated multi-tenant images, analyzer
    failure) plans nothing."""
    if cfg is None:
        from wasmedge_tpu.common.configure import BatchConfigure

        cfg = BatchConfigure()
    top_k = max(int(getattr(cfg, "tierup_top_k", 4)), 0)
    max_blocks = max(int(getattr(cfg, "tierup_max_blocks", 16)), 1)
    max_ops = max(int(getattr(cfg, "tierup_max_ops", 128)), 1)
    report: dict = {
        "enabled": bool(getattr(cfg, "tierup", True)),
        "top_k": top_k,
        "max_blocks": max_blocks,
        "max_ops": max_ops,
        "candidates": [],
        "promoted": [],
    }
    img.tierup_report = report
    img.tier_fn = None
    img.tier_fuel_bound = None
    img.tier_fns = ()
    if not report["enabled"] or top_k == 0:
        return report
    if analysis is None:
        analysis = img.analysis
    if analysis is None:
        return report
    licensed = getattr(analysis, "licensed_pcs", frozenset()) or frozenset()

    rows = []
    for f in analysis.funcs:
        verdict = _func_verdict(img, f, licensed, max_blocks, max_ops)
        row = {
            "idx": int(f.idx),
            "name": getattr(f, "name", None) or f"func{f.idx}",
            "cost_bound": f.cost_bound,
            "score": _hot_score(img, f),
            "promotable": verdict is None,
            "refusal": verdict,
        }
        rows.append((row, f))
    rows.sort(key=lambda rf: (-rf[0]["score"], rf[0]["idx"]))
    report["candidates"] = [r for r, _ in rows]

    tier_fn = np.full(int(img.code_len), -1, np.int32)
    fuel_bound = np.zeros(int(img.code_len), np.int32)
    plans: List[dict] = []
    for row, f in rows:
        if not row["promotable"] or len(plans) >= top_k:
            continue
        slot = len(plans)
        fb = min(_fuel_bound(img, cfg, f), (1 << 30))
        blocks = [{
            "start": int(b.start), "end": int(b.end),
            "kind": b.kind, "succ": tuple(int(s) for s in b.succ),
            "is_loop_head": bool(b.is_loop_head),
        } for b in f.cfg.blocks]
        plan = {
            "slot": slot,
            "idx": int(f.idx),
            "name": row["name"],
            "entry_pc": int(f.entry_pc),
            "end_pc": int(f.end_pc),
            "cost_bound": int(f.cost_bound),
            "fuel_bound": int(fb),
            "blocks": blocks,
            # the bounded-device-loop license: a loop head inside a
            # finite-cost_bound function iterates under the absint
            # trip bound (unbounded loops poison cost_bound to None)
            "device_loops": sum(1 for b in blocks if b["is_loop_head"]),
        }
        plans.append(plan)
        tier_fn[f.entry_pc] = slot
        fuel_bound[f.entry_pc] = fb
        report["promoted"].append({
            k: plan[k] for k in ("slot", "idx", "name", "entry_pc",
                                 "cost_bound", "fuel_bound",
                                 "device_loops")})
    if plans:
        img.tier_fn = tier_fn
        img.tier_fuel_bound = fuel_bound
        img.tier_fns = tuple(plans)
    return report


def make_tierup_apply(img, lanes: int, has_simd: bool,
                      cost_np=None):
    """Build the compiled-function handler `_make_step` merges in.

    One lane-masked CFG body per promoted function, each wrapped in
    its own any-lane conditional: a bounded `lax.while_loop` whose
    carry holds the per-lane block index, and whose body executes
    every block's straight-line ops as trace-time-static masked
    gather/scatter (pcs are Python ints, so operands come from numpy
    planes, not device gathers) and then resolves the terminator into
    the next block index.  Loop heads iterate INSIDE the device loop —
    the r19 trip bound (finite cost_bound, verdict-enforced) caps the
    iteration count, so the loop is bounded by construction.

    `cost_np` is the engine's per-op gas weight plane (None = flat 1);
    the body returns exact per-lane retired/fuel deltas so gas and the
    opcode histogram attribute identically to the per-op path.

    Returns tierup_apply(stacks, mem, op_hist, pc, sp, fp, opbase,
    is_comp) -> (stacks', op_hist', sp', returned, bailed, bail_pc,
    retired_d, fuel_d).  `mem` is READ-ONLY (v1 promotes load-only
    functions); lanes outside `is_comp` pass through bit-unchanged.

    jit-purity lint target (tools/lint_jit_purity.py): everything
    nested here runs under trace.
    """
    import jax.numpy as jnp
    from jax import lax

    from wasmedge_tpu.batch import laneops as lo_ops

    I32 = jnp.int32
    lane_iota = jnp.arange(lanes, dtype=I32)
    A2F = lo_ops.alu2_fns()
    A1F = lo_ops.alu1_fns()
    b2i = lo_ops.b2i
    NC = 4 if has_simd else 2
    plans = img.tier_fns
    cls_np = np.asarray(img.cls)
    sub_np = np.asarray(img.sub)
    a_np = np.asarray(img.a)
    b_np = np.asarray(img.b)
    c_np = np.asarray(img.c)
    ilo_np = np.asarray(img.imm_lo)
    ihi_np = np.asarray(img.imm_hi)
    w_np = (np.asarray(cost_np) if cost_np is not None
            else np.ones(cls_np.shape[0], np.int32))

    def gat(plane, idx):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        return jnp.take_along_axis(plane, idx[None, :], axis=0)[0]

    def scat(plane, idx, vals, mask):
        idx = jnp.clip(idx, 0, plane.shape[0] - 1)
        cur = jnp.take_along_axis(plane, idx[None, :], axis=0)[0]
        return plane.at[idx, lane_iota].set(jnp.where(mask, vals, cur))

    def tierup_apply(stacks, mem, op_hist, pc, sp, fp, opbase, is_comp):
        stacks = tuple(stacks)
        zl = jnp.zeros_like(sp)
        false_l = is_comp & False
        out_sp = sp
        out_ret = false_l
        out_bail = false_l
        out_bail_pc = pc
        out_rd = zl
        out_fd = zl

        for plan in plans:
            m_f = is_comp & (pc == plan["entry_pc"])
            blocks = plan["blocks"]
            bi_of = {b["start"]: bi for bi, b in enumerate(blocks)}
            nb = len(blocks)
            cap = max(int(plan["cost_bound"]), 1)
            track_hist = op_hist is not None

            def _run_fn(ops, blocks=blocks, bi_of=bi_of, nb=nb,
                        cap=cap, m_f=m_f, track_hist=track_hist,
                        plan=plan):
                stks, oh = ops

                def push(stks, spv, m, v):
                    for comp in range(NC):
                        stks[comp] = scat(stks[comp], spv,
                                          v[comp] if comp < len(v)
                                          else zl, m)
                    return jnp.where(m, spv + 1, spv)

                def rd3(stks, idx):
                    return tuple(gat(p, idx) for p in stks)

                def cond(carry):
                    _, _, _, live, _, _, _, i = carry
                    return (i < cap) & jnp.any(live)

                def body(carry):
                    stks, spv, blk, live, ret, rd, fd, i = carry
                    stks = list(stks)
                    blk_n = blk
                    for bi, blkp in enumerate(blocks):
                        mb = live & (blk == bi)
                        start, end, kind = (blkp["start"], blkp["end"],
                                            blkp["kind"])
                        term = end if kind != "fallthrough" else end + 1
                        for pcj in range(start, term):
                            cls_j = int(cls_np[pcj])
                            aj = int(a_np[pcj])
                            if cls_j == CLS_NOP:
                                continue
                            elif cls_j == CLS_CONST:
                                spv = push(stks, spv, mb, (
                                    jnp.full_like(zl, int(ilo_np[pcj])),
                                    jnp.full_like(zl, int(ihi_np[pcj]))))
                            elif cls_j == CLS_LOCAL_GET:
                                v = rd3(stks, fp + aj)
                                spv = push(stks, spv, mb, v)
                            elif cls_j in (CLS_LOCAL_SET,
                                           CLS_LOCAL_TEE):
                                v = rd3(stks, spv - 1)
                                for comp in range(NC):
                                    stks[comp] = scat(
                                        stks[comp], fp + aj, v[comp],
                                        mb)
                                if cls_j == CLS_LOCAL_SET:
                                    spv = jnp.where(mb, spv - 1, spv)
                            elif cls_j == CLS_DROP:
                                spv = jnp.where(mb, spv - 1, spv)
                            elif cls_j == CLS_SELECT:
                                cv = rd3(stks, spv - 1)
                                v2 = rd3(stks, spv - 2)
                                v1 = rd3(stks, spv - 3)
                                cz = cv[0] == 0
                                sel = tuple(jnp.where(cz, b_c, a_c)
                                            for b_c, a_c
                                            in zip(v2, v1))
                                for comp in range(NC):
                                    stks[comp] = scat(
                                        stks[comp], spv - 3,
                                        sel[comp], mb)
                                spv = jnp.where(mb, spv - 2, spv)
                            elif cls_j == CLS_ALU1:
                                v = rd3(stks, spv - 1)
                                rl, rh = A1F[int(sub_np[pcj])](
                                    v[0], v[1])
                                spv = push(stks,
                                           jnp.where(mb, spv - 1, spv),
                                           mb, (rl, rh))
                            elif cls_j == CLS_ALU2:
                                y = rd3(stks, spv - 1)
                                x = rd3(stks, spv - 2)
                                rl, rh = A2F[int(sub_np[pcj])](
                                    x[0], x[1], y[0], y[1])
                                spv = push(stks,
                                           jnp.where(mb, spv - 2, spv),
                                           mb, (rl, rh))
                            elif cls_j == CLS_LOAD:
                                # absint-licensed: in-bounds, never
                                # straddles a word (width-specialized,
                                # the make_memfuse_apply load shape)
                                nbytes = int(b_np[pcj])
                                signed = int(c_np[pcj]) & 1
                                is64 = (int(c_np[pcj]) >> 1) & 1
                                av = rd3(stks, spv - 1)
                                ea = av[0] + aj
                                widx = lax.shift_right_logical(ea, 2)
                                w0 = gat(mem, widx)
                                hi = zl
                                if nbytes == 8:
                                    lo = w0
                                    hi = gat(mem, widx + 1)
                                elif nbytes == 4:
                                    lo = w0
                                else:
                                    sh = (ea & 3) * 8
                                    raw = lax.shift_right_logical(
                                        w0, sh)
                                    bits = nbytes * 8
                                    if signed:
                                        lo = lax.shift_right_arithmetic(
                                            lax.shift_left(
                                                raw, 32 - bits),
                                            32 - bits)
                                    else:
                                        lo = raw & ((1 << bits) - 1)
                                if is64 and nbytes < 8:
                                    hi = lax.shift_right_arithmetic(
                                        lo, 31) if signed else zl
                                spv = push(stks,
                                           jnp.where(mb, spv - 1, spv),
                                           mb, (lo, hi))
                            else:  # planner bug: surface at trace time
                                raise AssertionError(
                                    f"uncompilable class {cls_j} at "
                                    f"pc {pcj} in promoted "
                                    f"{plan['name']}")
                        # terminator -> next block index / return
                        if kind == "fallthrough":
                            nxt = bi_of[blkp["succ"][0]]
                            blk_n = jnp.where(mb, nxt, blk_n)
                        elif kind == "br":
                            bv, cv_ = int(b_np[end]), int(c_np[end])
                            if bv == 1:
                                v = rd3(stks, spv - 1)
                                for comp in range(NC):
                                    stks[comp] = scat(
                                        stks[comp], opbase + cv_,
                                        v[comp], mb)
                            spv = jnp.where(mb, opbase + cv_ + bv, spv)
                            blk_n = jnp.where(
                                mb, bi_of[int(a_np[end])], blk_n)
                        elif kind == "brz":
                            cv = rd3(stks, spv - 1)
                            spv = jnp.where(mb, spv - 1, spv)
                            taken = mb & (cv[0] == 0)
                            blk_n = jnp.where(
                                taken, bi_of[int(a_np[end])],
                                jnp.where(mb, bi_of[end + 1], blk_n))
                        elif kind == "brnz":
                            bv, cv_ = int(b_np[end]), int(c_np[end])
                            cv = rd3(stks, spv - 1)
                            taken = mb & (cv[0] != 0)
                            if bv == 1:
                                v = rd3(stks, spv - 2)
                                for comp in range(NC):
                                    stks[comp] = scat(
                                        stks[comp], opbase + cv_,
                                        v[comp], taken)
                            spv = jnp.where(
                                taken, opbase + cv_ + bv,
                                jnp.where(mb, spv - 1, spv))
                            blk_n = jnp.where(
                                taken, bi_of[int(a_np[end])],
                                jnp.where(mb, bi_of[end + 1], blk_n))
                        else:  # return
                            nres = int(b_np[end])
                            if nres == 1:
                                v = rd3(stks, spv - 1)
                                for comp in range(NC):
                                    stks[comp] = scat(
                                        stks[comp], fp, v[comp], mb)
                            spv = jnp.where(mb, fp + nres, spv)
                            ret = ret | mb
                            live = live & ~mb
                        n_ops = end - start + 1
                        w_blk = int(w_np[start:end + 1].sum())
                        rd = rd + jnp.where(mb, n_ops, 0)
                        fd = fd + jnp.where(mb, w_blk, 0)
                        if track_hist:
                            nonlocal_oh[bi] = nonlocal_oh[bi] \
                                + jnp.sum(b2i(mb))
                    return (tuple(stks), spv, blk_n, live, ret, rd,
                            fd, i + 1)

                # per-block execution counters for the opcode
                # histogram (device plane; list is rebuilt per trace)
                nonlocal_oh = [jnp.int32(0)] * nb
                if track_hist:
                    def body_h(carry):
                        c, oh_c = carry
                        nonlocal_oh.clear()
                        nonlocal_oh.extend(
                            oh_c[bi] for bi in range(nb))
                        out = body(c)
                        return out, tuple(nonlocal_oh)

                    def cond_h(carry):
                        return cond(carry[0])

                    entry_bi = bi_of[plan["entry_pc"]]
                    carry0 = ((stks, sp, jnp.full_like(zl, entry_bi),
                               m_f, false_l, zl, zl, jnp.int32(0)),
                              tuple(jnp.int32(0) for _ in range(nb)))
                    (stks2, spv, blk, live, ret, rd, fd, _), oh_cnt = \
                        lax.while_loop(cond_h, body_h, carry0)
                    for bi, blkp in enumerate(blocks):
                        for pcj in range(blkp["start"],
                                         blkp["end"] + 1):
                            oh = oh.at[pcj].add(oh_cnt[bi])
                else:
                    entry_bi = bi_of[plan["entry_pc"]]
                    carry0 = (stks, sp, jnp.full_like(zl, entry_bi),
                              m_f, false_l, zl, zl, jnp.int32(0))
                    stks2, spv, blk, live, ret, rd, fd, _ = \
                        lax.while_loop(cond, body, carry0)
                starts = jnp.asarray(
                    np.array([b["start"] for b in blocks], np.int32))
                bail_pc = starts[jnp.clip(blk, 0, nb - 1)]
                return (tuple(stks2), oh, spv, ret, live, bail_pc,
                        rd, fd)

            def _skip_fn(ops):
                stks, oh = ops
                return (stks, oh, sp, false_l, false_l, pc, zl, zl)

            stacks, op_hist, f_sp, f_ret, f_bail, f_bpc, f_rd, f_fd = \
                lax.cond(jnp.any(m_f), _run_fn, _skip_fn,
                         (stacks, op_hist))
            out_sp = jnp.where(m_f, f_sp, out_sp)
            out_ret = out_ret | (m_f & f_ret)
            out_bail = out_bail | (m_f & f_bail)
            out_bail_pc = jnp.where(m_f & f_bail, f_bpc, out_bail_pc)
            out_rd = out_rd + jnp.where(m_f, f_rd, 0)
            out_fd = out_fd + jnp.where(m_f, f_fd, 0)
        return (list(stacks), op_hist, out_sp, out_ret, out_bail,
                out_bail_pc, out_rd, out_fd)

    return tierup_apply
