"""Uniform-mode engine: converged-lane lockstep with scalar control state.

When every lane runs the same module from the same entry with data that
resolves branches identically (BASELINE config 1/2: N copies of fib(30) /
CoreMark), pc/sp/fp/call_depth are lane-uniform. This engine keeps them as
*scalars*: instruction fetch is a scalar table read, dispatch is a scalar
`lax.switch` (one handler per step, not all handlers masked), and every
stack/memory access is a `dynamic_slice` / `dynamic_update_slice` row op of
[lanes] elements — the access pattern the TPU loves, no gathers at all.

Divergence (a data-dependent branch or trap disagreeing across lanes) is
detected on-device; the engine stops with `diverged=1` and the host falls
back to the SIMT engine (batch/engine.py), which shares the same state
layout. This is the PC-voting design from SURVEY.md §7 step 4 with vote =
"all lanes agree or bail".

Per-lane *data* still diverges freely (different args are fine as long as
branches resolve the same way); per-lane traps are only divergence when
they differ across lanes.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from wasmedge_tpu.common.errors import ErrCode
from wasmedge_tpu.batch.image import (
    ALU1_SUB,
    CLS_ALU1,
    CLS_ALU2,
    CLS_BR,
    CLS_BR_TABLE,
    CLS_BRNZ,
    CLS_BRZ,
    CLS_CALL,
    CLS_CALL_INDIRECT,
    CLS_CONST,
    CLS_DROP,
    CLS_GLOBAL_GET,
    CLS_GLOBAL_SET,
    CLS_HOSTCALL,
    CLS_LOAD,
    CLS_LOCAL_GET,
    CLS_LOCAL_SET,
    CLS_LOCAL_TEE,
    CLS_MEMCOPY,
    CLS_MEMFILL,
    CLS_MEMGROW,
    CLS_MEMSIZE,
    CLS_NOP,
    CLS_RETURN,
    CLS_SELECT,
    CLS_STORE,
    CLS_TRAP,
    NUM_CLASSES,
    TRAP_DONE,
    DeviceImage,
    _F32_BIN,
    _I32_BIN,
    ALU2_I32_BASE,
    ALU2_I64_BASE,
    ALU2_F32_BASE,
)


class UniformState(NamedTuple):
    # scalar (lane-uniform) control
    pc: object
    sp: object
    fp: object
    opbase: object
    call_depth: object
    status: object  # 0 running, 1 done, 2 diverged->SIMT, >2 trap code+16
    steps: object
    mem_pages: object
    # vector data planes
    stack_lo: object  # [D, L]
    stack_hi: object
    fr_ret_pc: object  # [CD] scalar frames! (uniform control)
    fr_fp: object
    fr_opbase: object
    glob_lo: object  # [NG, L]
    glob_hi: object
    mem: object  # [W, L]
    trap: object  # [L] per-lane pending trap (uniform or lane diverges)
    # tier-0 hostcall planes (same discipline as BatchState; present
    # only when the engine services tier-0 in-kernel).  A divergence
    # handoff carries them INTO the SIMT state — calls already retired
    # here must not lose their buffered output or counter positions.
    t0_ctr: object = None   # [4, L]
    so_buf: object = None   # [SW, L]
    so_off: object = None   # [L]


ST_RUNNING = 0
ST_DONE = 1
ST_DIVERGED = 2
ST_TRAPPED_BASE = 16  # status = 16 + ErrCode when ALL lanes trap identically


def make_uniform_step(img: DeviceImage, cfg, lanes: int, t0kinds=None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from wasmedge_tpu.batch import laneops as lo_ops

    I32 = jnp.int32
    D = cfg.value_stack_depth
    CD = cfg.call_stack_depth

    cls_t = jnp.asarray(img.cls)
    sub_t = jnp.asarray(img.sub)
    a_t = jnp.asarray(img.a)
    b_t = jnp.asarray(img.b)
    c_t = jnp.asarray(img.c)
    ilo_t = jnp.asarray(img.imm_lo)
    ihi_t = jnp.asarray(img.imm_hi)
    brt_t = jnp.asarray(img.br_table)
    f_entry = jnp.asarray(img.f_entry)
    f_nparams = jnp.asarray(img.f_nparams)
    f_nlocals = jnp.asarray(img.f_nlocals)
    f_frame_top = jnp.asarray(img.f_frame_top)
    f_type = jnp.asarray(img.f_type)
    table0 = jnp.asarray(img.table0)

    S_I32 = {n: ALU2_I32_BASE + i for i, n in enumerate(_I32_BIN)}
    S_I64 = {n: ALU2_I64_BASE + i for i, n in enumerate(_I32_BIN)}
    S_F32 = {n: ALU2_F32_BASE + i for i, n in enumerate(_F32_BIN)}
    A1 = ALU1_SUB
    b2i = lo_ops.b2i
    u_lt = lo_ops.u_lt

    def row(plane, i):
        """plane[i] via dynamic_slice (scalar i) -> [L]."""
        i = jnp.clip(i, 0, plane.shape[0] - 1)
        return lax.dynamic_slice_in_dim(plane, i, 1, 0)[0]

    def setrow(plane, i, vals):
        i = jnp.clip(i, 0, plane.shape[0] - 1)
        return lax.dynamic_update_slice_in_dim(plane, vals[None, :], i, 0)

    def sget(arr, i):
        i = jnp.clip(i, 0, arr.shape[0] - 1)
        return lax.dynamic_slice_in_dim(arr, i, 1, 0)[0]

    def sset(arr, i, v):
        i = jnp.clip(i, 0, arr.shape[0] - 1)
        return lax.dynamic_update_slice_in_dim(arr, v[None], i, 0)

    def halt(st, status):
        return st._replace(status=status)

    # ---------------- class handlers (each: (st, fetch) -> st) -----------
    # fetch = (sub, a, b, c, ilo, ihi) scalars

    def h_nop(st, f):
        return st._replace(pc=st.pc + 1)

    def h_const(st, f):
        sub, a, b, c, ilo, ihi = f
        sl = setrow(st.stack_lo, st.sp, jnp.full((lanes,), ilo, I32))
        sh = setrow(st.stack_hi, st.sp, jnp.full((lanes,), ihi, I32))
        return st._replace(pc=st.pc + 1, sp=st.sp + 1, stack_lo=sl, stack_hi=sh)

    def h_local_get(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.fp + a)
        vh = row(st.stack_hi, st.fp + a)
        sl = setrow(st.stack_lo, st.sp, vl)
        sh = setrow(st.stack_hi, st.sp, vh)
        return st._replace(pc=st.pc + 1, sp=st.sp + 1, stack_lo=sl, stack_hi=sh)

    def h_local_set(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.sp - 1)
        vh = row(st.stack_hi, st.sp - 1)
        sl = setrow(st.stack_lo, st.fp + a, vl)
        sh = setrow(st.stack_hi, st.fp + a, vh)
        return st._replace(pc=st.pc + 1, sp=st.sp - 1, stack_lo=sl, stack_hi=sh)

    def h_local_tee(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.sp - 1)
        vh = row(st.stack_hi, st.sp - 1)
        sl = setrow(st.stack_lo, st.fp + a, vl)
        sh = setrow(st.stack_hi, st.fp + a, vh)
        return st._replace(pc=st.pc + 1, stack_lo=sl, stack_hi=sh)

    def h_global_get(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.glob_lo, a)
        vh = row(st.glob_hi, a)
        sl = setrow(st.stack_lo, st.sp, vl)
        sh = setrow(st.stack_hi, st.sp, vh)
        return st._replace(pc=st.pc + 1, sp=st.sp + 1, stack_lo=sl, stack_hi=sh)

    def h_global_set(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.sp - 1)
        vh = row(st.stack_hi, st.sp - 1)
        gl = setrow(st.glob_lo, a, vl)
        gh = setrow(st.glob_hi, a, vh)
        return st._replace(pc=st.pc + 1, sp=st.sp - 1, glob_lo=gl, glob_hi=gh)

    def h_drop(st, f):
        return st._replace(pc=st.pc + 1, sp=st.sp - 1)

    def h_select(st, f):
        cond = row(st.stack_lo, st.sp - 1)
        v1l = row(st.stack_lo, st.sp - 2)
        v1h = row(st.stack_hi, st.sp - 2)
        v2l = row(st.stack_lo, st.sp - 3)
        v2h = row(st.stack_hi, st.sp - 3)
        rl = jnp.where(cond == 0, v1l, v2l)
        rh = jnp.where(cond == 0, v1h, v2h)
        sl = setrow(st.stack_lo, st.sp - 3, rl)
        sh = setrow(st.stack_hi, st.sp - 3, rh)
        return st._replace(pc=st.pc + 1, sp=st.sp - 2, stack_lo=sl, stack_hi=sh)

    A2 = lo_ops.alu2_fns()

    def _alu_result(sub, xl, xh, yl, yh):
        """Scalar-sub dispatch over the shared ALU table (laneops.alu2_fns,
        the single source of ALU semantics for all batch engines)."""
        n_subs = max(A2) + 1

        def mk(i):
            f = A2.get(i)
            if f is None:
                return lambda: (xl, xh)
            return lambda: f(xl, xh, yl, yh)

        fns = [mk(i) for i in range(n_subs)]
        return lax.switch(jnp.clip(sub, 0, n_subs - 1), fns)

    def h_alu2(st, f):
        sub, a, b, c, ilo, ihi = f
        xl = row(st.stack_lo, st.sp - 2)
        xh = row(st.stack_hi, st.sp - 2)
        yl = row(st.stack_lo, st.sp - 1)
        yh = row(st.stack_hi, st.sp - 1)
        rl, rh = _alu_result(sub, xl, xh, yl, yh)
        # div-by-zero / overflow traps (uniform check later via trap plane)
        is_div32 = (sub == S_I32["div_s"]) | (sub == S_I32["div_u"]) | \
            (sub == S_I32["rem_s"]) | (sub == S_I32["rem_u"])
        is_div64 = (sub == S_I64["div_s"]) | (sub == S_I64["div_u"]) | \
            (sub == S_I64["rem_s"]) | (sub == S_I64["rem_u"])
        dz = (is_div32 & (yl == 0)) | (is_div64 & ((yl | yh) == 0))
        ovf = ((sub == S_I32["div_s"]) & (xl == jnp.int32(-0x80000000)) & (yl == -1)) | \
              ((sub == S_I64["div_s"]) & (xl == 0) & (xh == jnp.int32(-0x80000000))
               & (yl == -1) & (yh == -1))
        lane_trap = jnp.where(dz, int(ErrCode.DivideByZero),
                              jnp.where(ovf, int(ErrCode.IntegerOverflow), 0))
        sl = setrow(st.stack_lo, st.sp - 2, rl)
        sh = setrow(st.stack_hi, st.sp - 2, rh)
        return st._replace(pc=st.pc + 1, sp=st.sp - 1, stack_lo=sl, stack_hi=sh,
                           trap=jnp.where(lane_trap != 0, lane_trap, st.trap))

    A1F = lo_ops.alu1_fns()
    A1T = lo_ops.alu1_trap_fns()

    def h_alu1(st, f):
        sub, a, b, c, ilo, ihi = f
        wl = row(st.stack_lo, st.sp - 1)
        wh = row(st.stack_hi, st.sp - 1)
        n_subs = max(A1F) + 1

        def mk(i):
            f1 = A1F.get(i)
            if f1 is None:
                return lambda: (wl, wh)
            return lambda: f1(wl, wh)

        fns = [mk(i) for i in range(n_subs)]
        rl, rh = lax.switch(jnp.clip(sub, 0, n_subs - 1), fns)

        def mk_trap(i):
            t1 = A1T.get(i)
            if t1 is None:
                return lambda: (jnp.zeros_like(wl) != 0, jnp.zeros_like(wl))
            return lambda: t1(wl, wh)

        tfns = [mk_trap(i) for i in range(n_subs)]
        bad, codes = lax.switch(jnp.clip(sub, 0, n_subs - 1), tfns)
        lane_trap = jnp.where(bad, codes, jnp.int32(0))
        sl = setrow(st.stack_lo, st.sp - 1, rl)
        sh = setrow(st.stack_hi, st.sp - 1, rh)
        return st._replace(pc=st.pc + 1, stack_lo=sl, stack_hi=sh,
                           trap=jnp.where(lane_trap != 0, lane_trap, st.trap))

    def h_br(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.sp - 1)
        vh = row(st.stack_hi, st.sp - 1)
        tgt_sp = st.opbase + c
        sl = jnp.where(b == 1, setrow(st.stack_lo, tgt_sp, vl), st.stack_lo)
        sh = jnp.where(b == 1, setrow(st.stack_hi, tgt_sp, vh), st.stack_hi)
        return st._replace(pc=a, sp=tgt_sp + b, stack_lo=sl, stack_hi=sh)

    def h_brz(st, f):
        sub, a, b, c, ilo, ihi = f
        cond = row(st.stack_lo, st.sp - 1)
        taken = cond == 0
        return _uniform_branch(st, f, taken, a, keep=0, cut=False)

    def h_brnz(st, f):
        sub, a, b, c, ilo, ihi = f
        cond = row(st.stack_lo, st.sp - 1)
        taken = cond != 0
        return _uniform_branch(st, f, taken, a, keep=b, cut=True)

    def _uniform_branch(st, f, taken_vec, target, keep, cut):
        sub, a, b, c, ilo, ihi = f
        t0 = taken_vec[0]
        agree = jnp.all(taken_vec == t0)
        # kept value sits just below the popped condition
        vl = row(st.stack_lo, st.sp - 2)
        vh = row(st.stack_hi, st.sp - 2)
        sp_pop = st.sp - 1

        def take(st):
            if cut:
                tgt_sp = st.opbase + c
                sl = jnp.where(keep == 1, setrow(st.stack_lo, tgt_sp, vl),
                               st.stack_lo)
                sh = jnp.where(keep == 1, setrow(st.stack_hi, tgt_sp, vh),
                               st.stack_hi)
                return st._replace(pc=target, sp=tgt_sp + keep,
                                   stack_lo=sl, stack_hi=sh)
            return st._replace(pc=target, sp=sp_pop)

        def fall(st):
            return st._replace(pc=st.pc + 1, sp=sp_pop)

        new_st = lax.cond(t0, take, fall, st)
        return lax.cond(agree, lambda s: s,
                        lambda s: halt(st, jnp.int32(ST_DIVERGED)), new_st)

    def h_br_table(st, f):
        sub, a, b, c, ilo, ihi = f
        idx = row(st.stack_lo, st.sp - 1)
        i0 = idx[0]
        agree = jnp.all(idx == i0)
        ii = jnp.where(u_lt(b, i0), b, i0)
        e = jnp.clip(a + ii, 0, brt_t.shape[0] - 1)
        tgt = brt_t[e, 0]
        keep = brt_t[e, 1]
        pop_to = brt_t[e, 2]
        vl = row(st.stack_lo, st.sp - 2)
        vh = row(st.stack_hi, st.sp - 2)
        tgt_sp = st.opbase + pop_to
        sl = jnp.where(keep == 1, setrow(st.stack_lo, tgt_sp, vl), st.stack_lo)
        sh = jnp.where(keep == 1, setrow(st.stack_hi, tgt_sp, vh), st.stack_hi)
        new_st = st._replace(pc=tgt, sp=tgt_sp + keep, stack_lo=sl, stack_hi=sh)
        return lax.cond(agree, lambda s: s,
                        lambda s: halt(st, jnp.int32(ST_DIVERGED)), new_st)

    def h_return(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.sp - 1)
        vh = row(st.stack_hi, st.sp - 1)
        sl = jnp.where(b == 1, setrow(st.stack_lo, st.fp, vl), st.stack_lo)
        sh = jnp.where(b == 1, setrow(st.stack_hi, st.fp, vh), st.stack_hi)
        done = st.call_depth == 0
        rd = jnp.clip(st.call_depth - 1, 0, CD - 1)
        r_pc = sget(st.fr_ret_pc, rd)
        r_fp = sget(st.fr_fp, rd)
        r_ob = sget(st.fr_opbase, rd)
        new_sp = st.fp + b
        st2 = st._replace(stack_lo=sl, stack_hi=sh, sp=new_sp)
        return lax.cond(
            done,
            lambda s: s._replace(status=jnp.int32(ST_DONE)),
            lambda s: s._replace(pc=r_pc, fp=r_fp, opbase=r_ob,
                                 call_depth=s.call_depth - 1),
            st2)

    def h_call(st, f):
        sub, a, b, c, ilo, ihi = f
        return _do_call(st, a, st.sp)

    def h_call_indirect(st, f):
        sub, a, b, c, ilo, ihi = f
        idx = row(st.stack_lo, st.sp - 1)
        i0 = idx[0]
        agree = jnp.all(idx == i0)
        tsize = table0.shape[0]
        oob = ~u_lt(i0, b)  # unsigned idx < size; b == 0 is always oob
        h = table0[jnp.clip(c + jnp.clip(i0, 0, jnp.maximum(b - 1, 0)),
                            0, tsize - 1)]
        null = h == 0
        callee = jnp.clip(h - 1, 0, f_entry.shape[0] - 1)
        sig_bad = f_type[callee] != a

        def bad(st):
            code = jnp.where(oob, int(ErrCode.UndefinedElement),
                             jnp.where(null, int(ErrCode.UninitializedElement),
                                       int(ErrCode.IndirectCallTypeMismatch)))
            return st._replace(trap=jnp.full((lanes,), code, I32),
                               status=jnp.int32(ST_TRAPPED_BASE) + code)

        def good(st):
            return _do_call(st._replace(sp=st.sp - 1), callee, st.sp - 1)

        new_st = lax.cond(oob | null | sig_bad, bad, good, st)
        return lax.cond(agree, lambda s: s,
                        lambda s: halt(st, jnp.int32(ST_DIVERGED)), new_st)

    def _do_call(st, callee, sp_eff):
        callee = jnp.clip(callee, 0, f_entry.shape[0] - 1)
        nargs = sget(f_nparams, callee)
        nloc = sget(f_nlocals, callee)
        ftop = sget(f_frame_top, callee)
        fp_new = sp_eff - nargs
        ob_new = fp_new + nloc
        ovf = (st.call_depth >= CD - 1) | (fp_new + ftop > D)

        def trap(st):
            code = jnp.where(st.call_depth >= CD - 1,
                             int(ErrCode.CallStackExhausted),
                             int(ErrCode.StackOverflow))
            return st._replace(trap=jnp.full((lanes,), code, I32),
                               status=jnp.int32(ST_TRAPPED_BASE) + code)

        def go(st):
            frp = sset(st.fr_ret_pc, st.call_depth, st.pc + 1)
            frf = sset(st.fr_fp, st.call_depth, st.fp)
            fro = sset(st.fr_opbase, st.call_depth, st.opbase)
            sl, sh = st.stack_lo, st.stack_hi
            zrow = jnp.zeros((lanes,), I32)
            for k in range(img.max_local_zeros):
                do = k < (nloc - nargs)
                sl = jnp.where(do, setrow(sl, fp_new + nargs + k, zrow), sl)
                sh = jnp.where(do, setrow(sh, fp_new + nargs + k, zrow), sh)
            return st._replace(pc=sget(f_entry, callee), fp=fp_new,
                               opbase=ob_new, sp=ob_new, call_depth=st.call_depth + 1,
                               fr_ret_pc=frp, fr_fp=frf, fr_opbase=fro,
                               stack_lo=sl, stack_hi=sh)

        return lax.cond(ovf, trap, go, st)

    def h_load(st, f):
        sub, a, b, c, ilo, ihi = f
        addr = row(st.stack_lo, st.sp - 1)
        ea = addr + a
        carry = u_lt(ea, addr) | u_lt(ea, jnp.full((lanes,), a, I32))
        mem_bytes = st.mem_pages * jnp.int32(65536)
        end = ea + b
        oob = carry | u_lt(end, ea) | u_lt(jnp.full((lanes,), mem_bytes, I32), end)
        widx = lax.shift_right_logical(ea, 2)
        shB = (ea & 3) * 8
        # per-lane word gather — addresses diverge, but memory rows are
        # lane-major so this is a [W, L] gather; uniform-address fast path
        # would need address agreement, data usually differs
        mw0 = _mem_gather(st.mem, widx)
        mw1 = _mem_gather(st.mem, widx + 1)
        mw2 = _mem_gather(st.mem, widx + 2)
        inv = (32 - shB) & 31
        hi_or = jnp.where(shB == 0, 0, -1)
        raw_lo = lax.shift_right_logical(mw0, shB) | (lax.shift_left(mw1, inv) & hi_or)
        raw_hi = lax.shift_right_logical(mw1, shB) | (lax.shift_left(mw2, inv) & hi_or)
        signed = (c & 1) != 0
        is64 = (c & 2) != 0
        b1 = b == 1
        b2_ = b == 2
        lraw = jnp.where(b1, raw_lo & 0xFF, jnp.where(b2_, raw_lo & 0xFFFF, raw_lo))
        lsext = jnp.where(b1, lax.shift_right_arithmetic(lax.shift_left(raw_lo, 24), 24),
                          jnp.where(b2_, lax.shift_right_arithmetic(lax.shift_left(raw_lo, 16), 16),
                                    raw_lo))
        ll = jnp.where(signed, lsext, lraw)
        lh = jnp.where(is64, jnp.where(b == 8, raw_hi,
                                       jnp.where(signed, lax.shift_right_arithmetic(ll, 31), 0)),
                       jnp.int32(0))
        any_oob = jnp.any(oob)
        sl = setrow(st.stack_lo, st.sp - 1, ll)
        sh = setrow(st.stack_hi, st.sp - 1, lh)
        new_st = st._replace(pc=st.pc + 1, stack_lo=sl, stack_hi=sh)
        return lax.cond(
            any_oob,
            lambda s: s._replace(
                trap=jnp.where(oob, int(ErrCode.MemoryOutOfBounds), s.trap),
                status=jnp.int32(ST_DIVERGED)),
            lambda s: s, new_st)

    def _mem_gather(mem, widx):
        import jax.numpy as jnp
        widx = jnp.clip(widx, 0, mem.shape[0] - 1)
        return jnp.take_along_axis(mem, widx[None, :], axis=0)[0]

    def h_store(st, f):
        sub, a, b, c, ilo, ihi = f
        vl = row(st.stack_lo, st.sp - 1)
        vh = row(st.stack_hi, st.sp - 1)
        addr = row(st.stack_lo, st.sp - 2)
        ea = addr + a
        carry = u_lt(ea, addr) | u_lt(ea, jnp.full((lanes,), a, I32))
        mem_bytes = st.mem_pages * jnp.int32(65536)
        end = ea + b
        oob = carry | u_lt(end, ea) | u_lt(jnp.full((lanes,), mem_bytes, I32), end)
        widx = lax.shift_right_logical(ea, 2)
        shB = (ea & 3) * 8
        b1 = b == 1
        b2_ = b == 2
        full_lo = jnp.where(b1, 0xFF, jnp.where(b2_, 0xFFFF, jnp.int32(-1)))
        full_hi = jnp.where(b == 8, jnp.int32(-1), 0)
        full_lo = jnp.broadcast_to(full_lo, (lanes,))
        full_hi = jnp.broadcast_to(full_hi, (lanes,))
        sm0, sm1 = lo_ops.shl64(full_lo, full_hi, shB)
        sm2 = jnp.where(shB == 0, 0, lo_ops.shr64_u(full_lo, full_hi, 64 - shB)[0])
        sv0, sv1 = lo_ops.shl64(vl, vh, shB)
        sv2 = jnp.where(shB == 0, 0, lo_ops.shr64_u(vl, vh, 64 - shB)[0])
        mem = st.mem
        mem = _mem_rmw(mem, widx, sm0, sv0, ~oob)
        mem = _mem_rmw(mem, widx + 1, sm1, sv1, ~oob)
        mem = _mem_rmw(mem, widx + 2, sm2, sv2, ~oob)
        any_oob = jnp.any(oob)
        new_st = st._replace(pc=st.pc + 1, sp=st.sp - 2, mem=mem)
        return lax.cond(
            any_oob,
            lambda s: s._replace(
                trap=jnp.where(oob, int(ErrCode.MemoryOutOfBounds), s.trap),
                status=jnp.int32(ST_DIVERGED)),
            lambda s: s, new_st)

    def _mem_rmw(mem, widx, m, v, ok):
        import jax.numpy as jnp
        lane_iota = jnp.arange(lanes, dtype=jnp.int32)
        widx = jnp.clip(widx, 0, mem.shape[0] - 1)
        cur = jnp.take_along_axis(mem, widx[None, :], axis=0)[0]
        new = jnp.where(ok & (m != 0), (cur & ~m) | (v & m), cur)
        return mem.at[widx, lane_iota].set(new)

    def h_memsize(st, f):
        sl = setrow(st.stack_lo, st.sp, jnp.full((lanes,), st.mem_pages, I32))
        sh = setrow(st.stack_hi, st.sp, jnp.zeros((lanes,), I32))
        return st._replace(pc=st.pc + 1, sp=st.sp + 1, stack_lo=sl, stack_hi=sh)

    def h_memgrow(st, f):
        delta_v = row(st.stack_lo, st.sp - 1)
        d0 = delta_v[0]
        agree = jnp.all(delta_v == d0)
        ok = (d0 >= 0) & ((st.mem_pages + d0) <= img.mem_pages_max) & \
            ((st.mem_pages + d0) >= st.mem_pages)
        res = jnp.where(ok, st.mem_pages, jnp.int32(-1))
        sl = setrow(st.stack_lo, st.sp - 1, jnp.full((lanes,), res, I32))
        sh = setrow(st.stack_hi, st.sp - 1, jnp.zeros((lanes,), I32))
        new_st = st._replace(pc=st.pc + 1, stack_lo=sl, stack_hi=sh,
                             mem_pages=jnp.where(ok, st.mem_pages + d0, st.mem_pages))
        return lax.cond(agree, lambda s: s,
                        lambda s: halt(st, jnp.int32(ST_DIVERGED)), new_st)

    def _bulk(st, is_copy):
        n = row(st.stack_lo, st.sp - 1)
        src_or_val = row(st.stack_lo, st.sp - 2)
        dst = row(st.stack_lo, st.sp - 3)
        mem_bytes = st.mem_pages * jnp.int32(65536)
        end = dst + n
        s_end = src_or_val + n
        oob = u_lt(end, dst) | u_lt(mem_bytes, end)
        if is_copy:
            oob = oob | u_lt(s_end, src_or_val) | u_lt(mem_bytes, s_end)
        go = ~oob & (n != 0)
        copy_lanes = jnp.ones_like(dst, bool) if is_copy else None
        mem = lo_ops.plane_fill_copy(st.mem, dst, end, src_or_val, go,
                                     copy_lanes=copy_lanes)
        any_oob = jnp.any(oob)
        new_st = st._replace(pc=st.pc + 1, sp=st.sp - 3, mem=mem)
        return lax.cond(
            any_oob,
            lambda s: s._replace(
                trap=jnp.where(oob, int(ErrCode.MemoryOutOfBounds), s.trap),
                status=jnp.int32(ST_DIVERGED)),
            lambda s: s, new_st)

    def h_memfill(st, f):
        return _bulk(st, False)

    def h_memcopy(st, f):
        return _bulk(st, True)

    def h_trap(st, f):
        sub, a, b, c, ilo, ihi = f
        return st._replace(trap=jnp.full((lanes,), a, I32),
                           status=jnp.int32(ST_TRAPPED_BASE) + a)

    # ---------------- tier-0 hostcalls on the converged path ----------
    # The stub pc is lane-uniform here, so the call KIND is scalar and
    # dispatch is a scalar cond chain; arguments/results stay per-lane
    # vectors.  Shapes the fast path cannot retire (cputime clocks,
    # oversized buffers, non-uniform stdout record sizes) hand off
    # un-advanced — the SIMT engine re-executes the stub and its own
    # tier 0 / the outcall channel takes over, with no double effects
    # (nothing is committed on the bail path).
    from wasmedge_tpu.batch.image import (
        T0_CLOCK_TIME_GET, T0_FD_WRITE, T0_PROC_EXIT, T0_RANDOM_GET,
        T0_SCHED_YIELD)
    from wasmedge_tpu.common.errors import ErrCode as _EC

    HAS_T0 = t0kinds is not None
    if HAS_T0:
        from wasmedge_tpu.batch.tier0 import (
            t0_clock_value, t0_masked_store, t0_random_fill,
            t0_rng_seq_hash, t0_shifted_src_word, t0_statics)

        t0k_t = jnp.asarray(np.asarray(t0kinds, np.int32))
        T0_PRESENT = sorted(set(int(k) for k in np.unique(t0kinds))
                            - {0})
        _t0s = t0_statics(cfg)
        RMAX_W = _t0s["RMAX_W"]
        WMAX_W = _t0s["WMAX_W"]
        RNG_SEED = jnp.asarray(_t0s["RNG_SEED"])
        _E_INVAL = _t0s["E_INVAL"]
        _E_FAULT = _t0s["E_FAULT"]
        lane_iota = jnp.arange(lanes, dtype=I32)
        zlv = jnp.zeros((lanes,), I32)

        def t0_retire(st2, res_vec):
            sl = setrow(st2.stack_lo, st2.opbase, res_vec)
            sh = setrow(st2.stack_hi, st2.opbase, zlv)
            return st2._replace(pc=st2.pc + 1, sp=st2.opbase + 1,
                                stack_lo=sl, stack_hi=sh)

        def t0_yield(st):
            return t0_retire(
                st._replace(t0_ctr=st.t0_ctr.at[3].add(1)), zlv)

        def t0_exit(st):
            code = row(st.stack_lo, st.fp)
            sl = setrow(st.stack_lo, st.opbase, code)
            return st._replace(
                stack_lo=sl,
                trap=jnp.full((lanes,), int(_EC.Terminated), I32),
                status=jnp.int32(ST_TRAPPED_BASE + int(_EC.Terminated)),
                t0_ctr=st.t0_ctr.at[3].add(1))

        def t0_clock(st, t0_time):
            cid = row(st.stack_lo, st.fp)
            tptr = row(st.stack_lo, st.fp + 2)
            hard = (cid == 2) | (cid == 3)     # cputime: tier 1
            bad = u_lt(jnp.int32(3), cid)
            mem_bytes = jnp.full((lanes,), st.mem_pages, I32) * \
                jnp.int32(65536)
            tend = tptr + 8
            oob = u_lt(tend, tptr) | u_lt(mem_bytes, tend)
            ctr = st.t0_ctr[0]
            tv_lo, tv_hi = t0_clock_value(t0_time, cid, ctr)
            wr = ~bad & ~oob & ~hard
            mem = t0_masked_store(_mem_rmw, st.mem, tptr, tv_lo, tv_hi,
                                  8, wr)
            res = jnp.where(bad, jnp.int32(_E_INVAL),
                            jnp.where(oob, jnp.int32(_E_FAULT), 0))
            st2 = t0_retire(
                st._replace(mem=mem, t0_ctr=st.t0_ctr.at[0].set(
                    jnp.where(wr, ctr + 1, ctr))), res)
            return lax.cond(jnp.any(hard),
                            lambda s: halt(st, jnp.int32(ST_DIVERGED)),
                            lambda s: s, st2)

        def t0_random(st):
            rbuf = row(st.stack_lo, st.fp)
            rlen = row(st.stack_lo, st.fp + 1)
            fits = ~u_lt(jnp.int32(RMAX_W * 4), rlen)
            mem_bytes = jnp.full((lanes,), st.mem_pages, I32) * \
                jnp.int32(65536)
            rend = rbuf + rlen
            oob = u_lt(rend, rbuf) | u_lt(mem_bytes, rend)
            ctr = st.t0_ctr[1]
            seq_h = t0_rng_seq_hash(RNG_SEED, lane_iota, ctr)
            wr = fits & ~oob & (rlen != 0)
            mem = t0_random_fill(_mem_rmw, st.mem, rbuf, rend, wr,
                                 seq_h, RMAX_W, zlv)
            res = jnp.where(oob, jnp.int32(_E_FAULT), 0)
            st2 = t0_retire(
                st._replace(mem=mem, t0_ctr=st.t0_ctr.at[1].set(
                    jnp.where(wr, ctr + 1, ctr))), res)
            return lax.cond(jnp.any(~fits),
                            lambda s: halt(st, jnp.int32(ST_DIVERGED)),
                            lambda s: s, st2)

        def t0_fdw(st):
            SW = st.so_buf.shape[0]
            wfd = row(st.stack_lo, st.fp)
            wiovs = row(st.stack_lo, st.fp + 1)
            wcnt = row(st.stack_lo, st.fp + 2)
            wnp = row(st.stack_lo, st.fp + 3)
            mem_bytes = jnp.full((lanes,), st.mem_pages, I32) * \
                jnp.int32(65536)
            iov_end = wiovs + 8
            iov_ok = ~(u_lt(iov_end, wiovs) | u_lt(mem_bytes, iov_end))
            iw = lax.shift_right_logical(wiovs, 2)
            wbuf = _mem_gather(st.mem, iw)
            wlen = _mem_gather(st.mem, iw + 1)
            fits = ~u_lt(jnp.int32(WMAX_W * 4), wlen)
            nwords = lax.shift_right_logical(wlen + 3, 2)
            npend = wnp + 4
            np_ok = ~(u_lt(npend, wnp) | u_lt(mem_bytes, npend))
            handled = ((wfd == 1) | (wfd == 2)) & (wcnt == 1) \
                & ((wiovs & 3) == 0) & iov_ok & fits & np_ok
            # the stdout record buffer is row-addressed: all lanes must
            # append the same number of rows from the same offset
            so0 = st.so_off[0]
            nw0 = nwords[0]
            uniform_rec = jnp.all(st.so_off == so0) & \
                jnp.all(jnp.where(handled, nwords, nw0) == nw0)
            space = ~u_lt(jnp.int32(SW), so0 + 1 + nw0)
            bail = jnp.any(~handled) | ~uniform_rec | ~space
            dend = wbuf + wlen
            d_oob = u_lt(dend, wbuf) | u_lt(mem_bytes, dend)
            wr = handled & ~d_oob
            shB = (wbuf & 3) * 8
            inv = (32 - shB) & 31
            hi_or = jnp.where(shB == 0, 0, -1)
            wsrc0 = lax.shift_right_logical(wbuf, 2)

            def commit(st):
                hdr = wlen | lax.shift_left(wfd, 28)
                cur = row(st.so_buf, so0)
                sob = setrow(st.so_buf, so0, jnp.where(wr, hdr, cur))
                for j in range(WMAX_W):
                    v = t0_shifted_src_word(_mem_gather, st.mem, wsrc0,
                                            j, shB, inv, hi_or)
                    mrow = wr & (jnp.int32(j) < nw0) & \
                        (jnp.int32(j * 4) < wlen)
                    curj = row(sob, so0 + 1 + j)
                    sob = setrow(sob, so0 + 1 + j,
                                 jnp.where(mrow, v, curj))
                mem = t0_masked_store(_mem_rmw, st.mem, wnp, wlen, zlv,
                                      4, wr)
                res = jnp.where(d_oob, jnp.int32(_E_FAULT), 0)
                ctr = st.t0_ctr[2]
                return t0_retire(st._replace(
                    mem=mem, so_buf=sob,
                    so_off=jnp.where(wr, st.so_off + 1 + nwords,
                                     st.so_off),
                    t0_ctr=st.t0_ctr.at[2].set(
                        jnp.where(wr, ctr + 1, ctr))), res)

            return lax.cond(bail,
                            lambda s: halt(s, jnp.int32(ST_DIVERGED)),
                            commit, st)

        _T0_HANDLERS = {
            T0_SCHED_YIELD: lambda st, tt: t0_yield(st),
            T0_PROC_EXIT: lambda st, tt: t0_exit(st),
            T0_CLOCK_TIME_GET: t0_clock,
            T0_RANDOM_GET: lambda st, tt: t0_random(st),
            T0_FD_WRITE: lambda st, tt: t0_fdw(st),
        }
        if not img.has_memory:
            for k in (T0_CLOCK_TIME_GET, T0_RANDOM_GET, T0_FD_WRITE):
                _T0_HANDLERS.pop(k, None)

    def h_hostcall(st, f, t0_time=None):
        # host outcalls: tier-0 kinds retire right here on the fast
        # path; everything else hands off un-advanced so the SIMT
        # engine re-executes the stub and parks the lanes
        if not HAS_T0:
            return halt(st, jnp.int32(ST_DIVERGED))
        kind = t0k_t[jnp.clip(st.pc, 0, img.code_len - 1)]

        def fall(s):
            return halt(s, jnp.int32(ST_DIVERGED))

        fn = fall
        for K in T0_PRESENT:
            h = _T0_HANDLERS.get(K)
            if h is None:
                continue
            fn = (lambda s, K=K, h=h, nxt=fn: lax.cond(
                kind == jnp.int32(K),
                lambda s2: h(s2, t0_time), nxt, s))
        return fn(st)

    handlers = [None] * NUM_CLASSES
    handlers[CLS_HOSTCALL] = h_hostcall
    handlers[CLS_NOP] = h_nop
    handlers[CLS_CONST] = h_const
    handlers[CLS_LOCAL_GET] = h_local_get
    handlers[CLS_LOCAL_SET] = h_local_set
    handlers[CLS_LOCAL_TEE] = h_local_tee
    handlers[CLS_GLOBAL_GET] = h_global_get
    handlers[CLS_GLOBAL_SET] = h_global_set
    handlers[CLS_ALU1] = h_alu1
    handlers[CLS_ALU2] = h_alu2
    handlers[CLS_SELECT] = h_select
    handlers[CLS_DROP] = h_drop
    handlers[CLS_BR] = h_br
    handlers[CLS_BRZ] = h_brz
    handlers[CLS_BRNZ] = h_brnz
    handlers[CLS_BR_TABLE] = h_br_table
    handlers[CLS_RETURN] = h_return
    handlers[CLS_CALL] = h_call
    handlers[CLS_CALL_INDIRECT] = h_call_indirect
    handlers[CLS_LOAD] = h_load
    handlers[CLS_STORE] = h_store
    handlers[CLS_MEMSIZE] = h_memsize
    handlers[CLS_MEMFILL] = h_memfill
    handlers[CLS_MEMCOPY] = h_memcopy
    handlers[CLS_MEMGROW] = h_memgrow
    handlers[CLS_TRAP] = h_trap

    # classes this converged engine does not execute (the v128 family
    # lives on the SIMT engine's 4-plane cells): divergence-bail stubs.
    # UniformBatchEngine.run routes has_simd modules to SIMT up front,
    # so these fire only as a safety net.
    def h_unsupported(st, f):
        return halt(st, jnp.int32(ST_DIVERGED))

    for k in range(NUM_CLASSES):
        if handlers[k] is None:
            handlers[k] = h_unsupported

    def step(st: UniformState, t0_time=None) -> UniformState:
        pc = jnp.clip(st.pc, 0, img.code_len - 1)
        fetch = (sub_t[pc], a_t[pc], b_t[pc], c_t[pc], ilo_t[pc], ihi_t[pc])
        cls = cls_t[pc]
        hs = list(handlers)
        hs[CLS_HOSTCALL] = (lambda s, f, tt=t0_time: h_hostcall(s, f, tt))
        new_st = lax.switch(cls, [
            (lambda s, f=fetch, h=h: h(s, f)) for h in hs
        ], st)
        # per-lane trap divergence check: if some (not all) lanes trapped in
        # an ALU, bail to SIMT; if all trapped identically, halt with code
        t = new_st.trap
        t0 = t[0]
        all_same = jnp.all(t == t0)
        any_trap = jnp.any(t != 0)

        def resolve(s):
            return lax.cond(
                all_same & (t0 != 0),
                lambda s: s._replace(status=jnp.int32(ST_TRAPPED_BASE) + t0),
                lambda s: lax.cond(
                    any_trap & (s.status == ST_RUNNING),
                    lambda s: s._replace(status=jnp.int32(ST_DIVERGED)),
                    lambda s: s, s),
                s)

        new_st = resolve(new_st)
        # A divergence handoff rewinds to the pre-step state: the SIMT engine
        # re-executes that instruction, so it must not count as a step here.
        counted = jnp.where(new_st.status == ST_DIVERGED, 0, 1)
        return new_st._replace(steps=new_st.steps + counted)

    return step


class UniformBatchEngine:
    """Converged-lane engine with automatic SIMT fallback on divergence.

    Chooses the fast path (scalar control, dynamic-slice stack rows) while
    lanes agree on control flow; hands the state over to the general SIMT
    engine (batch/engine.py) the moment they don't. This is the AUTO engine
    behavior for replicated workloads (BASELINE configs 1-2)."""

    def __init__(self, inst, store=None, conf=None, lanes=None, mesh=None):
        from wasmedge_tpu.batch.engine import BatchEngine

        self.simt = BatchEngine(inst, store=store, conf=conf, lanes=lanes,
                                mesh=mesh)
        self.inst = inst
        self.cfg = self.simt.cfg
        self.lanes = self.simt.lanes
        self.img = self.simt.img
        self.obs = self.simt.obs  # shared flight recorder (obs/)
        self._uchunk = None
        self.pallas = self._pick_pallas(inst, store, conf)

    def _pick_pallas(self, inst, store, conf):
        """The on-device Pallas dispatch loop is the fast path whenever the
        backend is TPU and the module fits the kernel geometry; the
        per-step XLA path below remains the CPU/testing vehicle and the
        fallback for oversized modules (conf.batch.use_pallas overrides)."""
        from wasmedge_tpu.batch.pallas_engine import (
            PallasUniformEngine, pallas_enabled)

        if not pallas_enabled(self.cfg):
            return None
        eng = PallasUniformEngine(inst, conf=conf, simt=self.simt,
                                  interpret=self.cfg.interpret or None)
        return eng if eng.eligible else None

    def _build_uniform(self):
        from wasmedge_tpu.batch import ensure_jax_backend

        ensure_jax_backend()
        import jax
        import jax.numpy as jnp
        from jax import lax

        step = make_uniform_step(self.img, self.cfg, self.lanes,
                                 t0kinds=getattr(self.simt, "_t0kinds",
                                                 None))
        chunk = self.cfg.steps_per_launch

        def run_chunk(st, t0_time):
            def cond(carry):
                i, s = carry
                return (i < chunk) & (s.status == ST_RUNNING)

            def body(carry):
                i, s = carry
                return i + 1, step(s, t0_time)

            _, st = lax.while_loop(cond, body, (jnp.int32(0), st))
            return st

        # same donation guard as the SIMT chunk (persistent-cache CPU
        # deserialization can drop input/output aliasing)
        donate = (0,)
        if jax.default_backend() == "cpu" and \
                getattr(jax.config, "jax_compilation_cache_dir", None):
            donate = ()
        self._uchunk = jax.jit(run_chunk, donate_argnums=donate)

    def _initial_uniform_state(self, func_idx, args_lanes):
        import jax.numpy as jnp

        base = self.simt.initial_state(func_idx, args_lanes)
        CD = self.cfg.call_stack_depth
        return UniformState(
            pc=base.pc[0], sp=base.sp[0], fp=jnp.int32(0),
            opbase=base.opbase[0], call_depth=jnp.int32(0),
            status=jnp.int32(ST_RUNNING), steps=jnp.int32(0),
            mem_pages=base.mem_pages[0],
            stack_lo=base.stack_lo, stack_hi=base.stack_hi,
            fr_ret_pc=jnp.zeros((CD,), jnp.int32),
            fr_fp=jnp.zeros((CD,), jnp.int32),
            fr_opbase=jnp.zeros((CD,), jnp.int32),
            glob_lo=base.glob_lo, glob_hi=base.glob_hi,
            mem=base.mem, trap=base.trap,
            t0_ctr=base.t0_ctr, so_buf=base.so_buf, so_off=base.so_off,
        )

    def _to_simt_state(self, ust: "UniformState"):
        import jax.numpy as jnp

        from wasmedge_tpu.batch.engine import (
            BatchState, r05_state_planes, t0_state_planes)

        L = self.lanes
        full = lambda v: jnp.full((L,), v, jnp.int32)
        status = int(ust.status)
        trap = ust.trap
        if status == ST_DONE:
            trap = jnp.full((L,), TRAP_DONE, jnp.int32)
        elif status >= ST_TRAPPED_BASE:
            trap = jnp.where(trap == 0, jnp.int32(status - ST_TRAPPED_BASE), trap)
        cfg = self.cfg
        fuel0 = cfg.fuel_per_launch if cfg.fuel_per_launch is not None else 0
        return BatchState(
            pc=full(ust.pc), sp=full(ust.sp), fp=full(ust.fp),
            opbase=full(ust.opbase), call_depth=full(ust.call_depth),
            trap=trap, retired=full(ust.steps),
            fuel=full(max(fuel0 - int(ust.steps), 1) if fuel0 else 0),
            mem_pages=full(ust.mem_pages),
            stack_lo=ust.stack_lo, stack_hi=ust.stack_hi,
            fr_ret_pc=jnp.broadcast_to(ust.fr_ret_pc[:, None],
                                       (cfg.call_stack_depth, L)),
            fr_fp=jnp.broadcast_to(ust.fr_fp[:, None],
                                   (cfg.call_stack_depth, L)),
            fr_opbase=jnp.broadcast_to(ust.fr_opbase[:, None],
                                       (cfg.call_stack_depth, L)),
            glob_lo=ust.glob_lo, glob_hi=ust.glob_hi, mem=ust.mem,
            # r05 planes at their pristine values: the converged path
            # cannot execute the ops that mutate them (it bails first),
            # so a divergence handoff always starts from the initial
            # table/segment state
            **r05_state_planes(self.img, L),
            # tier-0 planes carry over VERBATIM: the converged path
            # retires tier-0 calls itself, so buffered stdout records
            # and counter positions must survive the handoff
            **(dict(t0_ctr=ust.t0_ctr, so_buf=ust.so_buf,
                    so_off=ust.so_off)
               if ust.t0_ctr is not None else
               t0_state_planes(self.img, cfg, L,
                               getattr(self.simt, "_t0kinds", None))),
        )

    def run(self, func_name, args_lanes, max_steps: int = 10_000_000):
        import numpy as np

        from wasmedge_tpu.batch.engine import BatchResult

        ex = self.inst.exports.get(func_name)
        if ex is None or ex[0] != 0:
            raise KeyError(f"no exported function {func_name}")
        func_idx = ex[1]
        from wasmedge_tpu.batch.engine import new_hostcall_stats

        self.simt.hostcall_stats = new_hostcall_stats()
        from wasmedge_tpu.batch.hostcall import stdout_cursor_reset

        stdout_cursor_reset(self.simt)  # fresh run = fresh output stream
        # stale compaction mapping from a previous run must never leak
        # into this one (the handoff below re-arms when the knob is on)
        self.simt.compactor = None
        if self.pallas is not None:
            res = self.pallas.run(func_name, args_lanes, max_steps)
            self.fell_back_to_simt = self.pallas.fell_back_to_simt
            return res
        from wasmedge_tpu.batch.image import CLS_TABLE_GET

        if self.cfg.fuel_per_launch is not None or self.simt.mesh is not None \
                or getattr(self.img, "has_simd", False) \
                or bool((self.img.cls >= CLS_TABLE_GET).any()):
            # fuel accounting, mesh sharding, v128, and the r05 table/
            # segment/tail-call families live in the SIMT engine (the
            # converged single-pc path has neither 4-plane cells nor the
            # per-lane table planes)
            return self.simt.run(func_name, args_lanes, max_steps)
        if self._uchunk is None:
            self._build_uniform()
        import jax.numpy as jnp

        from wasmedge_tpu.batch.engine import t0_time_planes
        from wasmedge_tpu.batch.hostcall import flush_stdout_buffers

        ust = self._initial_uniform_state(func_idx, args_lanes)
        t0_active = ust.t0_ctr is not None
        dummy_time = np.zeros((2, 2), np.int32)
        fell_back = False
        obs = self.obs
        prev_steps = 0
        while int(ust.steps) < max_steps:
            tt = jnp.asarray(t0_time_planes() if t0_active
                             else dummy_time)
            t_launch = obs.now()
            ust = self._uchunk(ust, tt)
            status = int(ust.status)
            if obs.enabled:
                # converged path: every lane shares one pc, so
                # occupancy is all-or-nothing
                steps = int(ust.steps)
                obs.span("launch", t_launch, cat="engine",
                         track="uniform",
                         live_lanes=self.lanes if status == ST_RUNNING
                         else 0,
                         retired_delta=(steps - prev_steps) * self.lanes)
                prev_steps = steps
            if status == ST_RUNNING:
                continue
            if status == ST_DIVERGED:
                fell_back = True
            break
        self.fell_back_to_simt = fell_back
        if t0_active:
            # tier-0 retirements on the converged path (the SIMT
            # handoff below accounts only its own delta)
            ctr = np.asarray(ust.t0_ctr, np.int64).sum(axis=1)
            st_ = self.simt.hostcall_stats
            st_["tier0_clock"] += int(ctr[0])
            st_["tier0_random"] += int(ctr[1])
            st_["tier0_fd_write"] += int(ctr[2])
            st_["tier0_sys"] += int(ctr[3])
            st_["tier0_calls"] += int(ctr.sum())
        if fell_back:
            # migrate to SIMT and finish there (incl. host outcalls);
            # the divergence handoff is exactly where lane compaction
            # pays, so arm it for the SIMT leg (batch/compact.py)
            from wasmedge_tpu.batch.compact import arm

            arm(self.simt)
            state = self._to_simt_state(ust)
            state, total = self.simt.run_from_state(
                state, int(ust.steps), max_steps)
            return self._result_from_simt(func_idx, state, total)
        # uniform completion: drain the tier-0 stdout buffer
        state = self._to_simt_state(ust)
        state = flush_stdout_buffers(self.simt, state)
        return self._result_from_simt(func_idx, state, int(ust.steps))

    def _result_from_simt(self, func_idx, state, steps):
        import numpy as np

        from wasmedge_tpu.batch.engine import BatchResult

        nres = int(self.inst.lowered.funcs[func_idx].nresults)
        stack_lo = np.asarray(state.stack_lo)
        stack_hi = np.asarray(state.stack_hi)
        # the SIMT leg may have compacted (permuted) the lanes: gather
        # the result mirrors back to original lane order
        from wasmedge_tpu.batch.compact import restore_mirrors

        stack_lo, stack_hi, trap, retired = restore_mirrors(
            getattr(self.simt, "compactor", None), stack_lo, stack_hi,
            np.asarray(state.trap), np.asarray(state.retired))
        results = []
        for r in range(nres):
            lo = stack_lo[r].view(np.uint32).astype(np.uint64)
            hi = stack_hi[r].view(np.uint32).astype(np.uint64)
            results.append((lo | (hi << np.uint64(32))).view(np.int64))
        return BatchResult(results=results, trap=trap,
                           retired=retired, steps=steps)
